// Native quadratic-assignment solvers for topology-aware placement.
//
// TPU-native re-implementation of the reference's qap namespace
// (reference: include/stencil/qap.hpp:51-180): an exact brute-force
// search over permutations with a wall-clock timeout, and a greedy
// pairwise-swap hill climb with incremental cost updates. Exposed as a
// C ABI consumed from Python via ctypes (stencil_tpu/qap.py).
//
// Cost model: cost(f) = sum_{a,b} w[a][b] * d[f[a]][f[b]], with the
// convention that 0 * inf == 0 (the reference's cost-product rule,
// qap.hpp:16-21).

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <vector>

namespace {

using Perm = std::vector<int64_t>;

// one term of the objective; zero traffic over an unreachable link
// costs nothing (0 * inf == 0)
inline double weighted_hop(double traffic, double hops) {
  if (traffic == 0 || hops == 0) return 0;
  return traffic * hops;
}

double total_cost(int64_t n, const double *w, const double *d,
                  const Perm &f) {
  double acc = 0;
  for (int64_t a = 0; a < n; ++a)
    for (int64_t b = 0; b < n; ++b)
      acc += weighted_hop(w[a * n + b], d[f[a] * n + f[b]]);
  return acc;
}

// Sum of every objective term that involves subdomain i or j under
// permutation f — exactly the terms a swap of f[i]/f[j] changes.
double pair_terms(int64_t n, const double *w, const double *d,
                  const Perm &f, int64_t i, int64_t j) {
  double acc = 0;
  for (int64_t k = 0; k < n; ++k) {
    acc += weighted_hop(w[i * n + k], d[f[i] * n + f[k]]);
    acc += weighted_hop(w[j * n + k], d[f[j] * n + f[k]]);
    if (k != i && k != j) {
      acc += weighted_hop(w[k * n + i], d[f[k] * n + f[i]]);
      acc += weighted_hop(w[k * n + j], d[f[k] * n + f[j]]);
    }
  }
  return acc;
}

Perm identity(int64_t n) {
  Perm f(n);
  for (int64_t i = 0; i < n; ++i) f[i] = i;
  return f;
}

}  // namespace

extern "C" {

// Exact search: all permutations, best kept; stops after timeout_s
// seconds of wall clock (reference qap::solve uses a fixed 10 s cap).
// Returns the best cost found; writes the permutation into out_f.
double qap_solve_exact(int64_t n, const double *w, const double *d,
                       int64_t *out_f, double timeout_s) {
  using Clock = std::chrono::steady_clock;
  const auto deadline =
      Clock::now() + std::chrono::duration_cast<Clock::duration>(
                         std::chrono::duration<double>(timeout_s));
  Perm f = identity(n);
  Perm winner = f;
  double winner_cost = total_cost(n, w, d, f);
  uint64_t tick = 0;
  while (std::next_permutation(f.begin(), f.end())) {
    // poll the clock every 1024 permutations, not every one
    if ((++tick & 0x3FF) == 0 && Clock::now() > deadline) break;
    const double c = total_cost(n, w, d, f);
    if (c < winner_cost) {
      winner_cost = c;
      winner = f;
    }
  }
  std::copy(winner.begin(), winner.end(), out_f);
  return winner_cost;
}

// Greedy pairwise-swap hill climb (the reference's qap::solve_catch,
// qap.hpp:87-180, restructured): each round tries every (i, j) swap of
// the current assignment, scoring candidates incrementally by removing
// the terms the swap touches and re-adding them post-swap; the round's
// best strictly-improving swap is adopted until a fixpoint.
double qap_solve_catch(int64_t n, const double *w, const double *d,
                       int64_t *out_f) {
  Perm assign = identity(n);
  double assign_cost = total_cost(n, w, d, assign);

  for (;;) {
    Perm round_best = assign;
    double round_cost = assign_cost;
    for (int64_t i = 0; i < n; ++i) {
      for (int64_t j = i + 1; j < n; ++j) {
        Perm trial = assign;
        double c = assign_cost - pair_terms(n, w, d, trial, i, j);
        std::swap(trial[i], trial[j]);
        c += pair_terms(n, w, d, trial, i, j);
        // inf - inf = NaN: the incremental update is invalid when
        // unreachable-link terms are involved; recompute from scratch
        if (!std::isfinite(c)) c = total_cost(n, w, d, trial);
        if (c < round_cost) {
          round_best = std::move(trial);
          round_cost = c;
        }
      }
    }
    if (round_cost >= assign_cost) break;  // fixpoint
    assign = std::move(round_best);
    assign_cost = round_cost;
  }

  std::copy(assign.begin(), assign.end(), out_f);
  return assign_cost;
}

double qap_cost(int64_t n, const double *w, const double *d,
                const int64_t *f) {
  return total_cost(n, w, d, Perm(f, f + n));
}

}  // extern "C"
