"""6th-order central finite-difference operators on padded shards.

XLA-native equivalents of the Astaroth DSL derivative machinery
(reference: astaroth/user_kernels.h:36-121 first/second/cross_derivative
and derx/deryy/derxy/... pencils): instead of per-thread pencil loads,
each operator is a sum of shifted interior-shaped slices of the padded
(z,y,x) array — XLA fuses the whole stencil into one loop nest.

Coefficients (6th-order central):
* 1st derivative: (3/4, -3/20, 1/60) antisymmetric pairs / ds
* 2nd derivative: -49/18 center + (3/2, -3/20, 1/90) symmetric / ds^2
* cross derivative: (270, -27, 2)/720 over the two diagonals
  (reference: user_kernels.h:66-76) — requires edge halo data of the
  same radius, i.e. Radius.constant(3), matching the reference's
  STENCIL_ORDER 6 (astaroth/astaroth.h:8-9).

Axis convention: axis 0=x, 1=y, 2=z (grid order); arrays are (z,y,x).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import lax

from ..geometry import Dim3

# 6th-order coefficient tables
_D1 = (3.0 / 4.0, -3.0 / 20.0, 1.0 / 60.0)
_D2_C = -49.0 / 18.0
_D2 = (3.0 / 2.0, -3.0 / 20.0, 1.0 / 90.0)
_DC = (270.0 / 720.0, -27.0 / 720.0, 2.0 / 720.0)

RADIUS = 3


def _shift(padded: jnp.ndarray, off_xyz: Tuple[int, int, int],
           pad_lo: Dim3, interior: Dim3, x_wrap: bool = False) -> jnp.ndarray:
    ox, oy, oz = off_xyz
    if x_wrap:
        # x carries NO padding: the array spans the full (periodic) x
        # extent and a +ox shift is an in-register lane rotation
        # (pltpu.roll — Pallas kernels only). Keeps every buffer
        # lane-aligned at X instead of materializing an X+2r window.
        assert pad_lo.x == 0 and interior.x == padded.shape[2]
        w = lax.slice(
            padded, (pad_lo.z + oz, pad_lo.y + oy, 0),
            (pad_lo.z + oz + interior.z, pad_lo.y + oy + interior.y,
             interior.x))
        if ox:
            from jax.experimental.pallas import tpu as pltpu
            w = pltpu.roll(w, (interior.x - ox) % interior.x, 2)
        return w
    return lax.slice(
        padded,
        (pad_lo.z + oz, pad_lo.y + oy, pad_lo.x + ox),
        (pad_lo.z + oz + interior.z, pad_lo.y + oy + interior.y,
         pad_lo.x + ox + interior.x))


def _axis_off(axis: int, i: int) -> Tuple[int, int, int]:
    off = [0, 0, 0]
    off[axis] = i
    return tuple(off)


def der1(padded: jnp.ndarray, axis: int, inv_ds: float,
         pad_lo: Dim3, interior: Dim3, x_wrap: bool = False) -> jnp.ndarray:
    """6th-order first derivative along ``axis``
    (reference: user_kernels.h:36-48 first_derivative + derx/dery/derz)."""
    dt = padded.dtype
    acc = None
    for i, c in enumerate(_D1, start=1):
        hi = _shift(padded, _axis_off(axis, i), pad_lo, interior, x_wrap)
        lo = _shift(padded, _axis_off(axis, -i), pad_lo, interior, x_wrap)
        term = jnp.asarray(c, dt) * (hi - lo)
        acc = term if acc is None else acc + term
    return acc * jnp.asarray(inv_ds, dt)


def der2(padded: jnp.ndarray, axis: int, inv_ds: float,
         pad_lo: Dim3, interior: Dim3, x_wrap: bool = False) -> jnp.ndarray:
    """6th-order second derivative along ``axis``
    (reference: user_kernels.h:49-62 second_derivative)."""
    dt = padded.dtype
    acc = jnp.asarray(_D2_C, dt) * _shift(padded, (0, 0, 0), pad_lo,
                                          interior, x_wrap)
    for i, c in enumerate(_D2, start=1):
        hi = _shift(padded, _axis_off(axis, i), pad_lo, interior, x_wrap)
        lo = _shift(padded, _axis_off(axis, -i), pad_lo, interior, x_wrap)
        acc = acc + jnp.asarray(c, dt) * (hi + lo)
    return acc * jnp.asarray(inv_ds * inv_ds, dt)


def der_cross(padded: jnp.ndarray, axis_a: int, axis_b: int,
              inv_ds_a: float, inv_ds_b: float,
              pad_lo: Dim3, interior: Dim3,
              x_wrap: bool = False) -> jnp.ndarray:
    """6th-order mixed derivative d2/(da db), a != b
    (reference: user_kernels.h:63-76 cross_derivative + derxy/...):
    pencil_a runs along the (+a,+b) diagonal, pencil_b along (+a,-b).
    """
    dt = padded.dtype
    acc = None
    for i, c in enumerate(_DC, start=1):
        def at(sa: int, sb: int):
            off = [0, 0, 0]
            off[axis_a] = sa
            off[axis_b] = sb
            return _shift(padded, tuple(off), pad_lo, interior, x_wrap)
        term = jnp.asarray(c, dt) * (at(i, i) + at(-i, -i)
                                     - at(i, -i) - at(-i, i))
        acc = term if acc is None else acc + term
    return acc * jnp.asarray(inv_ds_a * inv_ds_b, dt)


def value(padded: jnp.ndarray, pad_lo: Dim3, interior: Dim3,
          x_wrap: bool = False) -> jnp.ndarray:
    """Center value (interior view)."""
    return _shift(padded, (0, 0, 0), pad_lo, interior, x_wrap)


class FieldData:
    """value + gradient + hessian of one scalar field, computed lazily
    and cached — the AcRealData analog (reference: user_kernels.h:19-23,
    read_data). ``inv_ds`` is (1/dsx, 1/dsy, 1/dsz)."""

    def __init__(self, padded: jnp.ndarray, inv_ds: Tuple[float, float, float],
                 pad_lo: Dim3, interior: Dim3, x_wrap: bool = False) -> None:
        self._p = padded
        self._inv = inv_ds
        self._lo = pad_lo
        self._n = interior
        self._xw = x_wrap
        self._cache = {}

    @property
    def value(self) -> jnp.ndarray:
        return self._get(("v",), lambda: value(self._p, self._lo, self._n,
                                               self._xw))

    def grad(self, axis: int) -> jnp.ndarray:
        return self._get(("g", axis), lambda: der1(
            self._p, axis, self._inv[axis], self._lo, self._n, self._xw))

    @property
    def gradient(self):
        return tuple(self.grad(a) for a in range(3))

    def hess(self, a: int, b: int) -> jnp.ndarray:
        if a > b:
            a, b = b, a
        if a == b:
            return self._get(("h", a, a), lambda: der2(
                self._p, a, self._inv[a], self._lo, self._n, self._xw))
        return self._get(("h", a, b), lambda: der_cross(
            self._p, a, b, self._inv[a], self._inv[b], self._lo, self._n,
            self._xw))

    @property
    def laplace(self) -> jnp.ndarray:
        return self.hess(0, 0) + self.hess(1, 1) + self.hess(2, 2)

    def _get(self, key, fn):
        if key not in self._cache:
            self._cache[key] = fn()
        return self._cache[key]
