"""Stencil compute ops: XLA-fused kernels and Pallas fast paths.

``PUBLIC_OPS`` is the lint-coverage manifest — the registry metadata
hook the static analyzer's drift guard checks (tests/test_lint.py):
every public op entry point shipped from this package maps to the
``analysis/registry.default_targets()`` name (prefix) that covers it.
Adding a public op without registering an analysis target fails the
guard — new kernels cannot silently escape the lint gate.

Keys are dotted op names rooted at the package; values are the
covering registry-target prefix (usually the same name; families
audited through one representative, e.g. ``jacobi7_wrap2_pallas``
being a steps=2 alias of ``jacobi7_wrapn_pallas``, point at it).
"""

from __future__ import annotations

from typing import Dict

PUBLIC_OPS: Dict[str, str] = {
    # XLA-fused stencil ops (footprint-audited against their Radius)
    "ops.stencil_kernels.jacobi7": "ops.stencil_kernels.jacobi7",
    "ops.stencil_kernels.laplacian27": "ops.stencil_kernels.laplacian27",
    "ops.stencil_kernels.central_diff": "ops.stencil_kernels.central_diff",
    "ops.fd6.der1": "ops.fd6.der1",
    "ops.fd6.der2": "ops.fd6.der2",
    "ops.fd6.der_cross": "ops.fd6.der_cross",
    # Pallas single-chip fast paths (VMEM/tiling-audited)
    "ops.pallas_stencil.jacobi7_pallas": "ops.pallas_stencil.jacobi7_pallas",
    "ops.pallas_stencil.jacobi7_wrap_pallas":
        "ops.pallas_stencil.jacobi7_wrap_pallas",
    "ops.pallas_stencil.jacobi7_wrapn_pallas":
        "ops.pallas_stencil.jacobi7_wrapn_pallas",
    "ops.pallas_stencil.jacobi7_wrap2_pallas":
        "ops.pallas_stencil.jacobi7_wrapn_pallas",  # steps=2 alias
    "ops.pallas_stencil.laplace6_pallas":
        "ops.pallas_stencil.laplace6_pallas",
    "ops.pallas_mhd.mhd_substep_wrap_pallas":
        "ops.pallas_mhd.mhd_substep_wrap_pallas",
    "ops.pallas_mhd.mhd_substep01_wrap_pallas":
        "ops.pallas_mhd.mhd_substep01_wrap_pallas",
    # Pallas multi-chip halo / overlap paths (DMA- and VMEM-audited)
    "ops.pallas_halo.jacobi7_halo_pallas":
        "ops.pallas_halo.jacobi7_halo_pallas",
    "ops.pallas_halo.jacobi7_halon_pallas":
        "ops.pallas_halo.jacobi7_halon_pallas",
    "ops.pallas_halo.jacobi7_halo2_pallas":
        "ops.pallas_halo.jacobi7_halon_pallas",  # steps=2 alias
    "ops.pallas_halo.mhd_substep_halo_pallas":
        "ops.pallas_halo.mhd_substep_halo_pallas",
    "ops.pallas_halo.mhd_substep01_halo_pallas":
        "ops.pallas_halo.mhd_substep01_halo_pallas",
    "ops.pallas_overlap.jacobi7_overlap_pallas":
        "ops.pallas_overlap.jacobi7_overlap_pallas",
    "ops.pallas_mhd_overlap.mhd_substep_overlap":
        "ops.pallas_mhd_overlap.mhd_substep_overlap",
    "ops.pallas_mhd_overlap.mhd_substep_overlap_pallas":
        "ops.pallas_mhd_overlap.mhd_substep_overlap",  # inner entry
    "ops.pallas_mhd_overlap.mhd_substep_fixup_pallas":
        "ops.pallas_mhd_overlap.mhd_substep_overlap",  # traced within
}
