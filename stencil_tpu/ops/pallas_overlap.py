"""True comm/compute overlap: one Pallas kernel that exchanges halos
over the ICI with explicit RDMA *while* computing the stencil interior.

This is the TPU re-creation of the reference's whole overlap
architecture — interior kernels launch, transports are polled, exterior
kernels launch once halos land (reference: bin/jacobi3d.cu:296-377,
src/stencil.cu:1081-1118) — as ONE kernel per step:

1. neighbor barrier (destination buffers quiescent),
2. ``make_async_remote_copy`` of the 4 face slabs starts (z/y mesh
   neighbors; x is never mesh-sharded),
3. a hand-rolled double-buffered z-block pipeline computes every output
   block from owned data while the DMAs are in flight — the face cells
   it produces are placeholders,
4. ``wait()`` on the slab-transfer semaphores,
5. thin face passes recompute the two y rows and two z planes from the
   landed slabs, overwriting the placeholders.

The 7-point star needs no corner data, so the exchange is pure face
slabs. Single-count axes fall back to local wrap copies into the same
buffers, so the compute phases are identical at any mesh shape — and
the whole kernel runs under the Pallas TPU interpreter off-TPU
(interpreted inter-device DMA), which is how the multi-chip tests
exercise it on the CPU mesh.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..geometry import Dim3
from .pallas_stencil import on_tpu

# collective_id namespace distinct from parallel/pallas_exchange.py
_OVERLAP_COLLECTIVE_ID = 21

#: schedule-certifier hint (analysis/schedule.py): the kernel arms at
#: most the four face-slab remote copies (z-lo/z-hi/y-lo/y-hi) before
#: the interior compute and drains all four before the face passes —
#: the registry pins the peak so a schedule refactor that raises the
#: in-flight pressure (or stops draining) fails the checker
SCHEDULE_EXPECT = {"max_in_flight": 4}


def _interpret_mode():
    return False if on_tpu() else pltpu.InterpretParams()


def jacobi7_overlap_pallas(interior: jnp.ndarray,
                           origin_zyx: jnp.ndarray,
                           hot_c: Tuple[int, int, int],
                           cold_c: Tuple[int, int, int], sph_r: int,
                           counts: Dim3,
                           block_z: int = 8,
                           interpret: Optional[object] = None
                           ) -> jnp.ndarray:
    """One overlapped Jacobi step on an interior-resident (Z, Y, X)
    shard. Call inside ``shard_map`` over mesh axes ('x','y','z') with
    x unsharded (``counts.x == 1``); ``origin_zyx`` is the shard's
    global interior origin (traced int32 (3,)).

    Semantics match the halo-kernel path (exchange_interior_slabs +
    jacobi7_halo_pallas) — but the slab exchange here is RDMA issued
    from inside the kernel, hidden behind the interior compute.
    """
    if interpret is None:
        interpret = _interpret_mode()
    Z, Y, X = interior.shape
    assert counts.x == 1, "x (lane) axis must not be mesh-sharded"
    if Z < 4 or Y < 2:
        raise ValueError(f"overlap kernel needs Z >= 4, Y >= 2, "
                         f"got {(Z, Y)}")
    bz = block_z
    while bz > 1 and Z % bz:
        bz //= 2
    while bz + 2 > Z:
        bz //= 2
    if bz < 1 or Z % bz:
        raise ValueError(f"no valid z block for Z={Z}")
    dt = jnp.dtype(interior.dtype)
    hx, hy, hz = hot_c
    cx, cy, cz = cold_c
    r2 = sph_r * sph_r
    nzb = Z // bz
    win = bz + 2                      # z window rows per block
    my_count = counts.y
    mz_count = counts.z

    def sources(vals, org, z0, y0):
        """Dirichlet spheres on a (nz, ny, X) region at shard-local
        (z0, y0); ``org`` is the shard's global (z, y, x) origin."""
        nz, ny = vals.shape[0], vals.shape[1]
        gy = (org[1] + y0
              + lax.broadcasted_iota(jnp.int32, (ny, X), 0))
        gx = org[2] + lax.broadcasted_iota(jnp.int32, (ny, X), 1)
        gz = (org[0] + z0
              + lax.broadcasted_iota(jnp.int32, (nz, 1, 1), 0))
        d2h = (gx - hx) ** 2 + (gy - hy) ** 2 + (gz - hz) ** 2
        d2c = (gx - cx) ** 2 + (gy - cy) ** 2 + (gz - cz) ** 2
        vals = jnp.where(d2h <= r2, dt.type(1.0), vals)
        vals = jnp.where(d2c <= r2, dt.type(0.0), vals)
        return vals

    def outer(org, in_hbm, out_hbm, zlo, zhi, ylo, yhi,
              wbuf, obuf, fbuf, frow, fout,
              slab_send, slab_recv, load_sem, store_sem, face_sem):
        # ---- 1. rendezvous: every mesh neighbor we will write into
        # must have entered this kernel (its slab buffers quiescent)
        n_remote_axes = (1 if mz_count > 1 else 0) + \
                        (1 if my_count > 1 else 0)
        if n_remote_axes:
            bsem = pltpu.get_barrier_semaphore()
            if mz_count > 1:
                me = lax.axis_index("z")
                up = lax.rem(me + 1, jnp.int32(mz_count))
                dn = lax.rem(me + jnp.int32(mz_count) - 1,
                             jnp.int32(mz_count))
                pltpu.semaphore_signal(bsem, inc=1, device_id={"z": up})
                pltpu.semaphore_signal(bsem, inc=1, device_id={"z": dn})
            if my_count > 1:
                me = lax.axis_index("y")
                up = lax.rem(me + 1, jnp.int32(my_count))
                dn = lax.rem(me + jnp.int32(my_count) - 1,
                             jnp.int32(my_count))
                pltpu.semaphore_signal(bsem, inc=1, device_id={"y": up})
                pltpu.semaphore_signal(bsem, inc=1, device_id={"y": dn})
            pltpu.semaphore_wait(bsem, 2 * n_remote_axes)

        # ---- 2. start the face-slab exchange. Slab contracts: zlo =
        # z-minus neighbor's top plane; zhi = z-plus neighbor's bottom
        # plane; ylo = y-minus neighbor's last row; yhi = y-plus
        # neighbor's first row (periodic wrap when that axis count is 1).
        copies = []
        if mz_count > 1:
            me = lax.axis_index("z")
            up = lax.rem(me + 1, jnp.int32(mz_count))
            dn = lax.rem(me + jnp.int32(mz_count) - 1,
                         jnp.int32(mz_count))
            copies.append(pltpu.make_async_remote_copy(
                src_ref=in_hbm.at[Z - 1:Z], dst_ref=zlo,
                send_sem=slab_send.at[0], recv_sem=slab_recv.at[0],
                device_id={"z": up}))
            copies.append(pltpu.make_async_remote_copy(
                src_ref=in_hbm.at[0:1], dst_ref=zhi,
                send_sem=slab_send.at[1], recv_sem=slab_recv.at[1],
                device_id={"z": dn}))
        else:
            copies.append(pltpu.make_async_copy(
                in_hbm.at[Z - 1:Z], zlo, slab_recv.at[0]))
            copies.append(pltpu.make_async_copy(
                in_hbm.at[0:1], zhi, slab_recv.at[1]))
        if my_count > 1:
            me = lax.axis_index("y")
            up = lax.rem(me + 1, jnp.int32(my_count))
            dn = lax.rem(me + jnp.int32(my_count) - 1,
                         jnp.int32(my_count))
            copies.append(pltpu.make_async_remote_copy(
                src_ref=in_hbm.at[:, Y - 1:Y], dst_ref=ylo,
                send_sem=slab_send.at[2], recv_sem=slab_recv.at[2],
                device_id={"y": up}))
            copies.append(pltpu.make_async_remote_copy(
                src_ref=in_hbm.at[:, 0:1], dst_ref=yhi,
                send_sem=slab_send.at[3], recv_sem=slab_recv.at[3],
                device_id={"y": dn}))
        else:
            copies.append(pltpu.make_async_copy(
                in_hbm.at[:, Y - 1:Y], ylo, slab_recv.at[2]))
            copies.append(pltpu.make_async_copy(
                in_hbm.at[:, 0:1], yhi, slab_recv.at[3]))
        for c in copies:
            c.start()

        # ---- 3. interior compute while the slabs fly: double-buffered
        # z-block pipeline over owned data. Each block k reads a
        # (bz+2)-row window clamped into [0, Z); rows 0 / Z-1 and
        # columns 0 / Y-1 of the output get placeholder values that
        # phase 5 overwrites.
        def win_start(k):
            s = k * bz - 1
            return jnp.clip(s, 0, Z - win)

        def load(k, slot):
            return pltpu.make_async_copy(
                in_hbm.at[pl.ds(win_start(k), win)],
                wbuf.at[slot], load_sem.at[slot])

        def store(k, slot):
            return pltpu.make_async_copy(
                obuf.at[slot], out_hbm.at[pl.ds(k * bz, bz)],
                store_sem.at[slot])

        def compute(k, slot):
            off = k * bz - win_start(k)        # my rows at [off, off+bz)
            c = wbuf[slot, pl.ds(off, bz)]
            # single boundary planes, clamped at the shard edge — the
            # clamp only affects rows 0 / Z-1 (placeholders; phase 5b
            # overwrites them). Interior rows' zm/zp come from c itself.
            zm0 = wbuf[slot, pl.ds(jnp.maximum(off - 1, 0), 1)]
            zp0 = wbuf[slot, pl.ds(jnp.minimum(off + bz, win - 1), 1)]
            zm = jnp.concatenate([zm0, c[:-1]], axis=0)
            zp = jnp.concatenate([c[1:], zp0], axis=0)
            # y neighbors in-shard; rows 0 / Y-1 clamped (placeholder)
            ym = jnp.concatenate([c[:, 0:1], c[:, :-1]], axis=1)
            yp = jnp.concatenate([c[:, 1:], c[:, -1:]], axis=1)
            xm = pltpu.roll(c, 1, 2)
            xp = pltpu.roll(c, X - 1, 2)
            new = (zm + zp + ym + yp + xm + xp) * dt.type(1.0 / 6.0)
            obuf[slot, pl.ds(0, bz)] = sources(new, org, k * bz, 0)

        load(0, 0).start()

        def body(k, _):
            slot = lax.rem(k, 2)
            nslot = lax.rem(k + 1, 2)

            @pl.when(k + 1 < nzb)
            def _():
                # the next load reuses the other slot; its previous
                # store (k-1) must have drained first
                @pl.when(k >= 1)
                def _():
                    store(k - 1, nslot).wait()
                load(k + 1, nslot).start()

            load(k, slot).wait()
            compute(k, slot)
            store(k, slot).start()
            return 0

        lax.fori_loop(0, nzb, body, 0)
        # drain the last two stores
        @pl.when(nzb >= 2)
        def _():
            store(nzb - 2, lax.rem(nzb - 2, 2)).wait()
        store(nzb - 1, lax.rem(nzb - 1, 2)).wait()

        # ---- 4. halos land
        for c in copies:
            c.wait()

        def sync_copy(src, dst, sem):
            pltpu.make_async_copy(src, dst, sem).start()
            pltpu.make_async_copy(src, dst, sem).wait()

        # ---- 5a. y rows: out[:, 0] and out[:, Y-1] from the y slabs.
        # fbuf stages in[:, edge 2 cols]; frow the slab (ANY -> VMEM);
        # fout the result. Rows z=0 / Z-1 stay placeholders (5b
        # overwrites them).
        for row, slab in ((0, ylo), (Y - 1, yhi)):
            src_lo = 0 if row == 0 else Y - 2
            sync_copy(in_hbm.at[:, pl.ds(src_lo, 2)], fbuf,
                      face_sem.at[0])
            sync_copy(slab, frow, face_sem.at[1])
            A = fbuf[...]                      # (Z, 2, X)
            me_col = 0 if row == 0 else 1      # my row within fbuf
            in_col = 1 if row == 0 else 0      # in-shard y neighbor
            c = A[:, me_col:me_col + 1]        # (Z, 1, X)
            nbr_in = A[:, in_col:in_col + 1]
            zm = jnp.concatenate([c[0:1], c[:-1]], axis=0)
            zp = jnp.concatenate([c[1:], c[-1:]], axis=0)
            xm = pltpu.roll(c, 1, 2)
            xp = pltpu.roll(c, X - 1, 2)
            new = (zm + zp + nbr_in + frow[...] + xm + xp) \
                * dt.type(1.0 / 6.0)
            fout[...] = sources(new, org, 0, row)
            sync_copy(fout, out_hbm.at[:, pl.ds(row, 1)],
                      face_sem.at[1])

        # ---- 5b. z planes: out[0] and out[Z-1] (including y-edge
        # cells from the slabs), overwriting 5a's corner placeholders.
        # wbuf slot 0 is free now; stage [plane; z-inner; zslab] rows
        # in it and the slab y rows in frow.
        for plane, zslab in ((0, zlo), (Z - 1, zhi)):
            zin_row = 1 if plane == 0 else Z - 2
            sync_copy(in_hbm.at[pl.ds(plane, 1)],
                      wbuf.at[0, pl.ds(0, 1)], face_sem.at[2])
            sync_copy(in_hbm.at[pl.ds(zin_row, 1)],
                      wbuf.at[0, pl.ds(1, 1)], face_sem.at[2])
            sync_copy(zslab, wbuf.at[0, pl.ds(2, 1)], face_sem.at[2])
            # the slab rows at this plane: frow[0] <- ylo[plane],
            # frow[1] <- yhi[plane] (frow is (Z,1,X); Z >= 4 > 2)
            sync_copy(ylo.at[pl.ds(plane, 1)],
                      frow.at[pl.ds(0, 1)], face_sem.at[3])
            sync_copy(yhi.at[pl.ds(plane, 1)],
                      frow.at[pl.ds(1, 1)], face_sem.at[3])
            c = wbuf[0, 0]                     # (Y, X)
            zin = wbuf[0, 1]
            zsl = wbuf[0, 2]
            ym = jnp.concatenate([frow[0], c[:-1]], axis=0)
            yp = jnp.concatenate([c[1:], frow[1]], axis=0)
            xm = pltpu.roll(c, 1, 1)
            xp = pltpu.roll(c, X - 1, 1)
            new = (ym + yp + zin + zsl + xm + xp) * dt.type(1.0 / 6.0)
            fplane = obuf.at[0, pl.ds(0, 1)]
            obuf[0, pl.ds(0, 1)] = sources(new[None], org, plane, 0)
            sync_copy(fplane, out_hbm.at[pl.ds(plane, 1)],
                      face_sem.at[3])

    out_shapes = [
        jax.ShapeDtypeStruct((Z, Y, X), dt),      # the new field
        jax.ShapeDtypeStruct((1, Y, X), dt),      # zlo slab buffer
        jax.ShapeDtypeStruct((1, Y, X), dt),      # zhi
        jax.ShapeDtypeStruct((Z, 1, X), dt),      # ylo
        jax.ShapeDtypeStruct((Z, 1, X), dt),      # yhi
    ]
    outs = pl.pallas_call(
        outer,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 5,
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((2, win, Y, X), dt),       # wbuf (in windows)
            pltpu.VMEM((2, bz, Y, X), dt),        # obuf (out blocks)
            pltpu.VMEM((Z, 2, X), dt),            # fbuf (y face cols)
            pltpu.VMEM((Z, 1, X), dt),            # frow (y slab, VMEM)
            pltpu.VMEM((Z, 1, X), dt),            # fout (y face out)
            pltpu.SemaphoreType.DMA((4,)),        # slab send
            pltpu.SemaphoreType.DMA((4,)),        # slab recv
            pltpu.SemaphoreType.DMA((2,)),        # window loads
            pltpu.SemaphoreType.DMA((2,)),        # block stores
            pltpu.SemaphoreType.DMA((4,)),        # face traffic
        ],
        compiler_params=pltpu.CompilerParams(
            collective_id=_OVERLAP_COLLECTIVE_ID, has_side_effects=True),
        interpret=interpret,
    )(jnp.asarray(origin_zyx, jnp.int32), interior)
    return outs[0]
