"""In-kernel RDMA comm/compute overlap for the MHD substeps.

The reference earns its overlap machinery in the astaroth app: every RK
substep runs interior-launch / exchange / exterior-launch over 26
per-region streams (reference: astaroth/astaroth.cu:552-646,476-486;
polled transports src/stencil.cu:1081-1118). This module is the TPU
re-creation for the multi-device slab layout, following the proven
Jacobi pattern (ops/pallas_overlap.py) at MHD scale:

* ``mhd_substep_overlap_pallas`` — ONE grid kernel per substep that
  (a) barriers with its mesh neighbors, (b) issues the radius-R slab
  RDMA for all 8 fields (z faces + the z-extended y faces, corner
  ride-along pieces fired as soon as the z slabs land), and (c) streams
  (bz, by, X) blocks through the SAME fused ``mhd_rates`` compute as
  the halo megakernel while the DMAs fly — reading CLAMPED in-shard
  windows, so blocks at shard edges hold placeholder values; the landed
  slab buffers are kernel outputs in the standard
  ``exchange_interior_slabs`` layout contract.
* ``mhd_substep_fixup_pallas`` — thin strip kernels (grids remapped
  onto only the z-edge / y-edge block rows, outputs aliased onto the
  overlap kernel's results) that recompute the edge blocks from the
  landed slabs via the halo kernel's own window plan — the exterior
  launch of the reference choreography.
* ``mhd_substep_overlap`` — the per-substep driver composing the two.

Even grids, x unsharded (the slab-layout contract); numerics match
``mhd_substep_halo_pallas`` exactly (same window values, same update).
The whole choreography runs under the Pallas TPU interpreter off-TPU
(interpreted inter-device DMA), which is how the multi-chip tests and
the race detector exercise it on the CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..geometry import Dim3
from .pallas_halo import R, _mhd_window_plan, mhd_halo_blocks
from .pallas_mhd import compute_dtype, mhd_tile
from .pallas_stencil import default_interpret, on_tpu

# collective_id namespace distinct from pallas_overlap (21) and
# pallas_exchange
_MHD_OVERLAP_COLLECTIVE_ID = 23

#: schedule-certifier hint (analysis/schedule.py): peak outstanding
#: remote copies across the phased z/y slab + corner exchange on the
#: registry's (1,2,2) certification mesh — all eight fields' z-lo/z-hi
#: + y-lo/y-hi slabs plus both yz corner legs fly together before the
#: phase-B waits (8 fields x 6 copies). Pinned so a phase reordering
#: that piles more copies in flight (or stops draining a phase) fails
#: the schedule checker instead of re-certifying
SCHEDULE_EXPECT = {"max_in_flight": 48}


def _interpret_mode():
    return False if on_tpu() else pltpu.InterpretParams()


def mhd_substep_overlap_pallas(fields: Dict[str, jnp.ndarray],
                               w: Optional[Dict[str, jnp.ndarray]],
                               s: int, prm, dt_phys: float,
                               counts: Dim3,
                               block_z: int = 8, block_y: int = 32,
                               pair: bool = False,
                               write_w: bool = True,
                               interpret: Optional[object] = None):
    """One overlapped RK3 MHD substep on interior-resident (Z, Y, X)
    shards: slab RDMA issued from inside the kernel, the fused
    ``mhd_rates`` interior compute running behind the in-flight DMAs.
    Call inside ``shard_map`` over mesh axes ('x','y','z') with x
    unsharded. Returns ``(new_fields, new_w, slabs)`` where edge
    blocks of the f/w outputs are PLACEHOLDERS (clamped windows) and
    ``slabs[q]`` holds the landed halo data in the
    ``exchange_interior_slabs(rz=bz, ry=mhd_tile(dtype), radius_rows=R,
    y_z_extended=True)`` layout — feed both to
    ``mhd_substep_fixup_pallas``. Reference choreography:
    astaroth/astaroth.cu:552-646 (interior launch + transports in
    flight), compressed into one kernel.

    ``pair=True`` fuses RK substeps 0+1 into the pass (the
    STENCIL_MHD_PAIR temporal blocking, ``pallas_mhd.mhd_pair_update``):
    ``s`` and the incoming ``w`` are ignored (alpha_0 == 0), the
    windows and the RDMA carry radius 2R, and the slabs come back with
    2R valid rows.

    Dead-w elision as in ``mhd_substep_wrap_pallas``: ``w=None`` drops
    the w read sweep (valid only at alpha_s == 0, i.e. substep 0 —
    pair mode always elides it); ``write_w=False`` drops the w write
    sweep (substep 2, whose w no one reads) and returns ``new_w``
    as None. write_w elision is bit-exact; w=None is ~1-ulp (compiler
    fusion changes without the 0*w term).
    """
    from ..models.astaroth import FIELDS, RK3_ALPHA, RK3_BETA, mhd_rates
    from .fd6 import FieldData
    from .pallas_mhd import mhd_pair_update

    if interpret is None:
        interpret = _interpret_mode()
    assert counts.x == 1, "x (lane) axis must not be mesh-sharded"
    hr = 2 * R if pair else R      # halo rows windows and DMAs carry
    Z, Y, X = fields[FIELDS[0]].shape
    dtype = fields[FIELDS[0]].dtype
    esub = mhd_tile(dtype)         # slab row tile: 8 f32/f64, 16 bf16
    comp = compute_dtype(dtype)    # bf16 stores, f32 computes
    bz, by = mhd_halo_blocks(Z, Y, block_z, block_y, esub, X=X,
                             itemsize=jnp.dtype(dtype).itemsize)
    assert hr <= min(bz, esub), (hr, bz, esub)
    dta = jnp.dtype(comp)
    inv_ds = (1.0 / prm.dsx, 1.0 / prm.dsy, 1.0 / prm.dsz)
    alpha = float(RK3_ALPHA[s])
    beta = float(RK3_BETA[s])
    dt_ = float(dt_phys)
    pad_lo = Dim3(0, R, R)
    interior = Dim3(X, by, bz)
    nzg = Z // bz
    nyg = Y // by
    mz = counts.z
    my = counts.y
    nf = len(FIELDS)
    zext = Z + 2 * bz

    # the halo kernel's own window plan in slabless mode: clamped
    # in-shard segments only, one source of truth for the geometry
    field_specs, inputs_for_field, select_window = _mhd_window_plan(
        Z, Y, X, bz, by, rr=hr, slabless=True, esub=esub)
    nseg = len(field_specs)
    main_spec = pl.BlockSpec((bz, by, X), lambda kz, ky: (kz, ky, 0))

    # pair mode (and w=None at alpha_s == 0) never reads the incoming
    # w: feeding it anyway would stream a full HBM read sweep of all 8
    # w fields per pass — exactly the sweep the elision exists to save
    # — so the w inputs vanish from the operand list entirely
    if w is None and not pair:
        assert alpha == 0.0, "w=None is only valid when alpha_s == 0"
    nw = 0 if (pair or w is None) else nf
    nwo = nf if write_w else 0

    def kern(*refs):
        field_refs = refs[:nseg * nf]
        w_refs = refs[nseg * nf:nseg * nf + nw]
        any_refs = refs[nseg * nf + nw:nseg * nf + nw + nf]
        outs = refs[nseg * nf + nw + nf:-2]
        out_f = outs[:nf]
        out_w = outs[nf:nf + nwo]
        zlo_o = outs[nf + nwo:2 * nf + nwo]
        zhi_o = outs[2 * nf + nwo:3 * nf + nwo]
        ylo_o = outs[3 * nf + nwo:4 * nf + nwo]
        yhi_o = outs[4 * nf + nwo:5 * nf + nwo]
        send = refs[-2]
        recv = refs[-1]
        kz = pl.program_id(0)
        ky = pl.program_id(1)
        first = jnp.logical_and(kz == 0, ky == 0)
        last = jnp.logical_and(kz == nzg - 1, ky == nyg - 1)

        def nbr(axis, n, up):
            me = lax.axis_index(axis)
            d = (lax.rem(me + 1, jnp.int32(n)) if up
                 else lax.rem(me + jnp.int32(n) - 1, jnp.int32(n)))
            return {axis: d}

        def z_copies(i):
            """slots 0 (zlo to z-up) / 1 (zhi to z-down); local wrap
            copies on a 1-count axis (sem: recv only)."""
            f_any = any_refs[i]
            if mz > 1:
                return [
                    pltpu.make_async_remote_copy(
                        src_ref=f_any.at[Z - hr:Z],
                        dst_ref=zlo_o[i].at[bz - hr:bz],
                        send_sem=send.at[i, 0], recv_sem=recv.at[i, 0],
                        device_id=nbr("z", mz, True)),
                    pltpu.make_async_remote_copy(
                        src_ref=f_any.at[0:hr],
                        dst_ref=zhi_o[i].at[0:hr],
                        send_sem=send.at[i, 1], recv_sem=recv.at[i, 1],
                        device_id=nbr("z", mz, False)),
                ]
            return [
                pltpu.make_async_copy(f_any.at[Z - hr:Z],
                                      zlo_o[i].at[bz - hr:bz],
                                      recv.at[i, 0]),
                pltpu.make_async_copy(f_any.at[0:hr], zhi_o[i].at[0:hr],
                                      recv.at[i, 1]),
            ]

        def y_interior_copies(i):
            """slots 2/3: the Z interior rows of the z-extended y
            faces (no z-slab dependency — fired at entry)."""
            f_any = any_refs[i]
            if my > 1:
                return [
                    pltpu.make_async_remote_copy(
                        src_ref=f_any.at[:, Y - hr:Y],
                        dst_ref=ylo_o[i].at[bz:bz + Z, esub - hr:esub],
                        send_sem=send.at[i, 2], recv_sem=recv.at[i, 2],
                        device_id=nbr("y", my, True)),
                    pltpu.make_async_remote_copy(
                        src_ref=f_any.at[:, 0:hr],
                        dst_ref=yhi_o[i].at[bz:bz + Z, 0:hr],
                        send_sem=send.at[i, 3], recv_sem=recv.at[i, 3],
                        device_id=nbr("y", my, False)),
                ]
            return [
                pltpu.make_async_copy(f_any.at[:, Y - hr:Y],
                                      ylo_o[i].at[bz:bz + Z,
                                                  esub - hr:esub],
                                      recv.at[i, 2]),
                pltpu.make_async_copy(f_any.at[:, 0:hr],
                                      yhi_o[i].at[bz:bz + Z, 0:hr],
                                      recv.at[i, 3]),
            ]

        def y_corner_copies(i):
            """slots 4-7: the R-row yz corner pieces of the y faces,
            sourced from MY landed z slabs (hence fired only after the
            slot-0/1 recv waits) — the corner ride-along of the
            sequential-sweep rule, as explicit messages."""
            pieces = [
                (zlo_o[i].at[bz - hr:bz, Y - hr:Y],
                 ylo_o[i].at[bz - hr:bz, esub - hr:esub], True, 4),
                (zhi_o[i].at[0:hr, Y - hr:Y],
                 ylo_o[i].at[bz + Z:bz + Z + hr, esub - hr:esub],
                 True, 5),
                (zlo_o[i].at[bz - hr:bz, 0:hr],
                 yhi_o[i].at[bz - hr:bz, 0:hr], False, 6),
                (zhi_o[i].at[0:hr, 0:hr],
                 yhi_o[i].at[bz + Z:bz + Z + hr, 0:hr], False, 7),
            ]
            out = []
            for src, dst, up, slot in pieces:
                if my > 1:
                    out.append(pltpu.make_async_remote_copy(
                        src_ref=src, dst_ref=dst,
                        send_sem=send.at[i, slot],
                        recv_sem=recv.at[i, slot],
                        device_id=nbr("y", my, up)))
                else:
                    out.append(pltpu.make_async_copy(src, dst,
                                                     recv.at[i, slot]))
            return out

        # ---- phase A (first grid step): rendezvous, then fire the z
        # slabs and the y interior rows for all fields
        @pl.when(first)
        def _():
            n_remote_axes = (1 if mz > 1 else 0) + (1 if my > 1 else 0)
            if n_remote_axes:
                bsem = pltpu.get_barrier_semaphore()
                if mz > 1:
                    pltpu.semaphore_signal(bsem, inc=1,
                                           device_id=nbr("z", mz, True))
                    pltpu.semaphore_signal(bsem, inc=1,
                                           device_id=nbr("z", mz, False))
                if my > 1:
                    pltpu.semaphore_signal(bsem, inc=1,
                                           device_id=nbr("y", my, True))
                    pltpu.semaphore_signal(bsem, inc=1,
                                           device_id=nbr("y", my, False))
                pltpu.semaphore_wait(bsem, 2 * n_remote_axes)
            for i in range(nf):
                for c in z_copies(i) + y_interior_copies(i):
                    c.start()

        # ---- interior compute for this block, behind the DMAs
        wins = {q: select_window(field_refs[nseg * i:nseg * (i + 1)])
                for i, q in enumerate(FIELDS)}
        if pair:
            f2, w2 = mhd_pair_update(wins, prm, dtype, dt_phys, bz, by)
            for i, q in enumerate(FIELDS):
                if nwo:
                    out_w[i][...] = w2[q]
                out_f[i][...] = f2[q]
        else:
            data = {q: FieldData(wins[q].astype(comp), inv_ds,
                                 pad_lo, interior, x_wrap=True)
                    for q in FIELDS}
            rates = mhd_rates(data, prm, comp)
            for i, q in enumerate(FIELDS):
                wq = dta.type(dt_) * rates[q]
                if nw:
                    wq = (dta.type(alpha) * w_refs[i][...].astype(comp)
                          + wq)
                if nwo:
                    out_w[i][...] = wq.astype(dtype)
                out_f[i][...] = (data[q].value
                                 + dta.type(beta) * wq).astype(dtype)

        # ---- phase B (still the first grid step, after one block of
        # compute): z slabs have landed — fire the corner pieces
        @pl.when(first)
        def _():
            for i in range(nf):
                for c in z_copies(i):
                    c.wait()
                for c in y_corner_copies(i):
                    c.start()

        # ---- phase C (last grid step): drain everything else
        @pl.when(last)
        def _():
            for i in range(nf):
                for c in y_interior_copies(i) + y_corner_copies(i):
                    c.wait()

    in_specs = []
    inputs = []
    for q in FIELDS:
        in_specs.extend(field_specs)
        inputs.extend(inputs_for_field(fields[q]))
    if nw:
        for q in FIELDS:
            in_specs.append(main_spec)
            inputs.append(w[q])
    for q in FIELDS:
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        inputs.append(fields[q])

    out_shape = ([jax.ShapeDtypeStruct((Z, Y, X), dtype)] * (nf + nwo)
                 + [jax.ShapeDtypeStruct((bz, Y, X), dtype)] * (2 * nf)
                 + [jax.ShapeDtypeStruct((zext, esub, X), dtype)]
                 * (2 * nf))
    out_specs = ([main_spec] * (nf + nwo)
                 + [pl.BlockSpec(memory_space=pl.ANY)] * (4 * nf))

    outs = pl.pallas_call(
        kern,
        grid=(nzg, nyg),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.SemaphoreType.DMA((nf, 8)),
                        pltpu.SemaphoreType.DMA((nf, 8))],
        compiler_params=pltpu.CompilerParams(
            collective_id=_MHD_OVERLAP_COLLECTIVE_ID,
            has_side_effects=True,
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(*inputs)
    new_f = {q: outs[i] for i, q in enumerate(FIELDS)}
    new_w = ({q: outs[nf + i] for i, q in enumerate(FIELDS)}
             if write_w else None)
    base = nf + nwo
    slabs = {}
    for i, q in enumerate(FIELDS):
        slabs[q] = {"zlo": outs[base + i], "zhi": outs[base + nf + i],
                    "ylo": outs[base + 2 * nf + i],
                    "yhi": outs[base + 3 * nf + i]}
    return new_f, new_w, slabs


def mhd_substep_fixup_pallas(fields: Dict[str, jnp.ndarray],
                             w: Optional[Dict[str, jnp.ndarray]],
                             f_partial: Dict[str, jnp.ndarray],
                             w_partial: Optional[Dict[str, jnp.ndarray]],
                             slabs: Dict[str, Dict[str, jnp.ndarray]],
                             s: int, prm, dt_phys: float, strip: str,
                             block_z: int = 8, block_y: int = 32,
                             pair: bool = False,
                             interpret: Optional[object] = None
                             ) -> Tuple[Dict[str, jnp.ndarray],
                                        Optional[Dict[str, jnp.ndarray]]]:
    """Exterior pass of the overlapped substep: recompute the shard-edge
    blocks from the landed slabs, writing into ``f_partial``/
    ``w_partial`` via output aliasing (unvisited blocks keep the
    overlap kernel's interior results). ``strip`` selects the z-edge
    block rows ("z": kz in {0, nzg-1}, all ky) or the y-edge columns
    excluding those rows ("y": ky in {0, nyg-1}, kz in [1, nzg-1));
    together they cover exactly the blocks whose clamped windows were
    placeholders. Window values come from the halo kernel's own
    ``_mhd_window_plan`` (same slab selection → numerics identical to
    ``mhd_substep_halo_pallas``). ``fields``/``w`` are the PRE-substep
    state. ``pair=True`` recomputes the fused substep-0+1 update on
    radius-2R windows (slabs must carry 2R rows). Dead-w elision
    mirrors the overlap kernel: ``w=None`` drops the w read (valid
    only at alpha_s == 0); ``w_partial=None`` drops the w outputs and
    aliases (the substep-2 case — the returned new_w is then None).
    Reference: the exterior kernel launches of
    astaroth/astaroth.cu:552-646."""
    from ..models.astaroth import FIELDS, RK3_ALPHA, RK3_BETA, mhd_rates
    from .fd6 import FieldData
    from .pallas_mhd import mhd_pair_update

    if interpret is None:
        interpret = default_interpret()
    hr = 2 * R if pair else R
    Z, Y, X = fields[FIELDS[0]].shape
    esub = mhd_tile(fields[FIELDS[0]].dtype)
    comp = compute_dtype(fields[FIELDS[0]].dtype)
    bz, by = mhd_halo_blocks(Z, Y, block_z, block_y, esub, X=X,
                             itemsize=jnp.dtype(
                                 fields[FIELDS[0]].dtype).itemsize)
    nzg = Z // bz
    nyg = Y // by
    if strip == "z":
        grid = (min(nzg, 2), nyg)

        def remap(i, j):
            return jnp.where(i == 0, 0, nzg - 1), j
    else:
        assert nzg > 2, "y strip only exists between the z strips"
        grid = (nzg - 2, min(nyg, 2))

        def remap(i, j):
            return i + 1, jnp.where(j == 0, 0, nyg - 1)

    dtype = fields[FIELDS[0]].dtype
    dta = jnp.dtype(comp)
    inv_ds = (1.0 / prm.dsx, 1.0 / prm.dsy, 1.0 / prm.dsz)
    alpha = float(RK3_ALPHA[s])
    beta = float(RK3_BETA[s])
    dt_ = float(dt_phys)
    pad_lo = Dim3(0, R, R)
    interior = Dim3(X, by, bz)
    nf = len(FIELDS)

    plan_specs, inputs_for_field, select_window = _mhd_window_plan(
        Z, Y, X, bz, by, rr=hr, esub=esub)
    nseg = len(plan_specs)

    def rm(spec):
        return pl.BlockSpec(
            spec.block_shape,
            functools.partial(lambda i, j, m: m(*remap(i, j)),
                              m=spec.index_map))

    field_specs = [rm(sp) for sp in plan_specs]
    main_spec = rm(pl.BlockSpec((bz, by, X), lambda kz, ky: (kz, ky, 0)))

    # pair (and w=None at alpha_s == 0) never reads w
    if w is None and not pair:
        assert alpha == 0.0, "w=None is only valid when alpha_s == 0"
    nw = 0 if (pair or w is None) else nf
    write_w = w_partial is not None
    nwo = nf if write_w else 0

    def kern(*refs):
        field_refs = refs[:nseg * nf]
        w_refs = refs[nseg * nf:nseg * nf + nw]
        # aliased f_partial/w_partial inputs follow; never read in-kern
        out_f = refs[nseg * nf + nw + nf + nwo:
                     nseg * nf + nw + nwo + 2 * nf]
        out_w = refs[nseg * nf + nw + nwo + 2 * nf:]
        kz, ky = remap(pl.program_id(0), pl.program_id(1))
        wins = {q: select_window(field_refs[nseg * i:nseg * (i + 1)],
                                 kz=kz, ky=ky)
                for i, q in enumerate(FIELDS)}
        if pair:
            f2, w2 = mhd_pair_update(wins, prm, dtype, dt_phys, bz, by)
            for i, q in enumerate(FIELDS):
                if nwo:
                    out_w[i][...] = w2[q]
                out_f[i][...] = f2[q]
            return
        data = {q: FieldData(wins[q].astype(comp), inv_ds, pad_lo,
                             interior, x_wrap=True) for q in FIELDS}
        rates = mhd_rates(data, prm, comp)
        for i, q in enumerate(FIELDS):
            wq = dta.type(dt_) * rates[q]
            if nw:
                wq = dta.type(alpha) * w_refs[i][...].astype(comp) + wq
            if nwo:
                out_w[i][...] = wq.astype(dtype)
            out_f[i][...] = (data[q].value
                             + dta.type(beta) * wq).astype(dtype)

    in_specs = []
    inputs = []
    for q in FIELDS:
        in_specs.extend(field_specs)
        inputs.extend(inputs_for_field(fields[q], slabs[q]))
    if nw:
        for q in FIELDS:
            in_specs.append(main_spec)
            inputs.append(w[q])
    alias_base = len(inputs)
    for q in FIELDS:
        in_specs.append(main_spec)
        inputs.append(f_partial[q])
    if write_w:
        for q in FIELDS:
            in_specs.append(main_spec)
            inputs.append(w_partial[q])

    out_shape = [jax.ShapeDtypeStruct((Z, Y, X), dtype)
                 for _ in range(nf + nwo)]
    out_specs = [main_spec] * (nf + nwo)
    aliases = {alias_base + i: i for i in range(nf + nwo)}

    outs = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        input_output_aliases=aliases,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(*inputs)
    new_f = {q: outs[i] for i, q in enumerate(FIELDS)}
    new_w = ({q: outs[nf + i] for i, q in enumerate(FIELDS)}
             if write_w else None)
    return new_f, new_w


def mhd_substep_overlap(fields: Dict[str, jnp.ndarray],
                        w: Optional[Dict[str, jnp.ndarray]],
                        s: int, prm, dt_phys: float, counts: Dim3,
                        block_z: int = 8, block_y: int = 32,
                        pair: bool = False,
                        write_w: bool = True,
                        interpret: Optional[object] = None
                        ) -> Tuple[Dict[str, jnp.ndarray],
                                   Optional[Dict[str, jnp.ndarray]]]:
    """One full overlapped substep: RDMA-overlap interior kernel, then
    the z- and y-strip exterior fix-ups. Drop-in equivalent of an
    exchange + ``mhd_substep_halo_pallas`` call (same numerics), with
    the exchange hidden behind the interior compute. ``pair=True`` is
    the fused substep-0+1 equivalent (one radius-2R overlapped
    exchange + one pass for two substeps). Dead-w elision as in
    ``mhd_substep_wrap_pallas``: ``w=None`` skips the w read sweep
    (alpha_s == 0 only), ``write_w=False`` skips the w write sweep
    and returns (new_fields, None)."""
    from ..models.astaroth import FIELDS

    Z, Y, X = fields[FIELDS[0]].shape
    bz, _by = mhd_halo_blocks(Z, Y, block_z, block_y,
                              mhd_tile(fields[FIELDS[0]].dtype), X=X,
                              itemsize=jnp.dtype(
                                  fields[FIELDS[0]].dtype).itemsize)
    nzg = Z // bz
    # the caller's interpret mode passes through VERBATIM: an
    # InterpretParams (e.g. detect_races=True from the sanitizer tests)
    # must reach the aliased fix-up kernels too
    f1, w1, slabs = mhd_substep_overlap_pallas(
        fields, w, s, prm, dt_phys, counts, block_z=block_z,
        block_y=block_y, pair=pair, write_w=write_w,
        interpret=interpret)
    f1, w1 = mhd_substep_fixup_pallas(
        fields, w, f1, w1, slabs, s, prm, dt_phys, "z",
        block_z=block_z, block_y=block_y, pair=pair,
        interpret=interpret)
    if nzg > 2:
        f1, w1 = mhd_substep_fixup_pallas(
            fields, w, f1, w1, slabs, s, prm, dt_phys, "y",
            block_z=block_z, block_y=block_y, pair=pair,
            interpret=interpret)
    return f1, w1
