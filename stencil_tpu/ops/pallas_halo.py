"""Halo-aware fused Pallas kernels for the multi-device slab layout.

These kernels close the gap the single-chip ``*_wrap_pallas`` kernels
leave open: those fuse the periodic wrap into the kernel and therefore
only work on a (1,1,1) mesh, while any real multi-chip mesh used to fall
back to the XLA slicing formulation (~3.5x slower for Jacobi, ~24x for
MHD). Here the shard stays *interior-resident* (unpadded, so the (y, x)
dims keep their natural (8, 128) HBM tiling) and the halo arrives as
thin, separately-exchanged slab arrays (see
``parallel.exchange.exchange_interior_slabs``); the kernel assembles
each block's stencil window from

* in-shard neighbor blocks (clamped, non-wrapping index maps), and
* the slab arrays at shard edges (selected by ``program_id``),

so an N-chip mesh runs the same one-read-one-write fused compute the
wrap kernels deliver on one chip. This is the TPU answer to the
reference running its fused ``solve`` kernel at every scale
(reference: astaroth/user_kernels.h:383-453 launched per-region from
astaroth/astaroth.cu:552-646, and bin/jacobi3d.cu:296-377).

Layout contract (all even-grid; ESUB = 8 sublane tile):

* field shard: interior (Z, Y, X), no padding;
* z slabs: (rz, Y, X) — data from the z-neighbors (lo slab holds the
  minus-neighbor's top rz rows, hi slab the plus-neighbor's bottom rz);
* y slabs: (Z, ry, X) for Jacobi, (Z + 2*rz, ry, X) for MHD — the MHD
  variant is z-extended so yz edge/corner data rides along (the
  sequential-sweep corner rule, SURVEY.md section 7 step 3);
* x is NOT mesh-sharded (mesh x-axis must be 1): the lane dimension is
  the worst axis to cut on TPU, so the orchestrator prefers z/y
  decompositions and the periodic x wrap stays in-kernel
  (``pltpu.roll`` / window concat).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..geometry import Dim3
from .pallas_stencil import default_interpret, sublane_tile_bytes

ESUB = 8  # f32 sublane tile; slab row granularity
R = 3     # MHD stencil radius (6th order)

#: schedule-certifier hint (analysis/schedule.py): these kernels issue
#: NO DMA at all — the slab exchange runs outside the kernel — so the
#: peak outstanding remote-copy count is zero by construction; the
#: registry pins it so a kernel that silently GAINS a semaphore or
#: remote copy fails the schedule checker instead of re-certifying
SCHEDULE_EXPECT = {"max_in_flight": 0}


def _shrink_block(dim: int, block: int, mult: int = 1) -> int:
    """Largest power-of-two-ish block <= ``block`` that divides ``dim``
    and is a multiple of ``mult`` (or equals mult). Kept for block-sweep
    scripts; the kernels' own selection goes through the block-shape
    planner (``analysis/tiling.py``)."""
    b = block
    while b > mult and dim % b:
        b //= 2
    if b < mult or dim % b:
        b = mult
    assert dim % b == 0, (dim, block, mult)
    return b


# the kernel-side selection budget (physical VMEM minus slack for
# semaphores/compute temporaries) now lives with the planner; the old
# name stays as an alias for block-sweep scripts
from ..analysis.tiling import TILE_SELECT_BUDGET_BYTES as _VMEM_BUDGET  # noqa: E402,E501


def _jacobi_halo_elems(esub: int):
    """Per-lane-column element model of one jacobi7_halo_pallas grid
    step for the planner: main block + 4 single-plane z rows
    (zprev/znext/zlo/zhi) + 4 esub-col y slabs in, the block out."""
    return lambda bz, by: (bz * by + 4 * by + 4 * bz * esub,
                           bz * by, 0)


def _jacobi_block_bytes(bz: int, by: int, X: int, esub: int,
                        itemsize: int) -> int:
    """Scoped-VMEM estimate for one jacobi7_halo_pallas grid step:
    the streamed blocks of ``_jacobi_halo_elems``, double-buffered by
    the Pallas pipeline (hence the factor 2)."""
    ein, eout, _held = _jacobi_halo_elems(esub)(bz, by)
    return 2 * itemsize * X * (ein + eout)


def fit_jacobi_halo_blocks(Z: int, Y: int, X: int, esub: int,
                           itemsize: int, block_z: int,
                           block_y: int) -> Tuple[int, int]:
    """Planner-derived (bz, by) for the Jacobi halo kernel: the
    cheapest-HBM-traffic legal shape at or below the (block_z, block_y)
    ceiling whose double-buffered footprint fits the physical VMEM
    budget, so kernel="auto" never selects a blocking Mosaic refuses —
    at 512^3 this lands on the judge-measured fast point (8, 128)
    where the old default (16, 128) overflowed (SNIPPETS.md). Raises
    :class:`~stencil_tpu.analysis.tiling.TilingInfeasibleError` when
    no legal shape exists (the old loop silently clamped to the
    sublane floor and let Mosaic fail at compile time)."""
    from ..analysis.tiling import plan_blocks

    return plan_blocks("jacobi7_halo_pallas", Z, Y, X, itemsize,
                       _jacobi_halo_elems(esub), sublane_y=esub,
                       cap_z=block_z, cap_y=block_y).blocks()


def jacobi7_halo_pallas(interior: jnp.ndarray,
                        slabs: Dict[str, jnp.ndarray],
                        origin_zyx: jnp.ndarray,
                        hot_c: Tuple[int, int, int],
                        cold_c: Tuple[int, int, int], sph_r: int,
                        block_z: Optional[int] = None,
                        block_y: Optional[int] = None,
                        interior_len_zy: Optional[jnp.ndarray] = None,
                        interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fused 7-point Jacobi step + Dirichlet sphere sources on one
    interior-resident (Z, Y, X) shard with exchanged halo slabs.

    ``slabs``: ``{"zlo": (rz,Y,X), "zhi": (rz,Y,X), "ylo": (Z,e,X),
    "yhi": (Z,e,X)}`` per the ``exchange_interior_slabs`` alignment
    contract: the adjacent planes are ``zlo[-1]`` / ``zhi[0]`` and the
    adjacent rows ``ylo[:, -1]`` / ``yhi[:, 0]`` (e is ESUB when Y
    allows, else 1; y slabs must NOT be z-extended).
    ``origin_zyx`` is this shard's global interior origin (int32
    (3,), traced under shard_map) for the sphere sources. x must be
    unsharded (periodic x wrap is done in-kernel via ``pltpu.roll``).

    ``interior_len_zy``: traced int32 (2,) = this shard's ACTUAL
    (z, y) interior extents for uneven (+-1) grids (reference:
    partition.hpp:55-86) — (Z, Y) are then capacities with a dead tail
    row/column on short shards; the stencil reads the neighbor slab at
    row Lz-1 / column Ly-1 instead of the capacity edge, and dead cells
    hold don't-care values. Omit for evenly divided grids.

    Semantics match ``jacobi7_wrap_pallas`` (which is the special case
    where every slab is the shard's own wrapped edge).
    """
    if interpret is None:
        interpret = default_interpret()
    Z, Y, X = interior.shape
    if interior_len_zy is None:
        interior_len_zy = jnp.array([Z, Y], jnp.int32)
    esub = slabs["ylo"].shape[1]
    rz = slabs["zlo"].shape[0]
    assert slabs["zlo"].shape == (rz, Y, X), slabs["zlo"].shape
    assert slabs["ylo"].shape == (Z, esub, X), (
        "jacobi halo kernel wants y slabs without z extension",
        slabs["ylo"].shape)
    dt = jnp.dtype(interior.dtype)
    if block_z is None and block_y is None:
        # default blocking: VMEM-fit so kernel="auto" never picks a
        # config Mosaic refuses to compile
        bz, by = fit_jacobi_halo_blocks(Z, Y, X, esub, dt.itemsize,
                                        16, 128)
    else:
        # explicit blocks (tuning sweeps) are honored as-given modulo
        # divisibility (warned once when replaced); a VMEM overflow
        # then surfaces as the compile error the operator asked to
        # measure
        from ..analysis.tiling import snap_blocks

        bz, by = snap_blocks(
            "jacobi7_halo_pallas", Z, Y,
            block_z if block_z is not None else 16,
            block_y if block_y is not None else 128, sublane_y=esub)
    hx, hy, hz = hot_c
    cx, cy, cz = cold_c
    r2 = sph_r * sph_r
    nzb = Z // bz
    nyb = Y // by
    byb = by // esub

    def kern(org, lens, zprev, main, znext, yprev, ynext,
             zlo, zhi, ylo, yhi, out):
        kz = pl.program_id(0)
        ky = pl.program_id(1)
        Lz = lens[0]
        Ly = lens[1]
        c = main[...]                              # (bz, by, X)
        ym_slab = jnp.where(ky == 0, ylo[...], yprev[...])
        yp_slab = jnp.where(ky == nyb - 1, yhi[...], ynext[...])
        ext = jnp.concatenate([ym_slab[:, esub - 1:esub], c,
                               yp_slab[:, 0:1]], axis=1)
        ym = ext[:, :by]
        yp = ext[:, 2:]
        # uneven overlay: the column at the shard's ACTUAL y end reads
        # the y-plus slab, wherever it falls (equals the static pick
        # when Ly == Y, so even grids pay only this select)
        col = ky * by + jax.lax.broadcasted_iota(jnp.int32, (1, by, 1), 1)
        yp = jnp.where(col == Ly - 1, yhi[:, 0:1], yp)
        xm = pltpu.roll(c, 1, 2)
        xp = pltpu.roll(c, X - 1, 2)
        lat = ym + yp + xm + xp
        zm0 = jnp.where(kz == 0, zlo[0], zprev[0])
        zp_last = jnp.where(kz == nzb - 1, zhi[0], znext[0])
        oz = org[0]
        oy = org[1]
        ox = org[2]
        gy = (oy + ky * by
              + jax.lax.broadcasted_iota(jnp.int32, (by, X), 0))
        gx = ox + jax.lax.broadcasted_iota(jnp.int32, (by, X), 1)
        d2yx_h = (gx - hx) ** 2 + (gy - hy) ** 2
        d2yx_c = (gx - cx) ** 2 + (gy - cy) ** 2
        for r in range(bz):
            zm = zm0 if r == 0 else c[r - 1]
            zp = zp_last if r == bz - 1 else c[r + 1]
            grow = kz * bz + r
            # uneven overlay: the row at the shard's actual z end reads
            # the z-plus slab
            zp = jnp.where(grow == Lz - 1, zhi[0], zp)
            new = (lat[r] + zm + zp) * dt.type(1.0 / 6.0)
            gz = oz + grow
            new = jnp.where(d2yx_h + (gz - hz) ** 2 <= r2,
                            dt.type(1.0), new)
            new = jnp.where(d2yx_c + (gz - cz) ** 2 <= r2,
                            dt.type(0.0), new)
            out[r] = new

    # NB index maps: in-shard neighbor specs clamp at the shard edge
    # (the clamped block is loaded but unused — the kernel selects the
    # slab instead); slab specs pin to block 0 when the grid row cannot
    # use them so Pallas's revisit cache skips the refetch.
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),                  # origin
        pl.BlockSpec(memory_space=pltpu.SMEM),                  # lens
        pl.BlockSpec((1, by, X),
                     lambda kz, ky: (jnp.maximum(kz * bz - 1, 0), ky, 0)),
        pl.BlockSpec((bz, by, X), lambda kz, ky: (kz, ky, 0)),
        pl.BlockSpec((1, by, X),
                     lambda kz, ky: (jnp.minimum(kz * bz + bz, Z - 1),
                                     ky, 0)),
        pl.BlockSpec((bz, esub, X),
                     lambda kz, ky: (kz, jnp.maximum(ky * byb - 1, 0), 0)),
        pl.BlockSpec((bz, esub, X),
                     lambda kz, ky: (kz, jnp.minimum(ky * byb + byb,
                                                     Y // esub - 1), 0)),
        pl.BlockSpec((1, by, X),
                     lambda kz, ky: (rz - 1, jnp.where(kz == 0, ky, 0), 0)),
        # zhi is read at the block holding row Lz-1: block nzb-1, or
        # nzb-2 on a short (+-1) shard when bz == 1 — fetch the real
        # y-block for both, pin elsewhere (revisit-cache skip)
        pl.BlockSpec((1, by, X),
                     lambda kz, ky: (0, jnp.where(kz >= nzb - 2, ky, 0),
                                     0)),
        pl.BlockSpec((bz, esub, X), lambda kz, ky: (kz, 0, 0)),
        pl.BlockSpec((bz, esub, X), lambda kz, ky: (kz, 0, 0)),
    ]
    return pl.pallas_call(
        kern,
        grid=(nzb, nyb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bz, by, X), lambda kz, ky: (kz, ky, 0)),
        out_shape=jax.ShapeDtypeStruct((Z, Y, X), interior.dtype),
        # belt-and-braces with fit_jacobi_halo_blocks: the byte model
        # there ignores compute temporaries, so also raise Mosaic's
        # scoped-VMEM ceiling (same precedent as the MHD kernel below)
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(jnp.asarray(origin_zyx, jnp.int32),
      jnp.asarray(interior_len_zy, jnp.int32), interior, interior,
      interior, interior, interior, slabs["zlo"], slabs["zhi"],
      slabs["ylo"], slabs["yhi"])


def _pair_halo_elems(esub: int, steps: int):
    """Per-lane-column element model of one jacobi7_halon_pallas grid
    step: main block + 2N z-in singles + 2N z-slab singles + 4 esub-col
    y slabs + 12N esub-col corner singles in, the block out, plus the
    held assembled (bz+2N, by+2N) window and its first shrinking
    intermediate (allocated once, not pipelined)."""
    N = int(steps)

    def elems(bz: int, by: int):
        ein = (bz * by + 4 * N * by + 4 * bz * esub
               + 12 * N * esub)
        held = ((bz + 2 * N) * (by + 2 * N)
                + (bz + 2 * N - 2) * (by + 2 * N - 2))
        return ein, bz * by, held

    return elems


def _pair_block_bytes(bz: int, by: int, X: int, itemsize: int,
                      steps: int = 2) -> int:
    """Scoped-VMEM estimate for one jacobi7_halon_pallas grid step:
    the streamed blocks of ``_pair_halo_elems`` double-buffered by the
    pipeline, plus the held window bytes."""
    esub = sublane_tile_bytes(itemsize)
    ein, eout, held = _pair_halo_elems(esub, steps)(bz, by)
    return itemsize * X * (2 * (ein + eout) + held)


def fit_pair_halo_blocks(Z: int, Y: int, X: int, itemsize: int,
                         steps: int = 2) -> Tuple[int, int]:
    """Planner-derived (bz, by) for the N-step halo kernel (ceiling
    (16, 128), bz kept >= steps — the in-shard ring reads rows
    kz*bz - N). Raises ``TilingInfeasibleError`` when no legal shape
    fits the budget instead of clamping to the sublane floor."""
    from ..analysis.tiling import plan_blocks

    esub = sublane_tile_bytes(itemsize)
    return plan_blocks(f"jacobi7_halon_pallas[n={steps}]", Z, Y, X,
                       itemsize, _pair_halo_elems(esub, steps),
                       sublane_y=esub, min_z=max(2, int(steps)),
                       cap_z=16, cap_y=128).blocks()


def jacobi7_halon_pallas(interior: jnp.ndarray,
                         slabs: Dict[str, jnp.ndarray],
                         origin_zyx: jnp.ndarray,
                         gsize_zyx: Tuple[int, int, int],
                         hot_c: Tuple[int, int, int],
                         cold_c: Tuple[int, int, int], sph_r: int,
                         steps: int = 2,
                         block_z: Optional[int] = None,
                         block_y: Optional[int] = None,
                         interpret: Optional[bool] = None) -> jnp.ndarray:
    """``steps`` fused Jacobi iterations (+ sphere sources after each)
    per slab exchange on one interior-resident (Z, Y, X) shard —
    temporal blocking for the multi-device halo path, the slab-layout
    counterpart of ``jacobi7_wrapn_pallas``. One radius-N exchange
    feeds N 7-point steps: each (bz, by, X) output block reads a
    (bz+2N, by+2N, X) window (x wraps in-core — x is never
    mesh-sharded), computes ring-extended intermediate steps with
    Dirichlet sources re-imposed at their wrapped GLOBAL positions,
    and finishes on the block. Bit-identical to N
    ``jacobi7_halo_pallas`` calls. Reference semantics:
    bin/jacobi3d.cu:40-85 applied N times per exchange (the reference
    exchanges every iteration; fewer, fatter exchanges are the
    TPU-side trade — same bytes, 1/N the latencies).

    ``slabs`` from ``exchange_interior_slabs(p, counts, rz=bz,
    ry=<sublane tile>, radius_rows=N, y_z_extended=True)``: zlo/zhi
    (bz, Y, X) with the adjacent N rows at zlo[-N:] / zhi[:N]; ylo/yhi
    (Z + 2*bz, esub, X) z-extended by one z block so yz corner data
    rides along (the sequential-sweep corner rule). ``gsize_zyx`` is
    the GLOBAL (Gz, Gy, Gx) — intermediate rings extend into neighbor
    shards, so their source test wraps global coordinates modulo the
    global grid. Even grids only (no uneven overlay — the caller gates
    on rem == 0). Needs steps <= bz and steps <= the sublane tile.
    """
    if interpret is None:
        interpret = default_interpret()
    N = int(steps)
    Z, Y, X = interior.shape
    esub = slabs["ylo"].shape[1]   # dtype sublane tile (8 f32 / 16 bf16)
    assert Y % esub == 0, (Y, esub)
    dt = jnp.dtype(interior.dtype)
    assert esub == sublane_tile_bytes(dt.itemsize), (esub, dt)
    if N < 1 or N > esub:
        raise ValueError(f"halo pair kernel needs 1 <= steps <= {esub},"
                         f" got steps={N}")
    if block_z is None and block_y is None:
        bz, by = fit_pair_halo_blocks(Z, Y, X, dt.itemsize, N)
    else:
        from ..analysis.tiling import snap_blocks

        bz, by = snap_blocks(
            f"jacobi7_halon_pallas[n={N}]", Z, Y,
            block_z if block_z is not None else 16,
            block_y if block_y is not None else 128, sublane_y=esub)
    if bz < N:
        raise ValueError(f"halo pair kernel needs bz >= steps, got "
                         f"bz={bz}, steps={N} for Z={Z}")
    rzb = slabs["zlo"].shape[0]
    assert rzb == bz and slabs["zlo"].shape == (bz, Y, X), \
        ("pair kernel wants (bz, Y, X) z slabs", slabs["zlo"].shape, bz)
    assert slabs["ylo"].shape == (Z + 2 * bz, esub, X), \
        ("pair kernel wants z-extended y slabs", slabs["ylo"].shape)
    Gz, Gy, Gx = gsize_zyx
    hx, hy, hz = hot_c
    cx, cy, cz = cold_c
    r2 = sph_r * sph_r
    nzg = Z // bz
    nyg = Y // by
    nyb = Y // esub
    byb = by // esub

    def sources(vals, org, z0, y0, nz, ny):
        """Re-impose the Dirichlet spheres on an (nz, ny, X) region at
        global origin (org_z + z0, org_y + y0, org_x), coords wrapped
        modulo the GLOBAL grid (ring cells outside the shard belong to
        periodic neighbors)."""
        gy = (org[1] + y0
              + jax.lax.broadcasted_iota(jnp.int32, (ny, X), 0)) % Gy
        gx = (org[2]
              + jax.lax.broadcasted_iota(jnp.int32, (ny, X), 1)) % Gx
        gz = (org[0] + z0
              + jax.lax.broadcasted_iota(jnp.int32, (nz, 1, 1), 0)) % Gz
        d2h = (gx - hx) ** 2 + (gy - hy) ** 2 + (gz - hz) ** 2
        d2c = (gx - cx) ** 2 + (gy - cy) ** 2 + (gz - cz) ** 2
        vals = jnp.where(d2h <= r2, dt.type(1.0), vals)
        return jnp.where(d2c <= r2, dt.type(0.0), vals)

    def jstep(w):
        """One 7-point step on the interior of an (nz, ny, X) window:
        returns (nz-2, ny-2, X); x is periodic in-core."""
        zsum = w[:-2, 1:-1] + w[2:, 1:-1]
        ysum = w[1:-1, :-2] + w[1:-1, 2:]
        xsum = (pltpu.roll(w, 1, 2) + pltpu.roll(w, X - 1, 2))[1:-1, 1:-1]
        return (zsum + ysum + xsum) * dt.type(1.0 / 6.0)

    # ring-row z offsets, ascending: -N..-1 (below), bz..bz+N-1 (above)
    ZOFFS = tuple(range(-N, 0)) + tuple(range(bz, bz + N))
    # ref order: org | main | z-in singles | z-slab singles | y-in
    # slabs | y-slab mains | corner in-shard singles | corner z-slab
    # singles | corner y-slab singles (each corner group (zoff, yside)
    # row-major over ZOFFS)
    n2 = 2 * N

    def kern(*refs):
        org = refs[0]
        main = refs[1]
        zin = refs[2:2 + n2]
        zsl = refs[2 + n2:2 + 2 * n2]
        yi_m, yi_p, ys_m, ys_p = refs[2 + 2 * n2:6 + 2 * n2]
        cin = refs[6 + 2 * n2:6 + 4 * n2]
        czs = refs[6 + 4 * n2:6 + 6 * n2]
        cys = refs[6 + 6 * n2:6 + 8 * n2]
        out = refs[-1]
        kz = pl.program_id(0)
        ky = pl.program_id(1)
        at_zlo = kz == 0
        at_zhi = kz == nzg - 1
        at_ylo = ky == 0
        at_yhi = ky == nyg - 1
        z0 = kz * bz
        y0 = ky * by

        def ring_row(i):
            """One (1, by+2N, X) window row outside the block in z:
            mid from in-shard vs z-slab, corner cols from y-slab (any
            z — it is z-extended) vs z-slab (full-Y) vs in-shard."""
            at_zedge = at_zlo if ZOFFS[i] < 0 else at_zhi
            mid = jnp.where(at_zedge, zsl[i][...], zin[i][...])
            left = jnp.where(at_ylo, cys[2 * i][...],
                             jnp.where(at_zedge, czs[2 * i][...],
                                       cin[2 * i][...]))
            right = jnp.where(at_yhi, cys[2 * i + 1][...],
                              jnp.where(at_zedge, czs[2 * i + 1][...],
                                        cin[2 * i + 1][...]))
            return jnp.concatenate(
                [left[:, esub - N:], mid, right[:, :N]], axis=1)

        rows = [ring_row(i) for i in range(N)]
        ym_slab = jnp.where(at_ylo, ys_m[...], yi_m[...])
        yp_slab = jnp.where(at_yhi, ys_p[...], yi_p[...])
        rows.append(jnp.concatenate(
            [ym_slab[:, esub - N:], main[...], yp_slab[:, :N]], axis=1))
        rows.extend(ring_row(N + i) for i in range(N))
        w = jnp.concatenate(rows, axis=0)        # (bz+2N, by+2N, X)
        for k in range(N):
            w = jstep(w)                         # ring shrinks by 1
            ring = N - 1 - k
            w = sources(w, org, z0 - ring, y0 - ring, bz + 2 * ring,
                        by + 2 * ring)
        out[...] = w

    def clampz1(off):
        # single in-shard row at kz*bz + off, clamped into [0, Z)
        return lambda kz, ky, o=off: (jnp.clip(kz * bz + o, 0, Z - 1),
                                      ky, 0)

    def zslab_row(off):
        # z-slab single row (zlo right-aligned: row bz + off for
        # off < 0; zhi left-aligned: row off - bz), fetched only when
        # the edge grid row needs it (pinned elsewhere: revisit skip)
        row = bz + off if off < 0 else off - bz
        edge_k = 0 if off < 0 else nzg - 1
        return lambda kz, ky, r=row, e=edge_k: (
            r, jnp.where(kz == e, ky, 0), 0)

    def ymap(yside):
        return ((lambda ky: jnp.maximum(ky * byb - 1, 0)) if yside < 0
                else (lambda ky: jnp.minimum(ky * byb + byb, nyb - 1)))

    def corner_in(off, yside):
        return lambda kz, ky, o=off, f=ymap(yside): (
            jnp.clip(kz * bz + o, 0, Z - 1), f(ky), 0)

    def corner_zslab(off, yside):
        row = bz + off if off < 0 else off - bz
        edge_k = 0 if off < 0 else nzg - 1
        return lambda kz, ky, r=row, e=edge_k, f=ymap(yside): (
            r, jnp.where(kz == e, f(ky), 0), 0)

    def corner_yslab(off):
        # y-slab singles: z-extended buffer, origin -bz, valid at every
        # z the window can touch (including off-shard rows)
        return lambda kz, ky, o=off: (bz + kz * bz + o, 0, 0)

    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),                  # origin
        pl.BlockSpec((bz, by, X), lambda kz, ky: (kz, ky, 0)),  # main
    ]
    in_specs += [pl.BlockSpec((1, by, X), clampz1(o)) for o in ZOFFS]
    in_specs += [pl.BlockSpec((1, by, X), zslab_row(o)) for o in ZOFFS]
    in_specs += [
        # y-in esub slabs (clamped; dead at y edges)
        pl.BlockSpec((bz, esub, X),
                     lambda kz, ky: (kz, jnp.maximum(ky * byb - 1, 0), 0)),
        pl.BlockSpec((bz, esub, X),
                     lambda kz, ky: (kz, jnp.minimum(ky * byb + byb,
                                                     nyb - 1), 0)),
        # y-slab main-z blocks (z-extended buffer: block kz+1)
        pl.BlockSpec((bz, esub, X), lambda kz, ky: (kz + 1, 0, 0)),
        pl.BlockSpec((bz, esub, X), lambda kz, ky: (kz + 1, 0, 0)),
    ]
    for off in ZOFFS:
        for yside in (-1, 1):
            in_specs.append(pl.BlockSpec((1, esub, X),
                                         corner_in(off, yside)))
    for off in ZOFFS:
        for yside in (-1, 1):
            in_specs.append(pl.BlockSpec((1, esub, X),
                                         corner_zslab(off, yside)))
    for off in ZOFFS:
        for _yside in (-1, 1):
            in_specs.append(pl.BlockSpec((1, esub, X), corner_yslab(off)))

    zlo, zhi = slabs["zlo"], slabs["zhi"]
    ylo, yhi = slabs["ylo"], slabs["yhi"]

    def zsrc(off):
        return zlo if off < 0 else zhi

    inputs = [jnp.asarray(origin_zyx, jnp.int32), interior]
    inputs += [interior] * n2                      # z-in singles
    inputs += [zsrc(o) for o in ZOFFS]             # z-slab singles
    inputs += [interior, interior, ylo, yhi]
    inputs += [interior] * (2 * n2)                # corner in-shard
    inputs += [z for o in ZOFFS for z in (zsrc(o), zsrc(o))]
    inputs += [ylo, yhi] * n2
    return pl.pallas_call(
        kern,
        grid=(nzg, nyg),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bz, by, X), lambda kz, ky: (kz, ky, 0)),
        out_shape=jax.ShapeDtypeStruct((Z, Y, X), interior.dtype),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(*inputs)


def jacobi7_halo2_pallas(interior: jnp.ndarray,
                         slabs: Dict[str, jnp.ndarray],
                         origin_zyx: jnp.ndarray,
                         gsize_zyx: Tuple[int, int, int],
                         hot_c: Tuple[int, int, int],
                         cold_c: Tuple[int, int, int], sph_r: int,
                         block_z: Optional[int] = None,
                         block_y: Optional[int] = None,
                         interpret: Optional[bool] = None) -> jnp.ndarray:
    """Two fused iterations per exchange — ``jacobi7_halon_pallas``
    with steps=2. Stable named entry for kernel-level tests; the model
    builder calls ``jacobi7_halon_pallas`` directly."""
    return jacobi7_halon_pallas(interior, slabs, origin_zyx, gsize_zyx,
                                hot_c, cold_c, sph_r, steps=2,
                                block_z=block_z, block_y=block_y,
                                interpret=interpret)


def _mhd_halo_elems(esub: int, rr: int = R, nf: int = 8):
    """Per-lane-column element model of one MHD halo-kernel grid step
    (``_mhd_window_plan`` segments x ``nf`` fields, the worst-case
    substep: w read + both output sweeps). Thin-z (default): main +
    2rr in-shard single rows + 2rr slab single rows + 4 esub-col y
    slabs + 12 esub^2 corner segments per field; tiled
    (STENCIL_MHD_THINZ=0) swaps the single rows for esub tiles."""
    from .pallas_mhd import _thin_z

    zrows = 2 * rr if _thin_z() else 2 * esub

    def elems(bz: int, by: int):
        per_field = (bz * by + 2 * zrows * by + 4 * bz * esub
                     + 12 * esub * esub)
        ein = nf * (per_field + bz * by)     # fields + w
        return ein, 2 * nf * bz * by, 0      # f and w outputs

    return elems


def mhd_halo_blocks(Z: int, Y: int, block_z: int = 8,
                    block_y: int = 32, esub: int = ESUB,
                    X: "int | None" = None,
                    itemsize: int = 4) -> Tuple[int, int]:
    """The (bz, by) blocking the MHD halo kernel will use for a
    (Z, Y, ·) shard — exposed so the slab exchange can size its z slabs
    to match (zlo/zhi must be (bz, Y, X); see mhd_substep_halo_pallas).
    Both are multiples of the dtype's ``esub`` sublane tile (8 f32 /
    16 bf16) and divide Z / Y, chosen by the block-shape planner
    against the halo window plan's own byte model (the radius-R
    worst-case substep; the 2R pair kernels reuse the SAME blocks so
    slab shapes stay substep-invariant — their extra VMEM pressure is
    pinned by the ``analysis.tiling`` production-size targets). Pass
    ``X``/``itemsize`` to apply the VMEM budget; without ``X`` (legacy
    callers) only alignment/divisibility constrain, which at budget-
    irrelevant sizes chooses identical shapes."""
    from ..analysis.tiling import plan_blocks

    budget_x = X if X is not None else 1  # X=1: budget never binds
    return plan_blocks("mhd_substep_halo_pallas", Z, Y, budget_x,
                       itemsize, _mhd_halo_elems(esub),
                       n_streams=8, sublane_z=esub, sublane_y=esub,
                       cap_z=block_z, cap_y=block_y).blocks()


def _mhd_window_plan(Z: int, Y: int, X: int, bz: int, by: int,
                     rr: int = R, slabless: bool = False,
                     esub: int = ESUB):
    """One closed unit (specs, inputs_for_field, select_window) for the
    MHD halo kernel's per-field stencil neighborhood on the slab
    layout — the spec list, the matching input ordering, and the
    in-kernel window assembly share one layout decision (each segment
    is registered once with its source kind and index), so they cannot
    desynchronize. Mirrors ops/pallas_mhd._window_plan for the wrap
    kernel.

    ``rr`` is the window radius: R for one substep, 2R for the fused
    substep-0+1 pair (ring recompute). Needs rr <= esub (slab buffers
    are one esub tile wide) and rr <= bz (z slabs hold bz rows); the
    slabs must carry rr valid rows (``radius_rows=rr`` at the
    exchange).

    ``slabless=True`` emits only the clamped IN-SHARD segments (no
    slab arrays exist yet): shard-edge blocks then assemble windows
    from clamped reads and produce placeholder values — the interior
    compute of the RDMA overlap kernel (ops/pallas_mhd_overlap.py),
    whose fix-up strips rewrite those blocks from the landed slabs
    using this same plan with slabs.

    Segment grid: z in {-,0,+} x y in {-,0,+}; edge/corner segments
    carry one spec per possible source (in-shard / z slab / y slab)
    and the kernel selects by ``program_id`` — clamped in-shard maps
    load an unused block at the shard edge, and slab maps pin to a
    constant block when their grid row cannot need them (Pallas's
    revisit cache then skips the fetch).

    Default (thin-z, 29 specs/field): the full-width z-neighbor
    segments are SINGLE ROWS at exactly the radius (z is the majormost,
    untiled dim) — at (8, 64) blocks this cuts per-block read
    amplification from ~4.5x to ~2.2x. STENCIL_MHD_THINZ=0 (tiled, 21
    specs/field) restores esub-row z tiles (the round-3
    hardware-measured layout, kept for A/B). Corner segments always
    stay at esub granularity (a small fraction of the traffic).

    Index-map geometry: the interior array A is (Z, Y, X); z slabs
    (bz, Y, X) with the adjacent planes at zlo[-1] / zhi[0]; y slabs
    (Z + 2*bz, ry=esub, X), z origin at -bz (z-extended so yz corner
    data rides along).
    """
    from .pallas_mhd import _thin_z

    assert rr <= esub and rr <= bz, (rr, esub, bz)
    thin = _thin_z()
    bzb = bz // esub
    byb = by // esub
    nzb8 = Z // esub
    nyb8 = Y // esub
    nzg = Z // bz
    nyg = Y // by

    def clampz(k):            # z-minus 8-row block, in-shard (8-units)
        return jnp.maximum(k * bzb - 1, 0)

    def clampZ(k):            # z-plus
        return jnp.minimum(k * bzb + bzb, nzb8 - 1)

    def clampy(k):            # y-minus (8-units)
        return jnp.maximum(k * byb - 1, 0)

    def clampY(k):            # y-plus
        return jnp.minimum(k * byb + byb, nyb8 - 1)

    specs = []
    kinds = []   # parallel to specs: "f" | "zlo" | "zhi" | "ylo" | "yhi"

    def add(kind, shape, imap):
        """Register one segment spec and return its index; slab
        segments vanish in slabless mode (index None — the selectors
        then keep the clamped in-shard value, which is exactly the
        overlap kernel's placeholder contract)."""
        if slabless and kind != "f":
            return None
        specs.append(pl.BlockSpec(shape, imap))
        kinds.append(kind)
        return len(specs) - 1

    i_main = add("f", (bz, by, X), lambda kz, ky: (kz, ky, 0))
    if thin:
        # zm_y0: exact-radius single rows z = kz*bz + o, o in -rr..-1
        # (in-shard clamped), with zlo slab rows bz+o fetched at kz==0;
        # zp_y0: rows kz*bz + bz + j with zhi slab rows j at the z end
        i_zm_in = [add("f", (1, by, X),
                       lambda kz, ky, o=o: (jnp.clip(kz * bz + o, 0,
                                                     Z - 1), ky, 0))
                   for o in range(-rr, 0)]
        i_zm_zs = [add("zlo", (1, by, X),
                       lambda kz, ky, o=o: (bz + o,
                                            jnp.where(kz == 0, ky, 0),
                                            0))
                   for o in range(-rr, 0)]
        i_zp_in = [add("f", (1, by, X),
                       lambda kz, ky, j=j: (jnp.clip(kz * bz + bz + j,
                                                     0, Z - 1), ky, 0))
                   for j in range(rr)]
        i_zp_zs = [add("zhi", (1, by, X),
                       lambda kz, ky, j=j: (j, jnp.where(kz == nzg - 1,
                                                         ky, 0), 0))
                   for j in range(rr)]
    else:
        i_zm0_in = add("f", (esub, by, X),
                       lambda kz, ky: (clampz(kz), ky, 0))
        i_zm0_zs = add("zlo", (esub, by, X),
                       lambda kz, ky: (bzb - 1,
                                       jnp.where(kz == 0, ky, 0), 0))
        i_zp0_in = add("f", (esub, by, X),
                       lambda kz, ky: (clampZ(kz), ky, 0))
        i_zp0_zs = add("zhi", (esub, by, X),
                       lambda kz, ky: (0, jnp.where(kz == nzg - 1,
                                                    ky, 0), 0))
    # z0_ym / z0_yp: rows y in [ky*by-8, ky*by) / [ky*by+by, +8)
    i_ym_in = add("f", (bz, esub, X),
                  lambda kz, ky: (kz, clampy(ky), 0))
    i_ym_ys = add("ylo", (bz, esub, X), lambda kz, ky: (kz + 1, 0, 0))
    i_yp_in = add("f", (bz, esub, X),
                  lambda kz, ky: (kz, clampY(ky), 0))
    i_yp_ys = add("yhi", (bz, esub, X), lambda kz, ky: (kz + 1, 0, 0))
    # corners (8, 8, X): (in-shard, z-slab, y-slab) source triples
    i_mm = (add("f", (esub, esub, X),
                lambda kz, ky: (clampz(kz), clampy(ky), 0)),
            add("zlo", (esub, esub, X),
                lambda kz, ky: (bzb - 1,
                                jnp.where(kz == 0, clampy(ky), 0), 0)),
            add("ylo", (esub, esub, X),
                lambda kz, ky: ((kz + 1) * bzb - 1, 0, 0)))
    i_mp = (add("f", (esub, esub, X),
                lambda kz, ky: (clampz(kz), clampY(ky), 0)),
            add("zlo", (esub, esub, X),
                lambda kz, ky: (bzb - 1,
                                jnp.where(kz == 0, clampY(ky), 0), 0)),
            add("yhi", (esub, esub, X),
                lambda kz, ky: ((kz + 1) * bzb - 1, 0, 0)))
    i_pm = (add("f", (esub, esub, X),
                lambda kz, ky: (clampZ(kz), clampy(ky), 0)),
            add("zhi", (esub, esub, X),
                lambda kz, ky: (0, jnp.where(kz == nzg - 1,
                                             clampy(ky), 0), 0)),
            add("ylo", (esub, esub, X),
                lambda kz, ky: ((kz + 2) * bzb, 0, 0)))
    i_pp = (add("f", (esub, esub, X),
                lambda kz, ky: (clampZ(kz), clampY(ky), 0)),
            add("zhi", (esub, esub, X),
                lambda kz, ky: (0, jnp.where(kz == nzg - 1,
                                             clampY(ky), 0), 0)),
            add("yhi", (esub, esub, X),
                lambda kz, ky: ((kz + 2) * bzb, 0, 0)))

    def inputs_for_field(f, slabs=None):
        """Input arrays matching ``specs`` order (``slabs`` unused —
        and optional — in slabless mode)."""
        return [f if k == "f" else slabs[k] for k in kinds]

    def select_window(refs, kz=None, ky=None) -> jnp.ndarray:
        """Assemble one field's (bz+2rr, by+2rr, X) stencil window from
        the segment refs, selecting slab sources at shard edges;
        x wraps per-derivative via pltpu.roll (x unsharded => in-core
        wrap IS the global periodic wrap). ``kz``/``ky`` override the
        block coordinates for kernels whose grid is remapped onto a
        subset of blocks (the overlap fix-up strips)."""
        if kz is None:
            kz = pl.program_id(0)
        if ky is None:
            ky = pl.program_id(1)
        at_zlo = kz == 0
        at_zhi = kz == nzg - 1
        at_ylo = ky == 0
        at_yhi = ky == nyg - 1

        def sel(i_in, i_slab, at_edge):
            v = refs[i_in][...]
            if i_slab is None:
                return v
            return jnp.where(at_edge, refs[i_slab][...], v)

        def sel3(idx3, at_zedge, at_yedge):
            # the y slab is z-extended, so a y-edge corner always comes
            # from it (covering simultaneous z edges); otherwise the z
            # slab covers z-edge corners at interior y
            i_in, i_zs, i_ys = idx3
            v = refs[i_in][...]
            if i_zs is not None:
                v = jnp.where(at_zedge, refs[i_zs][...], v)
            if i_ys is not None:
                v = jnp.where(at_yedge, refs[i_ys][...], v)
            return v

        if thin:
            zm_rows = [sel(i_zm_in[i], i_zm_zs[i], at_zlo)
                       for i in range(rr)]
            zp_rows = [sel(i_zp_in[i], i_zp_zs[i], at_zhi)
                       for i in range(rr)]
        else:
            # tiled esub blocks: the adjacent rr rows sit at the tile
            # end (zm) / start (zp)
            zm_y0 = sel(i_zm0_in, i_zm0_zs, at_zlo)
            zp_y0 = sel(i_zp0_in, i_zp0_zs, at_zhi)
            zm_rows = [zm_y0[esub - rr + i:esub - rr + i + 1]
                       for i in range(rr)]
            zp_rows = [zp_y0[i:i + 1] for i in range(rr)]
        z0_ym = sel(i_ym_in, i_ym_ys, at_ylo)
        z0_yp = sel(i_yp_in, i_yp_ys, at_yhi)
        zm_ym = sel3(i_mm, at_zlo, at_ylo)
        zm_yp = sel3(i_mp, at_zlo, at_yhi)
        zp_ym = sel3(i_pm, at_zhi, at_ylo)
        zp_yp = sel3(i_pp, at_zhi, at_yhi)
        c = refs[i_main][...]
        # corner blocks are esub rows; the zm rows sit at block rows
        # esub-rr+i, the zp rows at block rows i
        rows = [
            jnp.concatenate(
                [zm_ym[esub - rr + i:esub - rr + i + 1, esub - rr:],
                 zm_rows[i],
                 zm_yp[esub - rr + i:esub - rr + i + 1, :rr]], axis=1)
            for i in range(rr)
        ]
        rows.append(
            jnp.concatenate([z0_ym[:, esub - rr:], c, z0_yp[:, :rr]],
                            axis=1))
        rows.extend(
            jnp.concatenate([zp_ym[i:i + 1, esub - rr:], zp_rows[i],
                             zp_yp[i:i + 1, :rr]], axis=1)
            for i in range(rr))
        # x stays at full (unsharded, periodic) width: the per-
        # derivative pltpu.roll wrap (FieldData x_wrap) replaces the
        # lane-misaligned X+2R window, matching the wrap kernel
        return jnp.concatenate(rows, axis=0)

    return specs, inputs_for_field, select_window


def mhd_substep_halo_pallas(fields: Dict[str, jnp.ndarray],
                            w: Optional[Dict[str, jnp.ndarray]],
                            slabs: Dict[str, Dict[str, jnp.ndarray]],
                            s: int, prm, dt_phys: float,
                            block_z: int = 8, block_y: int = 32,
                            write_w: bool = True,
                            interpret: Optional[bool] = None
                            ) -> Tuple[Dict[str, jnp.ndarray],
                                       Optional[Dict[str, jnp.ndarray]]]:
    """One fused RK3 MHD substep on interior-resident (Z, Y, X) shards
    with exchanged halo slabs — the multi-device counterpart of
    ``pallas_mhd.mhd_substep_wrap_pallas`` (same RHS evaluation via
    ``mhd_rates`` on an in-core window, same Williamson update;
    reference: astaroth/user_kernels.h:383-453 solve +
    kernels.cu:63-90 integrate_substep), for shards on a z/y-sharded
    mesh (x unsharded, wrap in-core).

    ``slabs[q]`` comes from ``exchange_interior_slabs(fields[q],
    counts, rz=bz, ry=esub, radius_rows=R, y_z_extended=True)`` with
    (bz, _) = ``mhd_halo_blocks(Z, Y, block_z, block_y, esub, X=X,
    itemsize=...)`` — pass the SAME ``X``/``itemsize`` the kernel sees
    (it recomputes the blocking internally with them; a budget-bound
    fit without them would size the slabs differently and trip the
    shape asserts). Returns (new_fields, new_w).

    Dead-w elision as in ``mhd_substep_wrap_pallas``: ``w=None`` drops
    the w read sweep (only valid at alpha_s == 0, i.e. substep 0);
    ``write_w=False`` drops the w write sweep (substep 2, whose w no
    one reads) and returns (new_fields, None). write_w elision is
    bit-exact; w=None is ~1-ulp (compiler fusion changes without the
    0*w term).
    """
    from ..models.astaroth import FIELDS, RK3_ALPHA, RK3_BETA, mhd_rates
    from .fd6 import FieldData
    from .pallas_mhd import compute_dtype, mhd_tile

    if interpret is None:
        interpret = default_interpret()
    Z, Y, X = fields[FIELDS[0]].shape
    dtype = fields[FIELDS[0]].dtype
    esub = mhd_tile(dtype)
    comp = compute_dtype(dtype)
    bz, by = mhd_halo_blocks(Z, Y, block_z, block_y, esub, X=X,
                             itemsize=jnp.dtype(dtype).itemsize)
    for q in FIELDS:
        assert slabs[q]["zlo"].shape == (bz, Y, X), slabs[q]["zlo"].shape
        assert slabs[q]["ylo"].shape == (Z + 2 * bz, esub, X), \
            slabs[q]["ylo"].shape
    inv_ds = (1.0 / prm.dsx, 1.0 / prm.dsy, 1.0 / prm.dsz)
    alpha = float(RK3_ALPHA[s])
    beta = float(RK3_BETA[s])
    if w is None:
        assert alpha == 0.0, "w=None is only valid when alpha_s == 0"
    dt_ = float(dt_phys)
    pad_lo = Dim3(0, R, R)     # x unpadded: wrap via pltpu.roll
    interior = Dim3(X, by, bz)
    nzg = Z // bz
    nyg = Y // by
    field_specs, inputs_for_field, select_window = _mhd_window_plan(
        Z, Y, X, bz, by, esub=esub)
    nseg = len(field_specs)    # layout-dependent; kern slicing derives from it
    nf = len(FIELDS)
    nw = 0 if w is None else nf
    nwo = nf if write_w else 0

    main_spec = pl.BlockSpec((bz, by, X), lambda kz, ky: (kz, ky, 0))

    def kern(*refs):
        field_refs = refs[:nseg * nf]
        w_refs = refs[nseg * nf:nseg * nf + nw]
        out_f = refs[nseg * nf + nw:nseg * nf + nw + nf]
        out_w = refs[nseg * nf + nw + nf:]
        data = {}
        for i, q in enumerate(FIELDS):
            win = select_window(field_refs[nseg * i:nseg * (i + 1)])
            data[q] = FieldData(win.astype(comp), inv_ds, pad_lo,
                                interior, x_wrap=True)
        rates = mhd_rates(data, prm, comp)
        dta = jnp.dtype(comp)
        for i, q in enumerate(FIELDS):
            wq = dta.type(dt_) * rates[q]
            if nw:
                wq = dta.type(alpha) * w_refs[i][...].astype(comp) + wq
            if nwo:
                out_w[i][...] = wq.astype(dtype)
            out_f[i][...] = (data[q].value
                             + dta.type(beta) * wq).astype(dtype)

    in_specs = []
    inputs = []
    for q in FIELDS:
        in_specs.extend(field_specs)
        inputs.extend(inputs_for_field(fields[q], slabs[q]))
    if nw:
        for q in FIELDS:
            in_specs.append(main_spec)
            inputs.append(w[q])
    out_shape = [jax.ShapeDtypeStruct((Z, Y, X), dtype)
                 for _ in range(nf + nwo)]
    out_specs = [main_spec] * (nf + nwo)

    outs = pl.pallas_call(
        kern,
        grid=(nzg, nyg),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(*inputs)
    new_f = {q: outs[i] for i, q in enumerate(FIELDS)}
    new_w = ({q: outs[nf + i] for i, q in enumerate(FIELDS)}
             if write_w else None)
    return new_f, new_w


def mhd_substep01_halo_pallas(fields: Dict[str, jnp.ndarray],
                              slabs: Dict[str, Dict[str, jnp.ndarray]],
                              prm, dt_phys: float,
                              block_z: int = 8, block_y: int = 32,
                              interpret: Optional[bool] = None
                              ) -> Tuple[Dict[str, jnp.ndarray],
                                         Dict[str, jnp.ndarray]]:
    """RK3 substeps 0 AND 1 fused into one HBM pass on the multi-device
    slab layout — the halo-path counterpart of
    ``pallas_mhd.mhd_substep01_wrap_pallas``, so an N-chip mesh gets
    the same two-substeps-per-pass temporal blocking as one chip.
    alpha_0 == 0 makes the pair independent of the incoming w: each
    block reads the 8 fields through a radius-2R window (slab-fed at
    shard edges), evaluates rates_0 on the ring-extended region, forms
    (f_1, w_1) in VMEM, evaluates rates_1 on the block, and writes
    (f_2, w_2). Per-point op order matches two sequential substeps
    exactly (ring recomputed, not approximated). One radius-2R
    exchange replaces two radius-R exchanges: same wire bytes per
    iteration, 2/3 the exchange latencies, one fewer full HBM
    read+write sweep. Reference semantics: astaroth/kernels.cu:63-90
    for substeps 0 and 1 over the astaroth.cu:552-646 exchange
    choreography.

    ``slabs[q]`` must come from ``exchange_interior_slabs(fields[q],
    counts, rz=bz, ry=esub, radius_rows=2*R, y_z_extended=True)`` —
    2R valid rows, not R (the window reaches 2R across shard edges) —
    with bz from ``mhd_halo_blocks(..., X=X, itemsize=...)`` exactly
    as the single-substep kernel documents.
    Needs 2R <= min(bz, esub) (6 <= 8). Returns (new_fields, new_w).
    """
    from ..models.astaroth import FIELDS

    if interpret is None:
        interpret = default_interpret()
    R2 = 2 * R
    Z, Y, X = fields[FIELDS[0]].shape
    dtype = fields[FIELDS[0]].dtype
    from .pallas_mhd import mhd_tile
    esub = mhd_tile(dtype)
    bz, by = mhd_halo_blocks(Z, Y, block_z, block_y, esub, X=X,
                             itemsize=jnp.dtype(dtype).itemsize)
    assert R2 <= esub and R2 <= bz, (R2, esub, bz)
    for q in FIELDS:
        assert slabs[q]["zlo"].shape == (bz, Y, X), slabs[q]["zlo"].shape
        assert slabs[q]["ylo"].shape == (Z + 2 * bz, esub, X), \
            slabs[q]["ylo"].shape
    nzg = Z // bz
    nyg = Y // by
    field_specs, inputs_for_field, select_window = _mhd_window_plan(
        Z, Y, X, bz, by, rr=R2, esub=esub)
    nseg = len(field_specs)
    nf = len(FIELDS)

    main_spec = pl.BlockSpec((bz, by, X), lambda kz, ky: (kz, ky, 0))

    def kern(*refs):
        from .pallas_mhd import mhd_pair_update

        field_refs = refs[:nseg * nf]
        out_f = refs[nseg * nf:nseg * nf + nf]
        out_w = refs[nseg * nf + nf:]
        wins = {q: select_window(field_refs[nseg * i:nseg * (i + 1)])
                for i, q in enumerate(FIELDS)}
        f2, w2 = mhd_pair_update(wins, prm, dtype, dt_phys, bz, by)
        for i, q in enumerate(FIELDS):
            out_w[i][...] = w2[q]
            out_f[i][...] = f2[q]

    in_specs = []
    inputs = []
    for q in FIELDS:
        in_specs.extend(field_specs)
        inputs.extend(inputs_for_field(fields[q], slabs[q]))
    out_shape = [jax.ShapeDtypeStruct((Z, Y, X), dtype)
                 for _ in range(2 * nf)]
    out_specs = [main_spec] * (2 * nf)

    outs = pl.pallas_call(
        kern,
        grid=(nzg, nyg),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(*inputs)
    new_f = {q: outs[i] for i, q in enumerate(FIELDS)}
    new_w = {q: outs[nf + i] for i, q in enumerate(FIELDS)}
    return new_f, new_w
