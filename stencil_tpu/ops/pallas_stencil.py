"""Pallas TPU kernels for the hot stencil compute paths.

These are the hand-scheduled analogs of the reference's application
CUDA kernels (reference: bin/jacobi3d.cu:40-85 stencil_kernel;
astaroth/user_kernels.h:383-453 solve), built the TPU way: the padded
shard stays in HBM and the kernel streams z-planes through VMEM — the
grid walks the interior z extent and each step sees a (2r+1)-plane
window, so HBM traffic is one read + one write per point while the VPU
does the adds on (y, x) planes (8x128 lanes).

The XLA slicing versions in ``stencil_kernels.py`` / ``fd6.py`` remain
the default on CPU and the correctness oracle; these kernels are the
optimization path selected with ``kernel="pallas"`` on models, and run
under the Pallas TPU interpreter off-TPU so tests exercise them
everywhere.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..geometry import Dim3, Radius


def on_tpu() -> bool:
    """Single source of truth for "is this process on a TPU backend"
    (shared by kernel selection and exchange interpret-mode choices)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # backend not initialized yet
        return False


def default_interpret() -> bool:
    """Interpret Pallas kernels when not running on a TPU backend."""
    return not on_tpu()


def sublane_tile_bytes(itemsize: int) -> int:
    """Minimum sublane (second-minor) tile rows for an ``itemsize``-byte
    dtype on TPU: 8 for 4-byte types, 16 for 2-byte (bf16), 32 for
    1-byte — edge-slab block shapes must be multiples of this to stay
    tile-aligned. The single source of the tile rule."""
    return max(8, 32 // max(itemsize, 1))


def sublane_tile(dtype) -> int:
    """``sublane_tile_bytes`` by dtype."""
    return sublane_tile_bytes(jnp.dtype(dtype).itemsize)


def _plane_specs(n_planes: int, z_lo: int, yp: int, xp: int):
    """One BlockSpec per z-offset: the same padded input is passed
    ``n_planes`` times with shifted index maps, giving the kernel an
    overlapping (n_planes, yp, xp) window per grid step (BlockSpec tiles
    cannot overlap, so the window is expressed as multiple views)."""
    specs = []
    for off in range(n_planes):
        specs.append(pl.BlockSpec(
            (1, yp, xp),
            functools.partial(lambda k, o: (k + z_lo + o - (n_planes // 2), 0, 0),
                              o=off)))
    return specs


def jacobi7_pallas(padded: jnp.ndarray, radius: Radius, interior: Dim3,
                   interpret: Optional[bool] = None) -> jnp.ndarray:
    """7-point Jacobi average over a halo-padded (z,y,x) shard
    (reference: bin/jacobi3d.cu:65-80), z-plane-pipelined through VMEM.

    Returns the interior-shaped (Z, Y, X) update; the caller writes it
    back with ``write_interior``.
    """
    if interpret is None:
        interpret = default_interpret()
    lo = radius.pad_lo()
    Z, Y, X = interior.z, interior.y, interior.x
    Zp, Yp, Xp = padded.shape
    ly, lx = lo.y, lo.x

    def kern(pm, pc, pp, out):
        c = pc[0]
        acc = pm[0, ly:ly + Y, lx:lx + X] + pp[0, ly:ly + Y, lx:lx + X]
        acc += c[ly - 1:ly - 1 + Y, lx:lx + X]
        acc += c[ly + 1:ly + 1 + Y, lx:lx + X]
        acc += c[ly:ly + Y, lx - 1:lx - 1 + X]
        acc += c[ly:ly + Y, lx + 1:lx + 1 + X]
        out[0] = acc * (1.0 / 6.0)

    return pl.pallas_call(
        kern,
        grid=(Z,),
        in_specs=_plane_specs(3, lo.z, Yp, Xp),
        out_specs=pl.BlockSpec((1, Y, X), lambda k: (k, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((Z, Y, X), padded.dtype),
        interpret=interpret,
    )(padded, padded, padded)


#: default block-shape ceilings for the wrap kernels — the planner
#: picks the cheapest-traffic legal shape at or below these
_WRAP_CAPS = (8, 128)
_WRAPN_CAPS = (16, 128)


def _wrap_elems(esub: int, n_steps: int = 0):
    """Per-lane-column element model of the wrap kernels for the block
    planner (analysis/tiling.py): streamed inputs (main + 2 z segments
    of ``max(n_steps, 1)`` rows + 2 esub-col y slabs + 4*n_steps corner
    singles on the N-step kernel), the output block, and — for the
    N-step kernel — the held assembled window plus its first shrinking
    intermediate. Must count at least what the GridMapping will show
    (the plan -> audit round-trip contract)."""
    n = max(int(n_steps), 0)
    rows = max(n, 1)

    def elems(bz: int, by: int):
        ein = bz * by + 2 * rows * by + 2 * bz * esub + 4 * n * esub
        held = 0
        if n:
            held = ((bz + 2 * n) * (by + 2 * n)
                    + (bz + 2 * n - 2) * (by + 2 * n - 2))
        return ein, bz * by, held

    return elems


def jacobi7_wrap_pallas(interior: jnp.ndarray,
                        hot_c: Tuple[int, int, int],
                        cold_c: Tuple[int, int, int], sph_r: int,
                        block_z: Optional[int] = None,
                        block_y: Optional[int] = None,
                        interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fully-fused periodic Jacobi step for a single-shard axis layout:
    7-point update + Dirichlet sphere sources on an UNPADDED (Z, Y, X)
    array, with the periodic wrap done inside the kernel — z/y wrap via
    wrapped edge-slab index maps, x wrap via in-VMEM circular shift
    (``pltpu.roll``). No halo storage, no exchange program: ~1.3 HBM
    passes per step instead of the padded path's slab copies
    (the single-chip fast path; reference semantics bin/jacobi3d.cu:40-85).

    ``hot_c``/``cold_c`` are (cx, cy, cz) sphere centers. Blocks tile
    (z, y); edge reads come from four thin wrapped slabs, so the read
    amplification is ``1 + 2/block_z + 2/block_y`` (esub-scaled for the
    slab fetches) and VMEM use is ``~2 * 2 * block_z * block_y * X``
    elements. Default (None) blocks come from the VMEM block-shape
    planner (``analysis/tiling.py``: cheapest legal traffic at or
    below ``_WRAP_CAPS``, raising when nothing legal exists); explicit
    blocks are snapped to alignment with a one-shot warning when
    replaced (budget deliberately unchecked — sweeps measure what they
    asked for).
    """
    from ..analysis.tiling import plan_blocks, snap_blocks

    if interpret is None:
        interpret = default_interpret()
    Z, Y, X = interior.shape
    dt_i = jnp.dtype(interior.dtype)
    # y edge slabs are esub rows: the dtype's min sublane tile (8 f32 /
    # 16 bf16) when Y allows, else single rows (small/interpret grids)
    esub = sublane_tile(interior.dtype)
    if Y % esub:
        esub = 1
    if block_z is None and block_y is None:
        bz, by = plan_blocks(
            "jacobi7_wrap_pallas", Z, Y, X, dt_i.itemsize,
            _wrap_elems(esub), sublane_y=esub,
            cap_z=_WRAP_CAPS[0], cap_y=_WRAP_CAPS[1]).blocks()
    else:
        bz, by = snap_blocks(
            "jacobi7_wrap_pallas", Z, Y,
            block_z if block_z is not None else _WRAP_CAPS[0],
            block_y if block_y is not None else _WRAP_CAPS[1],
            sublane_y=esub)
    dt = jnp.dtype(interior.dtype)
    hx, hy, hz = hot_c
    cx, cy, cz = cold_c
    r2 = sph_r * sph_r

    def kern(zprev, main, znext, yprev, ynext, out):
        kz = pl.program_id(0)
        ky = pl.program_id(1)
        c = main[...]                            # (bz, by, X)
        # the wrapped neighbor row is the last row of the preceding
        # edge slab / first row of the following one
        ext = jnp.concatenate([yprev[:, esub - 1:esub], c, ynext[:, 0:1]],
                              axis=1)
        ym = ext[:, :by]                         # row j-1 (wrapped)
        yp = ext[:, 2:]
        xm = pltpu.roll(c, 1, 2)
        xp = pltpu.roll(c, X - 1, 2)
        lat = ym + yp + xm + xp
        gy = (ky * by
              + jax.lax.broadcasted_iota(jnp.int32, (by, X), 0))
        gx = jax.lax.broadcasted_iota(jnp.int32, (by, X), 1)
        d2yx_h = (gx - hx) ** 2 + (gy - hy) ** 2
        d2yx_c = (gx - cx) ** 2 + (gy - cy) ** 2
        for r in range(bz):
            zm = zprev[0] if r == 0 else c[r - 1]
            zp = znext[0] if r == bz - 1 else c[r + 1]
            new = (lat[r] + zm + zp) * dt.type(1.0 / 6.0)
            gz = kz * bz + r
            new = jnp.where(d2yx_h + (gz - jnp.int32(hz)) ** 2 <= r2,
                            dt.type(1.0), new)
            new = jnp.where(d2yx_c + (gz - jnp.int32(cz)) ** 2 <= r2,
                            dt.type(0.0), new)
            out[r] = new

    return pl.pallas_call(
        kern,
        grid=(Z // bz, Y // by),
        in_specs=[
            # plane before this z block, periodic
            pl.BlockSpec((1, by, X),
                         lambda kz, ky: ((kz * bz - 1) % Z, ky, 0)),
            pl.BlockSpec((bz, by, X), lambda kz, ky: (kz, ky, 0)),
            # plane after this z block, periodic
            pl.BlockSpec((1, by, X),
                         lambda kz, ky: ((kz * bz + bz) % Z, ky, 0)),
            # esub-row y slabs just outside this block, periodic
            pl.BlockSpec((bz, esub, X),
                         lambda kz, ky: (kz,
                                         (ky * (by // esub) - 1)
                                         % (Y // esub), 0)),
            pl.BlockSpec((bz, esub, X),
                         lambda kz, ky: (kz,
                                         (ky * (by // esub) + by // esub)
                                         % (Y // esub), 0)),
        ],
        out_specs=pl.BlockSpec((bz, by, X), lambda kz, ky: (kz, ky, 0)),
        out_shape=jax.ShapeDtypeStruct((Z, Y, X), interior.dtype),
        # allow larger-than-default blockings in tuning sweeps (Mosaic's
        # default scoped-VMEM ceiling is 16 MiB)
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(interior, interior, interior, interior, interior)


def jacobi7_wrapn_pallas(interior: jnp.ndarray,
                         hot_c: Tuple[int, int, int],
                         cold_c: Tuple[int, int, int], sph_r: int,
                         steps: int = 2,
                         block_z: Optional[int] = None,
                         block_y: Optional[int] = None,
                         interpret: Optional[bool] = None) -> jnp.ndarray:
    """``steps`` fused periodic Jacobi iterations (+ sphere sources
    after each) in ONE HBM pass — temporal blocking. The single-step
    kernel is bandwidth-bound at ~2.4 HBM passes per iteration;
    evaluating step k+1 from step k's values while they are still in
    VMEM (recomputing an edge ring of step-k values at block borders)
    costs the same traffic per *pass* but advances ``steps``
    iterations, dividing per-iteration traffic by ~``steps`` at the
    price of ring recompute that grows with ``steps``. Bit-identical
    to ``steps`` ``jacobi7_wrap_pallas`` calls (same op order per
    point; the ring is recomputed, not approximated). Reference
    semantics: bin/jacobi3d.cu:40-85 applied ``steps`` times.

    Each (bz, by, X) output block reads a wrapped (bz+2N, by+2N, X)
    window assembled from a main block, 2N single-row z segments, 2
    esub-col y slabs, and 4N corner singles (x wraps in-core via
    ``pltpu.roll``; z is the majormost dim, so single-row fetches are
    exact-radius). Needs Z % bz == 0, Y and by multiples of the
    dtype's sublane tile (8 f32 / 16 bf16), and steps <= that tile.
    """
    from ..analysis.tiling import plan_blocks, snap_blocks

    if interpret is None:
        interpret = default_interpret()
    N = int(steps)
    Z, Y, X = interior.shape
    esub = sublane_tile(interior.dtype)
    if N < 1 or N > esub:
        raise ValueError(f"wrapN kernel needs 1 <= steps <= {esub}, "
                         f"got steps={N}")
    if Y % esub:
        raise ValueError(f"wrap{N} kernel needs Y % {esub} == 0, "
                         f"got Y={Y}")
    isz = jnp.dtype(interior.dtype).itemsize
    if block_z is None and block_y is None:
        bz, by = plan_blocks(
            f"jacobi7_wrapn_pallas[n={N}]", Z, Y, X, isz,
            _wrap_elems(esub, N), sublane_y=esub,
            cap_z=_WRAPN_CAPS[0], cap_y=_WRAPN_CAPS[1]).blocks()
    else:
        bz, by = snap_blocks(
            f"jacobi7_wrapn_pallas[n={N}]", Z, Y,
            block_z if block_z is not None else _WRAPN_CAPS[0],
            block_y if block_y is not None else _WRAPN_CAPS[1],
            sublane_y=esub)
    # N-row slab fetches when block alignment permits (fewer, fatter
    # DMAs — the N=2 default then matches the original pair kernel's
    # descriptor structure exactly); single-row fetches otherwise
    slabbed = (bz % N == 0) and (Z % N == 0)
    dt = jnp.dtype(interior.dtype)
    hx, hy, hz = hot_c
    cx, cy, cz = cold_c
    r2 = sph_r * sph_r
    byb = by // esub       # y index maps use esub-col granularity
    nyb8 = Y // esub

    def sources(vals, z0, y0, nz, ny):
        """Re-impose Dirichlet spheres on a (nz, ny, X) region whose
        global origin is (z0, y0, 0). Coords wrap modulo the global
        size: ring cells outside an edge block are PERIODIC neighbors,
        so their sphere test must use the wrapped position."""
        gy = (y0 + jax.lax.broadcasted_iota(jnp.int32, (ny, X), 0)) % Y
        gx = jax.lax.broadcasted_iota(jnp.int32, (ny, X), 1)
        gz = (z0 + jax.lax.broadcasted_iota(jnp.int32, (nz, 1, 1), 0)) % Z
        d2h = (gx - hx) ** 2 + (gy - hy) ** 2 + (gz - hz) ** 2
        d2c = (gx - cx) ** 2 + (gy - cy) ** 2 + (gz - cz) ** 2
        vals = jnp.where(d2h <= r2, dt.type(1.0), vals)
        vals = jnp.where(d2c <= r2, dt.type(0.0), vals)
        return vals

    def jstep(w):
        """One 7-point step on the interior of a (nz, ny, X) window:
        returns (nz-2, ny-2, X); x is periodic in-core."""
        zsum = w[:-2, 1:-1] + w[2:, 1:-1]
        ysum = w[1:-1, :-2] + w[1:-1, 2:]
        xm = pltpu.roll(w, 1, 2)
        xp = pltpu.roll(w, X - 1, 2)
        xsum = (xm + xp)[1:-1, 1:-1]
        return (zsum + ysum + xsum) * dt.type(1.0 / 6.0)

    # ref order: main | z- segments | z+ segments | ym | yp | corners
    # (slabbed: one N-row segment per side, 4 N-row corners; unaligned:
    # N single rows per side, 4N single-row corners)
    nzseg = 1 if slabbed else N

    def kern(*refs):
        main = refs[0]
        zms = refs[1:1 + nzseg]
        zps = refs[1 + nzseg:1 + 2 * nzseg]
        ym, yp = refs[1 + 2 * nzseg:3 + 2 * nzseg]
        corners = refs[3 + 2 * nzseg:-1]
        out = refs[-1]
        kz = pl.program_id(0)
        ky = pl.program_id(1)
        z0 = kz * bz
        y0 = ky * by
        eN = esub - N

        def row(zref, cm, cp):
            return jnp.concatenate([cm[:, eN:], zref[...], cp[:, :N]],
                                   axis=1)

        rows = [row(zms[i], corners[2 * i], corners[2 * i + 1])
                for i in range(nzseg)]
        rows.append(jnp.concatenate([ym[:, eN:], main[...], yp[:, :N]],
                                    axis=1))
        rows.extend(row(zps[i], corners[2 * nzseg + 2 * i],
                        corners[2 * nzseg + 2 * i + 1])
                    for i in range(nzseg))
        w = jnp.concatenate(rows, axis=0)     # (bz+2N, by+2N, X)
        for k in range(N):
            w = jstep(w)                      # ring shrinks by 1 each
            ring = N - 1 - k
            w = sources(w, z0 - ring, y0 - ring, bz + 2 * ring,
                        by + 2 * ring)
        out[...] = w

    ym_map = lambda ky: (ky * byb - 1) % nyb8
    yp_map = lambda ky: (ky * byb + byb) % nyb8
    if slabbed:
        # N-row z segments in N-row block units (bz % N == 0 makes the
        # maps integral; matches the original wrap2 structure at N=2)
        bzN = bz // N
        nzN = Z // N
        zmaps = {-1: (lambda kz: (kz * bzN - 1) % nzN),
                 +1: (lambda kz: (kz * bzN + bzN) % nzN)}
        zsegs = [(N, -1), (N, +1)]
    else:
        zoffs = [-(N - i) for i in range(N)] + [bz + i for i in range(N)]
        zmaps = {o: (lambda kz, o=o: (kz * bz + o) % Z) for o in zoffs}
        zsegs = [(1, o) for o in zoffs]

    in_specs = [pl.BlockSpec((bz, by, X), lambda kz, ky: (kz, ky, 0))]
    in_specs += [pl.BlockSpec((rows_, by, X),
                              lambda kz, ky, f=zmaps[key]: (f(kz), ky, 0))
                 for rows_, key in zsegs]
    in_specs += [
        # esub-col y slabs just outside the block, periodic
        pl.BlockSpec((bz, esub, X),
                     lambda kz, ky: (kz, ym_map(ky), 0)),
        pl.BlockSpec((bz, esub, X),
                     lambda kz, ky: (kz, yp_map(ky), 0)),
    ]
    for rows_, key in zsegs:
        for ymap in (ym_map, yp_map):
            in_specs.append(pl.BlockSpec(
                (rows_, esub, X),
                lambda kz, ky, f=zmaps[key], g=ymap: (f(kz), g(ky), 0)))
    return pl.pallas_call(
        kern,
        grid=(Z // bz, Y // by),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bz, by, X), lambda kz, ky: (kz, ky, 0)),
        out_shape=jax.ShapeDtypeStruct((Z, Y, X), interior.dtype),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(*([interior] * len(in_specs)))


def jacobi7_wrap2_pallas(interior: jnp.ndarray,
                         hot_c: Tuple[int, int, int],
                         cold_c: Tuple[int, int, int], sph_r: int,
                         block_z: Optional[int] = None,
                         block_y: Optional[int] = None,
                         interpret: Optional[bool] = None) -> jnp.ndarray:
    """Two fused iterations per HBM pass — ``jacobi7_wrapn_pallas``
    with steps=2. Kept as a stable named entry for kernel-level tests
    and external callers; the model builder and the tuning harness
    patch ``jacobi7_wrapn_pallas`` directly."""
    return jacobi7_wrapn_pallas(interior, hot_c, cold_c, sph_r, steps=2,
                                block_z=block_z, block_y=block_y,
                                interpret=interpret)


# 6th-order central second-derivative coefficients (see ops/fd6.py)
_D2_C = -49.0 / 18.0
_D2 = (3.0 / 2.0, -3.0 / 20.0, 1.0 / 90.0)


def laplace6_pallas(padded: jnp.ndarray, radius: Radius, interior: Dim3,
                    inv_ds: Tuple[float, float, float] = (1.0, 1.0, 1.0),
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fused 6th-order Laplacian (the Astaroth-family hot derivative,
    reference: astaroth/user_kernels.h:49-62 second_derivative summed
    over axes) on a radius-3-padded shard, z-plane-pipelined: 7 planes
    resident in VMEM per grid step."""
    if interpret is None:
        interpret = default_interpret()
    lo = radius.pad_lo()
    Z, Y, X = interior.z, interior.y, interior.x
    Zp, Yp, Xp = padded.shape
    ly, lx = lo.y, lo.x
    dt = jnp.dtype(padded.dtype)
    ix2 = dt.type(inv_ds[0] * inv_ds[0])
    iy2 = dt.type(inv_ds[1] * inv_ds[1])
    iz2 = dt.type(inv_ds[2] * inv_ds[2])

    def kern(m3, m2, m1, c0, p1, p2, p3, out):
        c = c0[0]
        ctr = c[ly:ly + Y, lx:lx + X]
        accx = dt.type(_D2_C) * ctr
        accy = accx
        accz = dt.type(_D2_C) * ctr
        planes = {-3: m3, -2: m2, -1: m1, 1: p1, 2: p2, 3: p3}
        for i, w in enumerate(_D2, start=1):
            wc = dt.type(w)
            accx = accx + wc * (c[ly:ly + Y, lx + i:lx + i + X]
                                + c[ly:ly + Y, lx - i:lx - i + X])
            accy = accy + wc * (c[ly + i:ly + i + Y, lx:lx + X]
                                + c[ly - i:ly - i + Y, lx:lx + X])
            accz = accz + wc * (planes[i][0, ly:ly + Y, lx:lx + X]
                                + planes[-i][0, ly:ly + Y, lx:lx + X])
        out[0] = accx * ix2 + accy * iy2 + accz * iz2

    return pl.pallas_call(
        kern,
        grid=(Z,),
        in_specs=_plane_specs(7, lo.z, Yp, Xp),
        out_specs=pl.BlockSpec((1, Y, X), lambda k: (k, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((Z, Y, X), padded.dtype),
        interpret=interpret,
    )(*([padded] * 7))
