"""Fused Pallas MHD substep: the Astaroth "solve" megakernel, TPU-style.

The XLA slicing formulation of the MHD right-hand sides
(models/astaroth.mhd_rates over ops/fd6.FieldData) materializes dozens
of derivative intermediates to HBM per substep — measured ~1 iter/s at
256^3 on one chip, ~50x below the traffic bound. The reference solves
this with one fused CUDA kernel whose threads read pencils through
shared memory (reference: astaroth/user_kernels.h:383-453 solve,
kernels.cu:63-90 integrate_substep); this module is the TPU analog: one
``pallas_call`` per RK substep that streams (block_z, block_y, X)
tiles of ALL 8 fields through VMEM, assembles each field's
radius-3-halo window in-core (periodic wrap included), evaluates the
full RHS with the *same* ``FieldData``/``mhd_rates`` code (jnp ops on
VMEM values), and applies the Williamson RK update — one HBM read pass
+ one write pass per field per substep (plus thin halo refetches).

Single-shard-axis layout only (unpadded fields, wrap in kernel): the
multi-device path keeps the padded layout + ppermute exchange.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..geometry import Dim3
from .pallas_stencil import default_interpret, sublane_tile

R = 3          # stencil radius (6th order)
ESUB = 8       # edge-slab sublane tile (f32; bf16 paths use 16)


def compute_dtype(store_dtype):
    """In-kernel compute dtype for a storage dtype: bfloat16 fields
    are STORED half-width (halving HBM traffic — the whole point) but
    the 6th-order RHS is evaluated in float32 (bf16's ~8 mantissa bits
    are not enough for the derivative coefficient sums; the MXU/VPU
    idiom is bf16 in memory, f32 accumulate). Everything else computes
    in its own dtype."""
    dt = jnp.dtype(store_dtype)
    return jnp.float32 if dt == jnp.dtype(jnp.bfloat16) else dt


def mhd_tile(dtype) -> int:
    """Edge-slab sublane granularity for the MHD kernels — the dtype's
    minimum sublane tile (sublane_tile_bytes already floors at the f32
    tile: 8 for f32/f64, 16 for bf16), named here because every MHD
    window plan, block fitter, and slab exchange must agree on it and
    the rr <= tile window contract (2R = 6 <= 8) relies on the floor."""
    return sublane_tile(dtype)


def _thin_z() -> bool:
    """STENCIL_MHD_THINZ=0 restores the tiled (ESUB-row) z-neighbor
    segments — the hardware-proven round-3 layout — for A/B runs; the
    default is the exact-radius single-row scheme (see _window_plan)."""
    import os

    return os.environ.get("STENCIL_MHD_THINZ", "1").lower() not in (
        "0", "false", "no")


_YSEGS = (-1, 0, 1)


def _window_plan(Z: int, Y: int, X: int, bz: int, by: int,
                 rr: int = R, esub: int = ESUB):
    """(specs, assemble) for one field's (bz+2rr, by+2rr, X)
    neighborhood (rr defaults to the stencil radius R; the fused
    substep-pair kernel passes 2R), periodic via wrapped index maps;
    x is NOT extended (buffers stay lane-aligned at X; periodic x
    shifts happen per-derivative via ``pltpu.roll`` — the FieldData
    ``x_wrap`` mode). ``esub`` is the dtype's sublane tile (8 f32 /
    16 bf16): the y edge-slab granularity.

    Default (thin-z) plan: 2rr+1 z segments (rr wrapped single rows
    below, the main bz-row block, rr above — exact-radius fetches,
    since the majormost dim has no tile granularity) x 3 y segments
    (preceding esub-slab, main, following esub-slab); per-block read
    amplification (1 + 2rr/bz) * (1 + 2*esub/by).

    STENCIL_MHD_THINZ=0 plan: 3 z segments (esub-row tile below, main,
    esub-row tile above) x 3 y segments = 9 specs; amplification
    (1 + 2*esub/bz) * (1 + 2*esub/by) — more traffic, but fewer/fatter
    DMAs (the round-3 layout, kept for hardware A/B).
    """
    assert rr <= esub, (rr, esub)   # y slabs are one esub tile wide
    nyb = Y // esub
    byb = by // esub
    thin = _thin_z()
    if thin:
        zsegs = tuple(range(-rr, 0)) + (0,) + tuple(range(1, rr + 1))
    else:
        assert bz % esub == 0 and Z % esub == 0, (Z, bz)
        zsegs = (-1, 0, 1)
        bzb = bz // esub
        nzb = Z // esub

    def zy(zseg: int, yseg: int):
        if zseg == 0:
            zshape, zidx = bz, (lambda kz: kz)
        elif thin:
            # single wrapped row at element offset kz*bz + zseg (below)
            # or kz*bz + bz + zseg - 1 (above); block units == elements
            off = zseg if zseg < 0 else bz + zseg - 1
            zshape, zidx = 1, (lambda kz, o=off: (kz * bz + o) % Z)
        elif zseg < 0:
            zshape, zidx = esub, (lambda kz: (kz * bzb - 1) % nzb)
        else:
            zshape, zidx = esub, (lambda kz: (kz * bzb + bzb) % nzb)
        if yseg == 0:
            yshape, yidx = by, (lambda ky: ky)
        elif yseg < 0:
            yshape, yidx = esub, (lambda ky: (ky * byb - 1) % nyb)
        else:
            yshape, yidx = esub, (lambda ky: (ky * byb + byb) % nyb)
        return pl.BlockSpec(
            (zshape, yshape, X),
            functools.partial(lambda kz, ky, zf, yf: (zf(kz), yf(ky), 0),
                              zf=zidx, yf=yidx))

    specs = [zy(zs, ys) for zs in zsegs for ys in _YSEGS]

    def assemble(refs) -> jnp.ndarray:
        """(bz+2rr, by+2rr, X) periodic window from the segment refs
        (z segments outer, y in _YSEGS inner)."""
        rows = []
        for zi, zs in enumerate(zsegs):
            ym, y0, yp = refs[3 * zi:3 * zi + 3]
            if thin or zs == 0:
                zslice = slice(None)
            elif zs < 0:          # tiled: last rr rows of the esub tile
                zslice = slice(esub - rr, None)
            else:                 # tiled: first rr rows
                zslice = slice(None, rr)
            rows.append(jnp.concatenate(
                [ym[zslice, esub - rr:], y0[zslice], yp[zslice, :rr]],
                axis=1))
        return jnp.concatenate(rows, axis=0)

    return specs, assemble


def _wrap_mhd_elems(esub: int, rr: int = R, nf: int = 8):
    """Per-lane-column element model of one MHD wrap-kernel grid step
    for the block planner (the ``_window_plan`` segment cross product
    x ``nf`` fields, worst-case substep: w read + both output sweeps):
    per field ``(bz + zextra) * (by + 2*esub)`` streamed in, where
    ``zextra`` is 2rr single rows (thin-z) or two esub tiles."""
    zextra = 2 * rr if _thin_z() else 2 * esub

    def elems(bz: int, by: int):
        per_field = (bz + zextra) * (by + 2 * esub)
        ein = nf * (per_field + bz * by)     # field windows + w
        return ein, 2 * nf * bz * by, 0      # f and w outputs

    return elems


def _fit_blocks(Z: int, Y: int, block_z: int, block_y: int,
                esub: int = ESUB, X: "int | None" = None,
                itemsize: int = 4) -> Tuple[int, int]:
    """Planner-derived (block_z, block_y) for the wrap substep kernels:
    multiples of the dtype's ``esub`` tile dividing (Z, Y) at or below
    the requested ceiling, budget-checked against the wrap window
    plan's byte model when ``X``/``itemsize`` are given (without ``X``
    — legacy callers — only alignment/divisibility constrain, which
    chooses identical shapes wherever the budget is slack). Raises
    ``TilingInfeasibleError`` when nothing legal exists instead of
    clamping to the esub floor."""
    from ..analysis.tiling import plan_blocks

    assert Z % esub == 0 and Y % esub == 0, (Z, Y, esub)
    return plan_blocks("mhd_substep_wrap_pallas", Z, Y,
                       X if X is not None else 1, itemsize,
                       _wrap_mhd_elems(esub), n_streams=8,
                       sublane_z=esub, sublane_y=esub,
                       cap_z=block_z, cap_y=block_y).blocks()


def mhd_substep_wrap_pallas(fields: Dict[str, jnp.ndarray],
                            w: Optional[Dict[str, jnp.ndarray]],
                            s: int, prm, dt_phys: float,
                            block_z: int = 8, block_y: int = 32,
                            write_w: bool = True,
                            interpret: Optional[bool] = None
                            ) -> Tuple[Dict[str, jnp.ndarray],
                                       Optional[Dict[str, jnp.ndarray]]]:
    """One fused RK3 substep ``s`` on unpadded (Z, Y, X) fields with
    periodic wrap in-kernel. Returns (new_fields, new_w).

    Dead-w elision (the model's integrate loop uses both): Williamson's
    alpha_0 == 0 means substep 0 never consumes the incoming w — pass
    ``w=None`` and the kernel drops the 8-field w read sweep entirely
    (XLA cannot DCE through an opaque pallas_call, so feeding w anyway
    would stream a full HBM pass of dead data). Likewise nothing reads
    the w that substep 2 writes (the next iteration restarts at
    alpha_0 == 0): ``write_w=False`` drops the 8-field w write sweep
    and returns (new_fields, None). write_w elision is bit-exact;
    w=None changes how the compiler fuses the update (the 0*w term
    disappears, enabling different FMA contraction), so fields match
    to ~1 ulp rather than bit-for-bit. The reference app pays both
    sweeps every iteration (astaroth/kernels.cu:63-90 reads/writes w
    unconditionally).

    Requires Z, Y, block_z, block_y to be multiples of the dtype's
    sublane tile (8 f32 / 16 bf16) and block_z | Z, block_y | Y.
    bfloat16 fields compute in float32 (see ``compute_dtype``).
    """
    from ..models.astaroth import FIELDS, RK3_ALPHA, RK3_BETA, mhd_rates
    from .fd6 import FieldData

    if interpret is None:
        interpret = default_interpret()
    Z, Y, X = fields[FIELDS[0]].shape
    dtype = fields[FIELDS[0]].dtype
    esub = mhd_tile(dtype)
    comp = compute_dtype(dtype)
    bz, by = _fit_blocks(Z, Y, block_z, block_y, esub, X=X,
                         itemsize=jnp.dtype(dtype).itemsize)
    inv_ds = (1.0 / prm.dsx, 1.0 / prm.dsy, 1.0 / prm.dsz)
    alpha = float(RK3_ALPHA[s])
    beta = float(RK3_BETA[s])
    if w is None:
        assert alpha == 0.0, "w=None is only valid when alpha_s == 0"
    dt_ = float(dt_phys)
    pad_lo = Dim3(0, R, R)     # x unpadded: wrap via pltpu.roll
    interior = Dim3(X, by, bz)

    main_spec = pl.BlockSpec((bz, by, X), lambda kz, ky: (kz, ky, 0))
    nf = len(FIELDS)
    nw = 0 if w is None else nf
    nwo = nf if write_w else 0
    field_specs, assemble = _window_plan(Z, Y, X, bz, by, esub=esub)
    nseg = len(field_specs)

    def kern(*refs):
        field_refs = refs[:nseg * nf]
        w_refs = refs[nseg * nf:nseg * nf + nw]
        out_f = refs[nseg * nf + nw:nseg * nf + nw + nf]
        out_w = refs[nseg * nf + nw + nf:]
        data = {}
        for i, q in enumerate(FIELDS):
            win = assemble(field_refs[nseg * i:nseg * (i + 1)])
            data[q] = FieldData(win.astype(comp), inv_ds, pad_lo,
                                interior, x_wrap=True)
        rates = mhd_rates(data, prm, comp)
        dta = jnp.dtype(comp)
        for i, q in enumerate(FIELDS):
            wq = dta.type(dt_) * rates[q]
            if nw:
                wq = dta.type(alpha) * w_refs[i][...].astype(comp) + wq
            if nwo:
                out_w[i][...] = wq.astype(dtype)
            out_f[i][...] = (data[q].value
                             + dta.type(beta) * wq).astype(dtype)

    in_specs = []
    inputs = []
    for q in FIELDS:
        in_specs.extend(field_specs)
        inputs.extend([fields[q]] * nseg)
    if nw:
        for q in FIELDS:
            in_specs.append(main_spec)
            inputs.append(w[q])
    out_shape = [jax.ShapeDtypeStruct((Z, Y, X), dtype)
                 for _ in range(nf + nwo)]
    out_specs = [main_spec] * (nf + nwo)

    outs = pl.pallas_call(
        kern,
        grid=(Z // bz, Y // by),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(*inputs)
    new_f = {q: outs[i] for i, q in enumerate(FIELDS)}
    new_w = ({q: outs[nf + i] for i, q in enumerate(FIELDS)}
             if write_w else None)
    return new_f, new_w


def mhd_pair_update(wins: Dict[str, jnp.ndarray], prm, dtype,
                    dt_phys: float, bz: int, by: int
                    ) -> Tuple[Dict[str, jnp.ndarray],
                               Dict[str, jnp.ndarray]]:
    """The fused RK substep-0+1 update on radius-2R windows — the ONE
    implementation of the pair math shared by the halo-path pair
    kernel and the RDMA-overlap pair mode (the wrap-path kernel
    predates it and is kept verbatim as the hardware-measured
    reference). ``wins[q]`` is the (bz + 4R, by + 4R, X) window;
    returns ``({q: f2}, {q: w2})`` as (bz, by, X) blocks. alpha_0 == 0
    makes the pair independent of the incoming w: rates_0 is evaluated
    on the ring-extended region, (f_1, w_1) formed in VMEM, rates_1 on
    the block — per-point op order matches two sequential substeps
    exactly. Reference semantics: astaroth/kernels.cu:63-90 applied
    for substeps 0 and 1.

    ``dtype`` is the STORAGE dtype: bfloat16 windows are cast to
    float32 for the whole pair evaluation and the outputs cast back
    (see ``compute_dtype``)."""
    from ..models.astaroth import FIELDS, RK3_ALPHA, RK3_BETA, mhd_rates
    from .fd6 import FieldData

    assert float(RK3_ALPHA[0]) == 0.0, "pair fusion needs alpha_0 == 0"
    R2 = 2 * R
    comp = compute_dtype(dtype)
    dta = jnp.dtype(comp)
    dt_ = dta.type(float(dt_phys))
    beta0 = dta.type(float(RK3_BETA[0]))
    alpha1 = dta.type(float(RK3_ALPHA[1]))
    beta1 = dta.type(float(RK3_BETA[1]))
    inv_ds = (1.0 / prm.dsx, 1.0 / prm.dsy, 1.0 / prm.dsz)
    pad = Dim3(0, R, R)
    int0 = Dim3(wins[FIELDS[0]].shape[2], by + R2, bz + R2)
    int1 = Dim3(wins[FIELDS[0]].shape[2], by, bz)
    data0 = {q: FieldData(wins[q].astype(comp), inv_ds, pad, int0,
                          x_wrap=True)
             for q in FIELDS}
    rates0 = mhd_rates(data0, prm, comp)
    data1 = {}
    w1 = {}
    for q in FIELDS:
        w1[q] = dt_ * rates0[q]                    # alpha_0 == 0
        f1 = data0[q].value + beta0 * w1[q]
        data1[q] = FieldData(f1, inv_ds, pad, int1, x_wrap=True)
    rates1 = mhd_rates(data1, prm, comp)
    out_f = {}
    out_w = {}
    for q in FIELDS:
        w1c = w1[q][R:R + bz, R:R + by]
        wq = alpha1 * w1c + dt_ * rates1[q]
        out_w[q] = wq.astype(dtype)
        out_f[q] = (data1[q].value + beta1 * wq).astype(dtype)
    return out_f, out_w


def mhd_substep01_wrap_pallas(fields: Dict[str, jnp.ndarray],
                              prm, dt_phys: float,
                              block_z: int = 8, block_y: int = 32,
                              interpret: Optional[bool] = None
                              ) -> Tuple[Dict[str, jnp.ndarray],
                                         Dict[str, jnp.ndarray]]:
    """RK3 substeps 0 AND 1 fused into one HBM pass — temporal blocking
    across Runge-Kutta substeps. Williamson's alpha_0 is 0, so substep
    0 ignores the incoming w entirely (w_1 = dt * rates_0): the fused
    pair reads ONLY the 8 fields through a radius-2R window, evaluates
    rates_0 on the ring-extended (bz+2R, by+2R) region, forms the
    intermediate (f_1, w_1) in VMEM, evaluates rates_1 on the block,
    and writes (f_2, w_2) — replacing two full read+write sweeps (plus
    a w read) with one fatter read and the same writes. Per-point op
    order matches two ``mhd_substep_wrap_pallas`` calls exactly (the
    ring is recomputed, not approximated), so results are
    bit-compatible. Opt-in path (STENCIL_MHD_PAIR=1 in the model): the
    compute/VMEM pressure doubles per grid step, and the trade is
    unmeasured on hardware. Reference semantics:
    astaroth/kernels.cu:63-90 applied for substeps 0 and 1.

    Same layout contract as ``mhd_substep_wrap_pallas``; requires
    2R <= the ESUB tile (6 <= 8). Returns (new_fields, new_w).
    """
    from ..models.astaroth import FIELDS, RK3_ALPHA, RK3_BETA, mhd_rates
    from .fd6 import FieldData

    if interpret is None:
        interpret = default_interpret()
    assert float(RK3_ALPHA[0]) == 0.0, "pair fusion needs alpha_0 == 0"
    Z, Y, X = fields[FIELDS[0]].shape
    dtype = fields[FIELDS[0]].dtype
    esub = mhd_tile(dtype)
    comp = compute_dtype(dtype)
    bz, by = _fit_blocks(Z, Y, block_z, block_y, esub, X=X,
                         itemsize=jnp.dtype(dtype).itemsize)
    inv_ds = (1.0 / prm.dsx, 1.0 / prm.dsy, 1.0 / prm.dsz)
    beta0 = float(RK3_BETA[0])
    alpha1 = float(RK3_ALPHA[1])
    beta1 = float(RK3_BETA[1])
    dt_ = float(dt_phys)
    R2 = 2 * R
    # rates_0 is evaluated on the ring-extended region, rates_1 on the
    # block; both FieldData views sit on lane-aligned (.., X) buffers
    pad0 = Dim3(0, R, R)
    int0 = Dim3(X, by + R2, bz + R2)   # region carrying rates_0
    pad1 = Dim3(0, R, R)
    int1 = Dim3(X, by, bz)

    main_spec = pl.BlockSpec((bz, by, X), lambda kz, ky: (kz, ky, 0))
    nf = len(FIELDS)
    field_specs, assemble = _window_plan(Z, Y, X, bz, by, rr=R2,
                                         esub=esub)
    nseg = len(field_specs)

    def kern(*refs):
        field_refs = refs[:nseg * nf]
        out_f = refs[nseg * nf:nseg * nf + nf]
        out_w = refs[nseg * nf + nf:]
        dta = jnp.dtype(comp)
        data0 = {}
        for i, q in enumerate(FIELDS):
            win = assemble(field_refs[nseg * i:nseg * (i + 1)])
            data0[q] = FieldData(win.astype(comp), inv_ds, pad0, int0,
                                 x_wrap=True)
        rates0 = mhd_rates(data0, prm, comp)
        data1 = {}
        w1 = {}
        for q in FIELDS:
            w1[q] = dta.type(dt_) * rates0[q]          # alpha_0 == 0
            f1 = data0[q].value + dta.type(beta0) * w1[q]
            data1[q] = FieldData(f1, inv_ds, pad1, int1, x_wrap=True)
        rates1 = mhd_rates(data1, prm, comp)
        for i, q in enumerate(FIELDS):
            # w_1 sliced to the block for the substep-1 update
            w1c = w1[q][R:R + bz, R:R + by]
            wq = dta.type(alpha1) * w1c + dta.type(dt_) * rates1[q]
            out_w[i][...] = wq.astype(dtype)
            out_f[i][...] = (data1[q].value
                             + dta.type(beta1) * wq).astype(dtype)

    in_specs = []
    inputs = []
    for q in FIELDS:
        in_specs.extend(field_specs)
        inputs.extend([fields[q]] * nseg)
    out_shape = [jax.ShapeDtypeStruct((Z, Y, X), dtype)
                 for _ in range(2 * nf)]
    out_specs = [main_spec] * (2 * nf)

    outs = pl.pallas_call(
        kern,
        grid=(Z // bz, Y // by),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(*inputs)
    new_f = {q: outs[i] for i, q in enumerate(FIELDS)}
    new_w = {q: outs[nf + i] for i, q in enumerate(FIELDS)}
    return new_f, new_w
