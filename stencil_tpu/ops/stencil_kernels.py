"""Stencil compute kernels on padded (z,y,x) shards.

XLA-native equivalents of the reference's application kernels
(reference: bin/jacobi3d.cu:40-85 stencil_kernel). Each kernel takes a
halo-padded shard and produces interior values; slicing-based neighbor
access lowers to fused XLA ops (the VPU does the adds; no gather). A
Pallas version of the hot kernels lives in ``pallas_stencil.py``.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import lax

from ..geometry import Dim3, Radius


def shifted(padded: jnp.ndarray, off: Tuple[int, int, int],
            pad_lo: Dim3, interior: Dim3) -> jnp.ndarray:
    """Interior-shaped view of ``padded`` shifted by ``off`` (x,y,z
    direction vector): element [k,j,i] = padded[k+oz, j+oy, i+ox] in
    interior coordinates."""
    ox, oy, oz = off
    return lax.slice(
        padded,
        (pad_lo.z + oz, pad_lo.y + oy, pad_lo.x + ox),
        (pad_lo.z + oz + interior.z, pad_lo.y + oy + interior.y,
         pad_lo.x + ox + interior.x))


def jacobi7(padded: jnp.ndarray, radius: Radius, interior: Dim3) -> jnp.ndarray:
    """7-point Jacobi average: (sum of 6 face neighbors) / 6
    (reference: bin/jacobi3d.cu:65-80)."""
    lo = radius.pad_lo()
    acc = shifted(padded, (1, 0, 0), lo, interior)
    acc = acc + shifted(padded, (-1, 0, 0), lo, interior)
    acc = acc + shifted(padded, (0, 1, 0), lo, interior)
    acc = acc + shifted(padded, (0, -1, 0), lo, interior)
    acc = acc + shifted(padded, (0, 0, 1), lo, interior)
    acc = acc + shifted(padded, (0, 0, -1), lo, interior)
    return acc * (1.0 / 6.0)


def laplacian27(padded: jnp.ndarray, radius: Radius, interior: Dim3,
                weights=None) -> jnp.ndarray:
    """27-point weighted stencil (radius-1 box) — exercises edge/corner
    halo data; default weights are the standard 27-point Laplacian."""
    lo = radius.pad_lo()
    if weights is None:
        # face 6/26? use canonical 27-pt laplacian weights
        w_center, w_face = -88.0 / 26.0, 6.0 / 26.0
        w_edge, w_corner = 3.0 / 26.0, 2.0 / 26.0
    else:
        w_center, w_face, w_edge, w_corner = weights
    out = w_center * shifted(padded, (0, 0, 0), lo, interior)
    for dx in (-1, 0, 1):
        for dy in (-1, 0, 1):
            for dz in (-1, 0, 1):
                n = (dx != 0) + (dy != 0) + (dz != 0)
                if n == 0:
                    continue
                w = (w_face, w_edge, w_corner)[n - 1]
                out = out + w * shifted(padded, (dx, dy, dz), lo, interior)
    return out


def central_diff(padded: jnp.ndarray, axis: int, radius: Radius,
                 interior: Dim3) -> jnp.ndarray:
    """Second-order central difference along grid ``axis`` (0=x, 1=y,
    2=z): ``(p[i+1] - p[i-1]) / 2`` over the interior — the radius-1
    gradient component the PIC mini-app's field gather interpolates
    (``models/pic.py`` computes ``E = -grad rho`` from the deposited
    charge). ``radius`` is the ALLOCATION radius of ``padded`` (the
    slices reach one cell past the interior along ``axis`` only)."""
    lo = radius.pad_lo()
    plus = [0, 0, 0]
    plus[axis] = 1
    minus = [0, 0, 0]
    minus[axis] = -1
    return (shifted(padded, tuple(plus), lo, interior)
            - shifted(padded, tuple(minus), lo, interior)) * 0.5


def write_interior(padded: jnp.ndarray, interior_vals: jnp.ndarray,
                   radius: Radius) -> jnp.ndarray:
    """Place interior-shaped values into a padded shard (halos keep
    their previous contents)."""
    lo = radius.pad_lo()
    return lax.dynamic_update_slice(padded, interior_vals.astype(padded.dtype),
                                    (lo.z, lo.y, lo.x))


def global_coords(origin_xyz, interior: Dim3):
    """(z, y, x) broadcastable global-coordinate arrays for a shard's
    interior — the Accessor "friendly coordinates" analog for
    masks/sources (reference: include/stencil/accessor.hpp:31-45).
    ``origin_xyz`` is an (ox, oy, oz) triple; traced scalars are fine
    (e.g. derived from ``lax.axis_index`` inside shard_map)."""
    ox, oy, oz = origin_xyz
    gz = oz + jnp.arange(interior.z)[:, None, None]
    gy = oy + jnp.arange(interior.y)[None, :, None]
    gx = ox + jnp.arange(interior.x)[None, None, :]
    return gz, gy, gx
