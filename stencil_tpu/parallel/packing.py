"""Canonical irredundant halo wire layout: send every cell ONCE.

The slab layout (``wire_layout="slab"``, the default and the reference
library's shape — src/packer.cu ships full padded cross-sections) puts
each per-axis message over the FULL allocation of the other dims.  That
is simple and makes the sequential-sweep corner rule automatic, but it
re-sends redundantly: the pending axes' halo rows in a slab are stale
at ship time (the later sweep overwrites them anyway), and when the
allocation is padded deeper than the wire (temporal blocking,
``alloc_radius``) the slab drags the whole s-deep pad cross-section
along for a 1-deep refresh.

This module plans the IRREDUNDANT layout (``wire_layout="irredundant"``
— TEMPI's canonical datatype representation, arXiv:2012.14363, crossed
with the irredundant compressed stencil layout of arXiv:2401.12071):
each per-axis-direction message is ONE contiguous box that carries

* along the sweep axis: exactly the wire face rows;
* along every axis swept EARLIER in ``axis_order``: the interior plus
  that axis's wire halo rows — the minimal diagonal (edge/corner)
  segment, freshly filled by the earlier sweep, so corner data still
  propagates by the sequential-sweep rule;
* along every PENDING axis: the interior only — its halo is rewritten
  by the later sweep, so shipping it would be pure waste.

Each halo cell of the wire-radius shell is therefore sent exactly once
(telescoping: a cell in the halo shell of axes ``i < j`` rides only the
sweep-``j`` message), the collective bill is unchanged (still one
ppermute per direction per axis), and only the payload shrinks.

Boxes are STATIC capacity-sized spans so one program serves every
shard of an uneven (+-1 remainder) partition; a span whose start
depends on the shard's actual interior length carries ``plus_L`` and
the engine adds the traced ``shard_interior_len`` at slice time.  The
one-row static overhang a short shard ships lands in the receiver's
dead slack (same mesh coordinate on non-sweep axes, hence the same
traced length at both endpoints) or in a halo row the later sweep
rewrites — bitwise equality with the slab layout holds on the whole
live window (interior plus wire-radius shell).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Sequence, Tuple

from ..geometry import Dim3, Radius

#: halo wire LAYOUTS: how a per-axis message is shaped. "slab" ships
#: full-allocation cross-sections (the reference layout); "irredundant"
#: ships each wire-halo cell exactly once (this module's planner).
WIRE_LAYOUTS = ("slab", "irredundant")


def normalize_wire_layout(wire_layout) -> str:
    """Canonical wire-layout name; ``None`` means the slab default."""
    if wire_layout is None:
        return "slab"
    if wire_layout not in WIRE_LAYOUTS:
        raise ValueError(f"unknown wire layout {wire_layout!r}; "
                         f"expected one of {WIRE_LAYOUTS}")
    return str(wire_layout)


@dataclass(frozen=True)
class Span:
    """One axis extent of a message box: ``[start, start + size)``
    where ``start = base (+ L)`` and ``L`` is the shard's traced
    interior length along that grid axis (``plus_L`` marks the two
    dynamic placements: the hi-side halo landing and the lo-side
    interior-edge pickup)."""
    base: int
    plus_L: bool
    size: int


@dataclass(frozen=True)
class DirectionPlan:
    """Pack/unpack index map for ONE per-axis-direction message.

    ``src``/``dst`` are per-GRID-axis spans (index 0 = x, 1 = y,
    2 = z) into the sender's/receiver's padded allocation; sizes match
    span-for-span so the ppermuted box is a static reshape away from
    both."""
    axis: int
    side: int
    src: Tuple[Span, Span, Span]
    dst: Tuple[Span, Span, Span]

    @property
    def elems(self) -> int:
        n = 1
        for s in self.src:
            n *= s.size
        return n


def plan_direction(axis: int, side: int, radius: Radius,
                   alloc_radius: Radius, axis_order: Tuple[int, ...],
                   interiors: Sequence[int]) -> DirectionPlan:
    """Plan the irredundant box for sweep ``axis``, direction ``side``
    (+1 ships toward the +axis neighbor's lo halo landing at
    ``p_lo + L``; -1 the mirror).  ``interiors`` is the per-grid-axis
    interior CAPACITY (allocation minus both alloc pads)."""
    assert axis in axis_order, (axis, axis_order)
    pos = axis_order.index(axis)
    src = []
    dst = []
    for j in range(3):
        p_lo = alloc_radius.face(j, -1)
        c = int(interiors[j])
        if j == axis:
            r_lo = radius.face(j, -1)
            r_hi = radius.face(j, 1)
            if side == 1:
                # rows [p_lo, p_lo + r_hi) -> neighbor's [p_lo + L, ...)
                src.append(Span(p_lo, False, r_hi))
                dst.append(Span(p_lo, True, r_hi))
            else:
                # rows [p_lo + L - r_lo, ...) -> neighbor's [p_lo - r_lo, ...)
                src.append(Span(p_lo - r_lo, True, r_lo))
                dst.append(Span(p_lo - r_lo, False, r_lo))
        elif j in axis_order and axis_order.index(j) < pos:
            # already swept: interior plus its freshly-filled wire halo
            # rows — the minimal diagonal segment (edge/corner carry)
            r_lo = radius.face(j, -1)
            r_hi = radius.face(j, 1)
            span = Span(p_lo - r_lo, False, c + r_lo + r_hi)
            src.append(span)
            dst.append(span)
        else:
            # pending (or never-swept) axis: interior only — its halo
            # is rewritten by the later sweep
            span = Span(p_lo, False, c)
            src.append(span)
            dst.append(span)
    return DirectionPlan(axis=axis, side=side,
                         src=tuple(src), dst=tuple(dst))


def plan_sweep(radius: Radius, alloc_radius: "Radius | None",
               interiors: Sequence[int],
               axis_order: Tuple[int, ...] = (0, 1, 2)
               ) -> Dict[Tuple[int, int], DirectionPlan]:
    """All direction plans of one exchange round, keyed ``(axis,
    side)``; zero-radius directions are omitted (no message)."""
    alloc_r = alloc_radius if alloc_radius is not None else radius
    plans: Dict[Tuple[int, int], DirectionPlan] = {}
    for a in axis_order:
        for side in (1, -1):
            if radius.face(a, side) == 0:
                continue
            plans[(a, side)] = plan_direction(a, side, radius, alloc_r,
                                              axis_order, interiors)
    return plans


def _interiors_from_padded(shard_padded_shape_zyx: Sequence[int],
                           alloc_r: Radius) -> Tuple[int, int, int]:
    z, y, x = (int(v) for v in shard_padded_shape_zyx)
    dims = (x, y, z)  # per grid axis
    return tuple(dims[a] - alloc_r.face(a, -1) - alloc_r.face(a, 1)
                 for a in range(3))


def irredundant_bytes_per_sweep(shard_padded_shape_zyx: Sequence[int],
                                radius: Radius, mesh_counts: Dim3,
                                elem_size: int,
                                axis_order: Tuple[int, ...] = (0, 1, 2),
                                wire_format=None,
                                alloc_radius: "Radius | None" = None
                                ) -> Dict[str, int]:
    """Per-axis wire bytes one shard ships per exchange under the
    irredundant layout — the twin of
    :func:`..parallel.exchange.exchanged_bytes_per_sweep` (which prices
    the slab layout).  Counts only shifts that cross devices; a
    narrowing ``wire_format`` axis prices elements at on-wire width."""
    from .exchange import AXIS_NAME, normalize_wire_format, wire_elem_size

    alloc_r = alloc_radius if alloc_radius is not None else radius
    interiors = _interiors_from_padded(shard_padded_shape_zyx, alloc_r)
    plans = plan_sweep(radius, alloc_r, interiors, axis_order)
    wf = normalize_wire_format(wire_format)
    out = {"x": 0, "y": 0, "z": 0}
    for (a, _side), plan in plans.items():
        if mesh_counts[a] <= 1:
            continue
        es = wire_elem_size(elem_size, wf[AXIS_NAME[a]])
        out[AXIS_NAME[a]] += plan.elems * es
    return out


def pack_layout_report() -> Dict[str, Dict[str, object]]:
    """Slab-vs-irredundant modeled wire bytes for the canonical
    registered exchange configs — the CI pack-layout artifact archived
    next to ``precision_certificates.json``.  Every entry's
    irredundant bytes are strictly below slab wherever a diagonal
    (edge/corner) carry exists (r >= 1 on more than one axis)."""
    from .exchange import exchanged_bytes_per_sweep

    counts = Dim3(2, 2, 2)
    asym = Radius.constant(0)
    asym.set_dir((1, 0, 0), 2)
    asym.set_dir((-1, 0, 0), 1)
    asym.set_dir((0, 1, 0), 1)
    configs = [
        # name, shard_padded_zyx, radius, elem, alloc_radius
        ("exchange[r1]", (16, 16, 16), Radius.constant(1), 4, None),
        ("exchange[r3]", (20, 20, 20), Radius.constant(3), 4, None),
        ("exchange[asym]", (14, 15, 17), asym, 4, None),
        ("exchange_packed[uneven,f32]", (10, 10, 10), Radius.constant(1),
         4, None),
        ("temporal[s=2,deep]", (12, 12, 12), Radius.constant(2), 4, None),
        ("deep_tail[r1,alloc=r2]", (16, 16, 16), Radius.constant(1), 4,
         Radius.constant(2)),
    ]
    report: Dict[str, Dict[str, object]] = {}
    for name, padded, radius, elem, alloc in configs:
        slab = sum(exchanged_bytes_per_sweep(
            padded, radius, counts, elem).values())
        irr = sum(irredundant_bytes_per_sweep(
            padded, radius, counts, elem, alloc_radius=alloc).values())
        report[name] = {
            "shard_padded_zyx": list(padded),
            "slab_bytes": int(slab),
            "irredundant_bytes": int(irr),
            "saved_fraction": round(1.0 - irr / slab, 6) if slab else 0.0,
        }
    return report
