"""Megastep: a whole campaign segment as ONE compiled program.

The run loops used to return to Python every step (or every s-step
temporal group): at dispatch-bound sizes the host round-trip, not the
wire, set steps/s. Production stencil/PIC codes restructure exactly
this boundary — PIConGPU (arXiv:1606.02862) moves control into the
device program, POLAR-PIC (arXiv:2604.19337) co-designs the step loop
with its communication. The megastep is that restructuring for this
library: a ``check_every``-sized segment of the campaign fuses into a
single XLA program that

* advances the state ``check_every`` steps through the SAME per-shard
  step bodies the stepwise loops use (bitwise-identical evolution);
* carries the health-sentinel probe **in-graph** — every
  ``probe_every`` sub-steps (and always at the segment's final step)
  the fused :func:`~stencil_tpu.resilience.health.probe_shard`
  reduction appends one row to a stacked probe trace, so the driver's
  divergence predicate can locate the EXACT tripped step after the
  fact without replaying the segment on host;
* rides the telemetry step-metric columns on each probe row (the
  cumulative-substep / cumulative-wire-byte contract of
  ``telemetry/probe.py``) computed in-graph from a 2-element base
  vector, so the one-all-reduce-per-probe bill is unchanged;
* donates its state end-to-end (``input_output_alias`` for every field
  buffer — proven in ``tests/test_donation.py``), so a segment costs
  no more HBM than one step.

Audited like everything else: the ``parallel.megastep.segment[...]``
registry targets pin the lowered StableHLO to exactly ``k`` x the
per-step collective_permute count plus one small all_reduce per probe
row and NOTHING else, with the exchange bytes cross-checked exactly
against the analytic model (``k`` x the per-step figure). The negative
control ``tests/fixtures/lint/bad_megastep.py`` — a segment that
re-reduces the probe on every sub-step — is proven flagged.

The segment body is unrolled (a Python loop over the traced step
body): collective counts in the lowered module are literally ``k`` x
the per-step counts, which is what makes the registry contract exact.
``MAX_UNROLL`` bounds compile time; drivers cut longer spans into
multiple dispatches.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple

#: segments longer than this are cut into multiple dispatches by the
#: consumers (compile time of the unrolled body grows with k)
MAX_UNROLL = 64


def _metric_names() -> Tuple[str, ...]:
    """The telemetry metric columns a megastep probe row can carry —
    the one source of truth (``telemetry.probe.STEP_METRIC_NAMES``),
    imported lazily to keep this package import-light."""
    from ..telemetry.probe import STEP_METRIC_NAMES
    return STEP_METRIC_NAMES


def segment_chunks(k: int, stride: int = 1) -> List[int]:
    """The advance-chunk sizes of a ``k``-step segment whose step body
    moves ``stride`` steps per call (temporal blocking): whole groups
    first, then depth-1 tail steps — the same whole-groups-plus-tail
    shape the blocked run loops use."""
    k = int(k)
    stride = max(int(stride), 1)
    return [stride] * (k // stride) + [1] * (k % stride)


def probe_rel_steps(chunks: Sequence[int], probe_every: int = 1
                    ) -> Tuple[int, ...]:
    """The cumulative sub-step count at each probe row of a fused
    segment: a probe fires after a chunk once ``probe_every`` steps
    have accumulated since the last row, and always after the final
    chunk (the boundary step's health is never skipped)."""
    probe_every = max(int(probe_every), 1)
    rel: List[int] = []
    done = last = 0
    total = sum(chunks)
    for c in chunks:
        done += c
        if done - last >= probe_every or done == total:
            rel.append(done)
            last = done
    return tuple(rel)


def health_probe(probe_view: Callable[[Any], dict],
                 base_vec=None,
                 metric_names: Sequence[str] = (),
                 bytes_per_step: float = 0.0,
                 axis_names: Sequence[str] = ("z", "y", "x")):
    """The standard in-graph probe for :func:`fused_segment_shard`:
    one :func:`~stencil_tpu.resilience.health.probe_shard` reduction
    over ``probe_view(state)`` (ONE small all-reduce per row), with
    the telemetry step-metric columns computed in-graph from
    ``base_vec = [base_substeps, base_wire_bytes]`` — row ``done``
    carries ``base + done`` substeps and ``base + done *
    bytes_per_step`` wire bytes, the exact cumulative contract of
    ``telemetry/probe.py`` without any host round-trip."""
    metric_names = tuple(metric_names)
    known = _metric_names()
    for m in metric_names:
        if m not in known:
            raise ValueError(f"unknown megastep metric column {m!r} "
                             f"(have {known})")

    def probe(state, done: int):
        from ..resilience.health import probe_shard
        extra = None
        if metric_names:
            vals = {"substeps": base_vec[0] + float(done),
                    "wire_bytes": base_vec[1]
                    + float(done) * float(bytes_per_step)}
            extra = {m: vals[m] for m in metric_names}
        return probe_shard(probe_view(state), axis_names, extra=extra)

    return probe


def fused_segment_shard(state, advance, probe, chunks: Sequence[int],
                        probe_every: int = 1):
    """The fused segment body, for use INSIDE ``shard_map``: advance
    ``state`` through ``chunks`` (``advance(state, chunk_steps, idx)``
    per chunk, unrolled), emitting one ``probe(state, done)`` row per
    :func:`probe_rel_steps` point. Returns ``(state, trace)`` where
    ``trace`` stacks the probe rows along a new leading axis."""
    import jax.numpy as jnp

    probe_every = max(int(probe_every), 1)
    rows = []
    done = last = 0
    total = sum(chunks)
    for idx, c in enumerate(chunks):
        state = advance(state, int(c), idx)
        done += int(c)
        if done - last >= probe_every or done == total:
            rows.append(probe(state, done))
            last = done
    return state, jnp.stack(rows)


@dataclasses.dataclass
class SegmentTrace:
    """A fused segment's stacked probe trace, still on device.

    ``array`` is ``(n_rows, 2, n_cols)`` (ensembles:
    ``(n_rows, n_members, 2, n_quantities)``); ``steps`` holds the
    RELATIVE sub-step count of each row; readback is the consumer's
    business (``HealthSentinel.observe_segment`` polls ``is_ready``)."""

    array: Any
    steps: Tuple[int, ...]
    base_step: int = 0

    @property
    def abs_steps(self) -> List[int]:
        return [self.base_step + r for r in self.steps]


class Segment:
    """One compiled campaign segment bound to its owner's state.

    ``run(base_step)`` dispatches the fused program ONCE, advancing the
    owner's state in place by :attr:`steps` steps, and returns the
    :class:`SegmentTrace` (device handle — no sync). ``fn`` exposes
    the underlying jitted program (``fn(state, base_vec)``) for
    lowering-level introspection — the donation proof in
    ``tests/test_donation.py`` pins its ``input_output_alias`` map."""

    def __init__(self, run_fn: Callable[[int], SegmentTrace],
                 steps: int, probe_steps: Tuple[int, ...],
                 fn: Optional[Callable] = None) -> None:
        self._run = run_fn
        self.steps = int(steps)
        self.probe_steps = tuple(probe_steps)
        self.fn = fn

    def run(self, base_step: int = 0) -> SegmentTrace:
        return self._run(int(base_step))


def metric_base_vec(metrics, base_step: int, mesh=None):
    """The replicated f32 ``[substeps, wire_bytes]`` base the fused
    probe rows increment in-graph — ``metrics.values(base_step)`` (the
    metrics protocol of ``resilience/health.py``; ``StepMetrics``
    commits it replicated over its domain's mesh), or zeros over
    ``mesh`` when no metrics ride. Either way the host->device
    movement is EXPLICIT (``jax.device_put``) so the fused dispatch
    runs clean under the hot-loop ``jax.transfer_guard("disallow")`` —
    no implicit transfer, no dispatch-time reshard."""
    import jax
    import numpy as np

    if metrics is not None:
        return metrics.values(int(base_step))
    vec = np.zeros((2,), np.float32)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(vec, NamedSharding(mesh, P()))
    return jax.device_put(vec)


def make_segment_fn(mesh, advance, probe_view, state_specs,
                    chunks: Sequence[int], probe_every: int = 1,
                    metric_names: Sequence[str] = (),
                    bytes_per_step: float = 0.0):
    """Build the jitted fused-segment program: ``fn(state, base_vec) ->
    (state, trace)`` over ``mesh``, with the state pytree DONATED
    end-to-end and the trace replicated. ``advance(state, steps, idx)``
    and ``probe_view(state) -> {name: padded array}`` run per shard."""
    import jax
    from jax.sharding import PartitionSpec as P

    chunks = [int(c) for c in chunks]

    def shard_seg(state, vec):
        probe = health_probe(probe_view, base_vec=vec,
                             metric_names=metric_names,
                             bytes_per_step=bytes_per_step)
        return fused_segment_shard(state, advance, probe, chunks,
                                   probe_every)

    sm = jax.shard_map(shard_seg, mesh=mesh,
                       in_specs=(state_specs, P()),
                       out_specs=(state_specs, P()), check_vma=False)
    return jax.jit(sm, donate_argnums=0)


def make_domain_segment(dd, shard_step, check_every: int,
                        probe_every: int = 1,
                        metrics=None) -> Segment:
    """A fused segment over a realized ``DistributedDomain``'s field
    dict: ``shard_step(fields) -> fields`` (per shard, all quantities
    padded) applied ``check_every`` times with the in-graph probe over
    every registered quantity. The compiled program is cached on the
    domain, keyed by the step fn and the segment geometry."""
    from jax.sharding import PartitionSpec as P

    k = int(check_every)
    if k < 1:
        raise ValueError(f"check_every must be >= 1, got {check_every}")
    probe_every = max(int(probe_every), 1)
    names = list(dd._names)
    cache = getattr(dd, "_segment_cache", None)
    if cache is None:
        cache = {}
        dd._segment_cache = cache
    key = (shard_step, k, probe_every,
           None if metrics is None else float(metrics.bytes_per_step))
    fn = cache.get(key)
    chunks = segment_chunks(k)
    if fn is None:
        spec = {q: P("z", "y", "x") for q in names}
        fn = make_segment_fn(
            dd.mesh,
            lambda fields, c, i: shard_step(fields),
            lambda fields: {q: fields[q] for q in names},
            spec, chunks, probe_every=probe_every,
            metric_names=(metrics.names if metrics is not None else ()),
            bytes_per_step=(metrics.bytes_per_step
                            if metrics is not None else 0.0))
        cache[key] = fn
    rel = probe_rel_steps(chunks, probe_every)

    def run(base_step: int) -> SegmentTrace:
        vec = metric_base_vec(metrics, base_step, mesh=dd.mesh)
        out, trace = fn(dict(dd.curr), vec)
        dd.curr = dict(out)
        return SegmentTrace(trace, rel, base_step)

    return Segment(run, k, rel, fn=fn)
