"""Megastep: a whole campaign segment as ONE compiled program.

The run loops used to return to Python every step (or every s-step
temporal group): at dispatch-bound sizes the host round-trip, not the
wire, set steps/s. Production stencil/PIC codes restructure exactly
this boundary — PIConGPU (arXiv:1606.02862) moves control into the
device program, POLAR-PIC (arXiv:2604.19337) co-designs the step loop
with its communication. The megastep is that restructuring for this
library: a ``check_every``-sized segment of the campaign fuses into a
single XLA program.

This module is a SEGMENT COMPILER, not a Jacobi-shaped unroller: a
model targets it by declaring a :class:`CarryContract` — the carried
state pytree and its PartitionSpecs, the donation set, the probe
extraction, extra in-graph probe columns, and the stride one
``advance`` call moves (a temporal group, or a Pallas kernel's
in-kernel step count) — and registering a :class:`SegmentCompiler`.
PIC's particle lanes + in-graph overflow column, Astaroth's ``w``
accumulators under ``lcm(3, s)``-period temporal grouping, and the
Jacobi wrap/halo kernels' multi-step launches all compile to one
donated program per health boundary through this one interface. A
path that cannot fuse returns a :class:`SegmentDecline` (falsy, with
the reason) via :func:`decline` — never a silent ``None``.

Every fused segment

* advances the state ``check_every`` steps through the SAME per-shard
  step bodies the stepwise loops use (bitwise-identical evolution);
* carries the health-sentinel probe **in-graph** — every
  ``probe_every`` sub-steps (and always at the segment's final step)
  the fused :func:`~stencil_tpu.resilience.health.probe_shard`
  reduction appends one row to a stacked probe trace, so the driver's
  divergence predicate can locate the EXACT tripped step after the
  fact without replaying the segment on host;
* rides the telemetry step-metric columns on each probe row (the
  cumulative-substep / cumulative-wire-byte contract of
  ``telemetry/probe.py``) computed in-graph from a 2-element base
  vector, so the one-all-reduce-per-probe bill is unchanged;
* donates its state end-to-end (``input_output_alias`` for every field
  buffer — proven in ``tests/test_donation.py``), so a segment costs
  no more HBM than one step.

Audited like everything else: the ``parallel.megastep.segment[...]``
registry targets pin the lowered StableHLO to exactly ``k`` x the
per-step collective_permute count plus one small all_reduce per probe
row and NOTHING else, with the exchange bytes cross-checked exactly
against the analytic model (``k`` x the per-step figure). The negative
control ``tests/fixtures/lint/bad_megastep.py`` — a segment that
re-reduces the probe on every sub-step — is proven flagged.

The segment body is unrolled (a Python loop over the traced step
body): collective counts in the lowered module are literally ``k`` x
the per-step counts, which is what makes the registry contract exact.
``MAX_UNROLL`` bounds compile time; drivers cut longer spans into
multiple dispatches.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

#: segments longer than this are cut into multiple dispatches by the
#: consumers (compile time of the unrolled body grows with k)
MAX_UNROLL = 64


def _metric_names() -> Tuple[str, ...]:
    """The telemetry metric columns a megastep probe row can carry —
    the one source of truth (``telemetry.probe.STEP_METRIC_NAMES``),
    imported lazily to keep this package import-light."""
    from ..telemetry.probe import STEP_METRIC_NAMES
    return STEP_METRIC_NAMES


@dataclasses.dataclass(frozen=True)
class CarryContract:
    """A model's declaration of what its fused segment carries.

    The segment compiler (:class:`SegmentCompiler` /
    :func:`make_segment_fn`) is model-agnostic: everything
    model-specific about a fused segment — which pytree is the loop
    state, how it shards, what the in-graph probe reads, and which
    extra in-graph columns ride the probe's single all-reduce — lives
    in this contract, TEMPI-style (arXiv:2012.14363: a canonical
    interface the transport compiles against, instead of one bespoke
    code path per workload).

    * ``specs`` — PartitionSpec pytree matching the carried state
      (PIC: the padded rho plus every particle lane; Astaroth: the
      ``(fields, w)`` accumulator pair; Jacobi: one padded field);
    * ``probe_view(state) -> {name: array}`` — the quantities the
      in-graph health probe reduces (one row per probe point, ONE
      all-reduce per row);
    * ``probe_extra(state) -> {name: scalar}`` — extra IN-GRAPH probe
      columns riding that same all-reduce (PIC's cumulative
      migration-overflow counter; order must match the sentinel's
      ``extra_names``);
    * ``stride`` — steps one ``advance(state, c, idx)`` call moves
      when ``c`` equals it: a temporal group (``lcm(3, s)/3``
      iterations for Astaroth's RK grouping), or a Pallas kernel's
      in-kernel multi-step count (wrap/halo run ``steps`` inside one
      ``pallas_call``, so a chunk is one kernel launch, not an
      unroll). Chunks of 1 are the depth-1 tail;
    * ``donate`` — donate the state pytree end-to-end (default; the
      audit registry proves the alias map);
    * ``compute_dtype`` — the model's declared minimum accumulation
      dtype (default ``"float32"``): the precision certifier
      (``analysis/precision.py``) proves every reduction in the fused
      segment runs at >= this width even when storage is narrower —
      the MHD storage/compute split as a proven invariant;
    * ``wire_formats`` — declared per-axis halo wire formats
      (``{"x"|"y"|"z": "f32"|"bf16"}`` or a single format string,
      default None = full-precision wire): the certifier classifies
      the segment's narrow/widen convert pairs at the ppermute
      boundary as DECLARED rather than silent.
    """

    specs: Any
    probe_view: Callable[[Any], Dict[str, Any]]
    probe_extra: Optional[Callable[[Any], Dict[str, Any]]] = None
    stride: int = 1
    donate: bool = True
    compute_dtype: Optional[str] = "float32"
    wire_formats: Optional[Any] = None


# -- decline-reason vocabulary ----------------------------------------
# Every SegmentDecline carries one of these machine-readable codes
# alongside its prose reason, so ``fused:false`` report entries and
# the flight-recorder ``fused_decline`` timeline events are greppable
# by CAUSE instead of by free-form string (test_megastep pins the set).

#: the built path registered no fused-segment builder at all
DECLINE_NO_BUILDER = "no-fused-builder"
#: an RDMA kernel whose schedule certificate is missing or says
#: ``replay_safe=false`` (analysis/schedule.py) — proof, not policy
DECLINE_UNCERTIFIED_SCHEDULE = "uncertified-rdma-schedule"
#: the path keeps live state outside the segment carry (Astaroth's
#: extract/loop/insert program split)
DECLINE_INTERIOR_RESIDENT_STATE = "interior-resident-state"
#: the driver was constructed with fuse_segments disabled
DECLINE_POLICY_DISABLED = "policy-disabled"
#: the engine handed the driver no make_segment factory
DECLINE_NO_FACTORY = "no-segment-factory"
#: rebuild() after degradation returned no segment factory
DECLINE_REBUILD_NO_FACTORY = "rebuild-no-segment-factory"

DECLINE_REASONS = frozenset({
    DECLINE_NO_BUILDER, DECLINE_UNCERTIFIED_SCHEDULE,
    DECLINE_INTERIOR_RESIDENT_STATE, DECLINE_POLICY_DISABLED,
    DECLINE_NO_FACTORY, DECLINE_REBUILD_NO_FACTORY,
})


class SegmentDecline:
    """A falsy ``make_segment`` result that says WHY no fused segment
    exists for the built path — silent ``None`` returns made stepwise
    fallbacks invisible to operators. ``code`` is one of the
    ``DECLINE_*`` vocabulary constants; ``reason`` is the prose. The
    driver logs it, records ``fused: false`` + the reason/code in the
    :class:`~stencil_tpu.resilience.driver.ResilienceReport`, and
    exports the ``stencil_run_fused_dispatch_total{fused}`` counter."""

    def __init__(self, model: str, path: str, reason: str,
                 code: str = DECLINE_NO_BUILDER) -> None:
        self.model = str(model)
        self.path = str(path)
        self.reason = str(reason)
        self.code = str(code)

    def __bool__(self) -> bool:
        return False

    def __repr__(self) -> str:
        return (f"SegmentDecline({self.model}[{self.path}] "
                f"[{self.code}]: {self.reason})")


_DECLINES_WARNED: set = set()


def decline(model: str, path: str, reason: str,
            code: str = DECLINE_NO_BUILDER) -> SegmentDecline:
    """Record a fused-segment decline LOUDLY: warn once per
    (model, path, reason) and return the falsy, reason-carrying
    :class:`SegmentDecline` for the caller to hand back. ``code``
    must come from the ``DECLINE_REASONS`` vocabulary."""
    from ..utils.logging import LOG_WARN

    if code not in DECLINE_REASONS:
        raise ValueError(
            f"unknown decline code {code!r}; the vocabulary is "
            f"{sorted(DECLINE_REASONS)} (parallel/megastep.py)")
    key = (model, path, reason)
    if key not in _DECLINES_WARNED:
        _DECLINES_WARNED.add(key)
        LOG_WARN(f"{model}[{path}] declines megastep fusion "
                 f"[{code}]: {reason} — campaigns on this path run "
                 f"stepwise")
    return SegmentDecline(model, path, reason, code)


def certificate_gate(certificate) -> Optional[str]:
    """The megastep side of schedule certification
    (analysis/schedule.py): ``None`` when ``certificate`` licenses
    fusing the kernel's launches into one program (``replay_safe``),
    else the certificate-citing decline reason the path must carry
    (with code :data:`DECLINE_UNCERTIFIED_SCHEDULE`)."""
    if certificate is not None and getattr(certificate, "replay_safe",
                                           False):
        return None
    if certificate is None:
        return ("uncertified RDMA schedule: no schedule certificate "
                "for this kernel")
    cited = "; ".join(getattr(certificate, "reasons", ()) or ()) \
        or "certifier returned no reasons"
    return (f"uncertified RDMA schedule (replay_safe=false over "
            f"replay={getattr(certificate, 'replay', '?')}): {cited}")


def segment_chunks(k: int, stride: int = 1) -> List[int]:
    """The advance-chunk sizes of a ``k``-step segment whose step body
    moves ``stride`` steps per call (temporal blocking): whole groups
    first, then depth-1 tail steps — the same whole-groups-plus-tail
    shape the blocked run loops use."""
    k = int(k)
    stride = max(int(stride), 1)
    return [stride] * (k // stride) + [1] * (k % stride)


def probe_rel_steps(chunks: Sequence[int], probe_every: int = 1
                    ) -> Tuple[int, ...]:
    """The cumulative sub-step count at each probe row of a fused
    segment: a probe fires after a chunk once ``probe_every`` steps
    have accumulated since the last row, and always after the final
    chunk (the boundary step's health is never skipped)."""
    probe_every = max(int(probe_every), 1)
    rel: List[int] = []
    done = last = 0
    total = sum(chunks)
    for c in chunks:
        done += c
        if done - last >= probe_every or done == total:
            rel.append(done)
            last = done
    return tuple(rel)


def health_probe(probe_view: Callable[[Any], dict],
                 base_vec=None,
                 metric_names: Sequence[str] = (),
                 bytes_per_step: float = 0.0,
                 axis_names: Sequence[str] = ("z", "y", "x"),
                 probe_extra: Optional[Callable[[Any], dict]] = None):
    """The standard in-graph probe for :func:`fused_segment_shard`:
    one :func:`~stencil_tpu.resilience.health.probe_shard` reduction
    over ``probe_view(state)`` (ONE small all-reduce per row), with
    the telemetry step-metric columns computed in-graph from
    ``base_vec = [base_substeps, base_wire_bytes]`` — row ``done``
    carries ``base + done`` substeps and ``base + done *
    bytes_per_step`` wire bytes, the exact cumulative contract of
    ``telemetry/probe.py`` without any host round-trip.

    ``probe_extra(state) -> {name: scalar}`` appends model-owned
    IN-GRAPH columns (a :class:`CarryContract`'s extra probe columns —
    PIC's cumulative migration-overflow counter) on the same single
    all-reduce, after any metric columns."""
    metric_names = tuple(metric_names)
    known = _metric_names()
    for m in metric_names:
        if m not in known:
            raise ValueError(f"unknown megastep metric column {m!r} "
                             f"(have {known})")

    def probe(state, done: int):
        from ..resilience.health import probe_shard
        extra = None
        if metric_names:
            vals = {"substeps": base_vec[0] + float(done),
                    "wire_bytes": base_vec[1]
                    + float(done) * float(bytes_per_step)}
            extra = {m: vals[m] for m in metric_names}
        if probe_extra is not None:
            extra = dict(extra or {})
            extra.update(probe_extra(state))
        return probe_shard(probe_view(state), axis_names, extra=extra)

    return probe


def fused_segment_shard(state, advance, probe, chunks: Sequence[int],
                        probe_every: int = 1):
    """The fused segment body, for use INSIDE ``shard_map``: advance
    ``state`` through ``chunks`` (``advance(state, chunk_steps, idx)``
    per chunk, unrolled), emitting one ``probe(state, done)`` row per
    :func:`probe_rel_steps` point. Returns ``(state, trace)`` where
    ``trace`` stacks the probe rows along a new leading axis."""
    import jax.numpy as jnp

    probe_every = max(int(probe_every), 1)
    rows = []
    done = last = 0
    total = sum(chunks)
    for idx, c in enumerate(chunks):
        state = advance(state, int(c), idx)
        done += int(c)
        if done - last >= probe_every or done == total:
            rows.append(probe(state, done))
            last = done
    return state, jnp.stack(rows)


@dataclasses.dataclass
class SegmentTrace:
    """A fused segment's stacked probe trace, still on device.

    ``array`` is ``(n_rows, 2, n_cols)`` (ensembles:
    ``(n_rows, n_members, 2, n_quantities)``); ``steps`` holds the
    RELATIVE sub-step count of each row; readback is the consumer's
    business (``HealthSentinel.observe_segment`` polls ``is_ready``)."""

    array: Any
    steps: Tuple[int, ...]
    base_step: int = 0

    @property
    def abs_steps(self) -> List[int]:
        return [self.base_step + r for r in self.steps]


class Segment:
    """One compiled campaign segment bound to its owner's state.

    ``run(base_step)`` dispatches the fused program ONCE, advancing the
    owner's state in place by :attr:`steps` steps, and returns the
    :class:`SegmentTrace` (device handle — no sync). ``fn`` exposes
    the underlying jitted program (``fn(state, base_vec)``) for
    lowering-level introspection — the donation proof in
    ``tests/test_donation.py`` pins its ``input_output_alias`` map."""

    def __init__(self, run_fn: Callable[[int], SegmentTrace],
                 steps: int, probe_steps: Tuple[int, ...],
                 fn: Optional[Callable] = None) -> None:
        self._run = run_fn
        self.steps = int(steps)
        self.probe_steps = tuple(probe_steps)
        self.fn = fn

    def run(self, base_step: int = 0) -> SegmentTrace:
        return self._run(int(base_step))


def metric_base_vec(metrics, base_step: int, mesh=None):
    """The replicated f32 ``[substeps, wire_bytes]`` base the fused
    probe rows increment in-graph — ``metrics.values(base_step)`` (the
    metrics protocol of ``resilience/health.py``; ``StepMetrics``
    commits it replicated over its domain's mesh), or zeros over
    ``mesh`` when no metrics ride. Either way the host->device
    movement is EXPLICIT (``jax.device_put``) so the fused dispatch
    runs clean under the hot-loop ``jax.transfer_guard("disallow")`` —
    no implicit transfer, no dispatch-time reshard."""
    import jax
    import numpy as np

    if metrics is not None:
        return metrics.values(int(base_step))
    vec = np.zeros((2,), np.float32)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(vec, NamedSharding(mesh, P()))
    return jax.device_put(vec)


def make_segment_fn(mesh, advance, probe_view, state_specs,
                    chunks: Sequence[int], probe_every: int = 1,
                    metric_names: Sequence[str] = (),
                    bytes_per_step: float = 0.0,
                    probe_extra: Optional[Callable] = None,
                    donate: bool = True):
    """Build the jitted fused-segment program: ``fn(state, base_vec) ->
    (state, trace)`` over ``mesh``, with the state pytree DONATED
    end-to-end and the trace replicated. ``advance(state, steps, idx)``
    and ``probe_view(state) -> {name: padded array}`` run per shard;
    ``probe_extra(state) -> {name: scalar}`` appends model-owned
    in-graph probe columns (see :class:`CarryContract`)."""
    import jax
    from jax.sharding import PartitionSpec as P

    chunks = [int(c) for c in chunks]

    def shard_seg(state, vec):
        probe = health_probe(probe_view, base_vec=vec,
                             metric_names=metric_names,
                             bytes_per_step=bytes_per_step,
                             probe_extra=probe_extra)
        return fused_segment_shard(state, advance, probe, chunks,
                                   probe_every)

    sm = jax.shard_map(shard_seg, mesh=mesh,
                       in_specs=(state_specs, P()),
                       out_specs=(state_specs, P()), check_vma=False)
    return jax.jit(sm, donate_argnums=0 if donate else ())


def make_carry_segment_fn(mesh, contract: CarryContract, advance,
                          chunks: Sequence[int], probe_every: int = 1,
                          metric_names: Sequence[str] = (),
                          bytes_per_step: float = 0.0):
    """:func:`make_segment_fn` driven by a :class:`CarryContract` —
    the entry every model-specific segment builder compiles through,
    so the state pytree, its PartitionSpecs, the donation set, and the
    probe extraction all come from ONE declared object."""
    return make_segment_fn(mesh, advance, contract.probe_view,
                           contract.specs, chunks,
                           probe_every=probe_every,
                           metric_names=metric_names,
                           bytes_per_step=bytes_per_step,
                           probe_extra=contract.probe_extra,
                           donate=contract.donate)


class SegmentCompiler:
    """The per-model fused-segment factory: bind a
    :class:`CarryContract` plus the model's per-shard ``advance`` and
    its host-side state plumbing ONCE, then every
    ``(check_every, probe_every, metrics)`` request compiles (and
    caches) one donated program through the same machinery —
    ``models/pic.py``, ``models/astaroth.py``, ``models/jacobi.py``
    and the generic ``DistributedDomain.make_segment`` all register
    one of these instead of hand-rolling the jit/cache/trace wiring.

    ``advance(state, c, idx)`` runs per shard and moves ``c`` steps
    (``c`` is the contract's ``stride`` for a whole group/in-kernel
    chunk, 1 for a tail step). ``state_fn()`` fetches the live carry
    pytree (its buffers are donated); ``adopt(out)`` installs the
    result back into the owning engine. ``use_metrics=False`` drops
    the telemetry metric columns from the probe rows (models whose
    sentinel decodes its OWN in-graph columns — PIC's overflow — keep
    their column layout stable regardless of the metrics argument)."""

    def __init__(self, mesh, contract: CarryContract, advance,
                 state_fn: Callable[[], Any],
                 adopt: Callable[[Any], None],
                 use_metrics: bool = True) -> None:
        self.mesh = mesh
        self.contract = contract
        self._advance = advance
        self._state_fn = state_fn
        self._adopt = adopt
        self._use_metrics = bool(use_metrics)
        self._cache: Dict = {}

    def __call__(self, check_every: int, probe_every: int = 1,
                 metrics=None) -> Segment:
        k = int(check_every)
        if k < 1:
            raise ValueError(f"check_every must be >= 1, got "
                             f"{check_every}")
        probe_every = max(int(probe_every), 1)
        if not self._use_metrics:
            metrics = None
        chunks = segment_chunks(k, self.contract.stride)
        key = (k, probe_every,
               None if metrics is None
               else float(metrics.bytes_per_step))
        fn = self._cache.get(key)
        if fn is None:
            fn = make_carry_segment_fn(
                self.mesh, self.contract, self._advance, chunks,
                probe_every=probe_every,
                metric_names=(metrics.names if metrics is not None
                              else ()),
                bytes_per_step=(metrics.bytes_per_step
                                if metrics is not None else 0.0))
            self._cache[key] = fn
        rel = probe_rel_steps(chunks, probe_every)

        def run(base_step: int) -> SegmentTrace:
            vec = metric_base_vec(metrics, base_step, mesh=self.mesh)
            out, trace = fn(self._state_fn(), vec)
            self._adopt(out)
            return SegmentTrace(trace, rel, base_step)

        return Segment(run, k, rel, fn=fn)


def make_domain_segment(dd, shard_step, check_every: int,
                        probe_every: int = 1,
                        metrics=None) -> Segment:
    """A fused segment over a realized ``DistributedDomain``'s field
    dict: ``shard_step(fields) -> fields`` (per shard, all quantities
    padded) applied ``check_every`` times with the in-graph probe over
    every registered quantity. The compiled program is cached on the
    domain, keyed by the step fn and the segment geometry."""
    from jax.sharding import PartitionSpec as P

    names = list(dd._names)
    cache = getattr(dd, "_segment_compilers", None)
    if cache is None:
        cache = {}
        dd._segment_compilers = cache
    compiler = cache.get(shard_step)
    if compiler is None:
        contract = CarryContract(
            specs={q: P("z", "y", "x") for q in names},
            probe_view=lambda fields: {q: fields[q] for q in names})

        def adopt(out):
            dd.curr = dict(out)

        compiler = SegmentCompiler(
            dd.mesh, contract,
            lambda fields, c, i: shard_step(fields),
            lambda: dict(dd.curr), adopt)
        cache[shard_step] = compiler
    return compiler(check_every, probe_every=probe_every,
                    metrics=metrics)
