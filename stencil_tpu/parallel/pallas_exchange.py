"""Pallas remote-DMA halo exchange: the manual-transport data plane.

The true analog of the reference's hand-built transports: where
``exchange.py`` lets XLA lower ``lax.ppermute`` into ICI collectives,
this module issues explicit inter-chip RDMA — each shard writes its
boundary slabs *directly into its neighbors' halo memory* over the ICI
torus, the TPU equivalent of the reference's direct-write colocated
senders (reference: include/stencil/tx_colocated.cuh:30-76
ColoHaloSender — IPC-shared destination allocations written by a
translate kernel, then event+notify). The semaphore choreography
replaces the reference's IPC-event + MPI-notify rendezvous
(reference: src/tx_ipc.cpp:20-105):

* a neighbor barrier (signal left+right, wait 2) guarantees the
  destination buffers are quiescent before any remote write — the
  "you may write" rendezvous;
* per-direction DMA send/recv semaphore pairs replace the IPC event:
  ``wait()`` on the descriptor blocks until both our outgoing slab has
  left and the incoming slab has landed.

Each axis sweep moves full cross-section slabs (other-dim halos
included), so edge/corner data propagates across sweeps exactly as in
the ppermute engine. Off-TPU the kernels run under the Pallas TPU
interpreter, which emulates inter-device DMA on the host mesh — the
analog of the reference exercising IPC transports on one node.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..geometry import Dim3, Radius
from .exchange import AXIS_NAME, AXIS_TO_DIM, exchange_shard

# collective_id namespace for this module's barrier semaphores; one id
# per grid axis so interleaved per-axis kernels never alias barriers
_COLLECTIVE_ID_BASE = 11


def _axis_slice(ndim: int, dim: int, lo: int, hi: int) -> Tuple:
    idx = [slice(None)] * ndim
    idx[dim] = slice(lo, hi)
    return tuple(idx)


def _interpret_mode():
    from ..ops.pallas_stencil import on_tpu
    return False if on_tpu() else pltpu.InterpretParams()


def _exchange_axis_pallas(arr: jnp.ndarray, axis: int, r_lo: int, r_hi: int,
                          n_dev: int, interpret) -> jnp.ndarray:
    """One axis sweep: remote-write both boundary slabs into the
    periodic neighbors' halo regions."""
    dim = AXIS_TO_DIM[axis]
    name = AXIS_NAME[axis]
    alloc = arr.shape[dim]
    interior = alloc - r_lo - r_hi
    nd = arr.ndim

    def kern(in_ref, out_ref, send_sem, recv_sem):
        nd32 = jnp.int32(n_dev)
        my = lax.axis_index(name)
        right = lax.rem(my + jnp.int32(1), nd32)
        left = lax.rem(my + nd32 - jnp.int32(1), nd32)

        # rendezvous: both neighbors must have entered this kernel
        # (their buffers quiescent) before we write into them
        bsem = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(bsem, inc=1, device_id={name: left})
        pltpu.semaphore_signal(bsem, inc=1, device_id={name: right})
        pltpu.semaphore_wait(bsem, 2)

        copies = []
        if r_lo > 0:
            # right neighbor's lo halo [0, r_lo) <- our interior hi edge
            copies.append(pltpu.make_async_remote_copy(
                src_ref=out_ref.at[_axis_slice(nd, dim, r_lo + interior - r_lo,
                                               r_lo + interior)],
                dst_ref=out_ref.at[_axis_slice(nd, dim, 0, r_lo)],
                send_sem=send_sem.at[0],
                recv_sem=recv_sem.at[0],
                device_id={name: right},
            ))
        if r_hi > 0:
            # left neighbor's hi halo [r_lo+interior, alloc) <- our
            # interior lo edge
            copies.append(pltpu.make_async_remote_copy(
                src_ref=out_ref.at[_axis_slice(nd, dim, r_lo, r_lo + r_hi)],
                dst_ref=out_ref.at[_axis_slice(nd, dim, r_lo + interior,
                                               alloc)],
                send_sem=send_sem.at[1],
                recv_sem=recv_sem.at[1],
                device_id={name: left},
            ))
        for c in copies:
            c.start()
        for c in copies:
            c.wait()

    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(arr.shape, arr.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA((2,))],
        input_output_aliases={0: 0},
        compiler_params=pltpu.CompilerParams(
            collective_id=_COLLECTIVE_ID_BASE + axis, has_side_effects=True),
        interpret=interpret,
    )(arr)


def exchange_shard_pallas(arr: jnp.ndarray, radius: Radius,
                          mesh_counts: Dim3,
                          axis_order: Tuple[int, ...] = (0, 1, 2),
                          interpret: Optional[object] = None) -> jnp.ndarray:
    """Fill all halos of one padded (z,y,x) shard with explicit ICI RDMA.
    Same contract as ``exchange.exchange_shard``: call inside
    ``shard_map`` over mesh axes ('x','y','z')."""
    if interpret is None:
        interpret = _interpret_mode()
    for a in axis_order:
        r_lo = radius.face(a, -1)
        r_hi = radius.face(a, 1)
        if r_lo == 0 and r_hi == 0:
            continue
        n_dev = mesh_counts[a]
        if n_dev == 1:
            # periodic self-neighbor: a local slab copy, no DMA
            # (the same-GPU PeerAccessSender analog, tx_cuda.cuh:41-113)
            from .exchange import _single_axis_radius
            arr = exchange_shard(arr, _single_axis_radius(radius, a),
                                 mesh_counts, axis_order=(a,))
            continue
        arr = _exchange_axis_pallas(arr, a, r_lo, r_hi, n_dev, interpret)
    return arr
