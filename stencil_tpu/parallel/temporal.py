"""Communication-avoiding temporal blocking: one deep exchange feeds
``s`` fused stencil steps.

The per-step halo exchange is the wire cost the rest of this library
works to hide; this module stops paying it every step. One exchange
ships a depth-``s*r`` halo (``geometry.Radius.deepened``), then ``s``
stencil applications run locally on a *shrinking valid region*: each
sub-step consumes one base-radius ring, so sub-step ``k`` computes the
window ``interior + (s-1-k)*r`` and the final sub-step lands exactly on
the interior. Halo-ring cells are recomputed redundantly — the same
values their owner shard computes, so ``s``-blocked stepping is
numerically identical to step-by-step stepping (the classic
communication-avoiding trade: ``s``x fewer exchange rounds for a thin
ring of redundant compute and deeper slabs; compare the reference's
single-depth per-step exchange, src/stencil.cu:1002-1186).

Geometry (per axis, padded array coords; ``p = alloc_steps * r`` pads):

    [0 ......... p | interior capacity C | p ......... alloc)
    sub-step k window:  [p - m*r_lo,  p + C + m*r_hi),  m = s-1-k

Uneven (+-1 remainder) shards keep STATIC capacity-based windows: a
short shard's window reads at most one slack row of garbage at the top,
which only ever contaminates cells *beyond* the validity the next
sub-step requires (the same induction that makes the base exchange's
dead-row placement sound) — so one program serves every shard.

Overlap composition: with ``overlap=True`` the first sub-step splits
into the deep-interior block (computed from PRE-exchange owned data, so
XLA schedules it against the in-flight deep ppermutes — the
``parallel/overlap.py`` trick at temporal depth) plus thin shells of
thickness ``s*r`` computed from the exchanged fields.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
from jax import lax

from ..geometry import DepthsLike, Dim3, Radius, normalize_depths
from .exchange import dispatch_exchange
from .methods import Method

ZERO = Dim3(0, 0, 0)

# a temporal update function: (padded blocks per field, window interior
# dims (x,y,z as Dim3), window offset (x,y,z) in shard-interior coords
# — negative for halo-ring cells recomputed redundantly — and the
# sub-step index k) -> dict of window-shaped outputs for the fields it
# advances. The callee owns per-sub-step sources and boundary values:
# ring cells must receive exactly what their owner shard computes
# (wrap global coords in periodic mode; zero outside the domain in
# Boundary.NONE mode).
TemporalUpdateFn = Callable[[Dict[str, jnp.ndarray], Dim3,
                             Tuple[int, int, int], int],
                            Dict[str, jnp.ndarray]]


def validate_temporal(radius: Radius, local: Dim3, steps: DepthsLike,
                      rem: Dim3 = ZERO) -> None:
    """Feasibility of ``steps``-deep blocking on ``local``-capacity
    shards: every shard's ACTUAL interior must supply the deep slab the
    exchange ships from it (``s_a * r`` rows per side, per axis)."""
    depths = normalize_depths(steps)
    for a in range(3):
        min_interior = local[a] - (1 if rem[a] else 0)
        need = depths[a] * max(radius.face(a, -1), radius.face(a, 1))
        if need and min_interior < need:
            raise ValueError(
                f"temporal blocking depth {depths[a]} needs interior >= "
                f"{need} along axis {'xyz'[a]}, but the smallest shard "
                f"has {min_interior} (grow the grid or lower "
                f"exchange_every)")


def sub_step_windows(radius: Radius, capacity: Dim3, steps: DepthsLike
                     ) -> List[Tuple[Dim3, Dim3]]:
    """The shrinking-window schedule in shard-interior coords: for each
    sub-step ``k`` the (offset, dims) of the region it computes —
    offset components are ``-m_a * r_lo``, dims
    ``capacity + m_a * (r_lo + r_hi)`` with the per-axis extension
    ``m_a(k) = s_a - 1 - (k mod s_a)`` (negative offsets = halo ring).
    With uniform depths ``m = s - 1 - k``; sub-step ``max(s) - 1``
    lands exactly on ``((0,0,0), capacity)``. Per-axis depths saw-tooth:
    each axis's window re-extends right after its own mid-group
    exchange refreshes it (see :func:`temporal_shard_steps`)."""
    depths = normalize_depths(steps)
    out = []
    lo, hi = radius.pad_lo(), radius.pad_hi()
    for k in range(max(depths)):
        m = Dim3(depths.x - 1 - (k % depths.x),
                 depths.y - 1 - (k % depths.y),
                 depths.z - 1 - (k % depths.z))
        off = Dim3(-m.x * lo.x, -m.y * lo.y, -m.z * lo.z)
        dims = Dim3(capacity.x + m.x * (lo.x + hi.x),
                    capacity.y + m.y * (lo.y + hi.y),
                    capacity.z + m.z * (lo.z + hi.z))
        out.append((off, dims))
    return out


def refresh_axes(depths: DepthsLike, k: int) -> List[int]:
    """The axes whose halo an asymmetric group exchanges at sub-step
    ``k``: axis ``a`` is refreshed when ``k % s_a == 0`` (sub-step 0 is
    the full multi-axis exchange; shallow axes re-exchange mid-group
    while deep axes coast on their ring). Uniform depths refresh every
    axis at ``k == 0`` only."""
    depths = normalize_depths(depths)
    return [a for a in range(3) if k % depths[a] == 0]


def _axes_wire_radius(radius: Radius, depths: Dim3,
                      axes: Sequence[int]) -> Radius:
    """Wire radius for a mid-group refresh of ``axes`` only: those
    axes' faces deepen to ``s_a * r``; every other direction is zero,
    so the sequential-sweep engine skips the coasting axes entirely."""
    out = Radius.constant(0)
    for a in axes:
        for side in (-1, 1):
            d = [0, 0, 0]
            d[a] = side
            out.set_dir(tuple(d), depths[a] * radius.face(a, side))
    return out


def _region_blocks(fields: Dict[str, jnp.ndarray], p_lo: Dim3,
                   r_lo: Dim3, r_hi: Dim3, off: Dim3, dims: Dim3
                   ) -> Dict[str, jnp.ndarray]:
    """Slice every field's stencil-read block for the region at
    interior-coords ``off``: padded coords
    ``[p_lo + off - r_lo, p_lo + off + dims + r_hi)``."""
    z0 = p_lo.z + off.z - r_lo.z
    y0 = p_lo.y + off.y - r_lo.y
    x0 = p_lo.x + off.x - r_lo.x
    return {q: lax.slice(
        p, (z0, y0, x0),
        (z0 + r_lo.z + dims.z + r_hi.z,
         y0 + r_lo.y + dims.y + r_hi.y,
         x0 + r_lo.x + dims.x + r_hi.x))
        for q, p in fields.items()}


def _write_region(fields: Dict[str, jnp.ndarray], p_lo: Dim3, off: Dim3,
                  outs: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    fields = dict(fields)
    for q, val in outs.items():
        fields[q] = lax.dynamic_update_slice(
            fields[q], val,
            (p_lo.z + off.z, p_lo.y + off.y, p_lo.x + off.x))
    return fields


def temporal_shard_steps(fields: Dict[str, jnp.ndarray], radius: Radius,
                         mesh_counts: Dim3, method: Method,
                         update_fn: TemporalUpdateFn, steps: DepthsLike,
                         alloc_steps: Optional[DepthsLike] = None,
                         rem: Dim3 = ZERO,
                         exchange_keys: Optional[Sequence[str]] = None,
                         overlap: bool = False,
                         nonperiodic: bool = False,
                         wire_format=None,
                         wire_layout=None
                         ) -> Dict[str, jnp.ndarray]:
    """One ``steps``-deep blocked group on one shard: a single
    depth-``steps*r`` exchange, then ``steps`` applications of
    ``update_fn`` on the shrinking windows. Must be traced inside
    ``shard_map`` (the ``dispatch_exchange`` contract).

    ``fields``: padded (z,y,x) blocks, allocation pads
    ``alloc_steps * r`` per side (``alloc_steps`` defaults to
    ``steps``; a larger allocation lets tail groups of smaller depth
    run on the same buffers — the exchange then places ``steps*r``
    slabs immediately around the interior).
    ``exchange_keys``: the subset of fields the deep exchange carries
    (default: all). Fields outside it still window-cycle — their ring
    values come from earlier sub-steps' writes, never from the wire
    (e.g. an RK accumulator the group's first sub-step does not read).
    ``overlap``: split sub-step 0 into the pre-exchange deep-interior
    block plus post-exchange shells so the deep exchange hides behind
    compute (even shards only).
    ``wire_format``/``wire_layout``: the deep exchange's halo wire
    format and message layout (see ``parallel.exchange``) — the
    irredundant layout's win is largest here, where slab
    cross-sections grow with ``steps`` but the wire shell does not.

    Per-axis ``steps`` (e.g. ``{"z": 4, "y": 1, "x": 1}`` — deep
    blocking across a DCN axis, per-step exchange on ICI): the group
    runs ``max(steps)`` sub-steps; axis ``a`` is exchanged at depth
    ``s_a * r`` on every sub-step ``k`` with ``k % s_a == 0``
    (:func:`refresh_axes` — sub-step 0 is the full multi-axis
    exchange, mid-group refreshes carry only the shallow axes' faces).
    Each axis's window component saw-tooths with its own
    ``m_a(k) = s_a - 1 - (k mod s_a)``; the slab cross-sections span
    the full padded extents, so a refresh forwards the neighbor's
    coasting-axis ring rows exactly as deep as the next window reads
    (the same SPMD-symmetric induction that makes dead-row placement
    sound). Non-uniform depths decline ``overlap`` and the
    ``"irredundant"`` wire layout loudly — both assume one group-wide
    exchange.
    """
    depths = normalize_depths(steps)
    alloc_d = depths if alloc_steps is None else normalize_depths(alloc_steps)
    if any(not 1 <= depths[a] <= alloc_d[a] for a in range(3)):
        raise ValueError(f"steps={depths} outside [1, {alloc_d}]")
    steps = max(depths)
    uniform = depths.x == depths.y == depths.z
    if overlap and rem != ZERO:
        raise NotImplementedError(
            "overlap composition requires evenly divisible shards")
    if not uniform:
        if overlap:
            raise NotImplementedError(
                f"asymmetric temporal depths {tuple(depths)} decline "
                f"the overlap composition: the sub-step-0 shell split "
                f"assumes one group-wide exchange, not mid-group "
                f"refreshes")
        from .packing import normalize_wire_layout
        if normalize_wire_layout(wire_layout) != "slab":
            raise NotImplementedError(
                f"asymmetric temporal depths {tuple(depths)} decline "
                f"wire_layout {wire_layout!r}: the irredundant "
                f"dedup plan assumes one group-wide exchange whose "
                f"slabs carry the halo-of-halo rows mid-group "
                f"refreshes rely on")
    wire = radius.deepened(depths)
    alloc_r = radius.deepened(alloc_d)
    p_lo, p_hi = alloc_r.pad_lo(), alloc_r.pad_hi()
    r_lo, r_hi = radius.pad_lo(), radius.pad_hi()
    any_p = next(iter(fields.values()))
    cap = Dim3(any_p.shape[2] - p_lo.x - p_hi.x,
               any_p.shape[1] - p_lo.y - p_hi.y,
               any_p.shape[0] - p_lo.z - p_hi.z)
    validate_temporal(radius, cap, depths, rem)

    keys = sorted(fields) if exchange_keys is None else list(exchange_keys)
    pre = dict(fields)
    exchanged = dispatch_exchange({q: fields[q] for q in keys}, wire,
                                  mesh_counts, method, rem=rem,
                                  alloc_radius=alloc_r,
                                  nonperiodic=nonperiodic,
                                  wire_format=wire_format,
                                  wire_layout=wire_layout)
    out = dict(fields)
    out.update(exchanged)

    windows = sub_step_windows(radius, cap, depths)
    k0 = 0
    inner_dims = cap - r_lo - r_hi
    if overlap and not inner_dims.any_lt(1):
        # sub-step 0 as inner + shells: the inner block reads only
        # pre-exchange owned points, so it carries no data dependence
        # on the deep ppermutes and XLA may run it while slabs fly
        w_off, w_dims = windows[0]
        regions = [(Dim3(r_lo.x, r_lo.y, r_lo.z), inner_dims, pre)]
        for a in range(3):
            for side in (-1, 1):
                t = steps * radius.face(a, side)
                if t == 0:
                    continue
                off = [w_off.x, w_off.y, w_off.z]
                dims = [w_dims.x, w_dims.y, w_dims.z]
                if side == -1:
                    dims[a] = t
                else:
                    off[a] = cap[a] - r_hi[a]
                    dims[a] = t
                regions.append((Dim3(*off), Dim3(*dims), out))
        pieces = []
        for off, dims, src in regions:
            blocks = _region_blocks(src, p_lo, r_lo, r_hi, off, dims)
            pieces.append((off, update_fn(blocks, dims,
                                          (off.x, off.y, off.z), 0)))
        for off, outs in pieces:
            out = _write_region(out, p_lo, off, outs)
        k0 = 1

    for k in range(k0, steps):
        if k > 0 and not uniform:
            # mid-group refresh: the shallow axes re-exchange at their
            # own depth while deep axes coast on their remaining ring
            axes = [a for a in refresh_axes(depths, k)
                    if radius.wire_rows(a)]
            if axes:
                mid = _axes_wire_radius(radius, depths, axes)
                refreshed = dispatch_exchange(
                    {q: out[q] for q in keys}, mid, mesh_counts, method,
                    rem=rem, alloc_radius=alloc_r,
                    nonperiodic=nonperiodic, wire_format=wire_format,
                    wire_layout=wire_layout)
                out.update(refreshed)
        off, dims = windows[k]
        blocks = _region_blocks(out, p_lo, r_lo, r_hi, off, dims)
        outs = update_fn(blocks, dims, (off.x, off.y, off.z), k)
        out = _write_region(out, p_lo, off, outs)
    return out
