"""Exchange-strategy flags.

The analog of the reference's Method bitflags
(reference: include/stencil/method.hpp:5-16), which select per-pair
transports (CudaMpi, ColoPackMemcpyUnpack, CudaMemcpyPeer, CudaKernel,
...). On TPU there is no rank/IPC/MPI distinction — XLA SPMD owns the
wire — so the strategies select *how the halo data rides the ICI*:

* ``PpermuteSlab``  — one ``lax.ppermute`` per axis-direction per
  quantity (the default; XLA may combine collectives).
* ``PpermutePacked`` — all quantities packed into one buffer per
  axis-direction, one ``ppermute`` each (the DevicePacker analog,
  reference: src/packer.cu:10-44).
* ``PallasDMA``     — Pallas ``make_async_remote_copy`` ring DMA
  (the manual-transport analog; enables true comm/compute overlap).
* ``AllGather``     — per-axis ``all_gather`` then slice (control
  strategy for benchmarking, like the reference's method sweeps).
* ``Auto``          — no transport at all: a request that the exchange
  autotuner (:mod:`stencil_tpu.tuning`) measure the machine and pick
  the fastest runnable configuration — the analog of the reference's
  measured per-pair transport routing (src/stencil.cu:371-458) and of
  TEMPI's transparent measured-faster substitution.
"""

from __future__ import annotations

import enum
from typing import Callable, Optional, Set, Tuple


class Method(enum.Flag):
    """Bitmask of allowed exchange strategies
    (reference: include/stencil/method.hpp:5-16 to_string at :31-74)."""

    NONE = 0
    PpermuteSlab = 1
    PpermutePacked = 2
    PallasDMA = 4
    AllGather = 8
    Auto = 16
    Default = PpermuteSlab

    def __str__(self) -> str:  # reference: method.hpp to_string
        names = ["PpermuteSlab", "PpermutePacked", "PallasDMA",
                 "AllGather", "Auto"]
        parts = [n for n in names if Method[n] in self]
        return "|".join(parts) if parts else "none"


#: transport flags in routing-priority order (Auto is not a transport)
METHOD_PRIORITY: Tuple["Method", ...] = (
    Method.PallasDMA, Method.PpermutePacked, Method.PpermuteSlab,
    Method.AllGather)


#: strategies whose data plane supports narrow halo wire formats
#: (wire_format="bf16"): the slab/packed ppermute engines convert at
#: the send boundary and widen on arrival; the RDMA and all-gather
#: paths ship raw storage bytes
WIRE_CAPABLE: Tuple["Method", ...] = (Method.PpermuteSlab,
                                      Method.PpermutePacked)


def method_supports_wire_format(m: "Method") -> bool:
    """Can this strategy carry a NARROWING halo wire format?"""
    return m in WIRE_CAPABLE


def method_runnable(m: "Method") -> bool:
    """Can this strategy actually EXECUTE in this process? Every
    XLA-collective strategy runs anywhere; PallasDMA (explicit
    inter-chip RDMA) needs a TPU backend or the distributed (mosaic)
    interpreter — the ``_compat`` capability probe. Trace-only uses
    (the static analyzers) bypass this and call the engines directly."""
    if m == Method.PallasDMA:
        from .._compat import remote_dma_runnable
        return remote_dma_runnable()
    return True


# (requested, fallback) pairs already warned about — the orchestrator
# consults pick_method several times per realize(); warn once per fact
_warned: Set[Tuple[int, int]] = set()


def pick_method(methods: "Method",
                runnable: Optional[Callable[["Method"], bool]] = None
                ) -> "Method":
    """Choose the single strategy the exchange will use this run, by
    priority (the analog of the reference's per-pair transport routing,
    src/stencil.cu:371-458 — on TPU every pair rides the same ICI, so
    one strategy is picked globally).

    PallasDMA (explicit inter-chip RDMA, parallel/pallas_exchange.py)
    wins when requested — it is the opt-in manual-transport path, like
    the reference's direct-write Colo* methods. The pick is
    capability-aware: a requested strategy the current backend cannot
    RUN (``method_runnable``, e.g. PallasDMA off-TPU without the
    distributed interpreter) is skipped with a logged warning in favor
    of the next runnable requested strategy, or ``Method.Default`` when
    nothing requested is runnable — selecting an unrunnable transport
    would only defer the failure into the jitted program.

    ``runnable``: injectable capability predicate (tests exercise both
    branches without a TPU); defaults to :func:`method_runnable`.
    """
    if runnable is None:
        runnable = method_runnable
    requested = [m for m in METHOD_PRIORITY if m in methods]
    if not requested:
        if Method.Auto in methods:
            raise ValueError(
                "Method.Auto carries no transport — resolve it first "
                "via DistributedDomain.autotune()/realize() (the "
                "autotuner replaces Auto with the measured winner)")
        raise ValueError(f"no usable method in {methods}")
    skipped = []
    for m in requested:
        if runnable(m):
            if skipped:
                _warn_fallback(skipped, m)
            return m
        skipped.append(m)
    fallback = Method.Default
    _warn_fallback(skipped, fallback)
    return fallback


def _warn_fallback(skipped, chosen: "Method") -> None:
    from ..utils.logging import LOG_WARN

    key = (sum(m.value for m in skipped), chosen.value)
    if key in _warned:
        return
    _warned.add(key)
    names = "|".join(m.name or "?" for m in skipped)
    LOG_WARN(f"requested exchange method(s) {names} cannot run on this "
             f"backend (capability probe); falling back to {chosen}")
