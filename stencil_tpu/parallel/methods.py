"""Exchange-strategy flags.

The analog of the reference's Method bitflags
(reference: include/stencil/method.hpp:5-16), which select per-pair
transports (CudaMpi, ColoPackMemcpyUnpack, CudaMemcpyPeer, CudaKernel,
...). On TPU there is no rank/IPC/MPI distinction — XLA SPMD owns the
wire — so the strategies select *how the halo data rides the ICI*:

* ``PpermuteSlab``  — one ``lax.ppermute`` per axis-direction per
  quantity (the default; XLA may combine collectives).
* ``PpermutePacked`` — all quantities packed into one buffer per
  axis-direction, one ``ppermute`` each (the DevicePacker analog,
  reference: src/packer.cu:10-44).
* ``PallasDMA``     — Pallas ``make_async_remote_copy`` ring DMA
  (the manual-transport analog; enables true comm/compute overlap).
* ``AllGather``     — per-axis ``all_gather`` then slice (control
  strategy for benchmarking, like the reference's method sweeps).
"""

from __future__ import annotations

import enum


class Method(enum.Flag):
    """Bitmask of allowed exchange strategies
    (reference: include/stencil/method.hpp:5-16 to_string at :31-74)."""

    NONE = 0
    PpermuteSlab = 1
    PpermutePacked = 2
    PallasDMA = 4
    AllGather = 8
    Default = PpermuteSlab

    def __str__(self) -> str:  # reference: method.hpp to_string
        names = ["PpermuteSlab", "PpermutePacked", "PallasDMA", "AllGather"]
        parts = [n for n in names if Method[n] in self]
        return "|".join(parts) if parts else "none"


def pick_method(methods: "Method") -> "Method":
    """Choose the single strategy the exchange will use this run, by
    priority (the analog of the reference's per-pair transport routing,
    src/stencil.cu:371-458 — on TPU every pair rides the same ICI, so
    one strategy is picked globally).

    PallasDMA (explicit inter-chip RDMA, parallel/pallas_exchange.py)
    wins when requested — it is the opt-in manual-transport path, like
    the reference's direct-write Colo* methods.
    """
    for m in (Method.PallasDMA, Method.PpermutePacked, Method.PpermuteSlab,
              Method.AllGather):
        if m in methods:
            return m
    raise ValueError(f"no usable method in {methods}")
