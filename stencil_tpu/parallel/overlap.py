"""Comm/compute overlap: interior/exterior split inside one program.

The reference overlaps the halo exchange with stencil compute by
launching interior kernels, running ``exchange()``, then launching
exterior kernels per region (reference: bin/jacobi3d.cu:296-377,
src/stencil.cu:874-977 get_interior/get_exterior). The TPU analog keeps
the split *inside one XLA program*: the deep-interior update is
expressed on the **pre-exchange** shard (it reads only owned points),
so it carries no data dependence on the ppermute/RDMA ops and XLA's
latency-hiding scheduler is free to run it while halo slabs are in
flight; the thin exterior shells are computed from the exchanged shard
afterwards.

Region decomposition (per mesh shard, interior coordinates):

* inner block: points at least ``radius`` away from every face —
  ``[r_lo_a, n_a - r_hi_a)`` per axis;
* 6 face slabs of thickness ``r_lo``/``r_hi`` spanning the full cross
  section. Slabs overlap at edges/corners; overlapped points are
  computed twice with identical values (cheap: the shells are thin),
  which keeps every region shape static — the analog trade-off to the
  reference's non-overlapping but 26-piece decomposition.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

import jax.numpy as jnp
from jax import lax

from ..geometry import Dim3, Radius
from .methods import Method
from .exchange import dispatch_exchange

# an update function: (padded blocks per field, interior dims of this
# region, region offset (x, y, z) in shard-interior coords) -> dict of
# interior-shaped outputs for this region (any keys, e.g. field updates
# plus auxiliary accumulators)
UpdateFn = Callable[[Dict[str, jnp.ndarray], Dim3, Tuple[int, int, int]],
                    Dict[str, jnp.ndarray]]


def split_regions(radius: Radius, local: Dim3
                  ) -> Tuple[List[Tuple[Dim3, Dim3]], List[Tuple[Dim3, Dim3]]]:
    """(inner, exterior) region lists of (offset, dims) in interior
    coords (the get_interior/get_exterior analog, src/stencil.cu:874-977).
    Inner is empty when the shard is too thin to have one."""
    lo = radius.pad_lo()
    hi = radius.pad_hi()
    inner_dims = local - lo - hi
    if inner_dims.any_lt(1):
        return [], [(Dim3(0, 0, 0), local)]
    inner = [(Dim3(lo.x, lo.y, lo.z), inner_dims)]
    ext: List[Tuple[Dim3, Dim3]] = []
    for a in range(3):
        for side in (-1, 1):
            r = radius.face(a, side)
            if r == 0:
                continue
            off = [0, 0, 0]
            dims = [local.x, local.y, local.z]
            if side == -1:
                dims[a] = r
            else:
                off[a] = local[a] - r
                dims[a] = r
            ext.append((Dim3(*off), Dim3(*dims)))
    return inner, ext


def _region_blocks(fields: Dict[str, jnp.ndarray], radius: Radius,
                   off: Dim3, dims: Dim3) -> Dict[str, jnp.ndarray]:
    """Padded block covering region [off, off+dims) plus its stencil
    reads: padded coords [off, lo + off + dims + hi)."""
    lo = radius.pad_lo()
    hi = radius.pad_hi()
    out = {}
    for q, p in fields.items():
        out[q] = lax.slice(
            p,
            (off.z, off.y, off.x),
            (lo.z + off.z + dims.z + hi.z,
             lo.y + off.y + dims.y + hi.y,
             lo.x + off.x + dims.x + hi.x))
    return out


def overlapped_update(fields: Dict[str, jnp.ndarray], radius: Radius,
                      mesh_counts: Dim3, method: Method,
                      update_fn: UpdateFn,
                      nonperiodic: bool = False
                      ) -> Tuple[Dict[str, jnp.ndarray],
                                 Dict[str, jnp.ndarray]]:
    """Run ``update_fn`` over the interior/exterior decomposition with
    the halo exchange overlapping the inner block's compute.

    Returns ``(exchanged_fields, assembled)`` where ``assembled`` maps
    each key produced by ``update_fn`` to a full interior-shaped array.
    Must be traced inside ``shard_map`` (same contract as
    ``dispatch_exchange``).
    """
    lo = radius.pad_lo()
    hi = radius.pad_hi()
    any_p = next(iter(fields.values()))
    local = Dim3(any_p.shape[2] - lo.x - hi.x,
                 any_p.shape[1] - lo.y - hi.y,
                 any_p.shape[0] - lo.z - hi.z)
    inner, ext = split_regions(radius, local)

    # exchange starts here; inner compute below reads only pre-exchange
    # owned data, so XLA may overlap the two
    fields_ex = dispatch_exchange(fields, radius, mesh_counts, method,
                                  nonperiodic=nonperiodic)

    pieces: List[Tuple[Dim3, Dim3, Dict[str, jnp.ndarray]]] = []
    for off, dims in inner:
        blocks = _region_blocks(fields, radius, off, dims)
        pieces.append((off, dims,
                       update_fn(blocks, dims, (off.x, off.y, off.z))))
    for off, dims in ext:
        blocks = _region_blocks(fields_ex, radius, off, dims)
        pieces.append((off, dims,
                       update_fn(blocks, dims, (off.x, off.y, off.z))))

    assembled: Dict[str, jnp.ndarray] = {}
    for off, dims, outs in pieces:
        for key, val in outs.items():
            if key not in assembled:
                assembled[key] = jnp.zeros(
                    (local.z, local.y, local.x), dtype=val.dtype)
            assembled[key] = lax.dynamic_update_slice(
                assembled[key], val, (off.z, off.y, off.x))
    return fields_ex, assembled
