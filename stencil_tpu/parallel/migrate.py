"""Fixed-capacity particle migration: the second communication pattern.

Every exchange this framework performed until now was the static
26-direction halo sweep — the payload (which slab goes where) is known
at trace time. Particle-in-cell codes layered on halo frameworks
(PIConGPU, arXiv:1606.02862; POLAR-PIC, arXiv:2604.19337) add a
*dynamic, data-dependent* exchange: which particles cross which shard
boundary is decided by the physics at runtime. This module implements
that pattern so it still compiles to ONE static XLA program and lowers
to collective-permute only (proven by the ``parallel.migrate.*``
stencil-lint registry targets):

* per-shard particle state is SoA: a dict of same-dtype ``(capacity,)``
  arrays plus a ``(capacity,)`` validity mask — static shapes, dead
  slots masked;
* destinations are per-axis offsets in {-1, 0, +1} (a particle moves at
  most one shard per step — the standard PIC CFL-style contract);
  the 26 neighbor directions collapse into THREE sequential axis hops
  exactly like the halo sweep: a corner-bound particle hops x, then y
  on the intermediate shard, then z, its remaining offsets riding along
  in the wire record;
* per axis-direction, leavers are *sorted to the front* (a stable
  argsort over the leave mask), *padded to a static ``budget``* of
  record slots, packed into one ``(rows, budget)`` buffer and moved
  with ONE ``lax.ppermute`` per direction — at most 6 collectives per
  migration, mirroring the halo sweep's bill;
* arrivals are scattered into free slots (stable argsort over the
  validity mask); leavers beyond ``budget`` and arrivals beyond the
  free capacity are DROPPED and counted by the in-graph **overflow
  counter**, which rides the health probe's existing single all-reduce
  as an extra column (``models/pic.py``) — operators see lost
  particles without any added collective.

The wire record is ``n_fields + RECORD_EXTRA_ROWS`` rows of the field
dtype per particle slot: the SoA fields plus the packed control
row(s) — ``RECORD_EXTRA_ROWS`` is the single constant both this
packer and the byte model
(``analysis/costmodel.migration_record_rows``) derive from, so the
prose can never go stale against the code. Today that is ONE row: the
three remaining {-1, 0, +1} offset components and the validity flag
are base-3/flag-bit encoded into a single small integer
(``code = (ox+1) + 3*(oy+1) + 9*(oz+1) + 27*valid``, in [0, 53] —
exact in every supported float dtype, bf16 included), the canonical-
record analog of the irredundant halo layout (``parallel/packing.py``).
Modeled migration bytes are
``2 x active_axes x record_rows x budget x itemsize`` — priced by
``analysis/costmodel.migration_wire_bytes_per_shard`` and cross-checked
EXACTLY against the lowered HLO. ``capacity`` and ``budget`` are the
tuning knobs ``tuning/plan.py`` ranks (wire bytes scale with budget;
HBM with capacity; overflow risk caps how low either may go).
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax.numpy as jnp

from ..geometry import Dim3
from .exchange import AXIS_NAME, _shift_from_minus, _shift_from_plus

#: wire-record rows beyond the SoA fields. The three (remaining)
#: destination offset components and the validity flag pack into ONE
#: base-3/flag-bit coded row (see :func:`_encode_record_code`). The
#: cost model (analysis/costmodel.migration_record_rows) derives from
#: this — one constant, no drift.
RECORD_EXTRA_ROWS = 1


def migration_record_rows(n_fields: int) -> int:
    """Rows of one migration wire record: the SoA fields plus the
    packed control row(s) (see :data:`RECORD_EXTRA_ROWS`)."""
    return int(n_fields) + RECORD_EXTRA_ROWS


def _encode_record_code(comps, sent):
    """Pack three {-1, 0, +1} offset components plus the validity flag
    into one integer code in [0, 53]: ``(c0+1) + 3*(c1+1) + 9*(c2+1)
    + 27*sent``. Codes this small are exact in every supported float
    dtype (bf16's 8 mantissa bits cover integers to 256), so the code
    rides the wire as a field-dtype row."""
    code = 27 * sent.astype(jnp.int32)
    for k, c in enumerate(comps):
        code = code + (3 ** k) * (c + 1)
    return code


def _decode_record_code(row):
    """Invert :func:`_encode_record_code` on a received field-dtype
    row: returns ``(comps, valid)`` with int32 components."""
    code = jnp.round(row).astype(jnp.int32)
    valid = code >= 27
    base = jnp.where(valid, code - 27, code)
    comps = [base % 3 - 1, base // 3 % 3 - 1, base // 9 - 1]
    return comps, valid


def migrate_shard(fields: Dict[str, jnp.ndarray], valid: jnp.ndarray,
                  offsets: Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],
                  mesh_counts: Dim3, budget: int,
                  axis_order: Tuple[int, ...] = (0, 1, 2)
                  ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray,
                             jnp.ndarray]:
    """Migrate one shard's particles to their destination shards.

    ``fields``: SoA particle arrays, all ``(capacity,)`` of ONE common
    floating dtype. ``valid``: ``(capacity,)`` bool — live slots.
    ``offsets``: per-axis destination offsets ``(offx, offy, offz)``,
    integer arrays in {-1, 0, +1} (already computed by the caller from
    positions vs its shard bounds — periodic wrap is the ring's
    business, not this function's). ``budget``: static record slots per
    axis-direction message.

    Returns ``(fields, valid, overflow)`` where ``overflow`` is the
    f32 count of particles DROPPED this migration (send budget
    exceeded, or no free capacity slot on arrival). Must be traced
    inside ``shard_map``; one ppermute per direction per active axis.
    """
    names = sorted(fields)  # both endpoints agree on the record layout
    if not names:
        raise ValueError("migrate_shard needs at least one field")
    dt = fields[names[0]].dtype
    for q in names:
        if fields[q].dtype != dt:
            raise ValueError(
                f"migrate_shard fields must share one dtype: "
                f"{q!r} is {fields[q].dtype}, expected {dt}")
    capacity = fields[names[0]].shape[0]
    budget = int(budget)
    if budget < 1:
        raise ValueError(f"budget must be >= 1, got {budget}")

    work = {q: fields[q] for q in names}
    # offsets ride as working rows so an arrival's REMAINING hops
    # survive the intermediate shard (corner traffic hops per axis)
    offs = [jnp.asarray(o).astype(dt) for o in offsets]
    valid = jnp.asarray(valid).astype(bool)
    overflow = jnp.zeros((), jnp.float32)

    for a in axis_order:
        n_dev = mesh_counts[a]
        name = AXIS_NAME[a]
        off_a = offs[a]
        incoming = []
        leaving = jnp.zeros_like(valid)
        for side in (1, -1):
            leave = valid & (off_a == jnp.asarray(side, dt))
            leaving = leaving | leave
            # stable sort: leavers first, then pad to the static budget
            order = jnp.argsort(jnp.where(leave, 0, 1))
            idx = order[:budget]
            sent = leave[idx]
            overflow = overflow + jnp.maximum(
                jnp.sum(leave) - budget, 0).astype(jnp.float32)
            rows = [work[q][idx] for q in names]
            # the packed control row: this axis's offset is CONSUMED by
            # the hop (arrivals are home along it); the others ride on,
            # coded together with the validity flag in one row
            comps = [jnp.zeros((budget,), jnp.int32) if b == a
                     else jnp.clip(jnp.round(offs[b][idx]
                                             ).astype(jnp.int32), -1, 1)
                     for b in range(3)]
            rows.append(_encode_record_code(comps, sent).astype(dt))
            buf = jnp.stack(rows)  # (record_rows, budget)
            moved = (_shift_from_minus(buf, name, n_dev) if side == 1
                     else _shift_from_plus(buf, name, n_dev))
            incoming.append(moved)
        # leavers are gone (budget-overflowed ones are LOST + counted)
        valid = valid & ~leaving
        # merge both directions' arrivals into free slots
        buf = jnp.concatenate(incoming, axis=1)  # (rows, 2*budget)
        inc_fields = {q: buf[i] for i, q in enumerate(names)}
        nf = len(names)
        inc_comps, inc_valid = _decode_record_code(buf[nf])
        inc_offs = [c.astype(dt) for c in inc_comps]
        free_order = jnp.argsort(valid)  # invalid slots first, stable
        free_count = capacity - jnp.sum(valid)
        rank = jnp.cumsum(inc_valid) - 1
        ok = inc_valid & (rank < free_count)
        slot = jnp.where(
            ok, free_order[jnp.clip(rank, 0, capacity - 1)], capacity)
        overflow = overflow + (jnp.sum(inc_valid)
                               - jnp.sum(ok)).astype(jnp.float32)
        for q in names:
            work[q] = work[q].at[slot].set(inc_fields[q], mode="drop")
        for b in range(3):
            offs[b] = offs[b].at[slot].set(inc_offs[b], mode="drop")
        valid = valid.at[slot].set(True, mode="drop")
    return work, valid, overflow


def migration_messages(mesh_counts: Dim3,
                       axis_order: Sequence[int] = (0, 1, 2)) -> int:
    """Collective-permute launches one migration performs: 2 per mesh
    axis that actually crosses devices (1-device axes degenerate to
    local self-copies — no collective in the lowering)."""
    return sum(2 for a in axis_order if mesh_counts[a] > 1)
