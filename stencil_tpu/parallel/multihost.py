"""Multi-host / multi-slice (DCN) mesh tier.

The analog of the reference's two-level node/GPU hierarchy: MPI ranks
grouped by shared-memory node (reference: include/stencil/
mpi_topology.hpp:18-36 MPI_Comm_split_type) and ``NodePartition``'s
sysDim x nodeDim split (reference: partition.hpp:120-256). On TPU the
levels are ICI (intra-slice torus, fast) and DCN (inter-slice /
inter-host network, slow): one grid axis is designated the DCN axis and
sharded across slices, so per-step DCN traffic is only that axis's face
slabs while the other axes' exchanges ride the ICI — the same
"minimize inter-node communication" goal NodePartition's
interface-cost split rule encodes.

Control plane: ``initialize_distributed`` wraps
``jax.distributed.initialize`` (the MPI_Init analog); after it,
``jax.devices()`` spans all hosts and the SPMD programs built by this
package run unchanged — XLA routes per-axis collectives over ICI or DCN
according to the mesh layout chosen here.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax

from ..geometry import Dim3, Dim3Like
from .mesh import _torus_sorted, make_mesh


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> int:
    """Bring up the JAX distributed runtime (no-op when single-process
    or already initialized). Returns the process index.

    Must run before anything initializes the local XLA backend — do not
    query devices/process_count first (that would initialize a
    single-process backend and make distributed init fail)."""
    if coordinator_address is not None:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id)
        except RuntimeError as e:
            # tolerate repeat calls only; surface real init failures
            if "already" not in str(e).lower():
                raise
    return jax.process_index()


def slice_groups(devices: Optional[Sequence] = None) -> List[List]:
    """Group devices by slice (ICI domain): ``device.slice_index`` when
    exposed (multi-slice TPU), else by host process — the
    MpiTopology.colocated analog."""
    devs = list(devices) if devices is not None else list(jax.devices())
    groups: Dict[int, List] = {}
    for d in devs:
        key = getattr(d, "slice_index", None)
        if key is None:
            key = getattr(d, "process_index", 0)
        groups.setdefault(key, []).append(d)
    return [groups[k] for k in sorted(groups)]


def multihost_device_order(mesh_shape: Dim3Like, dcn_axis: int = 2,
                           devices: Optional[Sequence] = None,
                           groups: Optional[List[List]] = None) -> List:
    """Device order (subdomain linear index, x fastest) for a 3D mesh
    with ``dcn_axis`` blocked across slices/hosts: subdomains whose
    ``dcn_axis`` index falls in slice ``s``'s block are placed on slice
    ``s``'s devices, so only that axis's halo sweep crosses the DCN
    (NodePartition's two-level split, reference: partition.hpp:120-256,
    re-expressed as device order).

    ``groups`` injects an explicit device grouping (testing; otherwise
    discovered via ``slice_groups``).
    """
    shape = Dim3.of(mesh_shape)
    if groups is None:
        groups = slice_groups(devices)
    n_slices = len(groups)
    if shape[dcn_axis] % n_slices != 0:
        raise ValueError(f"mesh axis {dcn_axis} ({shape[dcn_axis]}) not "
                         f"divisible by {n_slices} slices")
    per_block = shape[dcn_axis] // n_slices
    sizes = {len(g) for g in groups}
    if len(sizes) != 1:
        raise ValueError(f"uneven slice sizes {sizes}")
    per_slice = shape.flatten() // n_slices
    if per_slice != sizes.pop():
        raise ValueError(f"mesh {shape} needs {per_slice} devices per "
                         f"slice, groups have {[len(g) for g in groups]}")
    ordered = [_torus_sorted(g) for g in groups]
    taken = [0] * n_slices
    device_list = []
    # linear subdomain order: x fastest, z slowest (make_mesh contract)
    for iz in range(shape.z):
        for iy in range(shape.y):
            for ix in range(shape.x):
                idx = (ix, iy, iz)[dcn_axis]
                g = idx // per_block
                device_list.append(ordered[g][taken[g]])
                taken[g] += 1
    return device_list


def make_multihost_mesh(mesh_shape: Dim3Like, dcn_axis: int = 2,
                        devices: Optional[Sequence] = None,
                        groups: Optional[List[List]] = None):
    """3D spatial mesh built from ``multihost_device_order`` — see
    there for the slice-blocking rule."""
    shape = Dim3.of(mesh_shape)
    return make_mesh(shape, multihost_device_order(
        shape, dcn_axis, devices=devices, groups=groups))


def dcn_bytes_per_exchange(dd, dcn_axis: int = 2) -> int:
    """Bytes per exchange crossing the DCN tier (per-shard, one axis) —
    the inter-node byte-counter analog (reference: stencil.hpp:86-93)."""
    name = "xyz"[dcn_axis]
    return dd.exchange_bytes_per_axis().get(name, 0)
