"""Device-mesh construction for the 3D spatial decomposition.

The analog of the reference's machine/topology discovery + placement
layers (reference: include/stencil/mpi_topology.hpp, gpu_topology.hpp,
partition.hpp NodeAware): instead of MPI rank sets, NVML distance
matrices and a QAP solve, a TPU slice *is* a torus — mapping mesh axes
onto the physical ICI torus coordinates (``device.coords``) makes
nearest-neighbor ppermute shifts single-hop by construction.

Mesh axis names are ``('x', 'y', 'z')`` matching the grid axes; arrays
are (z,y,x)-ordered so a padded field's PartitionSpec is
``P('z', 'y', 'x')``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..geometry import Dim3, Dim3Like

AXIS_NAMES = ("x", "y", "z")


def spec_zyx() -> P:
    """PartitionSpec for a (z,y,x)-ordered field over the 3D mesh."""
    return P("z", "y", "x")


def _torus_sorted(devices: Sequence) -> List:
    """Sort devices by their physical torus coordinates when exposed
    (TPU: ``device.coords`` is (x, y, z) on the ICI torus), so that
    adjacent mesh positions are physically adjacent and ppermute shifts
    ride single ICI hops. Falls back to id order (CPU/virtual devices).
    The analog of NodeAware placement's QAP solve
    (reference: partition.hpp:525-831) — on a torus it reduces to
    coordinate-order assignment.
    """
    devs = list(devices)
    try:
        keyed = [((d.coords[2], d.coords[1], d.coords[0],
                   getattr(d, "core_on_chip", 0)), d) for d in devs]
        keyed.sort(key=lambda t: t[0])
        return [d for _, d in keyed]
    except (AttributeError, TypeError, IndexError):
        return sorted(devs, key=lambda d: d.id)


def make_mesh(mesh_shape: Optional[Dim3Like] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a 3D ``jax.sharding.Mesh`` with axes ('x','y','z').

    ``mesh_shape`` is (mx, my, mz) subdomain counts per axis; defaults
    to a near-cubic factorization of the device count. Note the Mesh's
    internal device array is indexed [x, y, z] here; fields use
    ``spec_zyx()`` so array dims (z,y,x) map to the right axes.

    When ``devices`` is given explicitly its order IS the placement
    (subdomain linear index, x fastest) and is preserved verbatim; only
    auto-discovered devices are torus-sorted here.
    """
    if devices is None:
        devices = _torus_sorted(jax.devices())
    else:
        devices = list(devices)
    n = len(devices)
    if mesh_shape is None:
        mesh_shape = default_mesh_shape(n)
    shape = Dim3.of(mesh_shape)
    if shape.flatten() != n:
        raise ValueError(f"mesh shape {shape} needs {shape.flatten()} "
                         f"devices, have {n}")
    # device axis order (x fastest) matches _torus_sorted key order
    arr = np.array(devices, dtype=object).reshape(
        (shape.z, shape.y, shape.x)).transpose(2, 1, 0)
    return Mesh(arr, AXIS_NAMES)


def default_mesh_shape(n: int) -> Dim3:
    """Near-cubic factorization of ``n`` (prime factors round-robined
    onto axes, largest first)."""
    from ..numerics import prime_factors
    dims = [1, 1, 1]
    for f in prime_factors(n):
        if f < 2:
            continue
        dims[dims.index(min(dims))] *= f
    dims.sort(reverse=True)
    return Dim3(*dims)


def default_mesh_shape_xfree(n: int) -> Dim3:
    """Near-square (1, dy, dz) factorization of ``n`` — the x-unsharded
    decomposition the fused halo kernels want (ops/pallas_halo.py)."""
    from ..numerics import prime_factors
    dims = [1, 1]
    for f in prime_factors(n):
        if f < 2:
            continue
        dims[dims.index(min(dims))] *= f
    dims.sort(reverse=True)
    return Dim3(1, dims[1], dims[0])


def default_mesh_shape_dcn(n: int, n_slices: int, axis: int = 2,
                           xfree: bool = False) -> Dim3:
    """Mesh shape whose ``axis`` is divisible by ``n_slices`` (the
    constraint the slice-blocked DCN tier needs): the slice factor goes
    on ``axis`` and the per-slice remainder is factored near-cubic
    (or x-unsharded when ``xfree``)."""
    if n % n_slices:
        raise ValueError(f"{n} devices not divisible into {n_slices} "
                         f"slices")
    base = (default_mesh_shape_xfree(n // n_slices) if xfree
            else default_mesh_shape(n // n_slices))
    dims = [base.x, base.y, base.z]
    dims[axis] *= n_slices
    return Dim3(*dims)


def mesh_dim(mesh: Mesh) -> Dim3:
    """Subdomain-grid shape (x, y, z) of a 3D mesh."""
    return Dim3(mesh.shape["x"], mesh.shape["y"], mesh.shape["z"])


def field_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a (z,y,x)-ordered padded field."""
    return NamedSharding(mesh, spec_zyx())


def choose_grid_partition(global_size: Dim3Like, mesh: Mesh) -> Dim3:
    """Per-device interior size; requires the mesh to divide the grid
    exactly (XLA SPMD equal-shard constraint; the +-1 remainder scheme
    of the reference, partition.hpp:55-69, is handled by padding at a
    higher level or by choosing a divisible mesh via
    ``partition_dims_even``)."""
    gs = Dim3.of(global_size)
    md = mesh_dim(mesh)
    if gs % md != Dim3(0, 0, 0):
        raise ValueError(f"global size {gs} not divisible by mesh {md}; "
                         f"use partition_dims_even or pad the grid")
    return gs // md
