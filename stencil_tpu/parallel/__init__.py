"""Mesh construction and the halo-exchange engine.

``EXCHANGE_METHOD_TARGETS`` is the lint-coverage manifest — the
registry metadata hook the static analyzer's drift guard checks
(tests/test_lint.py): every ``methods.Method`` exchange strategy maps
to the ``analysis/registry.default_targets()`` name (prefix) covering
its data path. A new Method flag without a registered analysis target
fails the guard, so no exchange strategy ships un-audited.
"""

from __future__ import annotations

from typing import Dict

EXCHANGE_METHOD_TARGETS: Dict[str, str] = {
    "PpermuteSlab": "parallel.exchange.exchange_shard",
    "PpermutePacked": "parallel.exchange.exchange_shard_packed",
    "PallasDMA": "parallel.pallas_exchange.exchange_shard_pallas",
    "AllGather": "parallel.exchange.exchange_shard_allgather",
    # Auto is the autotuner request flag (stencil_tpu/tuning): its data
    # paths are whatever plan the tuner can emit — the registry's
    # tuning.plan[*] targets audit every emittable configuration
    "Auto": "tuning.plan",
}


def exchange_method_targets() -> Dict[str, str]:
    """The manifest, validated against the live ``Method`` enum: every
    single-bit strategy flag must have an entry (aliases like
    ``Default`` and the empty ``NONE`` excluded)."""
    from .methods import Method

    flags = {m.name for m in Method
             if m.name is not None and m.value and not (m.value & (m.value - 1))}
    missing = flags - set(EXCHANGE_METHOD_TARGETS)
    if missing:
        raise RuntimeError(
            f"exchange Method flags {sorted(missing)} have no analysis "
            f"coverage entry in EXCHANGE_METHOD_TARGETS")
    return dict(EXCHANGE_METHOD_TARGETS)
