"""The halo-exchange engine: per-axis neighbor shifts over the mesh.

This is the TPU-native replacement for the reference's entire transport
stack (reference: include/stencil/tx_cuda.cuh, tx_colocated.cuh,
tx_ipc.hpp, packer.cuh and the exchange orchestration in
src/stencil.cu:1002-1186). Where the reference plans 26 point-to-point
messages per subdomain and routes each over the fastest of 4 transports
(same-GPU kernel / cudaMemcpyPeer / IPC / MPI), the TPU design performs
**three sequential axis sweeps** of ``lax.ppermute`` shifts inside one
``shard_map``-ped XLA program:

* sweep x: exchange +-x face slabs spanning the full (y, z) allocation;
* sweep y: slabs span full (x, z) — x halos are now valid, so xy edge
  data propagates automatically;
* sweep z: slabs span full (x, y) — fills all z faces, xz/yz edges and
  corners.

26 directions collapse into at most 6 shifts, and edge/corner data
rides along for free (SURVEY.md section 7 step 3). Per-direction radii
are honored: the slab widths on each side of axis ``a`` are the *face*
radii (allocation geometry, reference local_domain.cuh raw_size), which
is exactly what the reference's messages carry (halo_extent uses face
radii — local_domain.cuh:212-222); zero-radius sides skip the shift.

Everything here operates on one shard's padded (z,y,x)-ordered block
and must run inside ``shard_map`` (or on a 1-device axis, where the
periodic neighbor is the shard itself and the shift degenerates to a
local slab copy — the analog of the reference's same-GPU
PeerAccessSender, tx_cuda.cuh:41-113).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import jax
import numpy as np
from jax import lax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..geometry import Dim3, Radius
from .methods import Method, pick_method

# grid axis index -> array dimension of a (z,y,x)-ordered field
AXIS_TO_DIM = {0: 2, 1: 1, 2: 0}
AXIS_NAME = {0: "x", 1: "y", 2: "z"}

#: halo WIRE formats: what a slab is converted to at the send boundary
#: (TEMPI's canonical-datatype pack layer, arXiv:2012.14363). "f32" is
#: the identity path — full storage precision on the wire; "bf16"
#: narrows float32 slabs to bfloat16 for the ppermute and widens on
#: arrival, so halo math runs unchanged at storage precision while
#: wire bytes exactly halve; "e4m3"/"e5m2" narrow to the fp8 dtypes
#: (quarter bytes — certificate-gated like bf16, with the coarser
#: ``max_rel_error_bound`` from their 3-/2-bit mantissas). Narrower
#: storage dtypes are never re-narrowed, and non-float lanes always
#: ride at full width.
WIRE_FORMATS = ("f32", "bf16", "e4m3", "e5m2")

#: wire format -> numpy dtype NAME of the on-wire element type for a
#: float32 lane — the single naming source the precision certifier
#: (analysis/precision.py) and the cost model both consume.
WIRE_DTYPE_NAMES = {"f32": "float32", "bf16": "bfloat16",
                    "e4m3": "float8_e4m3fn", "e5m2": "float8_e5m2"}

#: on-wire byte width of a 4-byte float32 element per wire format
_WIRE_F32_BYTES = {"f32": 4, "bf16": 2, "e4m3": 1, "e5m2": 1}


def normalize_wire_format(wire_format) -> Dict[str, str]:
    """Canonical per-axis wire-format map ``{"x"|"y"|"z": fmt}``.

    Accepts ``None`` (full precision), a single format string applied
    to every mesh axis, or a per-axis dict (missing axes default to
    "f32") — the per-link declaration surface: bf16 on the DCN axis,
    f32 on ICI."""
    if wire_format is None:
        return {"x": "f32", "y": "f32", "z": "f32"}
    if isinstance(wire_format, str):
        if wire_format not in WIRE_FORMATS:
            raise ValueError(f"unknown wire format {wire_format!r}; "
                             f"expected one of {WIRE_FORMATS}")
        return {"x": wire_format, "y": wire_format, "z": wire_format}
    out = {"x": "f32", "y": "f32", "z": "f32"}
    for k, v in dict(wire_format).items():
        if k not in out:
            raise ValueError(f"unknown mesh axis {k!r} in wire_format")
        if v not in WIRE_FORMATS:
            raise ValueError(f"unknown wire format {v!r} for axis "
                             f"{k!r}; expected one of {WIRE_FORMATS}")
        out[k] = v
    return out


def wire_dtype(dtype, fmt: str):
    """The on-wire dtype of a slab stored as ``dtype`` under wire
    format ``fmt`` — only float32 narrows (to the format's dtype,
    ``WIRE_DTYPE_NAMES``); everything else ships at storage width."""
    if fmt != "f32" and np.dtype(dtype) == np.dtype(np.float32):
        return {"bf16": jnp.bfloat16, "e4m3": jnp.float8_e4m3fn,
                "e5m2": jnp.float8_e5m2}[fmt]
    return dtype


def wire_elem_size(elem_size: int, fmt: str) -> int:
    """Byte width of one element on the wire (the cost-model twin of
    :func:`wire_dtype`): a 4-byte element ships as 2 under "bf16" and
    as 1 under the fp8 formats."""
    if int(elem_size) == 4:
        return _WIRE_F32_BYTES[fmt]
    return int(elem_size)


def _to_wire(slab, fmt: str):
    wd = wire_dtype(slab.dtype, fmt)
    return slab if wd == slab.dtype else slab.astype(wd)


def _from_wire(slab, dtype):
    return slab if slab.dtype == dtype else slab.astype(dtype)


def _axis_size(axis_name: str) -> int:
    """Size of a mesh axis from inside shard_map."""
    return lax.axis_size(axis_name)


def _shift_from_plus(block, axis_name: str, n: int):
    """Bring data from the +axis neighbor (periodic): device i receives
    from device i+1."""
    if n == 1:
        return block
    return lax.ppermute(block, axis_name, [((i + 1) % n, i) for i in range(n)])


def _shift_from_minus(block, axis_name: str, n: int):
    """Bring data from the -axis neighbor (periodic): device i receives
    from device i-1."""
    if n == 1:
        return block
    return lax.ppermute(block, axis_name, [(i, (i + 1) % n) for i in range(n)])


def shard_interior_len(axis: int, capacity: int, rem: Dim3):
    """This shard's actual interior extent along ``axis``: the +-1
    remainder rule (reference: partition.hpp:55-69) as a traced value —
    the first ``rem`` shards hold ``capacity`` points, the rest one
    fewer. Static ``capacity`` when the axis divides evenly."""
    r = rem[axis]
    if r == 0:
        return capacity
    i = lax.axis_index(AXIS_NAME[axis])
    return jnp.int32(capacity) - (i >= jnp.int32(r)).astype(jnp.int32)


def shard_origin(local: Dim3, rem: Dim3) -> Tuple:
    """Traced (ox, oy, oz) global origin of this shard's interior
    (reference: partition.hpp:71-86 _remainder_origin), valid inside
    shard_map. ``local`` is the per-shard capacity (ceil sizes)."""
    out = []
    for a in range(3):
        i = lax.axis_index(AXIS_NAME[a])
        o = i * jnp.int32(local[a])
        if rem[a] != 0:
            o = o - jnp.maximum(i - jnp.int32(rem[a]), jnp.int32(0))
        out.append(o)
    return tuple(out)


def _edge_masked(recv, side: int, axis_name: str, n_dev: int):
    """Non-periodic boundary rule: the mesh-edge shard's halo on the
    open side holds ZEROS (the zero-Dirichlet exterior), not the
    wrapped-around neighbor's data the periodic ppermute ring delivered.
    ``side`` is +1 for the hi-side halo (zeroed on the last shard) and
    -1 for the lo-side halo (zeroed on shard 0). A 1-device axis has no
    interior boundary at all: the whole halo is exterior, so it zeroes
    unconditionally."""
    if n_dev == 1:
        return jnp.zeros_like(recv)
    i = lax.axis_index(axis_name)
    edge = (i == n_dev - 1) if side == 1 else (i == 0)
    return jnp.where(edge, jnp.zeros_like(recv), recv)


def _box_starts(spans, Ls):
    """Per-array-dim (z,y,x) start indices of a DirectionPlan box —
    static ints except the two ``plus_L`` placements, which add the
    traced interior length of their grid axis. When any start is
    traced (uneven shards), ALL are cast to int32: dynamic_slice
    demands one index dtype, and an x64-enabled session would promote
    the static Python ints to int64 otherwise."""
    starts = []
    for d in range(3):
        s = spans[2 - d]  # grid axis of array dim d
        starts.append(s.base + Ls[2 - d] if s.plus_L else s.base)
    if not all(isinstance(st, (int, np.integer)) for st in starts):
        starts = [jnp.asarray(st, jnp.int32) for st in starts]
    return tuple(starts)


def _box_take(arr, spans, Ls):
    sizes = tuple(spans[2 - d].size for d in range(3))
    return lax.dynamic_slice(arr, _box_starts(spans, Ls), sizes)


def _box_put(arr, box, spans, Ls):
    return lax.dynamic_update_slice(arr, box, _box_starts(spans, Ls))


def _shard_interiors(arr, alloc_r: Radius) -> Tuple[int, int, int]:
    """Per-grid-axis interior capacity of one padded shard block."""
    return tuple(arr.shape[AXIS_TO_DIM[a]]
                 - alloc_r.face(a, -1) - alloc_r.face(a, 1)
                 for a in range(3))


def exchange_shard(arr: jnp.ndarray, radius: Radius,
                   mesh_counts: Dim3,
                   axis_order: Tuple[int, ...] = (0, 1, 2),
                   rem: Dim3 = Dim3(0, 0, 0),
                   alloc_radius: "Radius | None" = None,
                   nonperiodic: bool = False,
                   wire_format=None,
                   wire_layout=None) -> jnp.ndarray:
    """Fill all halo regions of one padded shard via sequential axis
    sweeps. Must be traced inside ``shard_map`` over mesh axes
    ('x','y','z') when the corresponding mesh_counts entry is > 1.

    ``arr``: padded (z,y,x) block; interior *capacity* along grid axis a
    is ``arr.shape[AXIS_TO_DIM[a]] - p_lo - p_hi`` where the allocation
    pads come from ``alloc_radius`` (default: ``radius``).
    ``mesh_counts``: subdomain count along each grid axis.
    ``rem``: per-axis remainder counts for uneven (+-1) subdomains
    (reference: partition.hpp:55-69). Shards allocate to the capacity;
    a short shard's halo is placed immediately after its actual
    interior (dynamic position), keeping interior+halo contiguous so
    stencil reads stay static slices. The slack row at the top of a
    short shard's allocation is dead space.

    ``alloc_radius``: when the allocation is padded deeper than this
    exchange's wire depth (temporal blocking: the buffer carries
    ``s x r`` pads but a tail step only refreshes the innermost ``r``
    ring), pass the allocation's Radius here; the slabs then ship
    ``radius`` rows placed immediately around the interior. Wire depth
    must not exceed the allocation pads on any face.
    ``nonperiodic``: zero-fill halos across the open global boundary
    (``topology.Boundary.NONE`` — zero-Dirichlet exterior).
    ``wire_format``: per-axis halo wire format (see
    :func:`normalize_wire_format`) — a narrowing axis converts the send
    slab at the wire boundary, one ppermute later widens it back to the
    storage dtype on arrival; halo math is unchanged. Single-device
    axes are local copies and always stay at full precision.
    ``wire_layout``: "slab" (default — full-allocation cross-sections)
    or "irredundant" (each wire-halo cell ships exactly once; see
    :mod:`.packing`). Same collective bill, smaller payload; the live
    window (interior + wire-radius shell) is bitwise identical.
    """
    alloc_r = alloc_radius if alloc_radius is not None else radius
    wf = normalize_wire_format(wire_format)
    from .packing import normalize_wire_layout, plan_sweep
    if normalize_wire_layout(wire_layout) == "irredundant":
        interiors = _shard_interiors(arr, alloc_r)
        plans = plan_sweep(radius, alloc_r, interiors, tuple(axis_order))
        Ls = [shard_interior_len(a, interiors[a], rem) for a in range(3)]
        for a in axis_order:
            if radius.wire_rows(a) == 0:
                continue
            assert (alloc_r.face(a, -1) >= radius.face(a, -1)
                    and alloc_r.face(a, 1) >= radius.face(a, 1)), \
                (f"axis {a}: wire depth exceeds allocation pads")
            name = AXIS_NAME[a]
            n_dev = mesh_counts[a]
            narrow = n_dev > 1 and wf[name] != "f32"
            for side, shift in ((1, _shift_from_plus),
                                (-1, _shift_from_minus)):
                plan = plans.get((a, side))
                if plan is None:
                    continue
                src = _box_take(arr, plan.src, Ls)
                if narrow:
                    src = _to_wire(src, wf[name])
                recv = _from_wire(shift(src, name, n_dev), arr.dtype)
                if nonperiodic:
                    recv = _edge_masked(recv, side, name, n_dev)
                arr = _box_put(arr, recv, plan.dst, Ls)
        return arr
    for a in axis_order:
        r_lo = radius.face(a, -1)
        r_hi = radius.face(a, 1)
        if r_lo == 0 and r_hi == 0:
            continue
        p_lo = alloc_r.face(a, -1)
        p_hi = alloc_r.face(a, 1)
        assert p_lo >= r_lo and p_hi >= r_hi, \
            (f"axis {a}: wire depth ({r_lo},{r_hi}) exceeds allocation "
             f"pads ({p_lo},{p_hi})")
        dim = AXIS_TO_DIM[a]
        name = AXIS_NAME[a]
        n_dev = mesh_counts[a]
        alloc = arr.shape[dim]
        interior = alloc - p_lo - p_hi
        # actual interior length of this shard (traced when uneven)
        L = shard_interior_len(a, interior, rem)

        # fill the hi-side halo [p_lo+L, p_lo+L+r_hi): data lives at the
        # +a neighbor's interior lo edge [p_lo, p_lo + r_hi)
        narrow = n_dev > 1 and wf[name] != "f32"
        if r_hi > 0:
            src = lax.slice_in_dim(arr, p_lo, p_lo + r_hi, axis=dim)
            if narrow:
                src = _to_wire(src, wf[name])
            recv = _from_wire(_shift_from_plus(src, name, n_dev),
                              arr.dtype)
            if nonperiodic:
                recv = _edge_masked(recv, 1, name, n_dev)
            arr = lax.dynamic_update_slice_in_dim(arr, recv, p_lo + L,
                                                  axis=dim)
        # fill the lo-side halo [p_lo-r_lo, p_lo): data lives at the -a
        # neighbor's interior hi edge [p_lo + L - r_lo, p_lo + L)
        if r_lo > 0:
            src = lax.dynamic_slice_in_dim(arr, p_lo + L - r_lo, r_lo,
                                           axis=dim)
            if narrow:
                src = _to_wire(src, wf[name])
            recv = _from_wire(_shift_from_minus(src, name, n_dev),
                              arr.dtype)
            if nonperiodic:
                recv = _edge_masked(recv, -1, name, n_dev)
            arr = lax.dynamic_update_slice_in_dim(arr, recv, p_lo - r_lo,
                                                  axis=dim)
    return arr


def accumulate_shard(arr: jnp.ndarray, radius: Radius,
                     mesh_counts: Dim3,
                     axis_order: Tuple[int, ...] = (2, 1, 0),
                     rem: Dim3 = Dim3(0, 0, 0),
                     nonperiodic: bool = False) -> jnp.ndarray:
    """The ADJOINT of :func:`exchange_shard`: fold halo-pad
    contributions back into the interiors that own them (scatter-add
    deposition — a PIC particle near a shard edge deposits charge into
    this shard's pad cells, which belong to the neighbor's interior).

    Per axis, each pad slab is shipped to the neighboring shard whose
    interior it overlays and ADDED into that interior's edge rows, then
    zeroed locally. Axis order is the REVERSE of the exchange sweep
    (z, y, x by default): a slab spans the full allocation in the other
    dims, so edge/corner contributions ride into the other axes' pads
    and are folded by the subsequent sweeps — the transpose of the
    sequential-sweep corner rule. After all sweeps the pads are zero
    and every interior cell holds the full periodic sum.

    ``rem``: uneven (+-1) subdomains — a short shard's hi pad sits at
    its ACTUAL interior end (dynamic position), same placement rule as
    :func:`exchange_shard`. ``nonperiodic``: contributions crossing the
    open global boundary are discarded (the zero-Dirichlet exterior
    absorbs them) instead of wrapping. Must be traced inside
    ``shard_map``; lowers to the same collective-permute-only bill as
    the forward exchange (2 ppermutes per active axis), with identical
    wire bytes — ``exchanged_bytes_per_sweep`` prices both."""
    for a in axis_order:
        r_lo = radius.face(a, -1)
        r_hi = radius.face(a, 1)
        if r_lo == 0 and r_hi == 0:
            continue
        dim = AXIS_TO_DIM[a]
        name = AXIS_NAME[a]
        n_dev = mesh_counts[a]
        alloc = arr.shape[dim]
        interior = alloc - r_lo - r_hi
        L = shard_interior_len(a, interior, rem)

        # hi pad [p_lo+L, p_lo+L+r_hi) overlays the +a neighbor's
        # interior lo rows [p_lo, p_lo+r_hi): ship it +1 and add
        if r_hi > 0:
            src = lax.dynamic_slice_in_dim(arr, r_lo + L, r_hi, axis=dim)
            recv = _shift_from_minus(src, name, n_dev)
            if nonperiodic:
                # shard 0 received the wrapped last shard's pad: the
                # open boundary absorbs it
                recv = _edge_masked(recv, -1, name, n_dev)
            cur = lax.slice_in_dim(arr, r_lo, r_lo + r_hi, axis=dim)
            arr = lax.dynamic_update_slice_in_dim(arr, cur + recv, r_lo,
                                                  axis=dim)
            arr = lax.dynamic_update_slice_in_dim(
                arr, jnp.zeros_like(src), r_lo + L, axis=dim)
        # lo pad [p_lo-r_lo, p_lo) overlays the -a neighbor's interior
        # hi rows [p_lo+L-r_lo, p_lo+L): ship it -1 and add
        if r_lo > 0:
            src = lax.slice_in_dim(arr, 0, r_lo, axis=dim)
            recv = _shift_from_plus(src, name, n_dev)
            if nonperiodic:
                recv = _edge_masked(recv, 1, name, n_dev)
            cur = lax.dynamic_slice_in_dim(arr, r_lo + L - r_lo, r_lo,
                                           axis=dim)
            arr = lax.dynamic_update_slice_in_dim(arr, cur + recv,
                                                  r_lo + L - r_lo,
                                                  axis=dim)
            arr = lax.dynamic_update_slice_in_dim(
                arr, jnp.zeros_like(src), 0, axis=dim)
    return arr


def exchange_interior_slabs(p: jnp.ndarray, mesh_counts: Dim3,
                            rz: int, ry: int, radius_rows: int = 0,
                            y_z_extended: bool = False,
                            rem: Dim3 = Dim3(0, 0, 0)
                            ) -> Dict[str, jnp.ndarray]:
    """Exchange halo SLABS of one interior-resident (unpadded) shard —
    the data plane of the fused halo kernels (ops/pallas_halo.py).

    Unlike ``exchange_shard`` (which fills halo regions of a padded
    allocation in place), this returns the four slab arrays the halo
    kernels consume, leaving the shard untouched:

    * ``zlo`` (rz, Y, X): the z-minus neighbor's TOP rows, right-
      aligned (the row adjacent to this shard is ``zlo[-1]``);
    * ``zhi`` (rz, Y, X): the z-plus neighbor's BOTTOM rows, left-
      aligned (adjacent row is ``zhi[0]``);
    * ``ylo`` / ``yhi``: the y-minus (y-plus) neighbor's LAST (FIRST)
      rows, right-/left-aligned in an ry-row buffer — shape
      (Z, ry, X), or (Z + 2*rz, ry, X) when ``y_z_extended`` (the y
      sources then span the z halo too, so yz edge/corner data
      propagates — the sequential-sweep corner rule, reference
      src/stencil.cu:331-464 collapsed per SURVEY.md §7).

    ``rz``/``ry`` are the buffer row counts the kernels' block specs
    want (block-aligned); ``radius_rows`` (default ``min(rz, ry)``)
    is how many rows actually cross the wire — only the stencil radius
    is needed, the rest of each buffer is zero filler. On a 1-device
    mesh axis the shift degenerates to the shard's own wrapped edge
    (periodic). x must not be mesh-sharded (the halo kernels wrap x
    in-kernel). Must be traced inside ``shard_map``.

    ``rem``: uneven (+-1) subdomain counts. Shards are capacity-sized
    with a dead tail row/column on short shards, so the hi-edge sends
    come from the shard's ACTUAL last interior rows (dynamic slice at
    ``shard_interior_len - r``, the partition.hpp:55-69 rule); lo-edge
    sends start at 0 regardless. Not supported with ``y_z_extended``.
    """
    Z = p.shape[0]
    Y = p.shape[1]
    X = p.shape[2]
    nz = mesh_counts.z
    ny = mesh_counts.y
    r = radius_rows or min(rz, ry)
    assert r <= rz and r <= ry, (r, rz, ry)
    uneven = rem != Dim3(0, 0, 0)
    assert not (uneven and y_z_extended), \
        "uneven shards unsupported with z-extended y slabs"
    dt = p.dtype

    def zfill(n, yext):
        return jnp.zeros((n, yext, X), dt)

    def yfill(zext, n):
        return jnp.zeros((zext, n, X), dt)

    # r-row wire transfers (reference sends exactly the halo bytes,
    # src/packer.cu:78-82; buffers are padded to block-aligned rows).
    # Hi-edge sends slice at the actual interior end (traced when
    # uneven; shard_interior_len collapses to the static Z/Y otherwise).
    Lz = shard_interior_len(2, Z, rem)
    Ly = shard_interior_len(1, Y, rem)
    if uneven and rem[2] != 0:
        ztop = lax.dynamic_slice_in_dim(p, Lz - r, r, axis=0)
    else:
        ztop = lax.slice_in_dim(p, Z - r, Z, axis=0)
    zlo_r = _shift_from_minus(ztop, "z", nz)
    zhi_r = _shift_from_plus(lax.slice_in_dim(p, 0, r, axis=0), "z", nz)
    if y_z_extended:
        # this shard's y-edge columns spanning z in [-r, Z+r): own
        # interior plus the just-received z slabs (corner ride-along)
        def ysrc_hi():
            return jnp.concatenate(
                [zlo_r[:, Y - r:Y], p[:, Y - r:Y], zhi_r[:, Y - r:Y]],
                axis=0)

        def ysrc_lo():
            return jnp.concatenate(
                [zlo_r[:, 0:r], p[:, 0:r], zhi_r[:, 0:r]], axis=0)
        zext = Z + 2 * rz
        zoff = rz - r
    else:
        def ysrc_hi():
            if uneven and rem[1] != 0:
                return lax.dynamic_slice_in_dim(p, Ly - r, r, axis=1)
            return p[:, Y - r:Y]

        def ysrc_lo():
            return p[:, 0:r]
        zext = Z
        zoff = 0
    ylo_r = _shift_from_minus(ysrc_hi(), "y", ny)
    yhi_r = _shift_from_plus(ysrc_lo(), "y", ny)

    zlo = (zlo_r if rz == r
           else jnp.concatenate([zfill(rz - r, Y), zlo_r], axis=0))
    zhi = (zhi_r if rz == r
           else jnp.concatenate([zhi_r, zfill(rz - r, Y)], axis=0))

    def yembed(recv, align_hi: bool):
        out = recv
        if ry != r:
            pad = yfill(out.shape[0], ry - r)
            out = (jnp.concatenate([pad, out], axis=1) if align_hi
                   else jnp.concatenate([out, pad], axis=1))
        if zoff:
            zpad = jnp.zeros((zoff, ry, X), dt)
            out = jnp.concatenate([zpad, out, zpad], axis=0)
        return out

    return {"zlo": zlo, "zhi": zhi,
            "ylo": yembed(ylo_r, True), "yhi": yembed(yhi_r, False)}


def exchange_shard_packed(arrs: Dict[str, jnp.ndarray], radius: Radius,
                          mesh_counts: Dim3,
                          axis_order: Tuple[int, ...] = (0, 1, 2),
                          rem: Dim3 = Dim3(0, 0, 0),
                          alloc_radius: "Radius | None" = None,
                          nonperiodic: bool = False,
                          wire_format=None,
                          wire_layout=None
                          ) -> Dict[str, jnp.ndarray]:
    """Multi-quantity exchange with per-direction packing: all
    quantities' slabs for one axis-direction are flattened and
    concatenated into a single buffer, moved with ONE ppermute, then
    unpacked — the analog of DevicePacker/DeviceUnpacker packing all
    quantities per message (reference: src/packer.cu:10-44, 69-82).

    All quantities are bitcast to a common byte layout via flattening in
    float32/raw dtype groups; quantities of differing dtypes are packed
    in separate groups (alignment rule analog, src/packer.cu:76-82).

    ``rem``: uneven (+-1) subdomain counts (reference:
    partition.hpp:55-69) — same placement rule as ``exchange_shard``:
    a short shard's hi-edge send comes from its ACTUAL last interior
    rows (dynamic slice at the traced interior length) and its hi-side
    halo lands immediately after the actual interior; packed buffer
    shapes stay static (capacity-sized slabs), so one program serves
    every shard.

    ``alloc_radius``/``nonperiodic``/``wire_format``/``wire_layout``:
    same contract as :func:`exchange_shard` (deep-carry allocations for
    temporal blocking; zero-Dirichlet exterior for ``Boundary.NONE``;
    per-axis halo wire narrowing — here the whole packed
    per-dtype-group buffer narrows once before its single ppermute and
    widens once on arrival; "irredundant" packs each quantity's
    minimal box instead of its fat slab, see :mod:`.packing`).
    """
    from .packing import normalize_wire_layout, plan_direction

    alloc_r = alloc_radius if alloc_radius is not None else radius
    wf = normalize_wire_format(wire_format)
    irredundant = normalize_wire_layout(wire_layout) == "irredundant"
    names = sorted(arrs.keys())  # sorted so both endpoints agree on
    # layout (reference sorts messages by size, src/packer.cu:69,182-183)
    out = {k: v for k, v in arrs.items()}
    for a in axis_order:
        r_lo = radius.face(a, -1)
        r_hi = radius.face(a, 1)
        if r_lo == 0 and r_hi == 0:
            continue
        p_lo = alloc_r.face(a, -1)
        p_hi = alloc_r.face(a, 1)
        assert p_lo >= r_lo and p_hi >= r_hi, \
            (f"axis {a}: wire depth ({r_lo},{r_hi}) exceeds allocation "
             f"pads ({p_lo},{p_hi})")
        dim = AXIS_TO_DIM[a]
        name = AXIS_NAME[a]
        n_dev = mesh_counts[a]
        uneven_axis = rem[a] != 0

        for side, r_fill in ((1, r_hi), (-1, r_lo)):
            if r_fill == 0:
                continue
            # group quantities by dtype so concatenation is well-typed
            groups: Dict[np.dtype, List[str]] = {}
            for q in names:
                groups.setdefault(out[q].dtype, []).append(q)
            for dt, qs in groups.items():
                slabs = []
                shapes = []
                unpacks = []  # irredundant: (DirectionPlan, Ls) per q
                for q in qs:
                    arr = out[q]
                    if irredundant:
                        interiors = _shard_interiors(arr, alloc_r)
                        plan = plan_direction(a, side, radius, alloc_r,
                                              tuple(axis_order), interiors)
                        Ls = [shard_interior_len(b, interiors[b], rem)
                              for b in range(3)]
                        src = _box_take(arr, plan.src, Ls)
                        unpacks.append((plan, Ls))
                        shapes.append(src.shape)
                        slabs.append(src.reshape(-1))
                        continue
                    alloc = arr.shape[dim]
                    interior = alloc - p_lo - p_hi
                    L = shard_interior_len(a, interior, rem)
                    if side == 1:
                        src = lax.slice_in_dim(arr, p_lo, p_lo + r_hi, axis=dim)
                    elif uneven_axis:
                        # hi edge of a short shard sits at its actual
                        # interior end [p_lo + L - r_lo, p_lo + L)
                        src = lax.dynamic_slice_in_dim(arr, p_lo + L - r_lo,
                                                       r_lo, axis=dim)
                    else:
                        src = lax.slice_in_dim(arr, p_lo + interior - r_lo,
                                               p_lo + interior, axis=dim)
                    shapes.append(src.shape)
                    slabs.append(src.reshape(-1))
                packed = jnp.concatenate(slabs) if len(slabs) > 1 else slabs[0]
                if n_dev > 1 and wf[name] != "f32":
                    packed = _to_wire(packed, wf[name])
                moved = (_shift_from_plus(packed, name, n_dev) if side == 1
                         else _shift_from_minus(packed, name, n_dev))
                moved = _from_wire(moved, dt)
                if nonperiodic:
                    moved = _edge_masked(moved, side, name, n_dev)
                # unpack
                off = 0
                for i, (q, shp) in enumerate(zip(qs, shapes)):
                    cnt = int(np.prod(shp))
                    recv = lax.dynamic_slice_in_dim(moved, off, cnt, axis=0
                                                    ).reshape(shp)
                    off += cnt
                    arr = out[q]
                    if irredundant:
                        plan, Ls = unpacks[i]
                        out[q] = _box_put(arr, recv, plan.dst, Ls)
                        continue
                    alloc = arr.shape[dim]
                    interior = alloc - p_lo - p_hi
                    if side == 1:
                        L = shard_interior_len(a, interior, rem)
                        start = p_lo + L
                    else:
                        start = p_lo - r_lo
                    out[q] = lax.dynamic_update_slice_in_dim(arr, recv, start,
                                                             axis=dim)
    return out


def exchange_shard_allgather(arr: jnp.ndarray, radius: Radius,
                             mesh_counts: Dim3,
                             axis_order: Tuple[int, ...] = (0, 1, 2)
                             ) -> jnp.ndarray:
    """Control strategy: per axis, all_gather the boundary slabs and
    slice out the two needed neighbors. Strictly more bytes on the wire
    than ppermute — exists for method A/B sweeps like the reference's
    bench_alltoallv (bin/bench_alltoallv.cu)."""
    for a in axis_order:
        r_lo = radius.face(a, -1)
        r_hi = radius.face(a, 1)
        if r_lo == 0 and r_hi == 0:
            continue
        dim = AXIS_TO_DIM[a]
        name = AXIS_NAME[a]
        n_dev = mesh_counts[a]
        alloc = arr.shape[dim]
        interior = alloc - r_lo - r_hi
        if n_dev == 1:
            arr = exchange_shard(arr, _single_axis_radius(radius, a), mesh_counts,
                                 axis_order=(a,))
            continue
        idx = lax.axis_index(name)
        if r_hi > 0:
            src = lax.slice_in_dim(arr, r_lo, r_lo + r_hi, axis=dim)
            gath = lax.all_gather(src, name, axis=0)  # (n_dev, ...slab)
            recv = gath[(idx + 1) % n_dev]
            arr = lax.dynamic_update_slice_in_dim(arr, recv, r_lo + interior,
                                                  axis=dim)
        if r_lo > 0:
            src = lax.slice_in_dim(arr, interior, r_lo + interior, axis=dim)
            gath = lax.all_gather(src, name, axis=0)
            recv = gath[(idx - 1) % n_dev]
            arr = lax.dynamic_update_slice_in_dim(arr, recv, 0, axis=dim)
    return arr


def _single_axis_radius(radius: Radius, axis: int) -> Radius:
    r = Radius.constant(0)
    for side in (-1, 1):
        d = [0, 0, 0]
        d[axis] = side
        r.set_dir(tuple(d), radius.face(axis, side))
    return r


def dispatch_exchange(fields: Dict[str, jnp.ndarray], radius: Radius,
                      mesh_counts: Dim3, method: Method,
                      axis_order: Tuple[int, ...] = (0, 1, 2),
                      rem: Dim3 = Dim3(0, 0, 0),
                      alloc_radius: "Radius | None" = None,
                      nonperiodic: bool = False,
                      wire_format=None,
                      wire_layout=None) -> Dict[str, jnp.ndarray]:
    """Route a multi-quantity shard exchange to the selected strategy —
    the single dispatch point shared by the orchestrator and the fused
    model steps (the Method-routing analog of src/stencil.cu:371-458).

    ``alloc_radius``/``nonperiodic``/``wire_format``/``wire_layout``
    (ppermute methods only): deep-carry allocations for temporal
    blocking, the zero-Dirichlet exterior of ``Boundary.NONE``,
    per-axis halo wire narrowing, and the irredundant wire layout —
    see :func:`exchange_shard`."""
    from .packing import normalize_wire_layout

    uneven = rem != Dim3(0, 0, 0)
    wf = normalize_wire_format(wire_format)
    narrows = any(v != "f32" for v in wf.values())
    layout = normalize_wire_layout(wire_layout)
    if uneven and method not in (Method.PpermuteSlab,
                                 Method.PpermutePacked):
        raise NotImplementedError(
            f"uneven (+-1 remainder) subdomains are only supported by "
            f"the PpermuteSlab and PpermutePacked methods, not {method}")
    if ((alloc_radius is not None or nonperiodic or narrows
         or layout != "slab")
            and method not in (Method.PpermuteSlab, Method.PpermutePacked)):
        raise NotImplementedError(
            f"deep-carry allocations, non-periodic boundaries, narrow "
            f"wire formats, and non-slab wire layouts are only "
            f"supported by the PpermuteSlab and PpermutePacked "
            f"methods, not {method}")
    if method == Method.PallasDMA:
        from .pallas_exchange import exchange_shard_pallas
        return {k: exchange_shard_pallas(v, radius, mesh_counts, axis_order)
                for k, v in fields.items()}
    if method == Method.PpermutePacked:
        return exchange_shard_packed(fields, radius, mesh_counts,
                                     axis_order, rem, alloc_radius,
                                     nonperiodic, wf, layout)
    if method == Method.AllGather:
        return {k: exchange_shard_allgather(v, radius, mesh_counts, axis_order)
                for k, v in fields.items()}
    return {k: exchange_shard(v, radius, mesh_counts, axis_order, rem,
                              alloc_radius, nonperiodic, wf, layout)
            for k, v in fields.items()}


def make_exchange(mesh: Mesh, radius: Radius,
                  methods: Method = Method.Default,
                  axis_order: Tuple[int, ...] = (0, 1, 2),
                  rem: Dim3 = Dim3(0, 0, 0),
                  nonperiodic: bool = False,
                  wire_format=None, fields_spec=None,
                  wire_layout=None):
    """Build a jitted multi-quantity halo exchange over ``mesh``.

    Returns ``exchange(fields: dict[str, Array]) -> dict[str, Array]``
    where each field is a *global* padded (z,y,x) array sharded
    ``P('z','y','x')``. The orchestrator analog of
    DistributedDomain::exchange() (reference: src/stencil.cu:1002-1186)
    — except the whole dance (pack, send, poll, unpack, sync) is one
    XLA program.

    The input fields are DONATED: the exchange updates halos in place
    (XLA aliases each output to its input buffer), so the per-call HBM
    copy of every field disappears. Callers must drop their references
    to the passed arrays (``DistributedDomain.exchange`` rebinds
    ``curr`` from the result).

    ``wire_format`` declares the per-axis halo wire dtype ("f32" |
    "bf16", uniform string or per-axis dict — see
    :func:`normalize_wire_format`). A NARROWING wire format is
    certificate-gated: ``fields_spec`` (a ``{name: ShapeDtypeStruct}``
    dict of the global padded fields) is then required, the precision
    checker (checker 13, ``analysis/precision.py``) proves the built
    program's dtype flow sound — declared converts only, reductions at
    >= f32, exactly the declared wire dtype per link class, no double
    quantization — and an unsafe certificate raises
    ``PrecisionGateError`` instead of realizing. The returned callable
    carries ``wire_format``, ``wire_layout``, ``precision_declaration``,
    and ``precision_certificate`` attributes.

    ``wire_layout`` selects the message shape ("slab" | "irredundant",
    see :mod:`.packing`) — orthogonal to ``wire_format`` and composed
    with it (pack -> narrow -> ship -> widen -> unpack).
    """
    from .packing import normalize_wire_layout

    method = pick_method(methods)
    counts = Dim3(mesh.shape["x"], mesh.shape["y"], mesh.shape["z"])
    spec = P("z", "y", "x")
    wf = normalize_wire_format(wire_format)
    narrows = any(v != "f32" for v in wf.values())
    layout = normalize_wire_layout(wire_layout)

    def shard_fn(fields: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        return dispatch_exchange(fields, radius, counts, method, axis_order,
                                 rem, nonperiodic=nonperiodic,
                                 wire_format=wf, wire_layout=layout)

    sm = jax.shard_map(shard_fn, mesh=mesh,
                       in_specs=spec, out_specs=spec, check_vma=False)
    ex = jax.jit(sm, donate_argnums=0)
    cert = None
    if narrows:
        # the certificate gate: an uncertified narrow wire format
        # refuses to realize, loudly (the schedule-certifier precedent,
        # parallel/megastep.certificate_gate)
        from ..analysis import precision as _precision

        if fields_spec is None:
            raise ValueError(
                "make_exchange: a narrowing wire_format is certificate-"
                "gated — pass fields_spec={name: jax.ShapeDtypeStruct("
                "global_padded_shape, dtype)} so the precision checker "
                "can prove the program before it realizes")
        cert = _precision.certify_wire_format(
            ex, ({q: jax.ShapeDtypeStruct(v.shape, v.dtype)
                  for q, v in dict(fields_spec).items()},),
            counts=counts, wire_formats=wf)
        if not cert.safe:
            raise _precision.PrecisionGateError(
                "make_exchange: wire format "
                f"{ {k: v for k, v in wf.items()} } is NOT certified "
                f"safe — refusing to realize: "
                + "; ".join(cert.reasons))
    ex.wire_format = dict(wf)
    ex.wire_layout = layout
    ex.precision_declaration = {"wire": {ax: fmt for ax, fmt in wf.items()},
                                "compute": "float32"}
    ex.precision_certificate = cert
    return ex


def interior_slab_bytes(shard_zyx: Sequence[int], mesh_counts: Dim3,
                        radius_rows: int, elem_size: int,
                        y_z_extended: bool = False) -> int:
    """Wire bytes ONE shard puts on the ICI per
    ``exchange_interior_slabs`` call — the fast-path counterpart of
    ``exchanged_bytes_per_sweep`` (reference byte-counter ethos:
    src/stencil.cu:516-637). Counts the r-row transfers actually
    ppermuted (buffer filler rows are local zeros, not traffic);
    axes with one device are in-core wraps and cost nothing."""
    Z, Y, X = shard_zyx
    r = radius_rows
    total = 0
    if mesh_counts.z > 1:
        total += 2 * r * Y * X * elem_size
    if mesh_counts.y > 1:
        zspan = Z + 2 * r if y_z_extended else Z
        total += 2 * r * zspan * X * elem_size
    return total


def measure_slab_exchange_seconds(mesh: Mesh, local: Dim3, dtype,
                                  rz: int, ry: int, radius_rows: int,
                                  y_z_extended: bool, nfields: int = 1,
                                  reps: int = 10) -> float:
    """Time ONE standalone ``exchange_interior_slabs`` round for
    ``nfields`` interior-resident fields over ``mesh`` — the honest
    exchange-cost estimate for the fused fast paths, which perform
    exactly this transfer inside their jitted loops where it cannot be
    timed separately (the per-iteration exchange-stats analog of
    src/stencil.cu:1005-1008,1174-1181). Returns seconds per exchange
    round (all fields). Compiles a throwaway program on zeros; the
    persistent compile cache keeps repeat calls cheap."""
    import time as _time

    from ..utils.timers import device_sync

    counts = Dim3(mesh.shape["x"], mesh.shape["y"], mesh.shape["z"])
    dim = Dim3(counts.x * local.x, counts.y * local.y,
               counts.z * local.z)
    sharding = jax.sharding.NamedSharding(mesh, P("z", "y", "x"))
    # allocate the zeros SHARDED (out_shardings), never staged on one
    # device — the global array at weak-scaled sizes would OOM the
    # default device if materialized there first
    make = jax.jit(lambda: jnp.zeros((dim.z, dim.y, dim.x), dtype),
                   out_shardings=sharding)
    fields = [make() for _ in range(nfields)]

    def shard_fn(*fs):
        outs = []
        for f in fs:
            s = exchange_interior_slabs(f, counts, rz=rz, ry=ry,
                                        radius_rows=radius_rows,
                                        y_z_extended=y_z_extended)
            # ALL four slabs are outputs: returning only zlo would let
            # XLA dead-code-eliminate the y-axis ppermutes (zlo depends
            # on the z shift alone) and the timing would silently drop
            # the y-face traffic
            outs.extend([s["zlo"], s["zhi"], s["ylo"], s["yhi"]])
        return tuple(outs)

    spec = P("z", "y", "x")
    fn = jax.jit(jax.shard_map(shard_fn, mesh=mesh,
                               in_specs=(spec,) * nfields,
                               out_specs=(spec,) * (4 * nfields),
                               check_vma=False))
    out = fn(*fields)
    device_sync(out[0])
    t0 = _time.perf_counter()
    for _ in range(reps):
        out = fn(*fields)
    device_sync(out[0])
    return (_time.perf_counter() - t0) / reps


def exchanged_bytes_per_sweep(shard_padded_shape_zyx: Sequence[int],
                              radius: Radius, mesh_counts: Dim3,
                              elem_size: int,
                              axis_order: Tuple[int, ...] = (0, 1, 2),
                              wire_format=None) -> Dict[str, int]:
    """Per-axis bytes one shard puts on the wire per exchange — the
    byte-counter observability analog (reference: stencil.hpp:86-93,
    src/stencil.cu:516-637). Counts only shifts that cross devices
    (n_dev > 1); same-device wraps are local copies. A narrowing
    ``wire_format`` axis prices its elements at the on-wire width
    (4-byte lanes exactly halve under "bf16", quarter under fp8).
    Prices the SLAB layout; the irredundant twin is
    :func:`..parallel.packing.irredundant_bytes_per_sweep`."""
    out = {"x": 0, "y": 0, "z": 0}
    shape = list(shard_padded_shape_zyx)
    wf = normalize_wire_format(wire_format)
    for a in axis_order:
        dim = AXIS_TO_DIM[a]
        if mesh_counts[a] <= 1:
            continue
        other = 1
        for d in range(3):
            if d != dim:
                other *= shape[d]
        es = wire_elem_size(elem_size, wf[AXIS_NAME[a]])
        out[AXIS_NAME[a]] = radius.wire_rows(a) * other * es
    return out
