"""Small numeric helpers.

TPU-native re-implementation of the reference's numeric utilities
(reference: include/stencil/numeric.hpp, src/numeric.cpp:7-27).
"""

from __future__ import annotations

import math
from typing import List, Sequence


def next_power_of_two(x: int) -> int:
    """Smallest power of two >= x (reference: include/stencil/numeric.hpp)."""
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


def prime_factors(n: int) -> List[int]:
    """Prime factorization of ``n``, sorted descending.

    Matches the semantics of the reference's ``prime_factors``
    (src/numeric.cpp:7-27): returns the multiset of prime factors,
    largest first, so recursive splitters cut by big factors first.
    ``prime_factors(1) == [1]`` and ``prime_factors(0) == []`` as in the
    reference.
    """
    if n <= 0:
        return []
    if n == 1:
        return [1]
    out: List[int] = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    out.sort(reverse=True)
    return out


def div_ceil(n: int, d: int) -> int:
    """Ceiling division (reference: include/stencil/numeric.hpp)."""
    return -(-n // d)


def next_align_of(x: int, align: int) -> int:
    """Round ``x`` up to a multiple of ``align``
    (reference: include/stencil/align.cuh:7-9)."""
    return div_ceil(x, align) * align


def get_max_abs_error(a: Sequence[float], b: Sequence[float]) -> float:
    """Max elementwise absolute error (reference: include/stencil/numeric.hpp)."""
    return max((abs(x - y) for x, y in zip(a, b)), default=0.0)


def trimean(samples: Sequence[float]) -> float:
    """Tukey trimean (q1 + 2*q2 + q3) / 4 over sorted samples.

    This is the summary statistic all reference benchmarks report
    (reference: bin/statistics.hpp:6-19).
    """
    s = sorted(samples)
    n = len(s)
    if n == 0:
        raise ValueError("trimean of empty sample set")

    def quantile(q: float) -> float:
        # linear interpolation between closest ranks (type-7, numpy default)
        idx = q * (n - 1)
        lo = math.floor(idx)
        hi = math.ceil(idx)
        frac = idx - lo
        return s[lo] * (1.0 - frac) + s[hi] * frac

    return (quantile(0.25) + 2.0 * quantile(0.5) + quantile(0.75)) / 4.0


class Statistics:
    """Streaming accumulator reporting min/max/avg/median/trimean/stddev.

    Mirrors the accumulator used by every reference benchmark
    (reference: bin/statistics.hpp:6-19).
    """

    def __init__(self) -> None:
        self._samples: List[float] = []

    def insert(self, x: float) -> None:
        self._samples.append(float(x))

    def __len__(self) -> int:
        return len(self._samples)

    @property
    def count(self) -> int:
        return len(self._samples)

    def min(self) -> float:
        return min(self._samples)

    def max(self) -> float:
        return max(self._samples)

    def avg(self) -> float:
        return sum(self._samples) / len(self._samples)

    def median(self) -> float:
        s = sorted(self._samples)
        n = len(s)
        mid = n // 2
        return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])

    def trimean(self) -> float:
        return trimean(self._samples)

    def stddev(self) -> float:
        n = len(self._samples)
        if n < 2:
            return 0.0
        mean = self.avg()
        var = sum((x - mean) ** 2 for x in self._samples) / (n - 1)
        return math.sqrt(var)
