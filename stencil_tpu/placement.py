"""Placement: mapping subdomain indices onto physical devices.

TPU-native re-implementation of the reference's placement layer
(reference: include/stencil/partition.hpp:258-831,
placement_intranoderandom.hpp): a bijection between subdomain index
(Dim3) and a device, chosen to put heavy halo traffic on fast links.

On a TPU slice the ICI fabric is a torus and ``device.coords`` exposes
the physical coordinates, so the NodeAware strategy reduces to sorting
devices by torus coordinates — nearest-neighbor mesh shifts become
single-hop by construction. The QAP machinery (reference:
partition.hpp:694-760) is retained for irregular device sets (e.g.
multi-host DCN pods or virtual meshes): it builds the subdomain-pair
communication-bytes matrix (periodic-aware halo bytes) and a device-pair
distance matrix (torus hop count), then solves the quadratic assignment.
"""

from __future__ import annotations

import enum
from typing import List, Optional, Sequence

import numpy as np

from . import qap
from .geometry import Dim3, Dim3Like, Radius, all_directions
from .local_domain import halo_bytes
from .partition import RankPartition
from .topology import Topology


class PlacementStrategy(enum.Enum):
    """reference: include/stencil/partition.hpp:258-262."""

    NodeAware = "node-aware"
    Trivial = "trivial"
    IntraNodeRandom = "random"


def iter_messages(part: RankPartition, radius: Radius,
                  elem_sizes: Sequence[int],
                  topo: Optional[Topology] = None):
    """Yield every planned cross-subdomain halo message as
    ``(i, j, direction, bytes)`` — the single source of truth for the
    comm matrix and the plan file's per-message lines (reference:
    src/stencil.cu:523-637 plans one message per direction).
    ``topo`` carries the boundary condition; defaults to periodic."""
    if topo is None:
        topo = Topology(part.dim())
    n = part.dim().flatten()
    for i in range(n):
        idx = part.dimensionize(i)
        for d in all_directions():
            if radius.dir(-d) == 0:
                # no send needed in d when the opposite radius is zero
                # (reference: src/stencil.cu:344)
                continue
            nbr = topo.get_neighbor(idx, d)
            if not nbr.exists:
                continue
            j = part.linearize(nbr.index)
            if i == j:
                continue  # same-device wrap is local
            dst_size = part.subdomain_size(nbr.index)
            nbytes = sum(halo_bytes(-d, dst_size, radius, es)
                         for es in elem_sizes)
            yield i, j, d, nbytes


def comm_bytes_matrix(part: RankPartition, radius: Radius,
                      elem_sizes: Sequence[int],
                      topo: Optional[Topology] = None) -> np.ndarray:
    """Subdomain-pair halo-communication bytes, the "w" matrix of the
    QAP (reference: partition.hpp:722-752).

    entry [i, j] = bytes subdomain i sends subdomain j per exchange,
    summed over all quantities and all directions that map i -> j.
    """
    n = part.dim().flatten()
    w = np.zeros((n, n), dtype=np.float64)
    for i, j, _, nbytes in iter_messages(part, radius, elem_sizes, topo):
        w[i, j] += nbytes
    return w


def torus_distance_matrix(devices: Sequence) -> np.ndarray:
    """Device-pair distance: ICI torus hop count (L1 over coords) when
    coords are exposed, else uniform distance 1 — the gpu_topo bandwidth
    analog (reference: src/gpu_topology.cpp:17-95, bandwidth=1/distance)."""
    n = len(devices)
    coords = []
    for d in devices:
        c = getattr(d, "coords", None)
        if c is None or len(c) < 3:
            coords = None
            break
        coords.append(tuple(c))
    dist = np.ones((n, n), dtype=np.float64)
    np.fill_diagonal(dist, 0.0)
    if coords is None:
        return dist
    for i in range(n):
        for j in range(n):
            if i != j:
                dist[i, j] = sum(abs(a - b) for a, b in zip(coords[i], coords[j]))
    return dist


class Placement:
    """Bijection subdomain-index <-> device slot
    (reference: partition.hpp:264-289 abstract Placement).

    ``order`` holds device objects; subdomain with linear id ``i``
    (x-fastest, via ``part.linearize``) runs on ``order[assignment[i]]``.
    """

    def __init__(self, part: RankPartition, devices: Sequence,
                 assignment: Optional[List[int]] = None) -> None:
        self.part = part
        self.devices = list(devices)
        n = part.dim().flatten()
        assert len(self.devices) == n, (len(self.devices), n)
        self.assignment = assignment or list(range(n))

    def dim(self) -> Dim3:
        return self.part.dim()

    def get_device(self, idx: Dim3Like):
        """Device hosting subdomain ``idx`` (the get_cuda analog)."""
        i = self.part.linearize(Dim3.of(idx))
        return self.devices[self.assignment[i]]

    def get_idx(self, device) -> Dim3:
        """Subdomain index hosted by ``device`` (the get_idx analog)."""
        slot = self.devices.index(device)
        i = self.assignment.index(slot)
        return self.part.dimensionize(i)

    def subdomain_size(self, idx: Dim3Like) -> Dim3:
        return self.part.subdomain_size(Dim3.of(idx))

    def subdomain_origin(self, idx: Dim3Like) -> Dim3:
        return self.part.subdomain_origin(Dim3.of(idx))

    def device_order_for_mesh(self) -> List:
        """Devices ordered by subdomain linear index (x fastest) — feed
        to ``mesh.make_mesh``."""
        return [self.devices[self.assignment[i]]
                for i in range(len(self.devices))]


# single source of truth for device ordering lives in parallel.mesh so
# the placement layer and the mesh provably agree
from .parallel.mesh import _torus_sorted as _torus_sorted_devices

#: placement-mode escape hatch (DistributedDomain.set_placement /
#: Jacobi3D(placement=...)): "auto" deploys the QAP assignment whenever
#: the fabric is non-uniform (measured ICI-hop spread, or a DCN-blocked
#: axis) and keeps the trivial order on uniform fabrics; "qap"/"trivial"
#: force one side for experiments and controls.
PLACEMENT_MODES = ("auto", "qap", "trivial")


def normalize_placement_mode(mode: str) -> str:
    m = "auto" if mode is None else str(mode)
    if m not in PLACEMENT_MODES:
        raise ValueError(f"unknown placement mode {mode!r} "
                         f"(expected one of {PLACEMENT_MODES})")
    return m


def make_placement(strategy: PlacementStrategy, part: RankPartition,
                   devices: Sequence, radius: Radius,
                   elem_sizes: Sequence[int], seed: int = 0,
                   qap_timeout_s: float = 2.0, mode: str = "auto",
                   dcn_axis: Optional[int] = None,
                   n_slices: int = 1) -> Placement:
    """Construct a placement (reference: src/stencil.cu:201-239
    do_placement dispatch).

    * Trivial: subdomain i -> device i in enumeration order
      (reference: partition.hpp:291-445).
    * NodeAware: torus-sort devices, then QAP-refine the assignment with
      the halo-bytes x hop-distance objective whenever the fabric is
      non-uniform (reference: partition.hpp:525-831).
    * IntraNodeRandom: seeded shuffle, the experimental control
      (reference: src/placement_intranoderandom.cpp:117-125).

    ``mode`` gates the NodeAware QAP refinement: ``"auto"`` (default)
    deploys it when the fabric is non-uniform — a measured ICI-hop
    spread in the device coords, or a DCN-blocked axis
    (``dcn_axis``/``n_slices``), for which coordless fabrics get the
    synthetic lattice-torus + DCN-penalty distances of
    ``observatory.linkmap.mesh_distance_matrix``; ``"trivial"`` keeps
    the identity assignment; ``"qap"`` always refines. The deployed
    assignment is clamped to never cost more than identity under the
    QAP objective, so the ``observatory linkmap --placement-report``
    gate (QAP cost <= trivial) holds structurally.
    """
    n = part.dim().flatten()
    mode = normalize_placement_mode(mode)
    if strategy == PlacementStrategy.Trivial:
        return Placement(part, list(devices))
    if strategy == PlacementStrategy.IntraNodeRandom:
        rng = np.random.default_rng(seed)
        assignment = list(rng.permutation(n))
        return Placement(part, list(devices), [int(a) for a in assignment])
    # NodeAware
    devs = _torus_sorted_devices(devices)
    if n <= 1 or mode == "trivial":
        return Placement(part, devs)
    dist = torus_distance_matrix(devs)
    offdiag = dist[~np.eye(n, dtype=bool)]
    measured_nonuniform = not np.all(offdiag == offdiag[0])
    dcn_blocked = dcn_axis is not None and int(n_slices) > 1
    if not measured_nonuniform and (dcn_blocked or mode == "qap"):
        # coordless (or coord-uniform) fabric: synthesize per-slot
        # distances from the deployed shard lattice — wrapped-torus
        # hops plus the DCN penalty on the blocked axis
        from .observatory.linkmap import mesh_distance_matrix
        dist = mesh_distance_matrix(part.dim(), dcn_axis=dcn_axis,
                                    n_slices=n_slices)
        offdiag = dist[~np.eye(n, dtype=bool)]
    nonuniform = not np.all(offdiag == offdiag[0])
    if mode == "auto" and not nonuniform:
        # uniform fabric: torus sort is already optimal
        return Placement(part, devs)
    w = comm_bytes_matrix(part, radius, elem_sizes)
    if n <= 8:
        f, _ = qap.solve(w, dist, timeout_s=qap_timeout_s)
    else:
        f, _ = qap.solve_catch(w, dist)
    f = [int(i) for i in f]
    identity = list(range(n))
    if qap.cost(w, dist, f) > qap.cost(w, dist, identity):
        f = identity  # never ship a costlier-than-trivial order
    return Placement(part, devs, f)
