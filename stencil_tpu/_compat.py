"""JAX version-compatibility shims and capability probes.

The framework targets the current JAX API surface (``jax.shard_map``,
``pltpu.CompilerParams``, the distributed TPU interpreter's
``pltpu.InterpretParams``); some deployment images pin an older JAX
where those names either do not exist yet or are spelled differently.
Importing :mod:`stencil_tpu` installs small forwarding shims so ONE
codebase runs on both:

* ``jax.shard_map``      -> ``jax.experimental.shard_map.shard_map``
  (the ``check_vma`` kwarg becomes the older ``check_rep``);
* ``pltpu.CompilerParams`` -> ``pltpu.TPUCompilerParams`` with unknown
  kwargs dropped (e.g. ``has_side_effects``, which the old class does
  not carry — only relevant to DCE on real TPUs, where a matching
  modern JAX is installed anyway);
* ``pltpu.InterpretParams`` -> a truthy stub, so modules can *construct*
  interpreter parameters on any version. The stub enables the generic
  Pallas interpreter; it does NOT provide the distributed TPU
  interpreter's inter-device DMA emulation or vector-clock race
  detector — code needing those must gate on the probes below.

Capability probes (evaluated once, against the PRE-shim API):

* ``HAS_NATIVE_SHARD_MAP``        — ``jax.shard_map`` existed already;
* ``HAS_DISTRIBUTED_INTERPRET``   — the real ``pltpu.InterpretParams``
  (mosaic interpret mode: emulated inter-device DMA on a host mesh);
* ``has_race_detector()``         — distributed interpret with
  ``detect_races`` (the vector-clock sanitizer the race tests need).

Tests that exercise interpreted remote DMA use these to skip — not
fail — on images whose JAX cannot run them (the "gate missing deps"
rule), keeping the suite green everywhere while still running the full
choreography wherever the real interpreter exists.
"""

from __future__ import annotations

import inspect
from typing import Any

import jax

HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")

try:  # the distributed (mosaic) TPU interpreter, JAX >= 0.5.x
    from jax.experimental.pallas import tpu as _pltpu

    HAS_DISTRIBUTED_INTERPRET = hasattr(_pltpu, "InterpretParams")
except Exception:  # pragma: no cover - pallas always importable in CI
    _pltpu = None
    HAS_DISTRIBUTED_INTERPRET = False


def has_race_detector() -> bool:
    """True when ``pltpu.InterpretParams(detect_races=True)`` is the
    real vector-clock race detector (not this module's stub)."""
    if not HAS_DISTRIBUTED_INTERPRET or _pltpu is None:
        return False
    params = inspect.signature(_pltpu.InterpretParams).parameters
    return "detect_races" in params


def remote_dma_runnable() -> bool:
    """True when the Pallas remote-DMA choreography can actually RUN in
    this process: on a real TPU backend always; off-TPU only when the
    distributed (mosaic) TPU interpreter exists to emulate inter-device
    DMA. Tests, the certification sweep, and CI smoke stages gate the
    RDMA/overlap paths on this (they are *traceable* everywhere — the
    static analysis pass still covers them — just not executable)."""
    if HAS_DISTRIBUTED_INTERPRET:
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend init failure
        return False


class _InterpretParamsStub:
    """Truthy stand-in for ``pltpu.InterpretParams`` on old JAX: lets
    modules build interpreter params unconditionally; pallas_call treats
    any truthy ``interpret=`` as the generic interpreter."""

    _stencil_tpu_compat_stub = True

    def __init__(self, **kwargs: Any) -> None:
        self.detect_races = bool(kwargs.pop("detect_races", False))
        self.kwargs = kwargs

    def __bool__(self) -> bool:
        return True

    def __repr__(self) -> str:
        return f"InterpretParamsStub(detect_races={self.detect_races})"


def _shard_map_shim(f=None, *, mesh=None, in_specs=None, out_specs=None,
                    check_vma: bool = True, **kwargs: Any):
    """``jax.shard_map`` on top of the legacy
    ``jax.experimental.shard_map.shard_map`` (``check_vma`` was called
    ``check_rep`` there). Unknown kwargs are REJECTED, not dropped —
    silently ignoring a semantic option would make old-JAX runs
    diverge from modern-JAX runs instead of failing loudly."""
    from jax.experimental.shard_map import shard_map as _legacy

    if kwargs:
        raise TypeError(
            f"jax.shard_map compat shim does not support kwargs "
            f"{sorted(kwargs)} on this JAX version")

    def bind(fun):
        return _legacy(fun, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=bool(check_vma))

    return bind if f is None else bind(f)


_installed = False


def install() -> None:
    """Install the shims (idempotent; called from package import)."""
    global _installed
    if _installed:
        return
    _installed = True
    if not HAS_NATIVE_SHARD_MAP:
        jax.shard_map = _shard_map_shim
    if _pltpu is not None:
        if not hasattr(_pltpu, "CompilerParams"):
            legacy = _pltpu.TPUCompilerParams
            accepted = set(inspect.signature(legacy.__init__).parameters)

            def _compiler_params(**kwargs: Any):
                return legacy(**{k: v for k, v in kwargs.items()
                                 if k in accepted})

            _pltpu.CompilerParams = _compiler_params
        if not HAS_DISTRIBUTED_INTERPRET:
            _pltpu.InterpretParams = _InterpretParamsStub
