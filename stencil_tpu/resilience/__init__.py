"""Driver-level robustness: health sentinels, fault injection, and
checkpoint-rollback recovery (see README "Resilience").

Three cooperating parts:

* :mod:`.health` — a fused on-device probe (one small all-reduce,
  proven by the ``resilience.health.*`` stencil-lint targets) with
  async host readback and a divergence predicate;
* :mod:`.faults` — deterministic, seeded fault injection (NaN steps,
  corrupted halos, checkpoint bit-rot, transient save ``IOError``,
  SIGTERM preemption) so every recovery path is pinned by tier-1;
* :mod:`.driver` — ``run_resilient``: checkpoint / watch / roll back /
  degrade / resume around any per-step engine.
"""

from .driver import (ResilienceError, ResiliencePolicy, ResilienceReport,
                     StepConfig, degradation_ladder, run_resilient)
from .faults import (CheckpointCorruption, FaultPlan, HaloCorruption,
                     NaNInjection, ParticleLoss, Preemption,
                     TransientSaveFailure)
from .health import HealthSentinel, HealthStats, make_probe, probe_shard

__all__ = [
    "CheckpointCorruption",
    "FaultPlan",
    "HaloCorruption",
    "HealthSentinel",
    "HealthStats",
    "NaNInjection",
    "ParticleLoss",
    "Preemption",
    "ResilienceError",
    "ResiliencePolicy",
    "ResilienceReport",
    "StepConfig",
    "TransientSaveFailure",
    "degradation_ladder",
    "make_probe",
    "probe_shard",
    "run_resilient",
]
