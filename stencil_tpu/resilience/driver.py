"""Checkpoint-rollback recovery driver: the resilient run loop.

``run_resilient(domain, step_fn, n_steps, policy)`` wraps any per-step
engine (``Jacobi3D.step``, ``Astaroth.step``, or a bare closure over a
``DistributedDomain``) with the full detect → degrade → retry ladder
the reference library never had and production stencil codes
(PIConGPU, arXiv:1606.02862) treat as table stakes:

* **checkpoint** every ``ckpt_every`` steps (integrity sha256 in the
  meta record, transient-I/O retry with backoff), after a *blocking*
  health drain so poisoned state is never persisted;
* **watch** via the in-graph :class:`~.health.HealthSentinel` every
  ``check_every`` steps — async readback, the loop never stalls;
* **roll back** to the last good checkpoint when the sentinel trips
  (corrupt checkpoints fall back to older steps automatically), with
  bounded attempts and exponential backoff;
* **degrade** when retries at the current configuration are exhausted:
  drop ``exchange_every`` toward 1, then fall down the capability-aware
  ``pick_method`` priority list (PR 4's fallback, reused) — the caller
  supplies ``rebuild(config)`` to re-realize the engine;
* **preempt cleanly**: SIGTERM (a fleet scheduler reclaiming the host,
  or an injected :class:`~.faults.Preemption`) writes a final
  checkpoint tagged ``preempted`` and returns; the next
  ``run_resilient`` on the same directory resumes from it.

Everything lands in a JSON-serializable :class:`ResilienceReport`
event log — the CI chaos-smoke artifact.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import signal
import threading
import time
from typing import Callable, Dict, List, Optional

from ..analysis.recompile import (ASSERT_SINGLE_COMPILE_ENV,
                                  SingleCompileGuard)
from ..analysis.transfer import hot_loop_transfer_guard
from ..parallel.methods import (METHOD_PRIORITY, Method, method_runnable,
                                pick_method)
from ..utils.checkpoint import restore_domain, save_domain
from ..utils.logging import LOG_INFO, LOG_WARN
from ..utils.retry import retry
from .faults import FaultPlan
from .health import HealthSentinel, HealthStats


class ResilienceError(RuntimeError):
    """The run could not be kept alive: the sentinel tripped with no
    checkpoint to roll back to, or every retry and degradation was
    exhausted."""


@dataclasses.dataclass
class ResiliencePolicy:
    """Knobs of the resilient loop (see README "Resilience")."""

    check_every: int = 10       # sentinel probe cadence (steps)
    ckpt_every: int = 50        # checkpoint cadence (steps)
    max_retries: int = 3        # rollbacks per configuration
    base_delay: float = 0.05    # backoff seed (seconds), doubles
    save_attempts: int = 3      # transient-I/O retries per save
    max_to_keep: Optional[int] = 3   # checkpoint history depth
    window: int = 8             # sentinel sliding window (probes)
    growth_factor: float = 1e6  # max-abs growth trip factor
    degrade: bool = True        # walk the degradation ladder
    sleep: Callable[[float], None] = time.sleep  # injectable clock
    # megastep execution (parallel/megastep.py): fuse check_every-sized
    # campaign segments into ONE compiled program when the engine
    # provides a segment factory; probe_every sets the in-graph probe
    # cadence INSIDE a segment (1 = per-step trace rows, exact trip
    # location; the segment's final step is always probed)
    fuse_segments: bool = True
    probe_every: int = 1
    # performance observatory (observatory/attribution.py): pair every
    # dispatch's measured seconds/step (block_until_ready-fenced,
    # amortized over the segment's k steps) against the calibrated
    # cost-model prediction of the active plan, exported as
    # stencil_perf_model_error_ratio{entry,method,s}. After
    # drift_window consecutive segments whose ratio departs from its
    # calibrated reference by more than drift_tolerance (relative), a
    # perf_drift event is emitted; retune_on_drift additionally
    # invalidates the plan-cache record (plan_cache_path, default
    # cache) so the tuner re-measures — stale plans heal themselves
    attribute_perf: bool = True
    drift_tolerance: float = 0.5
    drift_window: int = 3
    retune_on_drift: bool = False
    plan_cache_path: Optional[str] = None
    # flight recorder (observatory/recorder.py): bounded black box
    # (recent events + spans + metrics + probe history) dumped
    # atomically into this directory on sentinel trip, degradation,
    # SIGTERM preemption (before the preemption checkpoint), and
    # unhandled dispatch error; None falls back to
    # $STENCIL_FLIGHT_RECORDER_DIR, empty/unset disarms
    flight_recorder_dir: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class StepConfig:
    """One rung of the degradation ladder: the exchange transport and
    temporal-blocking depth the engine should be rebuilt with."""

    method: Method
    exchange_every: int

    def key(self) -> str:
        return f"{self.method.name}[s={self.exchange_every}]"


def degradation_ladder(method: Method, exchange_every: int,
                       runnable: Optional[Callable[[Method], bool]] = None
                       ) -> List[StepConfig]:
    """Successively safer configurations: first halve the temporal-
    blocking depth down to per-step exchanges (deep halos stress the
    fabric hardest), then fall down the capability-aware
    ``pick_method`` priority list below the current transport.
    ``runnable`` is injectable for tests (defaults to the real
    capability probe)."""
    if runnable is None:
        runnable = method_runnable
    out: List[StepConfig] = []
    s = int(exchange_every)
    while s > 1:
        s //= 2
        out.append(StepConfig(method, s))
    live = [m for m in METHOD_PRIORITY if runnable(m)]
    if method in live:
        live = live[live.index(method) + 1:]
    out.extend(StepConfig(m, 1) for m in live)
    return out


@dataclasses.dataclass
class ResilienceReport:
    """What happened, machine-readable (the chaos-smoke CI artifact).

    Events flow through the unified telemetry schema
    (:class:`~stencil_tpu.telemetry.EventLog`): every record carries
    the run id, a monotonic sequence number, and the schema version —
    the same shape the campaign service logs, so one scraper reads
    both. The serializable ``events`` list is fed by a ``ListSink``;
    ``sinks`` (e.g. a ``JsonlSink``) fan out the same records live."""

    steps: int = 0
    rollbacks: int = 0
    save_retries: int = 0
    degradations: List[str] = dataclasses.field(default_factory=list)
    preempted: bool = False
    resumed_from: Optional[int] = None
    final_config: str = ""
    run_id: str = ""
    #: did the campaign run fused (megastep) dispatches? False when
    #: fusion was off by policy, the engine provided no segment
    #: factory, or the built path declined — ``fused_decline_reason``
    #: then says WHY (silent stepwise fallbacks used to be invisible)
    fused: bool = False
    fused_decline_reason: str = ""
    #: the machine-readable ``megastep.DECLINE_*`` vocabulary code
    #: behind ``fused_decline_reason`` (greppable cause taxonomy)
    fused_decline_code: str = ""
    events: List[Dict] = dataclasses.field(default_factory=list)

    def __post_init__(self) -> None:
        from ..telemetry import EventLog, ListSink
        self._elog = EventLog(run_id=self.run_id or None,
                              sinks=(ListSink(self.events),))
        self.run_id = self._elog.run_id
        self._tracer = None

    def add_sink(self, sink) -> None:
        self._elog.add_sink(sink)

    def bind_tracer(self, tracer) -> None:
        """Span-correlate report events: records emitted inside a span
        of ``tracer`` carry its id (the same run-id/span-id identity
        the campaign service logs — one scraper joins both)."""
        self._tracer = tracer

    def log(self, kind: str, **kw) -> None:
        span = (self._tracer.current_span_id()
                if self._tracer is not None else None)
        self._elog.emit(kind, span=span, **kw)

    def to_record(self) -> Dict:
        return dataclasses.asdict(self)

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_record(), f, indent=1)


def _current_config(dd) -> StepConfig:
    return StepConfig(pick_method(dd.methods), dd.exchange_every)


class _ResilientRun:
    """One ``run_resilient`` invocation (state bundled for clarity)."""

    def __init__(self, dd, step_fn, n_steps, policy, ckpt_dir, faults,
                 rebuild, extra_fn, on_restore, fields_fn,
                 pre_checkpoint, make_segment=None,
                 sentinel_factory=None, model_step_seconds=None,
                 model_bytes_per_step=None, perf_entry=None):
        self.dd = dd
        self.step_fn = step_fn
        self.n_steps = int(n_steps)
        self.policy = policy or ResiliencePolicy()
        self.ckpt_dir = ckpt_dir
        self.faults = faults
        self.rebuild = rebuild
        self.extra_fn = extra_fn
        self.on_restore = on_restore
        self.fields_fn = fields_fn
        self.pre_checkpoint = pre_checkpoint
        #: megastep factory: make_segment(k, probe_every, metrics) ->
        #: Segment or None (parallel/megastep.py); when present and
        #: policy.fuse_segments, the loop dispatches ONE fused program
        #: per health boundary instead of one jitted step per iteration
        self.make_segment = make_segment
        self._fused = (make_segment is not None
                       and self.policy.fuse_segments)
        #: one async checkpoint in flight: (step, field copies, extras)
        self._pending_save = None
        #: recompile watchdog (analysis/recompile.py): armed via
        #: STENCIL_ASSERT_SINGLE_COMPILE=1, raises if a fused segment
        #: program re-traces between dispatches
        self._compile_guard = (
            SingleCompileGuard()
            if os.environ.get(ASSERT_SINGLE_COMPILE_ENV) == "1"
            else None)
        #: custom sentinel builder (models whose health state is wider
        #: than dd.curr — e.g. the PIC particle lanes with the in-graph
        #: overflow column — supply one; step-metrics riding/rebasing
        #: is then the factory's business, not the driver's)
        self.sentinel_factory = sentinel_factory
        self.report = ResilienceReport()
        if faults is not None:
            faults.bind(self.report.log)
        self.sentinel = self._make_sentinel(dd)
        self.step = 0
        self.attempts = 0
        self.last_saved: Optional[int] = None
        self.ladder: Optional[List[StepConfig]] = None
        self._preempt = False
        # run-loop metrics (stable names, README "Observability"):
        # exported through the process-default telemetry registry
        from ..telemetry import get_registry, get_tracer
        reg = get_registry()
        self._tracer = get_tracer()
        # report events carry the span id of the enclosing run-loop
        # span, mirroring the service's span-correlated event log
        self.report.bind_tracer(self._tracer)
        self._m_steps = reg.counter(
            "stencil_run_steps_total",
            "steps advanced by resilient run loops — steps inside a "
            "fused megastep each count (one count per STEP, never per "
            "dispatch); replayed rollback windows included (work done, "
            "not net progress)")
        self._m_rollbacks = reg.counter(
            "stencil_run_rollbacks_total",
            "sentinel-tripped rollbacks")
        self._m_save_retries = reg.counter(
            "stencil_run_save_retries_total",
            "transient checkpoint-save retries")
        self._m_checkpoints = reg.counter(
            "stencil_run_checkpoints_total", "checkpoints written")
        self._m_degradations = reg.counter(
            "stencil_run_degradations_total",
            "configuration degradations taken")
        self._m_steps_per_s = reg.gauge(
            "stencil_run_steps_per_s",
            "steps/s of the last resilient run")
        self._m_bytes_per_step = reg.gauge(
            "stencil_run_bytes_per_step",
            "amortized exchange B/step (source=model: the analytic "
            "model the HLO cross-check pins; source=probe: harvested "
            "from the in-graph probe counters)")
        self._m_fused_dispatch = reg.counter(
            "stencil_run_fused_dispatch_total",
            "compiled-program dispatches by the resilient run loops, "
            "labeled fused=true (one megastep per count, covering k "
            "steps) or fused=false (one stepwise step dispatch per "
            "count) — a fleet reads the false series to see which "
            "campaigns still run stepwise and the fused_decline "
            "events to learn why")
        # seed the unlabeled counters so the exported surface carries
        # an explicit 0 baseline from birth (prometheus_client
        # semantics); "== 0" assertions then test a series that exists
        for c in (self._m_steps, self._m_rollbacks,
                  self._m_save_retries, self._m_checkpoints,
                  self._m_degradations):
            c.inc(0)
        for fused in ("true", "false"):
            self._m_fused_dispatch.inc(0, fused=fused)
        # performance observatory: model-vs-measured attribution of
        # every dispatch (observatory/attribution.py) and the bounded
        # flight recorder (observatory/recorder.py). The attributed
        # program is the SAME compiled fn — attribution is a host-side
        # wall clock; the observatory.attribution.* registry targets
        # pin the HLO identity
        self._perf_entry = perf_entry or "resilience"
        # the fused/stepwise verdict in the report: campaigns that run
        # stepwise must say so (and why) instead of silently falling
        # back — ResilienceReport.fused + fused_decline_reason + the
        # fused_decline event (a declining make_segment adds its own
        # reason at the first dispatch attempt)
        self.report.fused = self._fused
        if not self._fused:
            from ..parallel.megastep import (DECLINE_NO_FACTORY,
                                             DECLINE_POLICY_DISABLED)
            if make_segment is not None:
                self._note_fused_decline(
                    "fuse_segments disabled by policy",
                    code=DECLINE_POLICY_DISABLED)
            else:
                self._note_fused_decline(
                    "engine provides no fused-segment factory",
                    code=DECLINE_NO_FACTORY)
        self._model_step_seconds = model_step_seconds
        self._model_bytes_per_step = model_bytes_per_step
        self.attributor = (self._make_attributor()
                           if self.policy.attribute_perf else None)
        #: stepwise attribution window (accumulated dispatch seconds +
        #: base step): the stepwise loop attributes one
        #: check_every-sized WINDOW per observation — only step_fn
        #: dispatch time plus ONE fence at the health boundary is
        #: timed (blocking checkpoint saves, probe polls, and fault
        #: host work between steps are excluded, and the async-
        #: readback design of the stepwise loop survives attribution)
        self._att_window_s = 0.0
        self._att_window_base = None
        from ..observatory.recorder import ENV_FLIGHT_DIR, FlightRecorder
        self._flight_dir = (self.policy.flight_recorder_dir
                            or os.environ.get(ENV_FLIGHT_DIR) or None)
        self.flight = None
        if self._flight_dir:
            self.flight = FlightRecorder(run_id=self.report.run_id,
                                         registry=reg,
                                         tracer=self._tracer)
            self.report.add_sink(self.flight)
            # the attributor (built above) classified the link map
            # before the recorder existed — arm the black box with it
            self.flight.set_linkmap(getattr(self, "_link_summary",
                                            None))

    def _make_sentinel(self, dd,
                       rebase_step: Optional[int] = None,
                       prev=None) -> HealthSentinel:
        """A sentinel whose probe also carries the telemetry step
        metrics (sub-steps + model-exact wire bytes) on its ONE
        all-reduce — when the domain prices its exchange; plain
        otherwise. A degradation rebuild rebases the byte counter at
        ``rebase_step`` (the restore anchor, not the trip step: the
        rolled-back window re-executes under the NEW configuration and
        must be priced at its rate) so the new configuration's price
        applies only to steps it actually runs, never retroactively to
        traffic already sent. ``prev`` overrides the metrics block the
        rebase derives from (the finalize-after-restore path must
        rebase from the PRE-degrade block, not compound the
        provisional rebase)."""
        if self.sentinel_factory is not None:
            self._step_metrics = None
            return self.sentinel_factory(dd)
        from ..telemetry.probe import step_metrics_for
        if prev is None:
            prev = getattr(self, "_step_metrics", None)
        if prev is not None:
            if rebase_step is None:
                rebase_step = getattr(self, "step", 0)
            try:
                self._step_metrics = prev.rebased(dd, rebase_step)
            except Exception:  # noqa: BLE001 - new config unpriceable
                self._step_metrics = step_metrics_for(dd)
        else:
            self._step_metrics = step_metrics_for(dd)
        return HealthSentinel(dd, window=self.policy.window,
                              growth_factor=self.policy.growth_factor,
                              metrics=self._step_metrics)

    # -- performance observatory ----------------------------------------
    def _make_attributor(self):
        """A :class:`~stencil_tpu.observatory.PerfAttributor` for the
        CURRENT engine configuration, or None when no calibrated price
        exists (unsharded mesh, unpriceable geometry). The model price
        is the caller's override (PIC passes its migration+sweep
        figure) on the first build; a degradation rebuild re-derives
        from the rebuilt domain — the old figure priced a dead
        configuration."""
        from ..observatory.attribution import (PerfAttributor,
                                               model_step_seconds_for)
        model = self._model_step_seconds
        if model is None:
            model = model_step_seconds_for(self.dd)
        if not model:
            return None
        cfg = _current_config(self.dd)
        plan = getattr(self.dd, "plan", None)
        p = self.policy
        nbytes = self._model_bytes_per_step
        if nbytes is None:
            nbytes = (self._step_metrics.bytes_per_step
                      if getattr(self, "_step_metrics", None) is not None
                      else 0.0)
        # per-link attribution (observatory/linkmap.py): the modeled
        # traffic matrix classified against the deployed device order,
        # exported as stencil_link_bytes_per_step /
        # stencil_link_utilization_ratio next to the error ratio; the
        # flight recorder carries the same snapshot in incident dumps
        from ..observatory.linkmap import link_attribution_for
        link = link_attribution_for(self.dd)
        self._link_summary = link["summary"] if link else None
        if getattr(self, "flight", None) is not None:
            self.flight.set_linkmap(self._link_summary)
        return PerfAttributor(
            entry=self._perf_entry, method=cfg.method.name,
            exchange_every=cfg.exchange_every,
            model_step_seconds=model,
            model_bytes_per_step=float(nbytes),
            tolerance=p.drift_tolerance, window=p.drift_window,
            warmup=1,  # the first dispatch pays XLA compilation
            emit=self.report.log,
            on_drift=(self._on_perf_drift if p.retune_on_drift
                      else None),
            link_bytes_per_step=(link["bytes_per_step"] if link
                                 else None),
            link_peak_bytes_per_s=(link["peak_bytes_per_s"] if link
                                   else None),
            fingerprint=(plan.fingerprint if plan is not None else None))

    def _on_perf_drift(self, attrs: Dict) -> None:
        """``retune_on_drift``: the plan whose prediction the machine
        stopped matching is stale evidence — drop its plan-cache record
        so the next tune re-measures instead of serving the hit
        (shared hook: ``observatory.make_drift_invalidator``)."""
        from ..observatory.attribution import make_drift_invalidator
        make_drift_invalidator(self.policy.plan_cache_path,
                               self.report.log)(attrs)

    def _block_fields(self) -> None:
        import jax

        jax.block_until_ready(self._fields())

    def _attributed(self, k: int):
        """The timing context for one dispatch of ``k`` steps (a
        no-op when attribution is off/unpriceable)."""
        if self.attributor is None:
            return contextlib.nullcontext()
        return self.attributor.dispatch(k, self._block_fields,
                                        step=self.step + k)

    def _flight_dump(self, reason: str, **attrs) -> Optional[str]:
        from ..observatory.recorder import safe_dump
        return safe_dump(self.flight, self._flight_dir, reason,
                         step=self.step, **attrs)

    # -- helpers --------------------------------------------------------
    def _fields(self):
        return self.fields_fn() if self.fields_fn is not None \
            else self.dd.curr

    _UNSET = object()

    def _save(self, preempted: bool = False, fields=None,
              extra=_UNSET, at_step: Optional[int] = None) -> None:
        if fields is None and self.pre_checkpoint is not None:
            self.pre_checkpoint()
        if extra is self._UNSET:
            extra = self.extra_fn() if self.extra_fn is not None \
                else None
        step = self.step if at_step is None else int(at_step)
        meta_extra = {"preempted": preempted,
                      "completed_steps": step,
                      "config": _current_config(self.dd).key()}

        def attempt():
            if self.faults is not None:
                self.faults.maybe_fail_save(step)
            # attempts=1: THIS retry loop (policy clock, event-logged)
            # is the only one — no hidden nested retries inside
            save_domain(self.dd, self.ckpt_dir, step, extra=extra,
                        max_to_keep=self.policy.max_to_keep,
                        meta_extra=meta_extra, attempts=1,
                        fields=fields)

        def on_retry(k, e, delay):
            self.report.save_retries += 1
            self._m_save_retries.inc()
            self.report.log("save_retry", step=step, attempt=k,
                            error=f"{type(e).__name__}: {e}",
                            delay=delay)

        with self._tracer.span("checkpoint", step=step,
                               preempted=preempted):
            retry(attempt, attempts=self.policy.save_attempts,
                  base_delay=self.policy.base_delay,
                  retriable=(OSError,),
                  sleep=self.policy.sleep, on_retry=on_retry)
        self._m_checkpoints.inc()
        if self.faults is not None:
            self.faults.after_save(self.ckpt_dir, step)
        self.last_saved = step
        # a successful checkpoint is verified-healthy progress: bound
        # retries per INCIDENT, not per configuration lifetime —
        # independent transient faults days apart must not accumulate
        # toward forced degradation
        self.attempts = 0
        self.report.log("checkpoint", step=step, preempted=preempted)

    # -- async checkpoint offload (megastep mode) -----------------------
    def _save_async(self) -> None:
        """Enqueue a checkpoint of the CURRENT state without stalling
        the step pipeline: device copies of the fields (cheap, ride the
        device queue) are taken at the segment boundary so the live
        buffers can be donated to the next megastep; the orbax write
        runs once the copies report ``is_ready`` (polled each loop
        turn) — the EnsembleSnapshot pattern applied to checkpoints.
        Exactly one save is in flight; ordering is preserved by
        flushing before the next enqueue, any restore, preemption, and
        loop end."""
        import jax.numpy as jnp

        self._flush_pending_save()
        if self.pre_checkpoint is not None:
            self.pre_checkpoint()
        fields = {q: jnp.copy(v) for q, v in self.dd.curr.items()}
        extra = self.extra_fn() if self.extra_fn is not None else None
        if extra:
            extra = {k: jnp.copy(v) for k, v in extra.items()}
        self._pending_save = (self.step, fields, extra)

    def _poll_pending_save(self, block: bool = False) -> None:
        from .health import _is_ready
        ps = self._pending_save
        if ps is None:
            return
        step, fields, extra = ps
        if not block and not all(_is_ready(v)
                                 for v in fields.values()):
            return
        self._pending_save = None
        self._save(fields=fields, extra=extra, at_step=step)

    def _flush_pending_save(self) -> None:
        self._poll_pending_save(block=True)

    def _drain_probe(self) -> List[HealthStats]:
        """Blocking health verdict on the CURRENT state (used at
        checkpoint boundaries and loop end). Reuses an in-flight probe
        of this step — or a fused-trace row of it already harvested
        clean this turn — rather than paying a duplicate reduction."""
        if not self.sentinel.has_pending(self.step) and \
                getattr(self, "_last_clean_health", None) != self.step:
            self.sentinel.probe(self._fields(), self.step)
        results = self.sentinel.poll(block=True)
        self._observe_probes(results)
        for s in results:
            if not s.tripped:
                self._last_clean_health = s.step
        return [s for s in results if s.tripped]

    def _observe_probes(self, results: List[HealthStats]) -> None:
        """Export the in-graph counters the probes carried: the
        probe-observed amortized B/step next to the model's figure.
        They agree while one configuration runs (the probe's counter
        IS the model-exact byte price — the costmodel checker pins it
        against HLO); after a degradation the probe figure is the
        campaign-average across the configurations actually run."""
        if self.flight is not None:
            for stats in results:
                self.flight.record_probe(stats.to_record())
        if self._step_metrics is None:
            return
        for stats in results:
            if not stats.metrics:
                continue
            decoded = self._step_metrics.decode(stats.metrics)
            self._m_bytes_per_step.set(decoded["bytes_per_step_probe"],
                                       source="probe")

    def _restore(self) -> None:
        with self._tracer.span("restore"):
            step, extras = restore_domain(self.dd, self.ckpt_dir)
        if self.on_restore is not None:
            self.on_restore(extras)
        self.step = step
        pre_degrade = getattr(self, "_rebase_from", None)
        if pre_degrade is not None:
            # finalize the post-degradation byte rebase at the step the
            # restore ACTUALLY landed on: restore_domain may have
            # walked back past a corrupt last_saved checkpoint, and the
            # whole re-executed window must be priced at the degraded
            # configuration's rate
            self._rebase_from = None
            self.sentinel = self._make_sentinel(
                self.dd, rebase_step=step, prev=pre_degrade)
        self.sentinel.reset()
        self._last_clean_health = None
        # a rolled-back window is replay, not fresh progress: never
        # attribute wall time that spans the restore
        self._att_window_base = None
        self.report.log("restored", step=step)

    def _handle_trip(self, tripped: List[HealthStats]) -> None:
        # an in-flight async checkpoint (healthy by construction: it was
        # enqueued only after a clean blocking drain) completes FIRST —
        # before the attempt counter moves — so it can anchor the
        # rollback AND its attempts-reset lands where the stepwise
        # ordering puts it (the save preceded the faulting steps; it
        # must not forgive the attempt recorded for THIS trip)
        self._flush_pending_save()
        stats = tripped[0]
        self.report.rollbacks += 1
        self._m_rollbacks.inc()
        self.attempts += 1
        self.report.log("sentinel_tripped", step=stats.step,
                        reason=stats.reason,
                        stats=stats.to_record(),
                        attempt=self.attempts)
        LOG_WARN(f"health sentinel tripped at step {stats.step}: "
                 f"{stats.reason} (attempt {self.attempts}/"
                 f"{self.policy.max_retries})")
        if self.ckpt_dir is None:
            raise ResilienceError(
                f"sentinel tripped at step {stats.step} "
                f"({stats.reason}) and no ckpt_dir was given — "
                f"nothing to roll back to")
        if self.attempts > self.policy.max_retries:
            self._degrade_or_die(stats)  # resets attempts to 0
        self.policy.sleep(self.policy.base_delay
                          * (2 ** max(self.attempts - 1, 0)))
        self._restore()
        # the black box captures the WHOLE incident — trip, any
        # degradation, and the rollback it resolved into
        self._flight_dump("sentinel_trip", trip_step=stats.step,
                          trip_reason=stats.reason)

    def _degrade_or_die(self, stats: HealthStats) -> None:
        if self.ladder is None:
            cfg = _current_config(self.dd)
            self.ladder = degradation_ladder(cfg.method,
                                             cfg.exchange_every)
        # walk rungs until one actually realizes: capability is known
        # up front (method_runnable) but domain feasibility (uneven
        # shards, Boundary.NONE, temporal-depth limits) only surfaces
        # in the constructor — an infeasible rung is skipped, never
        # allowed to kill the recovery with a raw NotImplementedError
        while (self.policy.degrade and self.rebuild is not None
               and self.ladder):
            cfg = self.ladder.pop(0)
            LOG_WARN(f"degrading configuration to {cfg.key()} after "
                     f"repeated failures")
            try:
                built = self.rebuild(cfg)
                # a 3-tuple rebuild also rebuilds the fused-segment
                # factory (megastep mode): the degraded engine's
                # segments, not the dead configuration's, serve from
                # here on; a 2-tuple (legacy) drops to the stepwise
                # loop — never dispatch a stale fused program
                if len(built) == 3:
                    self.dd, self.step_fn, self.make_segment = built
                    self._fused = (self.make_segment is not None
                                   and self.policy.fuse_segments)
                else:
                    self.dd, self.step_fn = built
                    if self._fused:
                        LOG_WARN("rebuild() returned no segment "
                                 "factory; continuing stepwise")
                        self._fused = False
                        # the fallback is a reported fact, not a
                        # silence: fused: false + reason + event
                        from ..parallel.megastep import \
                            DECLINE_REBUILD_NO_FACTORY
                        self._note_fused_decline(
                            "rebuild() returned no segment factory "
                            "after degradation",
                            code=DECLINE_REBUILD_NO_FACTORY)
            except (NotImplementedError, ValueError) as e:
                self.report.log("degrade_rung_infeasible",
                                config=cfg.key(),
                                error=f"{type(e).__name__}: {e}")
                LOG_WARN(f"degradation rung {cfg.key()} is infeasible "
                         f"for this domain ({e}); trying the next")
                continue
            # rebase at the restore anchor: _handle_trip restores right
            # after this, and every step past the restored checkpoint
            # re-runs under the degraded configuration's byte price.
            # last_saved is the provisional anchor; _restore finalizes
            # it from the PRE-degrade metrics stashed here, because a
            # corrupt last_saved checkpoint can make the restore walk
            # back further
            self._rebase_from = self._step_metrics
            anchor = (self.last_saved if self.last_saved is not None
                      else getattr(self, "step", 0))
            self.sentinel = self._make_sentinel(self.dd,
                                                rebase_step=anchor)
            if self._step_metrics is not None:
                # the degraded configuration has a new per-step byte
                # price — keep the exported model figure current so the
                # model-vs-probe comparison stays honest mid-run
                self._m_bytes_per_step.set(
                    self._step_metrics.bytes_per_step, source="model")
            self.attempts = 0
            self.report.degradations.append(cfg.key())
            self._m_degradations.inc()
            self.report.log("degraded", config=cfg.key())
            if self.attributor is not None:
                # the degraded engine has a new model price and labels;
                # the caller's override (if any) priced the dead config
                self._model_step_seconds = None
                self._model_bytes_per_step = None
                self.attributor = self._make_attributor()
                self._att_window_base = None
            self._flight_dump("degraded", config=cfg.key())
            return
        raise ResilienceError(
            f"retries exhausted ({self.policy.max_retries}) at "
            f"step {stats.step}: {stats.reason}; no degradation "
            f"available")

    # -- megastep segmentation ------------------------------------------
    def _note_fused_decline(self, reason: str, model: str = "",
                            path: str = "", code: str = "") -> None:
        """Make a stepwise fallback VISIBLE: the report says
        ``fused: false`` with the reason AND its vocabulary code
        (``megastep.DECLINE_*``), the event log carries a
        ``fused_decline`` record, and the fleet counter's
        ``fused=false`` series accumulates the stepwise dispatches."""
        from ..parallel.megastep import DECLINE_NO_FACTORY

        self.report.fused = False
        self.report.fused_decline_reason = reason
        self.report.fused_decline_code = code or DECLINE_NO_FACTORY
        self.report.log("fused_decline",
                        model=model or self._perf_entry,
                        path=path, reason=reason,
                        code=self.report.fused_decline_code)

    def _next_seg_len(self) -> int:
        """Steps until the next host boundary: campaign end, the
        check_every health boundary, a checkpoint boundary, a scheduled
        host fault, or the unroll cap — the fused segment runs exactly
        that far in ONE dispatch."""
        from ..parallel.megastep import MAX_UNROLL
        p = self.policy
        cands = [self.n_steps - self.step, MAX_UNROLL]
        ce = max(int(p.check_every), 1)
        cands.append(ce - self.step % ce)
        if self.ckpt_dir is not None and p.ckpt_every > 0:
            cands.append(p.ckpt_every - self.step % p.ckpt_every)
        if self.faults is not None:
            nf = self.faults.next_host_step(self.step)
            if nf is not None:
                cands.append(nf - self.step)
        return max(1, min(c for c in cands if c > 0))

    def _dispatch_segment(self) -> bool:
        """Advance one fused megastep (ONE compiled dispatch for the
        whole sub-check_every span, probe trace in-graph). Returns
        False when the current engine configuration has no fused
        segment — the caller re-enters the loop stepwise."""
        k = self._next_seg_len()
        seg = self.make_segment(k, self.policy.probe_every,
                                self._step_metrics)
        if not seg:
            # a SegmentDecline (or legacy None): record the fallback
            # with its reason — fused: false in the report, a
            # fused_decline event, and the fused=false counter series
            reason = getattr(seg, "reason",
                             "engine has no fused-segment support for "
                             "this configuration")
            self._note_fused_decline(
                reason, model=getattr(seg, "model", ""),
                path=getattr(seg, "path", ""),
                code=getattr(seg, "code", ""))
            LOG_WARN(f"no fused-segment support for this configuration "
                     f"({reason}); continuing with the stepwise "
                     f"dispatch loop")
            self._fused = False
            return False
        base = self.step
        with self._tracer.span("megastep", steps=k, step=base):
            # one Perfetto box per COMPILED PROGRAM (the megastep span
            # also covers guard/bookkeeping overhead around it), timed
            # by the attributor — model-vs-measured attribution is a
            # host wall clock; the dispatched program is unchanged
            with self._tracer.span("segment.dispatch", k=k,
                                   check_every=self.policy.check_every,
                                   entry=self._perf_entry):
                with self._attributed(k):
                    # the hot-loop dataflow contract, enforced at
                    # runtime: the fused dispatch moves NOTHING
                    # implicitly between host and device (the probe
                    # trace stays on device, the metric base vec is an
                    # explicit replicated device_put) — see
                    # analysis/transfer.py; STENCIL_ALLOW_TRANSFERS=1
                    # opts out
                    with hot_loop_transfer_guard():
                        trace = seg.run(base)
        if self._compile_guard is not None:
            self._compile_guard.observe(seg.fn, "megastep segment")
        self.step += k
        self.report.steps = self.step
        self._m_steps.inc(k)
        self._m_fused_dispatch.inc(fused="true")
        self.sentinel.observe_segment(trace.array, trace.abs_steps)
        return True

    # -- the loop -------------------------------------------------------
    def run(self) -> ResilienceReport:
        try:
            with self._tracer.span("resilience.run",
                                   run=self.report.run_id,
                                   n_steps=self.n_steps):
                return self._run()
        except Exception as e:
            # unhandled dispatch/recovery error: the black box is the
            # post-mortem (the raise still propagates unchanged)
            self._flight_dump("unhandled_error",
                              error=f"{type(e).__name__}: {e}")
            raise

    def _run(self) -> ResilienceReport:
        policy = self.policy
        if self._step_metrics is not None:
            self._m_bytes_per_step.set(
                self._step_metrics.bytes_per_step, source="model")
        t_start = time.perf_counter()
        steps_at_start = self.step
        if self.ckpt_dir is not None:
            try:
                self._restore()
                self.report.resumed_from = self.step
                LOG_INFO(f"resuming from checkpoint step {self.step}")
            except FileNotFoundError:
                self._save()  # step 0: the rollback anchor
        handler_installed = False
        prev_handler = None
        if threading.current_thread() is threading.main_thread():
            prev_handler = signal.signal(
                signal.SIGTERM, lambda *_: setattr(self, "_preempt",
                                                   True))
            handler_installed = True
        try:
            while True:
                self._poll_pending_save()
                if self._preempt:
                    self._flush_pending_save()
                    # black box BEFORE the preemption checkpoint: if
                    # the final save itself dies, the incident record
                    # already exists on disk
                    self._flight_dump("preempt")
                    if self.ckpt_dir is not None:
                        # same invariant as periodic checkpoints:
                        # poisoned state must never be persisted — if
                        # the drain trips, skip the save and let the
                        # last good checkpoint anchor the resume
                        tripped = self._drain_probe()
                        if tripped:
                            self.report.log(
                                "preempt_checkpoint_skipped",
                                step=self.step,
                                reason=tripped[0].reason)
                            LOG_WARN(
                                f"preempted at step {self.step} with "
                                f"unhealthy state ({tripped[0].reason})"
                                f"; NOT checkpointing it — resume will "
                                f"restore step {self.last_saved}")
                        else:
                            self._save(preempted=True)
                    self.report.preempted = True
                    self.report.log("preempted", step=self.step)
                    LOG_WARN(f"preempted at step {self.step}; exiting "
                             f"cleanly")
                    break
                if self.step >= self.n_steps:
                    self._flush_pending_save()
                    if self.last_saved == self.step:
                        break  # this step already drained + saved
                    tripped = self._drain_probe()
                    if tripped:
                        self._handle_trip(tripped)
                        continue
                    if self.ckpt_dir is not None:
                        self._save()
                    break
                if self._fused:
                    # megastep mode: ONE compiled dispatch to the next
                    # host boundary; the probe trace rides in-graph
                    if not self._dispatch_segment():
                        continue  # no fused support: retry stepwise
                    if self.faults is not None:
                        mutated = self.faults.on_step(
                            self.dd, self.step, self._fields())
                        if mutated:
                            # the in-graph trace predates the host
                            # injection: re-probe the poisoned fields
                            # so detection matches the stepwise loop —
                            # BEFORE any preempt drain can mistake the
                            # stale clean trace row for current health
                            self._last_clean_health = None
                            self.sentinel.probe(self._fields(),
                                                self.step)
                        if self._preempt:
                            continue  # SIGTERM landed at the boundary
                else:
                    att = self.attributor
                    if att is not None:
                        if self._att_window_base is None:
                            self._att_window_base = self.step
                            self._att_window_s = 0.0
                        t0 = time.perf_counter()
                        self.step_fn()
                        self._att_window_s += time.perf_counter() - t0
                    else:
                        self.step_fn()
                    self.step += 1
                    self.report.steps = self.step
                    self._m_steps.inc()
                    self._m_fused_dispatch.inc(fused="false")
                    if att is not None \
                            and self.step % policy.check_every == 0:
                        # boundary-amortized: the accumulated step
                        # dispatch time plus ONE fence per check_every
                        # window (the fused path's k-step
                        # amortization, mirrored) — never a fence per
                        # step, and never the saves/probes/fault host
                        # work that run between steps
                        t0 = time.perf_counter()
                        self._block_fields()
                        self._att_window_s += time.perf_counter() - t0
                        att.observe(self.step - self._att_window_base,
                                    self._att_window_s, step=self.step)
                        self._att_window_base = None
                    if self.faults is not None:
                        # faults hit the LIVE fields — the same dict
                        # the sentinel probes (interior-resident fast
                        # paths keep their state outside dd.curr)
                        self.faults.on_step(self.dd, self.step,
                                            self._fields())
                    if self._preempt:
                        continue  # SIGTERM landed during the step
                    if self.step % policy.check_every == 0 and not (
                            self.ckpt_dir is not None
                            and self.step % policy.ckpt_every == 0):
                        # checkpoint boundaries probe via the blocking
                        # drain below — one reduction per step, not two
                        self.sentinel.probe(self._fields(), self.step)
                results = self.sentinel.poll()
                self._observe_probes(results)
                for s in results:
                    if not s.tripped:
                        self._last_clean_health = s.step
                tripped = [s for s in results if s.tripped]
                if tripped:
                    self._handle_trip(tripped)
                    continue
                ckpt_due = (self.ckpt_dir is not None
                            and self.step % policy.ckpt_every == 0)
                if ckpt_due:
                    tripped = self._drain_probe()
                    if tripped:
                        self._handle_trip(tripped)
                        continue
                    if self._fused:
                        # async host offload: the TPU starts the next
                        # segment while orbax drains boundary copies
                        self._save_async()
                    else:
                        self._save()
        finally:
            if handler_installed:
                signal.signal(signal.SIGTERM,
                              prev_handler if prev_handler is not None
                              else signal.SIG_DFL)
            if self._pending_save is not None:
                # best-effort durability on abnormal exits: never mask
                # the in-flight exception with a failing late save
                try:
                    self._flush_pending_save()
                except Exception as e:  # noqa: BLE001
                    LOG_WARN(f"in-flight checkpoint lost on exit: "
                             f"{type(e).__name__}: {e}")
        self.report.steps = self.step
        self.report.final_config = _current_config(self.dd).key()
        elapsed = time.perf_counter() - t_start
        # steps THIS invocation advanced (a resume starts mid-campaign)
        done = self.step - max(steps_at_start,
                               self.report.resumed_from or 0)
        if done > 0 and elapsed > 0:
            self._m_steps_per_s.set(done / elapsed)
        return self.report


def run_resilient(dd, step_fn: Callable[[], None], n_steps: int,
                  policy: Optional[ResiliencePolicy] = None,
                  ckpt_dir: Optional[str] = None,
                  faults: Optional[FaultPlan] = None,
                  rebuild: Optional[Callable] = None,
                  extra_fn: Optional[Callable[[], Optional[Dict]]] = None,
                  on_restore: Optional[Callable[[Dict], None]] = None,
                  fields_fn: Optional[Callable[[], Dict]] = None,
                  pre_checkpoint: Optional[Callable[[], None]] = None,
                  make_segment: Optional[Callable] = None,
                  sentinel_factory: Optional[Callable] = None,
                  model_step_seconds: Optional[float] = None,
                  model_bytes_per_step: Optional[float] = None,
                  perf_entry: Optional[str] = None
                  ) -> ResilienceReport:
    """Drive ``step_fn`` for ``n_steps`` steps with health sentinels,
    periodic integrity-checked checkpoints, rollback-retry recovery,
    optional configuration degradation, and clean SIGTERM preemption.

    ``dd``: the realized :class:`~stencil_tpu.distributed.
    DistributedDomain` whose ``curr`` fields ARE the run state.
    ``step_fn()``: advance the state by one step (e.g. a model's
    ``step`` bound method). ``ckpt_dir``: checkpoint directory; when
    None the sentinel still watches but a trip raises (watchdog-only
    mode). ``rebuild(config)``: re-realize the engine at a degraded
    :class:`StepConfig`, returning ``(dd, step_fn)`` — required for the
    degradation ladder. ``extra_fn``/``on_restore``: checkpoint and
    reinstall auxiliary state (RK accumulators). ``fields_fn``: the
    dict the sentinel probes (defaults to ``dd.curr``).
    ``pre_checkpoint``: flush hook run before every save (fast paths
    sync interior-resident state).

    ``make_segment(k, probe_every, metrics)``: the megastep factory
    (``parallel/megastep.py``) — when given (the model entry points
    pass theirs) and ``policy.fuse_segments`` is on (default), the loop
    dispatches ONE fused program per health boundary: ``k`` steps plus
    the in-graph probe trace, state donated end-to-end, checkpoints
    offloaded asynchronously from boundary copies. ``rebuild`` may
    return ``(dd, step_fn, make_segment)`` so a degradation rebuilds
    the fused segment too (a 2-tuple falls back to stepwise).

    ``sentinel_factory(dd)``: build the health sentinel instead of the
    driver's default ``HealthSentinel(dd, ...)`` — models whose live
    state is wider than the domain's registered fields (PIC probes the
    particle lanes and carries the in-graph migration-overflow column)
    supply one; telemetry step-metrics riding is then the factory's
    responsibility.

    ``model_step_seconds``/``model_bytes_per_step``/``perf_entry``:
    the performance observatory's attribution inputs — the calibrated
    cost-model prediction of seconds/step and modeled wire B/step
    (models whose wire bill the generic exchange model cannot see,
    like PIC's migration ring, pass their own; None derives both from
    ``dd``) and the ``entry`` label of the exported
    ``stencil_perf_model_error_ratio{entry,method,s}`` gauges.

    Returns a :class:`ResilienceReport`; if it says ``preempted``,
    rerun with the same ``ckpt_dir`` to resume. If a run was previously
    preempted mid-campaign, the same call resumes it automatically."""
    return _ResilientRun(dd, step_fn, n_steps, policy, ckpt_dir, faults,
                         rebuild, extra_fn, on_restore, fields_fn,
                         pre_checkpoint, make_segment=make_segment,
                         sentinel_factory=sentinel_factory,
                         model_step_seconds=model_step_seconds,
                         model_bytes_per_step=model_bytes_per_step,
                         perf_entry=perf_entry).run()
