"""In-graph health sentinels: on-device divergence detection.

A week-long stencil campaign that NaNs at hour 30 and keeps burning the
fleet until hour 168 is the expensive failure mode; production codes
(PIConGPU, arXiv:1606.02862) treat in-loop health as a first-class
subsystem. The sentinel here is a fused, jitted probe that rides the
existing step loop:

* per quantity, two scalars are reduced on-device — the count of
  non-finite cells and the max |finite| value — stacked into one small
  ``(2, n_quantities)`` float32 vector;
* ONE ``lax.pmax`` over all mesh axes makes the vector globally
  consistent. It lowers to exactly one small ``stablehlo.all_reduce``
  and nothing else — proven by the ``resilience.health.*`` stencil-lint
  registry targets, so the probe can never smuggle hidden collectives
  into the step program. (A max-reduce serves both rows: "any shard
  saw a non-finite cell" is ``max(per-shard counts) > 0``.)
* readback is asynchronous: ``probe()`` only enqueues the tiny device
  computation; ``poll()`` harvests results whose buffers are already
  on host (``jax.Array.is_ready``), so the dispatch pipeline is never
  stalled by the watchdog. ``poll(block=True)`` drains — the driver
  does that only at checkpoint boundaries, where it must know the
  state is healthy before persisting it.

The probe reads the PADDED fields (halos included): a corrupted halo
region — e.g. a poisoned exchange — trips the sentinel even when the
next exchange would overwrite it.

The divergence predicate (host-side, on harvested stats):
``non-finite count > 0``, or max-abs growth by more than
``growth_factor`` over a sliding window of recent healthy probes.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

#: rows of the probe vector
ROW_NONFINITE = 0
ROW_MAX_ABS = 1


def probe_shard(fields: Dict[str, jnp.ndarray],
                axis_names: Sequence[str] = ("z", "y", "x"),
                extra: Optional[Dict[str, jnp.ndarray]] = None
                ) -> jnp.ndarray:
    """Per-shard health stats inside ``shard_map``: a ``(2, n)`` f32
    vector — row 0 the non-finite cell count, row 1 the max |finite|
    value — made globally consistent by ONE ``pmax`` over
    ``axis_names`` (one small all-reduce on the wire, nothing else).
    Quantity order is the dict's iteration order.

    ``extra`` (telemetry): named scalar step metrics appended as
    additional columns (the scalar in BOTH rows) BEFORE the single
    pmax, so in-graph counters ride the probe's existing all-reduce —
    the instrumented vector is ``(2, n + len(extra))`` and the
    collective count is unchanged (pinned by the ``telemetry.*``
    stencil-lint registry targets; ``bad_probe_metrics.py`` is the
    reduce-it-separately negative control). Max-reduction semantics:
    replicated metrics come back exact; per-shard metrics come back as
    the mesh max."""
    cols = []
    for q in fields:
        p = fields[q]
        finite = jnp.isfinite(p)
        nonfinite = jnp.sum(~finite).astype(jnp.float32)
        max_abs = jnp.max(
            jnp.where(finite, jnp.abs(p),
                      jnp.zeros_like(p))).astype(jnp.float32)
        cols.append(jnp.stack([nonfinite, max_abs]))
    for m in (extra or {}):
        v = jnp.asarray(extra[m]).astype(jnp.float32).reshape(())
        cols.append(jnp.stack([v, v]))
    vec = jnp.stack(cols, axis=1)
    if axis_names:
        vec = jax.lax.pmax(vec, tuple(axis_names))
    return vec


def make_probe(mesh, names: Sequence[str],
               extra_names: Sequence[str] = ()):
    """The jitted whole-mesh probe: ``fn(fields) -> (2, len(names))``
    replicated f32 stats for the named quantities (order pinned by
    ``names``). Shape-polymorphic across retraces, so padded and
    interior-resident field sets both work.

    With ``extra_names``, the probe becomes ``fn(fields, metrics_vec)
    -> (2, len(names) + len(extra_names))``: ``metrics_vec`` is a
    replicated f32 ``(len(extra_names),)`` vector of step metrics that
    ride the same single all-reduce (see :func:`probe_shard`)."""
    names = list(names)
    extras = list(extra_names)
    spec = {q: P("z", "y", "x") for q in names}

    if extras:
        def shard_m(fields, vec):
            return probe_shard(
                {q: fields[q] for q in names},
                extra={m: vec[i] for i, m in enumerate(extras)})

        sm = jax.shard_map(shard_m, mesh=mesh, in_specs=(spec, P()),
                           out_specs=P(), check_vma=False)
        return jax.jit(sm)

    def shard(fields):
        return probe_shard({q: fields[q] for q in names})

    sm = jax.shard_map(shard, mesh=mesh, in_specs=(spec,),
                       out_specs=P(), check_vma=False)
    return jax.jit(sm)


@dataclasses.dataclass
class HealthStats:
    """One harvested probe result plus the divergence verdict.

    ``metrics`` holds any telemetry step-metric columns that rode the
    probe (empty on uninstrumented probes)."""

    step: int
    nonfinite: Dict[str, int]
    max_abs: Dict[str, float]
    tripped: bool = False
    reason: str = ""
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)

    def to_record(self) -> Dict:
        rec = {"step": self.step, "nonfinite": dict(self.nonfinite),
               "max_abs": dict(self.max_abs), "tripped": self.tripped,
               "reason": self.reason}
        if self.metrics:
            rec["metrics"] = dict(self.metrics)
        return rec


def _is_ready(arr) -> bool:
    try:
        return bool(arr.is_ready())
    except AttributeError:  # pragma: no cover - older jax: block
        return True


class HealthSentinel:
    """The step loop's watchdog over a realized ``DistributedDomain``.

    ``probe(fields, step)`` enqueues the on-device reduction (async —
    returns immediately); ``poll()`` harvests ready results and
    evaluates the divergence predicate; :attr:`tripped` holds the first
    unhealthy result until :meth:`reset` (which the recovery driver
    calls after rolling back).
    """

    def __init__(self, dd, window: int = 8,
                 growth_factor: float = 1e6, metrics=None,
                 probe_fn=None, names: Optional[Sequence[str]] = None,
                 extra_names: Optional[Sequence[str]] = None) -> None:
        self.names = list(names) if names is not None else list(dd._names)
        self.window = int(window)
        self.growth_factor = float(growth_factor)
        #: telemetry step-metrics provider (``.names`` +
        #: ``.values(step) -> (k,) f32``), e.g. :class:`~stencil_tpu.
        #: telemetry.probe.StepMetrics` — its counters ride the probe's
        #: one all-reduce (no extra collectives)
        self._metrics = metrics
        #: custom probe program ``probe_fn(fields, step) -> (2, n+k)``
        #: (models with non-field state — e.g. the PIC particle lanes
        #: and their IN-GRAPH migration-overflow column — supply their
        #: own; ``extra_names`` labels the k trailing columns, which
        #: :meth:`poll` decodes into ``HealthStats.metrics`` exactly
        #: like telemetry step metrics)
        self._custom_probe = probe_fn
        if probe_fn is not None:
            if metrics is not None:
                raise ValueError("pass either metrics= (host-side "
                                 "columns) or probe_fn= (in-graph "
                                 "columns), not both")
            self._probe_fn = probe_fn
            self._extra_names = tuple(extra_names or ())
        else:
            self._extra_names = (tuple(metrics.names)
                                 if metrics is not None else ())
            self._probe_fn = make_probe(dd.mesh, self.names,
                                        extra_names=self._extra_names)
        self._pending: Deque[Tuple[int, jnp.ndarray]] = deque()
        self._history: Dict[str, Deque[float]] = {
            q: deque(maxlen=self.window) for q in self.names}
        self._tripped: Optional[HealthStats] = None

    # -- dispatch side --------------------------------------------------
    def probe(self, fields: Dict[str, jnp.ndarray], step: int) -> None:
        """Enqueue one health probe of ``fields`` at ``step`` (does not
        block; the reduction rides the device queue)."""
        if self._custom_probe is not None:
            self._pending.append(
                (step, self._custom_probe(dict(fields), step)))
            return
        if self._metrics is not None:
            self._pending.append(
                (step, self._probe_fn(dict(fields),
                                      self._metrics.values(step))))
            return
        self._pending.append((step, self._probe_fn(dict(fields))))

    def observe_segment(self, trace, steps: Sequence[int]) -> None:
        """Enqueue a fused-segment probe trace (``parallel/megastep``):
        ``trace`` stacks one probe row per entry of ``steps`` (campaign
        step numbers, oldest first). Rows ride the device queue exactly
        like individual probes — ``poll`` expands them, oldest row
        first, through the same divergence predicate, so the driver can
        locate the exact tripped step inside the segment without
        replaying it."""
        self._pending.append((tuple(int(s) for s in steps), trace))

    def has_pending(self, step: int) -> bool:
        """True when a probe of ``step`` is already in flight — as a
        single enqueued probe or as a row of a fused-segment trace (the
        driver avoids double-probing checkpoint-boundary steps)."""
        for s, _ in self._pending:
            if step in (s if isinstance(s, tuple) else (s,)):
                return True
        return False

    # -- harvest side ---------------------------------------------------
    def poll(self, block: bool = False) -> List[HealthStats]:
        """Harvest completed probes (all of them when ``block``),
        oldest first, evaluating the divergence predicate on each.
        Fused-segment traces expand into one result per probe row."""
        out: List[HealthStats] = []
        while self._pending:
            step, arr = self._pending[0]
            if not block and not _is_ready(arr):
                break
            self._pending.popleft()
            host = np.asarray(arr)
            if isinstance(step, tuple):
                for j, s in enumerate(step):
                    out.append(self._evaluate(s, host[j]))
            else:
                out.append(self._evaluate(step, host))
        return out

    @property
    def tripped(self) -> Optional[HealthStats]:
        """The first unhealthy probe since the last :meth:`reset`."""
        return self._tripped

    def reset(self) -> None:
        """Forget pending probes, history, and the tripped verdict —
        the state was rolled back; stale stats describe a dead world."""
        self._pending.clear()
        for h in self._history.values():
            h.clear()
        self._tripped = None

    # -- predicate ------------------------------------------------------
    def _evaluate(self, step: int, host: np.ndarray) -> HealthStats:
        nonfinite = {q: int(host[ROW_NONFINITE, i])
                     for i, q in enumerate(self.names)}
        max_abs = {q: float(host[ROW_MAX_ABS, i])
                   for i, q in enumerate(self.names)}
        stats = HealthStats(step, nonfinite, max_abs)
        if self._extra_names:
            n = len(self.names)
            stats.metrics = {m: float(host[ROW_NONFINITE, n + i])
                             for i, m in enumerate(self._extra_names)}
        bad_nf = [q for q, n in nonfinite.items() if n > 0]
        if bad_nf:
            stats.tripped = True
            stats.reason = (f"non-finite cells in {bad_nf} "
                            f"({ {q: nonfinite[q] for q in bad_nf} })")
        else:
            grown = []
            for q in self.names:
                hist = self._history[q]
                if hist:
                    baseline = min(hist)
                    if baseline > 0 and \
                            max_abs[q] > self.growth_factor * baseline:
                        grown.append(q)
            if grown:
                stats.tripped = True
                stats.reason = (f"max-abs grew more than "
                                f"x{self.growth_factor:g} over the "
                                f"window for {grown}")
            else:
                for q in self.names:
                    self._history[q].append(max_abs[q])
        if stats.tripped and self._tripped is None:
            self._tripped = stats
        return stats
