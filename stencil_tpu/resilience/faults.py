"""Deterministic fault injection: every recovery path is rehearsable.

A recovery path that has never run is a bug that hasn't happened yet.
This module makes the failure modes of a long campaign *injectable* —
seeded, step-addressed, CPU-runnable — so tier-1 tests (and the CI
chaos-smoke stage) pin rollback, retry, fallback, and preemption
behavior deterministically, the way TEMPI (arXiv:2012.14363) rehearses
its interposed degradation paths.

Fault classes (all dataclasses on a :class:`FaultPlan`):

* :class:`NaNInjection` — poison an interior cell of a chosen shard at
  a chosen step (a compute blow-up).
* :class:`HaloCorruption` — poison a halo (pad) cell post-step (a
  poisoned exchange; the sentinel probes padded fields exactly so this
  is caught even though the next exchange would overwrite it).
* :class:`ParticleLoss` — corrupt live particle records of a chosen
  shard (NaN a SoA lane; the PIC analog of lost particle memory —
  recovery restores the particle checkpoint extras).
* :class:`TransientSaveFailure` — the next orbax save raises
  ``IOError`` for the first N attempts (an NFS blip mid-checkpoint).
* :class:`CheckpointCorruption` — after checkpoint ``step`` lands on
  disk, truncate or bit-flip one of its data files (bit-rot; restore
  must fall back to an older step).
* :class:`Preemption` — deliver a real ``SIGTERM`` to this process at
  a chosen step (the fleet scheduler reclaiming the host).

Fleet-level fault classes (consumed by :class:`~..serving.fleet.
Fleet` rather than the single-process resilience driver; ``step`` is
the fleet serving ROUND, not a member step):

* :class:`ReplicaCrash` — hard-kill one replica mid-batch: its
  in-RAM lanes and unresolved handles are lost; recovery must come
  from the per-tenant checkpoint namespaces on the shared root.
* :class:`SlowReplica` — degrade one replica: the fleet's ladder
  drains it, reshards its tenants to survivors, and readmits it at
  ``recover_step``.
* :class:`AdmissionFlood` — a burst of low-priority junk requests
  that must be SHED loudly (request_shed events + counter), never
  allowed to starve protected tenants.

Each event fires at most ``repeat`` times, so a transient fault
disappears on the retry pass while a persistent one (``repeat`` large)
keeps tripping until the driver degrades the configuration.
"""

from __future__ import annotations

import dataclasses
import os
import signal
from pathlib import Path
from typing import Callable, List, Optional, Tuple

from ..utils.logging import LOG_WARN

LogFn = Callable[..., None]


def _noop_log(kind: str, **kw) -> None:  # pragma: no cover - default
    pass


@dataclasses.dataclass
class NaNInjection:
    """Write NaN into the interior center of shard ``shard`` of
    ``quantity`` (first registered quantity when None) right after step
    ``step`` completes."""

    step: int
    quantity: Optional[str] = None
    shard: Tuple[int, int, int] = (0, 0, 0)
    repeat: int = 1
    fired: int = 0

    def due(self, step: int) -> bool:
        return step == self.step and self.fired < self.repeat

    def fire(self, dd, log: LogFn, fields=None) -> None:
        self.fired += 1
        fields = dd.curr if fields is None else fields
        q = self.quantity or dd._names[0]
        z, y, x = _shard_cell(dd, self.shard, interior=True,
                              arr=fields[q])
        fields[q] = fields[q].at[z, y, x].set(float("nan"))
        log("fault_nan", step=self.step, quantity=q,
            shard=list(self.shard), cell=[z, y, x])


@dataclasses.dataclass
class HaloCorruption:
    """Write NaN into a halo (pad) cell of shard ``shard`` after step
    ``step`` — the signature of a poisoned exchange."""

    step: int
    quantity: Optional[str] = None
    shard: Tuple[int, int, int] = (0, 0, 0)
    repeat: int = 1
    fired: int = 0

    def due(self, step: int) -> bool:
        return step == self.step and self.fired < self.repeat

    def fire(self, dd, log: LogFn, fields=None) -> None:
        self.fired += 1
        fields = dd.curr if fields is None else fields
        q = self.quantity or dd._names[0]
        cell = _shard_cell(dd, self.shard, interior=False,
                           arr=fields[q])
        if cell is None:
            LOG_WARN("HaloCorruption: the live fields carry no halo "
                     "pads (radius 0 or interior-resident fast path); "
                     "fault is a no-op")
            return
        z, y, x = cell
        fields[q] = fields[q].at[z, y, x].set(float("nan"))
        log("fault_halo", step=self.step, quantity=q,
            shard=list(self.shard), cell=[z, y, x])


@dataclasses.dataclass
class ParticleLoss:
    """Corrupt ``count`` live particle records of shard ``shard``
    after step ``step`` (the PIC analog of a lost/rotted memory lane):
    the ``quantity`` lane of the chosen slots is set to NaN. Detection
    is guaranteed two ways on the next probe — the lane itself is
    probed non-finite by the PIC sentinel, and the next deposition
    scatters the NaN charge into ``rho``. Recovery must restore the
    particle lanes from the checkpoint extras and end bitwise-equal to
    the fault-free run.

    The live field dict must carry the particle SoA lanes (the PIC
    model's ``fields_fn`` contract) and the domain must expose
    ``particle_capacity`` (``models/pic.py`` stamps it) so the shard's
    slot block can be located under the ``P(('z','y','x'))`` layout."""

    step: int
    count: int = 1
    shard: Tuple[int, int, int] = (0, 0, 0)
    quantity: str = "q"
    repeat: int = 1
    fired: int = 0

    def due(self, step: int) -> bool:
        return step == self.step and self.fired < self.repeat

    def fire(self, dd, log: LogFn, fields=None) -> None:
        import numpy as np
        self.fired += 1
        cap = getattr(dd, "particle_capacity", None)
        if fields is None or cap is None or self.quantity not in fields:
            LOG_WARN("ParticleLoss: no particle state on this domain "
                     "(particle_capacity / particle lanes absent); "
                     "fault is a no-op")
            return
        bx, by, bz = self.shard
        dim = dd.placement.dim()
        base = ((bz * dim.y + by) * dim.x + bx) * cap
        valid = fields.get("valid")
        if valid is not None:
            live = np.nonzero(np.asarray(valid)[base:base + cap])[0]
            slots = [int(base + s) for s in live[:self.count]]
        else:
            slots = [int(base + s) for s in range(self.count)]
        if not slots:
            LOG_WARN(f"ParticleLoss: shard {self.shard} holds no live "
                     f"particles at step {self.step}; fault is a no-op")
            return
        arr = fields[self.quantity]
        for s in slots:
            arr = arr.at[s].set(float("nan"))
        fields[self.quantity] = arr
        log("fault_particle_loss", step=self.step, quantity=self.quantity,
            shard=list(self.shard), slots=slots)


@dataclasses.dataclass
class TransientSaveFailure:
    """The checkpoint save at step ``step`` raises ``IOError`` for its
    first ``failures`` attempts, then succeeds (exercises the retry/
    backoff path without touching the filesystem)."""

    step: int
    failures: int = 2
    fired: int = 0

    def maybe_raise(self, step: int, log: LogFn) -> None:
        if step == self.step and self.fired < self.failures:
            self.fired += 1
            log("fault_save_ioerror", step=step, attempt=self.fired)
            raise IOError(
                f"injected transient save failure "
                f"{self.fired}/{self.failures} at step {step}")


@dataclasses.dataclass
class CheckpointCorruption:
    """After checkpoint ``step`` is written, corrupt one of its data
    files on disk: ``mode='truncate'`` halves it, ``mode='bitflip'``
    flips one seeded byte. Restore must detect either (orbax/
    tensorstore error or integrity sha256 mismatch) and fall back."""

    step: int
    mode: str = "truncate"
    repeat: int = 1
    fired: int = 0

    def due(self, step: int) -> bool:
        return step == self.step and self.fired < self.repeat

    def fire(self, directory: str, step: int, rng, log: LogFn) -> None:
        self.fired += 1
        targets = _state_data_files(directory, step)
        if not targets:  # pragma: no cover - layout drift guard
            LOG_WARN(f"CheckpointCorruption: no data file under "
                     f"{directory}/{step}; fault is a no-op")
            return
        for target in targets:
            data = bytearray(target.read_bytes())
            if self.mode == "truncate":
                target.write_bytes(bytes(data[:max(len(data) // 2, 1)]))
            elif self.mode == "bitflip":
                i = int(rng.integers(0, len(data)))
                data[i] ^= 0xFF
                target.write_bytes(bytes(data))
            else:
                raise ValueError(f"unknown corruption mode {self.mode!r}")
        log("fault_ckpt_corruption", step=step, mode=self.mode,
            files=[str(t) for t in targets])


@dataclasses.dataclass
class Preemption:
    """Deliver ``SIGTERM`` to this process after step ``step`` — the
    driver's handler turns it into a final 'preempted' checkpoint and a
    clean exit, exactly like a fleet scheduler reclaiming the host."""

    step: int
    fired: int = 0

    def due(self, step: int) -> bool:
        return step == self.step and self.fired < 1

    def fire(self, log: LogFn) -> None:
        self.fired += 1
        log("fault_preemption", step=self.step)
        os.kill(os.getpid(), signal.SIGTERM)


@dataclasses.dataclass
class ReplicaCrash:
    """Hard-kill replica ``replica`` during the fleet round ``step``:
    the fleet arms the replica's crash hook so the kill lands at
    member step ``at_member_step`` INSIDE its next batch (after that
    boundary's checkpoints — state newer than the last periodic
    checkpoint is genuinely lost). No handles resolve, nothing is
    checkpointed at the kill point: recovery must re-admit the
    replica's campaigns to survivors from the per-tenant checkpoint
    namespaces, bitwise-continuous."""

    step: int
    replica: int = 0
    at_member_step: int = 0
    repeat: int = 1
    fired: int = 0

    def due(self, step: int) -> bool:
        return step == self.step and self.fired < self.repeat

    def fire(self, log: LogFn) -> None:
        self.fired += 1
        log("fault_replica_crash", step=self.step,
            replica=self.replica, at_member_step=self.at_member_step)


@dataclasses.dataclass
class SlowReplica:
    """Mark replica ``replica`` degraded at fleet round ``step``: the
    fleet trips its degradation ladder (drain -> reshard its tenants
    to survivors -> readmit on recovery). ``recover_step`` is the
    fleet round at which the replica rejoins the active set (None =
    it stays degraded)."""

    step: int
    replica: int = 0
    recover_step: Optional[int] = None
    repeat: int = 1
    fired: int = 0
    restored: int = 0

    def due(self, step: int) -> bool:
        return step == self.step and self.fired < self.repeat

    def fire(self, log: LogFn) -> None:
        self.fired += 1
        log("fault_slow_replica", step=self.step, replica=self.replica,
            recover_step=self.recover_step)

    def recover_due(self, step: int) -> bool:
        return (self.recover_step is not None and self.fired > 0
                and self.restored < self.fired
                and step >= self.recover_step)

    def recover(self, log: LogFn) -> None:
        self.restored += 1
        log("fault_slow_replica_recovered", step=self.recover_step,
            replica=self.replica)


@dataclasses.dataclass
class AdmissionFlood:
    """Submit ``count`` junk campaigns from ``tenant`` at ``priority``
    (below the fleet policy's protected floor by default) during fleet
    round ``step`` — the overload that must be SHED with a named
    reason, not silently queued until protected tenants starve."""

    step: int
    tenant: str = "flood"
    count: int = 8
    priority: int = 0
    n_steps: int = 1
    grid: Tuple[int, int, int] = (8, 8, 8)
    repeat: int = 1
    fired: int = 0

    def due(self, step: int) -> bool:
        return step == self.step and self.fired < self.repeat

    def fire(self, log: LogFn) -> None:
        self.fired += 1
        log("fault_admission_flood", step=self.step, tenant=self.tenant,
            count=self.count, priority=self.priority)


@dataclasses.dataclass
class FaultPlan:
    """A seeded schedule of injected faults, consumed by the resilience
    driver. All hooks are no-ops when their event lists are empty, so a
    production run with ``faults=None`` pays nothing."""

    nans: List[NaNInjection] = dataclasses.field(default_factory=list)
    halos: List[HaloCorruption] = dataclasses.field(default_factory=list)
    particle_losses: List[ParticleLoss] = \
        dataclasses.field(default_factory=list)
    save_failures: List[TransientSaveFailure] = \
        dataclasses.field(default_factory=list)
    ckpt_corruptions: List[CheckpointCorruption] = \
        dataclasses.field(default_factory=list)
    preemptions: List[Preemption] = dataclasses.field(default_factory=list)
    seed: int = 0

    def __post_init__(self) -> None:
        import numpy as np
        self._rng = np.random.default_rng(self.seed)
        self._log: LogFn = _noop_log

    def bind(self, log: LogFn) -> None:
        """Route fault firings into the driver's event log."""
        self._log = log

    # -- driver hooks ---------------------------------------------------
    def on_step(self, dd, step: int, fields=None) -> bool:
        """Fire state faults due after ``step`` (NaN, halo, SIGTERM).
        ``fields`` is the LIVE field dict (the driver passes the same
        one the sentinel probes) — on interior-resident fast paths
        that is the model's resident state, not the stale ``dd.curr``;
        it is mutated in place. Defaults to ``dd.curr``. Returns True
        when a STATE fault (NaN/halo) fired — the fused megastep loop
        re-probes the now-poisoned fields so detection matches the
        stepwise loop's post-injection probe semantics."""
        mutated = False
        for ev in self.nans:
            if ev.due(step):
                ev.fire(dd, self._log, fields)
                mutated = True
        for ev in self.halos:
            if ev.due(step):
                ev.fire(dd, self._log, fields)
                mutated = True
        for ev in self.particle_losses:
            if ev.due(step):
                ev.fire(dd, self._log, fields)
                mutated = True
        for ev in self.preemptions:
            if ev.due(step):
                ev.fire(self._log)
        return mutated

    def next_host_step(self, after: int) -> Optional[int]:
        """The next step at which a host-side hook must run (NaN, halo,
        SIGTERM still due) — the fused megastep loop cuts segments at
        these boundaries so host fault injection lands between
        dispatches exactly where the stepwise loop would fire it.
        None when no such fault remains."""
        cands = [ev.step
                 for ev in (*self.nans, *self.halos,
                            *self.particle_losses, *self.preemptions)
                 if ev.step > after
                 and ev.fired < getattr(ev, "repeat", 1)]
        return min(cands) if cands else None

    def maybe_fail_save(self, step: int) -> None:
        """Raise the scheduled transient ``IOError`` for this save."""
        for ev in self.save_failures:
            ev.maybe_raise(step, self._log)

    def after_save(self, directory: str, step: int) -> None:
        """Fire on-disk corruption due for the checkpoint just saved."""
        for ev in self.ckpt_corruptions:
            if ev.due(step):
                ev.fire(directory, step, self._rng, self._log)


# ----------------------------------------------------------------------
# geometry helpers
# ----------------------------------------------------------------------
def _shard_cell(dd, shard: Tuple[int, int, int], interior: bool,
                arr=None) -> Optional[Tuple[int, int, int]]:
    """An index into the live field array inside shard ``(bx, by,
    bz)``: the interior center (``interior=True``) or the first halo
    pad cell of the first padded axis (``interior=False``; None when
    the array has no pads). ``arr`` disambiguates the layout: the
    padded global (``dd.curr``) vs the interior-resident global of the
    fast paths (no pads — halo corruption is a no-op there)."""
    from ..geometry import Dim3
    from ..local_domain import raw_size, zyx_shape
    bx, by, bz = shard
    pr = raw_size(dd.local_size, dd.alloc_radius)
    lo = dd.alloc_radius.pad_lo()
    if arr is not None and tuple(arr.shape) != \
            zyx_shape(pr * dd.placement.dim()):
        if not interior:
            return None        # interior-resident: nothing to corrupt
        pr = dd.local_size
        lo = Dim3(0, 0, 0)
    base = (bz * pr.z, by * pr.y, bx * pr.x)
    if interior:
        return (base[0] + lo.z + dd.local_size.z // 2,
                base[1] + lo.y + dd.local_size.y // 2,
                base[2] + lo.x + dd.local_size.x // 2)
    center = (base[0] + lo.z + dd.local_size.z // 2,
              base[1] + lo.y + dd.local_size.y // 2,
              base[2] + lo.x + dd.local_size.x // 2)
    if lo.z > 0:     # first z-lo pad row of this shard, centered in y/x
        return (base[0], center[1], center[2])
    if lo.y > 0:
        return (center[0], base[1], center[2])
    if lo.x > 0:
        return (center[0], center[1], base[2])
    return None


def _state_data_files(directory: str, step: int) -> List[Path]:
    """The ocdbt data blobs of the step's ``state`` item (files under a
    ``d/`` directory) — where the array bytes live, so corrupting them
    is guaranteed to hit data, not an ignorable sidecar. Falls back to
    every state file when the layout has no ``d/`` dirs."""
    root = Path(directory).absolute() / str(step) / "state"
    if not root.is_dir():
        root = Path(directory).absolute() / str(step)
        if not root.is_dir():
            return []
    files = [p for p in sorted(root.rglob("*")) if p.is_file()]
    data = [p for p in files if p.parent.name == "d"]
    return data or files
