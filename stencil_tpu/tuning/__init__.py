"""Measurement-driven exchange autotuning with a persistent plan cache.

The reference library's defining optimization is that it *measures*
the machine and routes every halo message over the fastest transport
for its src/dst pair (reference: src/stencil.cu:371-458); TEMPI
(PAPERS.md) shows the same win done transparently under an unchanged
API. This package closes that loop for the TPU port — the static
priority list in ``parallel/methods.py`` becomes a measured decision:

1. **measure** (:mod:`.measure`) — pingpong ring shifts calibrate
   per-link alpha-beta coefficients; short jitted loops built from the
   existing exchange engines time whole candidate configurations;
2. **fit** (:mod:`.fit`) — least-squares alpha-beta over the pingpong
   samples replaces the assumed constants in
   ``analysis/costmodel.py``;
3. **plan** (:mod:`.plan`) — the calibrated cost model
   (``configured_step_seconds`` generalizing
   ``temporal_step_exchange_seconds``; ``predict_exchange_every`` for
   the depth crossover) ranks every feasible (Method, overlap,
   exchange_every) candidate and PRUNES the sweep so only the top few
   are ever timed; the measured winner becomes the :class:`Plan`;
4. **cache** (:mod:`.cache`) — the plan persists under a fingerprint
   of topology + mesh + grid + radius + dtypes + quantities + library
   version; a hit skips measurement entirely, a mismatch re-tunes.

It is the same measure → fit → plan → cache shape a training stack
uses for collective/layout autotuning. Everything is testable off-TPU
via the injectable timer (:class:`.measure.FakeTimer`): tier-1
exercises search, fit, pruning, and cache logic deterministically on
the CPU mesh.

Entry points: ``DistributedDomain.autotune()`` / ``Method.Auto`` at
``realize()`` time, ``python -m stencil_tpu.tune``, and
``apps/bench_exchange.py --autotune``.
"""

from __future__ import annotations

import time as _time
from typing import Dict, List, Optional, Sequence, Tuple

from ..analysis.costmodel import (LinkCoefficients,
                                  configured_step_seconds,
                                  predict_exchange_every)
from ..utils.logging import LOG_INFO
from .cache import (default_cache_path, invalidate_plan, load_plan,
                    store_plan)
from .fit import calibrate_link, coefficients_record, fit_alpha_beta
from .measure import CountingTimer, FakeTimer, MeshTimer
from .plan import (DEFAULT_DEPTHS, Candidate, MigrationCandidate, Plan,
                   TilingCandidate, TuneGeometry, candidate_space,
                   migration_candidate_space, fingerprint,
                   fingerprint_inputs, rank_migration_candidates,
                   rank_tiling_candidates, tiling_candidate_space,
                   tiling_record)

__all__ = [
    "Candidate", "MigrationCandidate", "Plan", "TilingCandidate",
    "TuneGeometry", "FakeTimer", "MeshTimer",
    "CountingTimer", "LinkCoefficients", "autotune_domain",
    "run_autotune", "candidate_space", "migration_candidate_space",
    "rank_migration_candidates", "rank_tiling_candidates",
    "tiling_candidate_space", "tiling_record", "calibrate_link",
    "fit_alpha_beta", "fingerprint", "fingerprint_inputs",
    "default_cache_path", "load_plan", "store_plan", "invalidate_plan",
    "DEFAULT_DEPTHS",
]


def run_autotune(geom: TuneGeometry, inputs: Dict, timer,
                 read_cache: bool = True, write_cache: bool = True,
                 cache_path=None,
                 depths: Sequence[int] = DEFAULT_DEPTHS,
                 overlap_options: Sequence[bool] = (False,),
                 max_measurements: int = 4,
                 runnable=None, topology: "Dict | None" = None,
                 wire_formats: Sequence[str] = ("f32",),
                 wire_layouts: Sequence[str] = ("slab",),
                 dcn_axis: "int | None" = None,
                 placement: str = "auto") -> Plan:
    """The core search (timer injected — deterministic under
    :class:`FakeTimer`): cache lookup, alpha-beta calibration,
    model-ranked pruning, measurement of the survivors, plan store.

    ``max_measurements`` bounds the exchange timing runs (the
    calibration pingpongs are counted separately in
    ``Plan.measurements``); the calibrated cost model decides WHICH
    candidates are worth those runs.

    ``topology``: a measured topology-fingerprint record
    (``observatory.linkmap.measure_topology`` / ``load_topology``) —
    its per-link (per mesh axis + DCN) alpha-beta coefficients are
    consumed INSTEAD of pingponging the two global link classes, so a
    machine fingerprinted once never pays calibration again and the
    plan records the full per-axis fabric.

    ``wire_formats``: halo wire formats to enumerate as candidate
    dimensions (default f32-only; add ``"bf16"`` to rank narrow-wire
    configurations — the calibrated model prices their halved wire
    bytes, and realize() only accepts the winner behind a ``safe``
    :class:`~stencil_tpu.analysis.precision.PrecisionCertificate`).

    ``wire_layouts``: halo wire message layouts to enumerate (default
    slab-only; add ``"irredundant"`` to rank the each-cell-once
    layout — the calibrated model prices its slimmer per-direction
    boxes, ``parallel.packing``).

    ``dcn_axis``: the slice-blocked mesh axis, when the domain has
    one. Arms two things: asymmetric-depth candidates that deepen
    ONLY the DCN axis (``{dcn: s}`` for every uniform depth in
    ``depths``) join the sweep automatically, and per-axis candidates
    are priced per LINK — the topology fingerprint's (or default) DCN
    coefficients on the blocked axis, ICI elsewhere — instead of the
    single bottleneck-link price.

    ``placement``: the domain's placement mode, recorded on the plan
    (``Plan.placement``) so a cached plan replays the same fabric.
    """
    fp = fingerprint(inputs)
    if read_cache:
        plan = load_plan(fp, cache_path)
        if plan is not None:
            plan.provenance = "cached"
            plan.measurements = 0
            LOG_INFO(f"autotune: plan cache hit for {fp[:12]}... -> "
                     f"{plan.config.key()} (no measurements)")
            return plan

    counted = CountingTimer(timer)

    # --- fit: measured alpha-beta replaces the assumed constants, per
    # link class. With a topology fingerprint the per-link (per-axis +
    # DCN) coefficients come from the persisted artifact — zero
    # pingpongs here; otherwise the classic two classes are measured:
    # the ICI always, the DCN when the mesh has a slice-blocked axis
    # (timer.has_dcn). The exchange is three SEQUENTIAL axis sweeps,
    # so for ranking the classes combine as the bottleneck link (max
    # latency, min bandwidth) — the conservative price of a sweep that
    # must cross every fabric tier.
    if topology is not None:
        from ..observatory.linkmap import topology_coefficients
        links = topology_coefficients(topology)
    else:
        links = {"ici": calibrate_link(counted.pingpong)}
        if getattr(counted, "has_dcn", False):
            links["dcn"] = calibrate_link(counted.pingpong_dcn)
    coeffs = LinkCoefficients(
        alpha_s=max(c.alpha_s for c in links.values()),
        beta_bytes_per_s=min(c.beta_bytes_per_s
                             for c in links.values()))

    # --- plan: rank every feasible candidate with the CALIBRATED model
    sweep = list(depths)
    if dcn_axis is not None:
        # a slice-blocked axis makes asymmetric blocking the
        # interesting move: deepen ONLY the DCN axis at every uniform
        # depth the caller swept (the ICI axes keep per-step exchange)
        name = "xyz"[dcn_axis]
        sweep += [{name: int(s)} for s in depths
                  if isinstance(s, int) and s > 1]
    cands = candidate_space(geom, depths=sweep,
                            overlap_options=overlap_options,
                            runnable=runnable,
                            wire_formats=wire_formats,
                            wire_layouts=wire_layouts)
    if not cands:
        raise ValueError("no feasible exchange configuration for this "
                         "geometry (shards smaller than the radius?)")
    # uniform candidates keep the classic single bottleneck-link price;
    # asymmetric ones are priced per link (DCN coefficients on the
    # blocked axis, per-axis/ICI elsewhere) — the whole point of
    # deepening one axis is that its link is NOT the others'
    per_link = dict(links)
    if dcn_axis is not None and "dcn" not in per_link:
        from ..analysis.costmodel import DEFAULT_DCN_COEFFS
        per_link["dcn"] = DEFAULT_DCN_COEFFS

    def _predict(c: Candidate) -> float:
        if c.depths is not None and len(set(c.depths)) > 1:
            return configured_step_seconds(
                c.method, geom.shard_interior_zyx, geom.radius,
                geom.counts, geom.elem_sizes, c.depths, per_link,
                geom.dtype_groups, wire_format=c.wire_format,
                wire_layout=c.wire_layout, dcn_axis=dcn_axis)
        return configured_step_seconds(
            c.method, geom.shard_interior_zyx, geom.radius,
            geom.counts, geom.elem_sizes, c.exchange_every, coeffs,
            geom.dtype_groups, wire_format=c.wire_format,
            wire_layout=c.wire_layout)

    predicted = {c: _predict(c) for c in cands}
    ranked = sorted(cands, key=lambda c: predicted[c])

    # the temporal-depth crossover predictor, on the calibrated
    # coefficients (recorded as Plan.predicted_best_depth)
    best_depth: Optional[int] = None
    try:
        best_depth, _ = predict_exchange_every(
            geom.shard_interior_zyx, geom.radius, geom.counts,
            max(geom.elem_sizes), coeffs.alpha_s * 6,
            coeffs.beta_bytes_per_s, candidates=tuple(depths))
    except ValueError:
        pass

    survivors = ranked[:max(int(max_measurements), 1)]
    pruned = len(ranked) - len(survivors)

    # --- measure the survivors ---------------------------------------
    measured: List[Tuple[float, Candidate]] = []
    for c in survivors:
        per_step = counted.exchange_round(c, geom) / c.exchange_every
        measured.append((per_step, c))
    win_s, winner = min(measured,
                        key=lambda t: (t[0], survivors.index(t[1])))

    costs = {}
    for c in cands:
        rec = {"predicted_s": predicted[c]}
        for s, mc in measured:
            if mc == c:
                rec["measured_s"] = s
        costs[c.key()] = rec

    plan = Plan(config=winner, fingerprint=fp,
                coefficients=coefficients_record(links),
                costs=costs, provenance="tuned",
                measurements=counted.calls,
                created=_time.time(),
                library_version=str(inputs.get("library_version", "")),
                fingerprint_inputs=dict(inputs),
                predicted_best_depth=best_depth,
                # the VMEM planner's prescribed Pallas block shape for
                # this geometry rides the plan record: Method.Auto
                # ships tile shapes the way it ships exchange methods
                tiling=tiling_record(geom),
                placement=str(placement))
    LOG_INFO(f"autotune: measured {len(survivors)}/{len(cands)} "
             f"candidates (pruned {pruned} by the calibrated model; "
             f"depth crossover predicts s={best_depth}) -> "
             f"{winner.key()} at {win_s:.3e}s/step "
             f"[alpha={coeffs.alpha_s:.2e}s "
             f"beta={coeffs.beta_bytes_per_s:.2e}B/s]")
    if write_cache:
        store_plan(plan, cache_path)
    return plan


# ---------------------------------------------------------------------------
# DistributedDomain adapters


def geometry_from_domain(dd, dim) -> TuneGeometry:
    """Per-shard tuning geometry from a configured (not yet realized)
    ``DistributedDomain`` and its chosen partition ``dim``."""
    from ..geometry import Dim3
    from ..numerics import div_ceil
    from ..topology import Boundary

    local = Dim3(*(div_ceil(dd.size[a], dim[a]) for a in range(3)))
    rem = dd.size % dim
    min_local = Dim3(*(local[a] - (1 if rem[a] else 0)
                       for a in range(3)))
    return TuneGeometry(
        shard_interior_zyx=(local.z, local.y, local.x),
        min_interior_zyx=(min_local.z, min_local.y, min_local.x),
        radius=dd.radius, counts=Dim3.of(dim),
        elem_sizes=tuple(dd._dtypes[q].itemsize for q in dd._names),
        uneven=rem != Dim3(0, 0, 0),
        nonperiodic=dd.boundary == Boundary.NONE,
        dtype_strs=tuple(str(dd._dtypes[q]) for q in dd._names))


def inputs_from_domain(dd, dim) -> Dict:
    """Fingerprint inputs from a configured ``DistributedDomain``."""
    platform = (dd._devices[0].platform if dd._devices else "cpu")
    depths = getattr(dd, "exchange_depths", None)
    return fingerprint_inputs(
        platform=platform, device_count=len(dd._devices),
        mesh_shape=list(dim), grid=list(dd.size), radius=dd.radius,
        quantities={q: str(dd._dtypes[q]) for q in dd._names},
        boundary=dd.boundary.name, n_slices=dd.n_slices,
        wire_format=getattr(dd, "wire_format", "f32"),
        wire_layout=getattr(dd, "wire_layout", "slab"),
        exchange_depths=tuple(depths) if depths is not None else None,
        placement=getattr(dd, "placement_mode", "auto"))


def autotune_domain(dd, timer=None, use_cache: bool = True,
                    force: bool = False, cache_path=None,
                    depths: Sequence[int] = DEFAULT_DEPTHS,
                    overlap_options: Sequence[bool] = (False,),
                    max_measurements: int = 4,
                    topology_path=None,
                    wire_formats: Sequence[str] = ("f32",),
                    wire_layouts: Sequence[str] = ("slab",)) -> Plan:
    """Autotune a configured ``DistributedDomain`` (called by
    ``DistributedDomain.autotune()`` — use that). Chooses the partition
    the orchestrator will use, builds the real :class:`MeshTimer` over
    a throwaway mesh of that shape (unless a timer is injected), and
    runs the search. Does NOT apply the plan; the domain does.

    ``topology_path`` (or ``$STENCIL_TOPOLOGY_CACHE``) arms the
    measured topology fingerprint: a stored per-axis link calibration
    for this fabric is consumed instead of the two global pingpong
    fits; a miss measures the per-axis sweeps once and persists them
    (atomic, fingerprint-keyed) for every later campaign on the same
    machine."""
    import os as _os

    dim = dd._choose_partition_dim()
    geom = geometry_from_domain(dd, dim)
    inputs = inputs_from_domain(dd, dim)
    if topology_path is None and _os.environ.get(
            "STENCIL_TOPOLOGY_CACHE"):
        topology_path = _os.environ["STENCIL_TOPOLOGY_CACHE"]
    if timer is None:
        from ..parallel.mesh import make_mesh
        from ..geometry import Dim3
        from ..numerics import div_ceil
        local = Dim3(*(div_ceil(dd.size[a], dim[a]) for a in range(3)))
        # time the fabric realize() will DEPLOY: the same placement
        # (slice-blocked / NodeAware device order), not raw device
        # order — on a DCN-tiered mesh the raw order would let the
        # "dcn" pingpong ride ICI links and fit fantasy coefficients
        groups = dd._discover_dcn_groups()
        placement = dd._choose_placement(dim, groups)
        mesh = make_mesh(dim, placement.device_order_for_mesh())
        timer = MeshTimer(mesh, local,
                          [dd._dtypes[q] for q in dd._names],
                          rem=dd.size % dim,
                          nonperiodic=geom.nonperiodic,
                          dcn_axis=(dd.dcn_axis if dd.n_slices > 1
                                    else None))
    topology = None
    if topology_path:
        from ..observatory.linkmap import (load_topology,
                                           measure_topology,
                                           topology_fingerprint,
                                           topology_fingerprint_inputs,
                                           save_topology)
        topo_inputs = topology_fingerprint_inputs(
            platform=inputs["platform"],
            device_count=inputs["device_count"],
            mesh_shape=inputs["mesh_shape"],
            n_slices=inputs["n_slices"])
        topology = load_topology(topology_fingerprint(topo_inputs),
                                 topology_path)
        if topology is None and hasattr(timer, "pingpong_axis"):
            topology = measure_topology(
                timer, inputs["mesh_shape"], topo_inputs,
                dcn_axis=(dd.dcn_axis if dd.n_slices > 1 else None))
            if not topology["links"]:
                # a mesh with no multi-device axis has no links to
                # fingerprint — fall back to the classic calibration
                # (which degenerates gracefully) instead of persisting
                # an empty record
                topology = None
            else:
                save_topology(topology, topology_path)
                LOG_INFO(f"autotune: measured topology fingerprint "
                         f"{topology['fingerprint'][:12]}... "
                         f"({len(topology['links'])} links) -> "
                         f"{topology_path}")
        elif topology is not None:
            LOG_INFO(f"autotune: topology fingerprint hit "
                     f"{topology['fingerprint'][:12]}... (per-axis "
                     f"links replace the pingpong calibration)")
    sweep = list(depths)
    dd_depths = getattr(dd, "exchange_depths", None)
    if dd_depths is not None and len(set(tuple(dd_depths))) > 1:
        # a configured per-axis depth is a candidate the user already
        # believes in — always rank it
        sweep.append(tuple(dd_depths))
    return run_autotune(geom, inputs, timer,
                        read_cache=use_cache and not force,
                        write_cache=use_cache, cache_path=cache_path,
                        depths=sweep, overlap_options=overlap_options,
                        max_measurements=max_measurements,
                        topology=topology, wire_formats=wire_formats,
                        wire_layouts=wire_layouts,
                        dcn_axis=(dd.dcn_axis if dd.n_slices > 1
                                  else None),
                        placement=getattr(dd, "placement_mode", "auto"))
