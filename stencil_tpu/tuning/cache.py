"""Persistent plan cache: fingerprint-keyed tuned plans on disk.

One JSON file holds every plan this machine has tuned, keyed by the
problem fingerprint (:func:`..tuning.plan.fingerprint`). Subsequent
runs with a matching fingerprint skip measurement entirely; a
fingerprint miss (different radius, dtype, mesh, grid, library
version...) re-tunes automatically. The schema is versioned: a cache
written by an incompatible library schema — or a corrupt/truncated
file — is REJECTED gracefully (warn + re-tune + rewrite), never
trusted and never fatal.

Location: ``$STENCIL_TUNE_CACHE`` when set, else
``~/.cache/stencil_tpu/plans.json``. Fleets can pre-bake a plan file
at that path (or point the env var at a read-only shipped plan) so no
job ever pays the measurement cost — the README "Autotuning" section
documents the recipe.

Concurrency: the campaign service runs several workers against ONE
cache file, so :func:`store_plan` is a read-merge-write under an
exclusive ``flock`` on a ``<cache>.lock`` sidecar (two writers storing
different fingerprints both land; last-writer-wins only on the SAME
fingerprint). Readers stay lock-free — they see either the old or the
new file thanks to the atomic tmp+rename publish.
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
import threading
from pathlib import Path
from typing import Dict, Optional, Union

try:
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX: thread lock only
    fcntl = None

from ..utils.logging import LOG_DEBUG, LOG_WARN
from ..utils.retry import retry
from .plan import Plan, SCHEMA_VERSION

ENV_CACHE = "STENCIL_TUNE_CACHE"

# transient-I/O retry budget for cache reads/writes (an NFS blip must
# not kill a tune or lose a measured plan); tests inject a fake clock
_RETRY_ATTEMPTS = 3
_RETRY_BASE_DELAY = 0.05
_RETRY_SLEEP = None  # None -> time.sleep


def default_cache_path() -> Path:
    env = os.environ.get(ENV_CACHE, "")
    if env:
        return Path(env)
    return Path(os.path.expanduser("~/.cache/stencil_tpu/plans.json"))


def _resolve(path: Union[str, Path, None]) -> Path:
    return Path(path) if path is not None else default_cache_path()


def load_cache(path: Union[str, Path, None] = None) -> Dict[str, Dict]:
    """The raw fingerprint -> plan-record table, or {} when the file is
    absent, unreadable, corrupt, or of a foreign schema version."""
    p = _resolve(path)
    if not p.exists():
        return {}
    try:
        text = retry(p.read_text, attempts=_RETRY_ATTEMPTS,
                     base_delay=_RETRY_BASE_DELAY, sleep=_RETRY_SLEEP)
        data = json.loads(text)
    except (OSError, ValueError) as e:
        LOG_WARN(f"plan cache {p} is corrupt or unreadable "
                 f"({type(e).__name__}: {e}); "
                 f"ignoring it (will re-tune and rewrite)")
        return {}
    if not isinstance(data, dict) or "plans" not in data:
        LOG_WARN(f"plan cache {p} has no 'plans' table; ignoring it")
        return {}
    schema = data.get("schema")
    if schema != SCHEMA_VERSION:
        LOG_WARN(f"plan cache {p} has schema {schema!r}, this library "
                 f"speaks {SCHEMA_VERSION}; ignoring it (will re-tune)")
        return {}
    plans = data["plans"]
    return dict(plans) if isinstance(plans, dict) else {}


def load_plan(fingerprint: str,
              path: Union[str, Path, None] = None) -> Optional[Plan]:
    """The cached plan for ``fingerprint``, or None (miss / bad file /
    record that does not parse)."""
    rec = load_cache(path).get(fingerprint)
    if rec is None:
        return None
    try:
        plan = Plan.from_record(rec)
    except (KeyError, TypeError, ValueError) as e:
        LOG_WARN(f"cached plan for {fingerprint[:12]}... does not parse "
                 f"({type(e).__name__}: {e}); treating as a miss")
        return None
    if plan.fingerprint != fingerprint:
        LOG_WARN(f"cached plan under key {fingerprint[:12]}... carries "
                 f"mismatched fingerprint {plan.fingerprint[:12]}...; "
                 f"treating as a miss")
        return None
    return plan


# in-process serialization, PER cache path (flock excludes other
# PROCESSES; threads of one process sharing the lock file need this
# too — and a flock blocked on one hung cache path must not stall
# stores to unrelated paths)
_PATH_LOCKS: Dict[str, threading.Lock] = {}
_PATH_LOCKS_GUARD = threading.Lock()


def _thread_lock_for(p: Path) -> threading.Lock:
    key = str(p.absolute())
    with _PATH_LOCKS_GUARD:
        lock = _PATH_LOCKS.get(key)
        if lock is None:
            lock = _PATH_LOCKS[key] = threading.Lock()
        return lock


@contextlib.contextmanager
def _write_lock(p: Path):
    """Exclusive writer lock for cache file ``p``: a ``flock`` on the
    ``<p>.lock`` sidecar (never the data file itself — the atomic
    rename publish replaces that inode) plus an in-process per-path
    mutex. On platforms without ``fcntl`` only the mutex applies."""
    with _thread_lock_for(p):
        if fcntl is None:  # pragma: no cover - non-POSIX
            yield
            return
        lock_path = p.with_name(p.name + ".lock")
        fd = os.open(lock_path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            finally:
                os.close(fd)


def _publish(p: Path, plans: Dict[str, Dict]) -> None:
    """Atomically publish the plans table (tmp + rename — lock-free
    readers never observe a torn file)."""
    payload = {"schema": SCHEMA_VERSION, "plans": plans}
    fd, tmp = tempfile.mkstemp(dir=str(p.parent),
                               prefix=p.name, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(tmp, p)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _mutate_cache(p: Path, mutate) -> None:
    """The ONE read-mutate-publish discipline both writers share:
    under :func:`_write_lock` (so two concurrent workers touching
    DIFFERENT fingerprints cannot drop each other's records), with
    transient-I/O retry. ``mutate(plans)`` edits the table in place
    and returns False to abandon the write (no-op mutation)."""

    def mutate_once():
        with _write_lock(p):
            plans = load_cache(p)
            if mutate(plans) is False:
                return
            _publish(p, plans)

    retry(mutate_once, attempts=_RETRY_ATTEMPTS,
          base_delay=_RETRY_BASE_DELAY, sleep=_RETRY_SLEEP)


def store_plan(plan: Plan, path: Union[str, Path, None] = None) -> Path:
    """Insert/replace ``plan`` under its fingerprint (see
    :func:`_mutate_cache` for the locking/publish discipline)."""
    p = _resolve(path)
    p.parent.mkdir(parents=True, exist_ok=True)

    def merge(plans):
        plans[plan.fingerprint] = plan.to_record()

    _mutate_cache(p, merge)
    LOG_DEBUG(f"plan cache {p}: stored {plan.config.key()} under "
              f"{plan.fingerprint[:12]}...")
    return p


def invalidate_plan(fingerprint: str,
                    path: Union[str, Path, None] = None) -> bool:
    """Drop the cached record for ``fingerprint`` so the next tune
    re-measures — the performance observatory's drift healer
    (``ResiliencePolicy.retune_on_drift``): a plan whose measured
    behavior departed from its calibrated prediction is stale evidence
    and must not keep serving cache hits. Same locking and atomic
    publish as :func:`store_plan` (shared :func:`_mutate_cache`).
    Returns True when a record was removed (False on a miss or an
    absent cache file)."""
    p = _resolve(path)
    if not p.exists():
        return False
    removed = False

    def drop(plans):
        nonlocal removed
        if fingerprint not in plans:
            return False
        del plans[fingerprint]
        removed = True

    _mutate_cache(p, drop)
    if removed:
        LOG_WARN(f"plan cache {p}: invalidated "
                 f"{fingerprint[:12]}... (perf drift — the next tune "
                 f"re-measures)")
    return removed
