"""Plan data model: candidates, fingerprints, and the tuned Plan.

The reference library routes every halo message over the fastest
measured transport for its src/dst pair (reference:
src/stencil.cu:371-458) and records the decision in per-rank plan
files. The TPU analog is a whole-program choice: ONE exchange
configuration — (Method, overlap, exchange_every) — serves every pair
because XLA SPMD owns the wire. A :class:`Plan` is that choice plus
everything needed to trust and reuse it: the measured alpha-beta
coefficients, the per-candidate costs, a provenance tag
(tuned/cached/default), and a fingerprint of the machine+problem the
measurements are valid for.

Fingerprint semantics: two runs share a plan iff their fingerprint
inputs match — device platform and count, mesh shape, slice count,
global grid, full 26-direction radius, quantity names and dtypes,
boundary, and the library version (a new library may lower the same
exchange differently, so plans do not survive upgrades). Anything else
(iteration counts, output prefixes, CLI flags) is deliberately NOT
fingerprinted.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..geometry import Dim3, Radius, all_directions

SCHEMA_VERSION = 1

#: temporal-blocking depths the tuner sweeps by default
DEFAULT_DEPTHS: Tuple[int, ...] = (1, 2, 4, 8)

#: the strategies a plan may select, in generation (tie-break) order
PLAN_METHODS: Tuple[str, ...] = ("PpermuteSlab", "PpermutePacked",
                                 "PallasDMA", "AllGather")

#: strategies supporting deep-carry allocations / uneven shards /
#: the zero-Dirichlet exterior (mirrors DistributedDomain.realize)
_PPERMUTE = ("PpermuteSlab", "PpermutePacked")


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point of the configuration space the tuner sweeps.

    ``wire_format`` is the halo wire dtype choice
    (``parallel.exchange.WIRE_FORMATS``): "f32" is the identity wire;
    the narrowing formats ("bf16", "e4m3", "e5m2") shrink the wire
    bytes on the ppermute engines and only realize behind a safe
    :class:`~stencil_tpu.analysis.precision.PrecisionCertificate`
    (the ``make_exchange`` gate). ``wire_layout`` is the message
    layout ("slab" | "irredundant", ``parallel.packing.WIRE_LAYOUTS``):
    "irredundant" sends every halo cell exactly once on the ppermute
    engines (corner/edge cells stop transiting multiple sweeps)."""

    method: str
    exchange_every: int = 1
    overlap: bool = False
    wire_format: str = "f32"
    wire_layout: str = "slab"
    #: per-axis temporal depths (x, y, z) — None means the symmetric
    #: ``exchange_every`` on every axis. A non-uniform tuple (e.g.
    #: ``(1, 1, 4)``) deepens only the named axes (DCN-crossing faces
    #: amortize while ICI faces exchange every step); serialized in the
    #: key as a dot-separated depth ``s=1.1.4``
    depths: Optional[Tuple[int, int, int]] = None

    def depths_xyz(self) -> Tuple[int, int, int]:
        """The effective (x, y, z) depths — ``depths`` or the symmetric
        fill of ``exchange_every``."""
        return (self.depths if self.depths is not None
                else (self.exchange_every,) * 3)

    def key(self) -> str:
        d = self.depths
        if d is not None and len(set(d)) > 1:
            tag = f"{self.method}[s={d[0]}.{d[1]}.{d[2]}"
        else:
            tag = f"{self.method}[s={self.exchange_every}"
        if self.overlap:
            tag += ",overlap"
        if self.wire_format != "f32":
            tag += f",wire={self.wire_format}"
        if self.wire_layout != "slab":
            tag += f",layout={self.wire_layout}"
        return tag + "]"

    @staticmethod
    def from_key(key: str) -> "Candidate":
        method, _, rest = key.partition("[")
        rest = rest.rstrip("]")
        parts = rest.split(",")
        sval = parts[0].split("=")[1]
        depths: Optional[Tuple[int, int, int]] = None
        if "." in sval:
            dx, dy, dz = (int(v) for v in sval.split("."))
            depths = (dx, dy, dz)
            s = max(depths)
        else:
            s = int(sval)
        wire = "f32"
        layout = "slab"
        for p in parts[1:]:
            if p.startswith("wire="):
                wire = p.split("=", 1)[1]
            elif p.startswith("layout="):
                layout = p.split("=", 1)[1]
        return Candidate(method, s, "overlap" in parts[1:], wire, layout,
                         depths)


@dataclasses.dataclass(frozen=True)
class TuneGeometry:
    """The per-shard geometry every cost/feasibility rule consumes.

    ``shard_interior_zyx`` is the CAPACITY interior (ceil sizes — the
    slabs that actually ride the wire); ``min_interior_zyx`` is the
    smallest shard (one less along remainder axes), which bounds the
    feasible blocking depth exactly like realize()'s check.
    """

    shard_interior_zyx: Tuple[int, int, int]
    min_interior_zyx: Tuple[int, int, int]
    radius: Radius
    counts: Dim3
    elem_sizes: Tuple[int, ...]
    uneven: bool = False
    nonperiodic: bool = False
    #: per-quantity dtype names — the packed engine groups launches by
    #: DTYPE, not element size (f32 + i32 pack separately); empty means
    #: unknown and the cost model falls back to distinct element sizes
    dtype_strs: Tuple[str, ...] = ()

    @property
    def dtype_groups(self) -> "int | None":
        return len(set(self.dtype_strs)) if self.dtype_strs else None


def candidate_feasible(cand: Candidate, geom: TuneGeometry) -> bool:
    """The realize()-equivalent feasibility rules, applied up front so
    the tuner never measures a configuration the orchestrator would
    reject."""
    if cand.method not in PLAN_METHODS:
        return False
    if cand.method not in _PPERMUTE:
        if cand.exchange_every > 1 or geom.uneven or geom.nonperiodic:
            return False
        if cand.overlap:
            return False
        # narrow wire formats and the irredundant layout ride the
        # ppermute engines only (parallel.methods.WIRE_CAPABLE)
        if cand.wire_format != "f32":
            return False
        if cand.wire_layout != "slab":
            return False
    if cand.exchange_every < 1:
        return False
    depths = cand.depths_xyz()
    if any(d < 1 for d in depths) or max(depths) != cand.exchange_every:
        return False
    if len(set(depths)) > 1:
        # asymmetric depths ride the ppermute engines with the slab
        # layout and no overlap (temporal_shard_steps' declines)
        if cand.method not in _PPERMUTE or cand.overlap:
            return False
        if cand.wire_layout != "slab":
            return False
        # each axis depth must divide the group length (refresh cadence)
        if any(max(depths) % d for d in depths):
            return False
    # the (per-axis) deepened radius must fit the SMALLEST shard on
    # every face
    mz, my, mx = geom.min_interior_zyx
    min_xyz = (mx, my, mz)
    for a in range(3):
        need = depths[a] * max(geom.radius.face(a, -1),
                               geom.radius.face(a, 1))
        if need > min_xyz[a]:
            return False
    return True


def candidate_space(geom: TuneGeometry,
                    depths: Sequence[int] = DEFAULT_DEPTHS,
                    overlap_options: Sequence[bool] = (False,),
                    runnable: Optional[Callable] = None,
                    wire_formats: Sequence[str] = ("f32",),
                    wire_layouts: Sequence[str] = ("slab",)
                    ) -> List[Candidate]:
    """Every feasible, runnable configuration, in deterministic
    tie-break order (method priority x depth ascending x overlap off
    first x full-precision wire first). ``runnable`` filters
    strategies the backend cannot execute (capability probes —
    PallasDMA off-TPU); defaults to
    ``parallel.methods.method_runnable``. ``wire_formats`` is opt-in:
    the default sweeps only the identity "f32" wire; pass
    ``("f32", "bf16")`` to also rank the certified half-width wire on
    the ppermute engines. ``wire_layouts`` is likewise opt-in: pass
    ``("slab", "irredundant")`` to also rank the each-cell-once
    message layout (``parallel.packing``).

    ``depths`` entries may be plain ints (symmetric blocking) or
    per-axis specs — a ``{"z": 4}``-style dict or an (x, y, z)
    tuple — which become asymmetric candidates (``Candidate.depths``,
    keys like ``PpermuteSlab[s=1.1.4]``)."""
    from ..geometry import normalize_depths
    from ..parallel.methods import Method, method_runnable

    if runnable is None:
        runnable = method_runnable
    uniform = set()
    asym = set()
    for d in depths:
        if isinstance(d, int):
            uniform.add(int(d))
        else:
            nd = normalize_depths(d)
            if nd.x == nd.y == nd.z:
                uniform.add(nd.x)
            else:
                asym.add((nd.x, nd.y, nd.z))
    out: List[Candidate] = []
    for name in PLAN_METHODS:
        if not runnable(Method[name]):
            continue
        specs = ([(s, None) for s in sorted(uniform)]
                 + [(max(d), d) for d in sorted(asym)])
        for s, dxyz in specs:
            for ovl in overlap_options:
                for wf in wire_formats:
                    for wl in wire_layouts:
                        cand = Candidate(name, s, bool(ovl), str(wf),
                                         str(wl), dxyz)
                        if candidate_feasible(cand, geom):
                            out.append(cand)
    return out


# ---------------------------------------------------------------------------
# VMEM tiling candidates (the Pallas block-shape tuning axis)

#: sustained HBM bytes/s one core can stream — a TPU-v4-ballpark
#: constant (the tuner's pingpong fit calibrates the WIRE, not HBM;
#: ranking block shapes only needs a monotone price, and amplification
#: differences dominate any bandwidth rescale)
DEFAULT_HBM_BYTES_PER_S = 1.2e12


@dataclasses.dataclass(frozen=True)
class TilingCandidate:
    """One planner-legal Pallas block shape, priced by the static VMEM
    planner (``analysis/tiling.py``): the double-buffered footprint it
    stages and the modeled HBM read amplification its edge refetches
    cost. The tuner ranks these exactly like exchange methods — the
    calibrated model orders, the plan record carries the winner — so
    ``Method.Auto`` ships a tile shape the same way it ships an
    exchange strategy."""

    block_z: int
    block_y: int
    footprint_bytes: int = 0
    amplification: float = 1.0

    def key(self) -> str:
        return f"tile[bz={self.block_z},by={self.block_y}]"


def tiling_candidate_space(geom: TuneGeometry,
                           kernel: str = "jacobi7_halo_pallas",
                           cap_z: int = 16, cap_y: int = 128
                           ) -> List[TilingCandidate]:
    """Every planner-legal block shape for the production multi-device
    Pallas kernel (the Jacobi halo kernel — the SNIPPETS.md 512^3
    failure's kernel) at this shard geometry, planner-ranked. Empty
    when the planner proves the shard infeasible (the model then
    declines the Pallas path; ``Plan.tiling`` records the constraint)."""
    from ..analysis.tiling import plan_blocks
    from ..ops.pallas_halo import _jacobi_halo_elems
    from ..ops.pallas_stencil import sublane_tile_bytes

    z, y, x = geom.shard_interior_zyx
    isz = max(geom.elem_sizes) if geom.elem_sizes else 4
    esub = sublane_tile_bytes(isz)
    if y % esub:
        esub = 1
    plan = plan_blocks(kernel, z, y, x, isz, _jacobi_halo_elems(esub),
                       sublane_y=esub, cap_z=cap_z, cap_y=cap_y)
    return [TilingCandidate(o.block_z, o.block_y, o.footprint_bytes,
                            o.amplification) for o in plan.options]


def rank_tiling_candidates(geom: TuneGeometry,
                           candidates: Optional[
                               Sequence[TilingCandidate]] = None,
                           hbm_bytes_per_s: float = DEFAULT_HBM_BYTES_PER_S
                           ) -> List[Tuple[float, TilingCandidate]]:
    """Rank legal tile shapes by modeled HBM seconds per step:
    ``(amplification + 1) x interior bytes / bandwidth`` (one amplified
    read pass + one write pass), cheapest first; ties prefer the fatter
    ``block_y`` then ``block_z`` (fatter lane-aligned DMAs)."""
    cands = (list(candidates) if candidates is not None
             else tiling_candidate_space(geom))
    z, y, x = geom.shard_interior_zyx
    isz = max(geom.elem_sizes) if geom.elem_sizes else 4
    interior_bytes = z * y * x * isz
    ranked = [((c.amplification + 1.0) * interior_bytes
               / float(hbm_bytes_per_s), c) for c in cands]
    ranked.sort(key=lambda t: (t[0], -t[1].block_y, -t[1].block_z))
    return ranked


def tiling_record(geom: TuneGeometry) -> Dict[str, Dict]:
    """The ``Plan.tiling`` payload: the prescribed block shape (and its
    planner metrics) per production Pallas kernel for this geometry —
    what a fleet pre-baking plans ships, and what the observatory
    ledger stamps bench records with so future real-TPU numbers group
    against the shapes that produced them. The kernels re-derive the
    identical shape deterministically from the same planner, so the
    record is provenance, not a second source of truth."""
    ranked = rank_tiling_candidates(geom)
    if not ranked:
        return {"jacobi7_halo_pallas": {
            "infeasible": "no planner-legal block shape at this shard "
                          "geometry (see analysis.tiling targets)"}}
    modeled_s, c = ranked[0]
    return {"jacobi7_halo_pallas": {
        "block": [c.block_z, c.block_y],
        "footprint_bytes": c.footprint_bytes,
        "amplification": c.amplification,
        "modeled_hbm_s_per_step": modeled_s,
    }}


# ---------------------------------------------------------------------------
# particle-migration candidates (the PIC workload's tuning axis)


@dataclasses.dataclass(frozen=True)
class MigrationCandidate:
    """One point of the particle-migration configuration space: the
    per-shard SoA ``capacity`` (HBM cost, receive headroom) and the
    per-direction wire ``budget`` (the static message size — the whole
    wire bill of the dynamic exchange, ``analysis/costmodel.
    migration_wire_bytes_per_shard``)."""

    capacity: int
    budget: int

    def key(self) -> str:
        return f"migrate[cap={self.capacity},budget={self.budget}]"


def migration_candidate_space(particles_per_shard: int,
                              capacities: Optional[Sequence[int]] = None,
                              budgets: Optional[Sequence[int]] = None
                              ) -> List[MigrationCandidate]:
    """The (capacity, budget) grid the migration tuner ranks. Defaults
    sweep power-of-two headrooms over the mean fill (capacity 1.25x-4x
    the per-shard particle count; budgets from capacity/32 up to
    capacity) — a candidate must at minimum hold the uniform fill."""
    n = max(int(particles_per_shard), 1)
    if capacities is None:
        capacities = sorted({max(8, int(n * f))
                             for f in (1.25, 1.5, 2.0, 4.0)})
    out: List[MigrationCandidate] = []
    for cap in capacities:
        if cap < n:
            continue
        bs = (budgets if budgets is not None
              else sorted({max(1, cap // d) for d in (32, 16, 8, 4, 2, 1)}))
        for b in bs:
            if 1 <= b <= cap:
                out.append(MigrationCandidate(int(cap), int(b)))
    return out


def migration_candidate_feasible(cand: MigrationCandidate,
                                 particles_per_shard: int,
                                 max_crossing_fraction: float,
                                 headroom: float = 1.5) -> bool:
    """Overflow-safety gate: the budget must hold the worst expected
    per-direction flux (``particles_per_shard x max_crossing_fraction``,
    padded by ``headroom`` for clumping) and the capacity must carry
    the uniform fill with the same ``headroom`` factor of slack for
    arrival imbalance — an overflowing plan DROPS particles (the
    in-graph counter reports it), so the tuner never ranks one, no
    matter how cheap its wire bill."""
    n = max(int(particles_per_shard), 1)
    need_budget = int(n * float(max_crossing_fraction)
                      * float(headroom)) + 1
    if cand.budget < need_budget:
        return False
    if cand.capacity < int(n * float(headroom)):
        return False
    return True


def rank_migration_candidates(particles_per_shard: int, n_fields: int,
                              counts, elem_size: int,
                              max_crossing_fraction: float = 0.25,
                              coeffs=None,
                              candidates: Optional[
                                  Sequence[MigrationCandidate]] = None,
                              headroom: float = 1.5
                              ) -> List[Tuple[float, MigrationCandidate]]:
    """Rank feasible migration configurations by the calibrated
    alpha-beta wire cost per step (``analysis/costmodel.
    migration_step_seconds``), cheapest first; capacity breaks ties
    (smaller = less HBM). The winner is the smallest overflow-safe
    budget — wire bytes scale linearly with the budget, so safety, not
    speed, is the binding constraint. Raises when nothing is feasible
    (the flux outruns every candidate: shrink dt or grow capacity)."""
    cands = (list(candidates) if candidates is not None
             else migration_candidate_space(particles_per_shard))
    from ..analysis.costmodel import migration_step_seconds

    ranked: List[Tuple[float, MigrationCandidate]] = []
    for c in cands:
        if not migration_candidate_feasible(
                c, particles_per_shard, max_crossing_fraction, headroom):
            continue
        ranked.append((migration_step_seconds(
            n_fields, c.budget, counts, elem_size, coeffs), c))
    if not ranked:
        raise ValueError(
            f"no feasible migration candidate for "
            f"{particles_per_shard} particles/shard at crossing "
            f"fraction {max_crossing_fraction} (budgets too small "
            f"everywhere — raise capacity or lower the flux)")
    ranked.sort(key=lambda t: (t[0], t[1].capacity, t[1].budget))
    return ranked


# ---------------------------------------------------------------------------
# fingerprint


def radius_signature(radius: Radius) -> List[List[int]]:
    """Canonical 26-direction serialization (x,y,z,dir -> r rows)."""
    return [[d.x, d.y, d.z, radius.dir(d)] for d in all_directions()]


def fingerprint(inputs: Dict) -> str:
    """Stable hash of the fingerprint inputs (sorted-key JSON)."""
    blob = json.dumps(inputs, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


def fingerprint_inputs(platform: str, device_count: int,
                       mesh_shape: Sequence[int],
                       grid: Sequence[int], radius: Radius,
                       quantities: Dict[str, str],
                       boundary: str, n_slices: int = 1,
                       library_version: Optional[str] = None,
                       wire_format: str = "f32",
                       wire_layout: str = "slab",
                       exchange_depths: Optional[Sequence[int]] = None,
                       placement: str = "auto") -> Dict:
    """The identity a plan is valid for (see module docstring).
    ``quantities`` maps name -> numpy dtype string. ``wire_format``
    and ``wire_layout`` are part of the identity: a plan tuned for
    the f32 slab wire must never replay onto a bf16 or irredundant
    wire domain (the measured coefficients price a different byte
    bill). ``exchange_depths`` (x, y, z) and ``placement`` join the
    identity only when NON-default (non-uniform depths / mode other
    than "auto") so fingerprints of symmetric auto-placed domains —
    and every plan cached before these axes existed — are unchanged."""
    if library_version is None:
        from .. import __version__ as library_version
    out = {
        "platform": str(platform),
        "device_count": int(device_count),
        "mesh_shape": [int(v) for v in mesh_shape],
        "grid": [int(v) for v in grid],
        "radius": radius_signature(radius),
        "quantities": {str(k): str(v) for k, v in quantities.items()},
        "boundary": str(boundary),
        "n_slices": int(n_slices),
        "library_version": str(library_version),
        "wire_format": str(wire_format),
        "wire_layout": str(wire_layout),
    }
    if exchange_depths is not None and len(set(exchange_depths)) > 1:
        out["exchange_depths"] = [int(v) for v in exchange_depths]
    if str(placement) != "auto":
        out["placement"] = str(placement)
    return out


# ---------------------------------------------------------------------------
# the Plan


@dataclasses.dataclass
class Plan:
    """The autotuner's output: the winning configuration plus the
    evidence (coefficients, per-candidate costs) and provenance."""

    config: Candidate
    fingerprint: str
    #: link class -> {"alpha_s": ..., "beta_bytes_per_s": ...}
    coefficients: Dict[str, Dict[str, float]]
    #: candidate key -> {"predicted_s": ..., "measured_s": ...?}
    costs: Dict[str, Dict[str, float]]
    provenance: str = "tuned"        # tuned | cached | default
    measurements: int = 0            # timer invocations THIS process
    created: float = 0.0
    library_version: str = ""
    fingerprint_inputs: Optional[Dict] = None
    #: predict_exchange_every's calibrated depth-crossover estimate
    #: (observability: what the analytic model alone would have picked)
    predicted_best_depth: Optional[int] = None
    #: kernel -> the VMEM planner's prescribed block shape + metrics
    #: (:func:`tiling_record`) — plan-cache records carry the chosen
    #: tile shape the same way they carry the chosen exchange method
    tiling: Dict[str, Dict] = dataclasses.field(default_factory=dict)
    #: the placement mode the plan was tuned under ("auto" | "qap" |
    #: "trivial") — records from before the placement axis existed
    #: load as "auto" (the then-only behavior)
    placement: str = "auto"

    def to_record(self) -> Dict:
        rec = dataclasses.asdict(self)  # recurses into Candidate
        rec["schema"] = SCHEMA_VERSION
        return rec

    @staticmethod
    def from_record(rec: Dict) -> "Plan":
        cfg = rec["config"]
        depths = cfg.get("depths")  # pre-per-axis records lack the key
        return Plan(
            config=Candidate(str(cfg["method"]),
                             int(cfg["exchange_every"]),
                             bool(cfg.get("overlap", False)),
                             str(cfg.get("wire_format", "f32")),
                             str(cfg.get("wire_layout", "slab")),
                             tuple(int(v) for v in depths)
                             if depths is not None else None),
            fingerprint=str(rec["fingerprint"]),
            coefficients=dict(rec.get("coefficients", {})),
            costs=dict(rec.get("costs", {})),
            provenance=str(rec.get("provenance", "tuned")),
            measurements=int(rec.get("measurements", 0)),
            created=float(rec.get("created", 0.0)),
            library_version=str(rec.get("library_version", "")),
            fingerprint_inputs=rec.get("fingerprint_inputs"),
            predicted_best_depth=rec.get("predicted_best_depth"),
            tiling=dict(rec.get("tiling", {})),
            placement=str(rec.get("placement", "auto")),
        )
