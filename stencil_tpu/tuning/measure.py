"""Measurement harness: short jitted timing loops over the live mesh.

Two timer implementations share one interface (the injectable-timer
contract the off-TPU tests rely on):

* :class:`MeshTimer` — the real thing: ``pingpong(nbytes)`` times a
  neighbor ring shift (the apps/pingpong.py harness, inlined) and
  ``exchange_round(candidate, geom)`` times one deep exchange round of
  a throwaway jitted program built from the EXISTING exchange engines
  (``parallel.exchange.make_exchange``) — the same code path
  ``DistributedDomain.realize`` will run, so the measurement is the
  deployment.
* :class:`FakeTimer` — deterministic: evaluates the SAME analytic
  alpha-beta model the calibrated cost model uses
  (``analysis.costmodel.exchange_round_model``), from injected
  coefficients. Search, fit, pruning, and cache logic are exercised
  bit-for-bit on CPU with zero hardware variance; tier-1 runs the
  whole autotune end-to-end this way.

:class:`CountingTimer` wraps either and counts invocations — the
number the plan records as ``measurements`` and the cache-hit CI gate
asserts is zero on the second run.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

import numpy as np

from ..analysis.costmodel import LinkCoefficients, exchange_round_model
from ..geometry import Dim3
from .plan import Candidate, TuneGeometry


class FakeTimer:
    """Deterministic measurement stand-in driven by injected
    alpha-beta coefficients (and optional per-method scale factors for
    tests that need a specific winner)."""

    def __init__(self, coeffs: Optional[LinkCoefficients] = None,
                 scale: Optional[Dict[str, float]] = None,
                 overlap_factor: float = 1.0,
                 dcn_coeffs: Optional[LinkCoefficients] = None,
                 axis_coeffs: Optional[
                     Dict[str, LinkCoefficients]] = None) -> None:
        self.coeffs = coeffs if coeffs is not None else LinkCoefficients(
            alpha_s=50e-6, beta_bytes_per_s=1e10)
        self.scale = dict(scale or {})
        self.overlap_factor = float(overlap_factor)
        self.dcn_coeffs = dcn_coeffs
        #: per-mesh-axis coefficients for the topology-fingerprint
        #: protocol (pingpong_axis); axes not listed fall back to the
        #: global coeffs — the anisotropic-fabric test hook
        self.axis_coeffs = dict(axis_coeffs or {})

    @property
    def has_dcn(self) -> bool:
        return self.dcn_coeffs is not None

    def pingpong(self, nbytes: int) -> float:
        return self.coeffs.seconds(1, nbytes)

    def pingpong_dcn(self, nbytes: int) -> float:
        assert self.dcn_coeffs is not None, "no DCN link configured"
        return self.dcn_coeffs.seconds(1, nbytes)

    def pingpong_axis(self, name: str, nbytes: int) -> float:
        """Seconds per ring shift along ONE named mesh axis — the
        per-link sample source of the topology fingerprint
        (``observatory.linkmap.measure_topology``)."""
        return self.axis_coeffs.get(name, self.coeffs).seconds(1, nbytes)

    def exchange_round(self, cand: Candidate, geom: TuneGeometry
                       ) -> float:
        depths = cand.depths_xyz()
        if len(set(depths)) > 1:
            # asymmetric group: axis a re-ships its deep slab
            # s / s_a times per group (parallel.temporal.refresh_axes)
            from ..analysis.costmodel import per_axis_round_model
            per_axis = per_axis_round_model(
                cand.method, geom.shard_interior_zyx, geom.radius,
                geom.counts, geom.elem_sizes, depths,
                geom.dtype_groups, wire_format=cand.wire_format,
                wire_layout=cand.wire_layout)
            s = max(depths)
            messages = sum(per_axis[n][0] * (s // depths[a])
                           for a, n in enumerate("xyz"))
            nbytes = sum(per_axis[n][1] * (s // depths[a])
                         for a, n in enumerate("xyz"))
        else:
            messages, nbytes = exchange_round_model(
                cand.method, geom.shard_interior_zyx, geom.radius,
                geom.counts, geom.elem_sizes, cand.exchange_every,
                geom.dtype_groups, wire_format=cand.wire_format,
                wire_layout=cand.wire_layout)
        t = self.coeffs.seconds(messages, nbytes)
        t *= self.scale.get(cand.method, 1.0)
        if cand.overlap:
            t *= self.overlap_factor
        return t


class MeshTimer:
    """Micro-benchmarks on the live mesh. ``dtypes`` are the realized
    quantities' dtypes (one timing field each, matching the deployed
    buffer layout); ``rem``/``nonperiodic`` mirror the orchestrator so
    the timed program is the one realize() would build."""

    def __init__(self, mesh, local: Dim3, dtypes: Sequence,
                 rem: Dim3 = Dim3(0, 0, 0), nonperiodic: bool = False,
                 reps: int = 5, dcn_axis: Optional[int] = None) -> None:
        self.mesh = mesh
        self.local = local
        self.dtypes = [np.dtype(d) for d in dtypes]
        self.rem = rem
        self.nonperiodic = nonperiodic
        self.reps = int(reps)
        self.dcn_axis = dcn_axis

    @property
    def has_dcn(self) -> bool:
        return self.dcn_axis is not None

    def _sync(self, tree) -> None:
        from ..utils.timers import device_sync
        device_sync(tree)

    def pingpong(self, nbytes: int) -> float:
        """Seconds per neighbor ring shift of one ``nbytes`` message
        along the largest mesh axis (the alpha-beta sample source)."""
        name = max(self.mesh.shape, key=lambda k: self.mesh.shape[k])
        return self._ring_shift_seconds(name, nbytes)

    def pingpong_dcn(self, nbytes: int) -> float:
        """Same, along the slice-blocked (DCN) mesh axis — the slow
        link class's alpha-beta samples."""
        assert self.dcn_axis is not None, "no DCN axis configured"
        return self._ring_shift_seconds("xyz"[self.dcn_axis], nbytes)

    def pingpong_axis(self, name: str, nbytes: int) -> float:
        """Seconds per ring shift along ONE named mesh axis (the
        topology-fingerprint sample source): each fabric axis gets its
        own alpha-beta fit instead of sharing the largest axis's."""
        return self._ring_shift_seconds(name, nbytes)

    def _ring_shift_seconds(self, name: str, nbytes: int) -> float:
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        n = self.mesh.shape[name]
        elems = max(int(nbytes) // 4, 1)
        spec = P(name)
        sharding = NamedSharding(self.mesh, spec)

        def shift(x):
            if n == 1:
                return x + 1.0
            return lax.ppermute(x, name,
                                [(i, (i + 1) % n) for i in range(n)])

        fn = jax.jit(jax.shard_map(shift, mesh=self.mesh, in_specs=spec,
                                   out_specs=spec, check_vma=False))
        x = jax.device_put(jnp.zeros((elems * n,), jnp.float32), sharding)
        x = fn(x)
        self._sync(x)
        t0 = time.perf_counter()
        for _ in range(self.reps):
            x = fn(x)
        self._sync(x)
        return (time.perf_counter() - t0) / self.reps

    def exchange_round(self, cand: Candidate, geom: TuneGeometry
                       ) -> float:
        """Seconds per deep exchange round of ``cand``'s configuration,
        timed on a throwaway jitted program over zero fields — built by
        the same ``make_exchange`` the orchestrator deploys. An
        asymmetric-depth candidate times one whole GROUP (the per-axis
        deep exchange plus every mid-group refresh) through
        ``temporal_shard_steps`` with an identity update — again the
        deployed code path — so the caller's ``/ exchange_every``
        amortization yields per-step seconds either way."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..local_domain import raw_size, zyx_shape
        from ..parallel.exchange import make_exchange
        from ..parallel.mesh import mesh_dim
        from ..parallel.methods import Method

        depths = cand.depths_xyz()
        if len(set(depths)) > 1:
            return self._asym_group_seconds(cand, geom)
        deep = geom.radius.deepened(cand.exchange_every)
        dim = mesh_dim(self.mesh)
        padded = raw_size(self.local, deep)
        gshape = zyx_shape(padded * dim)
        kw = {}
        if cand.wire_format != "f32":
            # narrow-wire candidates time the gated engine — the same
            # certificate-checked program realize() would deploy
            kw = dict(wire_format=cand.wire_format,
                      fields_spec={
                          f"q{i}": jax.ShapeDtypeStruct(gshape, dt)
                          for i, dt in enumerate(self.dtypes)})
        ex = make_exchange(self.mesh, deep, Method[cand.method],
                           rem=self.rem, nonperiodic=self.nonperiodic,
                           wire_layout=cand.wire_layout, **kw)
        sharding = NamedSharding(self.mesh, P("z", "y", "x"))
        make = {i: jax.jit(lambda dt=dt: jnp.zeros(gshape, dt),
                           out_shardings=sharding)
                for i, dt in enumerate(self.dtypes)}
        fields = {f"q{i}": mk() for i, mk in make.items()}
        # make_exchange DONATES its input dict: rebind every call
        fields = dict(ex(fields))
        self._sync(fields)
        t0 = time.perf_counter()
        for _ in range(self.reps):
            fields = dict(ex(fields))
        self._sync(fields)
        return (time.perf_counter() - t0) / self.reps

    def _asym_group_seconds(self, cand: Candidate, geom: TuneGeometry
                            ) -> float:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..local_domain import raw_size, zyx_shape
        from ..parallel.mesh import mesh_dim
        from ..parallel.methods import Method
        from ..parallel.temporal import temporal_shard_steps

        depths = Dim3(*cand.depths_xyz())
        dim = mesh_dim(self.mesh)
        deep = geom.radius.deepened(depths)
        padded = raw_size(self.local, deep)
        gshape = zyx_shape(padded * dim)

        def upd(blocks, dims, off, k):
            return dict(blocks)

        def shard(fields):
            return temporal_shard_steps(
                fields, geom.radius, dim, Method[cand.method], upd,
                depths, rem=self.rem, nonperiodic=self.nonperiodic,
                wire_format=(cand.wire_format
                             if cand.wire_format != "f32" else None),
                wire_layout=cand.wire_layout)

        spec = P("z", "y", "x")
        sharding = NamedSharding(self.mesh, spec)
        names = [f"q{i}" for i in range(len(self.dtypes))]
        specs = {q: spec for q in names}
        ex = jax.jit(jax.shard_map(shard, mesh=self.mesh,
                                   in_specs=(specs,), out_specs=specs,
                                   check_vma=False))
        make = {q: jax.jit(lambda dt=dt: jnp.zeros(gshape, dt),
                           out_shardings=sharding)
                for q, dt in zip(names, self.dtypes)}
        fields = {q: mk() for q, mk in make.items()}
        fields = dict(ex(fields))
        self._sync(fields)
        t0 = time.perf_counter()
        for _ in range(self.reps):
            fields = dict(ex(fields))
        self._sync(fields)
        return (time.perf_counter() - t0) / self.reps


class CountingTimer:
    """Delegating wrapper that counts timer invocations — the
    ``Plan.measurements`` source and the cache-hit-skips-measurement
    assertion's witness."""

    def __init__(self, timer) -> None:
        self._timer = timer
        self.calls = 0

    @property
    def has_dcn(self) -> bool:
        return bool(getattr(self._timer, "has_dcn", False))

    def pingpong(self, nbytes: int) -> float:
        self.calls += 1
        return self._timer.pingpong(nbytes)

    def pingpong_dcn(self, nbytes: int) -> float:
        self.calls += 1
        return self._timer.pingpong_dcn(nbytes)

    def pingpong_axis(self, name: str, nbytes: int) -> float:
        self.calls += 1
        return self._timer.pingpong_axis(name, nbytes)

    def exchange_round(self, cand: Candidate, geom: TuneGeometry
                       ) -> float:
        self.calls += 1
        return self._timer.exchange_round(cand, geom)
