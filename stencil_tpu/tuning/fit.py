"""Alpha-beta coefficient fitting from measured timings.

The reference measures the machine before planning (bandwidth matrix +
GPU distance matrix, reference: src/machine.cu, bin/pingpong.cu); the
TPU analog fits the two-parameter LogP-style model

    seconds(message) = alpha + bytes / beta

to ring-shift timings at several message sizes (the pingpong harness,
apps/pingpong.py). The fitted :class:`LinkCoefficients` replace the
assumed constants in ``analysis/costmodel.py`` so the candidate
ranking — ``configured_step_seconds`` / ``predict_exchange_every`` —
prices the actual fabric, not a datasheet.
"""

from __future__ import annotations

from typing import Callable, Dict, Sequence, Tuple

from ..analysis.costmodel import LinkCoefficients

#: message sizes the calibration samples: one latency-dominated, one
#: bandwidth-dominated, one in between (least squares over all three)
DEFAULT_CALIBRATION_BYTES: Tuple[int, ...] = (1 << 12, 1 << 17, 1 << 21)


def fit_alpha_beta(samples: Sequence[Tuple[int, float]]
                   ) -> LinkCoefficients:
    """Least-squares fit of ``seconds = alpha + bytes / beta`` over
    ``(bytes, seconds)`` samples. Degenerate inputs (a single sample,
    or zero byte spread) fall back to attributing everything to
    latency — safe for ranking, which only needs relative costs."""
    if not samples:
        raise ValueError("fit_alpha_beta needs at least one sample")
    if len(samples) == 1 or len({b for b, _ in samples}) == 1:
        alpha = max(min(t for _, t in samples), 1e-12)
        return LinkCoefficients(alpha_s=alpha, beta_bytes_per_s=1e30)
    n = len(samples)
    sx = sum(float(b) for b, _ in samples)
    sy = sum(float(t) for _, t in samples)
    sxx = sum(float(b) * float(b) for b, _ in samples)
    sxy = sum(float(b) * float(t) for b, t in samples)
    denom = n * sxx - sx * sx
    slope = (n * sxy - sx * sy) / denom     # seconds per byte = 1/beta
    alpha = (sy - slope * sx) / n
    # noisy small-sample fits can cross zero; clamp to physical values
    alpha = max(alpha, 1e-12)
    beta = 1.0 / slope if slope > 0 else 1e30
    return LinkCoefficients(alpha_s=alpha, beta_bytes_per_s=beta)


def calibrate_link(pingpong: Callable[[int], float],
                   sizes: Sequence[int] = DEFAULT_CALIBRATION_BYTES
                   ) -> LinkCoefficients:
    """Measure ``pingpong(nbytes)`` (seconds per neighbor shift of one
    ``nbytes`` message) at each size and fit the alpha-beta model."""
    return fit_alpha_beta([(int(b), float(pingpong(int(b))))
                           for b in sizes])


def coefficients_record(coeffs_by_link: Dict[str, LinkCoefficients]
                        ) -> Dict[str, Dict[str, float]]:
    """JSON-ready form for the plan cache."""
    return {link: {"alpha_s": c.alpha_s,
                   "beta_bytes_per_s": c.beta_bytes_per_s}
            for link, c in coeffs_by_link.items()}
