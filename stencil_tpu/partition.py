"""Grid partitioning: prime-factor recursive splitters.

TPU-native re-implementation of the reference's partition layer
(reference: include/stencil/partition.hpp:20-256). Splits a global 3D
grid into N subdomains with +-1-point remainder handling, either flat
(``RankPartition``) or two-level "system x node" (``NodePartition``,
which on TPU maps to "slice/DCN tier x chips-within-slice/ICI tier") with
the communication-minimizing split rule: cut the plane whose interface
area x (radius+ + radius-) is smallest (reference: partition.hpp:167-208).
"""

from __future__ import annotations

from typing import List, Optional

from .geometry import Dim3, Dim3Like, Radius
from .numerics import div_ceil, prime_factors


def _remainder_size(base: Dim3, rem: Dim3, idx: Dim3) -> Dim3:
    """+-1 remainder handling shared by all partitions
    (reference: partition.hpp:55-69, 222-236)."""
    ret = [base.x, base.y, base.z]
    for a, (r, i) in enumerate(zip(rem, idx)):
        if r != 0 and i >= r:
            ret[a] -= 1
    return Dim3(*ret)


def _remainder_origin(base: Dim3, rem: Dim3, idx: Dim3) -> Dim3:
    ret = [base.x * idx.x, base.y * idx.y, base.z * idx.z]
    for a, (r, i) in enumerate(zip(rem, idx)):
        if r != 0 and i >= r:
            ret[a] -= i - r
    return Dim3(*ret)


class RankPartition:
    """Flat split of ``size`` into ``n`` subdomains
    (reference: include/stencil/partition.hpp:20-116).

    Repeatedly divides the longest dimension by each prime factor of
    ``n`` (descending); remainder handling gives +-1-sized subdomains.
    """

    def __init__(self, size: Dim3Like, n: int) -> None:
        size = Dim3.of(size)
        self.global_size = size
        dim = Dim3(1, 1, 1)
        sz = size
        for amt in prime_factors(n):
            if amt < 2:
                continue
            if sz.x >= sz.y and sz.x >= sz.z:
                sz = Dim3(div_ceil(sz.x, amt), sz.y, sz.z)
                dim = Dim3(dim.x * amt, dim.y, dim.z)
            elif sz.y >= sz.z:
                sz = Dim3(sz.x, div_ceil(sz.y, amt), sz.z)
                dim = Dim3(dim.x, dim.y * amt, dim.z)
            else:
                sz = Dim3(sz.x, sz.y, div_ceil(sz.z, amt))
                dim = Dim3(dim.x, dim.y, dim.z * amt)
        self._dim = dim
        self._size = sz
        self._rem = size % dim

    @classmethod
    def from_dim(cls, size: Dim3Like, dim: Dim3Like) -> "RankPartition":
        """Partition with an explicitly chosen subdomain grid ``dim``
        (used when the mesh shape is fixed by the device topology)."""
        size = Dim3.of(size)
        dim = Dim3.of(dim)
        p = cls(size, 1)
        p._dim = dim
        p._size = Dim3(div_ceil(size.x, dim.x), div_ceil(size.y, dim.y),
                       div_ceil(size.z, dim.z))
        p._rem = size % dim
        return p

    def dim(self) -> Dim3:
        """Number of subdomains along each axis."""
        return self._dim

    def subdomain_size(self, idx: Dim3Like) -> Dim3:
        """Size of subdomain ``idx``; remainder handling per
        reference partition.hpp:55-69."""
        return _remainder_size(self._size, self._rem, Dim3.of(idx))

    def subdomain_origin(self, idx: Dim3Like) -> Dim3:
        return _remainder_origin(self._size, self._rem, Dim3.of(idx))

    def linearize(self, idx: Dim3Like) -> int:
        idx = Dim3.of(idx)
        d = self._dim
        assert 0 <= idx.x < d.x and 0 <= idx.y < d.y and 0 <= idx.z < d.z
        return idx.x + idx.y * d.x + idx.z * d.y * d.x

    def dimensionize(self, i: int) -> Dim3:
        d = self._dim
        assert 0 <= i < d.flatten()
        return Dim3(i % d.x, (i // d.x) % d.y, i // (d.x * d.y))


def _iface_split(sz: Dim3, dim: Dim3, radius: Radius, n: int):
    """One tier of the communication-minimizing recursive split
    (reference: partition.hpp:167-208): for each prime factor (desc),
    cut the plane with the smallest interface area x (r+ + r-)."""
    for amt in prime_factors(n):
        if amt < 2:
            continue
        x_iface = sz.y * sz.z * (radius.dir((1, 0, 0)) + radius.dir((-1, 0, 0)))
        y_iface = sz.x * sz.z * (radius.dir((0, 1, 0)) + radius.dir((0, -1, 0)))
        z_iface = sz.x * sz.y * (radius.dir((0, 0, 1)) + radius.dir((0, 0, -1)))
        if x_iface <= y_iface and x_iface <= z_iface:
            sz = Dim3(div_ceil(sz.x, amt), sz.y, sz.z)
            dim = Dim3(dim.x * amt, dim.y, dim.z)
        elif y_iface <= z_iface:
            sz = Dim3(sz.x, div_ceil(sz.y, amt), sz.z)
            dim = Dim3(dim.x, dim.y * amt, dim.z)
        else:
            sz = Dim3(sz.x, sz.y, div_ceil(sz.z, amt))
            dim = Dim3(dim.x, dim.y, dim.z * amt)
    return sz, dim


class NodePartition:
    """Two-level split: ``nodes`` (outer/DCN tier) x ``gpus`` per node
    (inner/ICI tier) (reference: include/stencil/partition.hpp:120-256).

    On TPU the outer tier corresponds to slices or hosts joined by DCN
    and the inner tier to chips joined by the ICI torus.
    """

    def __init__(self, size: Dim3Like, radius: Radius, nodes: int, gpus: int) -> None:
        size = Dim3.of(size)
        self.global_size = size
        sz = size
        sys_dim = Dim3(1, 1, 1)
        node_dim = Dim3(1, 1, 1)
        sz, sys_dim = _iface_split(sz, sys_dim, radius, nodes)
        sz, node_dim = _iface_split(sz, node_dim, radius, gpus)
        self._sys_dim = sys_dim
        self._node_dim = node_dim
        self._size = sz
        self._rem = size % (sys_dim * node_dim)

    def sys_dim(self) -> Dim3:
        return self._sys_dim

    def node_dim(self) -> Dim3:
        return self._node_dim

    def dim(self) -> Dim3:
        return self._sys_dim * self._node_dim

    def subdomain_size(self, idx: Dim3Like) -> Dim3:
        return _remainder_size(self._size, self._rem, Dim3.of(idx))

    def subdomain_origin(self, idx: Dim3Like) -> Dim3:
        return _remainder_origin(self._size, self._rem, Dim3.of(idx))

    @staticmethod
    def _dimensionize(i: int, d: Dim3) -> Dim3:
        assert 0 <= i < d.flatten()
        return Dim3(i % d.x, (i // d.x) % d.y, i // (d.x * d.y))

    @staticmethod
    def _linearize(idx: Dim3, d: Dim3) -> int:
        return idx.x + idx.y * d.x + idx.z * d.y * d.x

    def sys_idx(self, i: int) -> Dim3:
        return self._dimensionize(i, self._sys_dim)

    def node_idx(self, i: int) -> Dim3:
        return self._dimensionize(i, self._node_dim)


def sweep_wire_bytes(part: RankPartition, radius: Radius,
                     elem_size: int) -> dict:
    """Whole-mesh wire bytes per exchange under the sequential-sweep
    engine, derived from the PARTITION alone — the planning-side
    statement of the same analytic model whose per-shard form
    (``parallel.exchange.exchanged_bytes_per_sweep``) feeds the
    static analyzer's HLO cross-check (``analysis/costmodel.py``) and
    the runtime byte counters; ``tests/test_lint.py`` pins the two
    derivations equal so they cannot fork.

    Every shard ships capacity-sized slabs: allocations are sized to
    the ceil subdomain (uneven +-1 remainders included — a short
    shard's slack rows ride the wire as filler, exactly what the
    static-shape ppermute program moves), and each axis sweep's slab
    spans the full padded extents of the other two axes (edge/corner
    ride-along). Axes with one subdomain are in-core wraps and cost
    nothing. Returns ``{"x": .., "y": .., "z": .., "total": ..}``
    (bytes over the whole mesh, the ``exchange_bytes_total``
    convention).
    """
    dim = part.dim()
    cap = Dim3(div_ceil(part.global_size.x, dim.x),
               div_ceil(part.global_size.y, dim.y),
               div_ceil(part.global_size.z, dim.z))
    padded = cap + radius.pad_lo() + radius.pad_hi()
    out = {"x": 0, "y": 0, "z": 0}
    for a, name in enumerate(("x", "y", "z")):
        if dim[a] <= 1:
            continue
        other = 1
        for b in range(3):
            if b != a:
                other *= padded[b]
        out[name] = radius.wire_rows(a) * other * elem_size * dim.flatten()
    out["total"] = out["x"] + out["y"] + out["z"]
    return out


def temporal_sweep_wire_bytes(part: RankPartition, radius: Radius,
                              elem_size: int, steps: int) -> dict:
    """Amortized per-STEP whole-mesh wire bytes under ``steps``-deep
    temporal blocking: one ``radius.deepened(steps)`` exchange feeds
    ``steps`` stencil steps, so each step is charged ``1/steps`` of the
    deep sweep. The deep slabs are priced on the DEEPENED padded
    cross-sections (slabs span the full allocation of the other two
    axes — exactly what the static-shape ppermute program moves), which
    is why amortized bytes do not drop ``steps``x: rows amortize to the
    base count but cross-sections grow by ``2*steps*r`` per axis. The
    win is the ``steps``x cut in exchange ROUNDS; see
    ``analysis.costmodel.predict_exchange_every`` for the crossover.
    Returns per-axis + total floats (``steps == 1`` reproduces
    ``sweep_wire_bytes``)."""
    deep = sweep_wire_bytes(part, radius.deepened(steps), elem_size)
    return {k: v / steps for k, v in deep.items()}


def halo_byte_model(part: RankPartition, radius: Radius,
                    elem_size: int) -> dict:
    """The reference's per-message byte-placement model: for every
    subdomain and every direction with a nonzero radius, the halo
    region is (face/edge/corner area) x radius x element size
    (reference: local_domain.cuh halo_bytes over src/stencil.cu:331-344
    message planning), with the ACTUAL +-1-remainder subdomain sizes.
    Returns bytes per direction kind plus the total — the geometric
    lower bound a 26-message exchange would move (the sweep engine
    moves ``sweep_wire_bytes`` instead: fewer, fatter messages).
    """
    from .geometry import all_directions, direction_kind
    from .local_domain import halo_bytes

    dim = part.dim()
    out = {"face": 0, "edge": 0, "corner": 0}
    for iz in range(dim.z):
        for iy in range(dim.y):
            for ix in range(dim.x):
                sz = part.subdomain_size(Dim3(ix, iy, iz))
                for d in all_directions():
                    if radius.dir(d) == 0:
                        continue
                    if any(dim[a] <= 1 and d[a] != 0 for a in range(3)):
                        continue  # in-core wrap, no wire traffic
                    out[direction_kind(d)] += halo_bytes(
                        d, sz, radius, elem_size)
    out["total"] = out["face"] + out["edge"] + out["corner"]
    return out


def partition_dims_even(size: Dim3Like, n: int) -> Dim3:
    """Choose a subdomain grid ``dim`` with ``dim.flatten() == n`` that
    divides ``size`` exactly, preferring the RankPartition's greedy shape.

    XLA SPMD wants equal shards; when the RankPartition shape would leave
    a remainder we search prime-factor assignments for an exact divisor
    shape (SURVEY.md section 7 "uneven subdomains" risk). Raises
    ValueError if none exists.
    """
    size = Dim3.of(size)
    rp = RankPartition(size, n)
    d = rp.dim()
    if (size % d) == Dim3(0, 0, 0):
        return d
    best: List[Dim3] = []
    for dx in range(1, n + 1):
        if n % dx or size.x % dx:
            continue
        for dy in range(1, n // dx + 1):
            if (n // dx) % dy or size.y % dy:
                continue
            dz = n // dx // dy
            if size.z % dz:
                continue
            best.append(Dim3(dx, dy, dz))
    if not best:
        raise ValueError(f"no exact {n}-way factorization divides {size}")
    # prefer the most cube-like (smallest total interface area)
    def iface(d: Dim3) -> int:
        sx, sy, sz = size.x // d.x, size.y // d.y, size.z // d.z
        return sy * sz * (d.x > 1) + sx * sz * (d.y > 1) + sx * sy * (d.z > 1)
    return min(best, key=iface)


def exact_partition_candidates(size: Dim3Like, n: int) -> List[Dim3]:
    """All subdomain grids ``dim`` with ``dim.flatten() == n`` that
    divide ``size`` exactly — the candidate set the hierarchical
    partition planner prices with the per-link cost model
    (analysis/costmodel.asymmetric_step_seconds). Empty when no exact
    factorization exists; the caller falls back to the
    NodePartition/partition_dims_even ladder."""
    size = Dim3.of(size)
    out: List[Dim3] = []
    for dx in range(1, n + 1):
        if n % dx or size.x % dx:
            continue
        for dy in range(1, n // dx + 1):
            if (n // dx) % dy or size.y % dy:
                continue
            dz = n // dx // dy
            if size.z % dz:
                continue
            out.append(Dim3(dx, dy, dz))
    return out


def partition_dims_even_xfree(size: Dim3Like, n: int,
                              align: int = 1) -> Optional[Dim3]:
    """An exact ``n``-way factorization (1, dy, dz) that leaves the
    x (lane) axis unsharded, preferring the most cube-like (y, z) split
    — the decomposition the fused halo kernels want (cutting the lane
    dimension is the worst choice on TPU; see ops/pallas_halo.py).
    ``align`` additionally requires the local y/z extents to be
    multiples of it (the kernels' sublane-tile constraint).
    Returns None when no such factorization divides ``size``.
    """
    size = Dim3.of(size)
    best: List[Dim3] = []
    for dy in range(1, n + 1):
        if n % dy or size.y % dy:
            continue
        dz = n // dy
        if size.z % dz:
            continue
        if (size.y // dy) % align or (size.z // dz) % align:
            continue
        best.append(Dim3(1, dy, dz))
    if not best:
        return None

    def iface(d: Dim3) -> int:
        sx, sy, sz = size.x, size.y // d.y, size.z // d.z
        return sx * sz * (d.y > 1) + sx * sy * (d.z > 1)
    return min(best, key=iface)
