"""Geometry value types: Dim3, Rect3, DirectionMap, Radius.

TPU-native re-implementation of the reference's foundation layer
(reference: include/stencil/dim3.hpp, rect3.hpp, direction_map.hpp,
radius.hpp). These are pure-Python immutable values used for *planning*
(partitioning, halo geometry, byte accounting); the data plane is JAX.

Conventions
-----------
* A *direction* is a tuple ``(dx, dy, dz)`` with each component in
  ``{-1, 0, 1}``. There are 26 non-zero directions.
* ``Dim3`` is an immutable integer 3-vector with elementwise arithmetic
  and periodic ``wrap`` (reference: dim3.hpp:208-230).
* ``Radius`` stores 26 independent per-direction radii. The *allocation*
  halo padding on each face side equals the face radius on that side
  (reference: local_domain.cuh raw_size()); edge/corner radii gate
  whether diagonal-neighbor data is required (reference:
  src/stencil.cu:344).
"""

from __future__ import annotations

import operator
from typing import Dict, Iterator, List, NamedTuple, Tuple, Union

Dim3Like = Union["Dim3", Tuple[int, int, int]]
# temporal-blocking depth spec: uniform int, per-axis dict
# ({"z": 4, "y": 1, "x": 1}), or a 3-tuple/Dim3 (see normalize_depths)
DepthsLike = Union[int, "Dim3", Tuple[int, int, int], Dict[str, int]]


def _as_component(name: str, v) -> int:
    """Validate one Dim3/Radius component: exact integers only. A
    float slipping in (e.g. ``gsize.x / 2`` instead of ``// 2``) used
    to truncate silently and flow into slab-width math; now it is a
    loud ``ValueError`` at construction."""
    try:
        return operator.index(v)
    except TypeError:
        raise ValueError(
            f"Dim3/Radius component {name}={v!r} is not an integer "
            f"(got {type(v).__name__}; use // for integer division)"
        ) from None


class _Dim3Base(NamedTuple):
    x: int
    y: int
    z: int


class Dim3(_Dim3Base):
    """Immutable int64 3-vector (reference: include/stencil/dim3.hpp).

    Components must be exact integers (validated at construction);
    negative values are legal — direction vectors and differences need
    them. Non-negativity of *sizes* is the caller's contract; radii are
    validated in :class:`Radius`.

    Note: the reference's ``operator!=``/``max`` have latent bugs
    (dim3.hpp:195, 57-63); this class implements the intended semantics.
    """

    __slots__ = ()

    def __new__(cls, x: int, y: int, z: int) -> "Dim3":
        return super().__new__(cls, _as_component("x", x),
                               _as_component("y", y),
                               _as_component("z", z))

    # -- constructors -------------------------------------------------
    @staticmethod
    def of(v: Dim3Like) -> "Dim3":
        if isinstance(v, Dim3):
            return v
        return Dim3(v[0], v[1], v[2])

    @staticmethod
    def filled(v: int) -> "Dim3":
        return Dim3(v, v, v)

    # -- arithmetic ---------------------------------------------------
    def __add__(self, o: Dim3Like) -> "Dim3":  # type: ignore[override]
        o = Dim3.of(o)
        return Dim3(self.x + o.x, self.y + o.y, self.z + o.z)

    def __sub__(self, o: Dim3Like) -> "Dim3":
        o = Dim3.of(o)
        return Dim3(self.x - o.x, self.y - o.y, self.z - o.z)

    def __mul__(self, o: Union[int, Dim3Like]) -> "Dim3":  # type: ignore[override]
        if isinstance(o, int):
            return Dim3(self.x * o, self.y * o, self.z * o)
        o = Dim3.of(o)
        return Dim3(self.x * o.x, self.y * o.y, self.z * o.z)

    __rmul__ = __mul__

    def __floordiv__(self, o: Union[int, Dim3Like]) -> "Dim3":
        if isinstance(o, int):
            o = Dim3(o, o, o)
        o = Dim3.of(o)
        return Dim3(self.x // o.x, self.y // o.y, self.z // o.z)

    def __mod__(self, o: Dim3Like) -> "Dim3":
        o = Dim3.of(o)
        return Dim3(self.x % o.x, self.y % o.y, self.z % o.z)

    def __neg__(self) -> "Dim3":
        return Dim3(-self.x, -self.y, -self.z)

    # -- queries ------------------------------------------------------
    def flatten(self) -> int:
        """Product of components == element count (reference: dim3.hpp)."""
        return self.x * self.y * self.z

    def any_lt(self, v: int) -> bool:
        return self.x < v or self.y < v or self.z < v

    def all_lt(self, v: int) -> bool:
        return self.x < v and self.y < v and self.z < v

    def all_ge(self, v: int) -> bool:
        return self.x >= v and self.y >= v and self.z >= v

    def all_gt(self, v: int) -> bool:
        return self.x > v and self.y > v and self.z > v

    def elementwise_max(self, o: Dim3Like) -> "Dim3":
        o = Dim3.of(o)
        return Dim3(max(self.x, o.x), max(self.y, o.y), max(self.z, o.z))

    def elementwise_min(self, o: Dim3Like) -> "Dim3":
        o = Dim3.of(o)
        return Dim3(min(self.x, o.x), min(self.y, o.y), min(self.z, o.z))

    def wrap(self, lims: Dim3Like) -> "Dim3":
        """Periodic modulo into ``[0, lims)`` (reference: dim3.hpp:208-230)."""
        lims = Dim3.of(lims)
        return Dim3(self.x % lims.x, self.y % lims.y, self.z % lims.z)

    def __repr__(self) -> str:
        return f"[{self.x},{self.y},{self.z}]"


ZERO = Dim3(0, 0, 0)


def all_directions(include_zero: bool = False) -> Iterator[Dim3]:
    """Iterate the 26 (or 27) direction vectors in the reference's z-y-x
    loop order (reference: src/stencil.cu:331-336)."""
    for dz in (-1, 0, 1):
        for dy in (-1, 0, 1):
            for dx in (-1, 0, 1):
                d = Dim3(dx, dy, dz)
                if d == ZERO and not include_zero:
                    continue
                yield d


def direction_kind(d: Dim3Like) -> str:
    """'face' | 'edge' | 'corner' by number of nonzero components."""
    d = Dim3.of(d)
    n = (d.x != 0) + (d.y != 0) + (d.z != 0)
    return {1: "face", 2: "edge", 3: "corner"}[n] if n else "center"


class Rect3(NamedTuple):
    """Half-open box ``[lo, hi)`` (reference: include/stencil/rect3.hpp:13-22)."""

    lo: Dim3
    hi: Dim3

    @staticmethod
    def of(lo: Dim3Like, hi: Dim3Like) -> "Rect3":
        return Rect3(Dim3.of(lo), Dim3.of(hi))

    def extent(self) -> Dim3:
        return self.hi - self.lo

    def empty(self) -> bool:
        e = self.extent()
        return e.x <= 0 or e.y <= 0 or e.z <= 0

    def contains(self, p: Dim3Like) -> bool:
        p = Dim3.of(p)
        return (self.lo.x <= p.x < self.hi.x
                and self.lo.y <= p.y < self.hi.y
                and self.lo.z <= p.z < self.hi.z)

    def __repr__(self) -> str:
        return f"Rect3({self.lo!r}..{self.hi!r})"


class DirectionMap:
    """3x3x3 table indexed by direction vectors in {-1,0,1}^3
    (reference: include/stencil/direction_map.hpp:43-57)."""

    __slots__ = ("_data",)

    def __init__(self, fill=None) -> None:
        self._data: List = [fill] * 27

    @staticmethod
    def _idx(x: int, y: int, z: int) -> int:
        assert -1 <= x <= 1 and -1 <= y <= 1 and -1 <= z <= 1
        return (x + 1) + (y + 1) * 3 + (z + 1) * 9

    def at_dir(self, x: int, y: int, z: int):
        return self._data[self._idx(x, y, z)]

    def set_dir(self, x: int, y: int, z: int, v) -> None:
        self._data[self._idx(x, y, z)] = v

    def __getitem__(self, d: Dim3Like):
        d = Dim3.of(d)
        return self.at_dir(d.x, d.y, d.z)

    def __setitem__(self, d: Dim3Like, v) -> None:
        d = Dim3.of(d)
        self.set_dir(d.x, d.y, d.z, v)

    def __eq__(self, o) -> bool:
        return isinstance(o, DirectionMap) and self._data == o._data

    def copy(self) -> "DirectionMap":
        m = DirectionMap()
        m._data = list(self._data)
        return m


class Radius:
    """Per-direction stencil radius: 26 directions + center
    (reference: include/stencil/radius.hpp:14-104).

    Supports asymmetric/uncentered kernels: the radius may differ per
    direction (e.g. +x vs -x). The halo region a subdomain allocates on
    side ``d`` of axis ``a`` has width ``face radius of (sign d) along a``;
    edge/corner radii control whether diagonal-neighbor halo data is
    required at all (zero = that exchange may be skipped — reference:
    src/stencil.cu:344).
    """

    __slots__ = ("_m",)

    def __init__(self) -> None:
        self._m = DirectionMap(0)

    @staticmethod
    def _value(v) -> int:
        """Radii are non-negative exact integers: a negative (or
        truncated-float) radius would flow silently into allocation
        pads and slab widths — reject it loudly at the constructor."""
        r = _as_component("radius", v)
        if r < 0:
            raise ValueError(f"radius must be >= 0, got {r}")
        return r

    # -- indexing -----------------------------------------------------
    def dir(self, d: Dim3Like) -> int:
        return self._m[Dim3.of(d)]

    def set_dir(self, d: Dim3Like, v: int) -> None:
        d = Dim3.of(d)
        self._m[d] = self._value(v)

    def x(self, d: int) -> int:
        """Face radius along x on side ``d`` in {-1, 0, 1}."""
        return self._m.at_dir(d, 0, 0)

    def y(self, d: int) -> int:
        return self._m.at_dir(0, d, 0)

    def z(self, d: int) -> int:
        return self._m.at_dir(0, 0, d)

    def face(self, axis: int, side: int) -> int:
        """Face radius on ``side`` (+1/-1) of ``axis`` (0=x,1=y,2=z)."""
        d = [0, 0, 0]
        d[axis] = side
        return self._m.at_dir(*d)

    def __eq__(self, o) -> bool:
        return isinstance(o, Radius) and self._m == o._m

    # -- setters ------------------------------------------------------
    def set_face(self, r: int) -> None:
        r = self._value(r)
        for d in all_directions():
            if direction_kind(d) == "face":
                self._m[d] = r

    def set_edge(self, r: int) -> None:
        r = self._value(r)
        for d in all_directions():
            if direction_kind(d) == "edge":
                self._m[d] = r

    def set_corner(self, r: int) -> None:
        r = self._value(r)
        for d in all_directions():
            if direction_kind(d) == "corner":
                self._m[d] = r

    # -- constructors -------------------------------------------------
    @staticmethod
    def constant(r: int) -> "Radius":
        out = Radius()
        r = Radius._value(r)
        for d in all_directions(include_zero=True):
            out._m[d] = r
        return out

    @staticmethod
    def face_edge_corner(face: int, edge: int, corner: int) -> "Radius":
        out = Radius()
        out.set_face(face)
        out.set_edge(edge)
        out.set_corner(corner)
        out._m[ZERO] = 0
        return out

    # -- derived geometry --------------------------------------------
    def pad_lo(self) -> Dim3:
        """Allocation padding on the low side of each axis
        (reference: local_domain.cuh raw_size())."""
        return Dim3(self.x(-1), self.y(-1), self.z(-1))

    def pad_hi(self) -> Dim3:
        return Dim3(self.x(1), self.y(1), self.z(1))

    def wire_rows(self, axis: int) -> int:
        """Rows of axis ``axis`` a sequential-sweep exchange ships per
        shard (both sides): lo face radius + hi face radius. The
        per-axis factor of the analytic byte model
        (``partition.sweep_wire_bytes``,
        ``parallel.exchange.exchanged_bytes_per_sweep``)."""
        return self.face(axis, -1) + self.face(axis, 1)

    def deepened(self, steps: DepthsLike) -> "Radius":
        """Halo geometry for ``steps``-step temporal blocking
        (communication avoidance): every per-direction radius scaled by
        ``steps``, so ONE exchange delivers enough halo depth to run
        ``steps`` stencil applications locally — each sub-step consumes
        one base-radius ring. ``steps == 1`` returns an equal copy.
        Asymmetric and edge/corner radii deepen independently, keeping
        the per-direction contract the exchange plan prices.

        ``steps`` may be per-axis (dict / tuple / Dim3, see
        :func:`normalize_depths`): each FACE deepens by its own axis's
        depth (the exchange for axis ``a`` ships ``s_a * r`` rows once
        per ``s_a`` sub-steps), while edge/corner/center directions
        deepen by the max depth over their involved axes — a
        conservative allocation bound; the asymmetric temporal engine
        itself is face-slab only."""
        steps = normalize_depths(steps)
        out = Radius()
        if steps.x == steps.y == steps.z:
            s = steps.x
            for d in all_directions(include_zero=True):
                out._m[d] = self._m[d] * s
            return out
        s_max = max(steps)
        for d in all_directions(include_zero=True):
            involved = [steps[a] for a in range(3) if d[a] != 0]
            out._m[d] = self._m[d] * (max(involved) if involved else s_max)
        return out

    def max_side(self, axis: int, side: int) -> int:
        """Max radius over all directions whose ``axis`` component equals
        ``side`` — the amount the interior shrinks on that side
        (reference: src/stencil.cu get_interior, 874-921)."""
        best = 0
        for d in all_directions():
            if d[axis] == side:
                best = max(best, self._m[d])
        return best

    def to_dict(self) -> Dict[Tuple[int, int, int], int]:
        return {tuple(d): self._m[d] for d in all_directions(include_zero=True)}

    def __repr__(self) -> str:
        return (f"Radius(face=[{self.x(-1)},{self.x(1)},{self.y(-1)},{self.y(1)},"
                f"{self.z(-1)},{self.z(1)}])")


def deepened(radius: Radius, steps: "DepthsLike") -> Radius:
    """Module-level spelling of :meth:`Radius.deepened` — the deep-halo
    geometry one exchange ships to cover ``steps`` fused stencil steps
    (see ``parallel/temporal.py``)."""
    return radius.deepened(steps)


def normalize_depths(steps: "DepthsLike") -> Dim3:
    """Per-axis temporal-blocking depths as a ``Dim3`` ``(s_x, s_y,
    s_z)``. Accepts an int (uniform depth, the classic
    ``exchange_every``), an ``{"x": ..., "y": ..., "z": ...}`` dict
    (missing axes default to 1 — e.g. ``{"z": 4}`` is deep blocking
    across z only), or a 3-tuple/Dim3. Each depth must be >= 1 and
    must divide the max depth: the temporal group runs ``max(steps)``
    sub-steps and refreshes axis ``a`` every ``s_a`` of them, so a
    non-divisor would leave a partially-consumed ring at the group
    boundary (see ``parallel/temporal.py``)."""
    orig = steps
    if isinstance(steps, Dim3):
        pass
    elif isinstance(steps, dict):
        unknown = set(steps) - {"x", "y", "z"}
        if unknown:
            raise ValueError(f"unknown depth axes {sorted(unknown)} in "
                             f"{orig!r} (expected 'x'/'y'/'z')")
        steps = Dim3(_as_component("x", steps.get("x", 1)),
                     _as_component("y", steps.get("y", 1)),
                     _as_component("z", steps.get("z", 1)))
    elif isinstance(steps, (tuple, list)):
        steps = Dim3.of(tuple(steps))
    else:
        s = _as_component("steps", steps)
        steps = Dim3(s, s, s)
    if steps.any_lt(1):
        raise ValueError(f"temporal depth must be >= 1, got {orig}")
    s_max = max(steps)
    for a in range(3):
        if s_max % steps[a] != 0:
            raise ValueError(
                f"per-axis temporal depth {'xyz'[a]}={steps[a]} does "
                f"not divide the max depth {s_max} (in {orig!r}): the "
                f"deep group runs {s_max} sub-steps and must refresh "
                f"axis {'xyz'[a]} on a whole number of them")
    return steps
