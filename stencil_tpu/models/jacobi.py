"""Jacobi-3D heat solver: the flagship demo application.

TPU-native re-implementation of the reference's jacobi3d app
(reference: bin/jacobi3d.cu): a 7-point Jacobi relaxation over a
periodic global grid with a hot sphere (T=1) at x=1/3 and a cold sphere
(T=0) at x=2/3, each of radius gx/10, re-imposed every iteration
(bin/jacobi3d.cu:40-85); everything else initialized to the mean
temperature 0.5 (bin/jacobi3d.cu:18-27).

Design: unlike the reference's interior-launch / exchange / exterior-
launch choreography (bin/jacobi3d.cu:296-377), the whole iteration —
halo exchange + stencil + sources — is ONE ``shard_map``-ped XLA
program; XLA schedules the ppermutes against the compute (async
collectives are its overlap mechanism), and buffer donation makes the
double-buffer swap an in-place update.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..distributed import DistributedDomain
from ..geometry import Dim3, Dim3Like, Radius
from ..local_domain import zyx_shape
from ..ops.stencil_kernels import global_coords, jacobi7, write_interior
from ..parallel.exchange import dispatch_exchange
from ..parallel.mesh import mesh_dim
from ..parallel.methods import Method, pick_method

HOT_TEMP = 1.0   # reference: bin/jacobi3d.cu:12
COLD_TEMP = 0.0  # reference: bin/jacobi3d.cu:11


def sphere_geometry(gsize: Dim3):
    """Hot/cold Dirichlet sphere centers and radius for a global grid
    (reference: bin/jacobi3d.cu:255-260): hot at x/3, cold at 2x/3,
    both mid-(y,z), radius x/10. Returns (hot_xyz, cold_xyz, r)."""
    hot = Dim3(gsize.x // 3, gsize.y // 2, gsize.z // 2)
    cold = Dim3(gsize.x * 2 // 3, gsize.y // 2, gsize.z // 2)
    return hot, cold, gsize.x // 10


def jacobi_shard_step(p, radius: Radius, counts: Dim3, local: Dim3,
                      gsize: Dim3, origin_xyz, method: Method,
                      kernel: str = "xla", rem: Dim3 = Dim3(0, 0, 0),
                      nonperiodic: bool = False, wire_format=None,
                      wire_layout=None):
    """One fused Jacobi step on one shard: exchange + 7-point update +
    Dirichlet sphere sources. ``origin_xyz`` is the shard's global
    origin (traced axis_index-derived inside shard_map, or static
    (0,0,0) single-chip). Shared by Jacobi3D and the driver entry.
    ``kernel``: "xla" (fused slicing) or "pallas" (z-plane-pipelined
    VMEM kernel, ops/pallas_stencil.py). ``wire_format`` narrows the
    halo WIRE only (send-boundary convert, widen on arrival —
    parallel/exchange.py); the update math runs at storage dtype.
    ``wire_layout`` picks the wire message geometry ("slab" or
    "irredundant" — parallel/packing.py); interiors are bitwise
    identical either way."""
    hot_c, cold_c, sph_r = sphere_geometry(gsize)

    p = dispatch_exchange({"temp": p}, radius, counts, method,
                          rem=rem, nonperiodic=nonperiodic,
                          wire_format=wire_format,
                          wire_layout=wire_layout)["temp"]
    if kernel == "pallas":
        from ..ops.pallas_stencil import jacobi7_pallas
        new = jacobi7_pallas(p, radius, local)
    else:
        new = jacobi7(p, radius, local)
    new = _apply_sources(new, origin_xyz, local, hot_c, cold_c, sph_r)
    return write_interior(p, new, radius)


def _apply_sources(new, origin_xyz, local: Dim3, hot_c: Dim3, cold_c: Dim3,
                   sph_r: int):
    """Re-impose the Dirichlet hot/cold spheres
    (reference: bin/jacobi3d.cu:40-63)."""
    gz, gy, gx = global_coords(origin_xyz, local)

    def dist2(c: Dim3):
        return (gx - c.x) ** 2 + (gy - c.y) ** 2 + (gz - c.z) ** 2

    new = jnp.where(dist2(hot_c) <= sph_r * sph_r,
                    jnp.asarray(HOT_TEMP, new.dtype), new)
    new = jnp.where(dist2(cold_c) <= sph_r * sph_r,
                    jnp.asarray(COLD_TEMP, new.dtype), new)
    return new


def _apply_sources_windowed(new, origin_xyz, dims: Dim3, gsize: Dim3,
                            hot_c: Dim3, cold_c: Dim3, sph_r: int,
                            nonperiodic: bool):
    """Per-sub-step sources for a temporal-blocking window that may
    reach into the halo ring: ring cells must get exactly what their
    OWNER shard computes, so periodic coords wrap mod the global size
    before the sphere test; with the zero-Dirichlet exterior
    (Boundary.NONE) out-of-domain cells are forced to zero instead."""
    gz, gy, gx = global_coords(origin_xyz, dims)
    if nonperiodic:
        inside = ((gx >= 0) & (gx < gsize.x) & (gy >= 0) & (gy < gsize.y)
                  & (gz >= 0) & (gz < gsize.z))
    else:
        gx = gx % gsize.x
        gy = gy % gsize.y
        gz = gz % gsize.z

    def dist2(c: Dim3):
        return (gx - c.x) ** 2 + (gy - c.y) ** 2 + (gz - c.z) ** 2

    new = jnp.where(dist2(hot_c) <= sph_r * sph_r,
                    jnp.asarray(HOT_TEMP, new.dtype), new)
    new = jnp.where(dist2(cold_c) <= sph_r * sph_r,
                    jnp.asarray(COLD_TEMP, new.dtype), new)
    if nonperiodic:
        new = jnp.where(inside, new, jnp.zeros_like(new))
    return new


def jacobi_shard_step_overlap(p, radius: Radius, counts: Dim3, local: Dim3,
                              gsize: Dim3, origin_xyz, method: Method,
                              kernel: str = "xla",
                              nonperiodic: bool = False):
    """Overlapped variant of ``jacobi_shard_step``: the deep-interior
    update is computed from pre-exchange owned data so XLA can schedule
    it against the in-flight halo transfers; thin exterior shells are
    computed after (the reference's interior-launch / exchange /
    exterior-launch choreography, bin/jacobi3d.cu:296-377, as one
    program)."""
    from ..parallel.overlap import overlapped_update

    hot_c, cold_c, sph_r = sphere_geometry(gsize)

    def upd(blocks, dims, off):
        blk = blocks["temp"]
        if kernel == "pallas":
            from ..ops.pallas_stencil import jacobi7_pallas
            return {"temp": jacobi7_pallas(blk, radius, dims)}
        return {"temp": jacobi7(blk, radius, dims)}

    p_ex, new = overlapped_update({"temp": p}, radius, counts, method, upd,
                                  nonperiodic=nonperiodic)
    out = _apply_sources(new["temp"], origin_xyz, local, hot_c, cold_c, sph_r)
    return write_interior(p_ex["temp"], out, radius)


def _dcn_request_kwargs(dd) -> dict:
    """The DCN-tier request the domain was configured with, as model
    constructor kwargs — a degradation rebuild must not silently strip
    the slice tiering (``None`` axis means auto-derive, which the
    constructors spell ``"auto"``)."""
    if not dd._dcn_requested:
        return {}
    req = dd._dcn_axis_req
    return {"dcn_axis": "auto" if req is None else req,
            "dcn_groups": dd._dcn_groups}


def _wrap_steps(tile: int, requested: int = 0) -> int:
    """Temporal-blocking depth for the Pallas fast paths: an explicit
    ``exchange_every`` request wins; else STENCIL_WRAP_STEPS (default
    2). Clamped to [1, sublane tile] — shared by the wrap and halo step
    builders (one tunable, two kernel families)."""
    import os

    if requested:
        return min(max(int(requested), 1), tile)
    try:
        n = int(os.environ.get("STENCIL_WRAP_STEPS", "2") or 2)
    except ValueError:
        from ..utils.logging import LOG_WARN
        LOG_WARN("STENCIL_WRAP_STEPS is not an integer; using 2")
        n = 2
    return min(max(n, 1), tile)


#: memoized overlap-kernel schedule certificates, keyed by the traced
#: geometry AND the certifier's identity (so a monkeypatched certifier
#: in tests is never shadowed by a cached verdict)
_OVERLAP_CERT_MEMO: dict = {}


def _overlap_schedule_certificate(dd, dtype, hot, cold, sph_r,
                                  counts: Dim3):
    """Ask the schedule certifier (analysis/schedule.py) whether the
    in-kernel RDMA overlap kernel's semaphore schedule is sound under
    k-fold replay on ``dd``'s mesh: trace the same per-shard program
    ``_build_overlap_step`` runs (a synthetic even global of
    base-shard interiors — the schedule's shape does not depend on the
    ±1 remainder rows) and certify every Pallas kernel inside.  Any
    trace failure comes back as an unsafe certificate, so callers
    decline instead of crashing."""
    from ..analysis import schedule as schedule_checker
    from ..ops.pallas_overlap import jacobi7_overlap_pallas
    from ..parallel.exchange import shard_origin

    local = dd.local_size
    rem = dd.rem
    key = ((counts.z, counts.y, counts.x),
           (local.z, local.y, local.x), (rem.z, rem.y, rem.x),
           str(jnp.dtype(dtype)),
           id(schedule_checker.certify_traceable))
    hit = _OVERLAP_CERT_MEMO.get(key)
    if hit is not None:
        return hit

    def shard(q):
        ox, oy, oz = shard_origin(local, rem)
        org = jnp.stack([oz, oy, ox]).astype(jnp.int32)
        return jacobi7_overlap_pallas(q, org, hot, cold, sph_r, counts,
                                      interpret=False)

    spec = P("z", "y", "x")
    sm = jax.shard_map(shard, mesh=dd.mesh, in_specs=spec,
                       out_specs=spec, check_vma=False)
    gshape = (local.z * counts.z, local.y * counts.y,
              local.x * counts.x)
    cert = schedule_checker.certify_traceable(
        sm, (jax.ShapeDtypeStruct(gshape, dtype),))
    _OVERLAP_CERT_MEMO[key] = cert
    return cert


def _dcn_xfree_shape(size: Dim3, devices, dcn_axis, dcn_groups, kernel,
                     align: int = 1):
    """Slice-compatible x-unsharded mesh shape when a DCN tier is
    requested together with a halo-family fast path (explicit
    kernel='halo', or 'auto' on TPU) — NodePartition's derived split
    may shard x, which the slab kernels cannot use. Returns None —
    letting realize()'s NodePartition ladder stand — for non-halo
    kernels, an x-axis DCN tier, indivisible device counts, or a
    candidate shape the GRID cannot host (every axis must divide
    evenly with local z/y multiples of ``align``; the same
    guarantee-or-decline contract as ``partition_dims_even_xfree``)."""
    from ..ops.pallas_stencil import on_tpu

    if not (kernel == "halo" or (kernel == "auto" and on_tpu())):
        return None
    axis = dcn_axis
    if isinstance(axis, str):
        axis = {"x": 0, "y": 1, "z": 2, "auto": None}[axis]
    if axis == 0:
        return None          # x-axis DCN tier cannot be x-free
    from ..parallel.mesh import default_mesh_shape_dcn
    from ..parallel.multihost import slice_groups

    groups = dcn_groups or slice_groups(devices)
    if len(groups) <= 1 or len(devices) % len(groups):
        return None
    shape = default_mesh_shape_dcn(len(devices), len(groups),
                                   axis=2 if axis is None else axis,
                                   xfree=True)
    for a in range(3):
        if size[a] % shape[a]:
            return None
    if (size.z // shape.z) % align or (size.y // shape.y) % align:
        return None
    return shape


class Jacobi3D:
    """Distributed Jacobi-3D solver over a TPU mesh."""

    def __init__(self, x: int, y: int, z: int,
                 mesh_shape: Optional[Dim3Like] = None,
                 dtype=jnp.float32,
                 devices: Optional[Sequence] = None,
                 methods: Method = Method.Default,
                 placement=None, output_prefix: str = "",
                 kernel: str = "auto", overlap: bool = False,
                 dcn_axis=None, dcn_groups=None,
                 exchange_every: Optional[int] = None,
                 boundary=None, wire_format=None,
                 wire_layout=None) -> None:
        self.dd = DistributedDomain(x, y, z, devices=devices)
        self.dd.set_radius(1)
        self.dd.set_methods(methods)
        # temporal blocking: None = unset (fast paths keep their
        # STENCIL_WRAP_STEPS default); an explicit s pins the depth —
        # deep-carry allocations + one deep exchange per s steps on the
        # XLA path (parallel/temporal.py), the in-kernel step count on
        # the Pallas wrap/halo paths (s == 1 forces per-step exchange).
        # Per-axis specs ({"z": 4}, (1, 1, 4)) deepen only the named
        # axes — the XLA temporal engine only; the Pallas fast paths
        # decline them loudly below
        if exchange_every is None:
            self._exchange_every = 0
        elif isinstance(exchange_every, int):
            self._exchange_every = max(int(exchange_every), 1)
        else:
            from ..geometry import normalize_depths
            self._exchange_every = max(normalize_depths(exchange_every))
        if self._exchange_every > 1:
            self.dd.set_exchange_every(exchange_every)
        if boundary is not None:
            self.dd.set_boundary(boundary)
        if wire_format is not None:
            # halo wire narrowing (send-boundary bf16, widen on
            # arrival); realize() below runs the precision gate
            self.dd.set_wire_format(wire_format)
        if wire_layout is not None:
            # wire message geometry (slab / irredundant packed boxes)
            self.dd.set_wire_layout(wire_layout)
        if dcn_axis is not None or dcn_groups is not None:
            self.dd.set_dcn_axis(dcn_axis, dcn_groups)
        if placement is not None:
            self.dd.set_placement(placement)
        if output_prefix:
            self.dd.set_output_prefix(output_prefix)
        if mesh_shape is not None:
            self.dd.set_mesh_shape(mesh_shape)
        elif dcn_axis is not None or dcn_groups is not None:
            # DCN tier with no explicit shape: normally let realize()
            # derive the grid from NodePartition's two-level split —
            # but the halo fast paths need the lane (x) axis unsharded,
            # which that split does not know, so derive the x-free
            # slice-compatible shape here (the apps' dcn_mesh_shape
            # rule, in the model so library users get it too)
            shape = _dcn_xfree_shape(Dim3(x, y, z), self.dd._devices,
                                     dcn_axis, dcn_groups, kernel)
            if shape is not None:
                self.dd.set_mesh_shape(shape)
        else:
            from ..ops.pallas_stencil import on_tpu
            if (len(self.dd._devices) > 1 and not overlap
                    and (kernel == "halo"
                         or (kernel == "auto" and on_tpu()))):
                # prefer an x-unsharded decomposition so the fused halo
                # kernel path is available (ops/pallas_halo.py: cutting
                # the lane axis is the worst TPU choice anyway); other
                # paths keep the cube-like partition_dims_even choice
                from ..partition import partition_dims_even_xfree
                shape = partition_dims_even_xfree(
                    Dim3(x, y, z), len(self.dd._devices))
                if shape is not None:
                    self.dd.set_mesh_shape(shape)
        self.dd.add_data("temp", dtype)
        self.dd.realize()
        self._dtype = dtype
        if kernel not in ("auto", "wrap", "halo", "xla", "pallas"):
            raise ValueError(
                f"kernel must be auto|wrap|halo|xla|pallas, got {kernel!r}")
        self._kernel = kernel
        self._overlap = overlap
        self._build_step()

    # -- initial conditions (reference: bin/jacobi3d.cu:18-27) ---------
    def init(self) -> None:
        mean = np.asarray((HOT_TEMP + COLD_TEMP) / 2, dtype=self._dtype)
        vals = np.full(zyx_shape(self.dd.size), mean, dtype=self._dtype)
        self.dd.set_interior("temp", vals)

    # -- megastep: whole campaign segments as one program --------------
    def _set_segment_builder(self, shard_advance, stride: int = 1
                             ) -> None:
        """Register the fused-segment factory for the built compute
        path: ``shard_advance(p, steps)`` advances one shard's padded
        field ``steps`` steps (``steps`` is the path's stride — a
        whole temporal group or a Pallas kernel's in-kernel multi-step
        count — or a depth-1 tail step). The carry contract is one
        padded field under ``P('z','y','x')``; :meth:`make_segment`
        compiles/caches the megastep programs through the generic
        segment compiler (``parallel/megastep.py``)."""
        from jax.sharding import PartitionSpec as P

        from ..parallel import megastep as ms

        dd = self.dd

        def adopt(out):
            self.dd.curr["temp"] = out

        self._segment_decline = None
        self._segment_builder = ms.SegmentCompiler(
            dd.mesh,
            ms.CarryContract(specs=P("z", "y", "x"),
                             probe_view=lambda p: {"temp": p},
                             stride=stride),
            lambda p, c, i: shard_advance(p, c),
            lambda: self.dd.curr["temp"], adopt)

    def _set_segment_decline(self, reason: str,
                             code: Optional[str] = None) -> None:
        """The built path cannot fuse: record why (prose + a
        ``megastep.DECLINE_*`` vocabulary code), so
        :meth:`make_segment` returns a loud, reason-carrying
        :class:`~stencil_tpu.parallel.megastep.SegmentDecline` instead
        of a silent None."""
        self._segment_builder = None
        self._segment_decline = reason
        self._segment_decline_code = code

    def make_segment(self, check_every: int, probe_every: int = 1,
                     metrics=None):
        """ONE compiled program advancing ``check_every`` iterations
        with the health probe fused in-graph every ``probe_every``
        steps (``parallel/megastep.py``): the resilient driver, the
        apps, and the bench dispatch one of these per health boundary
        instead of one jitted step per iteration. Field state is
        donated end-to-end. Every built compute path fuses — the XLA
        and temporal paths unroll their shard bodies, the wrap/halo
        Pallas paths chunk into their in-kernel multi-step launches,
        and the in-kernel RDMA overlap path fuses its kernel launches
        when the schedule certifier (``analysis/schedule.py``) proves
        the semaphore schedule ``replay_safe``. A path that cannot
        fuse returns a falsy ``SegmentDecline`` carrying the reason
        (for the overlap path: the certificate's own reasons) and a
        ``DECLINE_*`` vocabulary code; the driver reports it and falls
        back to the stepwise dispatch loop."""
        builder = getattr(self, "_segment_builder", None)
        if builder is None:
            from ..parallel import megastep as ms
            reason = (getattr(self, "_segment_decline", None)
                      or "no fused-segment builder for this path")
            code = (getattr(self, "_segment_decline_code", None)
                    or ms.DECLINE_NO_BUILDER)
            return ms.decline("jacobi", self.kernel_path, reason,
                              code=code)
        return builder(int(check_every), max(int(probe_every), 1),
                       metrics)

    # -- the fused step ------------------------------------------------
    def _build_step(self) -> None:
        self._segment_builder = None
        self._segment_decline = None
        dd = self.dd
        radius = dd.radius
        counts = mesh_dim(dd.mesh)
        local = dd.local_size
        gsize = dd.size
        method = pick_method(self.dd.methods)
        kernel = self._kernel
        rem = dd.rem
        if self._overlap and rem != Dim3(0, 0, 0):
            raise NotImplementedError("overlap mode requires an evenly "
                                      "divisible grid")
        from ..topology import Boundary
        nonper = dd.boundary == Boundary.NONE
        s_every = dd.exchange_every
        depths = dd.exchange_depths
        asym = not (depths.x == depths.y == depths.z)
        if asym and self._overlap:
            raise NotImplementedError(
                "asymmetric temporal depths (per-axis exchange_every) "
                "are not supported with overlap=True — the overlap "
                "composition assumes one symmetric deep exchange per "
                "group (parallel/temporal.py declines it too)")
        if asym and kernel in ("wrap", "halo", "pallas"):
            raise NotImplementedError(
                f"asymmetric temporal depths "
                f"(exchange_every={tuple(depths)}) are not supported "
                f"with kernel={kernel!r} — the Pallas in-kernel "
                f"multi-step paths have one step count, not one per "
                f"axis; use kernel='xla' or 'auto'")
        from ..parallel.exchange import normalize_wire_format
        from ..parallel.packing import normalize_wire_layout
        wire = dd.wire_format
        wire_narrows = any(v != "f32"
                           for v in normalize_wire_format(wire).values())
        layout = getattr(dd, "wire_layout", "slab")
        irr_layout = normalize_wire_layout(layout) == "irredundant"
        # single-chip fast path: periodic wrap fused INTO the stencil
        # kernel (no halo storage, no exchange program) — the TPU-native
        # answer to the reference's same-GPU PeerAccessSender shortcut.
        # All Pallas fast paths assume the periodic wrap rule, so the
        # zero-Dirichlet exterior (Boundary.NONE) runs the XLA paths.
        radius_ok = all(radius.face(a, s) == 1
                        for a in range(3) for s in (-1, 1))
        wrap_ok = (counts == Dim3(1, 1, 1) and rem == Dim3(0, 0, 0)
                   and not self._overlap and radius_ok and not nonper
                   and not asym)
        # the multi-device fast path: interior-resident shards + slab
        # exchange + fused halo kernel (ops/pallas_halo.py); uneven
        # (+-1) z/y shards supported via the kernel's interior-length
        # overlay (x is never sharded here, so rem.x is always 0)
        halo_ok = (counts.x == 1 and not self._overlap and radius_ok
                   and not nonper and not wire_narrows
                   and not irr_layout and not asym)
        # the overlapped fast path: in-kernel RDMA slab exchange hidden
        # behind the interior compute (ops/pallas_overlap.py) — the
        # reference's interior/exchange/exterior choreography as one
        # kernel (bin/jacobi3d.cu:296-377). With exchange_every > 1 the
        # temporal paths amortize the exchange instead (the deep
        # exchange already hides behind sub-step-0 interior compute).
        overlap_ok = (self._overlap and counts.x == 1
                      and rem == Dim3(0, 0, 0) and radius_ok
                      and local.z >= 4 and local.y >= 2
                      and not nonper and s_every == 1
                      and not wire_narrows and not irr_layout)
        from ..ops.pallas_stencil import on_tpu
        from ..utils.logging import LOG_INFO
        # explicit kernel='halo' with overlap opts into the RDMA overlap
        # kernel anywhere (tests run it interpreted); 'auto' only
        # selects Pallas paths on real TPU hardware
        if overlap_ok and (kernel == "halo"
                           or (kernel == "auto" and on_tpu())):
            self.kernel_path = "overlap"
            self._build_overlap_step()
            LOG_INFO("jacobi kernel path: overlap (in-kernel RDMA)")
            return
        if kernel == "auto":
            if on_tpu():
                kernel = ("wrap" if wrap_ok
                          else "halo" if halo_ok else "xla")
            else:
                kernel = "xla"
            why = ""
            if kernel == "xla" and on_tpu():
                blockers = []
                if counts.x != 1:
                    blockers.append("x-axis sharded")
                if self._overlap:
                    blockers.append("overlap requested")
                if not radius_ok:
                    blockers.append("radius != 1")
                why = f" (fast paths unavailable: {', '.join(blockers)})"
            LOG_INFO(f"jacobi kernel path: {kernel}{why}")
        if kernel == "wrap":
            if not wrap_ok:
                raise ValueError("kernel='wrap' needs a (1,1,1) mesh, "
                                 "radius 1, even grid, overlap off")
            self.kernel_path = "wrap"
            self._build_wrap_step()
            return
        if kernel == "halo":
            if not halo_ok:
                raise ValueError("kernel='halo' needs an x-unsharded "
                                 "mesh, radius 1, periodic boundaries, "
                                 "overlap off (or overlap with local "
                                 "z>=4)")
            self.kernel_path = "halo"
            self._build_halo_step()
            return
        if s_every > 1:
            if kernel == "pallas":
                raise ValueError("exchange_every > 1 is not supported "
                                 "with kernel='pallas' (use xla, wrap "
                                 "or halo)")
            if wire_narrows:
                raise NotImplementedError(
                    "a narrowing wire_format is not supported with "
                    "exchange_every > 1 (the temporal deep exchange "
                    "has no wire-narrowing variant yet)")
            tag = (f"s={depths.x}.{depths.y}.{depths.z}" if asym
                   else f"s={s_every}")
            self.kernel_path = (f"xla-temporal[{tag}]"
                                + ("-overlap" if self._overlap else ""))
            self._build_temporal_step()
            from ..utils.logging import LOG_INFO
            LOG_INFO(f"jacobi kernel path: {self.kernel_path}")
            return
        self.kernel_path = f"{kernel}-overlap" if self._overlap else kernel
        step_fn = (jacobi_shard_step_overlap if self._overlap
                   else jacobi_shard_step)

        if wire_narrows and self._overlap:
            raise NotImplementedError(
                "a narrowing wire_format is not supported with "
                "overlap=True (overlapped_update has no wire-narrowing "
                "variant yet)")

        def shard_step(p):
            from ..parallel.exchange import shard_origin
            origin = shard_origin(local, rem)
            if self._overlap:
                return step_fn(p, radius, counts, local, gsize,
                               origin, method, kernel, nonper)
            return step_fn(p, radius, counts, local, gsize,
                           origin, method, kernel, rem, nonper,
                           wire_format=wire, wire_layout=layout)

        spec = P("z", "y", "x")
        sm = jax.shard_map(shard_step, mesh=dd.mesh, in_specs=spec,
                           out_specs=spec, check_vma=False)
        self._step = jax.jit(sm, donate_argnums=0)

        def shard_steps(p, n):
            return lax.fori_loop(0, n, lambda _, q: shard_step(q), p)

        sm_n = jax.shard_map(shard_steps, mesh=dd.mesh, in_specs=(spec, P()),
                             out_specs=spec, check_vma=False)
        self._step_n = jax.jit(sm_n, donate_argnums=0)
        self._set_segment_builder(lambda p, c: shard_step(p))

    def _build_temporal_step(self) -> None:
        """Communication-avoiding XLA steps: iterations run in groups of
        ``s = exchange_every`` through ``parallel/temporal.py`` — ONE
        depth-``s`` exchange, then ``s`` fused 7-point sub-steps on the
        shrinking window (ring cells recomputed redundantly, numerically
        identical to step-by-step) — with a depth-1 tail for the
        remainder. With ``overlap=True`` the deep exchange hides behind
        sub-step 0's interior compute (even shards)."""
        from ..parallel.exchange import shard_origin
        from ..parallel.temporal import temporal_shard_steps, validate_temporal
        from ..topology import Boundary

        dd = self.dd
        radius = dd.radius
        counts = mesh_dim(dd.mesh)
        local = dd.local_size
        gsize = dd.size
        method = pick_method(dd.methods)
        rem = dd.rem
        s = dd.exchange_every
        depths = dd.exchange_depths  # per-axis; == (s, s, s) when uniform
        nonper = dd.boundary == Boundary.NONE
        overlap = self._overlap
        layout = getattr(dd, "wire_layout", "slab")
        hot_c, cold_c, sph_r = sphere_geometry(gsize)
        validate_temporal(radius, local, depths, rem)

        def make_update(origin):
            ox, oy, oz = origin

            def update_fn(blocks, dims, off, k):
                new = jacobi7(blocks["temp"], radius, dims)
                org = (ox + off[0], oy + off[1], oz + off[2])
                new = _apply_sources_windowed(new, org, dims, gsize, hot_c,
                                              cold_c, sph_r, nonper)
                return {"temp": new.astype(blocks["temp"].dtype)}

            return update_fn

        def shard_steps(p, n):
            upd = make_update(shard_origin(local, rem))

            def group(q, depth, ovl):
                return temporal_shard_steps(
                    {"temp": q}, radius, counts, method, upd, depth,
                    alloc_steps=depths, rem=rem, overlap=ovl,
                    nonperiodic=nonper, wire_layout=layout)["temp"]

            p = lax.fori_loop(0, n // s,
                              lambda _, q: group(q, depths, overlap), p)
            return lax.fori_loop(0, n % s,
                                 lambda _, q: group(q, 1, False), p)

        spec = P("z", "y", "x")
        sm = jax.shard_map(shard_steps, mesh=dd.mesh, in_specs=(spec, P()),
                           out_specs=spec, check_vma=False)
        self._step_n = jax.jit(sm, donate_argnums=0)
        self._step = jax.jit(
            lambda p: sm(p, jnp.asarray(1, jnp.int32)), donate_argnums=0)

        def shard_advance(p, c):
            # one temporal group of c steps (c == s, run at the
            # configured per-axis depths) or a depth-1 tail step — the
            # same bodies the fused run loop iterates
            upd = make_update(shard_origin(local, rem))
            return temporal_shard_steps(
                {"temp": p}, radius, counts, method, upd,
                depths if c == s else c,
                alloc_steps=depths, rem=rem,
                overlap=(overlap and c == s),
                nonperiodic=nonper, wire_layout=layout)["temp"]

        self._set_segment_builder(shard_advance, stride=s)

    def _build_wrap_step(self) -> None:
        """Single-chip fused steps on the interior view: iterations run
        in groups of N through the temporally-blocked multi-step kernel
        (ops/pallas_stencil.jacobi7_wrapn_pallas — ~1/N the HBM traffic
        per iteration; N=2 default, STENCIL_WRAP_STEPS to tune) with a
        single-step tail; grids the blocked kernel can't tile fall back
        to single steps."""
        import os

        from ..ops.pallas_stencil import (jacobi7_wrapn_pallas,
                                          jacobi7_wrap_pallas,
                                          sublane_tile)
        from ..utils.config import wrap2_disabled

        dd = self.dd
        lo = dd.alloc_radius.pad_lo()
        local = dd.local_size
        gsize = dd.size
        hot, cold, sph_r = sphere_geometry(gsize)
        tile = sublane_tile(self._dtype)
        N = _wrap_steps(tile, self._exchange_every)
        pair_ok = (local.y % tile == 0 and N > 1
                   and not wrap2_disabled())

        def steps(p, n):
            inner = lax.slice(p, (lo.z, lo.y, lo.x),
                              (lo.z + local.z, lo.y + local.y,
                               lo.x + local.x))
            if pair_ok:
                inner = lax.fori_loop(
                    0, n // N,
                    lambda _, q: jacobi7_wrapn_pallas(q, hot, cold,
                                                      sph_r, steps=N),
                    inner)
                inner = lax.fori_loop(
                    0, n % N,
                    lambda _, q: jacobi7_wrap_pallas(q, hot, cold, sph_r),
                    inner)
            else:
                inner = lax.fori_loop(
                    0, n,
                    lambda _, q: jacobi7_wrap_pallas(q, hot, cold, sph_r),
                    inner)
            # halos go stale; nothing reads them before the next
            # exchange, and temperature() reads the interior only
            return lax.dynamic_update_slice(p, inner, (lo.z, lo.y, lo.x))

        self._step_n = jax.jit(steps, donate_argnums=0)
        self._step = jax.jit(
            lambda p: steps(p, jnp.asarray(1, jnp.int32)), donate_argnums=0)

        def shard_advance(p, c):
            # one segment chunk: c == N runs the temporally-blocked
            # multi-step kernel as ONE pallas launch; c == 1 tail steps
            # run the single-step kernel. Interior is sliced out and
            # written back per chunk (the probe reads the padded state)
            inner = lax.slice(p, (lo.z, lo.y, lo.x),
                              (lo.z + local.z, lo.y + local.y,
                               lo.x + local.x))
            if pair_ok and c == N:
                inner = jacobi7_wrapn_pallas(inner, hot, cold, sph_r,
                                             steps=N)
            else:
                for _ in range(c):
                    inner = jacobi7_wrap_pallas(inner, hot, cold, sph_r)
            return lax.dynamic_update_slice(p, inner, (lo.z, lo.y, lo.x))

        self._set_segment_builder(shard_advance,
                                  stride=N if pair_ok else 1)

    def _build_interior_resident_steps(self, make_body,
                                       segment_decline: Optional[str]
                                       = None,
                                       segment_stride: int = 1,
                                       segment_decline_code:
                                       Optional[str] = None) -> None:
        """Shared scaffolding for the interior-resident multi-device
        builders: slice the unpadded interior out of the padded shard,
        fori_loop the per-iteration body from ``make_body(org)``, write
        the interior back (halos go stale; nothing reads them before
        the next exchange, and temperature() reads the interior only),
        all inside one shard_map/jit with buffer donation.

        ``make_body(org)`` returns either a single-iteration body, or a
        ``(body, group_body, group_n)`` tuple — then ``n`` iterations
        run as ``n // group_n`` temporally-blocked groups plus a
        single-step tail."""
        from ..parallel.exchange import shard_origin

        dd = self.dd
        lo = dd.alloc_radius.pad_lo()
        local = dd.local_size
        rem = dd.rem

        def shard_steps(p, n):
            ox, oy, oz = shard_origin(local, rem)
            org = jnp.stack([oz, oy, ox]).astype(jnp.int32)
            inner = lax.slice(p, (lo.z, lo.y, lo.x),
                              (lo.z + local.z, lo.y + local.y,
                               lo.x + local.x))
            made = make_body(org)
            if isinstance(made, tuple):
                body, group_body, gn = made
                inner = lax.fori_loop(0, n // gn,
                                      lambda _, q: group_body(q), inner)
                inner = lax.fori_loop(0, n % gn,
                                      lambda _, q: body(q), inner)
            else:
                body = made
                inner = lax.fori_loop(0, n, lambda _, q: body(q), inner)
            return lax.dynamic_update_slice(p, inner, (lo.z, lo.y, lo.x))

        spec = P("z", "y", "x")
        sm = jax.shard_map(shard_steps, mesh=dd.mesh, in_specs=(spec, P()),
                           out_specs=spec, check_vma=False)
        self._step_n = jax.jit(sm, donate_argnums=0)
        self._step = jax.jit(
            lambda p: sm(p, jnp.asarray(1, jnp.int32)), donate_argnums=0)

        if segment_decline is not None:
            self._set_segment_decline(segment_decline,
                                      code=segment_decline_code)
            return

        def shard_advance(p, c):
            # one segment chunk, per shard: c == group_n is ONE
            # temporally-blocked kernel launch (its slab exchange
            # inside), c == 1 a single-step tail — the same bodies the
            # fused run loop iterates, with the interior written back
            # per chunk so the in-graph probe reads current state
            ox, oy, oz = shard_origin(local, rem)
            org = jnp.stack([oz, oy, ox]).astype(jnp.int32)
            inner = lax.slice(p, (lo.z, lo.y, lo.x),
                              (lo.z + local.z, lo.y + local.y,
                               lo.x + local.x))
            made = make_body(org)
            if isinstance(made, tuple):
                body, group_body, gn = made
                if c == gn:
                    inner = group_body(inner)
                else:
                    for _ in range(c):
                        inner = body(inner)
            else:
                for _ in range(c):
                    inner = made(inner)
            return lax.dynamic_update_slice(p, inner, (lo.z, lo.y, lo.x))

        self._set_segment_builder(shard_advance, stride=segment_stride)

    def _build_halo_step(self) -> None:
        """Multi-device fused steps: interior-resident shards, thin slab
        ppermutes, one fused Pallas kernel per step — so an N-chip mesh
        keeps single-chip per-chip throughput (the analog of the
        reference's fused solve kernel running at every scale,
        astaroth/astaroth.cu:552-646; see ops/pallas_halo.py).

        Even grids run iterations in groups of N through the
        temporally-blocked kernel (``jacobi7_halon_pallas``, N=2
        default / STENCIL_WRAP_STEPS): one radius-N exchange feeds N
        fused steps, dividing per-iteration HBM traffic AND exchange
        count by ~N (the slab-layout counterpart of the wrap-path
        kernel), with a single-step tail. Uneven (+-1) grids and grids
        the blocked kernel can't tile keep the single-step kernel."""
        import os

        from ..ops.pallas_halo import (fit_pair_halo_blocks,
                                       jacobi7_halon_pallas,
                                       jacobi7_halo_pallas)
        from ..ops.pallas_stencil import sublane_tile
        from ..parallel.exchange import (exchange_interior_slabs,
                                         shard_interior_len)
        from ..utils.config import wrap2_disabled

        dd = self.dd
        local = dd.local_size
        counts = mesh_dim(dd.mesh)
        rem = dd.rem
        gsize = (dd.size.z, dd.size.y, dd.size.x)
        hot, cold, sph_r = sphere_geometry(dd.size)
        tile = sublane_tile(self._dtype)
        esub = tile if local.y % tile == 0 else 1
        N = _wrap_steps(tile, self._exchange_every)
        pair_ok = (rem == Dim3(0, 0, 0) and N > 1 and esub == tile
                   and not wrap2_disabled())
        if pair_ok:
            from ..analysis.tiling import TilingInfeasibleError

            try:
                pbz, pby = fit_pair_halo_blocks(
                    local.z, local.y, local.x,
                    jnp.dtype(self._dtype).itemsize, N)
            except TilingInfeasibleError as e:
                # the planner found no legal blocking for the N-step
                # kernel at this shard: fall back to the single-step
                # kernel LOUDLY (the old fitter clamped silently and
                # let Mosaic fail at compile time). The planner
                # enforces bz >= steps, so a partial clamp cannot
                # happen — it is all-or-nothing by construction.
                from ..utils.logging import LOG_WARN
                LOG_WARN(f"halo temporal blocking declined: {e}")
                pair_ok = False
        if pair_ok:
            from ..utils.logging import LOG_INFO
            LOG_INFO(f"jacobi halo path: {N}-step temporal blocking, "
                     f"blocks ({pbz}, {pby})")
        # exchange accounting for exchange_stats(): the N-step groups
        # do one radius-N extended exchange per N iterations (the tail
        # uses the single-row config; stats report the group-amortized
        # steady state)
        self._slab_exchange_cfg = (
            dict(rz=pbz, ry=tile, radius_rows=N, y_z_extended=True,
                 per_iter_div=N) if pair_ok
            else dict(rz=1, ry=esub, radius_rows=1, y_z_extended=False,
                      per_iter_div=1))

        def make_body(org):
            lens = jnp.stack([
                jnp.asarray(shard_interior_len(2, local.z, rem)),
                jnp.asarray(shard_interior_len(1, local.y, rem)),
            ]).astype(jnp.int32)

            def body(q):
                slabs = exchange_interior_slabs(q, counts, rz=1, ry=esub,
                                                rem=rem)
                return jacobi7_halo_pallas(q, slabs, org, hot, cold,
                                           sph_r, interior_len_zy=lens)

            if not pair_ok:
                return body

            def pair_body(q):
                slabs = exchange_interior_slabs(
                    q, counts, rz=pbz, ry=tile, radius_rows=N,
                    y_z_extended=True)
                return jacobi7_halon_pallas(q, slabs, org, gsize, hot,
                                            cold, sph_r, steps=N,
                                            block_z=pbz, block_y=pby)

            return body, pair_body, N

        self._build_interior_resident_steps(
            make_body, segment_stride=N if pair_ok else 1)

    def _build_overlap_step(self) -> None:
        """Overlapped multi-device fused steps: ONE Pallas kernel per
        iteration issues the slab RDMA, computes the interior while the
        transfers fly, and fixes the faces once they land (the
        reference's polled-transport overlap, src/stencil.cu:1081-1118,
        as a single kernel; see ops/pallas_overlap.py)."""
        from ..ops.pallas_overlap import jacobi7_overlap_pallas
        from ..parallel import megastep as ms

        counts = mesh_dim(self.dd.mesh)
        hot, cold, sph_r = sphere_geometry(self.dd.size)

        def make_body(org):
            def body(q):
                return jacobi7_overlap_pallas(q, org, hot, cold, sph_r,
                                              counts)
            return body

        # the in-kernel RDMA moves the same single-row face slabs as a
        # radius-1 slab exchange (ops/pallas_overlap.py phase 2)
        self._slab_exchange_cfg = dict(rz=1, ry=1, radius_rows=1,
                                       y_z_extended=False, per_iter_div=1)
        # the formerly name-matched fused-segment decline is now
        # certificate-gated: the schedule certifier
        # (analysis/schedule.py) replays the kernel's semaphore
        # schedule k times and proves every launch hands the next a
        # quiescent semaphore file (drained send/recv slots, balanced
        # barrier, no unwaited-inbound reads). A replay_safe
        # certificate licenses chunk-of-1 fusion — k kernel launches
        # inside ONE compiled segment; anything else declines citing
        # the certificate's own reasons
        cert = _overlap_schedule_certificate(
            self.dd, self._dtype, hot, cold, sph_r, counts)
        self._schedule_certificate = cert
        gate = ms.certificate_gate(cert)
        if gate is None:
            self._build_interior_resident_steps(make_body)
        else:
            self._build_interior_resident_steps(
                make_body, segment_decline=gate,
                segment_decline_code=ms.DECLINE_UNCERTIFIED_SCHEDULE)

    def exchange_stats(self) -> dict:
        """Per-iteration exchange accounting for the BUILT compute
        path. The fused fast paths (wrap/halo/overlap) bypass
        ``dd.exchange()`` entirely, so the orchestrator's counters say
        nothing about exactly the paths that get benchmarked (the
        reference keeps per-iteration exchange stats on its one path,
        src/stencil.cu:1005-1008,1174-1181); this reports the wire
        bytes the built path moves per iteration (whole mesh, the
        ``exchange_bytes_total`` convention bench_exchange prints) and
        the exchange rounds per iteration (temporal blocking amortizes
        rounds below 1)."""
        from ..parallel.exchange import interior_slab_bytes

        counts = mesh_dim(self.dd.mesh)
        local = self.dd.local_size
        path = self.kernel_path
        if path == "wrap":
            return {"path": path, "bytes_per_iteration": 0,
                    "rounds_per_iteration": 0.0}
        cfg = getattr(self, "_slab_exchange_cfg", None)
        if cfg is not None and path in ("halo", "overlap"):
            per_shard = interior_slab_bytes(
                (local.z, local.y, local.x), counts, cfg["radius_rows"],
                jnp.dtype(self._dtype).itemsize, cfg["y_z_extended"])
            n = counts.flatten()
            return {"path": path,
                    "bytes_per_iteration":
                        per_shard * n / cfg["per_iter_div"],
                    "rounds_per_iteration": 1.0 / cfg["per_iter_div"]}
        d = self.dd.exchange_depths
        s = self.dd.exchange_every
        if d.x == d.y == d.z:
            rounds = 1.0 / s
        else:
            # asymmetric group: the deep exchange at sub-step 0 plus a
            # mid-group refresh at every k where some axis's cadence
            # divides k (parallel.temporal.refresh_axes)
            rounds = (1 + sum(1 for k in range(1, s)
                              if any(k % d[a] == 0
                                     for a in range(3)))) / s
        return {"path": path,
                "bytes_per_iteration":
                    float(self.dd.exchange_bytes_amortized_per_step()),
                "rounds_per_iteration": rounds}

    def measure_exchange_seconds(self, reps: int = 10) -> float:
        """Estimated exchange seconds per ITERATION of the built path,
        measured standalone per round config (the fused loops perform
        the exchange inside one XLA program where it cannot be timed
        separately) and scaled by the path's rounds-per-iteration —
        the same per-iteration convention as
        ``Astaroth.measure_exchange_seconds``. Returns 0.0 on the wrap
        path (no exchange exists)."""
        path = self.kernel_path
        if path == "wrap":
            return 0.0
        cfg = getattr(self, "_slab_exchange_cfg", None)
        if cfg is not None and path in ("halo", "overlap"):
            from ..parallel.exchange import measure_slab_exchange_seconds
            round_s = measure_slab_exchange_seconds(
                self.dd.mesh, self.dd.local_size, self._dtype,
                rz=cfg["rz"], ry=cfg["ry"],
                radius_rows=cfg["radius_rows"],
                y_z_extended=cfg["y_z_extended"], reps=reps)
            return round_s / cfg["per_iter_div"]
        import time

        from ..utils.timers import device_sync
        self.dd.exchange()
        device_sync(self.dd.curr["temp"])
        t0 = time.perf_counter()
        for _ in range(reps):
            self.dd.exchange()
        device_sync(self.dd.curr["temp"])
        # one (possibly deep) exchange feeds exchange_every iterations
        return (time.perf_counter() - t0) / reps / self.dd.exchange_every

    def step(self) -> None:
        """One iteration: exchange + 7-point update + sources."""
        self.dd.curr["temp"] = self._step(self.dd.curr["temp"])

    def run(self, iters: int) -> None:
        """``iters`` iterations in one XLA program (fori_loop — no
        per-iteration dispatch)."""
        self.dd.curr["temp"] = self._step_n(self.dd.curr["temp"],
                                            jnp.asarray(iters, jnp.int32))

    def block(self) -> None:
        from ..utils.timers import device_sync
        device_sync(self.dd.curr["temp"])

    def temperature(self) -> np.ndarray:
        """Global interior (z,y,x) on host."""
        return self.dd.interior_to_host("temp")

    # -- resilient run loop (stencil_tpu/resilience) -------------------
    def run_resilient(self, n_steps: int, policy=None,
                      ckpt_dir: Optional[str] = None, faults=None):
        """``n_steps`` iterations under the checkpoint-rollback
        recovery driver (:func:`stencil_tpu.resilience.run_resilient`):
        health sentinels every ``policy.check_every`` steps, integrity-
        checked checkpoints every ``policy.ckpt_every``, rollback +
        bounded retry on divergence, configuration degradation on
        repeat failure (the solver is rebuilt in place at the softer
        config), and clean SIGTERM preemption/resume via ``ckpt_dir``.
        Returns the :class:`~stencil_tpu.resilience.ResilienceReport`."""
        from ..resilience.driver import run_resilient

        def rebuild(cfg):
            new = Jacobi3D(
                self.dd.size.x, self.dd.size.y, self.dd.size.z,
                mesh_shape=tuple(self.dd.placement.dim()),
                dtype=self._dtype, devices=self.dd._devices,
                methods=cfg.method, kernel=self._kernel,
                overlap=self._overlap,
                exchange_every=cfg.exchange_every,
                boundary=self.dd.boundary,
                placement=self.dd.strategy,
                output_prefix=self.dd._output_prefix,
                **_dcn_request_kwargs(self.dd))
            # adopt the rebuilt engine in place so the caller's handle
            # (and the driver's fields_fn closure) stay valid; the
            # fused-segment factory is rebuilt with it (third element)
            # so the degraded configuration's megastep serves from here
            self.__dict__.update(new.__dict__)
            return self.dd, self.step, self.make_segment

        return run_resilient(self.dd, self.step, n_steps, policy=policy,
                             ckpt_dir=ckpt_dir, faults=faults,
                             rebuild=rebuild,
                             fields_fn=lambda: self.dd.curr,
                             # always passed: a path with no builder
                             # returns a reason-carrying decline the
                             # driver reports (never a silent stepwise
                             # fallback)
                             make_segment=self.make_segment,
                             perf_entry="jacobi")


def dense_reference_step(temp: np.ndarray, hot_c: Tuple[int, int, int],
                         cold_c: Tuple[int, int, int], sph_r: int
                         ) -> np.ndarray:
    """Single-device dense oracle of one jacobi step on a (z,y,x) global
    array with periodic wrap — the correctness reference for the
    distributed solver (BASELINE.md config 1)."""
    out = np.zeros_like(temp)
    for axis, dim in ((0, 0), (1, 1), (2, 2)):
        out += np.roll(temp, 1, axis=axis) + np.roll(temp, -1, axis=axis)
    out /= 6.0
    gz, gy, gx = np.meshgrid(np.arange(temp.shape[0]),
                             np.arange(temp.shape[1]),
                             np.arange(temp.shape[2]), indexing="ij")
    hx, hy, hz = hot_c
    cx, cy, cz = cold_c
    d2h = (gx - hx) ** 2 + (gy - hy) ** 2 + (gz - hz) ** 2
    d2c = (gx - cx) ** 2 + (gy - cy) ** 2 + (gz - cz) ** 2
    out = np.where(d2h <= sph_r * sph_r, HOT_TEMP, out)
    out = np.where(d2c <= sph_r * sph_r, COLD_TEMP, out)
    return out.astype(temp.dtype)
