"""Particle-in-cell mini-app: charged particles on the sharded grid.

The scenario-diversity workload (ROADMAP item 5): both PIConGPU
(arXiv:1606.02862) and POLAR-PIC (arXiv:2604.19337) are PIC codes
layered on exactly this kind of halo framework plus one thing the
static sweep never exercised — a *dynamic, data-dependent* exchange.
One PIC step, fused into a single ``shard_map``-ped XLA program per
shard:

1. **deposit** — every particle scatters its charge into the
   halo-padded ``rho`` array (NGP nearest-cell or CIC trilinear); edge
   particles legally land in pad cells that belong to a neighbor;
2. **reverse halo-accumulate** — the adjoint of the halo sweep
   (:func:`~stencil_tpu.parallel.exchange.accumulate_shard`) folds
   those pad contributions back into the owning interiors;
3. **exchange** — the ordinary forward halo sweep fills ``rho`` pads
   so the field stencil has support;
4. **gather** — ``E = -grad rho`` (``ops.stencil_kernels.central_diff``)
   interpolated at particle positions (NGP/CIC, same kernel family as
   the deposit);
5. **leapfrog push** — ``v += q E dt``, ``x += v dt`` (like charges
   repel: the deposited density is its own potential proxy — a
   mini-app, not a Poisson solver);
6. **migrate** — the fixed-capacity sort/pad/ppermute-ring migration
   (:mod:`stencil_tpu.parallel.migrate`), with the cumulative overflow
   counter carried in the particle state.

Communication bill per step, pinned by the ``models.pic.*`` registry
targets: 2 ppermutes per active axis for the accumulate + 2 for the
exchange + 2 for the migration — collective-permute only, bytes
matching the analytic model exactly. Health probing rides the standard
sentinel machinery: :meth:`Pic.make_sentinel` probes ``rho`` AND the
particle SoA arrays and appends the migration-overflow counter as an
extra probe column on the probe's one existing all-reduce.

CFL-style contract: a particle moves at most one shard per step
(``|v| * dt < min shard extent``) — the fixed ±1 ring is exact under
it; beyond it, migration drops and counts the particle (overflow).
Boundaries are periodic (the migration ring and the position wrap
share one topology).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..distributed import DistributedDomain
from ..geometry import Dim3
from ..ops.stencil_kernels import central_diff
from ..parallel.exchange import (accumulate_shard, dispatch_exchange,
                                 shard_interior_len, shard_origin)
from ..parallel.mesh import mesh_dim
from ..parallel.methods import Method, pick_method
from ..parallel.migrate import migrate_shard

#: the particle SoA fields, in state order (one common dtype)
PARTICLE_FIELDS = ("x", "y", "z", "vx", "vy", "vz", "q")

#: every per-particle state key checkpointed as extras
PARTICLE_STATE_KEYS = PARTICLE_FIELDS + ("valid", "overflow")

#: PIC stencil radius: CIC deposits reach 1 cell past the interior and
#: the gathered E needs rho one cell further out
RADIUS = 2


def _floor_int(v):
    return jnp.floor(v).astype(jnp.int32)


class Pic:
    """Distributed electrostatic-proxy PIC over a TPU mesh."""

    def __init__(self, x: int, y: int, z: int, n_particles: int,
                 mesh_shape=None, dtype=jnp.float32,
                 devices: Optional[Sequence] = None,
                 methods: Method = Method.Default,
                 capacity: Optional[int] = None,
                 budget: Optional[int] = None,
                 deposition: str = "cic", dt: float = 0.25,
                 push: float = 1.0, seed: int = 0) -> None:
        if deposition not in ("cic", "ngp"):
            raise ValueError(f"deposition must be cic|ngp, "
                             f"got {deposition!r}")
        self.dd = DistributedDomain(x, y, z, devices=devices)
        self.dd.set_radius(RADIUS)
        self.dd.set_methods(methods)
        if pick_method(methods) not in (Method.PpermuteSlab,
                                        Method.PpermutePacked):
            raise NotImplementedError(
                "Pic supports the PpermuteSlab and PpermutePacked "
                "exchange methods (the accumulate adjoint and the "
                "migration ring are ppermute engines)")
        if mesh_shape is not None:
            self.dd.set_mesh_shape(mesh_shape)
        self.dd.add_data("rho", dtype)
        self.dd.realize()
        self._dtype = np.dtype(self.dd._dtypes["rho"])
        self.n_particles = int(n_particles)
        self.deposition = deposition
        self.dt = float(dt)
        self.push = float(push)
        self.seed = int(seed)
        n_shards = self.dd.placement.dim().flatten()
        per = -(-self.n_particles // n_shards)
        self.capacity = (int(capacity) if capacity is not None
                         else max(2 * per, 8))
        if self.capacity < per:
            raise ValueError(
                f"capacity {self.capacity} < {per} particles/shard at "
                f"a uniform fill — even the initial state overflows")
        self.budget = (int(budget) if budget is not None
                       else max(self.capacity // 4, 4))
        if not 1 <= self.budget <= self.capacity:
            raise ValueError(f"budget must be in [1, capacity], got "
                             f"{self.budget}")
        # the ParticleLoss fault class reads the block layout off the
        # domain (resilience/faults.py)
        self.dd.particle_capacity = self.capacity
        self._psharding = NamedSharding(self.dd.mesh, P(("z", "y", "x")))
        #: the LIVE state the step advances, the sentinel probes, and
        #: the fault injector mutates: the padded rho global plus the
        #: particle SoA/validity/overflow lanes (dd.curr['rho'] stays
        #: aliased to state['rho'] after every step)
        self.state: Dict[str, jnp.ndarray] = {}
        self._build_step()
        self._build_probe()
        self.init()

    # -- geometry helpers ----------------------------------------------
    def _axis_bounds(self, axis: int) -> np.ndarray:
        """Subdomain origin boundaries along grid ``axis`` (len
        counts+1) — uneven (+-1) partitions included."""
        dim = self.dd.placement.dim()
        idx = [Dim3(*(b if a == axis else 0 for a in range(3)))
               for b in range(dim[axis])]
        orgs = [self.dd.placement.subdomain_origin(i)[axis] for i in idx]
        return np.asarray(orgs + [self.dd.size[axis]], dtype=np.float64)

    def _block_linear(self, bx, by, bz):
        """Linear particle-block index of shard (bx, by, bz) under the
        ``P(('z','y','x'))`` sharding: z outermost, x innermost."""
        dim = self.dd.placement.dim()
        return (bz * dim.y + by) * dim.x + bx

    # -- initial conditions --------------------------------------------
    def init(self) -> None:
        """Seeded uniform plasma: positions uniform over the grid,
        small thermal velocities, unit charges."""
        rng = np.random.default_rng(self.seed)
        g = self.dd.size
        n = self.n_particles
        arrays = {
            "x": rng.uniform(0, g.x, n), "y": rng.uniform(0, g.y, n),
            "z": rng.uniform(0, g.z, n),
            "vx": rng.normal(0, 0.05, n), "vy": rng.normal(0, 0.05, n),
            "vz": rng.normal(0, 0.05, n),
            "q": np.ones(n),
        }
        self.set_particles(arrays)

    def set_particles(self, arrays: Dict[str, np.ndarray]) -> None:
        """Install explicit particle ICs (host arrays of one common
        length, keys :data:`PARTICLE_FIELDS`; missing velocity/charge
        keys default to 0/1). Particles are binned to the shard owning
        their position and padded to the static capacity."""
        n = len(np.asarray(arrays["x"]))
        host = {}
        for k in PARTICLE_FIELDS:
            v = arrays.get(k)
            if v is None:
                v = np.ones(n) if k == "q" else np.zeros(n)
            host[k] = np.asarray(v, dtype=self._dtype)
            if host[k].shape != (n,):
                raise ValueError(f"particle field {k!r} has shape "
                                 f"{host[k].shape}, want ({n},)")
        bounds = [self._axis_bounds(a) for a in range(3)]
        pos = {0: host["x"], 1: host["y"], 2: host["z"]}
        for a in range(3):
            if np.any((pos[a] < 0) | (pos[a] >= self.dd.size[a])):
                raise ValueError(f"particle positions outside the "
                                 f"[0, {self.dd.size[a]}) grid along "
                                 f"{'xyz'[a]}")
        block = np.zeros(n, dtype=np.int64)
        bidx = {}
        for a in range(3):
            bidx[a] = np.searchsorted(bounds[a], pos[a],
                                      side="right") - 1
        block = self._block_linear(bidx[0], bidx[1], bidx[2])
        n_shards = self.dd.placement.dim().flatten()
        cap = self.capacity
        full = {k: np.zeros(n_shards * cap, dtype=self._dtype)
                for k in PARTICLE_FIELDS}
        valid = np.zeros(n_shards * cap, dtype=bool)
        for b in range(n_shards):
            sel = np.nonzero(block == b)[0]
            if len(sel) > cap:
                raise ValueError(
                    f"{len(sel)} particles land on shard block {b} "
                    f"but capacity is {cap}")
            dst = slice(b * cap, b * cap + len(sel))
            for k in PARTICLE_FIELDS:
                full[k][dst] = host[k][sel]
            valid[b * cap: b * cap + len(sel)] = True
        self.n_particles = n
        for k in PARTICLE_FIELDS:
            self.state[k] = jax.device_put(full[k], self._psharding)
        self.state["valid"] = jax.device_put(valid, self._psharding)
        self.state["overflow"] = jax.device_put(
            np.zeros(n_shards, dtype=np.float32), self._psharding)
        self.state["rho"] = self.dd.curr["rho"]

    # -- the fused step ------------------------------------------------
    def _build_step(self) -> None:
        dd = self.dd
        counts = mesh_dim(dd.mesh)
        local = dd.local_size
        gsize = dd.size
        rem = dd.rem
        radius = dd.alloc_radius
        lo = radius.pad_lo()
        method = pick_method(dd.methods)
        dep = self.deposition
        dt = self.dt
        push = self.push
        budget = self.budget
        cap = self.capacity

        def deposit_weights(px, py, pz):
            """Per-corner (cell_index, weight) pairs of the deposition
            stencil in LOCAL coordinates — shared by the charge
            scatter and the field gather so the two interpolate the
            same way (validity masking is the call sites' business)."""
            if dep == "ngp":
                cz = _floor_int(pz + 0.5)
                cy = _floor_int(py + 0.5)
                cx = _floor_int(px + 0.5)
                one = jnp.ones_like(px)
                return [((cz, cy, cx), one)]
            i0z, i0y, i0x = _floor_int(pz), _floor_int(py), _floor_int(px)
            fz = pz - jnp.floor(pz)
            fy = py - jnp.floor(py)
            fx = px - jnp.floor(px)
            out = []
            for dz in (0, 1):
                wz = fz if dz else (1.0 - fz)
                for dy in (0, 1):
                    wy = fy if dy else (1.0 - fy)
                    for dx in (0, 1):
                        wx = fx if dx else (1.0 - fx)
                        out.append(((i0z + dz, i0y + dy, i0x + dx),
                                    wz * wy * wx))
            return out

        def shard_step(st):
            rho = st["rho"]
            valid = st["valid"]
            q = st["q"]
            ox, oy, oz = shard_origin(local, rem)
            Lx = shard_interior_len(0, local.x, rem)
            Ly = shard_interior_len(1, local.y, rem)
            Lz = shard_interior_len(2, local.z, rem)
            # local (cell) coordinates of each particle on this shard
            px = st["x"] - ox
            py = st["y"] - oy
            pz = st["z"] - oz

            # 1. deposit charge into the padded shard (pads included).
            # The deposit target is the DONATED rho buffer scrubbed to
            # zero NaN-safely (nan_to_num first: a poisoned cell must
            # not survive the x*0 rewrite XLA is forbidden to fold) —
            # a plain zeros_like would leave the rho parameter unused
            # and the compiler would drop its input_output_alias
            rho_new = jnp.nan_to_num(rho) * jnp.zeros((), rho.dtype)
            corners = deposit_weights(px, py, pz)
            for (cz, cy, cx), w in corners:
                iz = jnp.where(valid, lo.z + cz, -1)
                iy = jnp.where(valid, lo.y + cy, -1)
                ix = jnp.where(valid, lo.x + cx, -1)
                rho_new = rho_new.at[(iz, iy, ix)].add(
                    jnp.where(valid, q * w.astype(q.dtype),
                              jnp.zeros_like(q)), mode="drop")

            # 2. fold pad deposits into the owning interiors (adjoint)
            rho_new = accumulate_shard(rho_new, radius, counts, rem=rem)

            # 3. forward halo sweep: fill pads for the field stencil
            rho_new = dispatch_exchange(
                {"rho": rho_new}, radius, counts, method,
                rem=rem)["rho"]

            # 4. gather E = -grad rho at the particles; the field is
            # computed on the static [0, capacity] node window (the
            # one-past-interior column edge particles interpolate)
            win = Dim3(local.x + 1, local.y + 1, local.z + 1)
            E = [-central_diff(rho_new, a, radius, win)
                 for a in range(3)]
            ex = jnp.zeros_like(px)
            ey = jnp.zeros_like(py)
            ez = jnp.zeros_like(pz)
            for (cz, cy, cx), w in corners:
                gz = jnp.clip(cz, 0, local.z)
                gy = jnp.clip(cy, 0, local.y)
                gx = jnp.clip(cx, 0, local.x)
                wt = w.astype(px.dtype)
                ex = ex + wt * E[0][(gz, gy, gx)]
                ey = ey + wt * E[1][(gz, gy, gx)]
                ez = ez + wt * E[2][(gz, gy, gx)]

            # 5. leapfrog push (unwrapped positions decide the hop;
            # the stored position wraps periodically)
            k = jnp.asarray(push * dt, q.dtype)
            vx = st["vx"] + k * q * ex
            vy = st["vy"] + k * q * ey
            vz = st["vz"] + k * q * ez
            ux = st["x"] + vx * dt
            uy = st["y"] + vy * dt
            uz = st["z"] + vz * dt

            def offset(u, o, ln):
                off = (jnp.where(u >= o + ln, 1, 0)
                       + jnp.where(u < o, -1, 0)).astype(jnp.int32)
                return jnp.where(valid, off, 0)

            offs = (offset(ux, ox, Lx), offset(uy, oy, Ly),
                    offset(uz, oz, Lz))

            # CFL guard: a particle that would hop MORE than one shard
            # cannot be routed by the +-1 ring — drop it and COUNT it
            # as overflow rather than ship it one hop short, where its
            # out-of-window deposits would be discarded silently
            def beyond(u, o, ln):
                return (u >= o + 2 * ln) | (u < o - ln)

            cfl = valid & (beyond(ux, ox, Lx) | beyond(uy, oy, Ly)
                           | beyond(uz, oz, Lz))
            valid = valid & ~cfl
            fields = {
                "x": jnp.mod(ux, gsize.x), "y": jnp.mod(uy, gsize.y),
                "z": jnp.mod(uz, gsize.z),
                "vx": vx, "vy": vy, "vz": vz, "q": q,
            }

            # 6. migrate across the ppermute ring; overflow accumulates
            fields, valid, ovf = migrate_shard(fields, valid, offs,
                                               counts, budget)
            ovf = ovf + jnp.sum(cfl).astype(jnp.float32)
            out = {"rho": rho_new, "valid": valid,
                   "overflow": st["overflow"] + ovf}
            out.update(fields)
            return out

        specs = {"rho": P("z", "y", "x")}
        for k in PARTICLE_STATE_KEYS:
            specs[k] = P(("z", "y", "x"))
        sm = jax.shard_map(shard_step, mesh=dd.mesh, in_specs=(specs,),
                           out_specs=specs, check_vma=False)
        self._step = jax.jit(sm, donate_argnums=0)
        self._shard_step = shard_step
        self._state_specs = specs

        def shard_steps(st, n):
            return lax.fori_loop(0, n, lambda _, s: shard_step(s), st)

        sm_n = jax.shard_map(shard_steps, mesh=dd.mesh,
                             in_specs=(specs, P()), out_specs=specs,
                             check_vma=False)
        self._step_n = jax.jit(sm_n, donate_argnums=0)
        self._build_segment_builder()
        # the per-axis displacement bound the +-1 ring can host, for
        # the CFL note in diagnostics; the in-graph guard above DROPS
        # and COUNTS violators (overflow), never corrupts
        self._min_extent = min(
            local[a] - (1 if rem[a] else 0) for a in range(3))

    def _adopt(self, out) -> None:
        self.state = dict(out)
        self.dd.curr["rho"] = self.state["rho"]

    # -- megastep: whole campaign segments as one program ---------------
    def segment_contract(self):
        """The PIC carry contract (``parallel/megastep.py``): the
        fused segment carries the FULL live state — the padded rho
        plus every particle SoA lane, the validity mask, and the
        in-graph overflow column — donated end-to-end, and its probe
        rows reduce rho + all 7 particle lanes with the cumulative
        migration-overflow counter riding the same one all-reduce as
        an extra column (the exact column layout
        :meth:`make_sentinel`'s ``extra_names`` decode). The negative
        control ``tests/fixtures/lint/bad_segment_carry.py`` is this
        contract with the overflow column DROPPED, proven flagged."""
        from ..parallel.megastep import CarryContract

        names = ["rho"] + list(PARTICLE_FIELDS)
        return CarryContract(
            specs=dict(self._state_specs),
            probe_view=lambda st: {q: st[q] for q in names},
            probe_extra=lambda st: {
                "migration_overflow": st["overflow"][0]})

    def _build_segment_builder(self) -> None:
        from ..parallel.megastep import SegmentCompiler

        self._segment_builder = SegmentCompiler(
            self.dd.mesh, self.segment_contract(),
            lambda st, c, i: self._shard_step(st),
            lambda: dict(self.state), self._adopt,
            # PIC's sentinel decodes its OWN in-graph overflow column;
            # telemetry StepMetrics columns would shift the decode
            # layout, so the builder pins the probe rows to the
            # contract's columns regardless of the metrics argument
            use_metrics=False)

    def make_segment(self, check_every: int, probe_every: int = 1,
                     metrics=None):
        """ONE compiled program advancing ``check_every`` PIC steps —
        deposit + accumulate + exchange + gather + push + migrate,
        unrolled ``check_every`` times — with the health probe trace
        (rho + particle lanes + the overflow column) fused in-graph
        every ``probe_every`` steps, the whole state dict donated.
        The ``models.pic.segment[k=4,*]`` registry targets pin one
        segment to exactly ``k x 18`` collective-permutes plus one
        probe all-reduce per trace row, bytes HLO-exact. ``metrics``
        is accepted for driver-interface compatibility and ignored
        (see :meth:`segment_contract`)."""
        return self._segment_builder(int(check_every),
                                     max(int(probe_every), 1), metrics)

    def step(self) -> None:
        """One PIC step: deposit + accumulate + exchange + gather +
        push + migrate, as one compiled dispatch."""
        self._adopt(self._step(self.state))

    def run(self, iters: int) -> None:
        """``iters`` steps in one XLA program (fori_loop body)."""
        self._adopt(self._step_n(self.state,
                                 jnp.asarray(iters, jnp.int32)))

    def block(self) -> None:
        from ..utils.timers import device_sync
        device_sync(self.state["rho"])

    # -- health probing -------------------------------------------------
    def _build_probe(self) -> None:
        dd = self.dd
        self._probe_names = ["rho"] + list(PARTICLE_FIELDS)
        specs = {"rho": P("z", "y", "x")}
        for k in PARTICLE_STATE_KEYS:
            specs[k] = P(("z", "y", "x"))
        names = list(self._probe_names)

        def shard(st):
            from ..resilience.health import probe_shard
            return probe_shard(
                {q: st[q] for q in names},
                extra={"migration_overflow": st["overflow"][0]})

        sm = jax.shard_map(shard, mesh=dd.mesh, in_specs=(specs,),
                           out_specs=P(), check_vma=False)
        self._probe_fn = jax.jit(sm)

    def make_sentinel(self, window: int = 8,
                      growth_factor: float = 1e6):
        """A :class:`~stencil_tpu.resilience.health.HealthSentinel`
        over the FULL live state (rho + every particle SoA lane), with
        a migration-overflow column riding the probe's one all-reduce
        (decoded into ``HealthStats.metrics['migration_overflow']``).
        The probe reduction is a max, so the column reports the WORST
        per-shard cumulative drop count — zero iff no shard dropped
        anything (the alerting predicate); the exported
        ``stencil_run_migration_overflow_total`` counter is the
        all-shard SUM (:meth:`overflow_total`, host-side)."""
        from ..resilience.health import HealthSentinel
        return HealthSentinel(
            self.dd, window=window, growth_factor=growth_factor,
            names=self._probe_names,
            probe_fn=lambda fields, step: self._probe_fn(dict(fields)),
            extra_names=("migration_overflow",))

    # -- diagnostics ----------------------------------------------------
    def rho(self) -> np.ndarray:
        """Global interior charge density (z,y,x) on host."""
        return self.dd.interior_to_host("rho")

    def total_charge(self) -> float:
        """Sum of the deposited charge over the global grid."""
        return float(np.sum(self.rho(), dtype=self._dtype))

    def particles_to_host(self) -> Dict[str, np.ndarray]:
        """Host copies of the LIVE particles only (invalid slots
        dropped), plus the per-shard overflow counters under
        ``'overflow'``."""
        valid = np.asarray(self.state["valid"])
        out = {k: np.asarray(self.state[k])[valid]
               for k in PARTICLE_FIELDS}
        out["overflow"] = np.asarray(self.state["overflow"])
        return out

    def overflow_total(self) -> float:
        """Particles dropped by migration so far (all shards)."""
        return float(np.sum(np.asarray(self.state["overflow"])))

    def perf_model_step_seconds(self) -> Optional[float]:
        """The calibrated cost-model prediction of this engine's wire
        seconds per STEP, for the performance observatory: the reverse
        halo-accumulate plus the forward exchange (two radius-2 sweeps
        — the adjoint pair the fused step pays) priced by the generic
        exchange model, plus the migration ring priced by
        ``analysis/costmodel.migration_step_seconds`` — the same
        figures whose byte bills the ``models.pic.step[cost]`` registry
        target pins HLO-exactly. None on an unsharded mesh (nothing on
        the wire to attribute)."""
        from ..analysis.costmodel import migration_step_seconds
        from ..observatory.attribution import model_step_seconds_for

        sweep = model_step_seconds_for(self.dd)
        if sweep is None:
            return None
        counts = mesh_dim(self.dd.mesh)
        mig = migration_step_seconds(len(PARTICLE_FIELDS), self.budget,
                                     counts, self._dtype.itemsize)
        total = 2.0 * sweep + mig
        return total if total > 0 else None

    def perf_model_bytes_per_step(self) -> float:
        """Whole-mesh modeled wire B/step for attribution: two
        radius-2 sweeps (accumulate + exchange) plus the migration
        ring on every shard — the byte side of
        :meth:`perf_model_step_seconds`, so the exported
        achieved-vs-modeled B/s gauges price the FULL fused step."""
        counts = mesh_dim(self.dd.mesh)
        n_shards = counts.flatten()
        mig = self.migration_stats()["migration_bytes_per_shard"]
        return (2.0 * float(self.dd.exchange_bytes_amortized_per_step())
                + mig * n_shards)

    def migration_stats(self) -> dict:
        """The wire-cost identity of this engine's migration step —
        the same figures the costmodel registry target pins against
        the lowered HLO, plus the CFL displacement bound."""
        from ..analysis.costmodel import migration_wire_bytes_per_shard
        from ..parallel.migrate import migration_record_rows
        counts = mesh_dim(self.dd.mesh)
        return {
            "capacity": self.capacity, "budget": self.budget,
            "record_bytes": migration_record_rows(len(PARTICLE_FIELDS))
            * self._dtype.itemsize,
            "migration_bytes_per_shard": migration_wire_bytes_per_shard(
                len(PARTICLE_FIELDS), self.budget, counts,
                self._dtype.itemsize),
            "max_displacement_per_step": float(self._min_extent),
        }

    # -- checkpointing / resilience -------------------------------------
    def _particle_extras(self) -> Dict[str, jnp.ndarray]:
        return {k: self.state[k] for k in PARTICLE_STATE_KEYS}

    def _install_particles(self, extras: Dict[str, jnp.ndarray]) -> None:
        for k in PARTICLE_STATE_KEYS:
            if k not in extras:
                raise ValueError(f"checkpoint extras missing particle "
                                 f"lane {k!r}")
            want = bool if k == "valid" else (
                np.float32 if k == "overflow" else self._dtype)
            self.state[k] = jax.device_put(
                np.asarray(extras[k]).astype(want, copy=False),
                self._psharding)

    def run_resilient(self, n_steps: int, policy=None,
                      ckpt_dir: Optional[str] = None, faults=None):
        """``n_steps`` PIC steps under the checkpoint-rollback driver:
        the particle lanes ride every checkpoint as extras (like the
        RK accumulators), the sentinel probes the FULL live state with
        the overflow column on its one all-reduce, and a recovered run
        is bitwise-equal to the fault-free one. Exports
        ``stencil_run_particles_total`` /
        ``stencil_run_migration_overflow_total``."""
        from ..resilience.driver import run_resilient

        ovf0 = self.overflow_total()

        def on_restore(extras):
            # restore_domain already reinstalled rho into dd.curr
            self.state["rho"] = self.dd.curr["rho"]
            self._install_particles(extras)

        report = run_resilient(
            self.dd, self.step, n_steps, policy=policy,
            ckpt_dir=ckpt_dir, faults=faults,
            extra_fn=self._particle_extras, on_restore=on_restore,
            fields_fn=lambda: self.state,
            # megastep mode (default): one fused dispatch per health
            # boundary, the overflow column riding the in-graph trace;
            # chaos recovery is BITWISE vs the stepwise loop
            # (tests/test_pic.py pins it)
            make_segment=self.make_segment,
            sentinel_factory=lambda dd: self.make_sentinel(),
            model_step_seconds=self.perf_model_step_seconds(),
            model_bytes_per_step=self.perf_model_bytes_per_step(),
            perf_entry="pic")
        self._export_run_metrics(report.steps, ovf0)
        return report

    def _export_run_metrics(self, steps: int, ovf0: float = 0.0) -> None:
        """Process-registry telemetry (README "Observability"):
        particle steps advanced and migration-overflow drops."""
        from ..telemetry import get_registry
        reg = get_registry()
        c = reg.counter(
            "stencil_run_particles_total",
            "particle steps advanced by PIC run loops (one count per "
            "particle per step; replayed rollback windows included)")
        c.inc(max(int(steps), 0) * self.n_particles)
        o = reg.counter(
            "stencil_run_migration_overflow_total",
            "particles dropped by fixed-capacity migration (send "
            "budget or receive capacity exceeded) — nonzero means the "
            "capacity/budget plan is undersized for the flux")
        o.inc(max(self.overflow_total() - ovf0, 0.0))


def dense_reference_rho(x, y, z, q, gsize, dtype=np.float64,
                        deposition: str = "cic") -> np.ndarray:
    """Single-host dense oracle of one deposition over the periodic
    global grid — the correctness reference for deposit + reverse
    halo-accumulate at any sharding."""
    g = Dim3.of(gsize)
    rho = np.zeros((g.z, g.y, g.x), dtype=dtype)
    x = np.asarray(x, dtype=dtype)
    y = np.asarray(y, dtype=dtype)
    z = np.asarray(z, dtype=dtype)
    q = np.asarray(q, dtype=dtype)
    if deposition == "ngp":
        cx = np.floor(x + 0.5).astype(int) % g.x
        cy = np.floor(y + 0.5).astype(int) % g.y
        cz = np.floor(z + 0.5).astype(int) % g.z
        np.add.at(rho, (cz, cy, cx), q)
        return rho
    i0x, fx = np.floor(x).astype(int), x - np.floor(x)
    i0y, fy = np.floor(y).astype(int), y - np.floor(y)
    i0z, fz = np.floor(z).astype(int), z - np.floor(z)
    for dz in (0, 1):
        wz = fz if dz else (1.0 - fz)
        for dy in (0, 1):
            wy = fy if dy else (1.0 - fy)
            for dx in (0, 1):
                wx = fx if dx else (1.0 - fx)
                np.add.at(rho, ((i0z + dz) % g.z, (i0y + dy) % g.y,
                                (i0x + dx) % g.x), q * wz * wy * wx)
    return rho
