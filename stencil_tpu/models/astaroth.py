"""Astaroth-parity MHD integrator: 8 fields, 6th order, RK3.

TPU-native re-implementation of the reference's astaroth mini-app
("rough approximation of astaroth using the stencil library",
reference: astaroth/astaroth.cu:1-3): 8 scalar fields — lnrho, uu(x,y,z),
aa(x,y,z), entropy (astaroth/astaroth.cu:19-27) — advanced by a
Williamson (1980) 3-step low-storage Runge-Kutta
(astaroth/integration.cuh:14-38) with 6th-order central + cross
derivatives (radius 3 <-> STENCIL_ORDER 6, astaroth/astaroth.h:8-9) and
periodic boundaries.

Physics (reference: astaroth/user_kernels.h:383-453):
* continuity:  d lnrho/dt = -u . grad lnrho - div u
* momentum:    du/dt = -(u.grad)u - cs2 (grad ss / cp + grad lnrho)
               + (1/rho) j x B + nu (lap u + (1/3) grad div u
               + 2 S . grad lnrho) + zeta grad div u
* induction:   dA/dt = u x B + eta lap A           (B = curl A)
* entropy:     d ss/dt = -u . grad ss + (1/(rho T)) [eta mu0 j.j
               + 2 rho nu S:S + zeta rho (div u)^2] + heat conduction
with j = (1/mu0)(grad div A - lap A),
cs2 = cs2_sound exp(gamma ss/cp + (gamma-1)(lnrho - lnrho0)).

Design notes vs the reference:
* One iteration = 3 substeps; each substep is exchange + rates + RK3
  update fused into a single shard_map'ped XLA program over the 3D mesh.
* The reference mini-app never swaps its in/out buffers between
  substeps, so substeps 1-2 re-read the original state
  (astaroth/astaroth.cu:643-649 swaps once per iteration) — a quirk of
  the mini-app, not of Astaroth. Here the 2N-storage scheme is applied
  correctly (w = alpha w + dt F(u); u += beta w per substep), which has
  identical per-iteration comm/compute cost (3 exchanges + 3 stencil
  sweeps).
* dtype is configurable: float32 is the TPU-native choice; float64
  (the reference's AcReal) works on CPU for validation.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..distributed import DistributedDomain
from ..geometry import Dim3, Dim3Like, Radius
from ..local_domain import zyx_shape
from ..ops.fd6 import RADIUS, FieldData
from ..parallel.exchange import dispatch_exchange
from ..parallel.mesh import mesh_dim
from ..parallel.methods import Method, pick_method
from ..utils.config import load_config

FIELDS = ("lnrho", "uux", "uuy", "uuz", "ax", "ay", "az", "ss")

# Williamson (1980) low-storage RK3 (reference: integration.cuh:20-21)
RK3_ALPHA = (0.0, -5.0 / 9.0, -153.0 / 128.0)
RK3_BETA = (1.0 / 3.0, 15.0 / 16.0, 8.0 / 15.0)


@dataclasses.dataclass
class MhdParams:
    """Physical constants (reference: astaroth/astaroth.conf defaults)."""

    dsx: float = 0.04908738521
    dsy: float = 0.04908738521
    dsz: float = 0.04908738521
    dt: float = 1e-8            # astaroth.cu:578 loads AC_dt = 1e-8
    nu_visc: float = 5e-3
    cs_sound: float = 1.0
    zeta: float = 0.01
    eta: float = 5e-3
    mu0: float = 1.4
    cp_sound: float = 1.0
    gamma: float = 0.5
    lnT0: float = 1.2
    lnrho0: float = 1.3

    @property
    def cs2_sound(self) -> float:
        return self.cs_sound * self.cs_sound

    @classmethod
    def from_conf(cls, path: str) -> "MhdParams":
        """Load from an astaroth.conf-style file (reference:
        astaroth/astaroth_utils.cu acLoadConfig)."""
        ints, reals = load_config(path)
        m = {"AC_dsx": "dsx", "AC_dsy": "dsy", "AC_dsz": "dsz",
             "AC_dt": "dt", "AC_nu_visc": "nu_visc",
             "AC_cs_sound": "cs_sound", "AC_zeta": "zeta", "AC_eta": "eta",
             "AC_mu0": "mu0", "AC_cp_sound": "cp_sound",
             "AC_gamma": "gamma", "AC_lnT0": "lnT0", "AC_lnrho0": "lnrho0"}
        kw = {}
        for src, dst in m.items():
            if src in reals:
                kw[dst] = reals[src]
            elif src in ints:
                kw[dst] = float(ints[src])
        return cls(**kw)


def _fast_dtype_ok(dtype) -> bool:
    """True when the fused Pallas kernel paths support ``dtype``:
    float32 (native) and bfloat16 (stored half-width, computed in
    float32 — see ops/pallas_mhd.compute_dtype). float64 falls back
    to the XLA path (TPU f64 is emulated anyway)."""
    import jax.numpy as jnp
    return np.dtype(dtype) in (np.dtype(np.float32),
                               np.dtype(jnp.bfloat16))


def _dot(a, b):
    return a[0] * b[0] + a[1] * b[1] + a[2] * b[2]


def _cross(a, b):
    return (a[1] * b[2] - a[2] * b[1],
            a[2] * b[0] - a[0] * b[2],
            a[0] * b[1] - a[1] * b[0])


def mhd_rates(f: Dict[str, FieldData], prm: MhdParams, dtype):
    """Right-hand sides of all 8 equations at the current state
    (reference: astaroth/user_kernels.h:383-453)."""

    def c(v):
        return jnp.asarray(v, dtype)

    lnrho, ss = f["lnrho"], f["ss"]
    uu = (f["uux"], f["uuy"], f["uuz"])
    aa = (f["ax"], f["ay"], f["az"])

    u = tuple(q.value for q in uu)
    grad_lnrho = lnrho.gradient
    grad_ss = ss.gradient

    div_u = uu[0].grad(0) + uu[1].grad(1) + uu[2].grad(2)

    # continuity (user_kernels.h continuity)
    d_lnrho = -_dot(u, grad_lnrho) - div_u

    # traceless rate-of-strain tensor S (user_kernels.h stress_tensor)
    third = c(1.0 / 3.0)
    S = [[None] * 3 for _ in range(3)]
    S[0][0] = c(2.0 / 3.0) * uu[0].grad(0) - third * (uu[1].grad(1) + uu[2].grad(2))
    S[1][1] = c(2.0 / 3.0) * uu[1].grad(1) - third * (uu[0].grad(0) + uu[2].grad(2))
    S[2][2] = c(2.0 / 3.0) * uu[2].grad(2) - third * (uu[0].grad(0) + uu[1].grad(1))
    S[0][1] = S[1][0] = c(0.5) * (uu[0].grad(1) + uu[1].grad(0))
    S[0][2] = S[2][0] = c(0.5) * (uu[0].grad(2) + uu[2].grad(0))
    S[1][2] = S[2][1] = c(0.5) * (uu[1].grad(2) + uu[2].grad(1))

    # current j = (1/mu0)(grad div A - lap A); B = curl A
    grad_div_a = tuple(
        aa[0].hess(i, 0) + aa[1].hess(i, 1) + aa[2].hess(i, 2)
        for i in range(3))
    lap_a = tuple(q.laplace for q in aa)
    inv_mu0 = c(1.0 / prm.mu0)
    j = tuple(inv_mu0 * (grad_div_a[i] - lap_a[i]) for i in range(3))
    B = (aa[2].grad(1) - aa[1].grad(2),
         aa[0].grad(2) - aa[2].grad(0),
         aa[1].grad(0) - aa[0].grad(1))

    # induction (user_kernels.h induction)
    u_x_B = _cross(u, B)
    d_aa = tuple(u_x_B[i] + c(prm.eta) * lap_a[i] for i in range(3))

    # momentum (user_kernels.h momentum)
    cs2 = c(prm.cs2_sound) * jnp.exp(
        c(prm.gamma / prm.cp_sound) * ss.value
        + c(prm.gamma - 1.0) * (lnrho.value - c(prm.lnrho0)))
    inv_rho = jnp.exp(-lnrho.value)
    adv = tuple(_dot((uu[i].grad(0), uu[i].grad(1), uu[i].grad(2)), u)
                for i in range(3))
    grad_div_u = tuple(
        uu[0].hess(i, 0) + uu[1].hess(i, 1) + uu[2].hess(i, 2)
        for i in range(3))
    lap_u = tuple(q.laplace for q in uu)
    j_x_B = _cross(j, B)
    S_dot_glnrho = tuple(_dot(S[i], grad_lnrho) for i in range(3))
    d_uu = tuple(
        -adv[i]
        - cs2 * (c(1.0 / prm.cp_sound) * grad_ss[i] + grad_lnrho[i])
        + inv_rho * j_x_B[i]
        + c(prm.nu_visc) * (lap_u[i] + third * grad_div_u[i]
                            + c(2.0) * S_dot_glnrho[i])
        + c(prm.zeta) * grad_div_u[i]
        for i in range(3))

    # entropy (user_kernels.h entropy, lnT, heat_conduction)
    lnT = (c(prm.lnT0) + c(prm.gamma / prm.cp_sound) * ss.value
           + c(prm.gamma - 1.0) * (lnrho.value - c(prm.lnrho0)))
    rho = jnp.exp(lnrho.value)
    inv_pT = jnp.exp(-lnrho.value - lnT)
    contract_S = sum(S[i][k] * S[i][k] for i in range(3) for k in range(3))
    rhs = (c(prm.eta * prm.mu0) * _dot(j, j)
           + c(2.0 * prm.nu_visc) * rho * contract_S
           + c(prm.zeta) * rho * div_u * div_u)
    # heat conduction with chi = 0.001/(rho cp) (user_kernels.h:441-449)
    inv_cp = c(1.0 / prm.cp_sound)
    gamma_ = c(prm.gamma)
    first_term = gamma_ * inv_cp * ss.laplace + (gamma_ - c(1.0)) * lnrho.laplace
    second = tuple(gamma_ * inv_cp * grad_ss[i] + (gamma_ - c(1.0)) * grad_lnrho[i]
                   for i in range(3))
    third_t = tuple(gamma_ * (inv_cp * grad_ss[i] + grad_lnrho[i])
                    - grad_lnrho[i] for i in range(3))
    chi = c(0.001) * jnp.exp(-lnrho.value) * inv_cp
    heat = c(prm.cp_sound) * chi * (first_term + _dot(second, third_t))
    d_ss = -_dot(u, grad_ss) + inv_pT * rhs + heat

    return {"lnrho": d_lnrho, "uux": d_uu[0], "uuy": d_uu[1], "uuz": d_uu[2],
            "ax": d_aa[0], "ay": d_aa[1], "az": d_aa[2], "ss": d_ss}


class Astaroth:
    """Distributed MHD integrator over a TPU mesh."""

    def __init__(self, nx: int, ny: int, nz: int,
                 params: Optional[MhdParams] = None,
                 mesh_shape: Optional[Dim3Like] = None,
                 dtype=jnp.float32,
                 devices: Optional[Sequence] = None,
                 methods: Method = Method.PpermutePacked,
                 overlap: bool = False, kernel: str = "auto",
                 dcn_axis=None, dcn_groups=None,
                 exchange_every: Optional[int] = None,
                 boundary=None) -> None:
        self.prm = params or MhdParams()
        self.dd = DistributedDomain(nx, ny, nz, devices=devices)
        self.dd.set_radius(Radius.constant(RADIUS))
        self.dd.set_methods(methods)
        # temporal blocking: one depth-(s*R) exchange per s RK SUBSTEPS
        # (a substep is one stencil application; 3 substeps = 1
        # iteration). s that is a multiple of 3 keeps every blocked
        # group starting at RK substep 0 (alpha_0 == 0), so the w
        # accumulator never rides the wire; other depths exchange w too
        # when a group starts mid-iteration. Pallas fast paths map
        # s == 2 onto the fused substep-0+1 kernel; deeper blocking
        # runs the XLA temporal path (parallel/temporal.py).
        self._exchange_every = 0 if exchange_every is None \
            else max(int(exchange_every), 1)
        if self._exchange_every > 1:
            self.dd.set_exchange_every(self._exchange_every)
        if boundary is not None:
            self.dd.set_boundary(boundary)
        if dcn_axis is not None or dcn_groups is not None:
            self.dd.set_dcn_axis(dcn_axis, dcn_groups)
        if mesh_shape is not None:
            self.dd.set_mesh_shape(mesh_shape)
        elif dcn_axis is not None or dcn_groups is not None:
            # DCN tier with no explicit shape: normally realize()
            # derives the grid from NodePartition's two-level split —
            # but the halo fast paths need x unsharded, which that
            # split does not know (same rule as Jacobi3D; the f32 gate
            # matches the kernel-selection gate below)
            from ..models.jacobi import _dcn_xfree_shape
            from ..ops.pallas_stencil import on_tpu
            halo_want = (kernel == "halo"
                         or (kernel == "auto" and on_tpu()
                             and _fast_dtype_ok(dtype)))
            shape = _dcn_xfree_shape(Dim3(nx, ny, nz),
                                     self.dd._devices, dcn_axis,
                                     dcn_groups,
                                     "halo" if halo_want else "xla",
                                     align=8)
            if shape is not None:
                self.dd.set_mesh_shape(shape)
        else:
            from ..ops.pallas_stencil import on_tpu
            # auto only takes the halo megakernel on TPU AND f32 (the
            # kernel is f32-tuned; _build_step applies the same gate),
            # so don't warp the mesh for configs that will run XLA.
            # overlap keeps the same preference: the in-kernel RDMA
            # overlap path shares the halo kernels' x-unsharded contract
            if (len(self.dd._devices) > 1
                    and (kernel == "halo"
                         or (kernel == "auto" and on_tpu()
                             and _fast_dtype_ok(dtype)))):
                # prefer an x-unsharded decomposition so the fused halo
                # megakernel path is available (ops/pallas_halo.py)
                from ..partition import partition_dims_even_xfree
                shape = partition_dims_even_xfree(
                    Dim3(nx, ny, nz), len(self.dd._devices), align=8)
                if shape is not None:
                    self.dd.set_mesh_shape(shape)
        for q in FIELDS:
            self.dd.add_data(q, dtype)
        self.dd.realize()
        self._dtype = np.dtype(dtype)
        self._overlap = overlap
        if kernel not in ("auto", "wrap", "halo", "xla"):
            raise ValueError(
                f"kernel must be auto|wrap|halo|xla, got {kernel!r}")
        self._kernel = kernel
        # RK3 accumulators (interior-shaped, no halos; the XLA temporal
        # path stores them PADDED so the deep exchange can carry them)
        self._w: Optional[Dict[str, jnp.ndarray]] = None
        self._w_padded = False
        # interior-resident fast-path state (wrap/halo kernels); any
        # external write to dd.curr must go through sync_domain() — the
        # set_interior hook below keeps it coherent automatically
        self._inner: Optional[Dict[str, jnp.ndarray]] = None
        self._insert = None
        self.dd.on_interior_write(lambda name: self.sync_domain())
        self._build_step()

    # -- initial conditions (reference: astaroth/astaroth.cu:509-528) --
    def init(self) -> None:
        """hash-random all fields in [-1, 1); lnrho constant 0.5;
        radial-explosion shell velocity."""
        size = self.dd.size
        shape = zyx_shape(size)
        # the reference's hash init has no per-field seed, so all fields
        # get the identical array — compute it once and skip the four
        # fields overwritten below (astaroth.cu:509-528)
        noise = _hash_field(shape).astype(self._dtype)
        for q in ("ax", "ay", "az", "ss"):
            self.dd.set_interior(q, noise)
        self.dd.set_interior("lnrho",
                             np.full(shape, 0.5, dtype=self._dtype))
        ux, uy, uz = _radial_explosion(size, self.prm)
        self.dd.set_interior("uux", ux.astype(self._dtype))
        self.dd.set_interior("uuy", uy.astype(self._dtype))
        self.dd.set_interior("uuz", uz.astype(self._dtype))
        self._w = None

    # -- fused iteration ----------------------------------------------
    def _build_step(self) -> None:
        self._segment_builder = None
        self._segment_decline = None
        dd = self.dd
        radius = dd.radius
        counts = mesh_dim(dd.mesh)
        local = dd.local_size
        prm = self.prm
        pad_lo = radius.pad_lo()
        inv_ds = (1.0 / prm.dsx, 1.0 / prm.dsy, 1.0 / prm.dsz)
        method = pick_method(dd.methods)
        dt = prm.dt

        rem = dd.rem
        # bf16 stores half-width but must not EVALUATE the 6th-order
        # RHS in bf16 — same storage/compute split as the Pallas paths
        from ..ops.pallas_mhd import compute_dtype
        comp = compute_dtype(self._dtype)
        store = jnp.dtype(self._dtype)

        from ..topology import Boundary
        nonper = dd.boundary == Boundary.NONE
        s_every = dd.exchange_every

        def substep_fused(fields, w, s):
            fields = dispatch_exchange(fields, radius, counts, method,
                                       rem=rem, nonperiodic=nonper)
            data = {q: FieldData(fields[q].astype(comp), inv_ds,
                                 pad_lo, local)
                    for q in FIELDS}
            rates = mhd_rates(data, prm, comp)
            alpha = jnp.asarray(RK3_ALPHA[s], comp)
            beta = jnp.asarray(RK3_BETA[s], comp)
            dt_ = jnp.asarray(dt, comp)
            new_f = {}
            new_w = {}
            for q in FIELDS:
                wq = alpha * w[q].astype(comp) + dt_ * rates[q]
                uq = data[q].value + beta * wq
                new_w[q] = wq.astype(store)
                new_f[q] = lax.dynamic_update_slice(
                    fields[q], uq.astype(store),
                    (pad_lo.z, pad_lo.y, pad_lo.x))
            return new_f, new_w

        def substep_overlap(fields, w, s):
            """Interior rates overlap the exchange (the reference's
            per-substep interior/exchange/exterior choreography,
            astaroth/astaroth.cu:552-646, as one program)."""
            from ..parallel.overlap import overlapped_update

            alpha = jnp.asarray(RK3_ALPHA[s], comp)
            beta = jnp.asarray(RK3_BETA[s], comp)
            dt_ = jnp.asarray(dt, comp)

            def upd(blocks, dims, off):
                data = {q: FieldData(blocks[q].astype(comp), inv_ds,
                                     pad_lo, dims)
                        for q in FIELDS}
                rates = mhd_rates(data, prm, comp)
                out = {}
                for q in FIELDS:
                    w_blk = lax.slice(
                        w[q], (off[2], off[1], off[0]),
                        (off[2] + dims.z, off[1] + dims.y, off[0] + dims.x))
                    wq = alpha * w_blk.astype(comp) + dt_ * rates[q]
                    out[f"w:{q}"] = wq.astype(store)
                    out[f"f:{q}"] = (data[q].value
                                     + beta * wq).astype(store)
                return out

            fields_ex, parts = overlapped_update(fields, radius, counts,
                                                 method, upd,
                                                 nonperiodic=nonper)
            new_f = {q: lax.dynamic_update_slice(
                fields_ex[q], parts[f"f:{q}"],
                (pad_lo.z, pad_lo.y, pad_lo.x)) for q in FIELDS}
            new_w = {q: parts[f"w:{q}"] for q in FIELDS}
            return new_f, new_w

        if self._overlap and rem != Dim3(0, 0, 0):
            raise NotImplementedError("overlap mode requires an evenly "
                                      "divisible grid")
        # single-chip fast path: the fused Pallas "solve" megakernel
        # with periodic wrap in-kernel (ops/pallas_mhd.py) — ~25x the
        # slicing formulation at 256^3
        from ..ops.pallas_mhd import mhd_tile
        tile = mhd_tile(self._dtype)
        aligned_t = (rem == Dim3(0, 0, 0)
                     and local.z % tile == 0 and local.y % tile == 0)
        aligned = aligned_t and not self._overlap
        # the Pallas paths assume periodic wrap; Boundary.NONE and
        # blocking depths beyond the fused substep-0+1 pair (s == 2)
        # run the XLA temporal path
        pallas_s_ok = s_every in (1, 2) and not nonper
        wrap_ok = counts == Dim3(1, 1, 1) and aligned and not nonper
        # multi-device fast path: interior-resident shards + slab
        # exchange + fused halo megakernel (ops/pallas_halo.py)
        halo_ok = counts.x == 1 and aligned and pallas_s_ok
        kernel = self._kernel
        # overlapped multi-device fast path: in-kernel RDMA slab
        # exchange hidden behind the fused interior compute
        # (ops/pallas_mhd_overlap.py) — explicit kernel='halo' +
        # overlap opts in anywhere (tests run it interpreted); 'auto'
        # takes it on real TPU hardware with f32 fields
        rdma_overlap_ok = (self._overlap and counts.x == 1
                           and aligned_t and pallas_s_ok)

        def _blocks_feasible(path: str) -> bool:
            """auto only: does the VMEM block planner find a legal
            shape for this Pallas path at this shard? An explicit
            kernel= request still raises the planner's
            TilingInfeasibleError (the operator asked for exactly that
            path); auto declines to the next path LOUDLY instead — the
            same catch-and-fall-back the Jacobi pair path got."""
            from ..analysis.tiling import TilingInfeasibleError
            from ..ops.pallas_halo import mhd_halo_blocks
            from ..ops.pallas_mhd import _fit_blocks

            blk_z, blk_y = (getattr(self, "_halo_blocks", None)
                            or (8, 32))
            isz = np.dtype(self._dtype).itemsize
            try:
                if path == "wrap":
                    _fit_blocks(local.z, local.y, blk_z, blk_y, tile,
                                X=local.x, itemsize=isz)
                else:
                    mhd_halo_blocks(local.z, local.y, blk_z, blk_y,
                                    tile, X=local.x, itemsize=isz)
                return True
            except TilingInfeasibleError as e:
                from ..utils.logging import LOG_WARN
                LOG_WARN(f"astaroth auto declines the {path} path: {e}")
                return False

        if rdma_overlap_ok:
            from ..ops.pallas_stencil import on_tpu
            if (kernel == "halo"
                    or (kernel == "auto" and on_tpu()
                        and _fast_dtype_ok(self._dtype)
                        and _blocks_feasible("halo"))):
                from ..utils.logging import LOG_INFO
                self.kernel_path = "halo-overlap"
                self._build_halo_overlap_step()
                LOG_INFO("astaroth kernel path: halo-overlap "
                         "(in-kernel RDMA)")
                return
        if kernel == "auto":
            from ..ops.pallas_stencil import on_tpu
            from ..utils.logging import LOG_INFO
            if on_tpu() and _fast_dtype_ok(self._dtype):
                kernel = ("wrap" if wrap_ok and _blocks_feasible("wrap")
                          else "halo" if halo_ok
                          and _blocks_feasible("halo") else "xla")
            else:
                kernel = "xla"
            why = ""
            if kernel == "xla" and on_tpu():
                blockers = []
                if not _fast_dtype_ok(self._dtype):
                    blockers.append(f"dtype {np.dtype(self._dtype).name}")
                if counts.x != 1:
                    blockers.append("x-axis sharded")
                if not aligned:
                    blockers.append(
                        f"uneven grid / z,y % {tile} != 0 / "
                        "overlap requested")
                why = f" (fast paths unavailable: {', '.join(blockers)})"
            LOG_INFO(f"astaroth kernel path: {kernel}{why}")
        if kernel == "wrap":
            if not wrap_ok:
                raise ValueError(
                    "kernel='wrap' needs a (1,1,1) mesh, even grid, z/y "
                    f"multiples of the dtype sublane tile ({tile}), "
                    "overlap off")
            self.kernel_path = "wrap"
            self._build_wrap_step()
            return
        if kernel == "halo":
            if not halo_ok:
                raise ValueError(
                    "kernel='halo' needs an x-unsharded mesh, even grid, "
                    f"local z/y multiples of the dtype sublane tile "
                    f"({tile}), overlap off, periodic boundaries, "
                    "exchange_every <= 2")
            self.kernel_path = "halo"
            self._build_halo_step()
            return
        if s_every > 1:
            self.kernel_path = (f"xla-temporal[s={s_every}]"
                                + ("-overlap" if self._overlap else ""))
            self._build_temporal_xla_step(comp, store, nonper)
            from ..utils.logging import LOG_INFO
            LOG_INFO(f"astaroth kernel path: {self.kernel_path}")
            return
        self.kernel_path = "xla-overlap" if self._overlap else "xla"
        substep = substep_overlap if self._overlap else substep_fused

        def shard_iter(fields, w):
            for s in range(3):
                fields, w = substep(fields, w, s)
            return fields, w

        spec = P("z", "y", "x")
        sm = jax.shard_map(shard_iter, mesh=dd.mesh,
                           in_specs=(spec, spec), out_specs=(spec, spec),
                           check_vma=False)
        self._iter = jax.jit(sm, donate_argnums=(0, 1))

        def shard_iters(fields, w, n):
            return lax.fori_loop(
                0, n, lambda _, fw: shard_iter(*fw), (fields, w))

        sm_n = jax.shard_map(shard_iters, mesh=dd.mesh,
                             in_specs=(spec, spec, P()),
                             out_specs=(spec, spec), check_vma=False)
        self._iter_n = jax.jit(sm_n, donate_argnums=(0, 1))
        self._set_segment_builder(lambda fw, c: shard_iter(*fw))

    def _set_segment_builder(self, advance_iters, stride: int = 1
                             ) -> None:
        """Megastep factory: the RK accumulators ride the fused
        segment as carry next to the fields, both donated end-to-end
        (the ``(fields, w)`` pair IS the carry contract's state
        pytree); the in-graph probe reads the PADDED fields after each
        full RK3 iteration. ``advance_iters((fields, w), c)`` advances
        ``c`` iterations per shard — ``c`` is the path's stride (one
        whole ``lcm(3, s)``-period group block on the temporal path,
        so every blocked group's RK phase stays static inside the
        segment) or a depth-1 tail iteration."""
        from ..parallel import megastep as ms

        dd = self.dd
        spec = P("z", "y", "x")
        fields_spec = {q: spec for q in FIELDS}

        def state_fn():
            self._ensure_w()
            return (dict(self.dd.curr), dict(self._w))

        def adopt(out):
            out_f, out_w = out
            self.dd.curr = dict(out_f)
            self._w = dict(out_w)

        self._segment_decline = None
        self._segment_builder = ms.SegmentCompiler(
            dd.mesh,
            ms.CarryContract(
                specs=(fields_spec, fields_spec),
                probe_view=lambda fw: {q: fw[0][q] for q in FIELDS},
                stride=stride),
            lambda fw, c, i: advance_iters(fw, c), state_fn, adopt)

    def _set_segment_decline(self, reason: str,
                             code: Optional[str] = None) -> None:
        self._segment_builder = None
        self._segment_decline = reason
        self._segment_decline_code = code

    def make_segment(self, check_every: int, probe_every: int = 1,
                     metrics=None):
        """ONE compiled program advancing ``check_every`` RK3
        iterations with the health probe fused in-graph
        (``parallel/megastep.py``); the ``w`` accumulators travel as
        segment carry. The XLA path unrolls per iteration; the
        temporal path chunks whole ``lcm(3, s)``-period groups (the w
        carry's group-straddle phases stay static) plus depth-1
        tails. The interior-resident Pallas fast paths return a falsy
        reason-carrying ``SegmentDecline`` (their state lives outside
        ``dd.curr`` in the extract/loop/insert program split) — the
        resilient driver reports it and falls back to stepwise
        dispatch there."""
        builder = getattr(self, "_segment_builder", None)
        if builder is None:
            from ..parallel import megastep as ms
            reason = (getattr(self, "_segment_decline", None)
                      or "no fused-segment builder for this path")
            code = (getattr(self, "_segment_decline_code", None)
                    or ms.DECLINE_NO_BUILDER)
            return ms.decline("astaroth", self.kernel_path, reason,
                              code=code)
        return builder(int(check_every), max(int(probe_every), 1),
                       metrics)

    def _build_temporal_xla_step(self, comp, store, nonper: bool) -> None:
        """Communication-avoiding XLA iteration: RK substeps run in
        groups of ``s = exchange_every`` through
        ``parallel/temporal.py`` — ONE depth-``s*R`` exchange per group,
        then ``s`` fused substeps on the shrinking window. When ``s``
        does not divide 3, groups straddle iteration boundaries, so the
        loop body covers ``lcm(3, s) / 3`` iterations (every group's RK
        phase is then static) and a group whose first substep has
        ``alpha != 0`` ships the ``w`` accumulator in the same deep
        exchange (pointwise reads only — the ring depth ``(s-1)*R``
        is covered by the uniform ``s*R`` slabs). ``w`` lives PADDED on
        this path so its halo ring has a home."""
        import math

        from ..parallel.exchange import shard_origin
        from ..parallel.temporal import temporal_shard_steps, validate_temporal

        dd = self.dd
        radius = dd.radius
        counts = mesh_dim(dd.mesh)
        local = dd.local_size
        prm = self.prm
        pad_lo = radius.pad_lo()
        inv_ds = (1.0 / prm.dsx, 1.0 / prm.dsy, 1.0 / prm.dsz)
        method = pick_method(dd.methods)
        dt = prm.dt
        rem = dd.rem
        gsize = dd.size
        s = dd.exchange_every
        overlap = self._overlap
        validate_temporal(radius, local, s, rem)
        period = math.lcm(3, s)
        self._w_padded = True
        w_keys = [f"w:{q}" for q in FIELDS]

        def make_update(start, origin):
            ox, oy, oz = origin

            def update_fn(blocks, dims, off, k):
                sub = (start + k) % 3
                data = {q: FieldData(blocks[q].astype(comp), inv_ds,
                                     pad_lo, dims)
                        for q in FIELDS}
                rates = mhd_rates(data, prm, comp)
                alpha = jnp.asarray(RK3_ALPHA[sub], comp)
                beta = jnp.asarray(RK3_BETA[sub], comp)
                dt_ = jnp.asarray(dt, comp)
                if nonper:
                    from ..ops.stencil_kernels import global_coords
                    gz, gy, gx = global_coords(
                        (ox + off[0], oy + off[1], oz + off[2]), dims)
                    inside = ((gx >= 0) & (gx < gsize.x)
                              & (gy >= 0) & (gy < gsize.y)
                              & (gz >= 0) & (gz < gsize.z))
                out = {}
                for q in FIELDS:
                    # w is read POINTWISE: the window-center slice of
                    # its base-radius-padded block
                    wv = lax.slice(
                        blocks[f"w:{q}"],
                        (pad_lo.z, pad_lo.y, pad_lo.x),
                        (pad_lo.z + dims.z, pad_lo.y + dims.y,
                         pad_lo.x + dims.x))
                    wq = alpha * wv.astype(comp) + dt_ * rates[q]
                    uq = data[q].value + beta * wq
                    if nonper:
                        # the zero-Dirichlet exterior: ring cells beyond
                        # the global domain hold 0, exactly what a
                        # stepwise exchange would re-deliver
                        uq = jnp.where(inside, uq, jnp.zeros_like(uq))
                    out[f"w:{q}"] = wq.astype(store)
                    out[q] = uq.astype(store)
                return out

            return update_fn

        def group(f, w, origin, start, depth):
            fields = {q: f[q] for q in FIELDS}
            fields.update({f"w:{q}": w[q] for q in FIELDS})
            # the group's first substep is the only one reading w from
            # before the group; its window ring needs wire data only
            # when alpha != 0 and the window extends past the interior
            keys = list(FIELDS)
            if RK3_ALPHA[start] != 0.0 and depth > 1:
                keys += w_keys
            out = temporal_shard_steps(
                fields, radius, counts, method, make_update(start, origin),
                depth, alloc_steps=s, rem=rem, exchange_keys=keys,
                overlap=overlap and depth > 1, nonperiodic=nonper)
            return ({q: out[q] for q in FIELDS},
                    {q: out[f"w:{q}"] for q in FIELDS})

        def shard_iters(f, w, n):
            origin = shard_origin(local, rem)

            def period_body(_, fw):
                f, w = fw
                for g in range(period // s):
                    f, w = group(f, w, origin, (g * s) % 3, s)
                return f, w

            def tail_iter(_, fw):
                f, w = fw
                for sub in range(3):
                    f, w = group(f, w, origin, sub, 1)
                return f, w

            iters_per_period = period // 3
            f, w = lax.fori_loop(0, n // iters_per_period, period_body,
                                 (f, w))
            return lax.fori_loop(0, n % iters_per_period, tail_iter, (f, w))

        spec = P("z", "y", "x")
        fields_spec = {q: spec for q in FIELDS}
        sm_n = jax.shard_map(shard_iters, mesh=dd.mesh,
                             in_specs=(fields_spec, fields_spec, P()),
                             out_specs=(fields_spec, fields_spec),
                             check_vma=False)
        self._iter_n = jax.jit(sm_n, donate_argnums=(0, 1))
        self._iter = lambda f, w: self._iter_n(f, w,
                                               jnp.asarray(1, jnp.int32))

        iters_per_period = period // 3

        def advance_iters(fw, c):
            # one segment chunk, per shard: a whole lcm(3, s)-period
            # block (every group's RK phase static — the SAME group
            # sequence period_body runs, w shipping in the deep
            # exchange exactly where alpha != 0), or one depth-1 tail
            # iteration (3 per-substep groups)
            f, w = fw
            origin = shard_origin(local, rem)
            if c == iters_per_period:
                for g in range(period // s):
                    f, w = group(f, w, origin, (g * s) % 3, s)
            else:
                for sub in range(3):
                    f, w = group(f, w, origin, sub, 1)
            return f, w

        self._set_segment_builder(advance_iters, stride=iters_per_period)

    def _build_wrap_step(self) -> None:
        """Single-chip fused substeps on interior views (see
        ops/pallas_mhd.mhd_substep_wrap_pallas).

        Extract / substep-loop / insert are three SEPARATE jitted
        programs: composing them into one jit makes XLA schedule the
        Pallas loop an order of magnitude slower (measured 3.5s vs
        ~110ms per iteration at 256^3), while the split pieces run at
        full speed."""
        from ..ops.pallas_mhd import mhd_substep_wrap_pallas

        dd = self.dd
        if dd.exchange_every > 1:
            from ..utils.logging import LOG_WARN
            LOG_WARN("exchange_every has no effect on the single-chip "
                     "wrap path (it performs no exchange); fields still "
                     "carry the deepened allocation pads")
        lo = dd.alloc_radius.pad_lo()
        local = dd.local_size
        prm = self.prm
        dt = prm.dt

        @jax.jit
        def extract(fields):
            return {q: lax.slice(
                p, (lo.z, lo.y, lo.x),
                (lo.z + local.z, lo.y + local.y, lo.x + local.x))
                for q, p in fields.items()}

        # STENCIL_MHD_PAIR=1 opts into the fused substep-0+1 kernel
        # (one HBM pass for two of the three RK substeps; alpha_0 == 0
        # makes the pair independent of the incoming w) — experimental
        # until hardware-measured, so default off
        from ..utils.config import mhd_pair_requested
        pair_on = mhd_pair_requested()
        if pair_on:
            from ..ops.pallas_mhd import mhd_substep01_wrap_pallas
            from ..utils.logging import LOG_INFO
            LOG_INFO("astaroth wrap path: fused substep-0+1 kernel")

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def loop(inner, w, n):
            # dead-w elision: substep 0 never reads w (alpha_0 == 0,
            # w=None) and nothing reads substep 2's w (the next
            # iteration restarts at alpha_0 == 0; write_w=False) — the
            # carry keeps the last WRITTEN w so the fori_loop structure
            # is stable. Saves a full 8-field read + write sweep per
            # iteration vs the reference's unconditional w traffic
            # (astaroth/kernels.cu:63-90).
            def body(_, fw):
                f, wk = fw
                if pair_on:
                    f, wk = mhd_substep01_wrap_pallas(f, prm, dt)
                    f, _ = mhd_substep_wrap_pallas(f, wk, 2, prm, dt,
                                                   write_w=False)
                else:
                    f, wk = mhd_substep_wrap_pallas(f, None, 0, prm, dt)
                    f, wk = mhd_substep_wrap_pallas(f, wk, 1, prm, dt)
                    f, _ = mhd_substep_wrap_pallas(f, wk, 2, prm, dt,
                                                   write_w=False)
                return f, wk
            return lax.fori_loop(0, n, body, (inner, w))

        @functools.partial(jax.jit, donate_argnums=(0,))
        def insert(fields, inner):
            # halos go stale; nothing reads them before the next
            # exchange, and field() reads the interior only
            return {q: lax.dynamic_update_slice(
                fields[q], inner[q], (lo.z, lo.y, lo.x))
                for q in fields}

        # interior-resident state between calls: step()-per-iteration
        # loops would otherwise pay extract+insert (3 extra full-field
        # HBM passes) every iteration. dd.curr is materialized lazily
        # via sync_domain() when the padded domain is accessed.
        self._insert = insert
        self._install_inner_iter(extract, loop)

    def _build_halo_step(self) -> None:
        """Multi-device fused substeps: interior-resident shards, thin
        slab ppermutes, one fused Pallas megakernel per substep — so an
        N-chip mesh keeps single-chip per-chip throughput (the analog
        of the reference's fused solve kernel running at every scale,
        astaroth/astaroth.cu:552-646; see ops/pallas_halo.py).

        Same extract / substep-loop / insert program split (and
        interior-resident caching) as wrap mode, but each program is
        shard_map'ped over the mesh."""
        from ..ops.pallas_halo import (R as HALO_R, mhd_halo_blocks,
                                       mhd_substep_halo_pallas)
        from ..ops.pallas_mhd import mhd_tile
        from ..parallel.exchange import exchange_interior_slabs

        dd = self.dd
        lo = dd.alloc_radius.pad_lo()
        local = dd.local_size
        counts = mesh_dim(dd.mesh)
        prm = self.prm
        dt = prm.dt
        tile = mhd_tile(self._dtype)   # 8 f32/f64, 16 bf16 slabs
        blk_z, blk_y = getattr(self, "_halo_blocks", None) or (8, 32)
        bz, by = mhd_halo_blocks(local.z, local.y, blk_z, blk_y, tile,
                                 X=local.x,
                                 itemsize=np.dtype(self._dtype).itemsize)
        spec = P("z", "y", "x")
        fields_spec = {q: spec for q in FIELDS}

        # STENCIL_MHD_PAIR=1: fused substep-0+1 kernel on the halo path
        # too — one radius-2R exchange + one HBM pass covers two of the
        # three RK substeps (same opt-in as the wrap path; needs the
        # slabs to carry 2R valid rows, hence 2R <= min(bz, tile))
        from ..utils.config import mhd_pair_requested
        pair_on = ((mhd_pair_requested() or self._exchange_every == 2)
                   and 2 * HALO_R <= min(bz, tile))
        if self._exchange_every == 2 and not pair_on:
            from ..utils.logging import LOG_WARN
            LOG_WARN("exchange_every=2 requested but the fused "
                     "substep-0+1 kernel cannot tile this shard; "
                     "falling back to per-substep exchanges")
        if pair_on:
            from ..ops.pallas_halo import mhd_substep01_halo_pallas
            from ..utils.logging import LOG_INFO
            LOG_INFO("astaroth halo path: fused substep-0+1 kernel")

        def extract_shard(fields):
            return {q: lax.slice(
                p, (lo.z, lo.y, lo.x),
                (lo.z + local.z, lo.y + local.y, lo.x + local.x))
                for q, p in fields.items()}

        extract = jax.jit(jax.shard_map(
            extract_shard, mesh=dd.mesh, in_specs=(fields_spec,),
            out_specs=fields_spec, check_vma=False))

        def exchange_all(f, radius_rows):
            return {q: exchange_interior_slabs(
                f[q], counts, rz=bz, ry=tile,
                radius_rows=radius_rows, y_z_extended=True)
                for q in FIELDS}

        def loop_shard(inner, w, n):
            # dead-w elision (see _build_wrap_step): substep 0 reads no
            # w, substep 2 writes none; the carry keeps the last
            # written w for fori_loop structural stability
            def body(_, fw):
                f, wk = fw
                if pair_on:
                    f, wk = mhd_substep01_halo_pallas(
                        f, exchange_all(f, 2 * HALO_R), prm, dt,
                        block_z=bz, block_y=by)
                    f, _ = mhd_substep_halo_pallas(
                        f, wk, exchange_all(f, HALO_R), 2, prm, dt,
                        block_z=bz, block_y=by, write_w=False)
                else:
                    f, wk = mhd_substep_halo_pallas(
                        f, None, exchange_all(f, HALO_R), 0, prm, dt,
                        block_z=bz, block_y=by)
                    f, wk = mhd_substep_halo_pallas(
                        f, wk, exchange_all(f, HALO_R), 1, prm, dt,
                        block_z=bz, block_y=by)
                    f, _ = mhd_substep_halo_pallas(
                        f, wk, exchange_all(f, HALO_R), 2, prm, dt,
                        block_z=bz, block_y=by, write_w=False)
                return f, wk
            return lax.fori_loop(0, n, body, (inner, w))

        loop = jax.jit(jax.shard_map(
            loop_shard, mesh=dd.mesh,
            in_specs=(fields_spec, fields_spec, P()),
            out_specs=(fields_spec, fields_spec), check_vma=False),
            donate_argnums=(0, 1))

        def insert_shard(fields, inner):
            return {q: lax.dynamic_update_slice(
                fields[q], inner[q], (lo.z, lo.y, lo.x))
                for q in fields}

        self._insert = jax.jit(jax.shard_map(
            insert_shard, mesh=dd.mesh, in_specs=(fields_spec, fields_spec),
            out_specs=fields_spec, check_vma=False), donate_argnums=0)
        # exchange accounting for exchange_stats(): per iteration the
        # pair path does one radius-2R + one radius-R extended slab
        # round; the sequential path three radius-R rounds
        self._slab_exchange_cfg = dict(rz=bz, ry=tile, pair=pair_on)
        self._install_inner_iter(extract, loop)

    def _build_halo_overlap_step(self) -> None:
        """Overlapped multi-device fused substeps: per substep, ONE
        Pallas kernel issues the slab RDMA and computes the interior
        behind the in-flight DMAs, then thin strip kernels recompute
        the shard-edge blocks from the landed slabs (the reference's
        per-substep interior/exchange/exterior choreography,
        astaroth/astaroth.cu:552-646; see ops/pallas_mhd_overlap.py).
        Same extract/loop/insert program split and interior-resident
        caching as the halo path."""
        from ..ops.pallas_halo import R as HALO_R, mhd_halo_blocks
        from ..ops.pallas_mhd import mhd_tile
        from ..ops.pallas_mhd_overlap import mhd_substep_overlap

        dd = self.dd
        lo = dd.alloc_radius.pad_lo()
        local = dd.local_size
        counts = mesh_dim(dd.mesh)
        prm = self.prm
        dt = prm.dt
        tile = mhd_tile(self._dtype)   # 8 f32/f64, 16 bf16 slabs
        blk_z, blk_y = getattr(self, "_halo_blocks", None) or (8, 32)
        bz, by = mhd_halo_blocks(local.z, local.y, blk_z, blk_y, tile,
                                 X=local.x,
                                 itemsize=np.dtype(self._dtype).itemsize)
        spec = P("z", "y", "x")
        fields_spec = {q: spec for q in FIELDS}

        def extract_shard(fields):
            return {q: lax.slice(
                p, (lo.z, lo.y, lo.x),
                (lo.z + local.z, lo.y + local.y, lo.x + local.x))
                for q, p in fields.items()}

        extract = jax.jit(jax.shard_map(
            extract_shard, mesh=dd.mesh, in_specs=(fields_spec,),
            out_specs=fields_spec, check_vma=False))

        # STENCIL_MHD_PAIR composes with the overlap path too: one
        # radius-2R overlapped exchange + one fused pass covers RK
        # substeps 0+1, then substep 2 runs overlapped as usual
        from ..utils.config import mhd_pair_requested
        pair_on = ((mhd_pair_requested() or self._exchange_every == 2)
                   and 2 * HALO_R <= min(bz, tile))
        if self._exchange_every == 2 and not pair_on:
            from ..utils.logging import LOG_WARN
            LOG_WARN("exchange_every=2 requested but the fused "
                     "substep-0+1 kernel cannot tile this shard; "
                     "falling back to per-substep exchanges")
        if pair_on:
            from ..utils.logging import LOG_INFO
            LOG_INFO("astaroth halo-overlap path: fused substep-0+1")

        def loop_shard(inner, w, n):
            # dead-w elision (see _build_wrap_step): substep 0 reads no
            # w, substep 2 writes none; the carry keeps the last
            # written w for fori_loop structural stability
            def body(_, fw):
                f, wk = fw
                if pair_on:
                    f, wk = mhd_substep_overlap(f, None, 0, prm, dt,
                                                counts, block_z=bz,
                                                block_y=by, pair=True)
                    f, _ = mhd_substep_overlap(f, wk, 2, prm, dt,
                                               counts, block_z=bz,
                                               block_y=by,
                                               write_w=False)
                else:
                    f, wk = mhd_substep_overlap(f, None, 0, prm, dt,
                                                counts, block_z=bz,
                                                block_y=by)
                    f, wk = mhd_substep_overlap(f, wk, 1, prm, dt,
                                                counts, block_z=bz,
                                                block_y=by)
                    f, _ = mhd_substep_overlap(f, wk, 2, prm, dt,
                                               counts, block_z=bz,
                                               block_y=by,
                                               write_w=False)
                return f, wk
            return lax.fori_loop(0, n, body, (inner, w))

        loop = jax.jit(jax.shard_map(
            loop_shard, mesh=dd.mesh,
            in_specs=(fields_spec, fields_spec, P()),
            out_specs=(fields_spec, fields_spec), check_vma=False),
            donate_argnums=(0, 1))

        def insert_shard(fields, inner):
            return {q: lax.dynamic_update_slice(
                fields[q], inner[q], (lo.z, lo.y, lo.x))
                for q in fields}

        self._insert = jax.jit(jax.shard_map(
            insert_shard, mesh=dd.mesh, in_specs=(fields_spec, fields_spec),
            out_specs=fields_spec, check_vma=False), donate_argnums=0)
        # same wire traffic as the sequential halo path (pair: one
        # radius-2R + one radius-R round; else 3 radius-R rounds per
        # iteration), issued in-kernel
        self._slab_exchange_cfg = dict(rz=bz, ry=tile, pair=pair_on)
        self._install_inner_iter(extract, loop)

    def _install_inner_iter(self, extract, loop) -> None:
        """Shared interior-resident iteration protocol for the wrap and
        halo fast paths: ``self._inner`` caches the interior state
        between calls; ``sync_domain()`` flushes it into ``dd.curr``
        (and runs automatically before any ``dd.set_interior``)."""
        def iteration_n(fields, w, n):
            inner = self._inner
            if inner is None:
                inner = extract(fields)
            inner, w = loop(inner, w, n)
            self._inner = dict(inner)
            return fields, w

        self._iter_n = iteration_n
        self._iter = lambda f, w: iteration_n(f, w, jnp.asarray(1, jnp.int32))
        # the interior-resident fast paths keep their state OUTSIDE
        # dd.curr in a three-program extract/loop/insert split (fusing
        # extract+loop+insert into one program measured an order of
        # magnitude slower — see _build_wrap_step); a megastep over
        # dd.curr would advance stale state, so the path declines
        # loudly and the driver runs its already-fused loop stepwise
        from ..parallel.megastep import DECLINE_INTERIOR_RESIDENT_STATE
        self._set_segment_decline(
            "interior-resident extract/loop/insert split keeps state "
            "outside dd.curr (one fused program measured ~10x slower)",
            code=DECLINE_INTERIOR_RESIDENT_STATE)

    def exchange_stats(self) -> dict:
        """Per-iteration exchange accounting for the BUILT compute path
        (whole-mesh bytes, the ``exchange_bytes_total`` convention) —
        honest numbers for the fused fast paths that never call
        ``dd.exchange()`` (reference per-iteration exchange stats:
        src/stencil.cu:1005-1008,1174-1181; astaroth.cu:668-676)."""
        from ..ops.pallas_halo import R as HALO_R
        from ..parallel.exchange import interior_slab_bytes

        path = self.kernel_path
        if path == "wrap":
            return {"path": path, "bytes_per_iteration": 0,
                    "rounds_per_iteration": 0.0}
        counts = mesh_dim(self.dd.mesh)
        local = self.dd.local_size
        cfg = getattr(self, "_slab_exchange_cfg", None)
        if cfg is not None and path in ("halo", "halo-overlap"):
            shard = (local.z, local.y, local.x)
            item = self._dtype.itemsize
            n = counts.flatten() * len(FIELDS)

            def rnd(r):
                return interior_slab_bytes(shard, counts, r, item,
                                           y_z_extended=True) * n

            if cfg["pair"]:
                return {"path": path,
                        "bytes_per_iteration": rnd(2 * HALO_R) + rnd(HALO_R),
                        "rounds_per_iteration": 2.0}
            return {"path": path, "bytes_per_iteration": 3 * rnd(HALO_R),
                    "rounds_per_iteration": 3.0}
        s = self.dd.exchange_every
        if s > 1:
            # one deep exchange per s substeps; groups starting at an
            # alpha != 0 substep also carry the 8 w accumulators (same
            # dtypes/geometry as the fields -> exactly 2x the bytes)
            import math
            period = math.lcm(3, s)
            starts = [(g * s) % 3 for g in range(period // s)]
            per_ex = float(self.dd.exchange_bytes_total())
            iters = period // 3
            return {"path": path,
                    "bytes_per_iteration": sum(
                        per_ex * (2.0 if RK3_ALPHA[st] != 0.0 else 1.0)
                        for st in starts) / iters,
                    "rounds_per_iteration": len(starts) / iters}
        return {"path": path,
                "bytes_per_iteration": 3.0 * self.dd.exchange_bytes_total(),
                "rounds_per_iteration": 3.0}

    def measure_exchange_seconds(self, reps: int = 5) -> float:
        """Estimated exchange seconds per ITERATION, measured
        standalone per round config (the fused loops exchange inside
        one XLA program where the cost cannot be timed separately) —
        the same per-iteration convention as
        ``Jacobi3D.measure_exchange_seconds``. Returns 0.0 on the wrap
        path."""
        from ..ops.pallas_halo import ESUB, R as HALO_R

        path = self.kernel_path
        if path == "wrap":
            return 0.0
        cfg = getattr(self, "_slab_exchange_cfg", None)
        if cfg is not None and path in ("halo", "halo-overlap"):
            from ..parallel.exchange import measure_slab_exchange_seconds

            def rnd(r):
                return measure_slab_exchange_seconds(
                    self.dd.mesh, self.dd.local_size, self._dtype,
                    rz=cfg["rz"], ry=cfg.get("ry", ESUB),
                    radius_rows=r,
                    y_z_extended=True, nfields=len(FIELDS), reps=reps)

            if cfg["pair"]:
                return rnd(2 * HALO_R) + rnd(HALO_R)
            return 3 * rnd(HALO_R)
        import time

        from ..utils.timers import device_sync
        self.dd.exchange()
        device_sync(self.dd.curr[FIELDS[0]])
        t0 = time.perf_counter()
        for _ in range(reps):
            self.dd.exchange()
        device_sync(self.dd.curr[FIELDS[0]])
        # rounds per iteration: 3 stepwise, 3/s under temporal blocking
        rounds = self.exchange_stats()["rounds_per_iteration"]
        return rounds * (time.perf_counter() - t0) / reps

    def sync_domain(self) -> None:
        """Materialize interior-resident fast-path state back into the
        padded ``dd.curr`` fields (no-op otherwise). Runs automatically
        before ``dd.set_interior`` writes (init, checkpoint restore);
        call it manually before reading/writing ``dd.curr`` directly."""
        if self._inner is not None:
            self.dd.curr = dict(self._insert(self.dd.curr, self._inner))
            self._inner = None

    def _ensure_w(self) -> None:
        if self._w is None:
            from jax.sharding import NamedSharding

            from ..local_domain import raw_size
            sharding = NamedSharding(self.dd.mesh, P("z", "y", "x"))
            dim = self.dd.placement.dim()
            per_shard = (raw_size(self.dd.local_size, self.dd.alloc_radius)
                         if self._w_padded else self.dd.local_size)
            shape = zyx_shape(per_shard * dim)
            # np.zeros + EXPLICIT device_put: _ensure_w runs inside
            # the fused-segment dispatch, which is guarded by
            # jax.transfer_guard("disallow") — jnp.zeros would lift
            # its fill scalar through an implicit transfer
            self._w = {q: jax.device_put(
                np.zeros(shape, dtype=self._dtype), sharding)
                for q in FIELDS}

    def step(self) -> None:
        """One full RK3 iteration (3 substeps, 3 exchanges)."""
        self._ensure_w()
        out_f, out_w = self._iter(self.dd.curr, self._w)
        self.dd.curr = dict(out_f)
        self._w = dict(out_w)

    def run(self, iters: int) -> None:
        self._ensure_w()
        out_f, out_w = self._iter_n(self.dd.curr, self._w,
                                    jnp.asarray(iters, jnp.int32))
        self.dd.curr = dict(out_f)
        self._w = dict(out_w)

    def block(self) -> None:
        from ..utils.timers import device_sync
        inner = self._inner
        device_sync(inner["lnrho"] if inner is not None
                    else self.dd.curr["lnrho"])

    def field(self, name: str) -> np.ndarray:
        inner = self._inner
        if inner is not None:
            # fast paths keep the interior resident: the cached array IS
            # the (sharded) global interior, no halo stripping needed
            return np.asarray(inner[name])
        return self.dd.interior_to_host(name)

    # -- resilient run loop (stencil_tpu/resilience) -------------------
    def run_resilient(self, n_steps: int, policy=None,
                      ckpt_dir: Optional[str] = None, faults=None):
        """``n_steps`` RK3 iterations under the checkpoint-rollback
        recovery driver. The RK accumulators ride the checkpoint as
        ``extra`` arrays; interior-resident fast-path state is flushed
        (``sync_domain``) before every save and invalidated on restore.
        Returns the :class:`~stencil_tpu.resilience.ResilienceReport`."""
        from ..resilience.driver import run_resilient

        size = self.dd.size

        def rebuild(cfg):
            from .jacobi import _dcn_request_kwargs
            new = Astaroth(size.x, size.y, size.z, params=self.prm,
                           mesh_shape=tuple(self.dd.placement.dim()),
                           dtype=self._dtype, devices=self.dd._devices,
                           methods=cfg.method, kernel=self._kernel,
                           overlap=self._overlap,
                           exchange_every=cfg.exchange_every,
                           boundary=self.dd.boundary,
                           **_dcn_request_kwargs(self.dd))
            # adopt in place; re-point the interior-write hook at the
            # surviving handle so sync_domain keeps working
            new.dd._on_interior_write.clear()
            self.__dict__.update(new.__dict__)
            self.dd.on_interior_write(lambda name: self.sync_domain())
            return self.dd, self.step, self.make_segment

        def on_restore(extras):
            # restored state replaces everything the fast paths cached
            self._inner = None
            self._w = dict(extras) if extras else None

        return run_resilient(
            self.dd, self.step, n_steps, policy=policy,
            ckpt_dir=ckpt_dir, faults=faults, rebuild=rebuild,
            extra_fn=lambda: self._w, on_restore=on_restore,
            fields_fn=lambda: (self._inner if self._inner is not None
                               else self.dd.curr),
            pre_checkpoint=self.sync_domain,
            # always passed: paths with no builder return a
            # reason-carrying decline the driver reports
            make_segment=self.make_segment,
            perf_entry="astaroth")


# ----------------------------------------------------------------------
# initial-condition fields (reference: astaroth/astaroth.cu:84-200)
# ----------------------------------------------------------------------
def _hash64(x: np.ndarray) -> np.ndarray:
    """splitmix64-style avalanche (reference: astaroth.cu:84-89)."""
    x = x.astype(np.uint64)
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return x


def _hash_field(shape_zyx) -> np.ndarray:
    """'bad random numbers from -1 to 1' (reference: astaroth.cu:92-114):
    val = hash(x) ^ hash(y) ^ hash(z) scaled to [-1, 1)."""
    nz, ny, nx = shape_zyx
    hz = _hash64(np.arange(nz))[:, None, None]
    hy = _hash64(np.arange(ny))[None, :, None]
    hx = _hash64(np.arange(nx))[None, None, :]
    h = hx ^ hy ^ hz
    val = h.astype(np.float64) / float(np.iinfo(np.uint64).max)
    return (val - 0.5) * 2.0


def _radial_explosion(size: Dim3, prm: MhdParams):
    """Gaussian shell of radially outward velocity
    (reference: astaroth.cu:136-200): amplitude 1, shell radius 0.8,
    width 0.2, origin (0.01, 32 dsy, 50 dsz); components via the unit
    radial vector (algebraically equal to the reference's spherical-
    angle decomposition, without the branch ladder)."""
    ampl, shell_r, width = 1.0, 0.8, 0.2
    ox, oy, oz = 0.01, 32 * prm.dsy, 50 * prm.dsz
    z, y, x = np.meshgrid(np.arange(size.z), np.arange(size.y),
                          np.arange(size.x), indexing="ij")
    xx = x * prm.dsx - ox
    yy = y * prm.dsy - oy
    zz = z * prm.dsz - oz
    rr = np.sqrt(xx * xx + yy * yy + zz * zz)
    u_rad = ampl * np.exp(-((rr - shell_r) ** 2) / (2.0 * width * width))
    with np.errstate(invalid="ignore", divide="ignore"):
        inv_r = np.where(rr > 0, 1.0 / np.where(rr > 0, rr, 1.0), 0.0)
    return (u_rad * xx * inv_r, u_rad * yy * inv_r, u_rad * zz * inv_r)
