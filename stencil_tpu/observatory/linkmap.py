"""Link observatory: per-link traffic attribution, a measured topology
fingerprint, and placement-quality scoring.

The reference library's placement layer is driven by *measured*
topology: an NVML-derived bandwidth/distance matrix feeds a QAP solve
that puts the largest halo messages on the fastest links (reference:
include/stencil/partition.hpp:525-831, qap.hpp, src/gpu_topology.cpp).
The observability stack so far sees only aggregates — one
model-error ratio per dispatch, one bytes-per-step gauge — so the
topology-aware placement work (ROADMAP item 3) had no per-link signal
to optimize against. This module is that signal, in four coupled
pieces:

* **Modeled traffic matrix** — :class:`TrafficMatrix`: per-(src, dst)
  shard wire bytes per exchange round, assembled from the same
  geometry sources the calibrated cost model and the HLO byte
  cross-check share (``parallel.exchange.exchanged_bytes_per_sweep``
  per-axis factors split per direction, the migration ring's static
  record buffers, the all-gather per-shard contribution). The
  ``observatory.linkmap.*`` registry targets prove the matrix total
  equals the HLO-extracted exchange bytes EXACTLY for every registered
  method — slab/packed at every plan depth, the all-gather control,
  particle migration, and the PIC step's accumulate adjoint.
  A matrix that drops corner traffic (the classic 6-neighbor-only
  bug, ``tests/fixtures/lint/bad_linkmap.py``) under-sums and is
  flagged with a nonzero CLI exit.

* **Link classification** — :func:`classify`: every matrix edge maps
  to a link class (``self`` / ``ici-hop<k>`` via the seed
  ``placement.torus_distance_matrix`` / ``dcn`` when the edge crosses
  a slice boundary) and aggregates per mesh axis and per
  face/edge/corner direction class — the TPU twin of the reference's
  NVML matrix rows.

* **Measured topology fingerprint** — :func:`measure_topology`:
  per-axis pingpong sweeps through the existing
  ``tuning.measure.MeshTimer``/``FakeTimer`` protocol
  (``pingpong_axis``), fitted to per-link alpha-beta coefficients and
  persisted as a versioned, fingerprint-keyed JSON artifact (atomic
  tmp+rename publish — the plan-cache discipline). The tuner consumes
  it (``run_autotune(topology=...)``) instead of measuring its two
  global alpha-betas.

* **Placement-quality scoring** — :func:`placement_report`: for every
  registered mesh the modeled traffic matrix and the (synthetic-torus
  or measured) distance matrix feed the seed ``qap.solve_catch``; the
  report gates modeled QAP-placement cost <= trivial placement cost —
  ROADMAP item 3's named gate, landed observability-first so the later
  placement PR only has to flip the deployment default.

Live attribution: :func:`link_attribution_for` derives the per-(axis,
link_class) modeled bytes/step and per-axis fitted peak rates for a
realized ``DistributedDomain``; :class:`~.attribution.PerfAttributor`
exports them as ``stencil_link_bytes_per_step{axis,link_class}`` and
``stencil_link_utilization_ratio{axis,link_class}`` next to the
model-error ratio, and the :class:`~.recorder.FlightRecorder` includes
the linkmap snapshot in incident dumps.

CLI: ``python -m stencil_tpu.observatory linkmap`` renders the matrix
heatmap and the per-link summary; ``--placement-report`` runs the QAP
gate over the registered meshes (nonzero exit on any failure).
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
from pathlib import Path
from typing import (Callable, Dict, List, Optional, Sequence, Tuple,
                    Union)

import numpy as np

from ..geometry import Dim3, Radius

#: modeled wire B/step per mesh axis and link class
METRIC_LINK_BYTES_PER_STEP = "stencil_link_bytes_per_step"
#: achieved-vs-fitted-peak utilization per mesh axis and link class
METRIC_LINK_UTILIZATION = "stencil_link_utilization_ratio"

AXIS_NAMES = ("x", "y", "z")

#: direction classes of the face/edge/corner byte decomposition
DIRECTION_CLASSES = ("face", "edge", "corner")


def _axis_index(axis: Union[int, str]) -> int:
    if isinstance(axis, str):
        return AXIS_NAMES.index(axis)
    return int(axis)


def _linearize(ix: int, iy: int, iz: int, counts: Dim3) -> int:
    """x-fastest shard linear index — the ``RankPartition.linearize``
    convention, so matrix rows align with
    ``Placement.device_order_for_mesh`` slots."""
    return ix + counts.x * (iy + counts.y * iz)


def _shard_index(i: int, counts: Dim3) -> Tuple[int, int, int]:
    return (i % counts.x, (i // counts.x) % counts.y,
            i // (counts.x * counts.y))


class TrafficEdge:
    """One planned wire message: ``src`` shard -> ``dst`` shard along
    ``axis`` toward ``side``, carrying ``nbytes`` split into
    face/edge/corner shares (``class_bytes`` sums to ``nbytes``)."""

    __slots__ = ("src", "dst", "axis", "side", "nbytes", "class_bytes")

    def __init__(self, src: int, dst: int, axis: str, side: int,
                 nbytes: int, class_bytes: Dict[str, int]) -> None:
        self.src = int(src)
        self.dst = int(dst)
        self.axis = str(axis)
        self.side = int(side)
        self.nbytes = int(nbytes)
        self.class_bytes = dict(class_bytes)


class TrafficMatrix:
    """Per-(src, dst) shard wire bytes of one exchange round.

    The edge list keeps axis/side/direction-class structure; the dense
    ``matrix()`` is the QAP's ``w``. All byte counts are exact
    integers — the registry targets pin the per-shard row sum to the
    HLO-extracted bytes with ZERO tolerance."""

    def __init__(self, counts: Dim3,
                 edges: Optional[List[TrafficEdge]] = None) -> None:
        self.counts = Dim3.of(counts)
        self.n = self.counts.flatten()
        self.edges: List[TrafficEdge] = list(edges or [])

    def add(self, edge: TrafficEdge) -> None:
        self.edges.append(edge)

    def merge(self, other: "TrafficMatrix") -> "TrafficMatrix":
        """Combine two rounds over the same shard lattice (e.g. the
        PIC step's accumulate + exchange + migration)."""
        assert self.counts == other.counts, (self.counts, other.counts)
        return TrafficMatrix(self.counts, self.edges + other.edges)

    def matrix(self) -> np.ndarray:
        w = np.zeros((self.n, self.n), dtype=np.int64)
        for e in self.edges:
            w[e.src, e.dst] += e.nbytes
        return w

    def per_shard_bytes(self) -> List[int]:
        """Row sums: wire bytes each shard puts on the fabric per
        round — the per-shard operand convention the HLO byte
        extraction uses."""
        out = [0] * self.n
        for e in self.edges:
            out[e.src] += e.nbytes
        return out

    def uniform_per_shard(self) -> Optional[int]:
        """The common row sum when every shard sends the same bytes
        (the SPMD capacity-shard contract), else None."""
        rows = self.per_shard_bytes()
        return rows[0] if len(set(rows)) == 1 else None

    def total(self) -> int:
        return sum(e.nbytes for e in self.edges)

    def axis_bytes(self) -> Dict[str, int]:
        out = {a: 0 for a in AXIS_NAMES}
        for e in self.edges:
            out[e.axis] = out.get(e.axis, 0) + e.nbytes
        return out

    def direction_class_bytes(self) -> Dict[str, int]:
        """Face/edge/corner byte shares. For the sweep engine the
        edge/corner shares are the pad rows forwarded inside the fat
        axis slabs — a matrix that loses them is the classic
        6-neighbor-only bug."""
        out = {k: 0 for k in DIRECTION_CLASSES}
        for e in self.edges:
            for k, v in e.class_bytes.items():
                out[k] = out.get(k, 0) + v
        return out


def _neighbor(ix: int, iy: int, iz: int, axis: int, side: int,
              counts: Dim3) -> Tuple[int, int, int]:
    idx = [ix, iy, iz]
    idx[axis] = (idx[axis] + side) % counts[axis]
    return idx[0], idx[1], idx[2]


def _cross_section_classes(axis: int, padded_zyx: Sequence[int],
                           lo: Dim3, hi: Dim3,
                           pads_included: bool) -> Dict[str, int]:
    """Decompose an axis message's cross-section (the product of the
    two OTHER padded dims) into interior x interior (face), interior x
    pad (edge, both orders) and pad x pad (corner) element counts —
    exact integers summing to the full product."""
    dims = []  # (interior, pad) per other axis
    for a in range(3):
        if a == axis:
            continue
        full = int(padded_zyx[2 - a])  # zyx storage, axis 0=x
        pad = (lo[a] + hi[a]) if pads_included else 0
        dims.append((full - pad, pad))
    (i1, p1), (i2, p2) = dims
    return {"face": i1 * i2, "edge": i1 * p2 + p1 * i2,
            "corner": p1 * p2}


def sweep_traffic(shard_padded_zyx: Sequence[int], radius: Radius,
                  counts: Dim3, elem_sizes: Sequence[int],
                  pads_included: bool = True,
                  reverse: bool = False,
                  layout: str = "slab",
                  alloc_radius: Optional[Radius] = None
                  ) -> TrafficMatrix:
    """The sequential-sweep engines' traffic matrix (PpermuteSlab /
    PpermutePacked / PallasDMA — packing changes launches, not
    payload): per active axis, one message per direction per quantity,
    rows x full padded cross-section x element size. Summed over
    directions this is exactly ``exchanged_bytes_per_sweep`` — the one
    byte source the runtime counters, the cost model, and the HLO
    cross-check already share. The per-direction split follows the
    ``placement.iter_messages`` convention: the message toward ``+a``
    fills the neighbor's low-side halo (rows = ``radius.face(a, -1)``).

    ``reverse=True`` is the halo-ACCUMULATE adjoint (the PIC deposit's
    reduction): same messages, opposite flow — src/dst swap.
    ``pads_included=False`` prices un-padded slabs (the all-gather
    engine's whole-interior contribution).

    ``layout="irredundant"`` prices the each-cell-once wire layout
    (``parallel.packing``): per other axis the cross-section spans the
    interior plus — for axes the sweep order already visited — the
    ``r_lo + r_hi`` halo extension rows (the only pad rows the
    irredundant box carries), summing per direction to exactly
    ``packing.irredundant_bytes_per_sweep``. The edge/corner shares
    then count just those extension rows. ``alloc_radius`` locates the
    interior inside deeper allocation pads (defaults to ``radius``)."""
    from ..parallel.packing import normalize_wire_layout

    counts = Dim3.of(counts)
    tm = TrafficMatrix(counts)
    lo, hi = radius.pad_lo(), radius.pad_hi()
    irredundant = normalize_wire_layout(layout) == "irredundant"
    if irredundant and not pads_included:
        raise ValueError("layout='irredundant' prices padded sweep "
                         "messages (pads_included=True)")
    ar = alloc_radius if alloc_radius is not None else radius
    alo, ahi = ar.pad_lo(), ar.pad_hi()
    interiors = [int(shard_padded_zyx[2 - a]) - alo[a] - ahi[a]
                 for a in range(3)]
    for a in range(3):
        if counts[a] <= 1:
            continue  # in-core wrap: no wire traffic
        if irredundant:
            # axes swept before `a` carry their halo extension; axes
            # still pending span the bare interior
            dims = [(interiors[j], (lo[j] + hi[j]) if j < a else 0)
                    for j in range(3) if j != a]
            (i1, e1), (i2, e2) = dims
            other = (i1 + e1) * (i2 + e2)
            classes = {"face": i1 * i2, "edge": i1 * e2 + e1 * i2,
                       "corner": e1 * e2}
        else:
            other = 1
            for d in range(3):
                if d != 2 - a:
                    other *= int(shard_padded_zyx[d])
            classes = _cross_section_classes(a, shard_padded_zyx, lo,
                                             hi, pads_included)
        for side in (1, -1):
            rows = radius.face(a, -side)
            if rows == 0:
                continue
            for es in elem_sizes:
                nbytes = rows * other * int(es)
                cb = {k: rows * v * int(es)
                      for k, v in classes.items()}
                for iz in range(counts.z):
                    for iy in range(counts.y):
                        for ix in range(counts.x):
                            src = _linearize(ix, iy, iz, counts)
                            nx, ny, nz = _neighbor(ix, iy, iz, a, side,
                                                   counts)
                            dst = _linearize(nx, ny, nz, counts)
                            if reverse:
                                src, dst = dst, src
                            tm.add(TrafficEdge(src, dst, AXIS_NAMES[a],
                                               side, nbytes, cb))
    return tm


def allgather_traffic(shard_zyx: Sequence[int], radius: Radius,
                      counts: Dim3,
                      elem_sizes: Sequence[int]) -> TrafficMatrix:
    """The all-gather control strategy's matrix under the package's
    one byte convention: each shard's per-axis-direction slab
    contribution counted once (the ring moves ``(n-1)x`` that — a
    ranking concern the cost model prices; the HLO operand extraction
    and therefore this matrix count the contribution), attributed to
    the ring successor in that direction."""
    return sweep_traffic(shard_zyx, radius, counts, elem_sizes,
                         pads_included=False)


def migration_traffic(counts: Dim3, n_fields: int, budget: int,
                      elem_size: int) -> TrafficMatrix:
    """The particle-migration ring's matrix: 2 fixed-size record
    buffers per active axis per shard (``record_rows x budget``), the
    static price of the dynamic exchange — identical to
    ``analysis.costmodel.migration_wire_bytes_per_shard`` per row."""
    from ..parallel.migrate import migration_record_rows

    counts = Dim3.of(counts)
    tm = TrafficMatrix(counts)
    nbytes = (migration_record_rows(int(n_fields)) * int(budget)
              * int(elem_size))
    for a in range(3):
        if counts[a] <= 1:
            continue
        for side in (1, -1):
            for iz in range(counts.z):
                for iy in range(counts.y):
                    for ix in range(counts.x):
                        src = _linearize(ix, iy, iz, counts)
                        nx, ny, nz = _neighbor(ix, iy, iz, a, side,
                                               counts)
                        dst = _linearize(nx, ny, nz, counts)
                        tm.add(TrafficEdge(src, dst, AXIS_NAMES[a],
                                           side, nbytes,
                                           {"face": nbytes}))
    return tm


def method_traffic(method_name: str,
                   shard_interior_zyx: Sequence[int], radius: Radius,
                   counts: Dim3, elem_sizes: Sequence[int],
                   steps=1,
                   wire_layout: str = "slab") -> TrafficMatrix:
    """The per-method matrix of one DEEP exchange round — the linkmap
    twin of ``analysis.costmodel.exchange_round_model``, sharing its
    geometry conventions (deepened radius, deep padded
    cross-sections; ``wire_layout`` prices the irredundant packing on
    the sweep engines, a no-op for the all-gather control).

    ``steps`` accepts the per-axis forms of
    ``geometry.normalize_depths`` (``{"z": 4}``, ``(1, 1, 4)``). For
    non-uniform depths the matrix covers the whole GROUP of
    ``max(steps)`` sub-steps: axis ``a`` re-ships its deep slab every
    ``s_a`` sub-steps (``parallel.temporal.refresh_axes``, with
    cross-sections spanning the full padded extents both times), so
    each axis-``a`` edge's bytes scale by ``s / s_a``; amortize with
    ``rounds_per_step = 1/s`` for per-step bytes."""
    from ..geometry import normalize_depths

    depths = normalize_depths(steps)
    s = max(depths)
    deep = radius.deepened(depths)
    lo, hi = deep.pad_lo(), deep.pad_hi()
    z, y, x = shard_interior_zyx
    padded = (z + lo.z + hi.z, y + lo.y + hi.y, x + lo.x + hi.x)
    if method_name == "AllGather":
        tm = allgather_traffic(shard_interior_zyx, deep, counts,
                               elem_sizes)
    else:
        tm = sweep_traffic(padded, deep, counts, elem_sizes,
                           layout=wire_layout)
    if depths.x == depths.y == depths.z:
        return tm
    out = TrafficMatrix(counts)
    for e in tm.edges:
        mult = s // depths[AXIS_NAMES.index(e.axis)]
        out.add(TrafficEdge(e.src, e.dst, e.axis, e.side,
                            e.nbytes * mult,
                            {k: v * mult
                             for k, v in e.class_bytes.items()}))
    return out


def pic_traffic(shard_interior_zyx: Sequence[int], radius: Radius,
                counts: Dim3, elem_size: int, n_fields: int,
                budget: int) -> TrafficMatrix:
    """The fused PIC step's whole wire bill: the reverse
    halo-accumulate (the deposit sweep's adjoint), the forward
    exchange, and the migration ring — the linkmap twin of the
    ``models.pic.step[cost]`` registry expectation."""
    lo, hi = radius.pad_lo(), radius.pad_hi()
    z, y, x = shard_interior_zyx
    padded = (z + lo.z + hi.z, y + lo.y + hi.y, x + lo.x + hi.x)
    acc = sweep_traffic(padded, radius, counts, (elem_size,),
                        reverse=True)
    fwd = sweep_traffic(padded, radius, counts, (elem_size,))
    mig = migration_traffic(counts, n_fields, budget, elem_size)
    return acc.merge(fwd).merge(mig)


# ---------------------------------------------------------------------------
# link classification: matrix edges -> self / ici-hop<k> / dcn


def _lattice_torus_hops(counts: Dim3) -> np.ndarray:
    """Wrapped-torus hop distance over the shard lattice itself — the
    synthetic fabric model when no physical device coords exist (CPU
    CI, virtual meshes): per axis ``min(|d|, n - |d|)`` (the ring's
    wrap link is one hop), summed. Vectorized — this runs per
    attributor build, and the multi-slice meshes ROADMAP item 3
    targets have thousands of shards."""
    counts = Dim3.of(counts)
    n = counts.flatten()
    idx = np.arange(n)
    coords = np.stack([idx % counts.x,
                       (idx // counts.x) % counts.y,
                       idx // (counts.x * counts.y)], axis=1)
    dist = np.zeros((n, n), dtype=np.float64)
    for a in range(3):
        d = np.abs(coords[:, None, a] - coords[None, :, a])
        dist += np.minimum(d, counts[a] - d)
    return dist


def mesh_distance_matrix(counts: Dim3,
                         devices: Optional[Sequence] = None,
                         dcn_axis: Optional[int] = None,
                         n_slices: int = 1,
                         dcn_hop_penalty: float = 8.0) -> np.ndarray:
    """Device-slot distance matrix for the shard lattice: the seed
    ``torus_distance_matrix`` over real device coords when available,
    else wrapped-torus hops over synthetic lattice coords;
    slice-crossing pairs (the DCN tier) add ``dcn_hop_penalty`` hops —
    the two-tier fabric the reference's gpu_topo bandwidth matrix
    models with 1/bandwidth."""
    from ..placement import torus_distance_matrix

    counts = Dim3.of(counts)
    devs = list(devices or ())
    have_coords = bool(devs) and all(
        getattr(d, "coords", None) is not None
        and len(getattr(d, "coords", ())) >= 3 for d in devs)
    dist = (torus_distance_matrix(devs) if have_coords
            else _lattice_torus_hops(counts))
    if dcn_axis is not None and int(n_slices) > 1:
        slices = np.array([shard_slice(i, counts, dcn_axis, n_slices)
                           for i in range(counts.flatten())])
        dist = dist + float(dcn_hop_penalty) * (slices[:, None]
                                                != slices[None, :])
    return dist


def shard_slice(i: int, counts: Dim3, dcn_axis: int,
                n_slices: int) -> int:
    """Which slice hosts shard ``i``: subdomains block onto slices
    along the DCN axis (the ``multihost_device_order`` contract)."""
    counts = Dim3.of(counts)
    coord = _shard_index(i, counts)[_axis_index(dcn_axis)]
    return coord * int(n_slices) // counts[_axis_index(dcn_axis)]


def link_class_of(src: int, dst: int, dist: np.ndarray,
                  counts: Dim3, dcn_axis: Optional[int] = None,
                  n_slices: int = 1) -> str:
    """The link class of one edge: ``self`` (no wire), ``dcn`` when
    the edge crosses a slice boundary, else ``ici-hop<k>`` from the
    torus hop count."""
    if src == dst:
        return "self"
    if dcn_axis is not None and int(n_slices) > 1:
        if shard_slice(src, counts, dcn_axis, n_slices) \
                != shard_slice(dst, counts, dcn_axis, n_slices):
            return "dcn"
    hops = max(int(round(float(dist[src, dst]))), 1)
    return f"ici-hop{hops}"


@dataclasses.dataclass
class LinkmapSummary:
    """The classified traffic matrix: per-(axis, link_class) bytes per
    exchange round plus the face/edge/corner shares — what the gauges
    export and the flight recorder snapshots."""

    counts: Tuple[int, int, int]
    total_bytes: int
    #: (axis, link_class) -> wire bytes per round, all shards
    link_bytes: Dict[Tuple[str, str], int]
    direction_class_bytes: Dict[str, int]
    rounds_per_step: float = 1.0

    def link_bytes_per_step(self) -> Dict[Tuple[str, str], float]:
        return {k: v * self.rounds_per_step
                for k, v in self.link_bytes.items()}

    def to_record(self) -> Dict:
        total = max(self.total_bytes, 1)
        return {
            "counts": list(self.counts),
            "total_bytes": self.total_bytes,
            "rounds_per_step": self.rounds_per_step,
            "links": {f"{a}/{c}": {"bytes": b,
                                   "share": b / total}
                      for (a, c), b in sorted(self.link_bytes.items())},
            "direction_classes": {
                k: {"bytes": v, "share": v / total}
                for k, v in self.direction_class_bytes.items()},
        }


def classify(tm: TrafficMatrix, devices: Optional[Sequence] = None,
             dcn_axis: Optional[Union[int, str]] = None,
             n_slices: int = 1,
             rounds_per_step: float = 1.0) -> LinkmapSummary:
    """Classify every matrix edge into its link class and aggregate
    per mesh axis — the measured-fabric attribution of the modeled
    traffic."""
    axis = None if dcn_axis is None else _axis_index(dcn_axis)
    dist = mesh_distance_matrix(tm.counts, devices=devices,
                                dcn_axis=axis, n_slices=n_slices)
    link_bytes: Dict[Tuple[str, str], int] = {}
    for e in tm.edges:
        klass = link_class_of(e.src, e.dst, dist, tm.counts,
                              dcn_axis=axis, n_slices=n_slices)
        key = (e.axis, klass)
        link_bytes[key] = link_bytes.get(key, 0) + e.nbytes
    return LinkmapSummary(counts=tuple(tm.counts),
                          total_bytes=tm.total(),
                          link_bytes=link_bytes,
                          direction_class_bytes=
                          tm.direction_class_bytes(),
                          rounds_per_step=float(rounds_per_step))


def link_attribution_for(dd) -> Optional[Dict]:
    """Live-attribution inputs for a realized ``DistributedDomain``:
    ``{"bytes_per_step": {(axis, class): B}, "peak_bytes_per_s":
    {axis: B/s}, "summary": record}`` — per-(axis, link_class) modeled
    wire B/step (the deep round amortized over ``exchange_every``) and
    the per-axis fitted peak (the tuned plan's per-link coefficients
    when present, the DCN split else the assumed ICI default). None on
    an unsharded mesh or an unpriceable geometry; never raises."""
    try:
        from ..analysis.costmodel import DEFAULT_ICI_COEFFS
        from ..parallel.mesh import mesh_dim
        from ..parallel.methods import pick_method

        counts = mesh_dim(dd.mesh)
        if counts.flatten() <= 1 or all(counts[a] <= 1
                                        for a in range(3)):
            return None
        local = dd.local_size
        elem_sizes = tuple(dd._dtypes[q].itemsize for q in dd._names)
        s = max(int(dd.exchange_every), 1)
        # per-axis depths: the group matrix (deep + refreshes) over
        # 1/s rounds-per-step amortizes each axis by its own cadence
        depths = getattr(dd, "exchange_depths", None)
        tm = method_traffic(pick_method(dd.methods).name,
                            (local.z, local.y, local.x), dd.radius,
                            counts, elem_sizes,
                            steps=depths if depths is not None else s,
                            wire_layout=getattr(dd, "wire_layout",
                                                "slab"))
        if not tm.edges:
            return None
        devices = None
        if getattr(dd, "placement", None) is not None:
            devices = dd.placement.device_order_for_mesh()
        summary = classify(tm, devices=devices,
                           dcn_axis=dd.dcn_axis,
                           n_slices=int(getattr(dd, "n_slices", 1)),
                           rounds_per_step=1.0 / s)
        peaks: Dict[str, float] = {}
        coeffs = getattr(getattr(dd, "plan", None), "coefficients",
                         None) or {}
        for a in range(3):
            if counts[a] <= 1:
                continue
            name = AXIS_NAMES[a]
            rec = coeffs.get(name)
            if rec is None and dd.dcn_axis == a and "dcn" in coeffs:
                rec = coeffs["dcn"]
            if rec is None:
                rec = coeffs.get("ici")
            peaks[name] = float(rec["beta_bytes_per_s"]) if rec \
                else DEFAULT_ICI_COEFFS.beta_bytes_per_s
        return {"bytes_per_step": summary.link_bytes_per_step(),
                "peak_bytes_per_s": peaks,
                "summary": summary.to_record()}
    except Exception:  # noqa: BLE001 - no linkmap -> attribution off
        return None


# ---------------------------------------------------------------------------
# the measured topology fingerprint (per-axis pingpong sweeps)

#: bump when a record key changes meaning; the loader keys on this
TOPOLOGY_SCHEMA_VERSION = 1

ENV_TOPOLOGY_CACHE = "STENCIL_TOPOLOGY_CACHE"


def default_topology_path() -> Path:
    env = os.environ.get(ENV_TOPOLOGY_CACHE, "")
    if env:
        return Path(env)
    return Path(os.path.expanduser("~/.cache/stencil_tpu/topology.json"))


def topology_fingerprint_inputs(platform: str, device_count: int,
                                mesh_shape: Sequence[int],
                                n_slices: int = 1) -> Dict:
    """The identity a topology fingerprint is valid for: the FABRIC
    (platform, device count, mesh shape, slice tier) — deliberately
    NOT the problem (grid/radius/dtypes), so every campaign on one
    machine shares one measurement."""
    return {
        "platform": str(platform),
        "device_count": int(device_count),
        "mesh_shape": [int(v) for v in mesh_shape],
        "n_slices": int(n_slices),
    }


def topology_fingerprint(inputs: Dict) -> str:
    from ..tuning.plan import fingerprint

    return fingerprint({"topology": inputs})


def measure_topology(timer, mesh_shape: Sequence[int],
                     inputs: Dict,
                     dcn_axis: Optional[int] = None,
                     sizes: Optional[Sequence[int]] = None,
                     created: Optional[float] = None) -> Dict:
    """One measured topology fingerprint record: per active mesh axis
    a pingpong sweep (``timer.pingpong_axis``) at the calibration
    sizes, least-squares fitted to alpha-beta link coefficients
    (``tuning.fit.fit_alpha_beta``) — plus a ``dcn`` link when the
    mesh has a slice-blocked axis. Raw samples ride the record so a
    refit never needs the hardware again."""
    from ..tuning.fit import DEFAULT_CALIBRATION_BYTES, fit_alpha_beta

    sizes = tuple(sizes or DEFAULT_CALIBRATION_BYTES)
    links: Dict[str, Dict] = {}
    for a, n in enumerate(mesh_shape):
        if int(n) <= 1:
            continue
        name = AXIS_NAMES[a]
        samples = [(int(b), float(timer.pingpong_axis(name, int(b))))
                   for b in sizes]
        fit = fit_alpha_beta(samples)
        links[name] = {"alpha_s": fit.alpha_s,
                       "beta_bytes_per_s": fit.beta_bytes_per_s,
                       "samples": [[b, t] for b, t in samples]}
    if dcn_axis is not None and AXIS_NAMES[int(dcn_axis)] in links:
        links["dcn"] = dict(links[AXIS_NAMES[int(dcn_axis)]])
    return {
        "schema": TOPOLOGY_SCHEMA_VERSION,
        "kind": "topology_fingerprint",
        "fingerprint": topology_fingerprint(inputs),
        "inputs": dict(inputs),
        "created": float(created if created is not None
                         else time.time()),
        "dcn_axis": (AXIS_NAMES[int(dcn_axis)]
                     if dcn_axis is not None else None),
        "links": links,
    }


def validate_topology(rec) -> List[str]:
    problems: List[str] = []
    if not isinstance(rec, dict):
        return ["topology record is not an object"]
    if rec.get("schema") != TOPOLOGY_SCHEMA_VERSION:
        problems.append(f"schema {rec.get('schema')!r} != "
                        f"{TOPOLOGY_SCHEMA_VERSION}")
    if rec.get("kind") != "topology_fingerprint":
        problems.append(f"kind {rec.get('kind')!r} != "
                        f"'topology_fingerprint'")
    if not isinstance(rec.get("fingerprint"), str) \
            or not rec.get("fingerprint"):
        problems.append("missing/invalid 'fingerprint'")
    links = rec.get("links")
    if not isinstance(links, dict) or not links:
        problems.append("missing/empty 'links'")
        return problems
    for name, c in links.items():
        for key in ("alpha_s", "beta_bytes_per_s"):
            v = (c or {}).get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v <= 0:
                problems.append(f"link {name!r}: invalid {key}={v!r}")
    return problems


def save_topology(rec: Dict,
                  path: Union[str, Path, None] = None) -> Path:
    """Publish one fingerprint record into the topology artifact
    (a fingerprint-keyed table, atomic tmp+rename — the plan-cache
    publish discipline, INCLUDING its writer lock: the read-merge-
    write runs under the ``<path>.lock`` flock + per-path mutex from
    ``tuning.cache``, so two processes fingerprinting different
    fabrics cannot drop each other's records; lock-free readers see
    old or new, never half)."""
    from ..tuning.cache import _write_lock

    problems = validate_topology(rec)
    if problems:
        raise ValueError(f"refusing to save invalid topology "
                         f"fingerprint: {problems}")
    p = Path(path) if path is not None else default_topology_path()
    p.parent.mkdir(parents=True, exist_ok=True)
    with _write_lock(p):
        table: Dict[str, Dict] = {}
        if p.exists():
            try:
                data = json.loads(p.read_text())
                if isinstance(data, dict) \
                        and data.get("schema") == TOPOLOGY_SCHEMA_VERSION:
                    table = dict(data.get("topologies") or {})
            except (OSError, ValueError):
                table = {}  # corrupt: rewrite (the cache contract)
        table[rec["fingerprint"]] = rec
        payload = {"schema": TOPOLOGY_SCHEMA_VERSION,
                   "topologies": table}
        fd, tmp = tempfile.mkstemp(dir=str(p.parent), prefix=p.name,
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
            os.replace(tmp, p)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    return p


def load_topology(fingerprint: str,
                  path: Union[str, Path, None] = None
                  ) -> Optional[Dict]:
    """The stored fingerprint record, or None (miss, absent/corrupt
    file, foreign schema, invalid record — never fatal)."""
    p = Path(path) if path is not None else default_topology_path()
    if not p.exists():
        return None
    try:
        data = json.loads(p.read_text())
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) \
            or data.get("schema") != TOPOLOGY_SCHEMA_VERSION:
        return None
    rec = (data.get("topologies") or {}).get(fingerprint)
    if rec is None or validate_topology(rec):
        return None
    return rec


def topology_coefficients(rec: Dict) -> Dict:
    """The record's links as ``LinkCoefficients`` per link name — what
    ``run_autotune(topology=...)`` consumes instead of pingponging its
    two global alpha-betas."""
    from ..analysis.costmodel import LinkCoefficients

    return {name: LinkCoefficients(
                alpha_s=float(c["alpha_s"]),
                beta_bytes_per_s=float(c["beta_bytes_per_s"]))
            for name, c in rec["links"].items()}


# ---------------------------------------------------------------------------
# placement-quality scoring (the ROADMAP item 3 gate)

#: the meshes the placement gate proves QAP <= trivial on — every
#: shard lattice the CI smoke paths deploy plus a two-tier (DCN) case
REGISTERED_MESHES: Tuple[Dict, ...] = (
    {"name": "2x2x2", "counts": (2, 2, 2)},
    {"name": "1x2x4", "counts": (1, 2, 4)},
    {"name": "4x2x1", "counts": (4, 2, 1)},
    {"name": "1x1x8", "counts": (1, 1, 8)},
    {"name": "2x2x2+dcn", "counts": (2, 2, 2), "dcn_axis": 2,
     "n_slices": 2},
)


class _ScoreDevice:
    """Coordless stand-in device for deployed-placement scoring: only
    ``id`` (the ``_torus_sorted`` fallback key), so ``make_placement``
    takes the same synthetic-fabric path a virtual mesh does."""

    __slots__ = ("id",)

    def __init__(self, i: int):
        self.id = int(i)


def placement_quality(counts: Dim3, radius: Radius,
                      elem_sizes: Sequence[int],
                      grid: Optional[Dim3] = None,
                      devices: Optional[Sequence] = None,
                      dcn_axis: Optional[int] = None,
                      n_slices: int = 1,
                      qap_solver: Optional[Callable] = None,
                      mode: str = "auto") -> Dict:
    """Score subdomain->device placements for one mesh: the seed
    ``placement.comm_bytes_matrix`` (the QAP's ``w``) against the
    fabric distance matrix, comparing trivial (identity) placement
    with the seed ``qap.solve_catch`` hill climb — the reference's
    NodeAware objective, scored on the TPU lattice.

    Also scores the assignment the orchestrator actually DEPLOYS:
    ``placement.make_placement`` under ``mode`` (default "auto", the
    deployment default — QAP on non-uniform fabrics, trivial order on
    uniform ones) runs on stub devices and its assignment is priced
    under the same objective (``deployed_cost``); the ``ok`` gate
    requires BOTH the hill-climb score and the deployed assignment to
    cost no more than trivial."""
    from .. import qap
    from ..partition import RankPartition
    from ..placement import (PlacementStrategy, comm_bytes_matrix,
                             make_placement)

    counts = Dim3.of(counts)
    if grid is None:
        grid = counts * Dim3(8, 8, 8)
    part = RankPartition.from_dim(tuple(grid), tuple(counts))
    w = comm_bytes_matrix(part, radius, elem_sizes)
    dist = mesh_distance_matrix(counts, devices=devices,
                                dcn_axis=dcn_axis, n_slices=n_slices)
    n = counts.flatten()
    trivial = qap.cost(w, dist, list(range(n)))
    solver = qap_solver or qap.solve_catch
    assignment, qap_cost = solver(w, dist)
    qap_cost = qap.cost(w, dist, list(assignment))
    # the deployed assignment: the real make_placement path on stub
    # (coordless) devices — exactly what a virtual/fake mesh gets
    stubs = (list(devices) if devices is not None
             else [_ScoreDevice(i) for i in range(n)])
    placed = make_placement(PlacementStrategy.NodeAware, part, stubs,
                            radius, elem_sizes, mode=mode,
                            dcn_axis=dcn_axis, n_slices=n_slices)
    deployed_cost = qap.cost(w, dist, list(placed.assignment))
    return {
        "counts": list(counts),
        "grid": list(grid),
        "subdomains": n,
        "dcn_axis": (AXIS_NAMES[dcn_axis] if dcn_axis is not None
                     else None),
        "n_slices": int(n_slices),
        "traffic_total_bytes": float(w.sum()),
        "trivial_cost": float(trivial),
        "qap_cost": float(qap_cost),
        "qap_over_trivial": (float(qap_cost) / float(trivial)
                             if trivial else 1.0),
        "assignment": [int(a) for a in assignment],
        "placement_mode": str(mode),
        "deployed_assignment": [int(a) for a in placed.assignment],
        "deployed_cost": float(deployed_cost),
        "ok": bool(qap_cost <= trivial * (1 + 1e-12)
                   and deployed_cost <= trivial * (1 + 1e-12)),
    }


def placement_report(meshes: Sequence[Dict] = REGISTERED_MESHES,
                     radius: Optional[Radius] = None,
                     elem_sizes: Sequence[int] = (4,)) -> Dict:
    """The placement-quality report over every registered mesh: the
    acceptance gate is ``ok`` on every row — BOTH the modeled
    QAP-placement cost and the cost of the assignment ``auto`` mode
    actually deploys must be <= trivial placement, so the default
    placement can only match or beat today's device order."""
    r = radius if radius is not None else Radius.constant(1)
    rows = []
    for spec in meshes:
        row = placement_quality(
            Dim3.of(tuple(spec["counts"])), r, elem_sizes,
            grid=(Dim3.of(tuple(spec["grid"]))
                  if spec.get("grid") else None),
            dcn_axis=spec.get("dcn_axis"),
            n_slices=int(spec.get("n_slices", 1)))
        row["name"] = spec.get("name", "x".join(
            str(c) for c in spec["counts"]))
        rows.append(row)
    return {
        "schema": 1,
        "kind": "placement_report",
        "radius": [[d.x, d.y, d.z, r.dir(d)]
                   for d in _radius_dirs(r)],
        "meshes": rows,
        "ok": all(row["ok"] for row in rows),
    }


def _radius_dirs(r: Radius):
    from ..geometry import all_directions

    return [d for d in all_directions() if r.dir(d)]


def render_heatmap(tm: TrafficMatrix, width: int = 2) -> str:
    """ASCII heatmap of the traffic matrix (rows = senders): shard
    pair intensity in eighth-block shades, the terminal twin of the
    reference's plan-file message table."""
    w = tm.matrix()
    peak = float(w.max()) or 1.0
    shades = " .:-=+*#%@"
    lines = [f"traffic matrix ({tm.n} shards, {tm.total()} B/round; "
             f"rows send, cols receive)"]
    for i in range(tm.n):
        cells = []
        for j in range(tm.n):
            level = int(round((len(shades) - 1)
                              * float(w[i, j]) / peak))
            cells.append(shades[level] * width)
        lines.append(f"  {i:>3} |{''.join(cells)}|")
    return "\n".join(lines)


def render_summary(summary: LinkmapSummary) -> str:
    rec = summary.to_record()
    lines = [f"link classes ({rec['total_bytes']} B/round, "
             f"{rec['rounds_per_step']:.3g} rounds/step):"]
    for key, row in rec["links"].items():
        lines.append(f"  {key:<14} {row['bytes']:>12} B  "
                     f"({100 * row['share']:5.1f}%)")
    lines.append("direction classes:")
    for key, row in rec["direction_classes"].items():
        lines.append(f"  {key:<14} {row['bytes']:>12} B  "
                     f"({100 * row['share']:5.1f}%)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the linkmap checker: modeled matrix vs HLO-extracted bytes, exactly


@dataclasses.dataclass
class LinkmapSpec:
    """A jittable exchange program plus its modeled traffic matrix.

    The checker proves (a) structural sanity — square, zero-diagonal,
    non-negative, uniform per-shard rows (the SPMD capacity contract)
    — and (b) the acceptance identity: the per-shard row sum equals
    the HLO-extracted wire bytes EXACTLY (zero tolerance — a matrix
    that drops corner traffic under-sums and fails).

    ``placement`` optionally ships the subdomain->device assignment the
    target deploys: a dict with ``counts`` (mesh shape), ``assignment``
    (the permutation), optional ``grid``/``radius``/``elem_sizes`` (the
    QAP's ``w`` inputs) and ``dcn_axis``/``n_slices`` (the fabric).
    The checker re-prices the claimed assignment under the NodeAware
    objective and flags any placement shipped as "optimized" that
    costs MORE than trivial device order."""

    fn: Callable
    args: Sequence
    traffic: TrafficMatrix
    count_kinds: Tuple[str, ...] = ("collective_permute", "all_gather")
    placement: Optional[Dict] = None


@dataclasses.dataclass
class LinkmapTarget:
    name: str
    build: Callable[[], LinkmapSpec]

    checker = "linkmap"


def check_linkmap(target: LinkmapTarget):
    """Checker 11: the modeled per-link traffic matrix sums exactly to
    what the lowered program moves."""
    from ..analysis.hlo import (_PALLAS_SKIP_NOTE, collect_collectives,
                                lowering_supported, pallas_unlowerable,
                                summarize)
    from ..analysis.report import Finding

    try:
        spec = target.build()
    except Exception as e:  # noqa: BLE001
        return [Finding("linkmap", target.name,
                        f"target build failed: "
                        f"{type(e).__name__}: {e}")], {}

    tm = spec.traffic
    metrics: Dict = {
        "shards": tm.n,
        "matrix_total_bytes": tm.total(),
        "axis_bytes": tm.axis_bytes(),
        "direction_class_bytes": tm.direction_class_bytes(),
    }
    findings: List[Finding] = []

    w = tm.matrix()
    if np.any(np.diag(w) != 0):
        findings.append(Finding(
            "linkmap", target.name,
            "traffic matrix has nonzero diagonal — a shard cannot put "
            "bytes on the wire to itself (same-device wraps are local "
            "copies)"))
    if np.any(w < 0):
        findings.append(Finding(
            "linkmap", target.name,
            "traffic matrix has negative entries"))
    per_shard = tm.uniform_per_shard()
    if per_shard is None:
        rows = tm.per_shard_bytes()
        findings.append(Finding(
            "linkmap", target.name,
            f"per-shard row sums are not uniform ({sorted(set(rows))}) "
            f"— SPMD capacity shards all move the same bytes; a "
            f"lopsided matrix mis-models the wire"))
        return findings, metrics
    metrics["matrix_bytes_per_shard"] = per_shard

    if spec.placement is not None:
        findings += _check_placement_payload(target.name,
                                             spec.placement, metrics)

    if not lowering_supported():
        metrics["skipped"] = ("HLO cross-check skipped: StableHLO "
                              "lowering unavailable in this "
                              "JAX/backend")
        return findings, metrics
    if pallas_unlowerable(spec.fn, spec.args):
        metrics["skipped"] = (f"HLO cross-check skipped: "
                              f"{_PALLAS_SKIP_NOTE}")
        return findings, metrics
    try:
        ops = collect_collectives(spec.fn, spec.args)
    except Exception as e:  # noqa: BLE001
        findings.append(Finding(
            "linkmap", target.name,
            f"lowering failed: {type(e).__name__}: {e}"))
        return findings, metrics

    observed = sum(op.bytes_per_shard for op in ops
                   if op.kind in spec.count_kinds)
    metrics["collectives"] = summarize(ops)
    metrics["observed_bytes_per_shard"] = observed
    if observed != per_shard:
        missing = observed - per_shard
        hint = ""
        if missing > 0 and tm.direction_class_bytes()["corner"] == 0:
            hint = (" — the matrix carries zero corner bytes: the "
                    "classic 6-neighbor-only traffic model that "
                    "drops the edge/corner rows riding the fat axis "
                    "slabs")
        findings.append(Finding(
            "linkmap", target.name,
            f"modeled traffic matrix moves {per_shard} B/shard but "
            f"the lowered HLO moves {observed} B/shard "
            f"({missing:+d} B unattributed){hint}"))
    return findings, metrics


def _check_placement_payload(name: str, payload: Dict,
                             metrics: Dict) -> List:
    """Re-price a target's claimed subdomain->device assignment under
    the NodeAware QAP objective: a placement shipped as "optimized"
    must be a permutation and must cost no more than trivial device
    order on its own fabric — the same gate ``placement_report`` holds
    every registered mesh to."""
    from .. import qap
    from ..analysis.report import Finding
    from ..geometry import Radius
    from ..partition import RankPartition
    from ..placement import comm_bytes_matrix

    findings: List = []
    counts = Dim3.of(tuple(payload["counts"]))
    n = counts.flatten()
    grid = (Dim3.of(tuple(payload["grid"])) if payload.get("grid")
            else counts * Dim3(8, 8, 8))
    radius = payload.get("radius") or Radius.constant(1)
    elem_sizes = tuple(payload.get("elem_sizes", (4,)))
    part = RankPartition.from_dim(tuple(grid), tuple(counts))
    w = comm_bytes_matrix(part, radius, elem_sizes)
    dist = mesh_distance_matrix(counts,
                                dcn_axis=payload.get("dcn_axis"),
                                n_slices=int(payload.get("n_slices",
                                                         1)))
    asn = [int(a) for a in payload["assignment"]]
    if sorted(asn) != list(range(n)):
        findings.append(Finding(
            "linkmap", name,
            f"claimed placement {asn} is not a permutation of "
            f"{n} subdomains"))
        return findings
    trivial = qap.cost(w, dist, list(range(n)))
    claimed = qap.cost(w, dist, asn)
    metrics["placement_trivial_cost"] = float(trivial)
    metrics["placement_claimed_cost"] = float(claimed)
    if claimed > trivial * (1 + 1e-12):
        findings.append(Finding(
            "linkmap", name,
            f"claimed 'optimized' placement costs {claimed:.0f} under "
            f"the NodeAware objective but trivial device order costs "
            f"{trivial:.0f} — a placement shipped as tuned must never "
            f"lose to the identity assignment"))
    return findings
