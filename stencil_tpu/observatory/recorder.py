"""The flight recorder: a bounded black box for post-mortem capture.

When a campaign trips, degrades, is preempted, or dies on an unhandled
dispatch error, the question is always the same: *what was happening
right before?* The resilience event log answers it only if someone
wired a sink, the spans only if someone exported a trace, the metrics
only if someone was scraping. :class:`FlightRecorder` holds the recent
past of all four — events (a bounded
:class:`~stencil_tpu.telemetry.RingSink`, so a year-long run holds
flat memory), the span tail, a metrics snapshot, and the health/probe
history — and dumps them ATOMICALLY (tmp + rename, one file per
incident) when the driver or the service hits a trigger:

* health-sentinel trip (after the rollback, so the dump shows both),
* configuration degradation,
* SIGTERM preemption (BEFORE the preemption checkpoint — if the save
  itself dies, the black box already exists),
* unhandled dispatch error.

``python -m stencil_tpu.observatory replay <dump>`` renders the merged
incident timeline; ``validate`` gates the dump schema (the CI chaos
stage archives and validates its dump). Triggers arm via
``ResiliencePolicy.flight_recorder_dir`` /
``CampaignService(flight_recorder_dir=...)`` or the
``STENCIL_FLIGHT_RECORDER_DIR`` environment variable.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from pathlib import Path
from typing import Dict, List, Optional, Union

#: bump when a dump key changes meaning; the validator keys on this
FLIGHT_SCHEMA_VERSION = 1

#: arms the recorder in the driver/service when no explicit dir is set
ENV_FLIGHT_DIR = "STENCIL_FLIGHT_RECORDER_DIR"


def _jsonable(v):
    return v if isinstance(v, (str, int, float, bool)) or v is None \
        else str(v)


class FlightRecorder:
    """Bounded in-memory black box with atomic incident dumps.

    Speaks the telemetry sink protocol (``emit``/``close``), so it
    plugs straight into an :class:`~stencil_tpu.telemetry.EventLog`
    via ``add_sink`` — every versioned event record the run emits also
    lands in the recorder's ring. ``record_probe`` keeps the recent
    health/probe verdicts (:meth:`HealthStats.to_record` dicts, wall
    time stamped on arrival); ``registry``/``tracer`` are snapshotted
    lazily at dump time, never polled."""

    def __init__(self, run_id: Optional[str] = None,
                 events_capacity: int = 1024,
                 probes_capacity: int = 256, spans_tail: int = 256,
                 registry=None, tracer=None,
                 clock=time.time) -> None:
        from ..telemetry import RingSink, new_run_id
        self.run_id = run_id or new_run_id()
        self._ring = RingSink(events_capacity)
        self._probes: deque = deque(maxlen=int(probes_capacity))
        self._spans_tail = int(spans_tail)
        self._registry = registry
        self._tracer = tracer
        self._clock = clock
        self._lock = threading.Lock()
        self._n_dumps = 0
        self._linkmap: Optional[Dict] = None

    # -- the telemetry sink protocol ------------------------------------
    def emit(self, record: Dict) -> None:
        self._ring.emit(record)

    def close(self) -> None:
        pass

    # -- history feeds --------------------------------------------------
    def record_probe(self, record: Dict) -> None:
        rec = dict(record)
        rec.setdefault("recorded", float(self._clock()))
        with self._lock:
            self._probes.append(rec)

    def set_linkmap(self, summary: Optional[Dict]) -> None:
        """Attach the link observatory's classified traffic snapshot
        (``linkmap.LinkmapSummary.to_record()``): incident dumps then
        show per-(axis, link_class) modeled wire shares next to the
        events — which fabric tier the dying campaign was leaning
        on."""
        with self._lock:
            self._linkmap = dict(summary) if summary else None

    # -- capture --------------------------------------------------------
    def snapshot(self, reason: str, **attrs) -> Dict:
        """The black-box payload: everything the recorder holds, as of
        now."""
        spans: List[Dict] = []
        if self._tracer is not None:
            epoch = float(getattr(self._tracer, "epoch_unix", 0.0))
            for sp in self._tracer.finished()[-self._spans_tail:]:
                spans.append({
                    "name": sp.name, "span_id": sp.span_id,
                    "parent_id": sp.parent_id,
                    "start": epoch + sp.start_s,
                    "end": (epoch + sp.end_s
                            if sp.end_s is not None else None),
                    "attrs": {k: _jsonable(v)
                              for k, v in sp.attrs.items()},
                })
        with self._lock:
            probes = [dict(p) for p in self._probes]
            linkmap = dict(self._linkmap) if self._linkmap else None
        return {
            "schema": FLIGHT_SCHEMA_VERSION,
            "kind": "flight_recorder",
            "run": self.run_id,
            "time": float(self._clock()),
            "reason": str(reason),
            "attrs": {k: _jsonable(v) for k, v in attrs.items()},
            "events": self._ring.records(),
            "dropped_events": self._ring.dropped,
            "probes": probes,
            "spans": spans,
            "metrics": (self._registry.snapshot()
                        if self._registry is not None else None),
            "linkmap": linkmap,
        }

    def dump(self, directory: Union[str, Path], reason: str,
             **attrs) -> str:
        """Atomically write one incident dump into ``directory``
        (created if needed); returns the dump path. The tmp + rename
        publish means a reader never sees a torn black box — the same
        contract as checkpoint meta and the plan cache."""
        payload = self.snapshot(reason, **attrs)
        d = Path(directory)
        d.mkdir(parents=True, exist_ok=True)
        with self._lock:
            n = self._n_dumps
            self._n_dumps += 1
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in str(reason))[:48]
        path = d / f"flight_{self.run_id}_{n:03d}_{safe}.json"
        fd, tmp = tempfile.mkstemp(dir=str(d), prefix=path.name,
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=1)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return str(path)


def safe_dump(recorder: Optional[FlightRecorder],
              directory: Optional[Union[str, Path]], reason: str,
              **attrs) -> Optional[str]:
    """Best-effort incident dump, shared by the driver and the
    service: a disarmed recorder is a no-op, and a FAILING dump warns
    and returns None — the black box must never mask the incident it
    records. Returns the dump path on success."""
    if recorder is None or not directory:
        return None
    from ..utils.logging import LOG_WARN
    try:
        path = recorder.dump(directory, reason, **attrs)
        LOG_WARN(f"flight recorder: {reason} black box -> {path}")
        return path
    except Exception as e:  # noqa: BLE001
        LOG_WARN(f"flight recorder dump failed: "
                 f"{type(e).__name__}: {e}")
        return None


def validate_dump(payload) -> List[str]:
    """Schema-check a flight-recorder dump (the CI gate). Accepts the
    payload dict or a path. Returns human-readable problems (empty =
    valid)."""
    problems: List[str] = []
    if isinstance(payload, (str, os.PathLike)):
        try:
            with open(payload, encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            return [f"cannot load dump: {type(e).__name__}: {e}"]
    if not isinstance(payload, dict):
        return ["top level must be a JSON object"]
    if payload.get("schema") != FLIGHT_SCHEMA_VERSION:
        problems.append(f"schema {payload.get('schema')!r} != "
                        f"{FLIGHT_SCHEMA_VERSION}")
    if payload.get("kind") != "flight_recorder":
        problems.append(f"kind {payload.get('kind')!r} != "
                        f"'flight_recorder'")
    for key, typ in (("run", str), ("reason", str)):
        if not isinstance(payload.get(key), typ) or not payload.get(key):
            problems.append(f"missing/invalid {key!r}")
    if not isinstance(payload.get("time"), (int, float)) \
            or isinstance(payload.get("time"), bool):
        problems.append("missing/invalid 'time'")
    for key in ("events", "probes", "spans"):
        if not isinstance(payload.get(key), list):
            problems.append(f"missing/invalid {key!r} (must be a list)")
    # the embedded events speak the unified telemetry schema
    if isinstance(payload.get("events"), list):
        from ..telemetry import validate_events
        problems.extend(f"events: {p}"
                        for p in validate_events(payload["events"]))
    for i, sp in enumerate(payload.get("spans") or []):
        if not isinstance(sp, dict) or not isinstance(sp.get("name"),
                                                      str):
            problems.append(f"span {i}: missing name")
        elif not isinstance(sp.get("start"), (int, float)):
            problems.append(f"span {i}: missing/invalid start")
    metrics = payload.get("metrics")
    if metrics is not None and (not isinstance(metrics, dict)
                                or "metrics" not in metrics):
        problems.append("'metrics' present but not a metrics snapshot")
    linkmap = payload.get("linkmap")
    if linkmap is not None and (not isinstance(linkmap, dict)
                                or "links" not in linkmap):
        problems.append("'linkmap' present but not a linkmap summary")
    return problems


def render_timeline(payload) -> str:
    """The merged incident timeline (``observatory replay``): events,
    probe verdicts, and span boundaries interleaved by wall time,
    offset-relative to the first entry so the story reads in seconds,
    newest history last. Accepts the payload dict or a path."""
    if isinstance(payload, (str, os.PathLike)):
        with open(payload, encoding="utf-8") as f:
            payload = json.load(f)

    def fmt_attrs(d: Dict, skip=()) -> str:
        parts = []
        for k in sorted(d):
            if k in skip:
                continue
            v = d[k]
            if isinstance(v, float):
                v = f"{v:.6g}"
            parts.append(f"{k}={v}")
        return " ".join(parts)

    rows: List = []  # (time, kind, text)
    for ev in payload.get("events") or []:
        t = ev.get("time")
        if not isinstance(t, (int, float)):
            continue
        extra = fmt_attrs({k: v for k, v in ev.items()
                           if k not in ("event", "time", "run", "seq",
                                        "schema", "span")})
        rows.append((float(t), "event",
                     f"{ev.get('event')}" + (f"  {extra}" if extra
                                             else "")))
    for pr in payload.get("probes") or []:
        t = pr.get("recorded")
        if not isinstance(t, (int, float)):
            continue
        verdict = "TRIPPED" if pr.get("tripped") else "ok"
        detail = f"step={pr.get('step')} {verdict}"
        if pr.get("reason"):
            detail += f" reason={pr.get('reason')}"
        rows.append((float(t), "probe", detail))
    for sp in payload.get("spans") or []:
        t = sp.get("start")
        if not isinstance(t, (int, float)):
            continue
        end = sp.get("end")
        dur = (f" [{1e3 * (end - t):.3f}ms]"
               if isinstance(end, (int, float)) else "")
        extra = fmt_attrs(sp.get("attrs") or {})
        rows.append((float(t), "span",
                     f"{sp.get('name')}{dur}"
                     + (f"  {extra}" if extra else "")))
    rows.sort(key=lambda r: r[0])
    t0 = rows[0][0] if rows else float(payload.get("time") or 0.0)
    lines = [
        f"flight recorder {payload.get('run')} — "
        f"reason={payload.get('reason')} "
        f"dumped={time.strftime('%Y-%m-%dT%H:%M:%S', time.gmtime(float(payload.get('time') or 0.0)))}Z "
        f"({len(payload.get('events') or [])} events, "
        f"{len(payload.get('probes') or [])} probes, "
        f"{len(payload.get('spans') or [])} spans"
        + (f", {payload['dropped_events']} events aged out"
           if payload.get("dropped_events") else "") + ")",
    ]
    attrs = payload.get("attrs") or {}
    if attrs:
        lines.append("  trigger: " + fmt_attrs(attrs))
    for t, kind, text in rows:
        lines.append(f"  {t - t0:+10.3f}s  {kind:<5}  {text}")
    return "\n".join(lines) + "\n"
