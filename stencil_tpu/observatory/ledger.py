"""The bench trajectory ledger: one versioned record schema, appended.

Before this module the repo's bench trajectory was five ad-hoc
``BENCH_*.json`` shapes with no shared schema, no history, and no
regression gate. Every record now carries one shape:

``{"schema": 1, "bench": <id>, "fingerprint": <hex>, "created": <unix
s>, "provenance": "measured"|"legacy", "source": <who wrote it>,
"config": {...}, "metrics": {"steps_per_s": <float>, ...}}``

* ``bench`` + ``fingerprint`` key the trajectory: records sharing both
  measured the SAME problem (the fingerprint is the tuning
  fingerprint when the bench carries one — ``Plan.fingerprint`` — or
  :func:`config_fingerprint`, a stable hash of the bench id + config,
  otherwise), so steps/s is comparable across records within a group
  and meaningless across groups.
* ``metrics["steps_per_s"]`` is the mandatory headline every record
  must carry (> 0); benches add their own extra metrics beside it
  (``particle_steps_per_s``, ``fused_over_stepwise``, ...).
* ``provenance`` separates live measurements from backfilled legacy
  snapshots: :func:`gate_regressions` gates ``measured`` records only
  by default — legacy history is trajectory context (different
  sessions, machines, thermal states), not a same-conditions gate.

The file format is append-only JSONL (one record per line, flushed per
append — a crashed bench keeps everything it recorded), the same
crash-durability contract as :class:`~stencil_tpu.telemetry.JsonlSink`.
``python -m stencil_tpu.observatory`` is the CLI over this module:
``validate`` / ``backfill`` / ``diff`` / ``gate``.
"""

from __future__ import annotations

import json
import math
import os
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple, Union

#: bump when a record key changes meaning; the validator keys on this
LEDGER_SCHEMA_VERSION = 1

PROVENANCES = ("measured", "legacy")

#: the mandatory headline metric every record carries
HEADLINE_METRIC = "steps_per_s"


def config_fingerprint(bench: str, config: Dict) -> str:
    """Stable hash of the bench id + its configuration — the record
    key when no tuning fingerprint exists. Reuses the tuner's
    sorted-key-JSON hash (:func:`stencil_tpu.tuning.plan.fingerprint`)
    so one hashing convention serves both key spaces."""
    from ..tuning.plan import fingerprint
    return fingerprint({"bench": str(bench), "config": config})


def make_record(bench: str, config: Dict, metrics: Dict,
                provenance: str = "measured",
                fingerprint: Optional[str] = None,
                source: Optional[str] = None,
                created: Optional[float] = None) -> Dict:
    """A schema-v1 ledger record (validated — raises ``ValueError`` on
    a malformed one so bad records die at the producer, not in some
    later consumer's gate)."""
    rec = {
        "schema": LEDGER_SCHEMA_VERSION,
        "bench": str(bench),
        "fingerprint": (str(fingerprint) if fingerprint
                        else config_fingerprint(bench, config)),
        "created": float(created if created is not None else time.time()),
        "provenance": str(provenance),
        "source": str(source or ""),
        "config": dict(config),
        "metrics": dict(metrics),
    }
    problems = validate_record(rec)
    if problems:
        raise ValueError(f"invalid ledger record for bench {bench!r}: "
                         f"{problems}")
    return rec


def validate_record(rec) -> List[str]:
    """Schema-check one record; returns human-readable problems
    (empty = valid)."""
    problems: List[str] = []
    if not isinstance(rec, dict):
        return ["record is not an object"]
    if rec.get("schema") != LEDGER_SCHEMA_VERSION:
        problems.append(f"schema {rec.get('schema')!r} != "
                        f"{LEDGER_SCHEMA_VERSION}")
    for key in ("bench", "fingerprint"):
        v = rec.get(key)
        if not isinstance(v, str) or not v:
            problems.append(f"missing/invalid {key!r}")
    if not isinstance(rec.get("created"), (int, float)) \
            or isinstance(rec.get("created"), bool):
        problems.append("missing/invalid 'created'")
    if rec.get("provenance") not in PROVENANCES:
        problems.append(f"provenance {rec.get('provenance')!r} not in "
                        f"{PROVENANCES}")
    if not isinstance(rec.get("config"), dict):
        problems.append("missing/invalid 'config'")
    metrics = rec.get("metrics")
    if not isinstance(metrics, dict):
        problems.append("missing/invalid 'metrics'")
    else:
        sps = metrics.get(HEADLINE_METRIC)
        if not isinstance(sps, (int, float)) or isinstance(sps, bool) \
                or not math.isfinite(float(sps)) or float(sps) <= 0:
            problems.append(
                f"metrics[{HEADLINE_METRIC!r}] must be a positive "
                f"finite number, got {sps!r}")
    return problems


def validate_ledger(records: Sequence[Dict]) -> List[str]:
    """Validate a whole ledger; problems are prefixed with the record
    index."""
    problems: List[str] = []
    for i, rec in enumerate(records):
        problems.extend(f"record {i}: {p}" for p in validate_record(rec))
    return problems


def append_record(path: Union[str, Path], rec: Dict) -> Path:
    """Append one validated record to the JSONL ledger (flushed — the
    crash-durability contract), creating the file and parents."""
    problems = validate_record(rec)
    if problems:
        raise ValueError(f"refusing to append invalid record: {problems}")
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with open(p, "a", encoding="utf-8") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")
        f.flush()
    return p


def read_ledger(path: Union[str, Path]) -> List[Dict]:
    """Every record of a JSONL ledger, in append order. Raises on a
    line that does not parse — a torn ledger must be noticed, not
    silently shortened."""
    out: List[Dict] = []
    with open(path, encoding="utf-8") as f:
        for n, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError as e:
                raise ValueError(f"{path}:{n}: unparseable ledger line "
                                 f"({e})") from e
    return out


def group_records(records: Sequence[Dict]
                  ) -> Dict[Tuple[str, str], List[Dict]]:
    """Records grouped by (fingerprint, bench) in append order — the
    comparable trajectories."""
    groups: Dict[Tuple[str, str], List[Dict]] = {}
    for rec in records:
        key = (str(rec.get("fingerprint")), str(rec.get("bench")))
        groups.setdefault(key, []).append(rec)
    return groups


def diff_records(a: Dict, b: Dict) -> Dict:
    """Metric-by-metric comparison of two records (``b`` relative to
    ``a``): every numeric metric appearing in either, with the ratio
    where computable. ``comparable`` is False when the records key
    different trajectories (fingerprint or bench differ) — the numbers
    still print, the caller decides what they mean."""
    am, bm = dict(a.get("metrics") or {}), dict(b.get("metrics") or {})
    out: Dict = {
        "bench": (a.get("bench"), b.get("bench")),
        "fingerprint": (a.get("fingerprint"), b.get("fingerprint")),
        "provenance": (a.get("provenance"), b.get("provenance")),
        "comparable": (a.get("bench") == b.get("bench")
                       and a.get("fingerprint") == b.get("fingerprint")),
        "metrics": {},
    }
    for key in sorted(set(am) | set(bm)):
        va, vb = am.get(key), bm.get(key)
        row = {"a": va, "b": vb}
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)) \
                and not isinstance(va, bool) and not isinstance(vb, bool) \
                and va:
            row["ratio"] = float(vb) / float(va)
        out["metrics"][key] = row
    return out


def gate_regressions(records: Sequence[Dict], threshold: float = 0.2,
                     provenances: Sequence[str] = ("measured",),
                     bench: Optional[str] = None) -> List[str]:
    """The regression gate: within every (fingerprint, bench) group,
    the NEWEST record's ``steps_per_s`` may not drop more than
    ``threshold`` (relative) below the best earlier record of the same
    group. Returns human-readable failures (empty = gate passes;
    nonzero CLI exit otherwise).

    Only ``provenances`` records participate (default: ``measured``
    only — backfilled legacy snapshots come from different sessions
    and machines, so they seed the trajectory but do not gate it);
    ``bench`` restricts the gate to matching bench ids — a glob with
    literal-bracket tolerance (``utils.naming.glob_match``), so
    ``--bench 'bench_exchange*'`` and ids carrying ``[...]`` both
    work."""
    from ..utils.naming import glob_match

    failures: List[str] = []
    eligible = [r for r in records
                if r.get("provenance") in tuple(provenances)
                and (bench is None
                     or glob_match(str(r.get("bench")), bench))]
    for (fp, b), group in group_records(eligible).items():
        if len(group) < 2:
            continue
        newest = group[-1]
        new_sps = float(newest["metrics"][HEADLINE_METRIC])
        best_prev = max(float(r["metrics"][HEADLINE_METRIC])
                        for r in group[:-1])
        if best_prev <= 0:
            continue
        drop = 1.0 - new_sps / best_prev
        if drop > float(threshold):
            failures.append(
                f"{b} [{fp[:12]}...]: steps/s regressed "
                f"{100 * drop:.1f}% (newest {new_sps:.3f} vs best "
                f"earlier {best_prev:.3f}; threshold "
                f"{100 * float(threshold):.0f}%, {len(group)} records)")
    return failures


def gate_groups_checked(records: Sequence[Dict],
                        provenances: Sequence[str] = ("measured",),
                        bench: Optional[str] = None) -> int:
    """How many (fingerprint, bench) groups the gate actually
    COMPARED (>= 2 eligible records). The gate's coverage figure: a
    healthy gate and a vacuous one both exit 0, but only this number
    tells them apart — the CLI stamps it into the ``--json`` artifact
    and ``--min-groups`` ratchets it. ``bench`` matches like
    :func:`gate_regressions` — glob with literal-bracket tolerance."""
    from ..utils.naming import glob_match

    eligible = [r for r in records
                if r.get("provenance") in tuple(provenances)
                and (bench is None
                     or glob_match(str(r.get("bench")), bench))]
    return sum(1 for g in group_records(eligible).values()
               if len(g) >= 2)


# ---------------------------------------------------------------------------
# legacy backfill: the five committed BENCH_*.json shapes -> records


def payload_records(payload: Dict, source: str,
                    provenance: str = "legacy",
                    created: float = 0.0
                    ) -> Tuple[List[Dict], List[str]]:
    """Convert one bench artifact (the ``--json-out`` payload shapes)
    into ledger records. ONE converter serves both directions: the
    live apps emit through it with ``provenance="measured"`` and the
    backfill CLI with ``provenance="legacy"`` — so a live record and
    its backfilled ancestor land in the same (fingerprint, bench)
    trajectory group by construction. Returns ``(records, skipped)``
    — ``skipped`` names sub-results that carry no usable measurement
    (a failed or suspect run is reported as skipped, never invented).
    Raises ``ValueError`` on a shape no converter knows."""
    records: List[Dict] = []
    skipped: List[str] = []

    def legacy(bench, config, metrics, fingerprint=None):
        records.append(make_record(bench, config, metrics,
                                   provenance=provenance,
                                   fingerprint=fingerprint,
                                   source=source, created=created))

    if payload.get("bench") == "bench_exchange":
        base_cfg = {"mesh": payload.get("mesh"),
                    "per_device_size": payload.get("per_device_size"),
                    "radius": payload.get("radius"),
                    "fields": payload.get("fields")}
        for cfg in payload.get("configs", ()):
            s = cfg.get("exchange_every")
            legacy("bench_exchange", {**base_cfg, "exchange_every": s},
                   {HEADLINE_METRIC: cfg["steps_per_s"],
                    "seconds": cfg.get("seconds"),
                    "trimean_exchange_s": cfg.get("trimean_exchange_s"),
                    "exchange_rounds_per_step":
                        cfg.get("exchange_rounds_per_step"),
                    "amortized_bytes_per_step_model":
                        cfg.get("amortized_bytes_per_step_model")})
            # per-axis depth provenance: the (x, y, z) depth vector an
            # asymmetric leg ran, stamped AFTER the fingerprint is
            # fixed (the label string in exchange_every already keys
            # the trajectory; the structured vector is a note that
            # never forks a group)
            if cfg.get("depths"):
                records[-1]["config"].setdefault(
                    "depths", [int(v) for v in cfg["depths"]])
        fused = payload.get("fused")
        if fused:
            legacy("bench_exchange.megastep",
                   {**base_cfg, "check_every": fused.get("check_every")},
                   {HEADLINE_METRIC: fused["fused_steps_per_s"],
                    "stepwise_steps_per_s":
                        fused.get("stepwise_steps_per_s"),
                    "fused_over_stepwise":
                        fused.get("fused_over_stepwise")})
            # the newly fused carry contracts' race legs (PIC, the
            # astaroth temporal path) land their OWN trajectories —
            # these paths had no measured history before the segment
            # compiler
            for leg in ("pic", "astaroth_temporal"):
                sub = fused.get(leg)
                if not sub:
                    continue
                cfg = {**base_cfg,
                       "check_every": sub.get("check_every",
                                              fused.get("check_every"))}
                if "exchange_every" in sub:
                    cfg["exchange_every"] = sub["exchange_every"]
                legacy(f"bench_exchange.megastep.{leg}", cfg,
                       {HEADLINE_METRIC: sub["fused_steps_per_s"],
                        "stepwise_steps_per_s":
                            sub.get("stepwise_steps_per_s"),
                        "fused_over_stepwise":
                            sub.get("fused_over_stepwise")})
        at = payload.get("autotune")
        if at:
            plan = at.get("plan") or {}
            legacy("bench_exchange.autotune",
                   {**base_cfg, "plan_config": plan.get("config")},
                   {HEADLINE_METRIC: at["tuned_steps_per_s"],
                    "default_steps_per_s": at.get("default_steps_per_s"),
                    "tuned_over_default": at.get("tuned_over_default")},
                   fingerprint=plan.get("fingerprint"))
        return records, skipped

    if payload.get("bench") == "pic":
        sps = payload.get("seconds_per_step")
        if not sps or sps <= 0:
            skipped.append("pic: no seconds_per_step")
            return records, skipped
        legacy("pic", dict(payload.get("config") or {}),
               {HEADLINE_METRIC: 1.0 / float(sps),
                "seconds_per_step": sps,
                "particle_steps_per_s":
                    payload.get("particle_steps_per_s"),
                "migration_bytes_per_shard":
                    payload.get("migration_bytes_per_shard"),
                "overflow": payload.get("overflow")})
        fused = payload.get("fused")
        if fused:
            # the pic smoke's fused/stepwise megastep race (its own
            # trajectory, gated in CI next to megastep_ratio.json)
            legacy("pic.megastep",
                   {**dict(payload.get("config") or {}),
                    "check_every": fused.get("check_every")},
                   {HEADLINE_METRIC: fused["fused_steps_per_s"],
                    "stepwise_steps_per_s":
                        fused.get("stepwise_steps_per_s"),
                    "fused_over_stepwise":
                        fused.get("fused_over_stepwise")})
        return records, skipped

    if "parsed" in payload:  # the graft-harness BENCH_r0*.json shape
        parsed = payload.get("parsed")
        if not isinstance(parsed, dict):
            skipped.append(f"{source}: run failed (rc="
                           f"{payload.get('rc')}), nothing parsed")
            return records, skipped
        value = parsed.get("value")
        if parsed.get("suspect") or not isinstance(value, (int, float)) \
                or isinstance(value, bool) or not value or value <= 0:
            skipped.append(f"{source}: suspect/empty measurement "
                           f"(value={value!r})")
            return records, skipped
        extra = parsed.get("extra") or {}
        # identity keys only: run-varying measured figures must not
        # leak into the config, or every record keys its own group
        config = {k: extra.get(k)
                  for k in ("devices", "mesh", "platform")
                  if k in extra}
        config["unit"] = parsed.get("unit")
        legacy(str(parsed.get("metric") or "graft_bench"), config,
               {HEADLINE_METRIC: float(value),
                "vs_baseline": parsed.get("vs_baseline")})
        return records, skipped

    if isinstance(payload.get("bench"), str) \
            and isinstance(payload.get("config"), dict) \
            and isinstance(payload.get("metrics"), dict):
        # the generic shape new apps emit: bench + config + metrics
        legacy(payload["bench"], payload["config"], payload["metrics"],
               fingerprint=payload.get("fingerprint"))
        return records, skipped

    raise ValueError(f"{source}: no ledger converter for this shape "
                     f"(keys: {sorted(payload)[:8]})")


def backfill_records(payload: Dict, source: str,
                     created: float = 0.0
                     ) -> Tuple[List[Dict], List[str]]:
    """Convert one LEGACY bench artifact (``provenance="legacy"``) —
    the ``observatory backfill`` entry over :func:`payload_records`."""
    return payload_records(payload, source, provenance="legacy",
                           created=created)


def backfill_files(paths: Sequence[Union[str, Path]]
                   ) -> Tuple[List[Dict], List[str]]:
    """Backfill several legacy artifacts (in the given order — append
    order IS trajectory order), stamping each record's ``created`` from
    the file's mtime so the legacy trajectory keeps its real
    chronology."""
    records: List[Dict] = []
    skipped: List[str] = []
    for path in paths:
        p = Path(path)
        with open(p, encoding="utf-8") as f:
            payload = json.load(f)
        recs, skips = backfill_records(payload, source=p.name,
                                       created=os.path.getmtime(p))
        records.extend(recs)
        skipped.extend(skips)
    return records, skipped
