"""CLI entry: ``python -m stencil_tpu.observatory``.

Subcommands (all artifact-facing — none touch accelerators):

* ``validate PATH``  — schema-check a bench ledger (JSONL, or a single
  record / array of records) or a flight-recorder dump (autodetected
  by shape); nonzero exit on problems (the CI gate).
* ``backfill --out LEDGER FILES...`` — convert the legacy
  ``BENCH_*.json`` artifacts into ledger records with provenance
  ``legacy`` (unusable legacy runs are reported as skipped, never
  invented), appended to ``--out`` in argument order.
* ``diff A [B]``     — metric-by-metric comparison: with one ledger,
  the two newest records of the newest record's (fingerprint, bench)
  group; with two paths, the last record of each.
* ``gate LEDGER``    — the regression gate: within every same-
  (fingerprint, bench) group of ``measured`` records, the newest
  steps/s may not drop more than ``--threshold`` below the best
  earlier one; nonzero exit on any regression. ``--include-legacy``
  widens the gate to backfilled history (off by default — legacy
  snapshots come from other sessions/machines). An empty or group-less
  ledger passes with an explicit "no measured trajectory" note (never
  a silent vacuous OK); ``--json`` stamps ``groups_checked`` into the
  artifact and ``--min-groups N`` fails the run when coverage
  regresses below the committed floor.
* ``linkmap``        — the link observatory (observatory/linkmap.py):
  render the modeled per-(src, dst) traffic matrix, its link-class /
  direction-class shares, and (``--placement-report``) the QAP
  placement-quality gate over every registered mesh — nonzero exit
  when QAP placement would lose to trivial placement anywhere.
* ``replay DUMP``    — render a flight-recorder dump's merged incident
  timeline (events + probes + spans).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _load_any(path: str):
    """(kind, payload): 'dump' | 'records'. A ledger is JSONL or a
    JSON array / single record; a flight dump is one JSON object with
    kind == flight_recorder."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        payload = json.loads(text)
    except ValueError:
        from .ledger import read_ledger
        return "records", read_ledger(path)
    if isinstance(payload, dict):
        if payload.get("kind") == "flight_recorder":
            return "dump", payload
        return "records", [payload]
    if isinstance(payload, list):
        return "records", payload
    raise ValueError(f"{path}: neither ledger records nor a flight dump")


def _print_diff(diff: dict) -> None:
    a_b, a_f = diff["bench"], diff["fingerprint"]
    print(f"diff: {a_b[0]} [{(a_f[0] or '?')[:12]}] -> "
          f"{a_b[1]} [{(a_f[1] or '?')[:12]}] "
          f"(provenance {diff['provenance'][0]} -> "
          f"{diff['provenance'][1]})")
    if not diff["comparable"]:
        print("  NOTE: records key different trajectories "
              "(bench/fingerprint differ) — ratios are apples/oranges")
    for name, row in diff["metrics"].items():
        ratio = row.get("ratio")
        tail = f"  (x{ratio:.3f})" if ratio is not None else ""
        print(f"  {name:<34} {row['a']!r:>16} -> {row['b']!r:>16}{tail}")


def _parse_dim3(text: str, what: str):
    toks = [int(t) for t in text.replace("x", ",").split(",") if t]
    if len(toks) != 3 or any(v < 1 for v in toks):
        raise SystemExit(f"--{what} wants three positive integers, "
                         f"got {text!r}")
    return tuple(toks)


def _parse_exchange_every(text: str):
    """``--exchange-every``: one integer (uniform depth), or
    ``axis=value`` tokens (``z=4,y=1,x=1``) normalized to a per-axis
    ``Dim3`` depth vector — the same syntax the bench CLI sweeps."""
    toks = [t for t in (t.strip() for t in str(text).split(",")) if t]
    axes = {}
    for t in toks:
        if "=" not in t:
            continue
        k, v = t.split("=", 1)
        k = k.strip().lower()
        if k not in ("x", "y", "z") or int(v) < 1:
            raise SystemExit(f"--exchange-every axis token wants "
                             f"x=/y=/z= with depth >= 1, got {t!r}")
        axes[k] = int(v)
    if axes:
        if len(axes) != len(toks):
            raise SystemExit(f"--exchange-every mixes axis tokens and "
                             f"bare integers: {text!r}")
        from ..geometry import normalize_depths
        return normalize_depths(axes)
    try:
        return max(int(toks[0]), 1) if toks else 1
    except ValueError:
        raise SystemExit(f"--exchange-every wants an integer or "
                         f"axis=value tokens, got {text!r}")


def _cmd_linkmap(args) -> int:
    """The ``linkmap`` subcommand: modeled traffic matrix + link-class
    summary, and the placement-quality QAP gate (artifact-facing —
    pure geometry/placement math, no accelerators touched)."""
    from ..geometry import Dim3, Radius
    from .linkmap import (REGISTERED_MESHES, classify, method_traffic,
                          placement_report, render_heatmap,
                          render_summary)

    counts = Dim3(*_parse_dim3(args.mesh, "mesh"))
    grid = (_parse_dim3(args.grid, "grid") if args.grid
            else tuple(8 * c for c in counts))
    if any(g % c for g, c in zip(grid, counts)):
        # this capacity-shard model cannot represent +-1 uneven
        # shards; a silently floor-divided grid would make the
        # rendered artifact misstate the stated configuration
        raise SystemExit(f"--grid {grid} is not divisible by --mesh "
                         f"{tuple(counts)}; pick a divisible grid")
    shard = tuple(g // c for g, c in zip(grid, counts))
    radius = Radius.constant(args.radius)
    elem_sizes = (4,) * max(int(args.fields), 1)
    dcn_axis = ({"x": 0, "y": 1, "z": 2}[args.dcn_axis]
                if args.dcn_axis else None)
    depths = _parse_exchange_every(args.exchange_every)
    uniform = isinstance(depths, int)
    s = depths if uniform else max(depths)
    s_label = (s if uniform
               else f"{depths.x}.{depths.y}.{depths.z}")
    tm = method_traffic(args.method, (shard[2], shard[1], shard[0]),
                        radius, counts, elem_sizes, steps=depths)
    summary = classify(tm, dcn_axis=dcn_axis,
                       n_slices=int(args.n_slices),
                       rounds_per_step=1.0 / s)
    print(f"linkmap: {args.method}[s={s_label}] on mesh "
          f"{counts.x}x{counts.y}x{counts.z}, grid {grid}, radius "
          f"{args.radius}, {args.fields} f32 field(s)")
    print(render_heatmap(tm))
    print(render_summary(summary))

    payload = {"schema": 1, "kind": "linkmap",
               "method": args.method, "exchange_every": s_label,
               "mesh": list(counts), "grid": list(grid),
               "radius": int(args.radius), "fields": int(args.fields),
               "matrix": tm.matrix().tolist(),
               "summary": summary.to_record()}
    rc = 0
    if args.placement_report:
        report = placement_report(REGISTERED_MESHES, radius=radius,
                                  elem_sizes=elem_sizes)
        payload["placement_report"] = report
        for row in report["meshes"]:
            verdict = "OK " if row["ok"] else "FAIL"
            print(f"  {verdict} placement {row['name']:<10} "
                  f"qap/trivial x{row['qap_over_trivial']:.3f} "
                  f"(trivial {row['trivial_cost']:.3e}, qap "
                  f"{row['qap_cost']:.3e}, deployed "
                  f"{row['deployed_cost']:.3e}"
                  + (f", dcn {row['dcn_axis']}x{row['n_slices']}"
                     if row["dcn_axis"] else "") + ")")
        if report["ok"]:
            print(f"observatory: placement gate OK — QAP and "
                  f"deployed (auto-mode) placement cost <= trivial on "
                  f"all {len(report['meshes'])} registered meshes")
        else:
            bad = [r["name"] for r in report["meshes"] if not r["ok"]]
            print(f"observatory: placement gate FAILED on {bad} — "
                  f"the QAP or deployed placement would move MORE "
                  f"modeled bytes than trivial device order")
            rc = 1
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=1)
        print(f"observatory: linkmap artifact -> {args.json}")
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m stencil_tpu.observatory",
        description="performance observatory tools: validate bench "
                    "ledgers and flight-recorder dumps, backfill "
                    "legacy BENCH_*.json history, diff records, gate "
                    "regressions, replay incidents")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_val = sub.add_parser("validate", help="schema-check a ledger or "
                                            "flight dump")
    p_val.add_argument("path")

    p_bf = sub.add_parser("backfill", help="convert legacy BENCH_*.json"
                                           " into ledger records")
    p_bf.add_argument("files", nargs="+")
    p_bf.add_argument("--out", required=True, metavar="LEDGER",
                      help="ledger JSONL to append the records to")

    p_diff = sub.add_parser("diff", help="compare two bench records")
    p_diff.add_argument("a")
    p_diff.add_argument("b", nargs="?", default=None)
    p_diff.add_argument("--bench", default=None,
                        help="single-ledger mode: diff this bench's "
                             "newest group instead of the newest "
                             "record's (glob; literal [ ] in bench "
                             "ids are matched as-is)")

    p_gate = sub.add_parser("gate", help="fail on same-fingerprint "
                                         "steps/s regressions")
    p_gate.add_argument("ledger")
    p_gate.add_argument("--threshold", type=float, default=0.2,
                        help="max tolerated relative steps/s drop "
                             "(default 0.2)")
    p_gate.add_argument("--bench", default=None,
                        help="gate only matching bench ids (glob; "
                             "literal [ ] in bench ids are matched "
                             "as-is)")
    p_gate.add_argument("--include-legacy", action="store_true",
                        help="also gate provenance=legacy records")
    p_gate.add_argument("--json", default=None, metavar="PATH",
                        help="write the gate verdict (records, "
                             "groups_checked, failures) as a JSON "
                             "artifact")
    p_gate.add_argument("--min-groups", type=int, default=0,
                        metavar="N",
                        help="fail when fewer than N comparable "
                             "(fingerprint, bench) groups were "
                             "actually gated — the committed coverage "
                             "floor that makes a vacuous pass loud")

    p_lm = sub.add_parser("linkmap",
                          help="render the modeled per-link traffic "
                               "matrix / placement-quality report")
    p_lm.add_argument("--mesh", default="2,2,2", metavar="X,Y,Z",
                      help="shard lattice (device counts per axis; "
                           "default 2,2,2)")
    p_lm.add_argument("--grid", default=None, metavar="X,Y,Z",
                      help="global grid (default 8 cells per shard "
                           "per axis)")
    p_lm.add_argument("--radius", type=int, default=1)
    p_lm.add_argument("--fields", type=int, default=1,
                      help="f32 quantities riding the exchange")
    p_lm.add_argument("--method", default="PpermuteSlab",
                      choices=("PpermuteSlab", "PpermutePacked",
                               "AllGather"))
    p_lm.add_argument("--exchange-every", default="1",
                      metavar="S|z=4,y=1,x=1",
                      help="temporal-blocking depth: one integer, or "
                           "axis=value tokens for per-axis asymmetric "
                           "depths")
    p_lm.add_argument("--dcn-axis", default=None,
                      choices=("x", "y", "z"),
                      help="slice-blocked axis (classifies its "
                           "slice-crossing edges as dcn)")
    p_lm.add_argument("--n-slices", type=int, default=1)
    p_lm.add_argument("--placement-report", action="store_true",
                      help="score QAP vs trivial placement over every "
                           "registered mesh; nonzero exit when QAP "
                           "placement would lose anywhere")
    p_lm.add_argument("--json", default=None, metavar="PATH",
                      help="write the linkmap / placement report as a "
                           "JSON artifact")

    p_rep = sub.add_parser("replay", help="render a flight dump's "
                                          "incident timeline")
    p_rep.add_argument("dump")

    args = parser.parse_args(argv)

    if args.cmd == "validate":
        from .ledger import validate_ledger
        from .recorder import validate_dump
        try:
            kind, payload = _load_any(args.path)
        except (OSError, ValueError) as e:
            print(f"observatory: cannot load {args.path}: {e}",
                  file=sys.stderr)
            return 2
        problems = (validate_dump(payload) if kind == "dump"
                    else validate_ledger(payload))
        for p in problems:
            print(f"  BAD  {p}")
        if problems:
            print(f"observatory: {kind} {args.path}: "
                  f"{len(problems)} problem(s)")
            return 1
        n = len(payload["events"]) if kind == "dump" else len(payload)
        what = ("flight dump" if kind == "dump"
                else f"ledger ({n} record(s))")
        print(f"observatory: {args.path} OK ({what})")
        return 0

    if args.cmd == "backfill":
        from .ledger import append_record, backfill_files
        try:
            records, skipped = backfill_files(args.files)
        except (OSError, ValueError, KeyError) as e:
            print(f"observatory: backfill failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2
        for rec in records:
            append_record(args.out, rec)
        for s in skipped:
            print(f"  SKIP {s}")
        print(f"observatory: backfilled {len(records)} record(s) from "
              f"{len(args.files)} file(s) into {args.out}"
              + (f" ({len(skipped)} skipped)" if skipped else ""))
        return 0

    if args.cmd == "diff":
        from .ledger import diff_records, group_records
        try:
            _, recs_a = _load_any(args.a)
        except (OSError, ValueError) as e:
            print(f"observatory: cannot load {args.a}: {e}",
                  file=sys.stderr)
            return 2
        if args.b is not None:
            try:
                _, recs_b = _load_any(args.b)
            except (OSError, ValueError) as e:
                print(f"observatory: cannot load {args.b}: {e}",
                      file=sys.stderr)
                return 2
            if not recs_a or not recs_b:
                print("observatory: nothing to diff", file=sys.stderr)
                return 2
            _print_diff(diff_records(recs_a[-1], recs_b[-1]))
            return 0
        groups = group_records(recs_a)
        if args.bench is not None:
            from ..utils.naming import glob_match
            groups = {k: g for k, g in groups.items()
                      if glob_match(str(k[1]), args.bench)}
        pairs = [g for g in groups.values() if len(g) >= 2]
        if not pairs:
            # an empty/group-less ledger is not an error — but it must
            # be LOUD that nothing was compared, never a silent pass
            print(f"observatory: no measured trajectory to diff in "
                  f"{args.a} ({len(recs_a)} record(s), "
                  f"{len(groups)} group(s), none with two records)")
            return 0
        # the group whose newest record is newest overall
        group = max(pairs, key=lambda g: g[-1].get("created", 0.0))
        _print_diff(diff_records(group[-2], group[-1]))
        return 0

    if args.cmd == "gate":
        from .ledger import (PROVENANCES, gate_groups_checked,
                             gate_regressions, read_ledger,
                             validate_ledger)
        try:
            records = read_ledger(args.ledger)
        except (OSError, ValueError) as e:
            # an EMPTY ledger is "no measured trajectory"; a MISSING
            # or unreadable path is a usage error — exiting 0 there
            # would be the vacuous-pass-on-typo this command exists
            # to make loud
            print(f"observatory: cannot load {args.ledger}: {e}",
                  file=sys.stderr)
            return 2
        problems = validate_ledger(records)
        if problems:
            for p in problems:
                print(f"  BAD  {p}")
            print(f"observatory: ledger {args.ledger} is invalid — "
                  f"fix it before gating")
            return 2
        prov = PROVENANCES if args.include_legacy else ("measured",)
        failures = gate_regressions(records,
                                    threshold=args.threshold,
                                    provenances=prov, bench=args.bench)
        groups_checked = gate_groups_checked(records, provenances=prov,
                                             bench=args.bench)
        if args.json:
            with open(args.json, "w", encoding="utf-8") as fh:
                json.dump({"schema": 1, "kind": "ledger_gate",
                           "ledger": args.ledger,
                           "records": len(records),
                           "groups_checked": groups_checked,
                           "min_groups": args.min_groups,
                           "threshold": args.threshold,
                           "failures": failures}, fh, indent=1)
        for f in failures:
            print(f"  REGRESSION  {f}")
        if failures:
            print(f"observatory: gate FAILED "
                  f"({len(failures)} regression(s), "
                  f"{groups_checked} group(s) checked)")
            return 1
        if groups_checked < args.min_groups:
            print(f"observatory: gate FAILED — only {groups_checked} "
                  f"comparable group(s) gated, below the committed "
                  f"floor of {args.min_groups} (coverage regressed: "
                  f"benches stopped appending, or the ledger path is "
                  f"wrong)")
            return 1
        if groups_checked == 0:
            # exit 0, but LOUDLY distinguishable from a healthy gate:
            # nothing was compared, so nothing was proven
            print(f"observatory: gate OK — no measured trajectory to "
                  f"gate ({len(records)} record(s), 0 comparable "
                  f"groups; the gate proved nothing)")
            return 0
        print(f"observatory: gate OK ({len(records)} record(s), "
              f"{groups_checked} group(s) checked, "
              f"threshold {100 * args.threshold:.0f}%)")
        return 0

    if args.cmd == "linkmap":
        return _cmd_linkmap(args)

    # replay
    from .recorder import render_timeline, validate_dump
    problems = validate_dump(args.dump)
    if problems:
        for p in problems:
            print(f"  BAD  {p}")
        print(f"observatory: dump {args.dump}: "
              f"{len(problems)} problem(s)")
        return 1
    sys.stdout.write(render_timeline(args.dump))
    return 0


if __name__ == "__main__":
    sys.exit(main())
