"""CLI entry: ``python -m stencil_tpu.observatory``.

Subcommands (all artifact-facing — none touch accelerators):

* ``validate PATH``  — schema-check a bench ledger (JSONL, or a single
  record / array of records) or a flight-recorder dump (autodetected
  by shape); nonzero exit on problems (the CI gate).
* ``backfill --out LEDGER FILES...`` — convert the legacy
  ``BENCH_*.json`` artifacts into ledger records with provenance
  ``legacy`` (unusable legacy runs are reported as skipped, never
  invented), appended to ``--out`` in argument order.
* ``diff A [B]``     — metric-by-metric comparison: with one ledger,
  the two newest records of the newest record's (fingerprint, bench)
  group; with two paths, the last record of each.
* ``gate LEDGER``    — the regression gate: within every same-
  (fingerprint, bench) group of ``measured`` records, the newest
  steps/s may not drop more than ``--threshold`` below the best
  earlier one; nonzero exit on any regression. ``--include-legacy``
  widens the gate to backfilled history (off by default — legacy
  snapshots come from other sessions/machines).
* ``replay DUMP``    — render a flight-recorder dump's merged incident
  timeline (events + probes + spans).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _load_any(path: str):
    """(kind, payload): 'dump' | 'records'. A ledger is JSONL or a
    JSON array / single record; a flight dump is one JSON object with
    kind == flight_recorder."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        payload = json.loads(text)
    except ValueError:
        from .ledger import read_ledger
        return "records", read_ledger(path)
    if isinstance(payload, dict):
        if payload.get("kind") == "flight_recorder":
            return "dump", payload
        return "records", [payload]
    if isinstance(payload, list):
        return "records", payload
    raise ValueError(f"{path}: neither ledger records nor a flight dump")


def _print_diff(diff: dict) -> None:
    a_b, a_f = diff["bench"], diff["fingerprint"]
    print(f"diff: {a_b[0]} [{(a_f[0] or '?')[:12]}] -> "
          f"{a_b[1]} [{(a_f[1] or '?')[:12]}] "
          f"(provenance {diff['provenance'][0]} -> "
          f"{diff['provenance'][1]})")
    if not diff["comparable"]:
        print("  NOTE: records key different trajectories "
              "(bench/fingerprint differ) — ratios are apples/oranges")
    for name, row in diff["metrics"].items():
        ratio = row.get("ratio")
        tail = f"  (x{ratio:.3f})" if ratio is not None else ""
        print(f"  {name:<34} {row['a']!r:>16} -> {row['b']!r:>16}{tail}")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m stencil_tpu.observatory",
        description="performance observatory tools: validate bench "
                    "ledgers and flight-recorder dumps, backfill "
                    "legacy BENCH_*.json history, diff records, gate "
                    "regressions, replay incidents")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_val = sub.add_parser("validate", help="schema-check a ledger or "
                                            "flight dump")
    p_val.add_argument("path")

    p_bf = sub.add_parser("backfill", help="convert legacy BENCH_*.json"
                                           " into ledger records")
    p_bf.add_argument("files", nargs="+")
    p_bf.add_argument("--out", required=True, metavar="LEDGER",
                      help="ledger JSONL to append the records to")

    p_diff = sub.add_parser("diff", help="compare two bench records")
    p_diff.add_argument("a")
    p_diff.add_argument("b", nargs="?", default=None)
    p_diff.add_argument("--bench", default=None,
                        help="single-ledger mode: diff this bench's "
                             "newest group instead of the newest "
                             "record's")

    p_gate = sub.add_parser("gate", help="fail on same-fingerprint "
                                         "steps/s regressions")
    p_gate.add_argument("ledger")
    p_gate.add_argument("--threshold", type=float, default=0.2,
                        help="max tolerated relative steps/s drop "
                             "(default 0.2)")
    p_gate.add_argument("--bench", default=None,
                        help="gate only this bench id")
    p_gate.add_argument("--include-legacy", action="store_true",
                        help="also gate provenance=legacy records")

    p_rep = sub.add_parser("replay", help="render a flight dump's "
                                          "incident timeline")
    p_rep.add_argument("dump")

    args = parser.parse_args(argv)

    if args.cmd == "validate":
        from .ledger import validate_ledger
        from .recorder import validate_dump
        try:
            kind, payload = _load_any(args.path)
        except (OSError, ValueError) as e:
            print(f"observatory: cannot load {args.path}: {e}",
                  file=sys.stderr)
            return 2
        problems = (validate_dump(payload) if kind == "dump"
                    else validate_ledger(payload))
        for p in problems:
            print(f"  BAD  {p}")
        if problems:
            print(f"observatory: {kind} {args.path}: "
                  f"{len(problems)} problem(s)")
            return 1
        n = len(payload["events"]) if kind == "dump" else len(payload)
        what = ("flight dump" if kind == "dump"
                else f"ledger ({n} record(s))")
        print(f"observatory: {args.path} OK ({what})")
        return 0

    if args.cmd == "backfill":
        from .ledger import append_record, backfill_files
        try:
            records, skipped = backfill_files(args.files)
        except (OSError, ValueError, KeyError) as e:
            print(f"observatory: backfill failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2
        for rec in records:
            append_record(args.out, rec)
        for s in skipped:
            print(f"  SKIP {s}")
        print(f"observatory: backfilled {len(records)} record(s) from "
              f"{len(args.files)} file(s) into {args.out}"
              + (f" ({len(skipped)} skipped)" if skipped else ""))
        return 0

    if args.cmd == "diff":
        from .ledger import diff_records, group_records
        try:
            _, recs_a = _load_any(args.a)
        except (OSError, ValueError) as e:
            print(f"observatory: cannot load {args.a}: {e}",
                  file=sys.stderr)
            return 2
        if args.b is not None:
            try:
                _, recs_b = _load_any(args.b)
            except (OSError, ValueError) as e:
                print(f"observatory: cannot load {args.b}: {e}",
                      file=sys.stderr)
                return 2
            if not recs_a or not recs_b:
                print("observatory: nothing to diff", file=sys.stderr)
                return 2
            _print_diff(diff_records(recs_a[-1], recs_b[-1]))
            return 0
        groups = group_records(recs_a)
        if args.bench is not None:
            groups = {k: g for k, g in groups.items()
                      if k[1] == args.bench}
        pairs = [g for g in groups.values() if len(g) >= 2]
        if not pairs:
            print("observatory: no (fingerprint, bench) group has two "
                  "records to diff", file=sys.stderr)
            return 2
        # the group whose newest record is newest overall
        group = max(pairs, key=lambda g: g[-1].get("created", 0.0))
        _print_diff(diff_records(group[-2], group[-1]))
        return 0

    if args.cmd == "gate":
        from .ledger import (PROVENANCES, gate_regressions, read_ledger,
                             validate_ledger)
        try:
            records = read_ledger(args.ledger)
        except (OSError, ValueError) as e:
            print(f"observatory: cannot load {args.ledger}: {e}",
                  file=sys.stderr)
            return 2
        problems = validate_ledger(records)
        if problems:
            for p in problems:
                print(f"  BAD  {p}")
            print(f"observatory: ledger {args.ledger} is invalid — "
                  f"fix it before gating")
            return 2
        prov = PROVENANCES if args.include_legacy else ("measured",)
        failures = gate_regressions(records,
                                    threshold=args.threshold,
                                    provenances=prov, bench=args.bench)
        for f in failures:
            print(f"  REGRESSION  {f}")
        if failures:
            print(f"observatory: gate FAILED "
                  f"({len(failures)} regression(s))")
            return 1
        print(f"observatory: gate OK ({len(records)} record(s), "
              f"threshold {100 * args.threshold:.0f}%)")
        return 0

    # replay
    from .recorder import render_timeline, validate_dump
    problems = validate_dump(args.dump)
    if problems:
        for p in problems:
            print(f"  BAD  {p}")
        print(f"observatory: dump {args.dump}: "
              f"{len(problems)} problem(s)")
        return 1
    sys.stdout.write(render_timeline(args.dump))
    return 0


if __name__ == "__main__":
    sys.exit(main())
