"""Performance observatory: close the loop between model and machine.

The audit stack proves what a program *will* do (exact collectives,
exact wire bytes, donation, zero host transfers — ``analysis/``) and
the tuner prices what it *should* cost (calibrated alpha-beta,
``tuning/`` + ``analysis/costmodel.py``), but neither observes what
dispatches *actually achieve*. This package is that third leg,
TEMPI-style (arXiv:2012.14363 — communication claims stand on
systematic measured-vs-modeled validation), in three coupled pieces:

* **Attribution** (:mod:`.attribution`) — :class:`PerfAttributor`
  wraps every fused-segment and stepwise dispatch in the resilient
  driver, the campaign service, and the bench apps, pairing measured
  seconds/step (``jax.block_until_ready``-fenced, amortized over the
  segment's k steps) against the calibrated cost-model prediction for
  the active plan. Exported as
  ``stencil_perf_model_error_ratio{entry,method,s}`` plus achieved-vs-
  modeled bytes/s; a drift detector emits a v1-schema ``perf_drift``
  event after K consecutive segments outside tolerance and (opt-in,
  ``ResiliencePolicy.retune_on_drift``) invalidates the plan-cache
  record so the tuner re-measures — stale plans heal themselves.
  Attribution is HOST-side (wall clock around the dispatch): the
  ``observatory.attribution.*`` registry targets prove the attributed
  entry points lower to the IDENTICAL HLO as uninstrumented ones.

* **Ledger** (:mod:`.ledger`) — ONE versioned bench-record schema
  every app's ``--json-out`` path also appends to
  ``bench/ledger.jsonl``, keyed by the tuning fingerprint + bench id.
  ``python -m stencil_tpu.observatory`` validates records, backfills
  the legacy ``BENCH_*.json`` snapshots, diffs records, and gates
  same-fingerprint steps/s regressions (nonzero exit) — the perf
  trajectory becomes append-only history instead of per-PR snapshots.

* **Flight recorder** (:mod:`.recorder`) — a bounded black box
  (recent events via :class:`~stencil_tpu.telemetry.RingSink`, recent
  spans, a metrics snapshot, health/probe history, the classified
  linkmap snapshot) dumped atomically on health trip, degradation,
  SIGTERM, and unhandled dispatch error;
  ``observatory replay <dump>`` renders the incident timeline.

* **Link observatory** (:mod:`.linkmap`) — the per-link signal: a
  modeled (src, dst) traffic matrix whose totals the
  ``observatory.linkmap.*`` registry targets pin HLO-exactly per
  method, classified into self/ici-hop-k/dcn link classes against
  the deployed device order
  (``stencil_link_bytes_per_step{axis,link_class}`` /
  ``stencil_link_utilization_ratio``), a measured per-axis topology
  fingerprint the tuner consumes instead of its two global
  alpha-betas, and ``observatory linkmap --placement-report`` — the
  QAP-vs-trivial placement-quality gate over every registered mesh.
"""

from .attribution import (METRIC_ACHIEVED_BYTES_PER_S,
                          METRIC_MODEL_ERROR_RATIO,
                          METRIC_MODELED_BYTES_PER_S, PerfAttributor,
                          make_drift_invalidator,
                          model_step_seconds_for)
from .ledger import (LEDGER_SCHEMA_VERSION, append_record,
                     backfill_records, config_fingerprint, diff_records,
                     gate_regressions, make_record, payload_records,
                     read_ledger, validate_record)
from .linkmap import (METRIC_LINK_BYTES_PER_STEP,
                      METRIC_LINK_UTILIZATION, LinkmapSpec,
                      LinkmapSummary, LinkmapTarget, TrafficMatrix,
                      allgather_traffic, check_linkmap, classify,
                      link_attribution_for, load_topology,
                      measure_topology, method_traffic,
                      migration_traffic, pic_traffic, placement_report,
                      save_topology, sweep_traffic,
                      topology_fingerprint,
                      topology_fingerprint_inputs)
from .recorder import (ENV_FLIGHT_DIR, FLIGHT_SCHEMA_VERSION,
                       FlightRecorder, render_timeline, validate_dump)

__all__ = [
    "METRIC_LINK_BYTES_PER_STEP", "METRIC_LINK_UTILIZATION",
    "LinkmapSpec", "LinkmapSummary", "LinkmapTarget", "TrafficMatrix",
    "allgather_traffic", "check_linkmap", "classify",
    "link_attribution_for", "load_topology", "measure_topology",
    "method_traffic", "migration_traffic", "pic_traffic",
    "placement_report", "save_topology", "sweep_traffic",
    "topology_fingerprint", "topology_fingerprint_inputs",
    "PerfAttributor", "model_step_seconds_for",
    "make_drift_invalidator",
    "METRIC_MODEL_ERROR_RATIO", "METRIC_ACHIEVED_BYTES_PER_S",
    "METRIC_MODELED_BYTES_PER_S",
    "LEDGER_SCHEMA_VERSION", "make_record", "validate_record",
    "append_record", "read_ledger", "diff_records", "gate_regressions",
    "backfill_records", "payload_records", "config_fingerprint",
    "FLIGHT_SCHEMA_VERSION", "ENV_FLIGHT_DIR", "FlightRecorder",
    "validate_dump", "render_timeline",
]
