"""Model-vs-measured attribution: what did the dispatch actually cost?

The calibrated cost model (``analysis/costmodel.configured_step_seconds``
for halo sweeps, ``migration_step_seconds`` for PIC) predicts what a
step *should* cost; :class:`PerfAttributor` measures what the shipped
dispatch *achieves* — wall seconds around the dispatch, fenced by
``jax.block_until_ready``, amortized over the segment's ``k`` steps —
and exports the ratio as ``stencil_perf_model_error_ratio{entry,
method,s}`` gauges next to achieved-vs-modeled bytes/s.

Attribution is strictly HOST-side: the dispatched program is returned
unchanged by :meth:`PerfAttributor.attributed` (an identity the
``observatory.attribution.*`` registry targets pin — same HLO, same
collective bill, same compile fingerprint as the uninstrumented entry
point; a timer that sneaks a host callback into the step is the
negative control, ``tests/fixtures/lint/bad_attribution.py``).

Drift detection: the raw error ratio absorbs everything the wire model
deliberately does not price (compute, dispatch overhead, the host
loop), so its absolute value is platform-shaped. What IS actionable is
a *departure*: the first observation calibrates a reference ratio —
which then stays FIXED until :meth:`~PerfAttributor.reset` (a moving
reference would chase a gradual slowdown and never flag it) — and
``window`` (K) consecutive observations whose ratio deviates from that
reference by more than ``tolerance`` (relative) raise one ``perf_drift``
event (v1 telemetry schema) and fire ``on_drift`` — which, when the
resilience policy opts in (``retune_on_drift``), invalidates the
plan-cache record so the tuner re-measures. A re-tuned or rebuilt plan
calls :meth:`reset`, which clears the gauge and re-arms the detector.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, Optional

#: measured/modeled seconds-per-step of attributed dispatches
METRIC_MODEL_ERROR_RATIO = "stencil_perf_model_error_ratio"
#: wire bytes/s the dispatch actually achieved (model bytes / measured s)
METRIC_ACHIEVED_BYTES_PER_S = "stencil_perf_achieved_bytes_per_s"
#: wire bytes/s the calibrated model promises (model bytes / model s)
METRIC_MODELED_BYTES_PER_S = "stencil_perf_modeled_bytes_per_s"


def make_drift_invalidator(cache_path, log: Callable) -> Callable:
    """The ``on_drift`` hook the driver and the service share when
    their policy opts into ``retune_on_drift``: drop the drifted
    plan's cache record (:func:`stencil_tpu.tuning.invalidate_plan`)
    so the next tune re-measures, and log ``plan_invalidated`` through
    the caller's versioned event front end (``log(kind, **attrs)``)."""
    def on_drift(attrs: Dict) -> None:
        fp = attrs.get("fingerprint")
        if not fp:
            return
        from ..tuning.cache import invalidate_plan
        removed = invalidate_plan(fp, cache_path)
        log("plan_invalidated", fingerprint=fp, removed=bool(removed))
    return on_drift


def model_step_seconds_for(dd) -> Optional[float]:
    """The calibrated cost-model prediction of exchange seconds per
    STEP for ``dd``'s active configuration: ``configured_step_seconds``
    with the tuned plan's fitted alpha-beta coefficients when the
    domain carries one (bottleneck combination across link classes, the
    same convention the tuner ranks with), the assumed ICI defaults
    otherwise. Returns None when the domain has no price — unsharded
    mesh (zero wire traffic), unrealized domain, or a geometry the
    model cannot host — so callers can disable attribution instead of
    dividing by zero. Never raises."""
    try:
        from ..analysis.costmodel import (DEFAULT_ICI_COEFFS,
                                          LinkCoefficients,
                                          configured_step_seconds)
        from ..parallel.mesh import mesh_dim
        from ..parallel.methods import pick_method

        method = pick_method(dd.methods).name
        counts = mesh_dim(dd.mesh)
        local = dd.local_size
        elem_sizes = tuple(dd._dtypes[q].itemsize for q in dd._names)
        coeffs = DEFAULT_ICI_COEFFS
        plan = getattr(dd, "plan", None)
        if plan is not None and getattr(plan, "coefficients", None):
            coeffs = LinkCoefficients(
                alpha_s=max(c["alpha_s"]
                            for c in plan.coefficients.values()),
                beta_bytes_per_s=min(c["beta_bytes_per_s"]
                                     for c in plan.coefficients.values()))
        groups = len({str(dd._dtypes[q]) for q in dd._names})
        model = configured_step_seconds(
            method, (local.z, local.y, local.x), dd.radius, counts,
            elem_sizes, int(dd.exchange_every), coeffs, groups)
        return model if model > 0.0 else None
    except Exception:  # noqa: BLE001 - no price -> attribution off
        return None


class PerfAttributor:
    """Measured-vs-modeled attribution for one dispatch entry point.

    ``entry``/``method``/``exchange_every`` become the stable
    ``{entry,method,s}`` labels of the exported gauges.
    ``model_step_seconds`` is the calibrated prediction the
    measurements are paired against (falsy disables the attributor —
    :attr:`enabled` — so unpriceable configurations cost nothing).
    ``emit(kind, **attrs)`` receives the ``perf_drift`` event (wire it
    to a versioned :class:`~stencil_tpu.telemetry.EventLog` front end
    like ``ResilienceReport.log``); ``on_drift(attrs)`` fires once per
    drift episode (plan-cache invalidation hook)."""

    def __init__(self, entry: str, method: str = "", exchange_every: int = 1,
                 model_step_seconds: Optional[float] = None,
                 model_bytes_per_step: float = 0.0,
                 tolerance: float = 0.5, window: int = 3,
                 warmup: int = 0,
                 emit: Optional[Callable] = None,
                 on_drift: Optional[Callable[[Dict], None]] = None,
                 fingerprint: Optional[str] = None,
                 registry=None,
                 link_bytes_per_step: Optional[Dict] = None,
                 link_peak_bytes_per_s: Optional[Dict] = None,
                 clock: Callable[[], float] = time.perf_counter) -> None:
        if float(tolerance) <= 0:
            raise ValueError(f"tolerance must be > 0, got {tolerance}")
        if int(window) < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.entry = str(entry)
        self.method = str(method)
        self.exchange_every = int(exchange_every)
        self.model_step_seconds = (float(model_step_seconds)
                                   if model_step_seconds else 0.0)
        self.model_bytes_per_step = float(model_bytes_per_step)
        self.tolerance = float(tolerance)
        self.window = int(window)
        #: observations to EXCLUDE from drift calibration (gauges still
        #: export): the driver/service pass 1 because their first
        #: dispatch pays XLA compilation — calibrating the reference
        #: ratio on a compile-contaminated window would make every
        #: later (faster) segment look like drift
        self._warmup = max(int(warmup), 0)
        self.fingerprint = fingerprint
        self._emit = emit
        self._on_drift = on_drift
        self._clock = clock
        if registry is None:
            from ..telemetry import get_registry
            registry = get_registry()
        self._g_ratio = registry.gauge(
            METRIC_MODEL_ERROR_RATIO,
            "measured/modeled seconds-per-step of attributed dispatches "
            "(block_until_ready-fenced, amortized over the segment's k "
            "steps); 0 = not yet observed / reset after a re-tune")
        self._g_achieved = registry.gauge(
            METRIC_ACHIEVED_BYTES_PER_S,
            "wire B/s the attributed dispatch actually achieved "
            "(modeled bytes over measured seconds)")
        self._g_modeled = registry.gauge(
            METRIC_MODELED_BYTES_PER_S,
            "wire B/s the calibrated cost model promises for the "
            "active plan")
        # per-link attribution (observatory/linkmap.py): the modeled
        # traffic matrix classified per (mesh axis, link class) plus
        # the per-axis fitted peak rate from the topology fingerprint
        # / tuned plan — the signal ROADMAP item 3's placement work
        # optimizes against
        self.link_bytes_per_step: Dict = dict(link_bytes_per_step or {})
        self.link_peak_bytes_per_s: Dict = dict(link_peak_bytes_per_s
                                                or {})
        from .linkmap import (METRIC_LINK_BYTES_PER_STEP,
                              METRIC_LINK_UTILIZATION)
        self._g_link_bytes = registry.gauge(
            METRIC_LINK_BYTES_PER_STEP,
            "modeled wire B/step per mesh axis and link class (self / "
            "ici-hop<k> / dcn) — the traffic matrix the "
            "observatory.linkmap.* registry targets pin HLO-exactly, "
            "classified against the deployed device order")
        self._g_link_util = registry.gauge(
            METRIC_LINK_UTILIZATION,
            "achieved/fitted-peak wire utilization per mesh axis and "
            "link class: the link's modeled B/step over the measured "
            "step seconds, against the topology fingerprint's (or "
            "tuned plan's) fitted beta for that axis; 0 = not yet "
            "observed / reset after a re-tune")
        self.last_ratio: Optional[float] = None
        self._baseline: Optional[float] = None
        self._streak = 0
        self._drifted = False

    @property
    def enabled(self) -> bool:
        return self.model_step_seconds > 0.0

    def labels(self) -> Dict[str, str]:
        return {"entry": self.entry, "method": self.method,
                "s": str(self.exchange_every)}

    # -- the honesty contract -------------------------------------------
    @staticmethod
    def attributed(fn):
        """The program the attributor dispatches — the caller's ``fn``,
        UNCHANGED. Attribution is a wall clock around the dispatch,
        never an edit of the compiled program; the
        ``observatory.attribution.*`` registry targets lower what this
        returns and pin it to the uninstrumented entry point's exact
        collective bill, byte model, and compile fingerprint. Any
        future attribution scheme that wraps the program (and would
        therefore change its HLO) breaks those targets loudly."""
        return fn

    # -- measurement ----------------------------------------------------
    @contextlib.contextmanager
    def dispatch(self, k: int, block: Callable[[], None],
                 step: Optional[int] = None):
        """Time one dispatch advancing ``k`` steps: the wall clock runs
        from entry to after ``block()`` (``jax.block_until_ready`` on
        the live state — async dispatch must not be credited with the
        seconds it merely deferred), then :meth:`observe` attributes
        the measurement. Disabled attributors pass straight through."""
        if not self.enabled:
            yield self
            return
        t0 = self._clock()
        yield self
        block()
        self.observe(k, self._clock() - t0, step=step)

    def observe(self, k: int, seconds: float,
                step: Optional[int] = None) -> Optional[Dict]:
        """Attribute one measured dispatch of ``k`` steps taking
        ``seconds``: export the gauges, run the drift detector, and
        return the ``perf_drift`` attrs when this observation fired a
        drift (None otherwise)."""
        if not self.enabled:
            return None
        measured = float(seconds) / max(int(k), 1)
        ratio = measured / self.model_step_seconds
        self.last_ratio = ratio
        labels = self.labels()
        self._g_ratio.set(ratio, **labels)
        if self.model_bytes_per_step > 0.0 and measured > 0.0:
            self._g_achieved.set(self.model_bytes_per_step / measured,
                                 **labels)
            self._g_modeled.set(
                self.model_bytes_per_step / self.model_step_seconds,
                **labels)
        self._export_links(measured)
        if self._warmup > 0:
            self._warmup -= 1  # compile-contaminated: export, don't
            return None        # calibrate or count toward drift
        if not self._baseline:
            # first usable observation calibrates; a degenerate zero
            # ratio (fake clocks) cannot anchor a relative comparison,
            # so calibration waits for a nonzero one
            self._baseline = ratio
            return None
        # the reference stays FIXED until reset(): a baseline that
        # chased the ratio (EWMA) would let boiling-frog degradations
        # — thermal throttling, a slowly failing link — walk the
        # reference along and never register as drift, which is
        # exactly the failure class this detector exists to catch
        rel = abs(ratio - self._baseline) / self._baseline
        if rel <= self.tolerance:
            self._streak = 0
            self._drifted = False
            return None
        self._streak += 1
        if self._streak < self.window or self._drifted:
            return None
        self._drifted = True
        attrs: Dict = {
            "entry": self.entry, "method": self.method,
            "s": self.exchange_every, "ratio": ratio,
            "baseline": self._baseline, "consecutive": self._streak,
            "tolerance": self.tolerance, "window": self.window,
        }
        if step is not None:
            attrs["step"] = int(step)
        if self.fingerprint:
            attrs["fingerprint"] = self.fingerprint
        if self._emit is not None:
            self._emit("perf_drift", **attrs)
        if self._on_drift is not None:
            self._on_drift(dict(attrs))
        return attrs

    def _export_links(self, measured_step_seconds: float,
                      clear: bool = False) -> None:
        """Per-link gauges for one observation: the modeled B/step of
        every (axis, link_class) pair, and — when the axis has a
        fitted peak — the utilization that measured step implies.
        ``clear`` zeroes both (a re-tuned plan supersedes the old
        link map)."""
        for (axis, klass), nbytes in self.link_bytes_per_step.items():
            labels = {"axis": str(axis), "link_class": str(klass)}
            self._g_link_bytes.set(0.0 if clear else float(nbytes),
                                   **labels)
            peak = self.link_peak_bytes_per_s.get(str(axis))
            if clear:
                self._g_link_util.set(0.0, **labels)
            elif peak and measured_step_seconds > 0.0:
                achieved = float(nbytes) / measured_step_seconds
                self._g_link_util.set(achieved / float(peak), **labels)

    def reset(self, model_step_seconds: Optional[float] = None,
              fingerprint: Optional[str] = None) -> None:
        """A re-tuned (or rebuilt) plan supersedes everything observed
        under the old one: clear the error-ratio gauge back to the
        not-yet-observed 0, drop the calibrated reference, and re-arm
        the drift latch. Pass the new model price / fingerprint when
        they changed."""
        if model_step_seconds is not None:
            self.model_step_seconds = float(model_step_seconds)
        if fingerprint is not None:
            self.fingerprint = fingerprint
        self._g_ratio.set(0.0, **self.labels())
        self._export_links(0.0, clear=True)
        self.last_ratio = None
        self._baseline = None
        self._streak = 0
        self._drifted = False
