"""``python -m stencil_tpu.tune`` — the exchange autotuner CLI.

Tunes an exchange plan for a described problem (grid, radius,
quantities, mesh) on the current devices and persists it to the plan
cache, so production runs — or a whole fleet pointed at the same cache
file via ``$STENCIL_TUNE_CACHE`` — start with a plan-cache hit and
never pay measurement cost. The deterministic ``--fake-timer`` mode
exercises the full search/fit/plan/cache pipeline with zero hardware
dependence (the CI stage and tier-1 tests run it on CPU).

Examples::

    # tune a 256^3 radius-2 two-field problem on this machine
    python -m stencil_tpu.tune --x 256 --y 256 --z 256 --fr 2 --fields 2

    # deterministic, hardware-free (CI): fake timer + scratch cache
    python -m stencil_tpu.tune --x 64 --y 64 --z 64 --fake-cpu 8 \
        --fake-timer --cache /tmp/plans.json --json plan.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _parse_ints(text: str) -> List[int]:
    return [int(t) for t in text.split(",") if t.strip()]


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m stencil_tpu.tune",
        description="Measurement-driven halo-exchange autotuner: "
                    "measure -> fit -> plan -> cache.")
    ap.add_argument("--x", type=int, default=128, help="global x size")
    ap.add_argument("--y", type=int, default=128)
    ap.add_argument("--z", type=int, default=128)
    ap.add_argument("--fr", type=int, default=1, help="face radius")
    ap.add_argument("--er", type=int, default=1, help="edge radius")
    ap.add_argument("--cr", type=int, default=1, help="corner radius")
    ap.add_argument("--fields", type=int, default=1,
                    help="number of quantities")
    ap.add_argument("--dtype", default="float32",
                    help="quantity dtype (numpy name)")
    ap.add_argument("--mesh-shape", default="", metavar="MX,MY,MZ",
                    help="explicit subdomain grid (default: derived)")
    ap.add_argument("--depths", default="1,2,4,8", metavar="S[,S...]",
                    help="temporal-blocking depths to sweep")
    ap.add_argument("--max-measure", type=int, default=4,
                    help="timing runs after cost-model pruning")
    ap.add_argument("--cache", default="", metavar="PATH",
                    help="plan cache file (default: "
                         "$STENCIL_TUNE_CACHE or "
                         "~/.cache/stencil_tpu/plans.json)")
    ap.add_argument("--no-cache", action="store_true",
                    help="neither read nor write the plan cache")
    ap.add_argument("--force", action="store_true",
                    help="ignore a cached plan; re-measure and rewrite")
    ap.add_argument("--fake-timer", action="store_true",
                    help="deterministic analytic measurements (no "
                         "hardware timing; exercises the full search)")
    ap.add_argument("--topology", default="", metavar="PATH",
                    help="measured topology-fingerprint artifact "
                         "(observatory/linkmap.py): per-axis link "
                         "calibrations are measured once per fabric "
                         "and consumed by every later tune instead of "
                         "the two global pingpong fits (default: "
                         "$STENCIL_TOPOLOGY_CACHE when set)")
    ap.add_argument("--json", default="", metavar="PATH",
                    help="write the tuned plan record as JSON")
    ap.add_argument("--fake-cpu", type=int, default=0, metavar="N",
                    help="run on N virtual CPU devices")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from .utils.config import apply_fake_cpu
    apply_fake_cpu(args.fake_cpu)

    import numpy as np

    from .distributed import DistributedDomain
    from .geometry import Radius
    from .tuning import FakeTimer
    from .utils.profiling import autotune_report

    dd = DistributedDomain(args.x, args.y, args.z)
    dd.set_radius(Radius.face_edge_corner(args.fr, args.er, args.cr))
    if args.mesh_shape:
        dd.set_mesh_shape(tuple(_parse_ints(args.mesh_shape)))
    for i in range(args.fields):
        dd.add_data(f"q{i}", np.dtype(args.dtype))

    timer = FakeTimer() if args.fake_timer else None
    plan = dd.autotune(timer=timer,
                       use_cache=not args.no_cache,
                       force=args.force,
                       cache_path=args.cache or None,
                       max_measurements=args.max_measure,
                       depths=tuple(_parse_ints(args.depths)),
                       topology_path=args.topology or None)
    print(autotune_report(plan))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(plan.to_record(), f, indent=2, sort_keys=True)
        print(f"tune: wrote {args.json}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
