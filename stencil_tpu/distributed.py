"""DistributedDomain: the public orchestrator.

TPU-native re-implementation of the reference's DistributedDomain
(reference: include/stencil/stencil.hpp:33-225, src/stencil.cu), the
single class applications talk to:

* configure: ``add_data`` / ``set_radius`` / ``set_methods`` /
  ``set_placement`` / ``set_mesh_shape`` / ``set_output_prefix``
* ``realize()`` — partition the global grid, place subdomains on the
  device mesh, allocate sharded double-buffered padded fields, build the
  jitted exchange program, and emit plan files + byte counters
  (reference: src/stencil.cu:241-850).
* per iteration: ``exchange()`` then ``swap()``
  (reference: src/stencil.cu:1002-1186, local_domain.cu:67-84).
* geometry queries for overlap: ``get_interior`` / ``get_exterior`` /
  ``get_compute_region`` (reference: src/stencil.cu:874-977).
* IO: ``write_paraview`` (reference: src/stencil.cu:1188-1264).

Where the reference plans per-pair transports and polls senders, here
``realize()`` lowers the whole exchange to one XLA SPMD program over a
3D ``jax.sharding.Mesh``; XLA owns scheduling, streams, and the wire.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .geometry import (DepthsLike, Dim3, Dim3Like, Radius, Rect3,
                       normalize_depths)
from .local_domain import (LocalDomain, get_exterior as _dom_exterior,
                           get_interior as _dom_interior, raw_size, zyx_shape)
from .parallel.exchange import (exchanged_bytes_per_sweep, make_exchange,
                                normalize_wire_format)
from .parallel.packing import (irredundant_bytes_per_sweep,
                               normalize_wire_layout)
from .parallel.mesh import make_mesh, mesh_dim
from .parallel.methods import Method, pick_method
from .numerics import div_ceil
from .partition import (RankPartition, exact_partition_candidates,
                        partition_dims_even)
from .placement import (Placement, PlacementStrategy, make_placement,
                        normalize_placement_mode)
from .topology import Boundary, Topology
from .utils.logging import LOG_INFO


class DistributedDomain:
    """Global 3D grid of quantities distributed over a TPU mesh."""

    def __init__(self, x: int, y: int, z: int,
                 devices: Optional[Sequence] = None) -> None:
        self.size = Dim3(x, y, z)
        self._devices = list(devices) if devices is not None else list(jax.devices())
        self.radius = Radius.constant(0)
        self._names: List[str] = []
        self._dtypes: Dict[str, np.dtype] = {}
        self.methods = Method.Default
        self.strategy = PlacementStrategy.NodeAware
        self._mesh_shape: Optional[Dim3] = None
        self._output_prefix = os.environ.get("STENCIL_OUTPUT_PREFIX", "")
        self.boundary = Boundary.PERIODIC
        # temporal blocking: one depth-(s*r) exchange per s steps
        # (communication avoidance; parallel/temporal.py). The
        # allocation pads deepen to s*r so the deep slabs have a home.
        # Depths may be per-axis (exchange_depths) — deep blocking
        # across a DCN axis, per-step exchange on ICI; exchange_every
        # stays the group length max(depths) for the step loop.
        self.exchange_every = 1
        self.exchange_depths = Dim3(1, 1, 1)
        # placement mode: "auto" deploys the QAP assignment on
        # non-uniform fabrics, "trivial"/"qap" force one side
        # (placement.make_placement)
        self.placement_mode = "auto"
        self.alloc_radius = self.radius
        # halo wire format ("f32" | "bf16" | per-axis dict): a
        # narrowing format is certificate-gated at realize() —
        # make_exchange refuses to build unless the precision checker
        # proves the program safe (analysis/precision.py)
        self.wire_format = "f32"
        # halo wire layout ("slab" | "irredundant"): "irredundant"
        # sends every halo cell exactly once (parallel/packing.py) —
        # corner/edge cells ride the first sweep that can carry them
        # instead of every fattened slab that overlaps them
        self.wire_layout = "slab"
        # hierarchical DCN tier (set_dcn_axis); populated by realize()
        self._dcn_requested = False
        self._dcn_axis_req: Optional[int] = None
        self._dcn_axis_planned: Optional[int] = None
        self._dcn_groups = None
        self.dcn_axis: Optional[int] = None
        self.n_slices: int = 1
        # exchange autotuning (stencil_tpu/tuning): the adopted Plan,
        # or None when the static Method priority list decided
        self.plan = None
        # populated by realize()
        self.mesh = None
        self.placement: Optional[Placement] = None
        self.topology: Optional[Topology] = None
        self.local_size: Optional[Dim3] = None
        self.rem = Dim3(0, 0, 0)
        self.curr: Dict[str, jnp.ndarray] = {}
        self.next_: Dict[str, jnp.ndarray] = {}
        self._exchange_fn = None
        self._bytes_per_axis: Dict[str, int] = {}
        self.setup_seconds: Dict[str, float] = {}
        self.exchange_seconds: List[float] = []
        self._timing = False
        # called (with the quantity name) BEFORE set_interior replaces a
        # field, so models holding interior-resident caches can flush
        # them first (models register via on_interior_write)
        self._on_interior_write: List = []

    # ------------------------------------------------------------------
    # configuration (reference: stencil.hpp:134-158)
    # ------------------------------------------------------------------
    def add_data(self, name: str, dtype=jnp.float32) -> str:
        """Register a quantity (reference: stencil.hpp add_data<T>).
        Returns the name as the data handle."""
        assert self.mesh is None, "add_data before realize()"
        assert name not in self._dtypes, f"duplicate quantity {name}"
        self._names.append(name)
        self._dtypes[name] = np.dtype(dtype)
        return name

    def set_radius(self, r: Union[int, Radius]) -> None:
        self.radius = Radius.constant(r) if isinstance(r, int) else r

    def set_methods(self, m: Method) -> None:
        self.methods = m

    def set_placement(self, s: Union[PlacementStrategy, str]) -> None:
        """A :class:`~stencil_tpu.placement.PlacementStrategy` selects
        the placement family (NodeAware/Trivial/IntraNodeRandom); a
        string ``"auto"`` | ``"qap"`` | ``"trivial"`` sets the
        NodeAware placement MODE instead — whether the QAP assignment
        deploys (``"auto"``: only on non-uniform fabrics, the default;
        see ``placement.make_placement``)."""
        if isinstance(s, PlacementStrategy):
            self.strategy = s
        else:
            self.placement_mode = normalize_placement_mode(s)

    def set_mesh_shape(self, shape: Dim3Like) -> None:
        """Explicit subdomain-grid shape (the set_gpus analog —
        reference tests oversubscribe one GPU via set_gpus({0,0}),
        here a 1-device mesh axis plays that role)."""
        self._mesh_shape = Dim3.of(shape)

    def set_output_prefix(self, prefix: str) -> None:
        self._output_prefix = prefix

    def set_boundary(self, b: Boundary) -> None:
        self.boundary = b

    def set_exchange_every(self, s: DepthsLike) -> None:
        """Temporal blocking depth: ``exchange()`` ships a depth-
        ``s * r`` halo once per ``s`` steps instead of a depth-``r``
        halo every step (communication avoidance — ``s``x fewer
        exchange rounds for deeper slabs; see parallel/temporal.py and
        the amortized byte model in analysis/costmodel.py). Allocations
        pad to the deepened radius. The step loop (model layer or
        application) owns calling ``exchange()`` every ``s``-th step
        and consuming one radius ring per sub-step.

        ``s`` may be PER-AXIS (``{"z": 4, "y": 1, "x": 1}``, a
        3-tuple, or a Dim3; see ``geometry.normalize_depths``): deep
        blocking across a slow (DCN) axis while cheap ICI axes keep
        per-step refreshes — the temporal engine exchanges axis ``a``
        every ``s_a`` sub-steps of the ``max(s)``-step group
        (``parallel/temporal.py``). ``exchange_every`` stays the group
        length ``max(s)``. Asymmetric (non-uniform) depths require the
        slab wire layout and the XLA temporal path.

        Note: allocations deepen (and the min-shard feasibility check
        tightens) even if a Pallas fast path later takes the blocking
        depth in-kernel and never runs this deep exchange — the cost
        is ``2*(s-1)*r`` extra halo rows per field per axis."""
        if self.mesh is not None:
            raise RuntimeError("set_exchange_every before realize() — "
                               "the allocation pads and the exchange "
                               "program are already built")
        if isinstance(s, int) and s < 1:
            raise ValueError(f"exchange_every must be >= 1, got {s}")
        depths = normalize_depths(s)
        self.exchange_depths = depths
        self.exchange_every = max(depths)

    def set_wire_format(self, fmt) -> None:
        """Per-axis halo wire format: ``"f32"`` (identity, the
        default), ``"bf16"`` (halos convert to bfloat16 at the send
        boundary and widen back on arrival — wire bytes exactly halve;
        halo MATH is unchanged, every field keeps its storage dtype),
        or a per-axis dict like ``{"x": "bf16"}``. A narrowing format
        only realizes behind a ``safe``
        :class:`~stencil_tpu.analysis.precision.PrecisionCertificate`
        (``realize()`` raises ``PrecisionGateError`` otherwise) and is
        supported by the PpermuteSlab/PpermutePacked methods only."""
        from .parallel.exchange import normalize_wire_format
        assert self.mesh is None, "set_wire_format before realize()"
        normalize_wire_format(fmt)  # validate eagerly, fail at the call
        self.wire_format = fmt

    def set_wire_layout(self, layout: str) -> None:
        """Halo wire message layout: ``"slab"`` (the default — each
        sweep ships the full fattened cross-section, so corner and
        edge cells transit the wire up to three times) or
        ``"irredundant"`` (each direction ships one packed box sized
        so every halo cell crosses the wire exactly once; see
        ``parallel/packing.py``). Same 6 collectives either way —
        only the per-message extent shrinks. Supported by the
        PpermuteSlab/PpermutePacked methods only."""
        assert self.mesh is None, "set_wire_layout before realize()"
        normalize_wire_layout(layout)  # validate eagerly
        self.wire_layout = layout

    def set_dcn_axis(self, axis: Union[int, str, None] = None,
                     groups=None) -> None:
        """Enable the hierarchical node/slice tier (the NodePartition
        analog, reference: partition.hpp:120-256): one grid axis is
        blocked across slices/hosts so only that axis's halo sweep
        crosses the slow DCN while the others ride the ICI.

        ``axis``: 0/'x', 1/'y', 2/'z', or None to derive it from
        ``NodePartition``'s interface-minimizing split. ``groups``
        injects an explicit device grouping (testing; otherwise
        discovered from device slice/process attributes)."""
        assert self.mesh is None, "set_dcn_axis before realize()"
        if isinstance(axis, str):
            axis = {"x": 0, "y": 1, "z": 2, "auto": None}[axis]
        self._dcn_requested = True
        self._dcn_axis_req = axis
        self._dcn_groups = groups

    def enable_timing(self, on: bool = True) -> None:
        """The STENCIL_EXCHANGE_STATS analog — off by default because it
        synchronizes every exchange (reference: bin/jacobi3d.cu:149-153
        warns it distorts benchmarks)."""
        self._timing = on

    # ------------------------------------------------------------------
    # exchange autotuning (stencil_tpu/tuning)
    # ------------------------------------------------------------------
    def autotune(self, timer=None, use_cache: bool = True,
                 force: bool = False, cache_path=None,
                 max_measurements: int = 4, depths=None,
                 overlap_options=(False,), topology_path=None,
                 wire_formats=("f32",), wire_layouts=("slab",)):
        """Measure the live mesh and adopt the fastest exchange plan
        (the measured per-pair transport routing of the reference,
        src/stencil.cu:371-458, as a whole-program decision). Runs the
        measure -> fit -> plan -> cache pipeline of
        :mod:`stencil_tpu.tuning`: a plan-cache hit (same fingerprint:
        topology + mesh + grid + radius + dtypes + quantities +
        library version) skips measurement entirely; ``force=True``
        re-measures and rewrites the cache entry. Call between
        configuration and ``realize()`` — or just set
        ``Method.Auto`` and realize() calls this itself.

        ``timer``: injectable measurement backend (tests/CI use the
        deterministic ``tuning.FakeTimer``; default is the real
        ``tuning.MeshTimer`` over this domain's mesh shape).
        ``topology_path`` (or ``$STENCIL_TOPOLOGY_CACHE``) arms the
        measured topology fingerprint: per-axis link calibrations are
        measured once per fabric and consumed ever after
        (``observatory/linkmap.py``).
        Returns the adopted :class:`stencil_tpu.tuning.Plan`."""
        assert self.mesh is None, "autotune() before realize()"
        assert self._names, "add_data at least one quantity first"
        from .tuning import DEFAULT_DEPTHS, autotune_domain
        plan = autotune_domain(
            self, timer=timer, use_cache=use_cache, force=force,
            cache_path=cache_path,
            depths=DEFAULT_DEPTHS if depths is None else depths,
            overlap_options=overlap_options,
            max_measurements=max_measurements,
            topology_path=topology_path, wire_formats=wire_formats,
            wire_layouts=wire_layouts)
        self.apply_plan(plan)
        return plan

    def apply_plan(self, plan) -> None:
        """Adopt a tuned/cached/pre-baked plan: the winning Method and
        temporal-blocking depth replace the static configuration (a
        fleet can ship a plan file and apply it without measuring).
        ``plan.config.overlap`` is advisory for the model layer
        (``Jacobi3D``/``Astaroth`` ``overlap=``) — the orchestrator's
        own exchange program has no overlap variant."""
        self.methods = Method[plan.config.method]
        depths = getattr(plan.config, "depths", None)
        if depths is not None:
            self.set_exchange_every(tuple(depths))
        elif plan.config.exchange_every != self.exchange_every:
            self.set_exchange_every(plan.config.exchange_every)
        mode = getattr(plan, "placement", "auto")
        if mode != self.placement_mode:
            self.placement_mode = normalize_placement_mode(mode)
        wf = getattr(plan.config, "wire_format", "f32")
        if wf != self.wire_format:
            self.set_wire_format(wf)
        wl = getattr(plan.config, "wire_layout", "slab")
        if wl != self.wire_layout:
            self.set_wire_layout(wl)
        self.plan = plan

    @property
    def plan_provenance(self) -> str:
        """How the exchange configuration was decided: ``tuned``
        (measured this run), ``cached`` (plan-cache hit), or
        ``default`` (static priority list, no autotuner involved)."""
        return self.plan.provenance if self.plan is not None else "default"

    def _discover_dcn_groups(self):
        """DCN tier discovery (reference: partition.hpp:120-256);
        idempotent — sets ``n_slices`` and returns the device groups
        (None when no DCN tier was requested)."""
        if not self._dcn_requested:
            return None
        from .parallel.multihost import slice_groups
        groups = self._dcn_groups or slice_groups(self._devices)
        self.n_slices = len(groups)
        return groups

    def _choose_partition_dim(self) -> Dim3:
        """The subdomain-grid shape realize() will use — factored out
        so the autotuner prices/measures the same partition the
        orchestrator deploys. Also resolves the DCN axis."""
        n = len(self._devices)
        self._discover_dcn_groups()
        if self._mesh_shape is not None:
            dim = self._mesh_shape
            if dim.flatten() != n:
                raise ValueError(f"mesh shape {dim} != device count {n}")
        elif self._dcn_requested and self.n_slices > 1:
            # hierarchical DCN-minimizing split: price every exact
            # (mesh shape x slice-blocked axis) candidate with the
            # per-link cost model so the largest halo cross-sections
            # land on ICI axes and only slice-boundary faces cross DCN
            dim = self._plan_dcn_partition(n)
            if dim is None:
                # no exact candidate admits the slice blocking: the
                # two-level interface-minimizing split (the reference's
                # NodePartition), then the same ladder as the flat path
                from .partition import NodePartition
                npart = NodePartition(self.size, self.radius,
                                      self.n_slices, n // self.n_slices)
                dim = npart.dim()
                if self.size % dim != Dim3(0, 0, 0):
                    try:
                        dim = partition_dims_even(self.size, n)
                    except ValueError:
                        dim = RankPartition(self.size, n).dim()
        else:
            try:
                dim = partition_dims_even(self.size, n)
            except ValueError:
                # no exact factorization: fall back to the reference's
                # greedy split with +-1 remainder subdomains
                dim = RankPartition(self.size, n).dim()
        if self._dcn_requested:
            self.dcn_axis = self._pick_dcn_axis(dim)
        return dim

    def _plan_dcn_partition(self, n: int) -> Optional[Dim3]:
        """The hierarchical partition planner: enumerate every exact
        subdomain-grid factorization of the device count times every
        slice-admissible DCN axis, price each candidate's per-step
        exchange with the per-link alpha-beta model (the configured
        per-axis temporal depths included — deep blocking across the
        DCN axis divides its launch count), and keep the cheapest.
        Returns None when no exact candidate admits the slice blocking
        (``dim[axis] % n_slices == 0``); the chosen axis lands in
        ``_dcn_axis_planned`` for ``_pick_dcn_axis``."""
        from .analysis.costmodel import asymmetric_step_seconds
        elem_sizes = ([self._dtypes[q].itemsize for q in self._names]
                      or [4])
        method = pick_method(self.methods).name
        best = None
        for dim in exact_partition_candidates(self.size, n):
            axes = ([self._dcn_axis_req] if self._dcn_axis_req is not None
                    else range(3))
            for a in axes:
                if dim[a] % self.n_slices != 0:
                    continue
                local = self.size // dim
                seconds = asymmetric_step_seconds(
                    method, (local.z, local.y, local.x), self.radius,
                    dim, elem_sizes, self.exchange_depths, dcn_axis=a,
                    wire_format=self.wire_format,
                    wire_layout=self.wire_layout)
                # deterministic tie-break: cheapest, then most cube-like
                # grid, then lowest axis
                key = (seconds, tuple(sorted(tuple(dim), reverse=True)),
                       tuple(dim), a)
                if best is None or key < best[0]:
                    best = (key, dim, a)
        if best is None:
            return None
        _, dim, axis = best
        self._dcn_axis_planned = axis
        return dim

    def _choose_placement(self, dim: Dim3, groups) -> Placement:
        """The device placement realize() will deploy for ``dim`` —
        factored out so the autotuner times the exact fabric (device
        order on the mesh) the orchestrator ships, not a raw-order
        stand-in (reference: src/stencil.cu:201-239)."""
        part = RankPartition.from_dim(self.size, dim)
        elem_sizes = [self._dtypes[q].itemsize for q in self._names]
        if self._dcn_requested and self.n_slices > 1:
            # two-tier placement: the slice-blocked device order IS the
            # assignment (subdomains along dcn_axis block onto slices);
            # reject contradictory strategy requests rather than
            # silently overriding an experiment's control placement
            if self.strategy != PlacementStrategy.NodeAware:
                raise ValueError(
                    f"placement strategy {self.strategy.value!r} is "
                    f"incompatible with the DCN tier (slice blocking "
                    f"determines the placement)")
            from .parallel.multihost import multihost_device_order
            order = multihost_device_order(dim, self.dcn_axis,
                                           groups=groups)
            return Placement(part, order)
        return make_placement(self.strategy, part, self._devices,
                              self.radius, elem_sizes,
                              mode=self.placement_mode,
                              dcn_axis=self.dcn_axis,
                              n_slices=self.n_slices)

    # ------------------------------------------------------------------
    # realize (reference: src/stencil.cu:241-850)
    # ------------------------------------------------------------------
    def realize(self) -> None:
        assert self._names, "add_data at least one quantity before realize()"
        if Method.Auto in self.methods:
            # the Auto flag is the standing autotune request: resolve
            # it to a concrete transport before any pick_method() use
            self.autotune()
        if self.boundary not in (Boundary.PERIODIC, Boundary.NONE):
            raise NotImplementedError(f"unsupported boundary {self.boundary}")
        if self.boundary == Boundary.NONE and pick_method(self.methods) not \
                in (Method.PpermuteSlab, Method.PpermutePacked):
            raise NotImplementedError(
                "Boundary.NONE (zero-Dirichlet exterior) is supported by "
                "the PpermuteSlab and PpermutePacked methods only")
        wire_narrows = any(v != "f32" for v in
                           normalize_wire_format(self.wire_format).values())
        if wire_narrows and pick_method(self.methods) not in \
                (Method.PpermuteSlab, Method.PpermutePacked):
            raise NotImplementedError(
                f"wire_format {self.wire_format!r} narrows the halo "
                f"wire, supported only by the PpermuteSlab and "
                f"PpermutePacked methods")
        wire_layout = normalize_wire_layout(self.wire_layout)
        if wire_layout != "slab" and pick_method(self.methods) not in \
                (Method.PpermuteSlab, Method.PpermutePacked):
            raise NotImplementedError(
                f"wire_layout {self.wire_layout!r} is supported only "
                f"by the PpermuteSlab and PpermutePacked methods")

        t0 = time.perf_counter()
        # --- DCN tier + partition: choose the subdomain grid -----------
        dim = self._choose_partition_dim()
        groups = self._discover_dcn_groups()
        # per-shard capacity = ceil sizes; uneven shards are one short
        # (reference: partition.hpp:55-69)
        self.local_size = Dim3(*(div_ceil(self.size[a], dim[a])
                                 for a in range(3)))
        self.rem = self.size % dim
        if self.rem != Dim3(0, 0, 0) and pick_method(self.methods) not in \
                (Method.PpermuteSlab, Method.PpermutePacked):
            raise NotImplementedError(
                f"grid {self.size} over mesh {dim} has uneven (+-1) "
                f"subdomains, supported only by the PpermuteSlab and "
                f"PpermutePacked methods")
        # temporal blocking: allocations and the exchange depth come
        # from the DEEPENED radius (one depth-(s_a*r) exchange per axis
        # feeds s_a steps); s == 1 collapses to the base radius
        self.alloc_radius = self.radius.deepened(self.exchange_depths)
        if self.exchange_every > 1 and pick_method(self.methods) not in \
                (Method.PpermuteSlab, Method.PpermutePacked):
            raise NotImplementedError(
                f"exchange_every > 1 is supported by the PpermuteSlab "
                f"and PpermutePacked methods, not "
                f"{pick_method(self.methods)}")
        d = self.exchange_depths
        if not d.x == d.y == d.z and wire_layout != "slab":
            raise NotImplementedError(
                f"asymmetric temporal depths {tuple(d)} decline "
                f"wire_layout {self.wire_layout!r}: the irredundant "
                f"dedup plan assumes one group-wide exchange (see "
                f"parallel/temporal.py)")
        min_local = [self.local_size[a] - (1 if self.rem[a] else 0)
                     for a in range(3)]
        if any(m < 1 for m in min_local):
            raise ValueError(f"zero-extent subdomains: grid {self.size} "
                             f"over mesh {dim}")
        if any(min_local[a] < self.alloc_radius.face(a, 1) or
               min_local[a] < self.alloc_radius.face(a, -1)
               for a in range(3)):
            raise ValueError(f"subdomain {min_local} smaller than "
                             f"(deepened) radius {self.alloc_radius}")
        self.setup_seconds["partition"] = time.perf_counter() - t0

        # --- placement (reference: src/stencil.cu:201-239) -------------
        t0 = time.perf_counter()
        self.placement = self._choose_placement(dim, groups)
        self.topology = Topology(dim, self.boundary)
        self.setup_seconds["placement"] = time.perf_counter() - t0

        # --- mesh + allocation (reference: src/stencil.cu:249-272) -----
        t0 = time.perf_counter()
        self.mesh = make_mesh(dim, self.placement.device_order_for_mesh())
        padded_local = raw_size(self.local_size, self.alloc_radius)
        global_padded = padded_local * dim
        sharding = NamedSharding(self.mesh, P("z", "y", "x"))
        self._padded_global = global_padded
        for q in self._names:
            shape = zyx_shape(global_padded)
            dt = self._dtypes[q]
            self.curr[q] = jax.device_put(jnp.zeros(shape, dtype=dt), sharding)
        # next_ buffers allocate lazily on first swap(): fused-step apps
        # (Jacobi3D) double-buffer via jit donation and never touch them,
        # which halves field HBM at benchmark sizes
        self.next_ = {}
        self.setup_seconds["realize"] = time.perf_counter() - t0

        # --- plan: build the exchange program --------------------------
        # the DEEP exchange: wire depth s*r, once per s steps (s == 1 is
        # the ordinary per-step exchange). Byte counters price the deep
        # slabs; exchange_bytes_amortized_per_step() divides by s.
        t0 = time.perf_counter()
        wire_kw = {}
        if wire_narrows:
            # the precision gate: make_exchange traces the exchange
            # over these specs, runs checker 13, and REFUSES to build
            # (PrecisionGateError) unless the certificate is safe
            wire_kw = dict(
                wire_format=self.wire_format,
                fields_spec={q: jax.ShapeDtypeStruct(
                    zyx_shape(global_padded), self._dtypes[q])
                    for q in self._names})
        self._exchange_fn = make_exchange(
            self.mesh, self.alloc_radius, self.methods, rem=self.rem,
            nonperiodic=self.boundary == Boundary.NONE,
            wire_layout=wire_layout, **wire_kw)
        counts = mesh_dim(self.mesh)
        self._bytes_per_axis = {"x": 0, "y": 0, "z": 0}
        for q in self._names:
            if wire_layout == "irredundant":
                b = irredundant_bytes_per_sweep(
                    zyx_shape(padded_local), self.alloc_radius, counts,
                    self._dtypes[q].itemsize,
                    wire_format=self.wire_format)
            else:
                b = exchanged_bytes_per_sweep(
                    zyx_shape(padded_local), self.alloc_radius, counts,
                    self._dtypes[q].itemsize,
                    wire_format=self.wire_format)
            for k in b:
                self._bytes_per_axis[k] += b[k]
        self.setup_seconds["plan"] = time.perf_counter() - t0

        if self._output_prefix:
            self._write_plan()
        dcn = (f", dcn axis {'xyz'[self.dcn_axis]}x{self.n_slices}"
               if self.dcn_axis is not None and self.n_slices > 1 else "")
        LOG_INFO(f"realized {self.size} over mesh {dim} "
                 f"(local {self.local_size}, padded {padded_local}, "
                 f"method {pick_method(self.methods)}{dcn})")

    def _pick_dcn_axis(self, dim: Dim3) -> int:
        """The mesh axis blocked across slices: the requested one
        (validated), else the axis NodePartition's interface rule would
        cut — approximated as the divisible axis with the smallest
        interface area (fewest DCN bytes)."""
        ns = self.n_slices
        if self._dcn_axis_req is not None:
            a = self._dcn_axis_req
            if ns > 1 and dim[a] % ns != 0:
                raise ValueError(f"dcn axis {a} has {dim[a]} mesh rows, "
                                 f"not divisible by {ns} slices")
            return a
        if self._dcn_axis_planned is not None \
                and (ns <= 1 or dim[self._dcn_axis_planned] % ns == 0):
            # the hierarchical planner already priced the axis jointly
            # with the mesh shape
            return self._dcn_axis_planned
        cands = [a for a in range(3) if ns <= 1 or dim[a] % ns == 0]
        if not cands:
            raise ValueError(f"no mesh axis of {dim} divisible by "
                             f"{ns} slices; set_mesh_shape or "
                             f"set_dcn_axis explicitly")
        sizes = [self.size.x, self.size.y, self.size.z]

        def iface(a):
            other = [sizes[b] for b in range(3) if b != a]
            return other[0] * other[1]

        return min(cands, key=iface)

    # ------------------------------------------------------------------
    # iteration hot path
    # ------------------------------------------------------------------
    def exchange(self) -> None:
        """Fill all halos of all quantities' *curr* buffers
        (reference: src/stencil.cu:1002-1186 — pack/send/poll/unpack
        collapse into one jitted SPMD program)."""
        assert self._exchange_fn is not None, "realize() first"
        if self._timing:
            from .utils.timers import device_sync
            t0 = time.perf_counter()
            out = self._exchange_fn(self.curr)
            device_sync(out)
            self.exchange_seconds.append(time.perf_counter() - t0)
            self.curr = dict(out)
        else:
            self.curr = dict(self._exchange_fn(self.curr))

    def make_segment(self, shard_step, check_every: int,
                     probe_every: int = 1, metrics=None):
        """Fuse ``check_every`` applications of ``shard_step`` (per
        shard: ``fields -> fields`` over the padded quantity dict) plus
        the in-graph health probe into ONE compiled program — the
        megastep (``parallel/megastep.py``). The returned
        :class:`~stencil_tpu.parallel.megastep.Segment` advances
        ``curr`` in place per ``run()`` and hands back the stacked
        per-step probe trace; state is donated end-to-end. ``metrics``
        (a :class:`~stencil_tpu.telemetry.probe.StepMetrics`) rides the
        telemetry counters on the probe rows."""
        assert self._exchange_fn is not None, "realize() first"
        from .parallel.megastep import make_domain_segment
        return make_domain_segment(self, shard_step, check_every,
                                   probe_every=probe_every,
                                   metrics=metrics)

    def swap(self) -> None:
        """Swap curr/next bindings (reference: src/local_domain.cu:67-84).
        next_ buffers are created on first use."""
        if not self.next_ and self._names:
            sharding = NamedSharding(self.mesh, P("z", "y", "x"))
            shape = zyx_shape(self._padded_global)
            self.next_ = {q: jax.device_put(
                jnp.zeros(shape, dtype=self._dtypes[q]), sharding)
                for q in self._names}
        self.curr, self.next_ = self.next_, self.curr

    # ------------------------------------------------------------------
    # geometry queries (reference: src/stencil.cu:874-977)
    # ------------------------------------------------------------------
    def num_subdomains(self) -> int:
        return self.placement.dim().flatten() if self.placement else 0

    def domain_view(self, i: int) -> LocalDomain:
        """Geometry-only LocalDomain for subdomain with linear id ``i``
        (no separate allocation — data lives in the sharded globals)."""
        idx = self.placement.part.dimensionize(i)
        dom = LocalDomain(self.placement.subdomain_size(idx),
                          self.placement.subdomain_origin(idx), self.radius)
        for q in self._names:
            dom.add_data(q, self._dtypes[q])
        return dom

    def get_interior(self) -> List[Rect3]:
        """Per-subdomain interior regions whose stencil reads never
        touch halos — safe to compute while the exchange is in flight."""
        return [_dom_interior(self.domain_view(i))
                for i in range(self.num_subdomains())]

    def get_exterior(self) -> List[List[Rect3]]:
        return [_dom_exterior(self.domain_view(i))
                for i in range(self.num_subdomains())]

    def get_compute_region(self) -> Rect3:
        return Rect3(Dim3(0, 0, 0), self.size)

    # ------------------------------------------------------------------
    # observability (reference: src/stencil.cu:482-637, stencil.hpp:86-93)
    # ------------------------------------------------------------------
    def exchange_bytes_per_axis(self) -> Dict[str, int]:
        """Bytes one shard puts on the ICI per exchange, per mesh axis
        (the per-method byte-counter analog). Wire-format and
        wire-layout aware: a bf16 axis reports its on-wire (halved)
        bytes; the irredundant layout reports its slimmer boxes."""
        return dict(self._bytes_per_axis)

    @property
    def precision_certificate(self):
        """The :class:`~stencil_tpu.analysis.precision.
        PrecisionCertificate` the realize()-time gate proved for this
        domain's exchange program — None before realize() and on the
        identity (all-f32) wire path, where no gate runs."""
        return getattr(self._exchange_fn, "precision_certificate", None)

    def exchange_bytes_total(self) -> int:
        """Total cross-device bytes per exchange over the whole mesh
        (the DEEP exchange when ``exchange_every > 1``)."""
        counts = mesh_dim(self.mesh)
        return sum(v * counts.flatten() for v in self._bytes_per_axis.values())

    def exchange_bytes_amortized_per_step(self) -> float:
        """Whole-mesh wire bytes per STEP under temporal blocking: the
        deep exchange's bytes spread over the ``exchange_every`` steps
        it feeds (== ``exchange_bytes_total()`` when s == 1). Per-axis
        depths amortize each axis over ITS OWN refresh period — axis
        ``a`` re-ships its deep slab every ``s_a`` steps
        (``parallel.temporal.refresh_axes``). The runtime face of the
        amortized model in analysis/costmodel.py."""
        d = self.exchange_depths
        if d.x == d.y == d.z:
            return self.exchange_bytes_total() / self.exchange_every
        counts = mesh_dim(self.mesh)
        return sum(self._bytes_per_axis[name] * counts.flatten() / d[a]
                   for a, name in ((0, "x"), (1, "y"), (2, "z")))

    def exchange_bytes_dcn(self) -> int:
        """Bytes per exchange crossing the DCN tier, whole mesh: along
        the DCN axis, ``n_slices`` of the ``counts[axis]`` periodic
        shard boundaries are inter-slice (the reference's inter-node
        byte counters, stencil.hpp:86-93)."""
        if self.dcn_axis is None or self.n_slices <= 1:
            return 0
        counts = mesh_dim(self.mesh)
        c = counts[self.dcn_axis]
        per_shard = self._bytes_per_axis["xyz"[self.dcn_axis]]
        return per_shard * counts.flatten() * self.n_slices // c

    def exchange_bytes_ici(self) -> int:
        """Bytes per exchange staying on the intra-slice ICI."""
        return self.exchange_bytes_total() - self.exchange_bytes_dcn()

    def _write_plan(self) -> None:
        """Emit plan file + communication matrix (reference:
        src/stencil.cu:482-637: plan_<rank>.txt and the rank x rank
        matrix in numpy.loadtxt format)."""
        prefix = self._output_prefix
        dim = self.placement.dim()
        n = dim.flatten()
        with open(f"{prefix}plan.txt", "w") as f:
            f.write(f"global size: {self.size}\n")
            f.write(f"mesh: {dim}\n")
            f.write(f"local size: {self.local_size}\n")
            f.write(f"method: {pick_method(self.methods)}\n")
            # where the exchange configuration came from (reference
            # plan files record the routed transport per message; the
            # autotuner analog records the decision's provenance)
            f.write(f"plan provenance: {self.plan_provenance}\n")
            if self.plan is not None:
                f.write(f"plan fingerprint: {self.plan.fingerprint}\n")
                f.write(f"plan config: {self.plan.config.key()}\n")
                f.write(f"plan measurements: {self.plan.measurements}\n")
            f.write(f"exchange_every: {self.exchange_every}\n")
            d = self.exchange_depths
            if not d.x == d.y == d.z:
                f.write(f"exchange_depths: {d.x}.{d.y}.{d.z}\n")
            f.write(f"placement mode: {self.placement_mode}\n")
            f.write(f"wire_layout: {self.wire_layout}\n")
            f.write(f"quantities: {self._names}\n")
            for i in range(n):
                idx = self.placement.part.dimensionize(i)
                dev = self.placement.get_device(idx)
                f.write(f"subdomain {i} idx {idx} -> device {dev}\n")
            for axis, b in self._bytes_per_axis.items():
                f.write(f"bytes per shard per exchange, axis {axis}: {b}\n")
            # per-message lines: subdomain -> neighbor, direction, bytes
            # (reference: src/stencil.cu:523-637 emits one line per
            # planned message)
            from .placement import iter_messages
            elem = [self._dtypes[q].itemsize for q in self._names]
            # per-message bytes price what the wire actually moves: the
            # deepened slabs under temporal blocking (== radius at s=1),
            # consistent with the per-axis counters above
            for i, j, d, nbytes in iter_messages(
                    self.placement.part, self.alloc_radius, elem,
                    self.topology):
                f.write(f"message {i} -> {j} dir "
                        f"({d.x},{d.y},{d.z}): {nbytes} B\n")
            if self.dcn_axis is not None and self.n_slices > 1:
                f.write(f"dcn axis: {'xyz'[self.dcn_axis]} "
                        f"({self.n_slices} slices)\n")
                f.write(f"bytes per exchange over DCN (whole mesh): "
                        f"{self.exchange_bytes_dcn()}\n")
                f.write(f"bytes per exchange over ICI (whole mesh): "
                        f"{self.exchange_bytes_ici()}\n")
        from .placement import comm_bytes_matrix
        w = comm_bytes_matrix(self.placement.part, self.alloc_radius,
                              [self._dtypes[q].itemsize
                               for q in self._names], self.topology)
        np.savetxt(f"{prefix}comm_matrix.txt", w, fmt="%d")

    # ------------------------------------------------------------------
    # checkpointing (utils/checkpoint.py keeps one cached
    # CheckpointManager per directory; the save loop of a long campaign
    # reuses it instead of paying construct/close churn every save)
    # ------------------------------------------------------------------
    def close_checkpoints(self) -> None:
        """Release the cached checkpoint managers for every directory
        this domain saved to or restored from (also runs atexit; call
        explicitly when a campaign rotates checkpoint directories)."""
        from .utils.checkpoint import close_checkpoints
        for d in getattr(self, "_ckpt_dirs", ()):
            close_checkpoints(d)

    # ------------------------------------------------------------------
    # IO (reference: src/stencil.cu:1188-1264)
    # ------------------------------------------------------------------
    def interior_to_host(self, name: str) -> np.ndarray:
        """Assemble the full global interior (z,y,x-ordered) on host by
        stripping per-shard halo padding."""
        return self.assemble_interior(np.asarray(self.curr[name]))

    def assemble_interior(self, host: np.ndarray) -> np.ndarray:
        """Strip per-shard halo padding from a host copy of ANY
        padded-global array laid out like this domain's fields (the
        ensemble serving layer reads member lanes through this without
        routing them through ``curr``)."""
        dim = self.placement.dim()
        pr = raw_size(self.local_size, self.alloc_radius)
        lo = self.alloc_radius.pad_lo()
        out = np.empty(zyx_shape(self.size), dtype=host.dtype)
        for bz in range(dim.z):
            for by in range(dim.y):
                for bx in range(dim.x):
                    idx = Dim3(bx, by, bz)
                    sz = self.placement.subdomain_size(idx)
                    org = self.placement.subdomain_origin(idx)
                    blk = host[bz * pr.z + lo.z: bz * pr.z + lo.z + sz.z,
                               by * pr.y + lo.y: by * pr.y + lo.y + sz.y,
                               bx * pr.x + lo.x: bx * pr.x + lo.x + sz.x]
                    out[org.z:org.z + sz.z,
                        org.y:org.y + sz.y,
                        org.x:org.x + sz.x] = blk
        return out

    def on_interior_write(self, cb) -> None:
        """Register a callback invoked before ``set_interior`` writes —
        the hook models use to keep interior-resident fast-path caches
        coherent (flush-then-invalidate)."""
        self._on_interior_write.append(cb)

    def set_interior(self, name: str, values: np.ndarray) -> None:
        """Scatter a global (z,y,x) interior array into the sharded
        padded field (initial conditions)."""
        for cb in self._on_interior_write:
            cb(name)
        assert tuple(values.shape) == zyx_shape(self.size)
        dim = self.placement.dim()
        pr = raw_size(self.local_size, self.alloc_radius)
        lo = self.alloc_radius.pad_lo()
        host = np.zeros(zyx_shape(pr * dim), dtype=self._dtypes[name])
        for bz in range(dim.z):
            for by in range(dim.y):
                for bx in range(dim.x):
                    idx = Dim3(bx, by, bz)
                    sz = self.placement.subdomain_size(idx)
                    org = self.placement.subdomain_origin(idx)
                    host[bz * pr.z + lo.z: bz * pr.z + lo.z + sz.z,
                         by * pr.y + lo.y: by * pr.y + lo.y + sz.y,
                         bx * pr.x + lo.x: bx * pr.x + lo.x + sz.x] = \
                        values[org.z:org.z + sz.z,
                               org.y:org.y + sz.y,
                               org.x:org.x + sz.x]
        sharding = NamedSharding(self.mesh, P("z", "y", "x"))
        self.curr[name] = jax.device_put(jnp.asarray(host), sharding)

    def write_paraview(self, prefix: str) -> None:
        """CSV dumps, one file per subdomain, rows ``Z,Y,X,q0,...``
        (reference: src/stencil.cu:1188-1264). Vectorized: the rows are
        assembled as one numpy table per subdomain (a per-cell Python
        loop is ~134M iterations at 512^3)."""
        interiors = {q: self.interior_to_host(q) for q in self._names}
        for i in range(self.num_subdomains()):
            idx = self.placement.part.dimensionize(i)
            org = self.placement.subdomain_origin(idx)
            sz = self.placement.subdomain_size(idx)
            gz, gy, gx = np.meshgrid(
                np.arange(org.z, org.z + sz.z),
                np.arange(org.y, org.y + sz.y),
                np.arange(org.x, org.x + sz.x), indexing="ij")
            cols = [gz.ravel(), gy.ravel(), gx.ravel()]
            # bfloat16 (ml_dtypes) cannot promote against the int64
            # index columns in column_stack; widen to f32 for the dump
            cols += [np.asarray(
                interiors[q][org.z:org.z + sz.z,
                             org.y:org.y + sz.y,
                             org.x:org.x + sz.x].ravel(),
                dtype=np.float32 if self._dtypes[q].itemsize < 4
                else self._dtypes[q])
                     for q in self._names]
            table = np.column_stack(cols)
            header = "Z,Y,X," + ",".join(self._names)
            # shortest value-roundtrip float format per quantity dtype
            fmt = ["%d", "%d", "%d"] + [
                "%.17g" if self._dtypes[q].itemsize > 4 else "%.9g"
                for q in self._names]
            np.savetxt(f"{prefix}{i}.txt", table, fmt=fmt, delimiter=",",
                       header=header, comments="")
