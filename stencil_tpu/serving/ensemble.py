"""Batched ensembles: one compiled executable, N independent runs.

The member axis is a leading, UNSHARDED dimension: fields are
``(n_members, Zp, Yp, Xp)`` sharded ``P(None, 'z', 'y', 'x')``, and the
per-shard step functions of :mod:`..models.jacobi` /
:mod:`..models.astaroth` are ``jax.vmap``-ped over it inside the same
``shard_map`` the single-member solvers use. Two properties fall out of
the vmap batching rules and are pinned by the ``serving.ensemble.*``
stencil-lint registry targets:

* the halo exchange lowers to the SAME number of collective-permutes
  as one member (6 for the radius-1 slab sweep) — the batch rides each
  permute, it does not multiply dispatches;
* the wire bytes are exactly ``n_members`` x the single-member analytic
  model (the costmodel checker cross-checks the lowered HLO).

Per-member parameters (Jacobi hot/cold Dirichlet temperatures, MHD
physics coefficients) enter as ``(n_members,)`` runtime arrays — NOT
baked constants — so a service can re-dispatch the same compiled
executable for every fingerprint-compatible request batch with zero
recompiles.

Health is per member: :func:`make_ensemble_probe` vmaps the
:func:`..resilience.health.probe_shard` reduction, producing a
``(n_members, 2, n_quantities)`` stats tensor with still exactly ONE
small all-reduce; :class:`EnsembleSentinel` evaluates the divergence
predicate per member, so one member's NaN trips only that member.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..distributed import DistributedDomain
from ..geometry import Dim3, Radius
from ..local_domain import zyx_shape
from ..parallel.exchange import dispatch_exchange, shard_origin
from ..parallel.mesh import mesh_dim
from ..parallel.methods import Method, pick_method
from ..resilience.health import ROW_MAX_ABS, ROW_NONFINITE, HealthStats, \
    _is_ready, probe_shard
from ..utils.checkpoint import (CorruptCheckpointError, all_steps,
                                array_digest, restore_state, save_state,
                                verify_digests)
from ..utils.logging import LOG_INFO, LOG_WARN

#: batched field sharding: member axis replicated, space sharded
ENSEMBLE_SPEC = P(None, "z", "y", "x")


# ---------------------------------------------------------------------------
# problem identity (shared by queue admission and engine construction)


def configured_domain(model: str, grid: Sequence[int], dtype=jnp.float32,
                      methods: Method = Method.Default, boundary=None,
                      mesh_shape=None, devices=None) -> DistributedDomain:
    """A configured (NOT realized) domain for ``model`` — the single
    source of the quantity set / radius / mesh choice, so the queue's
    admission fingerprint and the engine's compiled program can never
    disagree about problem identity."""
    x, y, z = (int(v) for v in grid)
    dd = DistributedDomain(x, y, z, devices=devices)
    if model == "jacobi":
        dd.set_radius(1)
        dd.add_data("temp", dtype)
    elif model == "astaroth":
        from ..models.astaroth import FIELDS
        from ..ops.fd6 import RADIUS
        dd.set_radius(Radius.constant(RADIUS))
        for q in FIELDS:
            dd.add_data(q, dtype)
    else:
        raise ValueError(f"unknown ensemble model {model!r} "
                         f"(jacobi|astaroth)")
    dd.set_methods(methods)
    if boundary is not None:
        dd.set_boundary(boundary)
    if mesh_shape is not None:
        dd.set_mesh_shape(mesh_shape)
    return dd


def domain_fingerprint(dd: DistributedDomain) -> str:
    """The :mod:`..tuning` problem fingerprint of a configured domain —
    the admission key: requests sharing it share a compiled executable
    AND a cached exchange plan."""
    from ..tuning import fingerprint, inputs_from_domain
    return fingerprint(inputs_from_domain(dd, dd._choose_partition_dim()))


# ---------------------------------------------------------------------------
# per-member health


def make_ensemble_probe(mesh, names: Sequence[str]):
    """The jitted per-member probe: ``fn(batched_fields) ->
    (n_members, 2, len(names))`` replicated f32 stats. The vmapped
    ``pmax`` still lowers to exactly ONE small all-reduce (pinned by
    the ``serving.ensemble.probe[hlo]`` registry target)."""
    names = list(names)
    spec = {q: ENSEMBLE_SPEC for q in names}

    def shard(fields):
        return jax.vmap(
            lambda f: probe_shard({q: f[q] for q in names}))(fields)

    sm = jax.shard_map(shard, mesh=mesh, in_specs=(spec,),
                       out_specs=P(), check_vma=False)
    return jax.jit(sm)


@dataclasses.dataclass
class EnsembleHealth:
    """One harvested per-member probe: ``members[k]`` is member k's
    :class:`~..resilience.health.HealthStats` at ``step``."""

    step: int
    members: List[HealthStats]

    @property
    def tripped_members(self) -> List[int]:
        return [k for k, s in enumerate(self.members) if s.tripped]


class EnsembleSentinel:
    """Per-member watchdog over an ensemble engine: ``probe(step)``
    enqueues the batched on-device reduction (async), ``poll()``
    harvests ready results and evaluates the divergence predicate
    independently per member — a NaN in member k trips member k and
    nobody else. ``reset_member(k)`` forgets k's history after the
    service rolls that campaign back (other members' histories and
    verdicts are untouched)."""

    def __init__(self, engine, window: int = 8,
                 growth_factor: float = 1e6) -> None:
        self.engine = engine
        self.names = list(engine.dd._names)
        self.window = int(window)
        self.growth_factor = float(growth_factor)
        self._pending: Deque[Tuple[int, jnp.ndarray]] = deque()
        self._history: List[Dict[str, Deque[float]]] = [
            {q: deque(maxlen=self.window) for q in self.names}
            for _ in range(engine.n_members)]

    def probe(self, step: int) -> None:
        self._pending.append(
            (step, self.engine._probe_fn(dict(self.engine.state))))

    def observe_segment(self, trace, steps) -> None:
        """Enqueue a fused-segment per-member probe trace
        (``run_segment``): row ``j`` is the batched probe of member
        step ``steps[j]``; ``poll`` expands the rows oldest first."""
        self._pending.append((tuple(int(s) for s in steps), trace))

    def poll(self, block: bool = False) -> List[EnsembleHealth]:
        out: List[EnsembleHealth] = []
        while self._pending:
            step, arr = self._pending[0]
            if not block and not _is_ready(arr):
                break
            self._pending.popleft()
            host = np.asarray(arr)
            if isinstance(step, tuple):
                for j, s in enumerate(step):
                    out.append(self._evaluate(s, host[j]))
            else:
                out.append(self._evaluate(step, host))
        return out

    def reset_member(self, k: int) -> None:
        for h in self._history[k].values():
            h.clear()

    def reset(self) -> None:
        self._pending.clear()
        for k in range(len(self._history)):
            self.reset_member(k)

    def _evaluate(self, step: int, host: np.ndarray) -> EnsembleHealth:
        members: List[HealthStats] = []
        for k in range(host.shape[0]):
            nonfinite = {q: int(host[k, ROW_NONFINITE, i])
                         for i, q in enumerate(self.names)}
            max_abs = {q: float(host[k, ROW_MAX_ABS, i])
                       for i, q in enumerate(self.names)}
            stats = HealthStats(step, nonfinite, max_abs)
            bad = [q for q, n in nonfinite.items() if n > 0]
            if bad:
                stats.tripped = True
                stats.reason = (f"member {k}: non-finite cells in {bad} "
                                f"({ {q: nonfinite[q] for q in bad} })")
            else:
                grown = []
                for q in self.names:
                    hist = self._history[k][q]
                    if hist:
                        baseline = min(hist)
                        if baseline > 0 and \
                                max_abs[q] > self.growth_factor * baseline:
                            grown.append(q)
                if grown:
                    stats.tripped = True
                    stats.reason = (f"member {k}: max-abs grew more "
                                    f"than x{self.growth_factor:g} "
                                    f"over the window for {grown}")
                else:
                    for q in self.names:
                        self._history[k][q].append(max_abs[q])
            members.append(stats)
        return EnsembleHealth(step, members)


# ---------------------------------------------------------------------------
# the engines


class _EnsembleBase:
    """Shared machinery of the batched engines: the domain, the batched
    state allocation, lane get/set, per-member parameters, snapshots,
    and per-member checkpoint save/restore."""

    MODEL = ""
    #: per-member runtime parameters, in the order the step consumes
    PARAM_NAMES: Tuple[str, ...] = ()

    def __init__(self, n_members: int, x: int, y: int, z: int,
                 dtype=jnp.float32, devices=None,
                 methods: Method = Method.Default, boundary=None,
                 mesh_shape=None, plan=None) -> None:
        if int(n_members) < 1:
            raise ValueError(f"n_members must be >= 1, got {n_members}")
        self.n_members = int(n_members)
        self.dd = configured_domain(self.MODEL, (x, y, z), dtype=dtype,
                                    methods=methods, boundary=boundary,
                                    mesh_shape=mesh_shape,
                                    devices=devices)
        #: the admission/plan-cache key (computed pre-realize)
        self.fingerprint = domain_fingerprint(self.dd)
        if plan is not None:
            # adopt the plan's transport; temporal blocking depths are
            # a single-run optimization the batched step does not take
            self.dd.set_methods(Method[plan.config.method])
            if plan.config.exchange_every != 1:
                LOG_INFO(f"ensemble engine ignores plan depth "
                         f"s={plan.config.exchange_every} (batched "
                         f"steps exchange every step)")
            self.dd.plan = plan
        self.dd.realize()
        self._dtype = np.dtype(dtype)
        self.names: List[str] = list(self.dd._names)
        self._batched_sharding = NamedSharding(self.dd.mesh, ENSEMBLE_SPEC)
        self._lane_shape = tuple(zyx_shape(self.dd._padded_global))
        #: batched padded fields: name -> (n_members, Zp, Yp, Xp)
        self.state: Dict[str, jnp.ndarray] = {
            q: self._zeros_batched() for q in self.names}
        self._params: Dict[str, np.ndarray] = {
            p: np.full(self.n_members, v, dtype=np.float64)
            for p, v in self.default_params().items()}
        self._probe_fn = make_ensemble_probe(self.dd.mesh, self.names)
        self._build_lane_ops()
        self._build_step()

    # -- subclass contract ---------------------------------------------
    def default_params(self) -> Dict[str, float]:
        raise NotImplementedError

    def _build_step(self) -> None:
        raise NotImplementedError

    def run(self, n_steps: int) -> None:
        """Advance ALL members ``n_steps`` steps in one dispatch."""
        raise NotImplementedError

    def run_segment(self, n_steps: int, probe_every: int = 1):
        """Advance ALL members ``n_steps`` steps AND carry the
        per-member health probe in-graph — one fused dispatch
        (``parallel/megastep.py``) whose returned
        :class:`~..parallel.megastep.SegmentTrace` stacks a
        ``(n_members, 2, n_quantities)`` probe row every
        ``probe_every`` steps (the vmapped reduction is still ONE
        small all-reduce per row). State is donated end-to-end."""
        raise NotImplementedError

    # -- allocation / lane plumbing ------------------------------------
    def _zeros_batched(self) -> jnp.ndarray:
        return jax.device_put(
            jnp.zeros((self.n_members,) + self._lane_shape,
                      dtype=self._dtype), self._batched_sharding)

    def _build_lane_ops(self) -> None:
        def get_lane(state, k):
            return {q: lax.dynamic_index_in_dim(state[q], k, axis=0,
                                                keepdims=False)
                    for q in state}

        self._get_lane = jax.jit(get_lane)

        def set_lane(state, lane, k):
            zero = jnp.zeros((), dtype=jnp.asarray(k).dtype)
            out = {}
            for q in state:
                if q in lane:
                    out[q] = lax.dynamic_update_slice(
                        state[q],
                        lane[q][None].astype(state[q].dtype),
                        (k, zero, zero, zero))
                else:
                    out[q] = state[q]
            return out

        self._set_lane = jax.jit(set_lane, donate_argnums=0)

    def _replicated(self, value) -> jnp.ndarray:
        """A replicated device array built via an EXPLICIT transfer
        (``jax.device_put`` with the mesh sharding) so segment
        dispatches stay clean under the hot-loop
        ``jax.transfer_guard("disallow")`` — an implicit scalar lift
        would both trip the guard and reshard at dispatch."""
        return jax.device_put(np.asarray(value, dtype=self._dtype),
                              NamedSharding(self.dd.mesh, P()))

    def _param_args(self) -> Tuple[jnp.ndarray, ...]:
        return tuple(self._replicated(self._params[p])
                     for p in self.PARAM_NAMES)

    def jit_entry_points(self) -> Dict[str, object]:
        """The hot-path jitted programs a recompile watchdog
        (:class:`~..analysis.recompile.SingleCompileGuard`) observes
        after each dispatch: the step loop and every built segment."""
        out: Dict[str, object] = {}
        for attr, label in (("_step_n", "step_n"), ("_iter_n", "iter_n")):
            fn = getattr(self, attr, None)
            if fn is not None:
                out[label] = fn
        for (k, p), fn in getattr(self, "_segments", {}).items():
            out[f"segment[k={k},probe_every={p}]"] = fn
        return out

    # -- per-member parameters -----------------------------------------
    def set_member_params(self, k: int, overrides: Dict[str, float]
                          ) -> None:
        for name, v in overrides.items():
            if name not in self._params:
                raise KeyError(
                    f"unknown ensemble parameter {name!r} for "
                    f"{self.MODEL} (have {sorted(self._params)})")
            self._params[name][k] = float(v)

    def member_params(self, k: int) -> Dict[str, float]:
        return {p: float(a[k]) for p, a in self._params.items()}

    # -- member state access -------------------------------------------
    def set_member_interior(self, name: str, k: int,
                            values: np.ndarray) -> None:
        """Scatter a global (z,y,x) interior into member ``k``'s lane
        of quantity ``name`` (initial conditions / restore)."""
        self.dd.set_interior(name, np.asarray(values, dtype=self._dtype))
        self.state = self._set_lane(self.state,
                                    {name: self.dd.curr[name]},
                                    jnp.int32(k))

    def member_interior(self, name: str, k: int) -> np.ndarray:
        """Member ``k``'s global interior of ``name`` on host
        (blocking)."""
        lane = self._get_lane(dict(self.state), jnp.int32(k))[name]
        return self.dd.assemble_interior(np.asarray(lane))

    def member_interiors(self, k: int) -> Dict[str, np.ndarray]:
        """All of member ``k``'s global interiors on host with ONE
        lane gather (checkpoints and completions want every quantity —
        per-quantity :meth:`member_interior` calls would re-slice the
        whole lane set each time)."""
        lanes = self._get_lane(dict(self.state), jnp.int32(k))
        return {q: self.dd.assemble_interior(np.asarray(v))
                for q, v in lanes.items()}

    def member_snapshot_async(self, k: int, step: int
                              ) -> "EnsembleSnapshot":
        """Enqueue a snapshot of member ``k``: the lane slice rides the
        device queue; poll :meth:`EnsembleSnapshot.ready` and call
        :meth:`~EnsembleSnapshot.get` once true — the step pipeline is
        never stalled by readback."""
        lanes = self._get_lane(dict(self.state), jnp.int32(k))
        return EnsembleSnapshot(self, k, step, lanes)

    def reset_member(self, k: int) -> None:
        """Benign (zero) state + default parameters for lane ``k`` —
        idle lanes of a partially-filled service batch, and poisoned
        lanes of failed campaigns, must not trip the sentinel."""
        zero = {q: jnp.zeros(self._lane_shape, dtype=self._dtype)
                for q in self.names}
        self.state = self._set_lane(self.state, zero, jnp.int32(k))
        for p, v in self.default_params().items():
            self._params[p][k] = v

    # -- per-member checkpoints (hardened layer) -----------------------
    def _member_extra_arrays(self, k: int) -> Dict[str, jnp.ndarray]:
        """Model-specific auxiliary state to checkpoint with a lane
        (the Astaroth RK accumulator)."""
        return {}

    def _member_extra_targets(self) -> Dict[str, jax.ShapeDtypeStruct]:
        """Restore targets (shape/dtype only) for the extras — no
        device gather, just the contract."""
        return {}

    def _restore_member_extras(self, k: int,
                               extras: Dict[str, jnp.ndarray]) -> None:
        pass

    def save_member(self, directory: str, step: int, k: int,
                    meta_extra: Optional[Dict] = None,
                    max_to_keep: Optional[int] = 3) -> None:
        """Checkpoint member ``k`` at campaign step ``step`` into
        ``directory`` (a tenant-namespace path): mesh-independent
        interiors + sha256 integrity digests in the meta record,
        through the retrying :func:`..utils.checkpoint.save_state`."""
        arrays: Dict[str, jnp.ndarray] = {
            q: jnp.asarray(v)
            for q, v in self.member_interiors(k).items()}
        for name, v in self._member_extra_arrays(k).items():
            arrays[f"extra:{name}"] = v
        meta = {"size": list(self.dd.size),
                "quantities": self.names,
                "dtypes": {q: str(self._dtype) for q in self.names},
                "member_params": self.member_params(k),
                "integrity": {q: array_digest(v)
                              for q, v in arrays.items()}}
        for key, v in (meta_extra or {}).items():
            meta[key] = v
        save_state(directory, step, arrays, meta=meta,
                   max_to_keep=max_to_keep)

    def restore_member(self, directory: str, k: int,
                       step: Optional[int] = None) -> int:
        """Restore member ``k`` from the newest restorable checkpoint
        in ``directory`` (or ``step``), verifying integrity digests and
        walking back past corrupt steps exactly like
        :func:`..utils.checkpoint.restore_domain`. Returns the restored
        step."""
        candidates = ([step] if step is not None
                      else sorted(all_steps(directory), reverse=True))
        if not candidates:
            raise FileNotFoundError(f"no checkpoint in {directory}")
        repl = NamedSharding(self.dd.mesh, P())
        last_err: Optional[Exception] = None
        targets = {q: jax.ShapeDtypeStruct(
            zyx_shape(self.dd.size), self._dtype, sharding=repl)
            for q in self.names}
        for name, s in self._member_extra_targets().items():
            targets[f"extra:{name}"] = jax.ShapeDtypeStruct(
                s.shape, s.dtype, sharding=repl)
        for cand in candidates:
            try:
                got, arrays, meta = restore_state(directory, targets,
                                                  step=cand)
                bad = verify_digests(arrays,
                                     meta.get("integrity") or {})
                if bad:
                    raise CorruptCheckpointError(
                        f"step {cand}: integrity sha256 mismatch for "
                        f"{bad}")
            except Exception as e:  # noqa: BLE001 - orbax raises many
                # (json.JSONDecodeError from truncated metadata blobs
                # is a ValueError subclass — every failure here is a
                # walk-back candidate, there is no compat gate to
                # re-raise through)
                last_err = e
                LOG_WARN(f"member checkpoint {directory} step {cand} "
                         f"unrestorable ({type(e).__name__}: {e}); "
                         f"falling back to an older step")
                continue
            for q in self.names:
                self.set_member_interior(q, k, np.asarray(arrays[q]))
            self._restore_member_extras(
                k, {key[len("extra:"):]: v for key, v in arrays.items()
                    if key.startswith("extra:")})
            if meta.get("member_params"):
                self.set_member_params(k, meta["member_params"])
            return got
        raise CorruptCheckpointError(
            f"no restorable member checkpoint in {directory} "
            f"(tried steps {candidates}): {last_err}")


class EnsembleSnapshot:
    """A streaming snapshot in flight: device lane slices enqueued by
    :meth:`_EnsembleBase.member_snapshot_async`."""

    def __init__(self, engine, member: int, step: int,
                 lanes: Dict[str, jnp.ndarray]) -> None:
        self.engine = engine
        self.member = member
        self.step = step
        self._lanes = lanes

    def ready(self) -> bool:
        return all(_is_ready(v) for v in self._lanes.values())

    def get(self) -> Dict[str, np.ndarray]:
        """Host interiors (blocks only if :meth:`ready` is False)."""
        return {q: self.engine.dd.assemble_interior(np.asarray(v))
                for q, v in self._lanes.items()}


# ---------------------------------------------------------------------------


class EnsembleJacobi(_EnsembleBase):
    """N independent Jacobi-3D heat runs per dispatch, with per-member
    hot/cold Dirichlet sphere temperatures (the "boundary values" of a
    parameter scan)."""

    MODEL = "jacobi"
    PARAM_NAMES = ("hot_temp", "cold_temp")

    def default_params(self) -> Dict[str, float]:
        from ..models.jacobi import COLD_TEMP, HOT_TEMP
        return {"hot_temp": HOT_TEMP, "cold_temp": COLD_TEMP}

    def init(self) -> None:
        """Every member starts at its own mean temperature
        ``(hot + cold) / 2`` (the reference's init, per member)."""
        means = (self._params["hot_temp"]
                 + self._params["cold_temp"]) / 2.0
        full = jnp.broadcast_to(
            jnp.asarray(means, self._dtype)[:, None, None, None],
            (self.n_members,) + self._lane_shape)
        self.state = {"temp": jax.device_put(jnp.array(full),
                                             self._batched_sharding)}

    def init_member(self, k: int, seed: int = 0) -> None:
        """Initial conditions for lane ``k`` alone: the member's mean
        temperature, plus a small seeded perturbation when ``seed`` is
        nonzero (distinct initial conditions per campaign)."""
        mean = (self._params["hot_temp"][k]
                + self._params["cold_temp"][k]) / 2.0
        interior = np.full(zyx_shape(self.dd.size), mean)
        if int(seed):
            rng = np.random.default_rng(int(seed))
            interior = interior + 0.01 * rng.standard_normal(
                interior.shape)
        self.set_member_interior("temp", k, interior)

    def _build_step(self) -> None:
        from ..models.jacobi import sphere_geometry
        from ..ops.stencil_kernels import (global_coords, jacobi7,
                                           write_interior)
        from ..topology import Boundary

        dd = self.dd
        radius = dd.radius
        counts = mesh_dim(dd.mesh)
        local = dd.local_size
        gsize = dd.size
        method = pick_method(dd.methods)
        rem = dd.rem
        nonper = dd.boundary == Boundary.NONE
        hot_c, cold_c, sph_r = sphere_geometry(gsize)

        def member_step(p, hot, cold, origin):
            p = dispatch_exchange({"temp": p}, radius, counts, method,
                                  rem=rem, nonperiodic=nonper)["temp"]
            new = jacobi7(p, radius, local)
            gz, gy, gx = global_coords(origin, local)

            def dist2(c: Dim3):
                return ((gx - c.x) ** 2 + (gy - c.y) ** 2
                        + (gz - c.z) ** 2)

            new = jnp.where(dist2(hot_c) <= sph_r * sph_r,
                            hot.astype(new.dtype), new)
            new = jnp.where(dist2(cold_c) <= sph_r * sph_r,
                            cold.astype(new.dtype), new)
            return write_interior(p, new, radius)

        def shard_steps(batched, hot, cold, n):
            origin = shard_origin(local, rem)

            def one(q):
                return jax.vmap(
                    lambda p, h, c: member_step(p, h, c, origin))(
                        q, hot, cold)

            return lax.fori_loop(0, n, lambda _, q: one(q), batched)

        sm = jax.shard_map(
            shard_steps, mesh=dd.mesh,
            in_specs=(ENSEMBLE_SPEC, P(), P(), P()),
            out_specs=ENSEMBLE_SPEC, check_vma=False)
        self._step_n = jax.jit(sm, donate_argnums=0)
        self._segments: Dict = {}

        def segment_fn(k: int, probe_every: int):
            from ..parallel.megastep import (fused_segment_shard,
                                             segment_chunks)

            def shard_seg(batched, hot, cold):
                origin = shard_origin(local, rem)

                def advance(q, c, i):
                    return jax.vmap(
                        lambda p, h, c2: member_step(p, h, c2, origin))(
                            q, hot, cold)

                def probe(q, done):
                    return jax.vmap(
                        lambda p: probe_shard({"temp": p}))(q)

                return fused_segment_shard(batched, advance, probe,
                                           segment_chunks(k),
                                           probe_every)

            sseg = jax.shard_map(
                shard_seg, mesh=dd.mesh,
                in_specs=(ENSEMBLE_SPEC, P(), P()),
                out_specs=(ENSEMBLE_SPEC, P()), check_vma=False)
            return jax.jit(sseg, donate_argnums=0)

        self._segment_fn = segment_fn

    def run(self, n_steps: int) -> None:
        hot, cold = self._param_args()
        self.state = {"temp": self._step_n(
            self.state["temp"], hot, cold,
            jnp.asarray(n_steps, jnp.int32))}

    def run_segment(self, n_steps: int, probe_every: int = 1):
        from ..parallel.megastep import (SegmentTrace, probe_rel_steps,
                                         segment_chunks)
        k = int(n_steps)
        probe_every = max(int(probe_every), 1)
        key = (k, probe_every)
        fn = self._segments.get(key)
        if fn is None:
            fn = self._segment_fn(k, probe_every)
            self._segments[key] = fn
        hot, cold = self._param_args()
        out, trace = fn(self.state["temp"], hot, cold)
        self.state = {"temp": out}
        return SegmentTrace(trace,
                            probe_rel_steps(segment_chunks(k),
                                            probe_every))


class EnsembleAstaroth(_EnsembleBase):
    """N independent MHD runs per dispatch, with per-member physics
    coefficients (viscosity / resistivity / bulk viscosity / sound
    speed — the PIConGPU-style parameter scan)."""

    MODEL = "astaroth"
    PARAM_NAMES = ("nu_visc", "eta", "zeta", "cs_sound")

    def __init__(self, *args, params=None, **kw) -> None:
        from ..models.astaroth import MhdParams
        self.prm = params or MhdParams()
        super().__init__(*args, **kw)

    def default_params(self) -> Dict[str, float]:
        return {p: float(getattr(self.prm, p)) for p in self.PARAM_NAMES}

    def init(self, seeds: Optional[Sequence[int]] = None) -> None:
        """Per-member initial conditions: member ``k`` draws its noise
        fields from ``seeds[k]`` (default ``k``) — distinct
        trajectories even under identical physics."""
        seeds = (list(seeds) if seeds is not None
                 else list(range(self.n_members)))
        if len(seeds) != self.n_members:
            raise ValueError(f"{len(seeds)} seeds for "
                             f"{self.n_members} members")
        for k, seed in enumerate(seeds):
            self.init_member(k, seed)
        self.w = {q: jax.device_put(
            jnp.zeros((self.n_members,) + zyx_shape(self.dd.size),
                      dtype=self._dtype),
            self._batched_sharding) for q in self.names}

    def init_member(self, k: int, seed: int = 0) -> None:
        """Initial conditions for lane ``k`` alone: seeded noise in the
        potential/entropy fields, constant lnrho, and the radial
        explosion shell velocity (the reference's init with a per-
        member random draw). Zeroes k's RK accumulator lane."""
        from ..models.astaroth import _radial_explosion
        size = self.dd.size
        shape = zyx_shape(size)
        rng = np.random.default_rng(int(seed))
        for q in ("ax", "ay", "az", "ss"):
            self.set_member_interior(q, k,
                                     rng.uniform(-1.0, 1.0, size=shape))
        self.set_member_interior("lnrho", k, np.full(shape, 0.5))
        ux, uy, uz = _radial_explosion(size, self.prm)
        self.set_member_interior("uux", k, ux)
        self.set_member_interior("uuy", k, uy)
        self.set_member_interior("uuz", k, uz)
        zero = {q: jnp.zeros(zyx_shape(size), dtype=self._dtype)
                for q in self.names}
        self.w = self._set_lane(self.w, zero, jnp.int32(k))

    def _build_step(self) -> None:
        from ..models.astaroth import (FIELDS, RK3_ALPHA, RK3_BETA,
                                       mhd_rates)
        from ..ops.fd6 import FieldData
        from ..ops.pallas_mhd import compute_dtype
        from ..topology import Boundary

        dd = self.dd
        radius = dd.radius
        counts = mesh_dim(dd.mesh)
        local = dd.local_size
        prm = self.prm
        pad_lo = radius.pad_lo()
        inv_ds = (1.0 / prm.dsx, 1.0 / prm.dsy, 1.0 / prm.dsz)
        method = pick_method(dd.methods)
        dt = prm.dt
        rem = dd.rem
        nonper = dd.boundary == Boundary.NONE
        comp = compute_dtype(self._dtype)
        store = jnp.dtype(self._dtype)

        #: RK accumulators ride interior-shaped, like the solver's xla
        #: path; init() allocates them batched
        self.w: Dict[str, jnp.ndarray] = {
            q: jax.device_put(
                jnp.zeros((self.n_members,) + zyx_shape(dd.size),
                          dtype=self._dtype), self._batched_sharding)
            for q in FIELDS}

        def member_iter(fields, w, pvals):
            mprm = dataclasses.replace(
                prm, **{p: pvals[p].astype(comp)
                        for p in self.PARAM_NAMES})
            for s in range(3):
                fields = dispatch_exchange(fields, radius, counts,
                                           method, rem=rem,
                                           nonperiodic=nonper)
                data = {q: FieldData(fields[q].astype(comp), inv_ds,
                                     pad_lo, local)
                        for q in FIELDS}
                rates = mhd_rates(data, mprm, comp)
                alpha = jnp.asarray(RK3_ALPHA[s], comp)
                beta = jnp.asarray(RK3_BETA[s], comp)
                dt_ = jnp.asarray(dt, comp)
                new_f = {}
                new_w = {}
                for q in FIELDS:
                    wq = alpha * w[q].astype(comp) + dt_ * rates[q]
                    uq = data[q].value + beta * wq
                    new_w[q] = wq.astype(store)
                    new_f[q] = lax.dynamic_update_slice(
                        fields[q], uq.astype(store),
                        (pad_lo.z, pad_lo.y, pad_lo.x))
                fields, w = new_f, new_w
            return fields, w

        def shard_iters(fields, w, pvals, n):
            def one(fw):
                return jax.vmap(member_iter)(fw[0], fw[1], pvals)

            return lax.fori_loop(0, n, lambda _, fw: one(fw),
                                 (fields, w))

        fspec = {q: ENSEMBLE_SPEC for q in FIELDS}
        pspec = {p: P() for p in self.PARAM_NAMES}
        sm = jax.shard_map(shard_iters, mesh=dd.mesh,
                           in_specs=(fspec, fspec, pspec, P()),
                           out_specs=(fspec, fspec), check_vma=False)
        self._iter_n = jax.jit(sm, donate_argnums=(0, 1))
        self._segments: Dict = {}

        def segment_fn(k: int, probe_every: int):
            from ..parallel.megastep import (fused_segment_shard,
                                             segment_chunks)

            def shard_seg(fields, w, pvals):
                def advance(fw, c, i):
                    return tuple(jax.vmap(member_iter)(fw[0], fw[1],
                                                       pvals))

                def probe(fw, done):
                    return jax.vmap(
                        lambda f: probe_shard(
                            {q: f[q] for q in FIELDS}))(fw[0])

                return fused_segment_shard((fields, w), advance, probe,
                                           segment_chunks(k),
                                           probe_every)

            sseg = jax.shard_map(
                shard_seg, mesh=dd.mesh,
                in_specs=(fspec, fspec, pspec),
                out_specs=((fspec, fspec), P()), check_vma=False)
            return jax.jit(sseg, donate_argnums=(0, 1))

        self._segment_fn = segment_fn

    def run(self, n_steps: int) -> None:
        pvals = {p: self._replicated(self._params[p])
                 for p in self.PARAM_NAMES}
        self.state, self.w = self._iter_n(
            dict(self.state), dict(self.w), pvals,
            jnp.asarray(n_steps, jnp.int32))

    def run_segment(self, n_steps: int, probe_every: int = 1):
        from ..parallel.megastep import (SegmentTrace, probe_rel_steps,
                                         segment_chunks)
        k = int(n_steps)
        probe_every = max(int(probe_every), 1)
        key = (k, probe_every)
        fn = self._segments.get(key)
        if fn is None:
            fn = self._segment_fn(k, probe_every)
            self._segments[key] = fn
        pvals = {p: self._replicated(self._params[p])
                 for p in self.PARAM_NAMES}
        (out_f, out_w), trace = fn(dict(self.state), dict(self.w),
                                   pvals)
        self.state, self.w = out_f, out_w
        return SegmentTrace(trace,
                            probe_rel_steps(segment_chunks(k),
                                            probe_every))

    # RK accumulators are campaign state: a lane rollback without its
    # w would resume mid-RK-iteration with a zeroed accumulator
    def _member_extra_arrays(self, k: int) -> Dict[str, jnp.ndarray]:
        lanes = self._get_lane(dict(self.w), jnp.int32(k))
        return {f"w:{q}": jnp.asarray(np.asarray(v))
                for q, v in lanes.items()}

    def _member_extra_targets(self) -> Dict[str, jax.ShapeDtypeStruct]:
        return {f"w:{q}": jax.ShapeDtypeStruct(
            zyx_shape(self.dd.size), self._dtype) for q in self.names}

    def _restore_member_extras(self, k: int,
                               extras: Dict[str, jnp.ndarray]) -> None:
        lane = {q: jnp.asarray(extras[f"w:{q}"]) for q in self.names
                if f"w:{q}" in extras}
        if lane:
            self.w = self._set_lane(self.w, lane, jnp.int32(k))

    def reset_member(self, k: int) -> None:
        super().reset_member(k)
        zero = {q: jnp.zeros(zyx_shape(self.dd.size),
                             dtype=self._dtype) for q in self.names}
        self.w = self._set_lane(self.w, zero, jnp.int32(k))
