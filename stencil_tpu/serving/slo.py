"""Admission policy for the serving fleet: shape bucketing, SLO
thresholds, and rendezvous tenant routing.

Three small, separately testable policies the :class:`~.fleet.Fleet`
coordinator composes:

* :class:`GridBucketer` — pads arbitrary user grids UP to a small
  declared bucket set (the SNIPPETS partition-rule pattern: a declared
  rule table, not per-request geometry), so the per-replica engine
  cache is bounded by ``len(buckets)`` executables no matter how many
  distinct grids users ask for. The padded request is fingerprinted at
  the BUCKET shape, so it literally reuses the bucket-shaped engine —
  and the ``serving.fleet.bucket_step[hlo]`` registry target proves
  the padded-admission step lowers to HLO *identical* to the native
  bucket-shape step (bucketing must not leak the pre-pad grid into
  the compiled program).

* :class:`SloPolicy` — the declared shed thresholds over the two
  signals the service already exports (``stencil_service_queue_depth``
  and ``stencil_service_admission_latency_seconds``). Requests at or
  above ``protected_priority`` are never shed; lower-priority work is
  shed with a NAMED reason (:data:`SHED_REASONS`) the moment a signal
  crosses its threshold — shedding is loud (a v1-schema
  ``request_shed`` event and ``stencil_fleet_shed_total`` counter),
  never silent.

* :func:`rendezvous_replica` — highest-random-weight (rendezvous)
  hashing of the admission key over the live replica set: every
  client agrees on the owner without coordination, and a replica's
  death remaps ONLY the keys it owned (no global reshuffle), which is
  exactly the recovery story the fleet needs.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Optional, Sequence, Tuple

Grid = Tuple[int, int, int]

#: grids a default fleet admits at (all divisible by the 2x2x2 test
#: mesh); callers with other meshes declare their own bucket set
DEFAULT_BUCKETS: Tuple[Grid, ...] = ((8, 8, 8), (16, 16, 16),
                                     (24, 24, 24), (32, 32, 32))

#: the named shed reasons — the `reason` label vocabulary of
#: stencil_fleet_shed_total and the request_shed event
SHED_REASONS: Tuple[str, ...] = ("queue_depth", "admission_latency")


class BucketError(ValueError):
    """No declared bucket can hold the requested grid."""


class GridBucketer:
    """Pad user grids up to a declared bucket set.

    A request whose grid fits inside a bucket (every dimension <= the
    bucket's) is admitted AT the smallest such bucket — the campaign
    runs at the bucket resolution, a declared admission contract. A
    grid larger than every bucket is rejected loudly
    (:class:`BucketError`), never silently truncated.
    """

    def __init__(self, buckets: Sequence[Grid] = DEFAULT_BUCKETS) -> None:
        if not buckets:
            raise ValueError("bucket set must not be empty")
        norm = []
        for b in buckets:
            g = tuple(int(v) for v in b)
            if len(g) != 3 or any(v < 1 for v in g):
                raise ValueError(f"bucket {b!r} is not a positive "
                                 f"(z, y, x) grid")
            norm.append(g)
        # smallest-first by volume (ties: lexicographic) so bucket_for
        # picks the cheapest bucket that fits
        self.buckets: Tuple[Grid, ...] = tuple(
            sorted(set(norm), key=lambda g: (g[0] * g[1] * g[2], g)))

    def bucket_for(self, grid: Grid) -> Grid:
        """The smallest declared bucket holding ``grid``."""
        g = tuple(int(v) for v in grid)
        for b in self.buckets:
            if all(gv <= bv for gv, bv in zip(g, b)):
                return b
        raise BucketError(
            f"grid {g} fits no declared bucket {list(self.buckets)} — "
            f"declare a larger bucket or reject the request")

    def apply(self, req):
        """``(request', padded)``: the request admitted at its bucket
        grid (a ``dataclasses.replace`` copy when padding changed the
        grid; the original object otherwise)."""
        bucket = self.bucket_for(req.grid)
        if tuple(req.grid) == bucket:
            return req, False
        return dataclasses.replace(req, grid=bucket), True


@dataclasses.dataclass(frozen=True)
class SloPolicy:
    """Declared shed thresholds over the exported admission signals.

    ``None`` disables a threshold. ``protected_priority`` is the
    admission floor: requests with ``priority >=`` it are NEVER shed
    (the fleet sheds lowest-priority work first, by construction —
    the default protects every default-priority request and sheds
    only work explicitly submitted below it, e.g. a flood at
    priority 0)."""

    max_queue_depth: Optional[int] = 64
    max_admission_latency_seconds: Optional[float] = None
    protected_priority: int = 1

    def shed_reason(self, priority: int, queue_depth: float,
                    admission_latency_seconds: Optional[float]
                    ) -> Optional[str]:
        """The named reason to shed this request, or None to admit."""
        if int(priority) >= self.protected_priority:
            return None
        if (self.max_queue_depth is not None
                and queue_depth >= self.max_queue_depth):
            return "queue_depth"
        if (self.max_admission_latency_seconds is not None
                and admission_latency_seconds is not None
                and admission_latency_seconds
                > self.max_admission_latency_seconds):
            return "admission_latency"
        return None


def rendezvous_replica(key: str, replicas: Sequence[str]) -> str:
    """Highest-random-weight owner of ``key`` among ``replicas``.

    sha256 keeps the weight stable across processes and Python runs
    (no PYTHONHASHSEED dependence) — every fleet member and every
    test agrees on the same owner."""
    if not replicas:
        raise ValueError("no replicas to route to")
    return max(
        replicas,
        key=lambda name: hashlib.sha256(
            f"{key}|{name}".encode()).hexdigest())
