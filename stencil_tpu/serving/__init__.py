"""Ensemble serving: batched campaigns behind an async multi-tenant
service.

Millions of users do not ask for one giant grid — they ask for
thousands of small-to-medium simulations in flight at once (the
parameter-scan / ensemble usage that drives production stencil
frameworks such as PIConGPU, arXiv:1606.02862). This package turns the
single-simulation stack built by PRs 1-5 into a serving system:

* **Batched ensembles** (:mod:`.ensemble`) — a leading member axis is
  vmapped through the shard step functions, so ONE compiled executable
  advances N independent simulations (distinct initial conditions and
  per-member physics parameters) per dispatch. The halo exchange stays
  collective-permute-only with the SAME collective count as a single
  member; each permute simply carries N slabs (wire bytes exactly xN —
  proven by the ``serving.ensemble.*`` stencil-lint registry targets).
  Health sentinels are per member: one member's NaN trips only that
  member (:class:`.ensemble.EnsembleSentinel`).

* **The campaign service** (:mod:`.queue`, :mod:`.service`) — an async
  multi-tenant front end: requests queue up, admission packs
  fingerprint-compatible requests (same compiled executable — the
  :mod:`..tuning` fingerprint) into one ensemble dispatch, the
  persistent tuning-plan cache supplies the exchange plan with zero
  re-measurement, checkpoints live in per-tenant namespaces under the
  hardened checkpoint layer, snapshot readback streams through the
  non-blocking ``is_ready`` polling pattern, and the resilience ladder
  (rollback, preempt/resume) applies per campaign, not per process.

``apps/serve.py`` is the runnable front end; the CI service smoke
drives >= 3 concurrent fake-tenant campaigns through it on CPU.
"""

from .ensemble import (EnsembleAstaroth, EnsembleHealth, EnsembleJacobi,
                       EnsembleSentinel, configured_domain,
                       domain_fingerprint, make_ensemble_probe)
from .fleet import (REPLICA_STATES, Fleet, RequestShed,
                    TransientDispatchError)
from .queue import (CampaignHandle, CampaignRequest, DeadlineExpired,
                    RequestQueue)
from .service import (CampaignResult, CampaignService, ReplicaCrashed,
                      ServiceStats)
from .slo import (DEFAULT_BUCKETS, SHED_REASONS, BucketError,
                  GridBucketer, SloPolicy, rendezvous_replica)

__all__ = [
    "EnsembleJacobi", "EnsembleAstaroth", "EnsembleSentinel",
    "EnsembleHealth", "make_ensemble_probe", "configured_domain",
    "domain_fingerprint", "CampaignRequest", "CampaignHandle",
    "RequestQueue", "CampaignService", "CampaignResult", "ServiceStats",
    "DeadlineExpired", "ReplicaCrashed",
    "Fleet", "RequestShed", "TransientDispatchError", "REPLICA_STATES",
    "GridBucketer", "SloPolicy", "BucketError", "rendezvous_replica",
    "DEFAULT_BUCKETS", "SHED_REASONS",
]
