"""Campaign requests and the admission queue.

A :class:`CampaignRequest` describes one tenant's simulation: model,
grid, step budget, per-member physics parameters, and the tenant's
resilience policy knobs. At submit time the request is fingerprinted
with the SAME problem fingerprint the autotuner caches plans under
(:func:`..serving.ensemble.domain_fingerprint`), so "these requests can
share a compiled executable" and "this request can reuse a cached
exchange plan" are one question with one answer.

:class:`RequestQueue` is the admission structure:
``pop_batch(width)`` removes the oldest request plus every younger
request with the SAME fingerprint (up to ``width``) — the batch a
single ensemble dispatch serves. Requests with other fingerprints keep
their queue order for later batches.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass
class CampaignRequest:
    """One tenant's simulation campaign."""

    tenant: str
    campaign: str
    model: str = "jacobi"               # jacobi | astaroth
    grid: Tuple[int, int, int] = (8, 8, 8)
    n_steps: int = 4
    dtype: str = "float32"
    boundary: str = "PERIODIC"
    mesh_shape: Optional[Tuple[int, int, int]] = None
    #: per-member physics parameters (e.g. jacobi hot_temp/cold_temp,
    #: astaroth nu_visc/eta/zeta/cs_sound); unset keys use defaults
    params: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: seeds the member's initial conditions (model-specific)
    init_seed: int = 0
    # -- per-tenant policy knobs (the resilience ladder, per campaign)
    check_every: int = 1        # sentinel probe cadence (member steps)
    ckpt_every: int = 0         # 0 = anchor checkpoint at step 0 only
    snapshot_every: int = 0     # 0 = final snapshot only
    max_retries: int = 2        # rollbacks before the campaign fails
    #: test/chaos hook: poison this member at the given member-step
    #: (None = no injection); fires once
    chaos_nan_step: Optional[int] = None

    def validate(self) -> None:
        from ..utils.checkpoint import validate_checkpoint_component
        validate_checkpoint_component(self.tenant, kind="tenant id")
        validate_checkpoint_component(self.campaign, kind="campaign id")
        if self.model not in ("jacobi", "astaroth"):
            raise ValueError(f"unknown model {self.model!r}")
        if int(self.n_steps) < 1:
            raise ValueError(f"n_steps must be >= 1, got {self.n_steps}")
        if int(self.check_every) < 1:
            raise ValueError("check_every must be >= 1")


def request_fingerprint(req: CampaignRequest, devices=None) -> str:
    """The problem fingerprint of a request — the admission AND
    plan-cache key (requests sharing it share a compiled executable and
    a tuned exchange plan)."""
    import jax.numpy as jnp

    from ..topology import Boundary
    from .ensemble import configured_domain, domain_fingerprint

    dd = configured_domain(
        req.model, req.grid, dtype=jnp.dtype(req.dtype),
        boundary=Boundary[req.boundary], mesh_shape=req.mesh_shape,
        devices=devices)
    return domain_fingerprint(dd)


class CampaignHandle:
    """The submitter's side of a campaign: wait on :meth:`result`."""

    def __init__(self, request: CampaignRequest) -> None:
        self.request = request
        #: set at submit time (the admission/plan-cache key)
        self.fingerprint: Optional[str] = None
        self._done = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    # -- service side ---------------------------------------------------
    def _resolve(self, result) -> None:
        self._result = result
        self._done.set()

    def _fail(self, error: BaseException) -> None:
        self._error = error
        self._done.set()

    # -- client side ----------------------------------------------------
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until the campaign completes (or fails, re-raising its
        error; or ``TimeoutError`` after ``timeout`` seconds)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"campaign {self.request.tenant}/{self.request.campaign}"
                f" not done after {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result


@dataclasses.dataclass
class _Entry:
    request: CampaignRequest
    handle: CampaignHandle
    fingerprint: str
    seq: int
    #: submit wall time — the service's admission-latency metric
    #: (time from submit to batch start) reads it
    submitted: float = dataclasses.field(default_factory=time.time)


class RequestQueue:
    """Thread-safe FIFO with fingerprint-compatible batch admission."""

    def __init__(self, devices=None) -> None:
        self._devices = devices
        self._entries: List[_Entry] = []
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._seq = 0

    def submit(self, req: CampaignRequest) -> CampaignHandle:
        req.validate()
        fp = request_fingerprint(req, devices=self._devices)
        handle = CampaignHandle(req)
        handle.fingerprint = fp
        with self._lock:
            self._entries.append(_Entry(req, handle, fp, self._seq))
            self._seq += 1
            self._not_empty.notify_all()
        return handle

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def wait_nonempty(self, timeout: Optional[float] = None) -> bool:
        with self._lock:
            if self._entries:
                return True
            return self._not_empty.wait_for(
                lambda: bool(self._entries), timeout)

    def pop_batch(self, width: int) -> List[_Entry]:
        """The next admission batch: the oldest request and every
        younger fingerprint-identical request, up to ``width`` members.
        Other fingerprints keep their positions."""
        with self._lock:
            if not self._entries:
                return []
            head_fp = self._entries[0].fingerprint
            batch: List[_Entry] = []
            rest: List[_Entry] = []
            for e in self._entries:
                if e.fingerprint == head_fp and len(batch) < int(width):
                    batch.append(e)
                else:
                    rest.append(e)
            self._entries = rest
            return batch
