"""Campaign requests and the admission queue.

A :class:`CampaignRequest` describes one tenant's simulation: model,
grid, step budget, per-member physics parameters, and the tenant's
resilience policy knobs. At submit time the request is fingerprinted
with the SAME problem fingerprint the autotuner caches plans under
(:func:`..serving.ensemble.domain_fingerprint`), so "these requests can
share a compiled executable" and "this request can reuse a cached
exchange plan" are one question with one answer.

:class:`RequestQueue` is the admission structure, priority-ordered
with stable FIFO within a priority class: ``pop_batch(width)`` removes
the highest-priority oldest request plus every younger request with
the SAME fingerprint (up to ``width``) — the batch a single ensemble
dispatch serves. Requests with other fingerprints keep their queue
order for later batches. Requests carrying a ``deadline_seconds`` that
has already expired are rejected AT POP (:class:`DeadlineExpired` on
their handle, plus the queue's ``on_expired`` callback — the service
turns it into a v1-schema ``request_expired`` event) instead of
burning a batch slot on dead work.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple


class DeadlineExpired(RuntimeError):
    """The request's deadline passed before admission."""


@dataclasses.dataclass
class CampaignRequest:
    """One tenant's simulation campaign."""

    tenant: str
    campaign: str
    model: str = "jacobi"               # jacobi | astaroth
    grid: Tuple[int, int, int] = (8, 8, 8)
    n_steps: int = 4
    dtype: str = "float32"
    boundary: str = "PERIODIC"
    mesh_shape: Optional[Tuple[int, int, int]] = None
    #: per-member physics parameters (e.g. jacobi hot_temp/cold_temp,
    #: astaroth nu_visc/eta/zeta/cs_sound); unset keys use defaults
    params: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: seeds the member's initial conditions (model-specific)
    init_seed: int = 0
    # -- per-tenant policy knobs (the resilience ladder, per campaign)
    check_every: int = 1        # sentinel probe cadence (member steps)
    ckpt_every: int = 0         # 0 = anchor checkpoint at step 0 only
    snapshot_every: int = 0     # 0 = final snapshot only
    max_retries: int = 2        # rollbacks before the campaign fails
    #: test/chaos hook: poison this member at the given member-step
    #: (None = no injection); fires once
    chaos_nan_step: Optional[int] = None
    # -- SLO knobs (fleet admission; see serving/slo.py)
    #: admission class: higher pops first; stable FIFO within a class.
    #: The fleet sheds work BELOW its policy's protected_priority
    #: under overload. Default 1 = protected under the default policy.
    priority: int = 1
    #: wall-clock admission deadline from submit; an expired request
    #: is rejected at pop with a request_expired event (None = none)
    deadline_seconds: Optional[float] = None

    def validate(self) -> None:
        from ..utils.checkpoint import validate_checkpoint_component
        validate_checkpoint_component(self.tenant, kind="tenant id")
        validate_checkpoint_component(self.campaign, kind="campaign id")
        if self.model not in ("jacobi", "astaroth"):
            raise ValueError(f"unknown model {self.model!r}")
        if int(self.n_steps) < 1:
            raise ValueError(f"n_steps must be >= 1, got {self.n_steps}")
        if int(self.check_every) < 1:
            raise ValueError("check_every must be >= 1")
        if self.deadline_seconds is not None \
                and float(self.deadline_seconds) <= 0:
            raise ValueError(
                f"deadline_seconds must be > 0 when set, got "
                f"{self.deadline_seconds}")


def request_fingerprint(req: CampaignRequest, devices=None) -> str:
    """The problem fingerprint of a request — the admission AND
    plan-cache key (requests sharing it share a compiled executable and
    a tuned exchange plan)."""
    import jax.numpy as jnp

    from ..topology import Boundary
    from .ensemble import configured_domain, domain_fingerprint

    dd = configured_domain(
        req.model, req.grid, dtype=jnp.dtype(req.dtype),
        boundary=Boundary[req.boundary], mesh_shape=req.mesh_shape,
        devices=devices)
    return domain_fingerprint(dd)


class CampaignHandle:
    """The submitter's side of a campaign: wait on :meth:`result`."""

    def __init__(self, request: CampaignRequest) -> None:
        self.request = request
        #: set at submit time (the admission/plan-cache key)
        self.fingerprint: Optional[str] = None
        self._done = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    # -- service side ---------------------------------------------------
    def _resolve(self, result) -> None:
        self._result = result
        self._done.set()

    def _fail(self, error: BaseException) -> None:
        self._error = error
        self._done.set()

    # -- client side ----------------------------------------------------
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until the campaign completes (or fails, re-raising its
        error; or ``TimeoutError`` after ``timeout`` seconds)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"campaign {self.request.tenant}/{self.request.campaign}"
                f" not done after {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result


@dataclasses.dataclass
class _Entry:
    request: CampaignRequest
    handle: CampaignHandle
    fingerprint: str
    seq: int
    #: submit wall time — the service's admission-latency metric
    #: (time from submit to batch start) reads it
    submitted: float = dataclasses.field(default_factory=time.time)


class RequestQueue:
    """Thread-safe priority queue with fingerprint-compatible batch
    admission (stable FIFO within a priority class; back-compat: all
    default-priority requests behave exactly as the old FIFO)."""

    def __init__(self, devices=None,
                 on_expired: Optional[Callable[["_Entry"], None]] = None
                 ) -> None:
        self._devices = devices
        self._entries: List[_Entry] = []
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._seq = 0
        #: called (outside handle resolution) for each entry rejected
        #: at pop with an expired deadline — the service's event hook
        self._on_expired = on_expired

    def submit(self, req: CampaignRequest) -> CampaignHandle:
        req.validate()
        fp = request_fingerprint(req, devices=self._devices)
        handle = CampaignHandle(req)
        handle.fingerprint = fp
        with self._lock:
            self._entries.append(_Entry(req, handle, fp, self._seq))
            self._seq += 1
            self._not_empty.notify_all()
        return handle

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def wait_nonempty(self, timeout: Optional[float] = None) -> bool:
        with self._lock:
            if self._entries:
                return True
            return self._not_empty.wait_for(
                lambda: bool(self._entries), timeout)

    def pop_batch(self, width: int) -> List[_Entry]:
        """The next admission batch: the highest-priority oldest
        request (stable FIFO within a priority class) and every
        younger fingerprint-identical request, up to ``width``
        members. Other fingerprints keep their positions. Entries
        whose deadline already passed are rejected here — their
        handles fail with :class:`DeadlineExpired` and ``on_expired``
        fires per entry — so a batch slot is never spent on work the
        tenant has already given up on."""
        now = time.time()
        with self._lock:
            expired = [e for e in self._entries
                       if e.request.deadline_seconds is not None
                       and now - e.submitted
                       > float(e.request.deadline_seconds)]
            if expired:
                gone = set(map(id, expired))
                self._entries = [e for e in self._entries
                                 if id(e) not in gone]
            if not self._entries:
                batch, head = [], None
            else:
                # priority class first, then submit order — max() is
                # stable in neither direction, so order the key by
                # (priority, -seq) and take the max explicitly
                head = max(self._entries,
                           key=lambda e: (e.request.priority, -e.seq))
                batch = []
                rest: List[_Entry] = []
                for e in sorted(self._entries,
                                key=lambda e: (-e.request.priority,
                                               e.seq)):
                    if e.fingerprint == head.fingerprint \
                            and len(batch) < int(width):
                        batch.append(e)
                    else:
                        rest.append(e)
                rest.sort(key=lambda e: e.seq)  # keep queue order
                self._entries = rest
        for e in expired:
            e.handle._fail(DeadlineExpired(
                f"{e.request.tenant}/{e.request.campaign}: deadline "
                f"{e.request.deadline_seconds}s expired after "
                f"{now - e.submitted:.3f}s in queue"))
            if self._on_expired is not None:
                self._on_expired(e)
        return batch

    def drain_entries(self) -> List[_Entry]:
        """Remove and return EVERY queued entry (queue order) — the
        fleet's reshard primitive when a replica degrades."""
        with self._lock:
            entries, self._entries = self._entries, []
            return entries

    def take(self, tenant: str, campaign: str) -> Optional[_Entry]:
        """Remove and return the queued entry for one campaign (None
        when it is not queued) — the fleet's migration primitive."""
        with self._lock:
            for i, e in enumerate(self._entries):
                if e.request.tenant == tenant \
                        and e.request.campaign == campaign:
                    return self._entries.pop(i)
        return None
