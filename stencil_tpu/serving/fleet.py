"""The serving fleet: N campaign-service replicas behind one
coordinator.

ROADMAP item 4 names the gap this closes: one :class:`~.service.
CampaignService` is one process with one engine cache, but the north
star is heavy traffic from millions of users. :class:`Fleet`
interposes between tenants and N in-process replicas — the TEMPI
(arXiv:2012.14363) shape: an interposed layer that ADDS capability
(sharding, admission control, failover) without touching the engine
underneath — composing four things:

1. **Sharded admission.** Tenants route to replicas by rendezvous
   hash (:func:`~.slo.rendezvous_replica`) over the admission
   fingerprint + tenant id: every submit agrees on the owner with no
   coordination, fingerprint-identical work from one tenant lands on
   one replica (so it batches), and a replica's death remaps only the
   keys it owned. User grids are **bucketed** first
   (:class:`~.slo.GridBucketer` pads up to a small declared bucket
   set), so each replica's engine cache is bounded by the bucket count
   no matter how many distinct grids users ask for; the
   ``serving.fleet.bucket_step[hlo]`` registry target proves the
   padded-bucket step lowers to HLO identical to the native bucket
   shape.

2. **SLO-aware admission.** Requests carry ``priority`` and
   ``deadline_seconds`` (:mod:`.queue`). The fleet reads the
   already-EXPORTED admission signals (the replicas'
   ``stencil_service_queue_depth`` gauges and
   ``stencil_service_admission_latency_seconds`` histograms, parsed
   from their Prometheus text — the external contract, not internal
   fields) and sheds work below the policy's protected priority with
   a NAMED reason when a signal crosses its declared threshold
   (:class:`~.slo.SloPolicy`). Shedding is loud: a v1-schema
   ``request_shed`` event plus ``stencil_fleet_shed_total`` — never a
   silent drop.

3. **Replica fault tolerance.** The deterministic-chaos story one
   level up (:mod:`..resilience.faults`): :class:`~..resilience.
   faults.ReplicaCrash` hard-kills a replica mid-batch (its in-RAM
   lanes and unresolved handles are lost), and the fleet recovers
   every one of its campaigns from the per-tenant checkpoint
   namespaces on the SHARED checkpoint root, re-admitting them to
   survivors — bitwise-continuous, because resume-and-replay is
   deterministic. :class:`~..resilience.faults.SlowReplica` trips
   the degradation ladder (drain -> reshard its tenants to survivors
   -> readmit on recovery); :class:`~..resilience.faults.
   AdmissionFlood` drives the shed path. Dispatch to a replica runs
   under :func:`~..utils.retry.retry` timeout/backoff, so a
   transient dispatch failure costs a short backoff, not a campaign.

4. **Live rebalancing.** :meth:`Fleet.rebalance` picks migrations
   from per-replica load and executes them preempt-on-src ->
   resume-on-dst (the PR 5/6 preempt/resume machinery; POLAR-PIC's
   principle that placement is a run-time decision). The SHARED
   flock'd plan cache guarantees the destination re-tunes nothing,
   and a destination that already built the fingerprint's engine
   recompiles nothing (``stencil_service_recompiles_total`` stays 0).

**The zero-loss gate** (ROADMAP item 4, verbatim): a replica killed
mid-fleet loses zero campaigns, every recovered campaign finishes
bitwise-equal to a fault-free fleet run, and surviving replicas'
``recompiles_total`` stays 0 for every fingerprint any survivor's
plan cache already held. CI asserts it from exported metrics/events.

The fleet serves in deterministic synchronous ROUNDS
(:meth:`Fleet.pump`): fire chaos due this round -> dispatch pending
campaigns to their routed replicas -> drain each live replica ->
harvest results (preempted-unfinished campaigns return to pending and
resume wherever routing/pinning sends them next). :meth:`Fleet.serve`
pumps until every campaign resolves and no chaos remains — the
test/CI entry point.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

from ..resilience.faults import AdmissionFlood, ReplicaCrash, SlowReplica
from ..utils.logging import LOG_INFO, LOG_WARN
from ..utils.retry import retry
from .queue import CampaignHandle, CampaignRequest, request_fingerprint
from .service import CampaignService, ReplicaCrashed
from .slo import (DEFAULT_BUCKETS, SHED_REASONS, BucketError,
                  GridBucketer, SloPolicy, rendezvous_replica)

#: replica lifecycle states — the label vocabulary of
#: stencil_fleet_replicas
REPLICA_STATES: Tuple[str, ...] = ("active", "degraded", "dead")


class RequestShed(RuntimeError):
    """The fleet shed this request under overload (named reason)."""


class TransientDispatchError(OSError):
    """A transient replica-dispatch failure — retriable by default
    (an ``OSError``, matching :func:`~..utils.retry.retry`'s default
    ``retriable`` tuple)."""


@dataclasses.dataclass
class _Replica:
    """One in-process campaign-service replica and its fleet state."""

    name: str
    index: int
    service: CampaignService
    state: str = "active"       # active | degraded | dead


@dataclasses.dataclass
class _FleetCampaign:
    """The fleet's book-keeping for one admitted campaign."""

    request: CampaignRequest          # the BUCKETED request (what runs)
    handle: CampaignHandle            # the tenant's (outer) handle
    fingerprint: str
    padded: bool = False
    #: rebalance pin: route here instead of the rendezvous owner
    pinned: Optional[str] = None
    #: replica currently holding the inner submission (None = pending)
    replica: Optional[str] = None
    inner: Optional[CampaignHandle] = None
    done: bool = False
    recoveries: int = 0

    @property
    def key(self) -> Tuple[str, str]:
        return (self.request.tenant, self.request.campaign)

    @property
    def pending(self) -> bool:
        return not self.done and self.inner is None


class Fleet:
    """N in-process :class:`~.service.CampaignService` replicas behind
    sharded, SLO-aware, fault-tolerant admission (module docstring).

    All replicas share ONE checkpoint root (``root_dir`` — so any
    survivor can resume any tenant's campaign from its namespace) and
    ONE flock'd plan-cache path (so no replica ever re-tunes a
    fingerprint the fleet has tuned). Everything else — engine cache,
    metrics registry, event ring, flight recorder — is per replica,
    exactly as it would be across processes.
    """

    def __init__(self, root_dir: str, n_replicas: int = 2, devices=None,
                 width: int = 4, tuner_timer=None, plan_cache_path=None,
                 buckets: Sequence = DEFAULT_BUCKETS,
                 policy: Optional[SloPolicy] = None,
                 chaos: Sequence = (),
                 retry_attempts: int = 3, retry_base_delay: float = 0.05,
                 retry_sleep=None, run_id: Optional[str] = None,
                 registry=None, events_capacity: int = 4096,
                 flight_recorder_dir: Optional[str] = None,
                 max_rounds: int = 64,
                 service_kwargs: Optional[Dict] = None) -> None:
        if int(n_replicas) < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self._devices = devices
        self._bucketer = GridBucketer(buckets)
        self._policy = policy if policy is not None else SloPolicy()
        self._chaos = list(chaos)
        self._retry_attempts = int(retry_attempts)
        self._retry_base_delay = float(retry_base_delay)
        self._retry_sleep = retry_sleep
        self._max_rounds = int(max_rounds)
        self._dispatch_errors: List[BaseException] = []
        from ..telemetry import EventLog, MetricsRegistry, RingSink
        self._ring = RingSink(events_capacity)
        self._elog = EventLog(run_id=run_id, sinks=(self._ring,))
        self.run_id = self._elog.run_id
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        self._register_metrics()
        kw = dict(service_kwargs or {})
        self.replicas: List[_Replica] = []
        for i in range(int(n_replicas)):
            svc = CampaignService(
                root_dir=root_dir, devices=devices, width=width,
                tuner_timer=tuner_timer,
                plan_cache_path=plan_cache_path,
                run_id=f"{self.run_id}-r{i}",
                flight_recorder_dir=flight_recorder_dir, **kw)
            self.replicas.append(_Replica(name=f"replica-{i}", index=i,
                                          service=svc))
        self._campaigns: Dict[Tuple[str, str], _FleetCampaign] = {}
        self._seeded_tenants: set = set()
        self._round = 0
        self._set_replica_gauges()
        # the fleet-level fault classes log through the fleet event log
        for ev in self._chaos:
            if not isinstance(ev, (ReplicaCrash, SlowReplica,
                                   AdmissionFlood)):
                raise TypeError(
                    f"fleet chaos takes ReplicaCrash/SlowReplica/"
                    f"AdmissionFlood, got {type(ev).__name__}")

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def _register_metrics(self) -> None:
        """The fleet metric surface (names/labels are a stable
        contract — README "Fleet serving"). Every enumerable label set
        is seeded to an explicit 0 at registration (the PR 7
        convention: "== 0" gates assert series that EXIST); per-tenant
        shed series are seeded the moment a tenant first submits."""
        m = self.metrics
        self._m_replicas = m.gauge(
            "stencil_fleet_replicas",
            "replicas by lifecycle state (active|degraded|dead)")
        self._m_shed = m.counter(
            "stencil_fleet_shed_total",
            "requests shed under overload, by tenant and named reason"
            " (queue_depth|admission_latency)")
        self._m_migrations = m.counter(
            "stencil_fleet_migrations_total",
            "campaigns migrated between replicas (rebalance: "
            "preempt-on-src -> resume-on-dst)")
        self._m_recovered = m.counter(
            "stencil_fleet_recovered_campaigns_total",
            "campaigns re-admitted to survivors after a replica "
            "death — the zero-loss gate counts these against losses")
        for c in (self._m_migrations, self._m_recovered):
            c.inc(0)
        for state in REPLICA_STATES:
            self._m_replicas.set(0, state=state)

    def _seed_tenant(self, tenant: str) -> None:
        if tenant in self._seeded_tenants:
            return
        self._seeded_tenants.add(tenant)
        for reason in SHED_REASONS:
            self._m_shed.inc(0, tenant=tenant, reason=reason)

    def _set_replica_gauges(self) -> None:
        for state in REPLICA_STATES:
            self._m_replicas.set(
                sum(1 for r in self.replicas if r.state == state),
                state=state)

    def _log(self, kind: str, **kw) -> None:
        self._elog.emit(kind, **kw)

    @property
    def events(self) -> List[Dict]:
        return self._ring.records()

    def metrics_text(self) -> str:
        return self.metrics.to_prometheus_text()

    def metrics_snapshot(self) -> Dict:
        return self.metrics.snapshot()

    def write_events(self, path: str) -> None:
        from ..telemetry import EVENT_SCHEMA_VERSION
        payload = {"schema": EVENT_SCHEMA_VERSION, "run": self.run_id,
                   "dropped_events": self._ring.dropped,
                   "events": self.events}
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------
    def _live(self) -> List[_Replica]:
        return [r for r in self.replicas if r.state == "active"]

    def replica(self, name: str) -> _Replica:
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(f"no replica named {name!r}")

    def _signals(self) -> Tuple[float, Optional[float]]:
        """The admission signals, read from the replicas' EXPORTED
        metric surfaces (queue-depth gauge sum + admission-latency
        histogram mean across live replicas) plus the fleet's own
        pending backlog — the same numbers an operator's scraper
        sees, not internal fields."""
        from ..telemetry import metric_value, parse_prometheus_text
        depth = float(sum(1 for c in self._campaigns.values()
                          if c.pending and not c.handle.done()))
        lat_sum, lat_count = 0.0, 0.0
        for r in self._live():
            parsed = parse_prometheus_text(r.service.metrics_text())
            depth += metric_value(parsed, "stencil_service_queue_depth")
            lat_sum += metric_value(
                parsed, "stencil_service_admission_latency_seconds_sum")
            lat_count += metric_value(
                parsed,
                "stencil_service_admission_latency_seconds_count")
        latency = (lat_sum / lat_count) if lat_count else None
        return depth, latency

    def submit(self, req: CampaignRequest) -> CampaignHandle:
        """Admit one campaign to the fleet; returns the tenant's
        handle. The grid is bucketed first (loud rejection when no
        bucket fits), then the SLO policy may shed the request with a
        named reason, then rendezvous routing decides the owning
        replica at dispatch time (:meth:`pump`)."""
        self._seed_tenant(req.tenant)
        try:
            bucketed, padded = self._bucketer.apply(req)
        except BucketError as e:
            handle = CampaignHandle(req)
            self._log("request_rejected", tenant=req.tenant,
                      campaign=req.campaign, reason="bucket",
                      grid=list(req.grid))
            handle._fail(e)
            return handle
        handle = CampaignHandle(bucketed)
        fp = request_fingerprint(bucketed, devices=self._devices)
        handle.fingerprint = fp
        depth, latency = self._signals()
        reason = self._policy.shed_reason(req.priority, depth, latency)
        if reason is not None:
            self._m_shed.inc(tenant=req.tenant, reason=reason)
            self._log("request_shed", tenant=req.tenant,
                      campaign=req.campaign, reason=reason,
                      priority=req.priority, queue_depth=depth,
                      admission_latency_seconds=latency)
            LOG_WARN(f"fleet shed {req.tenant}/{req.campaign} "
                     f"({reason}: depth={depth}, latency={latency})")
            handle._fail(RequestShed(
                f"{req.tenant}/{req.campaign} shed: {reason} "
                f"(queue_depth={depth}, "
                f"admission_latency={latency})"))
            return handle
        if padded:
            self._log("request_bucketed", tenant=req.tenant,
                      campaign=req.campaign, grid=list(req.grid),
                      bucket=list(bucketed.grid))
        key = (req.tenant, req.campaign)
        if key in self._campaigns and not self._campaigns[key].done:
            raise ValueError(
                f"campaign {req.tenant}/{req.campaign} is already "
                f"admitted and unfinished")
        self._campaigns[key] = _FleetCampaign(
            request=bucketed, handle=handle, fingerprint=fp,
            padded=padded)
        self._log("submitted", tenant=req.tenant,
                  campaign=req.campaign, fingerprint=fp,
                  priority=req.priority)
        return handle

    def route(self, c: _FleetCampaign) -> str:
        """The replica owning this campaign right now: its rebalance
        pin when that replica is live, else the rendezvous owner over
        the live set (fingerprint + tenant — one tenant's
        fingerprint-identical campaigns co-locate, so they batch)."""
        live = self._live()
        if not live:
            raise RuntimeError("fleet has no live replicas")
        if c.pinned is not None \
                and any(r.name == c.pinned for r in live):
            return c.pinned
        return rendezvous_replica(
            f"{c.fingerprint}|{c.request.tenant}",
            [r.name for r in live])

    # ------------------------------------------------------------------
    # the serving rounds
    # ------------------------------------------------------------------
    def pump(self) -> None:
        """One deterministic serving round: fire chaos due this round,
        dispatch pending campaigns to their routed replicas, drain
        each live replica (catching hard crashes), harvest results."""
        r = self._round
        self._round += 1
        self._fire_chaos(r)
        self._dispatch_pending()
        self._drain_replicas()
        self._harvest()

    def serve(self) -> None:
        """Pump rounds until every admitted campaign resolves and no
        scheduled chaos remains — the test/CI entry point."""
        while True:
            busy = any(not c.done and not c.handle.done()
                       for c in self._campaigns.values())
            chaos_left = any(
                ev.fired < ev.repeat
                or (isinstance(ev, SlowReplica)
                    and ev.recover_step is not None
                    and ev.restored < ev.fired)
                for ev in self._chaos)
            if not busy and not chaos_left:
                return
            if self._round >= self._max_rounds:
                raise RuntimeError(
                    f"fleet failed to quiesce within "
                    f"{self._max_rounds} rounds")
            self.pump()

    def _fire_chaos(self, rnd: int) -> None:
        for ev in self._chaos:
            if isinstance(ev, ReplicaCrash):
                if ev.due(rnd):
                    ev.fire(self._log)
                    rep = self.replicas[ev.replica]
                    if rep.state == "active":
                        rep.service.arm_crash_at(ev.at_member_step)
            elif isinstance(ev, SlowReplica):
                if ev.due(rnd):
                    ev.fire(self._log)
                    self._degrade(self.replicas[ev.replica])
                if ev.recover_due(rnd):
                    ev.recover(self._log)
                    self._restore(self.replicas[ev.replica])
            elif isinstance(ev, AdmissionFlood):
                if ev.due(rnd):
                    ev.fire(self._log)
                    for i in range(ev.count):
                        self.submit(CampaignRequest(
                            tenant=ev.tenant,
                            campaign=f"flood-{rnd}-{ev.fired}-{i}",
                            grid=ev.grid, n_steps=ev.n_steps,
                            priority=ev.priority))

    def _dispatch(self, rep: _Replica, req: CampaignRequest
                  ) -> CampaignHandle:
        """Submit to a replica under retry/backoff: a transient
        dispatch failure (an ``OSError``, incl. injected
        :class:`TransientDispatchError`) costs ``base_delay * 2**k``
        backoffs, not the campaign. Every retried failure is a loud
        ``dispatch_retry`` event."""
        def call() -> CampaignHandle:
            if self._dispatch_errors:
                raise self._dispatch_errors.pop(0)
            return rep.service.submit(req)

        def on_retry(attempt: int, exc: BaseException,
                     delay: float) -> None:
            self._log("dispatch_retry", replica=rep.name,
                      tenant=req.tenant, campaign=req.campaign,
                      attempt=attempt, delay_seconds=delay,
                      error=f"{type(exc).__name__}: {exc}")

        return retry(call, attempts=self._retry_attempts,
                     base_delay=self._retry_base_delay,
                     sleep=self._retry_sleep, on_retry=on_retry)

    def inject_dispatch_error(self, *errors: BaseException) -> None:
        """Test/chaos hook: the next ``len(errors)`` replica
        dispatches raise these (in order) before reaching the
        replica — the injectable face of the retry/backoff path."""
        self._dispatch_errors.extend(errors)

    def _dispatch_pending(self) -> None:
        for c in self._campaigns.values():
            if not c.pending or c.handle.done():
                continue
            try:
                name = self.route(c)
            except RuntimeError as e:
                c.handle._fail(e)
                c.done = True
                continue
            rep = self.replica(name)
            # a replica the fleet preempted or readmitted serves again
            rep.service._stop = False
            rep.service._preempt = False
            try:
                inner = self._dispatch(rep, c.request)
            except Exception as e:  # noqa: BLE001 - budget exhausted
                self._log("dispatch_failed", replica=name,
                          tenant=c.request.tenant,
                          campaign=c.request.campaign,
                          error=f"{type(e).__name__}: {e}")
                c.handle._fail(e)
                c.done = True
                continue
            c.replica, c.inner = name, inner

    def _drain_replicas(self) -> None:
        for rep in self.replicas:
            if rep.state != "active" or not len(rep.service.queue):
                continue
            # a replica stopped by graceful preemption serves its
            # remaining queue next round (the fleet, not the stop
            # flag, decides who serves)
            rep.service._stop = False
            rep.service._preempt = False
            try:
                rep.service.drain()
            except ReplicaCrashed as e:
                self._on_replica_crash(rep, e)

    def _harvest(self) -> None:
        for c in self._campaigns.values():
            if c.done or c.inner is None or not c.inner.done():
                continue
            try:
                res = c.inner.result(timeout=0)
            except Exception as e:  # noqa: BLE001 - pass through
                c.handle._fail(e)
                c.done = True
                continue
            if res.preempted and res.steps < c.request.n_steps:
                # graceful preemption checkpointed it mid-run: back to
                # pending; routing/pinning decides where it resumes
                self._log("campaign_requeued",
                          tenant=c.request.tenant,
                          campaign=c.request.campaign,
                          step=res.steps, from_replica=c.replica)
                c.inner = None
                c.replica = None
            else:
                c.handle._resolve(res)
                c.done = True

    # ------------------------------------------------------------------
    # fault tolerance
    # ------------------------------------------------------------------
    def _on_replica_crash(self, rep: _Replica,
                          err: ReplicaCrashed) -> None:
        """A replica hard-crashed mid-batch: mark it dead and recover
        every campaign it held — unresolved inner handles (in-RAM
        lanes died with the process) AND still-queued entries — back
        to pending, where dispatch re-routes them to survivors. The
        campaigns resume from their per-tenant checkpoint namespaces
        on the shared root (bitwise-continuous; zero-loss gate)."""
        rep.state = "dead"
        self._set_replica_gauges()
        self._log("replica_dead", replica=rep.name,
                  error=f"{type(err).__name__}: {err}")
        LOG_WARN(f"fleet: {rep.name} crashed ({err}); recovering its "
                 f"campaigns to survivors")
        # still-queued entries die with the process too
        rep.service.queue.drain_entries()
        for c in self._campaigns.values():
            if c.done or c.replica != rep.name:
                continue
            if c.inner is not None and c.inner.done():
                continue        # resolved before the crash: harvest it
            c.inner = None
            c.replica = None
            c.recoveries += 1
            self._m_recovered.inc()
            self._log("campaign_recovered", tenant=c.request.tenant,
                      campaign=c.request.campaign,
                      from_replica=rep.name)

    def _degrade(self, rep: _Replica) -> None:
        """The degradation ladder's first rungs for a slow replica:
        drain it (no new dispatches) and reshard its tenants — queued
        entries and unfinished campaigns go back to pending, where
        routing re-spreads them over the survivors."""
        if rep.state != "active":
            return
        rep.state = "degraded"
        self._set_replica_gauges()
        self._log("replica_degraded", replica=rep.name)
        rep.service.queue.drain_entries()
        for c in self._campaigns.values():
            if c.done or c.replica != rep.name:
                continue
            if c.inner is not None and c.inner.done():
                continue
            c.inner = None
            c.replica = None
            self._log("campaign_resharded", tenant=c.request.tenant,
                      campaign=c.request.campaign,
                      from_replica=rep.name)

    def _restore(self, rep: _Replica) -> None:
        """The ladder's last rung: readmit a recovered replica to the
        active set (routing sees it again on the next dispatch)."""
        if rep.state != "degraded":
            return
        rep.state = "active"
        rep.service._stop = False
        rep.service._preempt = False
        self._set_replica_gauges()
        self._log("replica_recovered", replica=rep.name)
        LOG_INFO(f"fleet: {rep.name} readmitted to the active set")

    # ------------------------------------------------------------------
    # rebalancing
    # ------------------------------------------------------------------
    def loads(self) -> Dict[str, int]:
        """Unfinished campaigns per live replica (current routing) —
        the signal :meth:`rebalance` balances."""
        load = {r.name: 0 for r in self._live()}
        if not load:
            return load
        for c in self._campaigns.values():
            if c.done or c.handle.done():
                continue
            name = c.replica if c.replica in load else self.route(c)
            if name in load:
                load[name] += 1
        return load

    def migrate(self, tenant: str, campaign: str, dst: str) -> None:
        """Move one campaign to replica ``dst``: preempt-on-src (take
        it back from the source queue if still queued; arm graceful
        preemption if it is mid-batch — the campaign checkpoints and
        returns to pending at the next boundary) then resume-on-dst
        (the pin routes it there on the next dispatch). The shared
        plan cache means ``dst`` re-tunes nothing; a ``dst`` that
        already built the fingerprint recompiles nothing."""
        c = self._campaigns.get((tenant, campaign))
        if c is None or c.done:
            raise KeyError(f"no unfinished campaign "
                           f"{tenant}/{campaign} to migrate")
        self.replica(dst)       # validate the destination exists
        src = c.replica
        if src is not None and c.inner is not None \
                and not c.inner.done():
            entry = self.replica(src).service.queue.take(tenant,
                                                         campaign)
            if entry is None:
                # mid-batch on src: graceful preemption brings it back
                # to pending at the next segment boundary
                self.replica(src).service.preempt()
            c.inner = None
        c.pinned = dst
        c.replica = None
        self._m_migrations.inc()
        self._log("migration", tenant=tenant, campaign=campaign,
                  from_replica=src, to_replica=dst)

    def rebalance(self) -> List[Dict]:
        """Pick migrations from per-replica load and execute them
        (:meth:`migrate`): while the most- and least-loaded live
        replicas differ by >= 2 campaigns, move the youngest movable
        campaign from the former to the latter. Returns the executed
        migration records."""
        out: List[Dict] = []
        while True:
            load = self.loads()
            if len(load) < 2:
                return out
            src = max(load, key=lambda n: (load[n], n))
            dst = min(load, key=lambda n: (load[n], n))
            if load[src] - load[dst] < 2:
                return out
            movable = [c for c in self._campaigns.values()
                       if not c.done and not c.handle.done()
                       and (c.replica or self.route(c)) == src]
            if not movable:
                return out
            c = movable[-1]
            self.migrate(c.request.tenant, c.request.campaign, dst)
            out.append({"tenant": c.request.tenant,
                        "campaign": c.request.campaign,
                        "from": src, "to": dst})
