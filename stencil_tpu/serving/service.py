"""The async multi-tenant campaign service.

One :class:`CampaignService` owns a request queue, a cache of compiled
ensemble engines keyed by problem fingerprint, and the persistent
tuning-plan cache. The worker loop:

1. **admit** — pop the oldest request plus every fingerprint-identical
   one (:meth:`..serving.queue.RequestQueue.pop_batch`) into one batch
   of at most ``width`` members;
2. **plan** — a plan-cache hit supplies the exchange configuration
   with ZERO measurements; a miss tunes once (injectable timer; depth
   pinned to 1 — the batched step exchanges every step) and persists
   the plan for every later fingerprint-identical request;
3. **compile** — the engine cache returns the already-built executable
   for a known fingerprint (zero recompiles); only a brand-new
   fingerprint constructs (and therefore compiles) an engine;
4. **run** — the segment loop advances ALL lanes per dispatch,
   probing per-member health, streaming snapshots through non-blocking
   ``is_ready`` polling, checkpointing each campaign into its tenant
   namespace, and rolling back ONLY the tripped member's lane on a
   fault (bounded retries per campaign, then the campaign fails while
   its batch-mates keep running);
5. **preempt/resume** — :meth:`CampaignService.preempt` checkpoints
   every active campaign (tagged ``preempted``) and stops; resubmitting
   a campaign whose namespace holds checkpoints resumes it from the
   newest restorable step.

Everything lands in a JSON-serializable event log (the CI service-smoke
artifact) plus :class:`ServiceStats` counters the smoke asserts on.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from ..utils.checkpoint import all_steps, validate_checkpoint_component
from ..utils.logging import LOG_INFO, LOG_WARN
from .ensemble import EnsembleAstaroth, EnsembleJacobi, EnsembleSentinel
from .queue import CampaignRequest, RequestQueue


class CampaignFailed(RuntimeError):
    """A campaign exhausted its per-tenant retry budget."""


@dataclasses.dataclass
class ServiceStats:
    """Counters the CI service smoke asserts on."""

    batches: int = 0
    compiles: int = 0            # engine constructions (new fingerprint)
    plan_cache_hits: int = 0
    tuner_measurements: int = 0  # total timer invocations
    completed: int = 0
    failed: int = 0
    rollbacks: int = 0

    def to_record(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CampaignResult:
    """What a completed campaign hands back to its tenant."""

    tenant: str
    campaign: str
    steps: int
    rollbacks: int = 0
    resumed_from: Optional[int] = None
    preempted: bool = False
    #: (member_step, {quantity: global interior}) in step order
    snapshots: List = dataclasses.field(default_factory=list)
    #: {quantity: global interior} at the final step
    final: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _Lane:
    """One campaign's slot in a running batch."""

    entry: object                # queue._Entry
    index: int                   # lane index in the ensemble
    ckpt_dir: str
    counter: int = 0             # member steps completed
    rollbacks: int = 0
    resumed_from: Optional[int] = None
    active: bool = True
    chaos_fired: bool = False
    snapshots: Dict[int, Dict[str, np.ndarray]] = \
        dataclasses.field(default_factory=dict)

    @property
    def request(self) -> CampaignRequest:
        return self.entry.request


class CampaignService:
    """Batched multi-tenant campaign server over one device set."""

    def __init__(self, root_dir: str, devices=None, width: int = 8,
                 tuner_timer=None, plan_cache_path=None,
                 window: int = 8, growth_factor: float = 1e6,
                 max_to_keep: int = 3) -> None:
        if int(width) < 1:
            raise ValueError(f"width must be >= 1, got {width}")
        self.root = Path(root_dir)
        self.root.mkdir(parents=True, exist_ok=True)
        self.width = int(width)
        self._devices = devices
        self._tuner_timer = tuner_timer
        self._plan_cache_path = plan_cache_path
        self._window = int(window)
        self._growth_factor = float(growth_factor)
        self._max_to_keep = int(max_to_keep)
        self.queue = RequestQueue(devices)
        self.stats = ServiceStats()
        self.events: List[Dict] = []
        self._events_lock = threading.Lock()
        self._engines: Dict[str, object] = {}
        self._sentinels: Dict[str, EnsembleSentinel] = {}
        self._preempt = False
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # client API
    # ------------------------------------------------------------------
    def submit(self, req: CampaignRequest):
        """Queue a campaign; returns its :class:`~.queue.
        CampaignHandle`. If the campaign's tenant namespace already
        holds checkpoints (a preempted earlier run), it resumes from
        the newest restorable step."""
        handle = self.queue.submit(req)
        self._log("submitted", tenant=req.tenant, campaign=req.campaign,
                  fingerprint=handle.fingerprint)
        return handle

    def drain(self) -> None:
        """Synchronously serve batches until the queue is empty (the
        test/CLI entry; :meth:`start` is the async one)."""
        while len(self.queue) and not self._stop:
            batch = self.queue.pop_batch(self.width)
            if not batch:
                break
            self._run_batch(batch)

    def start(self) -> None:
        """Serve from a background worker thread until :meth:`stop`."""
        if self._thread is not None:
            raise RuntimeError("service already started")
        self._stop = False

        def worker():
            while not self._stop:
                if not self.queue.wait_nonempty(timeout=0.05):
                    continue
                batch = self.queue.pop_batch(self.width)
                if batch:
                    self._run_batch(batch)

        self._thread = threading.Thread(target=worker,
                                        name="campaign-service",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop = True
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def preempt(self) -> None:
        """Fleet reclaim: the current batch checkpoints every active
        campaign (tagged ``preempted``) at the next segment boundary
        and the worker stops; resubmitting the campaigns resumes them
        from those checkpoints."""
        self._preempt = True
        self._stop = True

    def namespace(self, tenant: str, campaign: str) -> Path:
        """``root/<tenant>/<campaign>`` — both components validated
        against path traversal before they touch the filesystem."""
        t = validate_checkpoint_component(tenant, kind="tenant id")
        c = validate_checkpoint_component(campaign, kind="campaign id")
        return self.root / t / c

    def write_events(self, path: str) -> None:
        with self._events_lock:
            payload = {"stats": self.stats.to_record(),
                       "events": list(self.events)}
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _log(self, kind: str, **kw) -> None:
        with self._events_lock:
            self.events.append({"event": kind, "time": time.time(),
                                **kw})

    def _plan_for(self, fingerprint: str, req: CampaignRequest):
        """The exchange plan for a fingerprint: cache hit (zero
        measurements) or a one-time tune when a timer is configured
        (depth pinned to 1 — see module docstring)."""
        from ..tuning import load_plan
        plan = load_plan(fingerprint, self._plan_cache_path)
        if plan is not None:
            plan.provenance = "cached"
            plan.measurements = 0
            self.stats.plan_cache_hits += 1
            return plan
        if self._tuner_timer is None:
            return None
        import jax.numpy as jnp

        from ..topology import Boundary
        from ..tuning import autotune_domain
        from .ensemble import configured_domain
        dd = configured_domain(req.model, req.grid,
                               dtype=jnp.dtype(req.dtype),
                               boundary=Boundary[req.boundary],
                               mesh_shape=req.mesh_shape,
                               devices=self._devices)
        plan = autotune_domain(dd, timer=self._tuner_timer,
                               cache_path=self._plan_cache_path,
                               depths=(1,))
        assert plan.fingerprint == fingerprint, \
            (plan.fingerprint, fingerprint)
        self.stats.tuner_measurements += plan.measurements
        return plan

    def _engine_for(self, fingerprint: str, req: CampaignRequest):
        """The compiled ensemble engine for a fingerprint — built once,
        reused for every later fingerprint-identical batch."""
        eng = self._engines.get(fingerprint)
        if eng is not None:
            return eng, False, None
        import jax.numpy as jnp

        from ..topology import Boundary
        plan = self._plan_for(fingerprint, req)
        cls = EnsembleJacobi if req.model == "jacobi" else EnsembleAstaroth
        eng = cls(self.width, *req.grid, dtype=jnp.dtype(req.dtype),
                  boundary=Boundary[req.boundary],
                  mesh_shape=req.mesh_shape, devices=self._devices,
                  plan=plan)
        assert eng.fingerprint == fingerprint, \
            (eng.fingerprint, fingerprint)
        self._engines[fingerprint] = eng
        self._sentinels[fingerprint] = EnsembleSentinel(
            eng, window=self._window,
            growth_factor=self._growth_factor)
        self.stats.compiles += 1
        return eng, True, plan

    def _admit_lane(self, eng, lane: _Lane) -> None:
        """Set up one lane: parameters, resume-or-init, and the step-0
        rollback anchor checkpoint."""
        req = lane.request
        k = lane.index
        eng.reset_member(k)
        if req.params:
            eng.set_member_params(k, req.params)
        if all_steps(lane.ckpt_dir):
            step = eng.restore_member(lane.ckpt_dir, k)
            lane.counter = step
            lane.resumed_from = step
            self._log("resumed", tenant=req.tenant,
                      campaign=req.campaign, step=step)
            LOG_INFO(f"campaign {req.tenant}/{req.campaign} resumes "
                     f"from step {step}")
        else:
            eng.init_member(k, req.init_seed)
            eng.save_member(lane.ckpt_dir, 0, k,
                            max_to_keep=self._max_to_keep)
            self._log("checkpoint", tenant=req.tenant,
                      campaign=req.campaign, step=0)

    @staticmethod
    def _steps_to_boundary(lane: _Lane) -> int:
        """Member steps until lane's next event: completion, probe,
        checkpoint, snapshot, or chaos injection."""
        req = lane.request
        c = lane.counter
        cands = [req.n_steps - c]
        for cad in (req.check_every, req.ckpt_every,
                    req.snapshot_every):
            if cad and cad > 0:
                cands.append(cad - (c % cad))
        if req.chaos_nan_step is not None and not lane.chaos_fired \
                and req.chaos_nan_step > c:
            cands.append(req.chaos_nan_step - c)
        return max(1, min(x for x in cands if x > 0))

    def _inject_nan(self, eng, lane: _Lane) -> None:
        q = eng.names[0]
        host = eng.member_interior(q, lane.index)
        host[tuple(0 for _ in host.shape)] = np.nan
        eng.set_member_interior(q, lane.index, host)
        lane.chaos_fired = True
        self._log("fault_injected", tenant=lane.request.tenant,
                  campaign=lane.request.campaign, step=lane.counter,
                  quantity=q)

    def _handle_trip(self, eng, sentinel, lane: _Lane,
                     reason: str) -> None:
        req = lane.request
        self._log("sentinel_tripped", tenant=req.tenant,
                  campaign=req.campaign, member=lane.index,
                  step=lane.counter, reason=reason,
                  attempt=lane.rollbacks + 1)
        LOG_WARN(f"campaign {req.tenant}/{req.campaign}: sentinel "
                 f"tripped at member step {lane.counter} ({reason}), "
                 f"attempt {lane.rollbacks + 1}/{req.max_retries}")
        sentinel.reset_member(lane.index)
        # rollback counters count RESTORES performed, not trips — a
        # campaign that fails on its first trip reports zero rollbacks
        if lane.rollbacks >= req.max_retries:
            lane.active = False
            eng.reset_member(lane.index)
            self.stats.failed += 1
            self._log("campaign_failed", tenant=req.tenant,
                      campaign=req.campaign, reason=reason)
            lane.entry.handle._fail(CampaignFailed(
                f"{req.tenant}/{req.campaign}: retries exhausted "
                f"({req.max_retries}) at step {lane.counter}: "
                f"{reason}"))
            return
        step = eng.restore_member(lane.ckpt_dir, lane.index)
        lane.counter = step
        lane.rollbacks += 1
        self.stats.rollbacks += 1
        self._log("rollback", tenant=req.tenant, campaign=req.campaign,
                  member=lane.index, restored_step=step)

    def _complete_lane(self, eng, lane: _Lane,
                       preempted: bool = False) -> None:
        req = lane.request
        final = eng.member_interiors(lane.index)
        result = CampaignResult(
            tenant=req.tenant, campaign=req.campaign,
            steps=lane.counter, rollbacks=lane.rollbacks,
            resumed_from=lane.resumed_from, preempted=preempted,
            snapshots=sorted(lane.snapshots.items()), final=final)
        lane.active = False
        if preempted:
            self._log("campaign_preempted", tenant=req.tenant,
                      campaign=req.campaign, step=lane.counter)
        else:
            self.stats.completed += 1
            self._log("campaign_completed", tenant=req.tenant,
                      campaign=req.campaign, steps=lane.counter,
                      rollbacks=lane.rollbacks)
        lane.entry.handle._resolve(result)

    def _run_batch(self, batch) -> None:
        fp = batch[0].fingerprint
        req0 = batch[0].request
        eng, compiled, plan = self._engine_for(fp, req0)
        sentinel = self._sentinels[fp]
        sentinel.reset()
        self.stats.batches += 1
        self._log(
            "batch_started", fingerprint=fp, members=len(batch),
            width=eng.n_members, compiled=compiled,
            plan_provenance=(eng.dd.plan_provenance),
            measurements=(plan.measurements if plan is not None
                          and plan.provenance == "tuned" else 0),
            tenants=[e.request.tenant for e in batch])
        lanes = [
            _Lane(entry=e, index=k,
                  ckpt_dir=str(self.namespace(e.request.tenant,
                                              e.request.campaign)))
            for k, e in enumerate(batch)]
        for lane in lanes:
            try:
                self._admit_lane(eng, lane)
            except Exception as err:  # noqa: BLE001 - admission faults
                lane.active = False
                self.stats.failed += 1
                self._log("campaign_failed",
                          tenant=lane.request.tenant,
                          campaign=lane.request.campaign,
                          reason=f"admission: {err}")
                lane.entry.handle._fail(err)
        # idle lanes of a partially-filled batch stay benign
        for k in range(len(batch), eng.n_members):
            eng.reset_member(k)
        # a resubmitted campaign whose restored checkpoint already
        # meets the requested budget completes immediately — it must
        # not run past n_steps
        for lane in lanes:
            if lane.active and lane.counter >= lane.request.n_steps:
                self._complete_lane(eng, lane)

        pending_snaps: List = []

        def poll_snapshots(block: bool = False) -> None:
            remaining = []
            for lane, snap in pending_snaps:
                if block or snap.ready():
                    if lane.active and snap.step <= \
                            lane.request.n_steps:
                        lane.snapshots[snap.step] = snap.get()
                else:
                    remaining.append((lane, snap))
            pending_snaps[:] = remaining

        while any(lane.active for lane in lanes):
            if self._preempt:
                # drain in-flight probes; never persist poisoned state
                for health in sentinel.poll(block=True):
                    for k in health.tripped_members:
                        lane = next((ln for ln in lanes
                                     if ln.index == k and ln.active),
                                    None)
                        if lane is not None:
                            self._handle_trip(
                                eng, sentinel, lane,
                                health.members[k].reason)
                # harvest in-flight snapshots BEFORE materializing the
                # preempted results — completion deactivates the lane
                # and would silently drop them
                poll_snapshots(block=True)
                for lane in lanes:
                    if lane.active:
                        eng.save_member(lane.ckpt_dir, lane.counter,
                                        lane.index,
                                        meta_extra={"preempted": True},
                                        max_to_keep=self._max_to_keep)
                        self._log("checkpoint",
                                  tenant=lane.request.tenant,
                                  campaign=lane.request.campaign,
                                  step=lane.counter, preempted=True)
                        self._complete_lane(eng, lane, preempted=True)
                self._log("preempted", fingerprint=fp)
                return
            seg = min(self._steps_to_boundary(lane)
                      for lane in lanes if lane.active)
            eng.run(seg)
            for lane in lanes:
                if lane.active:
                    lane.counter += seg
            # chaos injections land AFTER the step that reaches them
            for lane in lanes:
                req = lane.request
                if (lane.active and req.chaos_nan_step is not None
                        and not lane.chaos_fired
                        and lane.counter >= req.chaos_nan_step):
                    self._inject_nan(eng, lane)
            sentinel.probe(max(lane.counter for lane in lanes))
            poll_snapshots()
            # blocking drain BEFORE any checkpoint/completion below —
            # the same invariant as the resilience driver: poisoned
            # state is never persisted or handed back
            tripped: Dict[int, str] = {}
            for health in sentinel.poll(block=True):
                for k in health.tripped_members:
                    tripped.setdefault(k, health.members[k].reason)
            for lane in list(lanes):
                if not lane.active:
                    continue
                req = lane.request
                if lane.index in tripped:
                    self._handle_trip(eng, sentinel, lane,
                                      tripped[lane.index])
                    continue
                if (req.snapshot_every and lane.counter
                        and lane.counter % req.snapshot_every == 0
                        and lane.counter < req.n_steps):
                    pending_snaps.append(
                        (lane, eng.member_snapshot_async(
                            lane.index, lane.counter)))
                    self._log("snapshot_enqueued", tenant=req.tenant,
                              campaign=req.campaign, step=lane.counter)
                if (req.ckpt_every and lane.counter
                        and lane.counter % req.ckpt_every == 0
                        and lane.counter < req.n_steps):
                    eng.save_member(lane.ckpt_dir, lane.counter,
                                    lane.index,
                                    max_to_keep=self._max_to_keep)
                    self._log("checkpoint", tenant=req.tenant,
                              campaign=req.campaign, step=lane.counter)
                if lane.counter >= req.n_steps:
                    eng.save_member(lane.ckpt_dir, lane.counter,
                                    lane.index,
                                    meta_extra={"completed": True},
                                    max_to_keep=self._max_to_keep)
                    poll_snapshots(block=True)
                    self._complete_lane(eng, lane)
        poll_snapshots(block=True)
        self._log("batch_finished", fingerprint=fp)
