"""The async multi-tenant campaign service.

One :class:`CampaignService` owns a request queue, a cache of compiled
ensemble engines keyed by problem fingerprint, and the persistent
tuning-plan cache. The worker loop:

1. **admit** — pop the oldest request plus every fingerprint-identical
   one (:meth:`..serving.queue.RequestQueue.pop_batch`) into one batch
   of at most ``width`` members;
2. **plan** — a plan-cache hit supplies the exchange configuration
   with ZERO measurements; a miss tunes once (injectable timer; depth
   pinned to 1 — the batched step exchanges every step) and persists
   the plan for every later fingerprint-identical request;
3. **compile** — the engine cache returns the already-built executable
   for a known fingerprint (zero recompiles); only a brand-new
   fingerprint constructs (and therefore compiles) an engine;
4. **run** — the segment loop advances ALL lanes per dispatch,
   probing per-member health, streaming snapshots through non-blocking
   ``is_ready`` polling, checkpointing each campaign into its tenant
   namespace, and rolling back ONLY the tripped member's lane on a
   fault (bounded retries per campaign, then the campaign fails while
   its batch-mates keep running);
5. **preempt/resume** — :meth:`CampaignService.preempt` checkpoints
   every active campaign (tagged ``preempted``) and stops; resubmitting
   a campaign whose namespace holds checkpoints resumes it from the
   newest restorable step.

Observability is the unified telemetry layer (:mod:`..telemetry`):
events flow through the versioned :class:`~..telemetry.EventLog` into
a BOUNDED in-memory ring (flat memory over millions of requests) and
out to the JSON artifact; spans (campaign.batch -> segment ->
compile/tune/checkpoint/rollback) export as Perfetto-loadable Chrome
trace JSON via :meth:`CampaignService.export_trace`; and the metric
surface (:meth:`CampaignService.metrics_text` Prometheus text /
:meth:`~CampaignService.metrics_snapshot` JSON, served over HTTP by
``apps/serve.py --metrics-port``) is what the warm-path CI gates
assert on — zero ``stencil_service_recompiles_total``, zero
``stencil_service_tuner_measurements_total`` on cache hits — instead
of internal fields. :class:`ServiceStats` remains as the legacy
in-process counter block.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from ..analysis.recompile import (ASSERT_SINGLE_COMPILE_ENV,
                                  SingleCompileGuard)
from ..analysis.transfer import hot_loop_transfer_guard
from ..utils.checkpoint import all_steps, validate_checkpoint_component
from ..utils.logging import LOG_INFO, LOG_WARN
from .ensemble import EnsembleAstaroth, EnsembleJacobi, EnsembleSentinel
from .queue import CampaignRequest, RequestQueue


class CampaignFailed(RuntimeError):
    """A campaign exhausted its per-tenant retry budget."""


class ReplicaCrashed(RuntimeError):
    """Deterministic chaos: this replica was hard-killed mid-batch
    (see :meth:`CampaignService.arm_crash_at`). Unlike preemption,
    NOTHING is checkpointed or resolved on the way out — in-RAM lane
    state newer than the last periodic checkpoint is lost, exactly
    like a real process death. The fleet recovers the replica's
    campaigns from their per-tenant checkpoint namespaces."""


def _block_state(eng) -> None:
    """Fence the ensemble's live state (the attribution clock must not
    credit async dispatch with seconds it merely deferred)."""
    import jax

    jax.block_until_ready(eng.state)


@dataclasses.dataclass
class ServiceStats:
    """Counters the CI service smoke asserts on."""

    batches: int = 0
    compiles: int = 0            # engine constructions (new fingerprint)
    plan_cache_hits: int = 0
    tuner_measurements: int = 0  # total timer invocations
    completed: int = 0
    failed: int = 0
    rollbacks: int = 0

    def to_record(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CampaignResult:
    """What a completed campaign hands back to its tenant."""

    tenant: str
    campaign: str
    steps: int
    rollbacks: int = 0
    resumed_from: Optional[int] = None
    preempted: bool = False
    #: (member_step, {quantity: global interior}) in step order
    snapshots: List = dataclasses.field(default_factory=list)
    #: {quantity: global interior} at the final step
    final: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _Lane:
    """One campaign's slot in a running batch."""

    entry: object                # queue._Entry
    index: int                   # lane index in the ensemble
    ckpt_dir: str
    counter: int = 0             # member steps completed
    rollbacks: int = 0
    resumed_from: Optional[int] = None
    active: bool = True
    chaos_fired: bool = False
    snapshots: Dict[int, Dict[str, np.ndarray]] = \
        dataclasses.field(default_factory=dict)

    @property
    def request(self) -> CampaignRequest:
        return self.entry.request


class CampaignService:
    """Batched multi-tenant campaign server over one device set."""

    def __init__(self, root_dir: str, devices=None, width: int = 8,
                 tuner_timer=None, plan_cache_path=None,
                 window: int = 8, growth_factor: float = 1e6,
                 max_to_keep: int = 3, events_capacity: int = 4096,
                 run_id: Optional[str] = None, registry=None,
                 tracer=None, fuse_segments: bool = True,
                 flight_recorder_dir: Optional[str] = None,
                 attribute_perf: bool = True,
                 drift_tolerance: float = 0.5, drift_window: int = 3,
                 retune_on_drift: bool = False) -> None:
        if int(width) < 1:
            raise ValueError(f"width must be >= 1, got {width}")
        #: megastep mode (default): each batch segment is ONE fused
        #: dispatch carrying the per-member probe trace in-graph
        #: (parallel/megastep.py) instead of a step loop + separate
        #: probe dispatch
        self._fuse = bool(fuse_segments)
        self.root = Path(root_dir)
        self.root.mkdir(parents=True, exist_ok=True)
        self.width = int(width)
        self._devices = devices
        self._tuner_timer = tuner_timer
        self._plan_cache_path = plan_cache_path
        self._window = int(window)
        self._growth_factor = float(growth_factor)
        self._max_to_keep = int(max_to_keep)
        self.queue = RequestQueue(devices,
                                  on_expired=self._on_request_expired)
        self.stats = ServiceStats()
        # unified telemetry: events through the versioned EventLog into
        # a BOUNDED ring (a long-running service holds flat memory over
        # millions of requests; `dropped` in the payload makes the
        # truncation visible), metrics through a per-service registry,
        # spans through a per-service tracer sharing the run id
        from ..telemetry import (EventLog, MetricsRegistry, RingSink,
                                 Tracer)
        self._ring = RingSink(events_capacity)
        self._elog = EventLog(run_id=run_id, sinks=(self._ring,))
        self.run_id = self._elog.run_id
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        self.tracer = tracer if tracer is not None \
            else Tracer(run_id=self.run_id)
        self._register_metrics()
        self._engines: Dict[str, object] = {}
        #: fingerprints EVER built — a construction for a known
        #: fingerprint is a recompile (warm-path regression)
        self._built: set = set()
        self._sentinels: Dict[str, EnsembleSentinel] = {}
        #: recompile watchdog (analysis/recompile.py): armed via
        #: STENCIL_ASSERT_SINGLE_COMPILE=1 — a cached engine whose
        #: step/segment programs re-trace between dispatches raises
        #: instead of silently recompiling per batch
        self._compile_guard = (
            SingleCompileGuard()
            if os.environ.get(ASSERT_SINGLE_COMPILE_ENV) == "1"
            else None)
        # performance observatory: per-engine model-vs-measured
        # attribution (observatory/attribution.py — host wall clock,
        # the dispatched program is unchanged) and the bounded flight
        # recorder (observatory/recorder.py) dumped on sentinel trip,
        # preemption, and unhandled batch errors
        self._attribute = bool(attribute_perf)
        self._drift_tolerance = float(drift_tolerance)
        self._drift_window = int(drift_window)
        self._retune_on_drift = bool(retune_on_drift)
        self._attributors: Dict[str, object] = {}
        from ..observatory.recorder import ENV_FLIGHT_DIR, FlightRecorder
        self._flight_dir = (flight_recorder_dir
                            or os.environ.get(ENV_FLIGHT_DIR) or None)
        self.flight = None
        if self._flight_dir:
            self.flight = FlightRecorder(run_id=self.run_id,
                                         registry=self.metrics,
                                         tracer=self.tracer)
            self._elog.add_sink(self.flight)
        self._preempt = False
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # deterministic chaos thresholds (member steps), armed by the
        # fleet's fault plan; checked at segment boundaries
        self._crash_at_step: Optional[int] = None
        self._preempt_at_step: Optional[int] = None

    def _register_metrics(self) -> None:
        """Declare the service metric surface (names and labels are a
        stable contract — README "Observability")."""
        m = self.metrics
        self._m_requests = m.counter(
            "stencil_service_requests_total",
            "campaign requests submitted, by tenant")
        self._m_queue_depth = m.gauge(
            "stencil_service_queue_depth",
            "requests waiting for admission")
        self._m_admission = m.histogram(
            "stencil_service_admission_latency_seconds",
            "submit-to-batch-start latency")
        self._m_batches = m.counter(
            "stencil_service_batches_total", "ensemble batches served")
        self._m_occupancy = m.gauge(
            "stencil_service_batch_occupancy_ratio",
            "members / width of the last admitted batch")
        self._m_compiles = m.counter(
            "stencil_service_compiles_total",
            "engine constructions (every build; recompiles_total "
            "counts the already-seen-fingerprint subset)")
        self._m_recompiles = m.counter(
            "stencil_service_recompiles_total",
            "engine constructions for an ALREADY-SEEN fingerprint — "
            "warm-path regressions; 0 on a healthy service")
        self._m_engine_hits = m.counter(
            "stencil_service_engine_cache_hits_total",
            "batches served by an already-built engine")
        self._m_engine_size = m.gauge(
            "stencil_service_engine_cache_size", "engines resident")
        self._m_plan_hits = m.counter(
            "stencil_service_plan_cache_hits_total",
            "exchange plans served from the persistent cache")
        self._m_plan_misses = m.counter(
            "stencil_service_plan_cache_misses_total",
            "fingerprints that had to tune (or run untuned)")
        self._m_tuner = m.counter(
            "stencil_service_tuner_measurements_total",
            "tuner timer invocations; 0 on the warm path")
        self._m_rollbacks = m.counter(
            "stencil_service_rollbacks_total",
            "member-isolated rollbacks, by tenant")
        self._m_campaigns = m.counter(
            "stencil_service_campaigns_total",
            "campaign outcomes, by tenant and outcome "
            "(completed|failed|preempted)")
        self._m_steps = m.counter(
            "stencil_service_member_steps_total",
            "member steps advanced across all lanes")
        self._m_steps_per_s = m.gauge(
            "stencil_service_member_steps_per_s",
            "member steps/s of the last served batch")
        self._m_checkpoints = m.counter(
            "stencil_service_checkpoints_total",
            "member checkpoints written")
        self._m_snapshots = m.counter(
            "stencil_service_snapshots_total",
            "streaming snapshots enqueued")
        self._m_fused_dispatch = m.counter(
            "stencil_run_fused_dispatch_total",
            "compiled-program dispatches by the batch loop, labeled "
            "fused=true (one megastep covering k member steps) or "
            "fused=false (one stepwise run dispatch) — the fleet "
            "signal for campaigns still running stepwise")
        # unlabeled counters export an explicit 0 sample from birth
        # (prometheus_client semantics): the warm-path gates assert
        # recompiles/tuner-measurements == 0 against a series that
        # EXISTS, and a scraper sees the 0 baseline before the first
        # increment; labeled counters appear on first labeled inc
        for c in (self._m_batches, self._m_compiles,
                  self._m_recompiles, self._m_engine_hits,
                  self._m_plan_hits, self._m_plan_misses,
                  self._m_tuner, self._m_steps, self._m_checkpoints,
                  self._m_snapshots):
            c.inc(0)
        for fused in ("true", "false"):
            self._m_fused_dispatch.inc(0, fused=fused)

    # ------------------------------------------------------------------
    # telemetry surfaces
    # ------------------------------------------------------------------
    @property
    def events(self) -> List[Dict]:
        """The newest events (bounded ring — see ``events_capacity``)."""
        return self._ring.records()

    def metrics_text(self) -> str:
        """Prometheus text exposition of the service metrics — the
        surface the warm-path CI gates and tests assert on (external
        contract, not internal fields)."""
        return self.metrics.to_prometheus_text()

    def metrics_snapshot(self) -> Dict:
        """JSON-serializable metrics snapshot (the CI artifact)."""
        return self.metrics.snapshot()

    def export_trace(self, path: str) -> None:
        """Chrome trace-event JSON of this service's spans (Perfetto)."""
        self.tracer.export_chrome_trace(path)

    # ------------------------------------------------------------------
    # client API
    # ------------------------------------------------------------------
    def submit(self, req: CampaignRequest):
        """Queue a campaign; returns its :class:`~.queue.
        CampaignHandle`. If the campaign's tenant namespace already
        holds checkpoints (a preempted earlier run), it resumes from
        the newest restorable step."""
        handle = self.queue.submit(req)
        self._m_requests.inc(tenant=req.tenant)
        self._m_queue_depth.set(len(self.queue))
        self._log("submitted", tenant=req.tenant, campaign=req.campaign,
                  fingerprint=handle.fingerprint)
        return handle

    def drain(self) -> None:
        """Synchronously serve batches until the queue is empty (the
        test/CLI entry; :meth:`start` is the async one)."""
        while len(self.queue) and not self._stop:
            batch = self.queue.pop_batch(self.width)
            if not batch:
                break
            self._run_batch(batch)
        self._m_queue_depth.set(len(self.queue))

    def start(self) -> None:
        """Serve from a background worker thread until :meth:`stop`."""
        if self._thread is not None:
            raise RuntimeError("service already started")
        self._stop = False

        def worker():
            while not self._stop:
                if not self.queue.wait_nonempty(timeout=0.05):
                    continue
                batch = self.queue.pop_batch(self.width)
                if batch:
                    self._run_batch(batch)

        self._thread = threading.Thread(target=worker,
                                        name="campaign-service",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop = True
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def preempt(self) -> None:
        """Fleet reclaim: the current batch checkpoints every active
        campaign (tagged ``preempted``) at the next segment boundary
        and the worker stops; resubmitting the campaigns resumes them
        from those checkpoints."""
        self._preempt = True
        self._stop = True

    def arm_crash_at(self, member_step: int) -> None:
        """Deterministic chaos: hard-crash this replica (raise
        :class:`ReplicaCrashed` out of the serving loop) at the first
        segment boundary where any lane's member step reaches
        ``member_step``. Periodic checkpoints written BEFORE the
        boundary survive; everything newer is lost — the recovery
        path the fleet's zero-loss gate exercises."""
        self._crash_at_step = int(member_step)

    def arm_preempt_at(self, member_step: int) -> None:
        """Deterministic chaos: trip the graceful preemption path
        (checkpoint every active campaign, tagged ``preempted``) at
        the first segment boundary where any lane reaches
        ``member_step`` — the fleet's migration primitive, made
        step-deterministic for bitwise tests."""
        self._preempt_at_step = int(member_step)

    def namespace(self, tenant: str, campaign: str) -> Path:
        """``root/<tenant>/<campaign>`` — both components validated
        against path traversal before they touch the filesystem."""
        t = validate_checkpoint_component(tenant, kind="tenant id")
        c = validate_checkpoint_component(campaign, kind="campaign id")
        return self.root / t / c

    def write_events(self, path: str) -> None:
        from ..telemetry import EVENT_SCHEMA_VERSION
        payload = {"schema": EVENT_SCHEMA_VERSION, "run": self.run_id,
                   "dropped_events": self._ring.dropped,
                   "stats": self.stats.to_record(),
                   "events": self.events}
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _log(self, kind: str, **kw) -> None:
        # events correlate with the enclosing telemetry span (if any)
        self._elog.emit(kind, span=self.tracer.current_span_id(), **kw)

    def _on_request_expired(self, entry) -> None:
        """Queue hook: a request's deadline passed before admission —
        loud (v1-schema event), never a silent drop."""
        req = entry.request
        self._log("request_expired", tenant=req.tenant,
                  campaign=req.campaign,
                  deadline_seconds=req.deadline_seconds)
        self.stats.failed += 1
        self._m_campaigns.inc(tenant=req.tenant, outcome="expired")

    def _flight_dump(self, reason: str, **attrs) -> None:
        from ..observatory.recorder import safe_dump
        safe_dump(self.flight, self._flight_dir, reason, **attrs)

    def _make_attributor(self, eng):
        """A :class:`~stencil_tpu.observatory.PerfAttributor` for one
        cached engine, or None when its domain has no calibrated wire
        price. Gauges land in THIS service's registry (labels
        entry="service"); drift events flow through the service's
        versioned event log."""
        from ..observatory.attribution import (PerfAttributor,
                                               model_step_seconds_for)
        from ..parallel.methods import pick_method
        model = model_step_seconds_for(eng.dd)
        if not model:
            return None
        plan = getattr(eng.dd, "plan", None)
        try:
            nbytes = float(eng.dd.exchange_bytes_amortized_per_step())
        except Exception:  # noqa: BLE001 - no byte model: B/s gauges off
            nbytes = 0.0
        # per-link attribution (observatory/linkmap.py): link-class
        # gauges in THIS service's registry; the service flight
        # recorder snapshots the same classified traffic matrix
        from ..observatory.linkmap import link_attribution_for
        link = link_attribution_for(eng.dd)
        if link and self.flight is not None:
            self.flight.set_linkmap(link["summary"])
        return PerfAttributor(
            entry="service", method=pick_method(eng.dd.methods).name,
            exchange_every=int(eng.dd.exchange_every),
            model_step_seconds=model, model_bytes_per_step=nbytes,
            tolerance=self._drift_tolerance, window=self._drift_window,
            warmup=1,  # the first segment dispatch pays compilation
            emit=self._log, registry=self.metrics,
            on_drift=(self._on_perf_drift if self._retune_on_drift
                      else None),
            link_bytes_per_step=(link["bytes_per_step"] if link
                                 else None),
            link_peak_bytes_per_s=(link["peak_bytes_per_s"] if link
                                   else None),
            fingerprint=(plan.fingerprint if plan is not None
                         else None))

    def _on_perf_drift(self, attrs: Dict) -> None:
        """``retune_on_drift``: invalidate the drifted plan's cache
        record so the next fingerprint-identical tune re-measures —
        stale plans heal themselves (shared hook:
        ``observatory.make_drift_invalidator``)."""
        from ..observatory.attribution import make_drift_invalidator
        make_drift_invalidator(self._plan_cache_path, self._log)(attrs)

    def _plan_for(self, fingerprint: str, req: CampaignRequest):
        """The exchange plan for a fingerprint: cache hit (zero
        measurements) or a one-time tune when a timer is configured
        (depth pinned to 1 — see module docstring)."""
        from ..tuning import load_plan
        plan = load_plan(fingerprint, self._plan_cache_path)
        if plan is not None:
            plan.provenance = "cached"
            plan.measurements = 0
            self.stats.plan_cache_hits += 1
            self._m_plan_hits.inc()
            return plan
        self._m_plan_misses.inc()
        if self._tuner_timer is None:
            return None
        import jax.numpy as jnp

        from ..topology import Boundary
        from ..tuning import autotune_domain
        from .ensemble import configured_domain
        dd = configured_domain(req.model, req.grid,
                               dtype=jnp.dtype(req.dtype),
                               boundary=Boundary[req.boundary],
                               mesh_shape=req.mesh_shape,
                               devices=self._devices)
        with self.tracer.span("tune", fingerprint=fingerprint):
            plan = autotune_domain(dd, timer=self._tuner_timer,
                                   cache_path=self._plan_cache_path,
                                   depths=(1,))
        assert plan.fingerprint == fingerprint, \
            (plan.fingerprint, fingerprint)
        self.stats.tuner_measurements += plan.measurements
        self._m_tuner.inc(plan.measurements)
        return plan

    def _engine_key(self, fingerprint: str, req: CampaignRequest) -> str:
        """The engine-cache key: the problem fingerprint PLUS the
        fusion geometry — a megastep engine compiles segments per
        ``check_every``, so differently-fused requests must not collide
        on one cache slot (they would thrash segment compiles and lie
        to the recompile counter)."""
        if not self._fuse:
            return fingerprint
        return f"{fingerprint}|ck={int(req.check_every)}"

    def _engine_for(self, fingerprint: str, req: CampaignRequest):
        """The compiled ensemble engine for a fingerprint — built once,
        reused for every later fingerprint-identical batch."""
        key = self._engine_key(fingerprint, req)
        eng = self._engines.get(key)
        if eng is not None:
            self._m_engine_hits.inc()
            return eng, False, None
        import jax.numpy as jnp

        from ..topology import Boundary
        plan = self._plan_for(fingerprint, req)
        cls = EnsembleJacobi if req.model == "jacobi" else EnsembleAstaroth
        with self.tracer.span("compile", fingerprint=fingerprint,
                              model=req.model):
            eng = cls(self.width, *req.grid,
                      dtype=jnp.dtype(req.dtype),
                      boundary=Boundary[req.boundary],
                      mesh_shape=req.mesh_shape, devices=self._devices,
                      plan=plan)
        assert eng.fingerprint == fingerprint, \
            (eng.fingerprint, fingerprint)
        self._engines[key] = eng
        self._sentinels[key] = EnsembleSentinel(
            eng, window=self._window,
            growth_factor=self._growth_factor)
        self.stats.compiles += 1
        self._m_compiles.inc()
        if key in self._built:
            # the engine cache dropped a key it had already built — the
            # warm-path regression the CI counter gate is for
            # (stencil_service_recompiles_total stays 0 normally)
            self._m_recompiles.inc()
        self._built.add(key)
        self._m_engine_size.set(len(self._engines))
        if self._attribute:
            att = self._make_attributor(eng)
            if att is not None:
                self._attributors[key] = att
        return eng, True, plan

    def _admit_lane(self, eng, lane: _Lane) -> None:
        """Set up one lane: parameters, resume-or-init, and the step-0
        rollback anchor checkpoint."""
        req = lane.request
        k = lane.index
        eng.reset_member(k)
        if req.params:
            eng.set_member_params(k, req.params)
        if all_steps(lane.ckpt_dir):
            step = eng.restore_member(lane.ckpt_dir, k)
            lane.counter = step
            lane.resumed_from = step
            self._log("resumed", tenant=req.tenant,
                      campaign=req.campaign, step=step)
            LOG_INFO(f"campaign {req.tenant}/{req.campaign} resumes "
                     f"from step {step}")
        else:
            eng.init_member(k, req.init_seed)
            eng.save_member(lane.ckpt_dir, 0, k,
                            max_to_keep=self._max_to_keep)
            self._m_checkpoints.inc()
            self._log("checkpoint", tenant=req.tenant,
                      campaign=req.campaign, step=0)

    @staticmethod
    def _steps_to_boundary(lane: _Lane) -> int:
        """Member steps until lane's next event: completion, probe,
        checkpoint, snapshot, or chaos injection."""
        req = lane.request
        c = lane.counter
        cands = [req.n_steps - c]
        for cad in (req.check_every, req.ckpt_every,
                    req.snapshot_every):
            if cad and cad > 0:
                cands.append(cad - (c % cad))
        if req.chaos_nan_step is not None and not lane.chaos_fired \
                and req.chaos_nan_step > c:
            cands.append(req.chaos_nan_step - c)
        return max(1, min(x for x in cands if x > 0))

    def _inject_nan(self, eng, lane: _Lane) -> None:
        q = eng.names[0]
        host = eng.member_interior(q, lane.index)
        host[tuple(0 for _ in host.shape)] = np.nan
        eng.set_member_interior(q, lane.index, host)
        lane.chaos_fired = True
        self._log("fault_injected", tenant=lane.request.tenant,
                  campaign=lane.request.campaign, step=lane.counter,
                  quantity=q)

    def _handle_trip(self, eng, sentinel, lane: _Lane,
                     reason: str) -> None:
        req = lane.request
        self._log("sentinel_tripped", tenant=req.tenant,
                  campaign=req.campaign, member=lane.index,
                  step=lane.counter, reason=reason,
                  attempt=lane.rollbacks + 1)
        LOG_WARN(f"campaign {req.tenant}/{req.campaign}: sentinel "
                 f"tripped at member step {lane.counter} ({reason}), "
                 f"attempt {lane.rollbacks + 1}/{req.max_retries}")
        sentinel.reset_member(lane.index)
        # rollback counters count RESTORES performed, not trips — a
        # campaign that fails on its first trip reports zero rollbacks
        if lane.rollbacks >= req.max_retries:
            lane.active = False
            eng.reset_member(lane.index)
            self.stats.failed += 1
            self._m_campaigns.inc(tenant=req.tenant, outcome="failed")
            self._log("campaign_failed", tenant=req.tenant,
                      campaign=req.campaign, reason=reason)
            lane.entry.handle._fail(CampaignFailed(
                f"{req.tenant}/{req.campaign}: retries exhausted "
                f"({req.max_retries}) at step {lane.counter}: "
                f"{reason}"))
            self._flight_dump("campaign_failed", tenant=req.tenant,
                              campaign=req.campaign,
                              member=lane.index, trip_reason=reason)
            return
        with self.tracer.span("rollback", tenant=req.tenant,
                              member=lane.index):
            step = eng.restore_member(lane.ckpt_dir, lane.index)
        lane.counter = step
        lane.rollbacks += 1
        self.stats.rollbacks += 1
        self._m_rollbacks.inc(tenant=req.tenant)
        self._log("rollback", tenant=req.tenant, campaign=req.campaign,
                  member=lane.index, restored_step=step)
        # the black box captures trip AND rollback in one incident
        self._flight_dump("sentinel_trip", tenant=req.tenant,
                          campaign=req.campaign, member=lane.index,
                          trip_step=lane.counter, trip_reason=reason)

    def _complete_lane(self, eng, lane: _Lane,
                       preempted: bool = False) -> None:
        req = lane.request
        final = eng.member_interiors(lane.index)
        result = CampaignResult(
            tenant=req.tenant, campaign=req.campaign,
            steps=lane.counter, rollbacks=lane.rollbacks,
            resumed_from=lane.resumed_from, preempted=preempted,
            snapshots=sorted(lane.snapshots.items()), final=final)
        lane.active = False
        if preempted:
            self._m_campaigns.inc(tenant=req.tenant,
                                  outcome="preempted")
            self._log("campaign_preempted", tenant=req.tenant,
                      campaign=req.campaign, step=lane.counter)
        else:
            self.stats.completed += 1
            self._m_campaigns.inc(tenant=req.tenant,
                                  outcome="completed")
            self._log("campaign_completed", tenant=req.tenant,
                      campaign=req.campaign, steps=lane.counter,
                      rollbacks=lane.rollbacks)
        lane.entry.handle._resolve(result)

    def _run_batch(self, batch) -> None:
        fp = batch[0].fingerprint
        try:
            with self.tracer.span("campaign.batch", fingerprint=fp,
                                  members=len(batch)):
                self._serve_batch(batch)
        except Exception as e:
            # unhandled dispatch error: the black box is the
            # post-mortem (the raise still propagates unchanged)
            self._flight_dump("unhandled_error", fingerprint=fp,
                              error=f"{type(e).__name__}: {e}")
            raise

    def _serve_batch(self, batch) -> None:
        fp = batch[0].fingerprint
        req0 = batch[0].request
        now = time.time()
        for e in batch:
            self._m_admission.observe(max(0.0, now - e.submitted))
        self._m_queue_depth.set(len(self.queue))
        self._m_occupancy.set(len(batch) / self.width)
        eng, compiled, plan = self._engine_for(fp, req0)
        sentinel = self._sentinels[self._engine_key(fp, req0)]
        sentinel.reset()
        self.stats.batches += 1
        self._m_batches.inc()
        t_batch = time.perf_counter()
        steps_advanced = 0
        self._log(
            "batch_started", fingerprint=fp, members=len(batch),
            width=eng.n_members, compiled=compiled,
            plan_provenance=(eng.dd.plan_provenance),
            measurements=(plan.measurements if plan is not None
                          and plan.provenance == "tuned" else 0),
            fused=self._fuse,
            tenants=[e.request.tenant for e in batch])
        if not self._fuse:
            # the stepwise fallback is a fleet-visible fact, not a
            # silent mode: mirrored from the resilient driver's
            # fused_decline event + stencil_run_fused_dispatch_total
            self._log("fused_decline", fingerprint=fp,
                      model="service", path="ensemble",
                      reason="fuse_segments disabled by service "
                             "configuration")
        lanes = [
            _Lane(entry=e, index=k,
                  ckpt_dir=str(self.namespace(e.request.tenant,
                                              e.request.campaign)))
            for k, e in enumerate(batch)]
        for lane in lanes:
            try:
                self._admit_lane(eng, lane)
            except Exception as err:  # noqa: BLE001 - admission faults
                lane.active = False
                self.stats.failed += 1
                self._log("campaign_failed",
                          tenant=lane.request.tenant,
                          campaign=lane.request.campaign,
                          reason=f"admission: {err}")
                lane.entry.handle._fail(err)
        # idle lanes of a partially-filled batch stay benign
        for k in range(len(batch), eng.n_members):
            eng.reset_member(k)
        # a resubmitted campaign whose restored checkpoint already
        # meets the requested budget completes immediately — it must
        # not run past n_steps
        for lane in lanes:
            if lane.active and lane.counter >= lane.request.n_steps:
                self._complete_lane(eng, lane)

        pending_snaps: List = []

        def poll_snapshots(block: bool = False) -> None:
            remaining = []
            for lane, snap in pending_snaps:
                if block or snap.ready():
                    if lane.active and snap.step <= \
                            lane.request.n_steps:
                        lane.snapshots[snap.step] = snap.get()
                else:
                    remaining.append((lane, snap))
            pending_snaps[:] = remaining

        while any(lane.active for lane in lanes):
            if self._preempt:
                # drain in-flight probes; never persist poisoned state
                for health in sentinel.poll(block=True):
                    for k in health.tripped_members:
                        lane = next((ln for ln in lanes
                                     if ln.index == k and ln.active),
                                    None)
                        if lane is not None:
                            self._handle_trip(
                                eng, sentinel, lane,
                                health.members[k].reason)
                # harvest in-flight snapshots BEFORE materializing the
                # preempted results — completion deactivates the lane
                # and would silently drop them
                poll_snapshots(block=True)
                # black box BEFORE the preemption checkpoints: if a
                # final save dies, the incident record already exists
                self._flight_dump("preempt", fingerprint=fp)
                for lane in lanes:
                    if lane.active:
                        eng.save_member(lane.ckpt_dir, lane.counter,
                                        lane.index,
                                        meta_extra={"preempted": True},
                                        max_to_keep=self._max_to_keep)
                        self._m_checkpoints.inc()
                        self._log("checkpoint",
                                  tenant=lane.request.tenant,
                                  campaign=lane.request.campaign,
                                  step=lane.counter, preempted=True)
                        self._complete_lane(eng, lane, preempted=True)
                self._log("preempted", fingerprint=fp)
                return
            seg = min(self._steps_to_boundary(lane)
                      for lane in lanes if lane.active)
            if self._fuse:
                from ..parallel.megastep import MAX_UNROLL
                seg = min(seg, MAX_UNROLL)
            trace = None
            att = self._attributors.get(self._engine_key(fp, req0))
            timed = (att.dispatch(seg, lambda: _block_state(eng))
                     if att is not None else contextlib.nullcontext())
            with self.tracer.span("segment", steps=seg,
                                  fused=self._fuse):
                if self._fuse:
                    # one Perfetto box per COMPILED PROGRAM, timed by
                    # the attributor (host wall clock — the dispatched
                    # program is unchanged); the megastep runs the
                    # per-member probe trace in the same single
                    # dispatch (one all-reduce per row), under the
                    # hot-loop transfer guard — nothing moves
                    # implicitly between host and device inside the
                    # fused dispatch (analysis/transfer.py;
                    # STENCIL_ALLOW_TRANSFERS=1 opts out)
                    with self.tracer.span(
                            "segment.dispatch", k=seg,
                            check_every=int(req0.check_every),
                            entry="service"):
                        with timed:
                            with hot_loop_transfer_guard():
                                trace = eng.run_segment(seg)
                    if self._compile_guard is not None:
                        for name, fn in eng.jit_entry_points().items():
                            self._compile_guard.observe(
                                fn, f"ensemble {name}")
                else:
                    with timed:
                        eng.run(seg)
            self._m_fused_dispatch.inc(
                fused="true" if self._fuse else "false")
            n_active = 0
            for lane in lanes:
                if lane.active:
                    lane.counter += seg
                    n_active += 1
            self._m_steps.inc(seg * n_active)
            steps_advanced += seg * n_active
            top = max(lane.counter for lane in lanes)
            if trace is not None:
                sentinel.observe_segment(
                    trace.array, [top - seg + r for r in trace.steps])
            # chaos injections land AFTER the step that reaches them
            chaos_fired = False
            for lane in lanes:
                req = lane.request
                if (lane.active and req.chaos_nan_step is not None
                        and not lane.chaos_fired
                        and lane.counter >= req.chaos_nan_step):
                    self._inject_nan(eng, lane)
                    chaos_fired = True
            if trace is None or chaos_fired:
                # stepwise mode probes every boundary; fused mode
                # re-probes only when a host-side injection poisoned
                # state AFTER the in-graph trace rows were produced
                sentinel.probe(top)
            poll_snapshots()
            # blocking drain BEFORE any checkpoint/completion below —
            # the same invariant as the resilience driver: poisoned
            # state is never persisted or handed back
            tripped: Dict[int, str] = {}
            for health in sentinel.poll(block=True):
                for k in health.tripped_members:
                    tripped.setdefault(k, health.members[k].reason)
            for lane in list(lanes):
                if not lane.active:
                    continue
                req = lane.request
                if lane.index in tripped:
                    self._handle_trip(eng, sentinel, lane,
                                      tripped[lane.index])
                    continue
                if (req.snapshot_every and lane.counter
                        and lane.counter % req.snapshot_every == 0
                        and lane.counter < req.n_steps):
                    pending_snaps.append(
                        (lane, eng.member_snapshot_async(
                            lane.index, lane.counter)))
                    self._m_snapshots.inc()
                    self._log("snapshot_enqueued", tenant=req.tenant,
                              campaign=req.campaign, step=lane.counter)
                if (req.ckpt_every and lane.counter
                        and lane.counter % req.ckpt_every == 0
                        and lane.counter < req.n_steps):
                    with self.tracer.span("checkpoint",
                                          tenant=req.tenant,
                                          step=lane.counter):
                        eng.save_member(lane.ckpt_dir, lane.counter,
                                        lane.index,
                                        max_to_keep=self._max_to_keep)
                    self._m_checkpoints.inc()
                    self._log("checkpoint", tenant=req.tenant,
                              campaign=req.campaign, step=lane.counter)
                if lane.counter >= req.n_steps:
                    eng.save_member(lane.ckpt_dir, lane.counter,
                                    lane.index,
                                    meta_extra={"completed": True},
                                    max_to_keep=self._max_to_keep)
                    self._m_checkpoints.inc()
                    poll_snapshots(block=True)
                    self._complete_lane(eng, lane)
            # deterministic chaos: armed thresholds fire at the END of
            # boundary processing, so checkpoints due at this boundary
            # have already landed — a crash loses exactly the work
            # since the last ckpt_every boundary, no more, no less
            if (self._preempt_at_step is not None
                    and top >= self._preempt_at_step):
                self._preempt_at_step = None
                # same contract as preempt(): this batch checkpoints
                # and the worker stops (the fleet requeues + resumes)
                self._preempt = True
                self._stop = True
            if (self._crash_at_step is not None
                    and top >= self._crash_at_step):
                armed = self._crash_at_step
                self._crash_at_step = None
                self._log("replica_crash", fingerprint=fp, step=top,
                          armed_at=armed)
                raise ReplicaCrashed(
                    f"replica hard-crashed at member step {top} "
                    f"(armed at {armed})")
        poll_snapshots(block=True)
        elapsed = time.perf_counter() - t_batch
        if steps_advanced and elapsed > 0:
            self._m_steps_per_s.set(steps_advanced / elapsed)
        self._log("batch_finished", fingerprint=fp)
