"""``key = value`` configuration loader.

The analog of the reference's astaroth.conf parser
(reference: astaroth/astaroth_utils.cu acLoadConfig,
astaroth/astaroth.conf): lines of ``name = value`` with ``//`` and
``/* */`` comments; int-valued names and real-valued names are kept in
separate tables like AcMeshInfo's int_params/real_params.
"""

from __future__ import annotations


def apply_fake_cpu(n: int) -> None:
    """Point JAX at ``n`` virtual CPU devices (the analog of the
    reference's GPU oversubscription, test/test_exchange.cu:52). Must
    run before anything initializes the XLA backend; shared by the app
    CLIs (--fake-cpu) and the bench/CI harnesses."""
    if n:
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", n)

import re
from typing import Dict, Tuple


def load_config(path: str) -> Tuple[Dict[str, int], Dict[str, float]]:
    """Parse a conf file into (int_params, real_params)."""
    with open(path) as f:
        text = f.read()
    return parse_config(text)


def parse_config(text: str) -> Tuple[Dict[str, int], Dict[str, float]]:
    text = re.sub(r"/\*.*?\*/", "", text, flags=re.S)
    int_params: Dict[str, int] = {}
    real_params: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.split("//")[0].strip()
        if not line or "=" not in line:
            continue
        name, _, val = line.partition("=")
        name = name.strip()
        val = val.strip()
        if not name or not val:
            continue
        try:
            if re.fullmatch(r"[+-]?\d+", val):
                int_params[name] = int(val)
            else:
                real_params[name] = float(val)
        except ValueError:
            continue  # non-numeric values are ignored, as in the reference
    return int_params, real_params
