"""``key = value`` configuration loader.

The analog of the reference's astaroth.conf parser
(reference: astaroth/astaroth_utils.cu acLoadConfig,
astaroth/astaroth.conf): lines of ``name = value`` with ``//`` and
``/* */`` comments; int-valued names and real-valued names are kept in
separate tables like AcMeshInfo's int_params/real_params.
"""

from __future__ import annotations


def apply_fake_cpu(n: int) -> None:
    """Point JAX at ``n`` virtual CPU devices (the analog of the
    reference's GPU oversubscription, test/test_exchange.cu:52). Must
    run before anything initializes the XLA backend; shared by the app
    CLIs (--fake-cpu) and the bench/CI harnesses."""
    if n:
        import jax
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", n)
        except AttributeError:
            # older JAX has no jax_num_cpu_devices: the host device
            # count can only come from XLA_FLAGS (read at backend
            # init, which this function predates by contract)
            import os

            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count"
                    f"={n}").strip()


def enable_compile_cache(path: str = "") -> None:
    """Turn on JAX's persistent compilation cache (TPU backends only —
    CPU compiles are fast and the tests would just churn the disk).
    The fused megakernels take minutes to compile over a tunnelled
    chip; caching makes every bench / app rerun after the first warm.
    Safe to call any time before the first compilation — the gate reads
    the REQUESTED platform list (config/env), not the initialized
    backend, so this never forces backend init (multihost wiring must
    still run first, parallel/multihost.py:36). Skips only when cpu is
    the PRIMARY requested platform ("cpu", "cpu,..."): accelerator
    lists with a cpu fallback ("axon,cpu", "tpu,cpu") must still cache,
    and an unset list means platform discovery may find a TPU."""
    import os

    import jax

    primary = str(jax.config.jax_platforms or "").split(",")[0].strip()
    if primary == "cpu":
        return
    cache = (path or os.environ.get("JAX_COMPILATION_CACHE_DIR")
             or os.path.expanduser("~/.cache/stencil_tpu_xla"))
    os.makedirs(cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache)
    # cache every program that takes noticeable compile time
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def mhd_pair_requested() -> bool:
    """STENCIL_MHD_PAIR=1 opts the MHD fast paths (wrap, halo, and
    halo-overlap) into the fused RK substep-0+1 pair kernels — the ONE
    parse of the flag, shared by every builder that gates on it."""
    import os

    return (os.environ.get("STENCIL_MHD_PAIR", "").lower()
            in ("1", "true", "yes"))


def wrap2_disabled() -> bool:
    """STENCIL_DISABLE_WRAP2=1 is the kill-switch harnesses use to fall
    back from the temporally-blocked pair kernels to the hardware-proven
    single-step kernels ("0" and unset both leave pairs on). Shared by
    the wrap and halo step builders (models/jacobi.py)."""
    import os

    return (os.environ.get("STENCIL_DISABLE_WRAP2", "").lower()
            in ("1", "true", "yes"))

import re
from typing import Dict, Tuple


def load_config(path: str) -> Tuple[Dict[str, int], Dict[str, float]]:
    """Parse a conf file into (int_params, real_params)."""
    with open(path) as f:
        text = f.read()
    return parse_config(text)


def parse_config(text: str) -> Tuple[Dict[str, int], Dict[str, float]]:
    text = re.sub(r"/\*.*?\*/", "", text, flags=re.S)
    int_params: Dict[str, int] = {}
    real_params: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.split("//")[0].strip()
        if not line or "=" not in line:
            continue
        name, _, val = line.partition("=")
        name = name.strip()
        val = val.strip()
        if not name or not val:
            continue
        try:
            if re.fullmatch(r"[+-]?\d+", val):
                int_params[name] = int(val)
            else:
                real_params[name] = float(val)
        except ValueError:
            continue  # non-numeric values are ignored, as in the reference
    return int_params, real_params
