"""Wall-clock timing utilities.

The analog of the reference's Timer/rt wrappers (reference:
include/stencil/timer.hpp:21-39, rt.hpp:9-37) adapted to async XLA
dispatch: on some platforms (notably the axon TPU tunnel used in this
environment) ``jax.block_until_ready`` does not actually drain the
execution pipeline, so ``device_sync`` forces a one-element
device-to-host transfer instead — the only reliable fence.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict

import jax
import numpy as np


def device_sync(tree: Any) -> None:
    """Force completion of all computations producing ``tree``'s leaves
    by fetching one element of each to host (transfer is the only
    reliable fence on the axon tunnel platform)."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "addressable_shards"):
            for s in leaf.addressable_shards:
                np.asarray(s.data.ravel()[:1])
        elif hasattr(leaf, "__array__"):
            np.asarray(leaf).ravel()[:1]


class Timer:
    """Accumulating wall timer (reference: timer.hpp:21-39)."""

    def __init__(self) -> None:
        self.seconds = 0.0
        self._t0 = 0.0

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self) -> float:
        dt = time.perf_counter() - self._t0
        self.seconds += dt
        return dt


def time_fn(fn: Callable, *args, sync: Any = None, **kw) -> float:
    """Time one call including device completion (the rt::time analog,
    reference: rt.hpp:9-22): argument evaluation is excluded, the
    returned value (or ``sync``) is fetched to fence."""
    t0 = time.perf_counter()
    out = fn(*args, **kw)
    device_sync(out if sync is None else sync)
    return time.perf_counter() - t0


# global accumulators, the timers::cudaRuntime / timers::mpi analog
# (reference: src/timer.cpp:13-16)
timers: Dict[str, Timer] = {}


def get_timer(name: str) -> Timer:
    return timers.setdefault(name, Timer())
