"""Profiling / tracing scopes (SURVEY.md section 5.1).

The analog of the reference's NVTX ranges + named streams + stat
reductions (reference: src/stencil.cu:311,1003-1080 nvtx ranges;
timer.hpp/rt.hpp pass-through timers; STENCIL_SETUP_STATS /
STENCIL_EXCHANGE_STATS barrier+MPI_Wtime+MPI_Reduce(MAX) aggregation,
src/stencil.cu:36-48,1174-1181). On TPU: ``jax.named_scope`` labels ops
in the XLA profile the way NVTX labels CUDA streams, and
``jax.profiler`` produces the nsys-equivalent trace viewable in
TensorBoard/Perfetto.

:func:`scope` is also the substrate of the structured-span layer:
``stencil_tpu.telemetry.Tracer.span`` wraps it, so every telemetry
span is simultaneously a ``named_scope``/``TraceAnnotation`` range
(correlating with XLA profiler output) AND an exportable record with a
stable id — dumped as Perfetto-loadable Chrome trace JSON without a
profiler session (see README "Observability").
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator

import jax


@contextlib.contextmanager
def scope(name: str) -> Iterator[None]:
    """Label both traced ops (named_scope -> XLA metadata) and host
    wall time (TraceAnnotation -> profiler timeline) — the NVTX range
    analog."""
    with jax.named_scope(name):
        with jax.profiler.TraceAnnotation(name):
            yield


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device+host profile to ``log_dir`` (the nsys recipe in
    the reference README, README.md:96-135; view with TensorBoard or
    Perfetto)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class PhaseTimer:
    """Named wall-clock phases with the max-over-processes reduction the
    reference's setup stats use (single-process: identity)."""

    def __init__(self) -> None:
        self.seconds: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.seconds[name] = (self.seconds.get(name, 0.0)
                                  + time.perf_counter() - t0)

    def reduced(self) -> Dict[str, float]:
        if jax.process_count() == 1:
            return dict(self.seconds)
        from jax.experimental import multihost_utils
        import numpy as np
        names = sorted(self.seconds)
        vals = np.asarray([self.seconds[n] for n in names])
        reduced = multihost_utils.process_allgather(vals).max(axis=0)
        return dict(zip(names, reduced.tolist()))


def setup_stats_report(dd) -> str:
    """One-line setup-time report (the STENCIL_SETUP_STATS print,
    reference: src/stencil.cu:205-236)."""
    parts = [f"{k}={v:.6f}s" for k, v in dd.setup_seconds.items()]
    return "setup: " + " ".join(parts)


def exchange_stats_report(dd) -> str:
    """Exchange-time report (STENCIL_EXCHANGE_STATS analog; requires
    ``dd.enable_timing(True)``).

    Reports the ANALYTIC expected wire bytes next to the measured
    times: ``dd.exchange_bytes_total()`` comes from
    ``parallel.exchange.exchanged_bytes_per_sweep`` — the same byte
    model the static analyzer cross-checks against lowered HLO
    (``analysis/costmodel.py``), so runtime observability and the
    static cost model share one source of truth. ``eff`` is the
    implied whole-mesh wire rate at the trimean; a gap against the
    fabric's nominal bandwidth localizes exchange-time regressions
    without re-deriving byte counts by hand."""
    if not dd.exchange_seconds:
        return "exchange: no samples (enable_timing first)"
    from ..numerics import trimean
    xs = dd.exchange_seconds
    line = (f"exchange: n={len(xs)} min={min(xs):.6e}s "
            f"trimean={trimean(xs):.6e}s")
    try:
        expected = int(dd.exchange_bytes_total())
    except (AttributeError, TypeError):
        return line
    tm = trimean(xs)
    if expected and tm > 0:
        line += (f" expected={expected}B/exchange (analytic)"
                 f" eff={expected / tm / 1e9:.2f}GB/s")
    # temporal blocking: one deep exchange feeds s steps — report the
    # per-STEP amortization (same analytic byte source as the deep
    # figure above and the static analyzer's cross-check)
    s = getattr(dd, "exchange_every", 1)
    if s > 1 and expected and tm > 0:
        amortized = dd.exchange_bytes_amortized_per_step()
        line += (f" exchange_every={s}"
                 f" amortized={amortized:.0f}B/step"
                 f" ({tm / s:.6e}s/step exchange cost)")
    # autotuned domains: say who decided this configuration
    prov = getattr(dd, "plan_provenance", "default")
    if prov != "default":
        line += f" plan={prov}"
    return line


def autotune_report(plan) -> str:
    """Multi-line report of an autotuner Plan (stencil_tpu/tuning):
    the decision, its provenance, the measured link coefficients, and
    the best few candidate costs — the plan-file observability analog
    of the reference's transport-routing printout
    (src/stencil.cu:482-637)."""
    lines = [f"autotune: {plan.config.key()} provenance={plan.provenance}"
             f" measurements={plan.measurements}"
             f" fingerprint={plan.fingerprint[:12]}..."]
    for link, c in sorted(plan.coefficients.items()):
        lines.append(f"  link {link}: alpha={c['alpha_s']:.3e}s"
                     f" beta={c['beta_bytes_per_s']:.3e}B/s (measured)")
    ranked = sorted(plan.costs.items(),
                    key=lambda kv: kv[1].get(
                        "measured_s", kv[1].get("predicted_s", 0.0)))
    for key, rec in ranked[:4]:
        meas = (f" measured={rec['measured_s']:.3e}s/step"
                if "measured_s" in rec else " (pruned by model)")
        lines.append(f"  {key}: predicted="
                     f"{rec.get('predicted_s', float('nan')):.3e}s/step"
                     f"{meas}")
    return "\n".join(lines)
