"""Checkpoint / resume for distributed domain state (orbax-backed).

The reference has no true checkpointing — its nearest features are the
ParaView CSV dumps (reference: src/stencil.cu:1188-1264) and astaroth's
``AC_start_step`` config knob that the mini-app never restores
(reference: astaroth/astaroth.conf:36-38). SURVEY.md section 5.4 calls for
real checkpoint/restore as the modern equivalent; this module provides
it: sharded field arrays are written with orbax (each host writes its
own shards; restore re-shards onto the current mesh), alongside a JSON
metadata record (step counter, grid geometry) used to validate
compatibility on resume.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def _manager(directory: str, max_to_keep: Optional[int] = None):
    import orbax.checkpoint as ocp
    opts = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                        create=True)
    return ocp.CheckpointManager(Path(directory).absolute(), options=opts)


def save_state(directory: str, step: int, arrays: Dict[str, jnp.ndarray],
               meta: Optional[Dict[str, Any]] = None,
               max_to_keep: Optional[int] = None) -> None:
    """Write ``arrays`` (a flat dict of possibly-sharded jax arrays) and
    JSON-serializable ``meta`` as checkpoint ``step``."""
    import orbax.checkpoint as ocp
    mgr = _manager(directory, max_to_keep)
    mgr.save(step, args=ocp.args.Composite(
        state=ocp.args.StandardSave(arrays),
        meta=ocp.args.JsonSave(meta or {})))
    mgr.wait_until_finished()
    mgr.close()


def latest_step(directory: str) -> Optional[int]:
    mgr = _manager(directory)
    out = mgr.latest_step()
    mgr.close()
    return out


def restore_state(directory: str,
                  targets: Dict[str, jax.ShapeDtypeStruct],
                  step: Optional[int] = None
                  ) -> Tuple[int, Dict[str, jnp.ndarray], Dict[str, Any]]:
    """Restore arrays onto the shardings given in ``targets`` (a dict of
    ``jax.ShapeDtypeStruct`` with ``.sharding`` set — restoring onto a
    different mesh than the one that saved is supported, orbax reshards).
    Returns ``(step, arrays, meta)``."""
    import orbax.checkpoint as ocp
    mgr = _manager(directory)
    if step is None:
        step = mgr.latest_step()
        if step is None:
            mgr.close()
            raise FileNotFoundError(f"no checkpoint in {directory}")
    out = mgr.restore(step, args=ocp.args.Composite(
        state=ocp.args.StandardRestore(targets),
        meta=ocp.args.JsonRestore()))
    mgr.close()
    return step, dict(out["state"]), dict(out["meta"] or {})


# ----------------------------------------------------------------------
# DistributedDomain integration
# ----------------------------------------------------------------------
def _interior_fns(dd):
    """Jitted global-padded <-> global-interior converters (device-side,
    stay sharded): checkpoints are mesh-independent so they can be
    restored onto a different decomposition. Cached on the domain so
    periodic checkpoints don't retrace/recompile every save."""
    cached = getattr(dd, "_ckpt_interior_fns", None)
    if cached is not None:
        return cached
    from jax import lax
    from jax.sharding import PartitionSpec as P

    # allocation pads, not the stencil radius: temporal blocking
    # (set_exchange_every) deepens the buffers to s*r per side
    lo = dd.alloc_radius.pad_lo()
    hi = dd.alloc_radius.pad_hi()
    local = dd.local_size
    spec = P("z", "y", "x")

    def extract_shard(p):
        return lax.slice(p, (lo.z, lo.y, lo.x),
                         (lo.z + local.z, lo.y + local.y, lo.x + local.x))

    def insert_shard(interior):
        padded = jnp.zeros((local.z + lo.z + hi.z, local.y + lo.y + hi.y,
                            local.x + lo.x + hi.x), dtype=interior.dtype)
        return lax.dynamic_update_slice(padded, interior,
                                        (lo.z, lo.y, lo.x))

    fns = tuple(
        jax.jit(jax.shard_map(f, mesh=dd.mesh, in_specs=spec,
                              out_specs=spec, check_vma=False))
        for f in (extract_shard, insert_shard))
    dd._ckpt_interior_fns = fns
    return fns


def domain_meta(dd) -> Dict[str, Any]:
    return {
        "size": list(dd.size),
        "mesh": list(dd.placement.dim()),
        "quantities": list(dd._names),
        "dtypes": {q: str(dd._dtypes[q]) for q in dd._names},
    }


def save_domain(dd, directory: str, step: int,
                extra: Optional[Dict[str, jnp.ndarray]] = None,
                max_to_keep: Optional[int] = None) -> None:
    """Checkpoint a DistributedDomain's curr fields (+ optional extra
    arrays, e.g. RK accumulators) at ``step``."""
    from ..geometry import Dim3
    if dd.rem == Dim3(0, 0, 0):
        extract, _ = _interior_fns(dd)
        arrays = {q: extract(v) for q, v in dd.curr.items()}
    else:
        # uneven shards: per-shard interior extents differ, so the
        # device-side uniform extraction would embed dead rows; gather
        # the true dd.size interior on host instead (slower, correct)
        arrays = {q: jnp.asarray(dd.interior_to_host(q))
                  for q in dd._names}
    meta = domain_meta(dd)
    meta["extra"] = {}
    for k, v in (extra or {}).items():
        arrays[f"extra:{k}"] = v
        meta["extra"][k] = {"shape": list(v.shape), "dtype": str(v.dtype)}
    save_state(directory, step, arrays, meta=meta, max_to_keep=max_to_keep)


def restore_domain(dd, directory: str, step: Optional[int] = None
                   ) -> Tuple[int, Dict[str, jnp.ndarray]]:
    """Restore a realized DistributedDomain's curr fields in place;
    returns ``(step, extra_arrays)``. The domain must have the same
    global size and quantities as the checkpoint (mesh may differ —
    orbax reshards onto the current one)."""
    from ..geometry import Dim3
    from ..local_domain import zyx_shape
    from jax.sharding import NamedSharding, PartitionSpec as P
    targets: Dict[str, jax.ShapeDtypeStruct] = {}
    ishape = zyx_shape(dd.size)
    uneven = dd.rem != Dim3(0, 0, 0)
    # even: interior globals shard P(z,y,x); uneven: dd.size doesn't
    # divide the mesh, restore replicated and re-scatter via set_interior
    repl = NamedSharding(dd.mesh, P())
    for q in dd._names:
        cur = dd.curr[q]
        targets[q] = jax.ShapeDtypeStruct(
            ishape, cur.dtype, sharding=repl if uneven else cur.sharding)
    # one manager for step lookup, the meta probe, and the restore
    import orbax.checkpoint as ocp
    mgr = _manager(directory)
    try:
        step_found = mgr.latest_step() if step is None else step
        if step_found is None:
            raise FileNotFoundError(f"no checkpoint in {directory}")
        # extras are described in the JSON meta record (saved alongside)
        probe = mgr.restore(
            step_found, args=ocp.args.Composite(meta=ocp.args.JsonRestore()))
        saved_meta = dict(probe["meta"] or {})
        cur0 = dd.curr[dd._names[0]]
        for k, desc in (saved_meta.get("extra") or {}).items():
            targets[f"extra:{k}"] = jax.ShapeDtypeStruct(
                tuple(desc["shape"]), jnp.dtype(desc["dtype"]),
                sharding=cur0.sharding)
        out = mgr.restore(step_found, args=ocp.args.Composite(
            state=ocp.args.StandardRestore(targets),
            meta=ocp.args.JsonRestore()))
    finally:
        mgr.close()
    step_out, arrays, meta = step_found, dict(out["state"]), dict(
        out["meta"] or {})
    if meta.get("size") and list(dd.size) != meta["size"]:
        raise ValueError(f"checkpoint size {meta['size']} != domain "
                         f"{list(dd.size)}")
    if meta.get("quantities") and meta["quantities"] != list(dd._names):
        raise ValueError(f"checkpoint quantities {meta['quantities']} != "
                         f"{list(dd._names)}")
    for q, dt in (meta.get("dtypes") or {}).items():
        if q in dd._dtypes and str(dd._dtypes[q]) != dt:
            raise ValueError(f"checkpoint dtype {dt} for {q!r} != "
                             f"domain dtype {dd._dtypes[q]}")
    from ..geometry import Dim3
    if dd.rem == Dim3(0, 0, 0):
        _, insert = _interior_fns(dd)
        for q in dd._names:
            dd.curr[q] = insert(arrays[q])
    else:
        import numpy as np
        for q in dd._names:
            dd.set_interior(q, np.asarray(arrays[q]))
    # halos are zero after insert; one exchange makes the state whole
    dd.exchange()
    extra = {k[len("extra:"):]: v for k, v in arrays.items()
             if k.startswith("extra:")}
    return step_out, extra
